"""The actor→encoding compiler: generic ``ActorModel`` → TPU encoding.

This is the framework's counterpart of the reference's generic actor
bridge (``/root/reference/src/actor/model.rs:214-649``), which is what
lets *every* actor system run through one code path. Here the same
genericity targets the TPU wave engines: given an :class:`ActorModel`,
:func:`compile_actor_model` produces an
:class:`~stateright_tpu.encoding.EncodedModel` — lane layout,
``step_vec``, ``encode``, property lanes — with **zero hand-written
device code** (SURVEY.md §7 step 5, the "#[derive(TpuState)]-style
codegen").

**How.** Actor systems factorize: a system state is (per-actor local
states, network multiset, timer sets, crashed bits, history). Each
component ranges over a domain that is exponentially smaller than the
product state space. The compiler computes a **component closure** —
the set of local states each actor can reach, the envelope universe,
the timer universe, and the history domain — by running the REAL actor
handlers (``on_start``/``on_msg``/``on_timeout``) and history hooks on
the host over all (state, envelope) pairs to a fixpoint. The closure
*overapproximates* per-component reachability (it pairs local states
with envelopes that may never co-occur in a reachable system state),
which is sound: unreachable table rows are simply never gathered.

The device step function is then pure table lookups with STATIC
action-slot layout, mirroring ``ActorModel.actions``/``next_state``
(actor/model.rs:243-380):

* one Deliver slot per envelope in the universe — valid iff present in
  the network, dst alive, and the (state, envelope) pair is not a
  no-op (the model.rs:317-319 pruning, precomputed);
* one Drop slot per envelope on lossy networks;
* one Timeout slot per (actor, timer-universe element) — the fired
  timer's clear plus the handler's timer commands fold into one
  precomputed mask pair;
* one Crash slot per actor when ``max_crashes > 0``.

Network sends become precomputed per-(state, envelope) lane deltas
(OR-masks for duplicating-set semantics, field adds for the
non-duplicating multiset); history transitions collapse to
"effect classes" (distinct (incoming-envelope, send-sequence)
signatures) so the history table is ``|H| × #classes``.

**Codegen shapes.** The sparse-dispatch surface emits the same op
shapes the hand encodings use (PERF.md §ordered priced the old forms
at ~8x hand-encoding per-state cost): ``enabled_bits_vec`` builds the
enabled mask as a packed ``uint32[ceil(K/32)]`` bitmap from shift-mask
field extracts and host-packed not-noop bit tables (no per-slot table
gathers, no dense bool mask — GPUexplore-style guards-as-bitwise-ops,
arXiv:1801.05857), and ``step_slot_vec`` runs every per-row chain as
flat 1-D lane ops with static-lane selects for assembly (no
``[N, 1]``-shaped compute). tests/test_codegen_shapes.py pins both at
the jaxpr level.

**Properties and boundaries** are declared as *specs*: small functions
``spec(ctx, jnp) -> bool`` where ``ctx`` offers component-tabulated
values (:meth:`_SpecCtx.actor_values`, :meth:`_SpecCtx.history_value`,
:meth:`_SpecCtx.network_any`). The referenced host functions run only
at compile time, over component domains — never on device.

**Limits** (explicit, checked):

* Ordered (FIFO) networks need per-channel queue-length bounds:
  harvested by ``closure="reachable"`` from its host exploration, or
  DECLARED via ``closure_queue_bound`` so overapprox mode compiles
  with no host search (a protocol bound like ABD's clock/ops bounds;
  under-declared bounds raise the truncation flag instead of silently
  truncating). Lossy ordered networks are rejected (the reference
  drops arbitrary flow positions, which the head-only queue encoding
  cannot express). Channels encode as INTEGER QUEUES —
  base-(alphabet+1) numbers, head at the least-significant digit; pop
  is a divide, push adds ``code*base^len`` (network.rs:67, 221-244
  semantics, including the no-op-delivery exception of
  model.rs:317-319).
* Component domains must close finitely; systems whose local closure
  diverges under overapproximation (e.g. paxos ballots, which are
  bounded only by *system*-level reachability) exceed ``max_domain``
  and fail loudly — those keep hand-written encodings
  (models/paxos_tpu.py).
* Non-duplicating envelope counts ride in 8-bit fields with an
  effective bound of 127 (host ``encode`` raises at 128). On device, a
  successor whose count reaches 128 is pruned AND — unless the model
  boundary would prune it anyway — reported through ``step_vec``'s
  truncation flag, which every engine raises on: a model with
  unbounded multiset counts fails loudly instead of reporting a
  truncated space as verified.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..encoding import EncodedModelBase
from ..ops.bitmask import pack_bits_host
from ..fingerprint import stable_hash
from .base import CancelTimer, Cow, Id, Out, Send, SetTimer, is_no_op, \
    is_no_op_with_timer
from .model import ActorModel
from .network import Envelope, Ordered, UnorderedDuplicating, \
    UnorderedNonDuplicating


# -- spec context --------------------------------------------------------


class _SpecCtx:
    """What a property/boundary spec can read, all component-tabulated.

    The same spec body runs in two modes: at compile time the callbacks
    are evaluated over whole component domains to build tables; at
    trace time the tables are gathered by the state vector's component
    indices. Spec authors only see jnp scalars/arrays.
    """

    def __init__(self, enc: "CompiledActorEncoding", vec, jnp):
        self._enc = enc
        self._vec = vec
        self._jnp = jnp

    def actor_values(self, fn: Callable[[int, Any], Any]):
        """``jnp.int32[n_actors]`` — fn(i, local_state) per actor,
        tabulated over each actor's closure domain."""
        jnp = self._jnp
        enc = self._enc
        vals = []
        for i in range(enc.n):
            table = jnp.asarray(
                np.array([int(fn(i, s)) for s in enc.S[i]], dtype=np.int32)
            )
            vals.append(table[enc._get_actor_idx(self._vec, i, jnp)])
        return jnp.stack(vals)

    def history_value(self, fn: Callable[[Any], Any]):
        """``jnp.int32`` scalar — fn(history), tabulated over the
        history domain."""
        jnp = self._jnp
        enc = self._enc
        table = jnp.asarray(
            np.array([int(fn(h)) for h in enc.H], dtype=np.int32)
        )
        return table[enc._get_field(self._vec, enc.f_history, jnp)]

    def network_any(self, fn: Callable[[Envelope], bool]):
        """``jnp.bool_`` — True iff any envelope matching ``fn`` is
        currently deliverable."""
        jnp = self._jnp
        enc = self._enc
        hit = jnp.bool_(False)
        for k, env in enumerate(enc.E):
            if fn(env):
                hit = hit | (enc._net_count(self._vec, k, jnp) > 0)
        return hit

    def crashed_count(self):
        jnp = self._jnp
        enc = self._enc
        total = jnp.uint32(0)
        for i in range(enc.n):
            total = total + enc._get_field(self._vec, enc.f_crashed[i], jnp)
        return total


PropertySpec = Callable[[_SpecCtx, Any], Any]


# -- layout helpers ------------------------------------------------------


class _Field:
    """A bit field at (lane, shift, bits) in the uint32 state vector."""

    __slots__ = ("lane", "shift", "bits")

    def __init__(self, lane: int, shift: int, bits: int):
        self.lane, self.shift, self.bits = lane, shift, bits

    @property
    def mask(self) -> int:
        return ((1 << self.bits) - 1) << self.shift


class _LayoutBuilder:
    def __init__(self):
        self.lane = 0
        self.shift = 0

    def add(self, bits: int) -> _Field:
        if bits > 32:
            raise ValueError(f"field too wide: {bits} bits")
        if self.shift + bits > 32:  # fields never straddle lanes
            self.lane += 1
            self.shift = 0
        f = _Field(self.lane, self.shift, bits)
        self.shift += bits
        if self.shift == 32:
            self.lane += 1
            self.shift = 0
        return f

    @property
    def width(self) -> int:
        return self.lane + (1 if self.shift else 0)


def _bits_for(n: int) -> int:
    return max(1, (n - 1).bit_length()) if n > 1 else 1




def _domain_sort_key(value: Any):
    return (stable_hash(value), repr(value))


# -- the compiler --------------------------------------------------------


def compile_actor_model(
    model: ActorModel,
    properties: Optional[dict[str, PropertySpec]] = None,
    boundary: Optional[PropertySpec] = None,
    closure: str = "overapprox",
    closure_actor_bound: Optional[Callable[[int, Any], bool]] = None,
    closure_history_bound: Optional[Callable[[Any], bool]] = None,
    closure_queue_bound=None,
    max_domain: int = 1 << 15,
    closure_max_states: int = 1 << 21,
    device_rewrite_spec=None,
    ample_mask=None,
    optimize: bool = True,
    pair_width_hint: Optional[int] = None,
) -> "CompiledActorEncoding":
    """Compile ``model`` into a TPU :class:`EncodedModel`.

    ``properties`` maps each host property name to its device spec;
    every host property must have one (the encoding must discover the
    identical property set). ``boundary`` is the device counterpart of
    ``within_boundary_fn``. ``closure_*_bound`` stop the component
    closure from expanding values that only occur beyond the boundary
    (they are kept, unexpanded, so boundary evaluation still sees
    them — mirroring bfs.rs:279-281, where out-of-boundary successors
    are pruned before expansion in this engine and the host BFS alike).

    ``closure`` selects how component domains are discovered:

    * ``"overapprox"`` (default) — the fixpoint over all (state,
      envelope) pairs. No host exploration: the device does ALL the
      search work. Requires the per-component closure to converge
      (pass ``closure_*_bound`` for components only bounded by the
      boundary).
    * ``"reachable"`` — harvest domains and co-occurring pairs from a
      host breadth-first exploration at compile time. Always
      converges when the model does, with minimal domains — the right
      mode for protocols whose local closure diverges under
      overapproximation (e.g. ABD timestamps, which are bounded only
      by system-level reachability). The host explores once; use it
      as the bootstrap / differential mode, not the scale mode.

    ``closure_queue_bound`` makes ordered (FIFO) networks compile in
    overapprox mode (VERDICT r4 item 4): a declared per-channel
    queue-length bound — an ``int`` (uniform), a ``dict``
    ``{(src, dst): depth}`` with int actor ids, or a callable
    ``(src, dst) -> depth`` — replaces the queue bounds that
    ``closure="reachable"`` harvests from its host exploration. A
    protocol bound in the same family as ABD's clock/ops bounds: the
    device prunes a push past the declared depth and raises the
    truncation flag when the successor is in boundary, so an
    under-declared bound fails loudly rather than silently
    truncating. Ignored for unordered networks.

    ``device_rewrite_spec`` (an ``ops.canonical.DeviceRewriteSpec``)
    declares the encoding's interchangeable limb group for device
    symmetry reduction — validated against the compiled lane layout.
    ``ample_mask`` is a packed slot-word tuple (ops/bitmask.py layout)
    for the static ample-set filter; the caller owns its soundness
    argument (see encoding.SymmetricEncodedModel / ample_mask_host).

    ``optimize`` (default True) runs the post-``_build_tables`` codegen
    optimizer (round 23, PERF.md §compiled-parity): effect-class fusion
    (the deliver/timeout switch ladder collapses when the transition
    tables subsume it), flat-table interning + constant-column pruning
    (duplicate effect blocks share storage; host-constant table columns
    become immediates instead of gather lanes), history/crash gather
    elision, and word-level enabled-mask assembly from condition-gated
    host class masks (ops/bitmask.py builders — the hand encodings'
    predicate idiom). Semantics are identical either way (the
    differential tests run both); ``optimize=False`` keeps the naive
    emission for A/B ablation. The applied rewrites are reported in
    ``encoding.codegen_opt``.

    ``pair_width_hint`` declares a static bound on simultaneously
    enabled action slots per state — the EV the sparse engines size
    their per-row peel from. Unhinted UNORDERED compilations default
    to EV = K (every envelope slot), which makes the pair
    mask+peel+compact stage pay for slots that can never co-occur
    (PERF.md §compiled-parity: the production 2pc peel at EV=27 vs
    the hand encoding's declared 15). The caller owns the bound's
    argument (e.g. a bijection with a hand encoding's reasoning); an
    under-declared bound fails LOUDLY, not wrongly — the engines
    detect peel overflow, warn, and resize-and-retry from the
    measured peak (a recompile, never dropped pairs). Reachable-mode
    compilations measure the exact peak over the harvested space
    automatically; the declaration overrides even that.
    """
    return CompiledActorEncoding(
        model,
        properties or {},
        boundary,
        closure,
        closure_actor_bound,
        closure_history_bound,
        max_domain,
        closure_max_states,
        closure_queue_bound=closure_queue_bound,
        device_rewrite_spec=device_rewrite_spec,
        ample_mask=ample_mask,
        optimize=optimize,
        pair_width_hint=pair_width_hint,
    )


class CompiledActorEncoding(EncodedModelBase):
    def __init__(
        self,
        model: ActorModel,
        property_specs: dict[str, PropertySpec],
        boundary_spec: Optional[PropertySpec],
        closure_mode: str,
        closure_actor_bound,
        closure_history_bound,
        max_domain: int,
        closure_max_states: int,
        closure_queue_bound=None,
        device_rewrite_spec=None,
        ample_mask=None,
        optimize: bool = True,
        pair_width_hint: Optional[int] = None,
    ):
        if closure_mode not in ("overapprox", "reachable"):
            raise ValueError(f"unknown closure mode {closure_mode!r}")
        if pair_width_hint is not None and pair_width_hint < 1:
            raise ValueError(
                f"pair_width_hint must be >= 1, got {pair_width_hint}"
            )
        self._pair_width_decl = pair_width_hint
        #: reachable-mode measured enabled-slot peak (None until the
        #: harvest runs; stays None in overapprox mode)
        self._pair_width_auto: Optional[int] = None
        self.ordered = isinstance(model._init_network, Ordered)
        self._queue_bound_decl = closure_queue_bound
        if self.ordered:
            # FIFO queue lengths are bounded only by system-level
            # reachability (like ABD timestamps): either harvest the
            # bound from a reachable-mode host exploration, or accept
            # it as a DECLARED protocol bound so overapprox mode needs
            # no host search at all (VERDICT r4 item 4).
            if closure_mode != "reachable" and closure_queue_bound is None:
                raise ValueError(
                    "ordered (FIFO) networks need queue-length bounds: "
                    'use closure="reachable" (harvested bounds) or pass '
                    "closure_queue_bound (declared protocol bounds; "
                    "under-declared bounds raise the truncation flag)"
                )
            if model.lossy_network:
                raise ValueError(
                    "lossy ordered networks are not compiled yet (the "
                    "reference drops arbitrary flow positions, which "
                    "breaks the head-only queue encoding); use the host "
                    "checkers"
                )
        self.model = model
        self.host_model = model
        self.n = len(model.actors)
        self.dup = isinstance(model._init_network, UnorderedDuplicating)
        self.lossy = model.lossy_network
        self.max_crashes = model.max_crashes
        self.max_domain = max_domain
        self.closure_mode = closure_mode
        self.closure_max_states = closure_max_states
        self._actor_bound = closure_actor_bound or (lambda i, s: True)
        self._history_bound = closure_history_bound or (lambda h: True)
        self.property_specs = property_specs
        self.boundary_spec = boundary_spec

        host_props = [p.name for p in model.properties()]
        missing = [p for p in host_props if p not in property_specs]
        if missing:
            raise ValueError(
                f"no device spec for host properties {missing}; "
                "compile_actor_model needs a spec per property"
            )

        self._close()
        self._build_layout()
        self._build_tables()
        self._opt = None
        self.codegen_opt = None
        if optimize:
            self._optimize_codegen()
        self._spec = device_rewrite_spec
        self._ample_mask = ample_mask
        if device_rewrite_spec is not None:
            from ..ops.canonical import validate_spec

            validate_spec(device_rewrite_spec, width=self.width)
        if ample_mask is not None:
            from ..ops.bitmask import mask_words

            if len(ample_mask) != mask_words(self.max_actions):
                raise ValueError(
                    f"ample_mask has {len(ample_mask)} words; this "
                    f"encoding's {self.max_actions}-slot mask needs "
                    f"{mask_words(self.max_actions)}"
                )

    def device_rewrite_spec(self):
        """The declared symmetry spec (compile_actor_model's
        ``device_rewrite_spec``), or None."""
        return self._spec

    def ample_mask_host(self):
        """The declared ample-set slot words, or None."""
        return self._ample_mask

    def cache_key(self):
        """Identity for compiled-program sharing. Includes the property
        and boundary spec BODIES (bytecode + captured cell values), not
        just their names — two compilations with identical domains but
        different specs must not share a jitted chunk program. A spec
        whose captured values lack a stable repr over-distinguishes,
        which costs a recompile, never a wrong verdict."""
        def spec_fp(fn):
            if fn is None:
                return None
            code = getattr(fn, "__code__", None)
            if code is None:
                return repr(fn)
            cells = tuple(
                repr(c.cell_contents) for c in (fn.__closure__ or ())
            )
            return (code.co_code, repr(code.co_consts), cells)

        return (
            "actor-compile",
            self.n,
            self.dup,
            self.lossy,
            self.max_crashes,
            tuple(tuple(stable_hash(s) for s in S) for S in self.S),
            tuple(stable_hash(e) for e in self.E),
            tuple(stable_hash(h) for h in self.H),
            tuple(
                (name, spec_fp(fn))
                for name, fn in sorted(self.property_specs.items())
            ),
            spec_fp(self.boundary_spec),
            # Symmetry / ample declarations are baked into the chunk
            # program (canonicalization kernel, enabled-word AND).
            repr(self._spec) if self._spec is not None else None,
            tuple(self._ample_mask) if self._ample_mask else None,
            # Ordered: the queue bounds shape the integer-queue layout
            # (field widths), so two compilations differing only in
            # declared bounds must not share a chunk program.
            tuple(
                sorted(
                    (int(c[0]), int(c[1]), self.ch_q[c])
                    for c in self.channels
                )
            )
            if self.ordered
            else None,
            # The resolved EV bound shapes the engines' pair buffers
            # and peel loop — two compilations with different hints
            # must not share a chunk program (the engine's program key
            # reads the encoding's cache_key, not pair_width_hint).
            self.pair_width_hint,
            # The optimizer changes the traced emission (table shapes,
            # gather columns, mask assembly); optimized and naive
            # compilations of the same model must not share a chunk
            # program. The plan itself is a deterministic function of
            # the tables (already keyed above), so a flag suffices.
            "codegen-opt-v1" if self._opt is not None else "naive",
        )

    # -- closure ---------------------------------------------------------

    def _close(self) -> None:
        model = self.model
        init_states = list(model.init_states())
        if len(init_states) != 1:
            raise ValueError("ActorModel must have exactly one init state")
        init = init_states[0]
        self._init_state = init

        # Domains under construction (dict preserves insertion order;
        # sorted canonically after the fixpoint).
        S: list[dict] = [dict() for _ in range(self.n)]
        E: dict = {}
        T: list[dict] = [dict() for _ in range(self.n)]
        H: dict = {}
        expandable_s: list[dict] = [dict() for _ in range(self.n)]
        expandable_h: dict = {}
        work: deque = deque()

        def add_actor_state(i: int, s: Any) -> None:
            if s not in S[i]:
                if len(S[i]) >= self.max_domain:
                    raise RuntimeError(
                        f"actor {i} local-state closure exceeded "
                        f"{self.max_domain} values — the component closure "
                        "diverges (overapproximation pairs states with "
                        "envelopes that never co-occur; see module "
                        "docstring). Pass closure_actor_bound, use "
                        'closure="reachable", raise max_domain, or use '
                        "a hand encoding."
                    )
                S[i][s] = len(S[i])
                expandable_s[i][s] = bool(self._actor_bound(i, s))
                work.append(("s", i, s))

        def add_envelope(env: Envelope) -> None:
            if env not in E:
                if len(E) >= self.max_domain:
                    raise RuntimeError(
                        f"envelope-universe closure exceeded "
                        f"{self.max_domain} values — see the actor-state "
                        "divergence notes in the module docstring"
                    )
                E[env] = len(E)
                work.append(("e", env))

        def add_timer(i: int, t: Any) -> None:
            if t not in T[i]:
                T[i][t] = len(T[i])
                work.append(("t", i, t))

        def add_history(h: Any) -> None:
            if h not in H:
                if len(H) >= self.max_domain:
                    raise RuntimeError(
                        f"history closure exceeded {self.max_domain} values "
                        "— pass closure_history_bound (mirroring the "
                        "boundary) or use a hand encoding"
                    )
                H[h] = len(H)
                expandable_h[h] = bool(self._history_bound(h))
                work.append(("h", h))

        for i, s in enumerate(init.actor_states):
            add_actor_state(i, s)
        for env in init.network.iter_deliverable():
            add_envelope(env)
        for i, timers in enumerate(init.timers_set):
            for t in timers:
                add_timer(i, t)
        add_history(init.history)

        # Memoized handler transitions, filled during the fixpoint.
        self._msg_tr: dict = {}    # (i, s, env) -> (s2, noop, sends, tmap)
        self._tmo_tr: dict = {}    # (i, s, t)  -> (s2, noop, sends, tmap)
        self._hist_tr: dict = {}   # (h, env|None, sends) -> h2
        #: (i, s, env) pairs whose handler RAISED under overapprox
        #: (possibly system-unreachable). Ordered networks must keep
        #: these UNDELIVERABLE rather than forcing the usual noop-pop
        #: (a raising handler is not a pop): if such a pair is
        #: reachable, the host model raises there and the differential
        #: replay flags the divergence — same contract as unordered.
        self._raised_msg: set = set()

        def run_msg(i: int, s: Any, env: Envelope):
            key = (i, s, env)
            if key in self._msg_tr:
                return
            cow = Cow(s)
            out = Out()
            try:
                model.actors[i].on_msg(Id(i), cow, env.src, env.msg, out)
            except Exception as exc:
                if self.closure_mode == "reachable":
                    # Every harvested pair comes from a reachable system
                    # state: a raising handler is a genuine model bug
                    # (the reference propagates handler panics), not an
                    # overapproximation artifact — fail the compile.
                    raise RuntimeError(
                        f"actor {i} on_msg raised on a reachable "
                        f"(state, envelope) pair: state={s!r}, "
                        f"envelope={env!r}"
                    ) from exc
                # The closure overapproximates: this (state, envelope)
                # pair can be system-unreachable, in which case the
                # handler may legitimately reject it. Record a no-op
                # row; if the pair IS reachable the host model crashes
                # identically and the differential replay flags it.
                self._msg_tr[key] = (s, True, (), {})
                self._raised_msg.add(key)
                return
            noop = is_no_op(cow, out)
            sends, tmap = self._fold_commands(Id(i), out)
            self._msg_tr[key] = (cow.value, noop, sends, tmap)
            if not noop:
                add_actor_state(i, cow.value)
                for send in sends:
                    add_envelope(send)
                for t, armed in tmap.items():
                    if armed:
                        add_timer(i, t)

        def run_timeout(i: int, s: Any, t: Any):
            key = (i, s, t)
            if key in self._tmo_tr:
                return
            cow = Cow(s)
            out = Out()
            try:
                model.actors[i].on_timeout(Id(i), cow, t, out)
            except Exception as exc:
                if self.closure_mode == "reachable":
                    raise RuntimeError(
                        f"actor {i} on_timeout raised on a reachable "
                        f"(state, timer) pair: state={s!r}, timer={t!r}"
                    ) from exc
                self._tmo_tr[key] = (s, True, (), {})
                return
            noop = is_no_op_with_timer(cow, out, t)
            sends, tmap = self._fold_commands(Id(i), out)
            self._tmo_tr[key] = (cow.value, noop, sends, tmap)
            if not noop:
                add_actor_state(i, cow.value)
                for send in sends:
                    add_envelope(send)
                for t2, armed in tmap.items():
                    if armed:
                        add_timer(i, t2)

        def run_history(h: Any, env: Optional[Envelope],
                        sends: tuple) -> None:
            key = (h, env, sends)
            if key in self._hist_tr:
                return
            h2 = h
            try:
                if env is not None:
                    nh = model._record_msg_in(model.cfg, h2, env)
                    if nh is not None:
                        h2 = nh
                for send in sends:
                    nh = model._record_msg_out(model.cfg, h2, send)
                    if nh is not None:
                        h2 = nh
            except Exception:
                # Overapproximated (history, event) pair — e.g. a
                # double-invoke the real system cannot produce. Self-
                # loop; unreachable rows are never gathered.
                h2 = h
            self._hist_tr[key] = h2
            add_history(h2)

        if self.closure_mode == "reachable":
            self._harvest_reachable(
                model, init, add_actor_state, add_envelope, add_timer,
                add_history, run_msg, run_timeout, run_history,
            )
            work.clear()
        # Fixpoint: drain the worklist (actor-state / envelope / timer
        # cross-products), then close the history domain against the
        # current effect classes; repeat until neither grows.
        while self.closure_mode == "overapprox":
            while work:
                kind, *rest = work.popleft()
                if kind == "s":
                    i, s = rest
                    if not expandable_s[i][s]:
                        continue
                    for env in list(E):
                        if int(env.dst) == i:
                            run_msg(i, s, env)
                    for t in list(T[i]):
                        run_timeout(i, s, t)
                elif kind == "e":
                    (env,) = rest
                    i = int(env.dst)
                    if i < self.n:
                        for s in list(S[i]):
                            if expandable_s[i][s]:
                                run_msg(i, s, env)
                elif kind == "t":
                    i, t = rest
                    for s in list(S[i]):
                        if expandable_s[i][s]:
                            run_timeout(i, s, t)
                # "h" items just mark domain growth; the history
                # cross-product runs against effect classes below.
            classes = self._effect_classes()
            grew = False
            for h in list(H):
                if not expandable_h[h]:
                    continue
                for cls in classes:
                    if (h, cls[0], cls[1]) not in self._hist_tr:
                        run_history(h, cls[0], cls[1])
                        grew = True
            if not work and not grew:
                break

        self.S = [
            sorted(S[i], key=_domain_sort_key) for i in range(self.n)
        ]
        self.sidx = [
            {s: k for k, s in enumerate(self.S[i])} for i in range(self.n)
        ]
        self.E = sorted(E, key=lambda e: (_domain_sort_key(e)))
        self.eidx = {e: k for k, e in enumerate(self.E)}
        self.T = [sorted(T[i], key=_domain_sort_key) for i in range(self.n)]
        self.tidx = [
            {t: k for k, t in enumerate(self.T[i])} for i in range(self.n)
        ]
        self.H = sorted(H, key=_domain_sort_key)
        self.hidx = {h: k for k, h in enumerate(self.H)}
        self._expandable_s = expandable_s
        self._expandable_h = expandable_h

    def _harvest_reachable(self, model, init, add_actor_state,
                           add_envelope, add_timer, add_history,
                           run_msg, run_timeout, run_history) -> None:
        """Breadth-first host exploration; harvest component domains
        and exactly the (state, event) pairs that co-occur in reachable
        system states. Sound for the device engine because it explores
        the same space: only harvested pairs are ever gathered."""
        seen = {init}
        queue = deque([init])
        #: ordered only: per-channel max observed queue length
        self._q_bound: dict = {}
        # Enabled-slot peak over the harvested space: the harvest IS
        # the device space in reachable mode, so the observed peak is
        # an exact EV bound for the sparse engines' per-row peel
        # (pair_width_hint). Counted conservatively — drops over ALL
        # present envelopes, timers/crashes without liveness gating —
        # so it can only over-approximate the bitmap popcount; the
        # engines' peel-overflow guard resize-retries loudly anyway.
        peak_enabled = 0
        while queue:
            st = queue.popleft()
            for i, s in enumerate(st.actor_states):
                add_actor_state(i, s)
            present = set(st.network.iter_all())
            for env in present:
                add_envelope(env)
            if self.ordered:
                for ch, flow in st.network.flows.items():
                    self._q_bound[ch] = max(
                        self._q_bound.get(ch, 0), len(flow)
                    )
            for i, timers in enumerate(st.timers_set):
                for t in timers:
                    add_timer(i, t)
            add_history(st.history)
            n_enabled = (len(present) if self.lossy else 0) + sum(
                len(t) for t in st.timers_set
            )
            if self.max_crashes and sum(st.crashed) < self.max_crashes:
                n_enabled += self.n - sum(st.crashed)
            prev_channel = None
            for env in st.network.iter_deliverable():
                i = int(env.dst)
                if i >= self.n or st.crashed[i]:
                    continue
                if self.ordered:
                    # FIFO: only channel heads are deliverable, and a
                    # no-op handler still pops the queue and records
                    # history (model.rs:252-266, 317-319 exception).
                    channel = (env.src, env.dst)
                    if prev_channel == channel:
                        continue
                    prev_channel = channel
                n_enabled += 1
                run_msg(i, st.actor_states[i], env)
                tr = self._msg_tr[(i, st.actor_states[i], env)]
                if self.ordered or not tr[1]:
                    run_history(st.history, env, tr[2])
            for i, timers in enumerate(st.timers_set):
                for t in timers:
                    run_timeout(i, st.actor_states[i], t)
                    tr = self._tmo_tr[(i, st.actor_states[i], t)]
                    if not tr[1]:
                        run_history(st.history, None, tr[2])
            peak_enabled = max(peak_enabled, n_enabled)
            for action in model.actions(st):
                ns = model.next_state(st, action)
                if ns is None or not model.within_boundary(ns):
                    continue
                if ns not in seen:
                    if len(seen) >= self.closure_max_states:
                        raise RuntimeError(
                            f"reachable closure exceeded "
                            f"{self.closure_max_states} system states; "
                            "raise closure_max_states or use overapprox "
                            "mode with bounds"
                        )
                    seen.add(ns)
                    queue.append(ns)
        self._pair_width_auto = max(1, peak_enabled)

    def _declared_queue_bound(self, ch) -> int:
        """Resolve ``closure_queue_bound`` for channel ``ch`` =
        (src, dst): int (uniform), {(src, dst): depth} (int actor
        ids), or callable (src, dst) -> depth. 0 when undeclared."""
        decl = self._queue_bound_decl
        if decl is None:
            return 0
        if isinstance(decl, int):
            return decl
        key = (int(ch[0]), int(ch[1]))
        if isinstance(decl, dict):
            return int(decl.get(key, decl.get(ch, 0)))
        return int(decl(*key))

    def _fold_commands(self, id: Id, out: Out):
        """Sends in emission order + net timer effect (last op wins,
        mirroring _process_commands's sequential set algebra)."""
        sends: list[Envelope] = []
        tmap: dict[Any, bool] = {}
        for cmd in out.commands:
            if isinstance(cmd, Send):
                sends.append(Envelope(id, cmd.dst, cmd.msg))
            elif isinstance(cmd, SetTimer):
                tmap[cmd.timer] = True
            elif isinstance(cmd, CancelTimer):
                tmap[cmd.timer] = False
            else:
                raise TypeError(f"unknown command {cmd!r}")
        return tuple(sends), tmap

    def _effect_classes(self) -> list:
        """Distinct (env_in | None, sends) history-event signatures.
        Ordered networks record history on NO-OP deliveries too (the
        pop itself is the transition; model.rs:317-319 exception)."""
        seen = {}
        for (i, s, env), (s2, noop, sends, tmap) in self._msg_tr.items():
            if self.ordered or not noop:
                seen.setdefault((env, sends), None)
        for (i, s, t), (s2, noop, sends, tmap) in self._tmo_tr.items():
            if not noop:
                seen.setdefault((None, sends), None)
        return list(seen)

    # -- layout ----------------------------------------------------------

    def _build_layout(self) -> None:
        lb = _LayoutBuilder()
        self.f_actor = [lb.add(_bits_for(len(self.S[i]))) for i in
                        range(self.n)]
        self.f_history = lb.add(_bits_for(len(self.H)))
        self.f_crashed = [lb.add(1) for _ in range(self.n)]
        self.f_timer = [
            [lb.add(1) for _ in self.T[i]] for i in range(self.n)
        ]
        if self.ordered:
            # FIFO channels as INTEGER QUEUES: channel (src, dst) with
            # message alphabet A holds its queue as a base-(|A|+1)
            # number, head = least-significant digit (digit 0 = empty
            # slot, codes 1..|A|). Canonical by construction (one
            # integer per queue content), pop = divide by base, push =
            # add code*base^len — no ring pointers, no shifting
            # (encoding.py's "FIFO channels become fixed rings" design,
            # realized arithmetically).
            chans: dict = {}
            for env in self.E:
                chans.setdefault((env.src, env.dst), []).append(env)
            self.channels = sorted(chans, key=lambda c: (int(c[0]),
                                                         int(c[1])))
            self.chidx = {c: k for k, c in enumerate(self.channels)}
            #: per channel: sorted message list and msg -> 1-based code
            self.ch_msgs = {}
            self.ch_code = {}
            for ch, envs in chans.items():
                msgs = sorted(
                    {e.msg for e in envs}, key=_domain_sort_key
                )
                self.ch_msgs[ch] = msgs
                self.ch_code[ch] = {m: j + 1 for j, m in enumerate(msgs)}
            #: per channel: queue-length bound (harvested in reachable
            #: mode, declared via closure_queue_bound in overapprox
            #: mode; with both, the max wins so a declared bound can
            #: never shrink below what the host exploration observed)
            #: and base
            harvested = getattr(self, "_q_bound", {})
            self.ch_q = {}
            for ch in self.channels:
                q = max(
                    1,
                    harvested.get(ch, 0),
                    self._declared_queue_bound(ch),
                )
                # A DECLARED (not harvested) bound is a safety
                # ceiling, not an observed depth: cap it to the
                # deepest queue the 32-bit lane can hold at this
                # channel's alphabet. If the cap ever truncates a
                # reachable queue, the engines' truncation flag
                # raises — loud, never silent.
                if q > harvested.get(ch, 0):
                    base = len(self.ch_msgs[ch]) + 1
                    fit = q
                    while fit > 1 and (base**fit - 1).bit_length() > 32:
                        fit -= 1
                    if fit < q and fit > harvested.get(ch, 0):
                        import warnings

                        warnings.warn(
                            f"ordered channel {ch}: declared queue "
                            f"bound {q} needs more than one uint32 "
                            f"lane at alphabet {base - 1}; capped to "
                            f"{fit} (a reachable queue beyond the cap "
                            "raises the truncation error)",
                            RuntimeWarning,
                            stacklevel=2,
                        )
                        q = fit
                self.ch_q[ch] = q
            self.ch_base = {
                ch: len(self.ch_msgs[ch]) + 1 for ch in self.channels
            }
            self.f_ch = []
            for ch in self.channels:
                span = self.ch_base[ch] ** self.ch_q[ch]
                bits = max(1, (span - 1).bit_length())
                if bits > 32:
                    raise ValueError(
                        f"ordered channel {ch} needs {bits} queue bits "
                        f"(alphabet {len(self.ch_msgs[ch])}, max depth "
                        f"{self.ch_q[ch]}) — exceeds one uint32 lane; "
                        "bound the model or use the host checkers"
                    )
                self.f_ch.append(lb.add(bits))
            self.f_net = []
            self.width = lb.width
            self._net_top_mask = np.zeros(self.width, np.uint32)
        else:
            # Network: 1 bit per envelope (duplicating set) or an 8-bit
            # count per envelope (non-duplicating multiset).
            bits = 1 if self.dup else 8
            self.f_net = [lb.add(bits) for _ in self.E]
            self.width = lb.width
            # Per-lane mask of every count field's TOP bit: a successor
            # with any count ≥ 128 is treated as beyond an implicit
            # bound and pruned (valid=False) rather than risking a
            # carry into the neighboring field — the device-side
            # counterpart of encode()'s loud 8-bit check.
            # Closure-bounded systems stay far below this.
            self._net_top_mask = np.zeros(self.width, np.uint32)
            if not self.dup:
                for f in self.f_net:
                    self._net_top_mask[f.lane] |= np.uint32(
                        1 << (f.shift + bits - 1)
                    )

        # Action slots: delivers, drops, timeouts, crashes.
        self.deliver_slots = [
            k for k, e in enumerate(self.E) if int(e.dst) < self.n
        ]
        self.drop_slots = list(range(len(self.E))) if self.lossy else []
        self.timeout_slots = [
            (i, j) for i in range(self.n) for j in range(len(self.T[i]))
        ]
        self.crash_slots = (
            list(range(self.n)) if self.max_crashes > 0 else []
        )
        self.max_actions = (
            len(self.deliver_slots)
            + len(self.drop_slots)
            + len(self.timeout_slots)
            + len(self.crash_slots)
        )
        if self.max_actions == 0:
            self.max_actions = 1  # engines require K >= 1

    # -- tables ----------------------------------------------------------

    def _tr_effects(self, i: int, tr, fired_timer=None, force=False):
        """(next_state_idx, noop, net_delta[W], timer_and[W], timer_or[W],
        snd_ch[SMAX], snd_code[SMAX]) for one transition record.
        ``force`` applies the effects even for a no-op (ordered
        deliveries: the queue pop is a transition regardless)."""
        s2, noop, sends, tmap = tr
        apply = force or not noop
        next_idx = self.sidx[i][s2] if apply else 0
        net_delta = np.zeros(self.width, np.uint32)
        snd_ch = np.zeros(self._smax, np.uint32)
        snd_code = np.zeros(self._smax, np.uint32)
        if apply:
            for j, env in enumerate(sends):
                if self.ordered:
                    ch = (env.src, env.dst)
                    snd_ch[j] = self.chidx[ch]
                    snd_code[j] = self.ch_code[ch][env.msg]
                else:
                    f = self.f_net[self.eidx[env]]
                    if self.dup:
                        net_delta[f.lane] |= np.uint32(1 << f.shift)
                    else:
                        net_delta[f.lane] += np.uint32(1 << f.shift)
        t_and = np.full(self.width, 0xFFFFFFFF, np.uint32)
        t_or = np.zeros(self.width, np.uint32)
        if fired_timer is not None:
            f = self.f_timer[i][self.tidx[i][fired_timer]]
            t_and[f.lane] &= ~np.uint32(1 << f.shift)
        if apply:
            for t, armed in tmap.items():
                f = self.f_timer[i][self.tidx[i][t]]
                if armed:
                    t_or[f.lane] |= np.uint32(1 << f.shift)
                    t_and[f.lane] |= np.uint32(1 << f.shift)
                else:
                    t_and[f.lane] &= ~np.uint32(1 << f.shift)
                    t_or[f.lane] &= ~np.uint32(1 << f.shift)
        return next_idx, noop, net_delta, t_and, t_or, snd_ch, snd_code

    def _build_tables(self) -> None:
        classes = self._effect_classes()
        cls_idx = {c: k for k, c in enumerate(classes)}
        n_cls = max(1, len(classes))
        #: max sends per applied transition (send-sequence columns)
        self._smax = max(
            [1]
            + [
                len(tr[2])
                for tr in self._msg_tr.values()
                if self.ordered or not tr[1]
            ]
            + [len(tr[2]) for tr in self._tmo_tr.values() if not tr[1]]
        )

        # Per deliver slot: tables indexed by the dst actor's state idx.
        self.tbl_deliver = []
        for k in self.deliver_slots:
            env = self.E[k]
            i = int(env.dst)
            ns = len(self.S[i])
            nxt = np.zeros(ns, np.uint32)
            noop = np.ones(ns, bool)
            ndl = np.zeros((ns, self.width), np.uint32)
            tan = np.full((ns, self.width), 0xFFFFFFFF, np.uint32)
            tor = np.zeros((ns, self.width), np.uint32)
            hcl = np.zeros(ns, np.uint32)
            sch = np.zeros((ns, self._smax), np.uint32)
            scd = np.zeros((ns, self._smax), np.uint32)
            for si, s in enumerate(self.S[i]):
                tr = self._msg_tr.get((i, s, env))
                if tr is None:
                    continue  # unexpandable state: row never used
                (nxt[si], noop[si], ndl[si], tan[si], tor[si],
                 sch[si], scd[si]) = self._tr_effects(
                    i, tr, force=self.ordered
                )
                if self.ordered:
                    if (i, s, env) in self._raised_msg:
                        # A raising handler is NOT a pop: keep the
                        # row undeliverable (see _raised_msg notes).
                        noop[si] = True
                    else:
                        # Ordered records history on no-op pops too.
                        noop[si] = False
                        hcl[si] = cls_idx[(env, tr[2])]
                elif not noop[si]:
                    hcl[si] = cls_idx[(env, tr[2])]
            self.tbl_deliver.append(
                (i, k, nxt, noop, ndl, tan, tor, hcl, sch, scd)
            )

        self.tbl_timeout = []
        for (i, j) in self.timeout_slots:
            t = self.T[i][j]
            ns = len(self.S[i])
            nxt = np.zeros(ns, np.uint32)
            noop = np.ones(ns, bool)
            ndl = np.zeros((ns, self.width), np.uint32)
            tan = np.full((ns, self.width), 0xFFFFFFFF, np.uint32)
            tor = np.zeros((ns, self.width), np.uint32)
            hcl = np.zeros(ns, np.uint32)
            sch = np.zeros((ns, self._smax), np.uint32)
            scd = np.zeros((ns, self._smax), np.uint32)
            for si, s in enumerate(self.S[i]):
                tr = self._tmo_tr.get((i, s, t))
                if tr is None:
                    continue
                (nxt[si], noop[si], ndl[si], tan[si], tor[si],
                 sch[si], scd[si]) = self._tr_effects(
                    i, tr, fired_timer=t
                )
                if not noop[si]:
                    hcl[si] = cls_idx[(None, tr[2])]
            self.tbl_timeout.append(
                (i, j, nxt, noop, ndl, tan, tor, hcl, sch, scd)
            )

        # History table: H × effect classes. Un-harvested (h, class)
        # transitions (h beyond closure_history_bound — reachable only
        # when a search continues past a violating state, or when the
        # bound is tighter than the model boundary) are tracked in a
        # parallel missing-mask and surfaced through the engines'
        # truncation flag; defaulting them to history 0 silently
        # corrupted post-violation successors (ADVICE r4).
        # Only the PACKED form is kept: history index in bits 0-30
        # (bounded far below 2^31 by max_domain), missing flag in bit
        # 31 — one gather serves both in the per-pair/per-slot step.
        hist = np.zeros((len(self.H), n_cls), np.uint32)
        missing = np.ones((len(self.H), n_cls), bool)
        # Sentinel lookup, NOT .get(key) is-None: a history-free model
        # (init_history=None) legitimately stores None as the harvested
        # next-history value, and conflating that with "key absent"
        # marked EVERY deliver/timeout missing — hard-truncating the
        # whole model on its first wave.
        _absent = object()
        for hi, h in enumerate(self.H):
            for ci, cls in enumerate(classes):
                h2 = self._hist_tr.get((h, cls[0], cls[1]), _absent)
                if h2 is not _absent:
                    hist[hi, ci] = self.hidx[h2]
                    missing[hi, ci] = False
        self.tbl_history_packed = hist | (
            missing.astype(np.uint32) << 31
        )
        self.n_cls = n_cls
        self._build_sparse_tables()

    # -- sparse dispatch tables (SparseEncodedModel) ----------------------
    #
    # The same per-slot tables the dense step unrolls statically,
    # re-laid-out for TRACED slot indices so the sort-merge engine's
    # sparse path (checkers/tpu_sortmerge.py) can run the transition on
    # compacted (row, slot) pairs only. Layout is gather-lean (the TPU
    # pair-kernel lessons from PERF.md §sparse): all per-slot constants
    # pack into ONE [A, 12] params row, all per-(slot, actor-state)
    # transition effects into ONE [R, 3W+3] flat row, and every lane
    # read/write is a static per-lane select — never a dynamic-index
    # scatter.
    #
    # Params row layout (uint32):
    #   0 kind (0=deliver, 1=drop, 2=timeout, 3=crash, 4=pad)
    #   1 actor index (deliver dst / timeout owner / crash target)
    #   2 flat-table row offset (deliver/timeout)
    #   3 actor-state field lane   4 shift   5 mask
    #   6 net/queue field lane     7 shift   8 mask   (deliver/drop)
    #   9 timer/crashed field lane 10 shift
    #   11 channel base (ordered deliver; 0 otherwise)
    #   12 head code (ordered deliver)
    #   13 channel index (ordered deliver)
    # Flat transition row layout: [nxt, noop, hcl] + ndl[W] + tan[W]
    # + tor[W] + snd_ch[SMAX] + snd_code[SMAX].

    _SK_DELIVER, _SK_DROP, _SK_TIMEOUT, _SK_CRASH, _SK_PAD = range(5)

    def _build_sparse_tables(self) -> None:
        W = self.width
        A = self.max_actions
        params = np.zeros((A, 14), np.uint32)
        params[:, 0] = self._SK_PAD
        flat_rows: list = []

        def flat_of(tbl) -> int:
            """Append one per-state transition block; return its base
            row. tbl = (nxt, noop, ndl, tan, tor, hcl, sch, scd) arrays
            over the dst actor's state domain."""
            nxt, noop, ndl, tan, tor, hcl, sch, scd = tbl
            base = len(flat_rows)
            for si in range(len(nxt)):
                flat_rows.append(
                    np.concatenate(
                        [
                            np.array(
                                [nxt[si], np.uint32(bool(noop[si])),
                                 hcl[si]],
                                np.uint32,
                            ),
                            ndl[si], tan[si], tor[si],
                            sch[si], scd[si],
                        ]
                    )
                )
            return base

        a = 0
        for (i, k, nxt, noop, ndl, tan, tor, hcl, sch, scd) in (
            self.tbl_deliver
        ):
            f = self.f_actor[i]
            row = [
                self._SK_DELIVER, i,
                flat_of((nxt, noop, ndl, tan, tor, hcl, sch, scd)),
                f.lane, f.shift, (1 << f.bits) - 1,
                0, 0, 0, 0, 0, 0, 0, 0,
            ]
            if self.ordered:
                env = self.E[k]
                ch = (env.src, env.dst)
                ci = self.chidx[ch]
                fq = self.f_ch[ci]
                row[6:9] = [fq.lane, fq.shift, (1 << fq.bits) - 1]
                row[11] = self.ch_base[ch]
                row[12] = self.ch_code[ch][env.msg]
                row[13] = ci
            else:
                fn = self.f_net[k]
                row[6:9] = [fn.lane, fn.shift, (1 << fn.bits) - 1]
            params[a] = row
            a += 1
        for k in self.drop_slots:
            fn = self.f_net[k]
            params[a] = [
                self._SK_DROP, 0, 0, 0, 0, 0,
                fn.lane, fn.shift, (1 << fn.bits) - 1, 0, 0, 0, 0, 0,
            ]
            a += 1
        for (i, j, nxt, noop, ndl, tan, tor, hcl, sch, scd) in (
            self.tbl_timeout
        ):
            f, ft = self.f_actor[i], self.f_timer[i][j]
            params[a] = [
                self._SK_TIMEOUT, i,
                flat_of((nxt, noop, ndl, tan, tor, hcl, sch, scd)),
                f.lane, f.shift, (1 << f.bits) - 1,
                0, 0, 0, ft.lane, ft.shift, 0, 0, 0,
            ]
            a += 1
        for i in self.crash_slots:
            fc = self.f_crashed[i]
            params[a] = [
                self._SK_CRASH, i, 0, 0, 0, 0, 0, 0, 0,
                fc.lane, fc.shift, 0, 0, 0,
            ]
            a += 1

        self._sp_params = params
        self._sp_flat = (
            np.stack(flat_rows)
            if flat_rows
            else np.zeros((1, 3 + 3 * W + 2 * self._smax), np.uint32)
        )
        self._sp_hist_flat = self.tbl_history_packed.reshape(-1)
        # Crash: per-actor [W] AND-mask clearing every timer bit.
        cr = np.full((max(1, self.n), W), 0xFFFFFFFF, np.uint32)
        for i in range(self.n):
            for ftm in self.f_timer[i]:
                cr[i, ftm.lane] &= ~np.uint32(1 << ftm.shift)
        self._sp_crash_and = cr

        # Per-slot specs for the PACKED bitmap mask (enabled_bits_vec):
        # the same slot order as params, but with each slot's
        # (state-indexed) not-noop table pre-packed into host-constant
        # bit words, so the traced mask is pure shift-mask ALU — no
        # per-slot table gathers, no dense [F, K] bool (PERF.md
        # §ordered: the compiled-codegen mask tax).
        mask_slots: list = []
        for (i, k, nxt, noop, ndl, tan, tor, hcl, sch, scd) in (
            self.tbl_deliver
        ):
            mask_slots.append(("deliver", i, k, pack_bits_host(~noop)))
        for k in self.drop_slots:
            mask_slots.append(("drop", k))
        for (i, j, nxt, noop, ndl, tan, tor, hcl, sch, scd) in (
            self.tbl_timeout
        ):
            mask_slots.append(("timeout", i, j, pack_bits_host(~noop)))
        for i in self.crash_slots:
            mask_slots.append(("crash", i))
        self._mask_slots = mask_slots

    # -- codegen optimizer (round 23) -------------------------------------

    def _optimize_codegen(self) -> None:
        """Post-``_build_tables`` table/emission rewrite (PERF.md
        §compiled-parity): computes a host-side plan that the optimized
        ``enabled_bits_vec`` / ``step_slot_vec`` emissions trace from,
        leaving the naive tables (and the dense ``step_vec``) intact as
        the differential baseline.

        * **Effect-class fusion** — deliver and timeout collapse into
          ONE table class: timeout rows carry all-zero channel/envelope
          params, so the deliver formula (nondup decrement, ordered
          head pop) degenerates to the identity on them and the kind
          switch disappears. The drop and crash branches are emitted
          only when such slots exist; with neither, the 4-way select
          ladder and the kind column vanish entirely.
        * **Table interning + constant-column pruning** — duplicate
          per-state effect blocks share one flat base (host interning
          by block bytes); table columns that are constant over every
          row become immediates, shrinking the two row gathers to the
          columns that actually vary. A params/flat gather whose every
          read column is constant is dropped altogether.
        * **History / crash elision** — a single-valued, fully
          harvested history domain drops the packed history gather,
          the history field write, and the hard-truncation flag; a
          crash-free model drops the crash AND-mask gather and the
          per-actor crashed gating on deliver guards.
        * **Word-level mask plan** — the enabled mask is rebuilt from
          condition-gated host class masks (ops/bitmask.py:
          ``slot_mask_host`` / ``or_class_words`` /
          ``select_words_host`` — the PR-2 hand-encoding lever) with
          single-bit presence extracts coalesced into word runs
          (``bit_run_plan``), instead of per-slot lane predicates.
        """
        from ..ops.bitmask import bit_run_plan, mask_words, slot_mask_host

        W = self.width
        A = self.max_actions
        real = [
            a for a in range(A)
            if self._sp_params[a, 0] != self._SK_PAD
        ]
        table_slots = [
            a for a in real
            if self._sp_params[a, 0]
            in (self._SK_DELIVER, self._SK_TIMEOUT)
        ]
        if not table_slots:
            return  # degenerate encoding: nothing to rewrite
        hist = self._sp_hist_flat
        trivial_history = (
            len(self.H) == 1
            and not (hist >> np.uint32(31)).any()
            and not (hist & np.uint32(0x7FFFFFFF)).any()
        )
        has_drop = any(
            self._sp_params[a, 0] == self._SK_DROP for a in real
        )
        has_crash = any(
            self._sp_params[a, 0] == self._SK_CRASH for a in real
        )

        # (a) flat-block interning: duplicate effect blocks share one
        # base row (paxos-style identical-effect envelopes); a dead
        # history-class column is zeroed first so it can't defeat
        # sharing.
        params = self._sp_params.copy()
        fw = self._sp_flat.shape[1]
        blocks: dict = {}
        new_rows: list = []
        for a in table_slots:
            i = int(params[a, 1])
            ns = len(self.S[i])
            base = int(params[a, 2])
            blk = self._sp_flat[base : base + ns].copy()
            if trivial_history:
                blk[:, 2] = 0
            key = blk.tobytes()
            if key not in blocks:
                blocks[key] = len(new_rows)
                new_rows.extend(blk)
            params[a, 2] = blocks[key]
        flat2 = (
            np.stack(new_rows).astype(np.uint32)
            if new_rows
            else np.zeros((1, fw), np.uint32)
        )

        # Constant-column pruning over the columns the emission READS
        # (the noop column is never read by the sparse step; send
        # columns only exist for ordered networks). Pad params rows are
        # rewritten to copies of a real row first — they are never
        # enabled, never stepped, and must not defeat constancy.
        for a in range(A):
            if self._sp_params[a, 0] == self._SK_PAD:
                params[a] = params[real[0]]

        read_f = [0]
        if not trivial_history:
            read_f.append(2)
        read_f += [3 + j for j in range(3 * W)]
        if self.ordered:
            read_f += [3 + 3 * W + j for j in range(2 * self._smax)]
        keep_f: list = []
        fcol: dict = {}
        for c in read_f:
            col = flat2[:, c]
            if (col == col[0]).all():
                fcol[c] = ("c", int(col[0]))
            else:
                fcol[c] = ("v", len(keep_f))
                keep_f.append(c)
        flat_opt = flat2[:, keep_f] if keep_f else None

        read_p = [2, 3, 4, 5]
        if has_drop or has_crash:
            read_p.insert(0, 0)
        if has_crash:
            read_p += [1, 9, 10]
        if self.ordered:
            read_p += [6, 7, 8, 11]
        elif (not self.dup) or has_drop:
            read_p += [6, 7, 8]
        if flat_opt is None:
            read_p.remove(2)  # no flat gather left to base-index
        keep_p: list = []
        pcol: dict = {}
        for c in sorted(read_p):
            col = params[:, c]
            if (col == col[0]).all():
                pcol[c] = ("c", int(col[0]))
            else:
                pcol[c] = ("v", len(keep_p))
                keep_p.append(c)
        params_opt = params[:, keep_p] if keep_p else None

        # (b) the word-level mask plan: guard groups keyed by (actor,
        # packed not-noop table, crash gating) — every slot of a group
        # shares ONE traced condition; single-bit presence sources
        # (dup envelope bits, timer armed bits) coalesce into runs.
        L = mask_words(A)
        groups: dict = {}
        run_sources: list = []
        slot_pres: list = []
        pres_const_slots: list = []
        guardless: list = []
        crash_conds: list = []
        gate = self.max_crashes > 0
        for a, spec in enumerate(self._mask_slots):
            kind = spec[0]
            if kind == "deliver":
                _, i, k, nn = spec
                groups.setdefault((i, nn, gate), []).append(a)
                if self.ordered:
                    slot_pres.append((a, ("ord", k)))
                elif self.dup and self.f_net[k].bits == 1:
                    f = self.f_net[k]
                    run_sources.append((a, f.lane, f.shift))
                else:
                    slot_pres.append((a, ("net", k)))
            elif kind == "timeout":
                _, i, j, nn = spec
                groups.setdefault((i, nn, False), []).append(a)
                ft = self.f_timer[i][j]
                run_sources.append((a, ft.lane, ft.shift))
            elif kind == "drop":
                k = spec[1]
                guardless.append(a)
                if self.dup and self.f_net[k].bits == 1:
                    f = self.f_net[k]
                    run_sources.append((a, f.lane, f.shift))
                else:
                    slot_pres.append((a, ("net", k)))
            else:  # crash
                i = spec[1]
                pres_const_slots.append(a)
                crash_conds.append((i, slot_mask_host(A, [a])))

        # Small-domain actors with several ungated guard groups fold
        # into ONE select_words_host row table (one where-chain over
        # the domain replaces all that actor's bit_selects); everyone
        # else stays a bit_select-gated class.
        by_actor: dict = {}
        for (i, nn, g), slots in sorted(groups.items()):
            by_actor.setdefault(i, []).append((nn, g, slots))
        sel_actors: dict = {}
        bitsel: list = []
        for i, gs in sorted(by_actor.items()):
            ns = len(self.S[i])
            if ns <= 16 and len(gs) >= 2 and not any(g for _, g, _ in gs):
                rows = []
                for v in range(ns):
                    w = [0] * L
                    for nn, _, slots in gs:
                        if (nn[v // 32] >> (v % 32)) & 1:
                            sw = slot_mask_host(A, slots)
                            for x in range(L):
                                w[x] |= sw[x]
                    rows.append(tuple(w))
                sel_actors[i] = rows
            else:
                for nn, g, slots in gs:
                    bitsel.append((i, nn, g, slot_mask_host(A, slots)))

        runs = bit_run_plan(A, run_sources)
        self._opt = dict(
            trivial_history=trivial_history,
            has_drop=has_drop,
            has_crash=has_crash,
            params=params_opt,
            pcol=pcol,
            flat=flat_opt,
            fcol=fcol,
            mask=dict(
                runs=runs,
                slot_pres=slot_pres,
                pres_const=slot_mask_host(A, pres_const_slots),
                guardless=slot_mask_host(A, guardless),
                sel_actors=sel_actors,
                bitsel=bitsel,
                crash_conds=crash_conds,
            ),
        )
        self.codegen_opt = {
            "fused_switch": not (has_drop or has_crash),
            "history_gather_elided": trivial_history,
            "crash_gather_elided": not has_crash,
            "flat_rows": [int(self._sp_flat.shape[0]),
                          int(flat2.shape[0])],
            "flat_cols": [int(fw), len(keep_f)],
            "params_cols": [14, len(keep_p)],
            "step_gathers": (
                int(params_opt is not None)
                + int(flat_opt is not None)
                + int(not trivial_history)
                + int(has_crash)
            ),
            "mask_guard_selects": len(sel_actors),
            "mask_guard_classes": len(bitsel),
            "mask_bit_runs": len(runs),
            "mask_per_slot": len(slot_pres),
            "k": int(A),
        }

    def _enabled_bits_opt(self, vec):
        """Optimized mask emission: presence words (coalesced bit runs
        + per-slot leftovers + crash constants) AND guard words (per
        small-domain-actor row selects | condition-gated classes) —
        O(L x classes) lane ops, zero gathers, no dense bool."""
        import jax.numpy as jnp

        from ..ops.bitmask import (
            bit_select,
            const_words,
            mask_words,
            or_bit_runs,
            or_class_words,
            select_words_host,
        )

        u32 = jnp.uint32
        mp = self._opt["mask"]
        L = mask_words(self.max_actions)

        need_idx = set(mp["sel_actors"]) | {c[0] for c in mp["bitsel"]}
        s_idx = {
            i: self._get_actor_idx(vec, i, jnp)
            for i in sorted(need_idx)
        }
        need_cr = {c[0] for c in mp["bitsel"] if c[2]} | {
            i for i, _ in mp["crash_conds"]
        }
        crashed = {
            i: self._get_field(vec, self.f_crashed[i], jnp) != 0
            for i in sorted(need_cr)
        }
        if mp["crash_conds"]:
            allc = [
                self._get_field(vec, self.f_crashed[i], jnp) != 0
                for i in range(self.n)
            ]
            ncr = allc[0].astype(u32)
            for c in allc[1:]:
                ncr = ncr + c.astype(u32)
            can_crash = ncr < u32(self.max_crashes)

        pres = or_bit_runs(jnp, vec, mp["runs"], L)

        def fx(f):
            return (vec[f.lane] >> u32(f.shift)) & u32(
                (1 << f.bits) - 1
            )

        for a, spec in mp["slot_pres"]:
            if spec[0] == "ord":
                env = self.E[spec[1]]
                ch = (env.src, env.dst)
                b = (
                    fx(self.f_ch[self.chidx[ch]])
                    % u32(self.ch_base[ch])
                ) == u32(self.ch_code[ch][env.msg])
            else:
                b = fx(self.f_net[spec[1]]) != 0
            w, p = a // 32, a % 32
            t = b.astype(u32)
            if p:
                t = t << u32(p)
            pres[w] = t if pres[w] is None else pres[w] | t
        for w in range(L):
            cw = mp["pres_const"][w]
            if cw:
                pres[w] = (
                    u32(cw) if pres[w] is None else pres[w] | u32(cw)
                )

        guard = None
        for i, rows in sorted(mp["sel_actors"].items()):
            term = select_words_host(jnp, rows, s_idx[i])
            guard = term if guard is None else guard | term
        classes = []
        for i, nn, g, words in mp["bitsel"]:
            cond = bit_select(jnp, nn, s_idx[i]) != 0
            if g:
                cond = cond & ~crashed[i]
            classes.append((cond, words))
        for i, words in mp["crash_conds"]:
            classes.append((~crashed[i] & can_crash, words))
        if classes:
            cls = or_class_words(jnp, classes, L)
            if L == 1 and cls.ndim == 1:
                # or_class_words restores the [L] row contract at its
                # end; at L=1 the guard chain must stay SCALAR — a
                # [1]-shaped `or` is real compute at 128x lane
                # padding (the no-lane-padded-alu rule). Static index
                # = slice+squeeze, not a gather.
                cls = cls[0]
            guard = cls if guard is None else guard | cls
        if any(mp["guardless"]):
            gw = const_words(jnp, mp["guardless"])
            guard = gw if guard is None else guard | gw

        # Per-word scalar AND before the single update-slice per word:
        # vmapped math stays [N]-shaped (no [N, 1] ALU; the same
        # discipline as the naive emission and the hand encodings).
        # At L=1 `guard` is a scalar (every builder degenerates to
        # scalar words there); at L>1 it is a [L] row indexed
        # statically per word.
        out = jnp.zeros(L, u32)
        for w in range(L):
            if pres[w] is None:
                continue
            word = pres[w]
            if guard is not None:
                word = word & (guard if L == 1 else guard[w])
            out = out.at[w].set(word)
        return out

    def _step_slot_opt(self, vec, slot):
        """Optimized step emission traced from the ``_opt`` plan: the
        surviving row gathers (pruned params/flat columns), fused
        deliver/timeout table path, branch ladder only over the effect
        classes that exist, and lane writes only on lanes some effect
        can touch."""
        import jax.numpy as jnp

        xp = jnp
        W = self.width
        u32 = xp.uint32
        plan = self._opt
        slot = slot.astype(u32)
        prow = (
            xp.asarray(plan["params"])[slot]
            if plan["params"] is not None
            else None
        )

        def pc(c):
            tag, v = plan["pcol"][c]
            return v if tag == "c" else prow[v]

        def tr(x):
            return u32(x) if isinstance(x, int) else x

        lanes = [vec[j] for j in range(W)]

        def lane_sel(vals, idx):
            if isinstance(idx, int):
                return vals[idx]
            v = vals[0]
            for j in range(1, W):
                v = xp.where(idx == j, vals[j], v)
            return v

        al, ash, am = pc(3), pc(4), pc(5)
        s_idx = (lane_sel(lanes, al) >> tr(ash)) & tr(am)
        if plan["flat"] is not None:
            F = plan["flat"]
            frow_i = xp.minimum(
                tr(pc(2)) + s_idx, u32(F.shape[0] - 1)
            )
            frow = xp.asarray(F)[frow_i]

        def fc(c):
            tag, v = plan["fcol"][c]
            return v if tag == "c" else frow[v]

        def fconst(c):
            tag, v = plan["fcol"][c]
            return v if tag == "c" else None

        nxt = fc(0)
        trivial_h = plan["trivial_history"]
        if not trivial_h:
            h_idx = self._get_field(vec, self.f_history, xp)
            hg = xp.asarray(self._sp_hist_flat)[
                h_idx * u32(self.n_cls) + tr(fc(2))
            ]
            h2 = hg & u32(0x7FFFFFFF)
            h_missing = (hg >> 31) != 0
        hf = self.f_history
        if isinstance(am, int) and isinstance(ash, int):
            amask = u32((am << ash) & 0xFFFFFFFF)
        else:
            amask = tr(am) << tr(ash)
        aval = (tr(nxt) & tr(am)) << tr(ash)

        app = []
        for j in range(W):
            v = lanes[j]
            if isinstance(al, int):
                if al == j:
                    v = (v & ~amask) | aval
            else:
                v = xp.where(al == j, (v & ~amask) | aval, v)
            if fconst(3 + j) != 0:
                d = tr(fc(3 + j))
                v = (v | d) if self.dup else (v + d)
            if not (
                fconst(3 + W + j) == 0xFFFFFFFF
                and fconst(3 + 2 * W + j) == 0
            ):
                v = (v & tr(fc(3 + W + j))) | tr(fc(3 + 2 * W + j))
            if not trivial_h and j == hf.lane:
                v = (v & ~u32(hf.mask)) | (
                    (h2 & u32((1 << hf.bits) - 1)) << u32(hf.shift)
                )
            app.append(v)

        ord_over = xp.bool_(False)
        if self.ordered:
            # FUSED deliver/timeout: the pop is no longer kind-gated —
            # timeout rows carry zero channel params, so pop_amt is
            # zero there by table construction.
            base = xp.maximum(tr(pc(11)), u32(1))
            nl, nsh, nm = pc(6), pc(7), pc(8)
            qv = (lane_sel(app, nl) >> tr(nsh)) & tr(nm)
            pop_amt = (qv - qv // base) << tr(nsh)
            if isinstance(nl, int):
                s_table = list(app)
                s_table[nl] = app[nl] - pop_amt
            else:
                s_table = [
                    app[j] - xp.where(nl == j, pop_amt, u32(0))
                    for j in range(W)
                ]
            for j in range(self._smax):
                if fconst(3 + 3 * W + self._smax + j) == 0:
                    continue  # no row ever sends in this emission slot
                chj = fc(3 + 3 * W + j)
                cdj = tr(fc(3 + 3 * W + self._smax + j))
                do = cdj > 0
                adds: dict = {}
                for cc in range(len(self.channels)):
                    if isinstance(chj, int) and chj != cc:
                        continue
                    cch = self.channels[cc]
                    cbase = self.ch_base[cch]
                    Q = self.ch_q[cch]
                    f = self.f_ch[cc]
                    fmask = u32((1 << f.bits) - 1)
                    q = (s_table[f.lane] >> u32(f.shift)) & fmask
                    ln = sum(
                        (q >= u32(cbase**p)).astype(u32)
                        for p in range(Q)
                    )
                    powv = u32(0)
                    for pp in range(Q):
                        powv = xp.where(
                            ln == pp, u32(cbase**pp), powv
                        )
                    sel = (
                        do
                        if isinstance(chj, int)
                        else do & (chj == cc)
                    )
                    full = ln >= Q
                    adds[f.lane] = adds.get(f.lane, u32(0)) + (
                        xp.where(sel & ~full, cdj * powv, u32(0))
                        << u32(f.shift)
                    )
                    ord_over = ord_over | (sel & full)
                for lj, add in adds.items():
                    s_table[lj] = s_table[lj] + add
            s_drop = lanes
        elif self.dup:
            s_table = app
            if plan["has_drop"]:
                nl, nsh, nm = pc(6), pc(7), pc(8)
                if isinstance(nm, int) and isinstance(nsh, int):
                    nmask = u32((nm << nsh) & 0xFFFFFFFF)
                else:
                    nmask = tr(nm) << tr(nsh)
                if isinstance(nl, int):
                    s_drop = list(lanes)
                    s_drop[nl] = lanes[nl] & ~nmask
                else:
                    s_drop = [
                        xp.where(
                            nl == j, lanes[j] & ~nmask, lanes[j]
                        )
                        for j in range(W)
                    ]
        else:
            # FUSED deliver/timeout: timeout rows carry zero envelope
            # params, so the post-delta decrement is the identity on
            # them and the kind switch disappears.
            nl, nsh, nm = pc(6), pc(7), pc(8)
            if isinstance(nm, int) and isinstance(nsh, int):
                nmask = u32((nm << nsh) & 0xFFFFFFFF)
            else:
                nmask = tr(nm) << tr(nsh)
            ac = (lane_sel(app, nl) >> tr(nsh)) & tr(nm)
            dec = ((ac - u32(1)) & tr(nm)) << tr(nsh)
            if isinstance(nl, int):
                s_table = list(app)
                s_table[nl] = (app[nl] & ~nmask) | dec
            else:
                s_table = [
                    xp.where(
                        nl == j, (app[j] & ~nmask) | dec, app[j]
                    )
                    for j in range(W)
                ]
            if plan["has_drop"]:
                vc = (lane_sel(lanes, nl) >> tr(nsh)) & tr(nm)
                dc = ((vc - u32(1)) & tr(nm)) << tr(nsh)
                if isinstance(nl, int):
                    s_drop = list(lanes)
                    s_drop[nl] = (lanes[nl] & ~nmask) | dc
                else:
                    s_drop = [
                        xp.where(
                            nl == j,
                            (lanes[j] & ~nmask) | dc,
                            lanes[j],
                        )
                        for j in range(W)
                    ]

        if plan["has_crash"]:
            ai = xp.minimum(tr(pc(1)), u32(max(0, self.n - 1)))
            crow = xp.asarray(self._sp_crash_and)[ai]
            cl, csh = pc(9), pc(10)
            if isinstance(cl, int):
                s_crash = [lanes[j] & crow[j] for j in range(W)]
                s_crash[cl] = (
                    lanes[cl] | (u32(1) << tr(csh))
                ) & crow[cl]
            else:
                s_crash = [
                    xp.where(
                        cl == j,
                        lanes[j] | (u32(1) << tr(csh)),
                        lanes[j],
                    )
                    & crow[j]
                    for j in range(W)
                ]

        succ_lanes = list(s_table)
        table_gate = None
        if plan["has_drop"] or plan["has_crash"]:
            kind = tr(pc(0))
            if plan["has_drop"]:
                is_drop = kind == u32(self._SK_DROP)
                succ_lanes = [
                    succ_lanes[j]
                    if (
                        s_drop[j] is lanes[j]
                        and succ_lanes[j] is lanes[j]
                    )
                    else xp.where(is_drop, s_drop[j], succ_lanes[j])
                    for j in range(W)
                ]
                table_gate = ~is_drop
            if plan["has_crash"]:
                is_crash = kind == u32(self._SK_CRASH)
                succ_lanes = [
                    xp.where(is_crash, s_crash[j], succ_lanes[j])
                    for j in range(W)
                ]
                table_gate = (
                    ~is_crash
                    if table_gate is None
                    else table_gate & ~is_crash
                )

        # Class-local writes: a lane no effect class can touch keeps
        # its input row (no update-slice emitted for it).
        succ = vec
        for j in range(W):
            if succ_lanes[j] is lanes[j]:
                continue
            succ = succ.at[j].set(succ_lanes[j])

        if self.ordered:
            trunc = (
                ord_over
                if table_gate is None
                else table_gate & ord_over
            )
        elif self.dup:
            trunc = xp.bool_(False)
        else:
            top = xp.bool_(False)
            for j in range(W):
                m = int(self._net_top_mask[j])
                if m:
                    top = top | ((succ_lanes[j] & u32(m)) != 0)
            trunc = top if table_gate is None else table_gate & top
        if trivial_h:
            hard = xp.bool_(False)
        else:
            hard = (
                h_missing
                if table_gate is None
                else table_gate & h_missing
            )
        return succ, trunc, hard

    @property
    def trivial_boundary(self) -> bool:
        """Lets the sparse engine skip the per-pair boundary pass and
        the terminal scatter-back when no boundary spec exists."""
        return self.boundary_spec is None

    @property
    def pair_width_hint(self):
        """Static bound on enabled slots per state for the sparse
        engine's per-row peel, resolved in priority order:

        1. the DECLARED ``compile_actor_model(pair_width_hint=...)``
           (the caller owns the bound's argument; the engines' peel
           overflow guard warns and resize-retries if it ever
           breaks — a recompile, never dropped pairs),
        2. the reachable-mode harvested peak (exact for that mode:
           the harvest explores the same space the device does),
        3. ordered structure: only each channel's HEAD is deliverable
           (one deliver slot per channel), plus armed timers and
           crash slots — far below the K = |E| deliver-slot universe
           (ABD 2c/3s: 16 vs K=110; the unhinted EV=K sizing OOMed
           the engine's pair buffers).

        Unhinted unordered overapprox compilations have no useful
        static bound (any present envelope is deliverable): None
        defers to the engine default EV = K."""
        if self._pair_width_decl is not None:
            return min(self._pair_width_decl, self.max_actions)
        if self._pair_width_auto is not None:
            return min(self._pair_width_auto, self.max_actions)
        if not self.ordered:
            return None
        return max(
            1,
            len(self.channels)
            + len(self.timeout_slots)
            + len(self.crash_slots),
        )

    def enabled_bits_vec(self, vec):
        """``uint32[ceil(A/32)]``: the enabled mask as a PACKED bitmap
        (ops/bitmask.py word layout), built entirely from shift-mask
        field extracts on the state lanes — no per-slot table gathers,
        no dense bool[A] materialization. This is the op shape the
        hand encodings use and the sparse engines consume directly
        (PERF.md §ordered traced ~1.6s/run of 1-D mask gathers to the
        old table-gather form at abd-ordered shapes). The no-gather /
        no-dense-mask / no-[N, 1]-ALU contract is pinned statically by
        the kernel lint (stateright_tpu/analysis/, ``pytest -m
        lint``) for the registered compiled encodings.

        Semantics are the dense ``step_vec`` validity EXCEPT the
        count-bound poison, which ``step_slot_vec`` reports as its
        truncation flag (the engine excludes those pairs and raises
        when in-boundary).

        With the codegen optimizer active (compile_actor_model's
        ``optimize``, the default) the emission is
        :meth:`_enabled_bits_opt` — word-level assembly from
        condition-gated class masks; this naive per-slot form is the
        ``optimize=False`` ablation baseline."""
        if self._opt is not None:
            return self._enabled_bits_opt(vec)
        import jax.numpy as jnp

        from ..ops.bitmask import bit_select, mask_words

        u32 = jnp.uint32
        L = mask_words(self.max_actions)
        s_idx = [self._get_actor_idx(vec, i, jnp) for i in range(self.n)]
        crashed = [
            self._get_field(vec, self.f_crashed[i], jnp) != 0
            for i in range(self.n)
        ]
        if self.crash_slots:
            n_crashed = crashed[0].astype(u32)
            for c in crashed[1:]:
                n_crashed = n_crashed + c.astype(u32)
            can_crash = n_crashed < u32(self.max_crashes)

        def fx(f, width_mask):
            return (vec[f.lane] >> u32(f.shift)) & u32(width_mask)

        out = jnp.zeros(L, u32)
        for w0 in range(L):
            acc = u32(0)
            for pos, spec in enumerate(
                self._mask_slots[w0 * 32 : w0 * 32 + 32]
            ):
                kind = spec[0]
                if kind == "deliver":
                    _, i, k, nn = spec
                    if self.ordered:
                        env = self.E[k]
                        ch = (env.src, env.dst)
                        f = self.f_ch[self.chidx[ch]]
                        qv = fx(f, (1 << f.bits) - 1)
                        # HEAD of the channel queue: least-significant
                        # base digit.
                        present = (qv % u32(self.ch_base[ch])) == u32(
                            self.ch_code[ch][env.msg]
                        )
                    else:
                        f = self.f_net[k]
                        present = fx(f, (1 << f.bits) - 1) != 0
                    b = (
                        present
                        & ~crashed[i]
                        & (bit_select(jnp, nn, s_idx[i]) != 0)
                    )
                elif kind == "drop":
                    f = self.f_net[spec[1]]
                    b = fx(f, (1 << f.bits) - 1) != 0
                elif kind == "timeout":
                    _, i, j, nn = spec
                    b = (fx(self.f_timer[i][j], 1) != 0) & (
                        bit_select(jnp, nn, s_idx[i]) != 0
                    )
                else:  # crash
                    b = ~crashed[spec[1]] & can_crash
                acc = acc | (b.astype(u32) << u32(pos))
            out = out.at[w0].set(acc)
        return out

    def enabled_mask_vec(self, vec):
        """bool[A]: the dense view of :meth:`enabled_bits_vec` (the
        SparseEncodedModel contract and its differential tests); the
        engines consume the packed words directly."""
        import jax.numpy as jnp

        from ..ops.bitmask import words_to_mask

        return words_to_mask(
            jnp, self.enabled_bits_vec(vec), self.max_actions
        )

    def step_slot_vec(self, vec, slot):
        """(successor, trunc, hard_trunc) for one enabled (state,
        slot) pair — trunc is boundary-gated by the engines (count
        poison), hard_trunc is raised unconditionally (un-harvested
        history transition; see ``step_vec``'s hmiss notes).

        Codegen shape contract (pinned by tests/test_codegen_shapes):
        four row-table gathers (params, flat transition, packed
        history, crash mask — the intended sparse idiom), then pure
        1-D LANE OPS: every per-row chain (integer-queue shift/select,
        field extracts, guard predicates) runs on flat ``[N]`` scalars
        under vmap, and the successor is assembled with static-lane
        selects — no stack-of-scalars concats, whose ``[N, 1]``
        operands pay the full 128-lane tile-padding tax on TPU
        (PERF.md §ordered: ~470ms/run at abd-ordered shapes).

        With the codegen optimizer active the emission is
        :meth:`_step_slot_opt` (fused classes, pruned gather columns);
        this form is the ``optimize=False`` ablation baseline."""
        if self._opt is not None:
            return self._step_slot_opt(vec, slot)
        import jax.numpy as jnp

        xp = jnp
        W = self.width
        u32 = xp.uint32
        slot = slot.astype(u32)
        prow = xp.asarray(self._sp_params)[slot]
        kind = prow[0]
        is_deliver = kind == self._SK_DELIVER
        is_drop = kind == self._SK_DROP
        is_timeout = kind == self._SK_TIMEOUT
        is_crash = kind == self._SK_CRASH

        lanes = [vec[j] for j in range(W)]

        def lane_sel(vals, lane_idx):
            v = vals[0]
            for j in range(1, W):
                v = xp.where(lane_idx == j, vals[j], v)
            return v

        # Actor-state index -> flat transition row.
        s_idx = (lane_sel(lanes, prow[3]) >> prow[4]) & prow[5]
        frow_i = xp.minimum(
            prow[2] + s_idx, u32(self._sp_flat.shape[0] - 1)
        )
        frow = xp.asarray(self._sp_flat)[frow_i]
        nxt, hcl = frow[0], frow[2]
        ndl = [frow[3 + j] for j in range(W)]
        tan = [frow[3 + W + j] for j in range(W)]
        tor = [frow[3 + 2 * W + j] for j in range(W)]
        snd_ch = [frow[3 + 3 * W + j] for j in range(self._smax)]
        snd_cd = [
            frow[3 + 3 * W + self._smax + j] for j in range(self._smax)
        ]

        h_idx = self._get_field(vec, self.f_history, xp)
        # One packed gather: history index in bits 0-30, the
        # un-harvested-transition flag in bit 31 (successor
        # unrepresentable — reported through the hard-truncation
        # element, ADVICE r4, matching dense step_vec's hmiss).
        hg = xp.asarray(self._sp_hist_flat)[
            h_idx * u32(self.n_cls) + hcl
        ]
        h2 = hg & u32(0x7FFFFFFF)
        h_missing = (hg >> 31) != 0

        # deliver/timeout: the table-driven transition, lane by lane —
        # actor-state field set (dynamic lane via a per-lane select on
        # the host-constant lane id), net delta add/or, timer and/or,
        # history field set (static lane).
        amask = prow[5] << prow[4]
        aval = (nxt & prow[5]) << prow[4]
        hf = self.f_history
        app = []
        for j in range(W):
            v = lanes[j]
            v = xp.where(prow[3] == j, (v & ~amask) | aval, v)
            if self.dup:
                v = v | ndl[j]
            else:
                v = v + ndl[j]
            v = (v & tan[j]) | tor[j]
            if j == hf.lane:
                v = (v & ~u32(hf.mask)) | (
                    (h2 & u32((1 << hf.bits) - 1)) << u32(hf.shift)
                )
            app.append(v)

        ord_over = xp.bool_(False)
        if self.ordered:
            # Pop the delivered channel's head (divide by base), then
            # append the transition's send sequence to its queues in
            # emission order. Composed as PURE PER-LANE ARITHMETIC —
            # static-index lane reads, per-static-lane scalar delta
            # accumulators, no masked vector writes: the masked
            # read-modify-write form miscompiled under vmap on TPU
            # (sibling queue lanes were zeroed; same hazard family as
            # the dynamic-index scatter drop documented in PERF.md).
            base = xp.maximum(prow[11], u32(1))
            qv = (lane_sel(app, prow[6]) >> prow[7]) & prow[8]
            pop_amt = (qv - qv // base) << prow[7]
            s_net = [
                app[j]
                - xp.where(is_deliver & (prow[6] == j), pop_amt, u32(0))
                for j in range(W)
            ]
            for j in range(self._smax):
                chj = snd_ch[j]
                cdj = snd_cd[j]
                do = cdj > 0
                adds: dict = {}
                for cc in range(len(self.channels)):
                    cch = self.channels[cc]
                    cbase = self.ch_base[cch]
                    Q = self.ch_q[cch]
                    f = self.f_ch[cc]
                    fmask = u32((1 << f.bits) - 1)
                    q = (s_net[f.lane] >> u32(f.shift)) & fmask
                    ln = sum(
                        (q >= u32(cbase**p)).astype(u32)
                        for p in range(Q)
                    )
                    powv = u32(0)
                    for pp in range(Q):
                        powv = xp.where(
                            ln == pp, u32(cbase**pp), powv
                        )
                    sel = do & (chj == cc)
                    full = ln >= Q
                    adds[f.lane] = adds.get(f.lane, u32(0)) + (
                        xp.where(sel & ~full, cdj * powv, u32(0))
                        << u32(f.shift)
                    )
                    ord_over = ord_over | (sel & full)
                for lj, add in adds.items():
                    s_net[lj] = s_net[lj] + add
            s_deliver = s_net
            s_drop = lanes  # lossy ordered rejected at compile
            s_timeout = s_net
        else:
            # deliver additionally consumes the envelope (nondup). The
            # count must be read POST-delta (a handler may re-send the
            # envelope it consumed, exactly as the dense dec_net reads
            # the updated state).
            if self.dup:
                s_deliver = app  # redeliverable (network.rs:204-206)
                s_drop = [
                    xp.where(
                        prow[6] == j,
                        lanes[j] & ~(prow[8] << prow[7]),
                        lanes[j],
                    )
                    for j in range(W)
                ]
            else:
                nmask = prow[8] << prow[7]
                ac = (lane_sel(app, prow[6]) >> prow[7]) & prow[8]
                s_deliver = [
                    xp.where(
                        prow[6] == j,
                        (app[j] & ~nmask)
                        | (((ac - 1) & prow[8]) << prow[7]),
                        app[j],
                    )
                    for j in range(W)
                ]
                vc = (lane_sel(lanes, prow[6]) >> prow[7]) & prow[8]
                s_drop = [
                    xp.where(
                        prow[6] == j,
                        (lanes[j] & ~nmask)
                        | (((vc - 1) & prow[8]) << prow[7]),
                        lanes[j],
                    )
                    for j in range(W)
                ]

            s_timeout = app  # fired-timer clear already folded into tan

        ai = xp.minimum(prow[1], u32(max(0, self.n - 1)))
        crow = xp.asarray(self._sp_crash_and)[ai]
        s_crash = [
            xp.where(
                prow[9] == j, lanes[j] | (u32(1) << prow[10]), lanes[j]
            )
            & crow[j]
            for j in range(W)
        ]

        # Compose the output with static-lane selects (the hand
        # encodings' idiom — see models/paxos_tpu.py step_slot_vec's
        # lowering-hazard notes), never a stack of per-lane scalars.
        succ_lanes = [
            xp.where(
                is_deliver, s_deliver[j],
                xp.where(
                    is_drop, s_drop[j],
                    xp.where(
                        is_timeout, s_timeout[j],
                        xp.where(is_crash, s_crash[j], lanes[j]),
                    ),
                ),
            )
            for j in range(W)
        ]
        succ = vec
        for j in range(W):
            succ = succ.at[j].set(succ_lanes[j])
        if self.ordered:
            trunc = (is_deliver | is_timeout) & ord_over
        elif self.dup:
            trunc = xp.bool_(False)
        else:
            top = xp.bool_(False)
            for j in range(W):
                m = int(self._net_top_mask[j])
                if m:
                    top = top | ((succ_lanes[j] & u32(m)) != 0)
            trunc = (is_deliver | is_timeout) & top
        # Third element = HARD truncation: un-harvested (h, class)
        # transition, raised by the engines regardless of the boundary
        # (the successor's history field is garbage, so the boundary
        # cannot be evaluated faithfully on it — unlike count poison,
        # where the count field keeps its true value).
        hard = (is_deliver | is_timeout) & h_missing
        return succ, trunc, hard

    # -- field access (host + device) ------------------------------------

    def _get_field(self, vec, f: _Field, xp):
        return (vec[f.lane] >> xp.uint32(f.shift)) & xp.uint32(
            (1 << f.bits) - 1
        )

    def _set_field(self, vec, f: _Field, value, jnp):
        cleared = vec[f.lane] & ~jnp.uint32(f.mask)
        return vec.at[f.lane].set(
            cleared | (value.astype(jnp.uint32) << jnp.uint32(f.shift))
        )

    def _get_actor_idx(self, vec, i: int, xp):
        return self._get_field(vec, self.f_actor[i], xp)

    def _net_count(self, vec, k: int, xp):
        if self.ordered:
            # Envelope k is "in flight" iff its code appears at any
            # position of its channel's queue (iter_deliverable yields
            # every flow position, not just heads — network.rs:149-170).
            env = self.E[k]
            ch = (env.src, env.dst)
            base = self.ch_base[ch]
            code = self.ch_code[ch][env.msg]
            q = self._get_field(vec, self.f_ch[self.chidx[ch]], xp)
            cnt = xp.uint32(0)
            for p in range(self.ch_q[ch]):
                digit = (q // xp.uint32(base**p)) % xp.uint32(base)
                cnt = cnt + (digit == code).astype(xp.uint32)
            return cnt
        return self._get_field(vec, self.f_net[k], xp)

    # -- host side --------------------------------------------------------

    def encode(self, state) -> np.ndarray:
        vec = np.zeros(self.width, np.uint32)

        def put(f: _Field, value: int):
            if value >= (1 << f.bits):
                raise ValueError(
                    f"field overflow: {value} in {f.bits} bits (an envelope "
                    "count above 255 means the closure bounds are wrong)"
                )
            vec[f.lane] |= np.uint32(value << f.shift)

        for i, s in enumerate(state.actor_states):
            try:
                put(self.f_actor[i], self.sidx[i][s])
            except KeyError:
                raise KeyError(
                    f"actor {i} state outside closure: {s!r}"
                ) from None
        put(self.f_history, self.hidx[state.history])
        for i, crashed in enumerate(state.crashed):
            put(self.f_crashed[i], int(crashed))
        for i, timers in enumerate(state.timers_set):
            for t in timers:
                put(self.f_timer[i][self.tidx[i][t]], 1)
        if self.ordered:
            for ci, ch in enumerate(self.channels):
                flow = state.network.flows.get(ch, ())
                if len(flow) > self.ch_q[ch]:
                    raise ValueError(
                        f"channel {ch} queue depth {len(flow)} exceeds "
                        f"the queue bound {self.ch_q[ch]} (harvested or "
                        "declared via closure_queue_bound)"
                    )
                base = self.ch_base[ch]
                q = 0
                for pos, msg in enumerate(flow):
                    code = self.ch_code[ch].get(msg)
                    if code is None:
                        raise KeyError(
                            f"message outside channel {ch} alphabet: "
                            f"{msg!r}"
                        )
                    q += code * base**pos
                put(self.f_ch[ci], q)
        elif self.dup:
            for env in state.network.envelopes:
                put(self.f_net[self.eidx[env]], 1)
        else:
            for env, count in state.network.counts.items():
                if count >= 128:
                    raise ValueError(
                        f"envelope count {count} for {env!r} exceeds the "
                        "compiled encoding's implicit bound of 127 (the "
                        "device prunes successors past it)"
                    )
                put(self.f_net[self.eidx[env]], count)
        return vec

    def decode(self, vec):
        from dataclasses import replace

        vec = np.asarray(vec, dtype=np.uint32)
        actor_states = tuple(
            self.S[i][int(self._get_actor_idx(vec, i, np))]
            for i in range(self.n)
        )
        history = self.H[int(self._get_field(vec, self.f_history, np))]
        crashed = tuple(
            bool(self._get_field(vec, self.f_crashed[i], np))
            for i in range(self.n)
        )
        timers = tuple(
            frozenset(
                t for j, t in enumerate(self.T[i])
                if self._get_field(vec, self.f_timer[i][j], np)
            )
            for i in range(self.n)
        )
        if self.ordered:
            flows = {}
            for ci, ch in enumerate(self.channels):
                q = int(self._get_field(vec, self.f_ch[ci], np))
                base = self.ch_base[ch]
                flow = []
                while q:
                    flow.append(self.ch_msgs[ch][q % base - 1])
                    q //= base
                if flow:
                    flows[ch] = tuple(flow)
            net = Ordered(flows)
        elif self.dup:
            net = UnorderedDuplicating(frozenset(
                e for k, e in enumerate(self.E)
                if self._net_count(vec, k, np)
            ))
        else:
            net = UnorderedNonDuplicating({
                e: int(self._net_count(vec, k, np))
                for k, e in enumerate(self.E)
                if self._net_count(vec, k, np)
            })
        return replace(
            self._init_state,
            actor_states=actor_states,
            network=net,
            timers_set=timers,
            crashed=crashed,
            history=history,
        )

    def init_vecs(self) -> np.ndarray:
        return np.stack(
            [self.encode(s) for s in self.model.init_states()]
        )

    # -- device side ------------------------------------------------------

    def step_vec(self, vec):
        import jax.numpy as jnp

        succs, valids = [], []
        # Any otherwise-valid, in-boundary successor pruned by the
        # implicit count bound (top bit of an 8-bit envelope field)
        # raises this flag; the engines carry it to the host and raise,
        # so a truncated space is never reported as a clean
        # verification. Successors the model boundary would prune
        # anyway are NOT truncation: the count field still holds the
        # true value (128 = the top bit itself, no carry corruption),
        # so the boundary predicate evaluates faithfully.
        trunc = jnp.bool_(False)

        def in_bound(s):
            if self.boundary_spec is None:
                return jnp.bool_(True)
            return jnp.asarray(self.within_boundary_vec(s), dtype=bool)
        n_crashed = jnp.uint32(0)
        for i in range(self.n):
            n_crashed = n_crashed + self._get_field(
                vec, self.f_crashed[i], jnp
            )
        h_idx = self._get_field(vec, self.f_history, jnp)
        h_table = jnp.asarray(self.tbl_history_packed)

        def apply_transition(i, nxt, noop, ndl, tan, tor, hcl,
                             extra_net=None):
            s_idx = self._get_actor_idx(vec, i, jnp)
            t_noop = jnp.asarray(noop)[s_idx]
            s = self._set_field(vec, self.f_actor[i],
                                jnp.asarray(nxt)[s_idx], jnp)
            delta = jnp.asarray(ndl)[s_idx]
            if self.dup:
                s = s | delta
            else:
                s = s + delta
            s = (s & jnp.asarray(tan)[s_idx]) | jnp.asarray(tor)[s_idx]
            hg = h_table[h_idx, jnp.asarray(hcl)[s_idx]]
            h2 = hg & jnp.uint32(0x7FFFFFFF)
            s = self._set_field(s, self.f_history, h2, jnp)
            if extra_net is not None:
                s = extra_net(s)
            if not self.dup and not self.ordered:
                poisoned = jnp.any(
                    (s & jnp.asarray(self._net_top_mask)) != 0
                )
            else:
                poisoned = jnp.bool_(False)
            # An un-harvested (h, class) transition makes the successor
            # unrepresentable — returned SEPARATELY from the count
            # poison, because the caller's in_bound(s) gate is sound
            # only for count poison (the count field still holds its
            # true value); here the history field is garbage, so the
            # boundary cannot be trusted to evaluate faithfully and
            # truncation must be raised unconditionally.
            hmiss = (hg >> 31) != 0
            return s, t_noop, poisoned, hmiss

        def ord_sends(s, i, sch, scd):
            """Append this transition's send sequence to its FIFO
            queues, in emission order: per send, q += code*base^len
            (len from Q static comparisons); a full queue poisons the
            successor (cannot occur for harvested reachable bounds —
            safety net only)."""
            s_idx = self._get_actor_idx(vec, i, jnp)
            over = jnp.bool_(False)
            for j in range(self._smax):
                chj = jnp.asarray(sch)[s_idx, j]
                cdj = jnp.asarray(scd)[s_idx, j]
                do = cdj > 0
                for cc in range(len(self.channels)):
                    base = self.ch_base[self.channels[cc]]
                    Q = self.ch_q[self.channels[cc]]
                    f = self.f_ch[cc]
                    q = self._get_field(s, f, jnp)
                    ln = sum(
                        (q >= jnp.uint32(base**p)).astype(jnp.uint32)
                        for p in range(Q)
                    )
                    powv = jnp.uint32(0)
                    for p in range(Q):
                        powv = jnp.where(
                            ln == p, jnp.uint32(base**p), powv
                        )
                    sel = do & (chj == cc)
                    full = ln >= Q
                    q2 = jnp.where(
                        sel & ~full, q + cdj * powv, q
                    )
                    s = self._set_field(s, f, q2, jnp)
                    over = over | (sel & full)
            return s, over

        # Deliver slots (model.rs:299-351).
        for (i, k, nxt, noop, ndl, tan, tor, hcl, sch, scd) in (
            self.tbl_deliver
        ):
            crashed = self._get_field(vec, self.f_crashed[i], jnp) != 0
            if self.ordered:
                env = self.E[k]
                ch = (env.src, env.dst)
                ci = self.chidx[ch]
                base = self.ch_base[ch]
                code = self.ch_code[ch][env.msg]
                fq = self.f_ch[ci]
                q0 = self._get_field(vec, fq, jnp)
                # Deliverable iff this message is the channel HEAD
                # (model.rs:252-266); a no-op handler still pops.
                present = (q0 % jnp.uint32(base)) == code

                def pop_net(s, fq=fq, base=base):
                    return self._set_field(
                        s, fq,
                        self._get_field(s, fq, jnp) // jnp.uint32(base),
                        jnp,
                    )

                s, t_noop, apply_poisoned, hmiss = apply_transition(
                    i, nxt, noop, ndl, tan, tor, hcl, extra_net=pop_net
                )
                s, poisoned = ord_sends(s, i, sch, scd)
                poisoned = poisoned | apply_poisoned
                enabled = present & ~crashed & ~t_noop
                trunc = trunc | (
                    enabled & ((poisoned & in_bound(s)) | hmiss)
                )
                succs.append(s)
                valids.append(enabled & ~poisoned & ~hmiss)
                continue
            f = self.f_net[k]
            present = self._net_count(vec, k, jnp) > 0

            def dec_net(s, f=f):
                if self.dup:
                    return s  # redeliverable (network.rs:204-206)
                return self._set_field(
                    s, f, self._get_field(s, f, jnp) - 1, jnp
                )

            s, t_noop, poisoned, hmiss = apply_transition(
                i, nxt, noop, ndl, tan, tor, hcl, extra_net=dec_net
            )
            enabled = present & ~crashed & ~t_noop
            trunc = trunc | (
                enabled & ((poisoned & in_bound(s)) | hmiss)
            )
            succs.append(s)
            valids.append(enabled & ~poisoned & ~hmiss)

        # Drop slots — lossy networks only (model.rs:246-249).
        for k in self.drop_slots:
            f = self.f_net[k]
            present = self._net_count(vec, k, jnp) > 0
            if self.dup:
                s = vec.at[f.lane].set(vec[f.lane] & ~jnp.uint32(f.mask))
            else:
                s = self._set_field(
                    vec, f, self._get_field(vec, f, jnp) - 1, jnp
                )
            succs.append(s)
            valids.append(present)

        # Timeout slots (model.rs:352-371).
        for idx, (i, j, nxt, noop, ndl, tan, tor, hcl, sch, scd) in (
            enumerate(self.tbl_timeout)
        ):
            f = self.f_timer[i][j]
            armed = self._get_field(vec, f, jnp) != 0
            s, t_noop, poisoned, hmiss = apply_transition(
                i, nxt, noop, ndl, tan, tor, hcl
            )
            if self.ordered:
                s, over = ord_sends(s, i, sch, scd)
                poisoned = poisoned | over
            enabled = armed & ~t_noop
            trunc = trunc | (
                enabled & ((poisoned & in_bound(s)) | hmiss)
            )
            succs.append(s)
            valids.append(enabled & ~poisoned & ~hmiss)

        # Crash slots (model.rs:372-380).
        for i in self.crash_slots:
            crashed = self._get_field(vec, self.f_crashed[i], jnp) != 0
            s = self._set_field(vec, self.f_crashed[i], jnp.uint32(1), jnp)
            for j in range(len(self.T[i])):
                f = self.f_timer[i][j]
                s = s.at[f.lane].set(s[f.lane] & ~jnp.uint32(f.mask))
            succs.append(s)
            valids.append(
                ~crashed & (n_crashed < jnp.uint32(self.max_crashes))
            )

        if not succs:  # degenerate: no possible actions
            succs.append(vec)
            valids.append(jnp.bool_(False))
        return jnp.stack(succs), jnp.stack(valids), trunc

    def property_conditions_vec(self, vec):
        import jax.numpy as jnp

        ctx = _SpecCtx(self, vec, jnp)
        conds = [
            jnp.asarray(self.property_specs[p.name](ctx, jnp), dtype=bool)
            for p in self.model.properties()
        ]
        if not conds:
            return jnp.zeros((0,), dtype=bool)
        return jnp.stack(conds)

    def within_boundary_vec(self, vec):
        if self.boundary_spec is None:
            return True
        import jax.numpy as jnp

        ctx = _SpecCtx(self, vec, jnp)
        return jnp.asarray(self.boundary_spec(ctx, jnp), dtype=bool)
