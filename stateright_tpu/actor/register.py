"""Register protocol adapters: the client/server message protocol shared
by all register examples, plus consistency-history recording hooks.

Counterpart of stateright src/actor/register.rs:17-248:
``Put``/``Get`` requests with ``PutOk``/``GetOk`` responses (and
``Internal`` for the server protocol), ``record_invocations``/
``record_returns`` to feed the message stream into a consistency
tester as the model history, and ``RegisterClient`` which performs
``put_count`` puts followed by a get, rotating across servers.

Clients must be added to the model *after* servers so that server ids
can be derived as ``(client_id + k) % server_count``
(register.rs:118-120, 155).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..semantics.register import ReadOk, ReadOp, WriteOk, WriteOp
from .base import Actor, Cow, Id, Out
from .network import Envelope

DEFAULT_VALUE = "\x00"  # Rust's char::default()


# -- protocol messages (register.rs:17-31) ------------------------------


@dataclass(frozen=True)
class Internal:
    msg: Any


@dataclass(frozen=True)
class Put:
    req_id: int
    value: Any


@dataclass(frozen=True)
class Get:
    req_id: int


@dataclass(frozen=True)
class PutOk:
    req_id: int


@dataclass(frozen=True)
class GetOk:
    req_id: int
    value: Any


# -- history hooks (register.rs:38-91) ----------------------------------


def record_invocations(cfg: Any, history, env: Envelope):
    """``record_msg_out`` hook: Put → Write invocation, Get → Read
    invocation, keyed by the client id."""
    if isinstance(env.msg, Get):
        return history.on_invoke(env.src, ReadOp())
    if isinstance(env.msg, Put):
        return history.on_invoke(env.src, WriteOp(env.msg.value))
    return None


def record_returns(cfg: Any, history, env: Envelope):
    """``record_msg_in`` hook: GetOk → ReadOk return, PutOk → WriteOk
    return, keyed by the client id."""
    if isinstance(env.msg, GetOk):
        return history.on_return(env.dst, ReadOk(env.msg.value))
    if isinstance(env.msg, PutOk):
        return history.on_return(env.dst, WriteOk())
    return None


# -- model-checking client (register.rs:94-248) -------------------------


@dataclass(frozen=True)
class RegisterClientState:
    awaiting: Optional[int]
    op_count: int


class RegisterClient(Actor):
    """Puts ``put_count`` values then gets, round-robining servers.

    Request ids, values, and server rotation mirror the reference
    exactly (register.rs:144-236): client ``i``'s k-th request id is
    ``k * i``; the first put writes ``chr(ord('A') + i - server_count)``
    and subsequent puts write ``chr(ord('Z') - (i - server_count))``.
    """

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def name(self) -> str:
        return "Client"

    def on_start(self, id: Id, out: Out) -> RegisterClientState:
        index = int(id)
        if index < self.server_count:
            raise ValueError(
                "register clients must be added to the model after servers"
            )
        if self.put_count == 0:
            return RegisterClientState(awaiting=None, op_count=0)
        req_id = index
        value = chr(ord("A") + index - self.server_count)
        out.send(Id(index % self.server_count), Put(req_id, value))
        return RegisterClientState(awaiting=req_id, op_count=1)

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        client = state.value
        if client.awaiting is None:
            return
        index = int(id)
        if isinstance(msg, PutOk) and msg.req_id == client.awaiting:
            req_id = (client.op_count + 1) * index
            if client.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                out.send(
                    Id((index + client.op_count) % self.server_count),
                    Put(req_id, value),
                )
            else:
                out.send(
                    Id((index + client.op_count) % self.server_count),
                    Get(req_id),
                )
            state.set(
                RegisterClientState(awaiting=req_id, op_count=client.op_count + 1)
            )
        elif isinstance(msg, GetOk) and msg.req_id == client.awaiting:
            state.set(
                RegisterClientState(awaiting=None, op_count=client.op_count + 1)
            )
        # else: stale/unexpected response → no-op → pruned


@dataclass(frozen=True)
class ServerState:
    """Wrapper marking a server's state (register.rs:107-116)."""

    state: Any


class RegisterServer(Actor):
    """Wraps a server actor, delegating events (register.rs:176-273)."""

    def __init__(self, inner: Actor):
        self.inner = inner

    def name(self) -> str:
        return self.inner.name() or "Server"

    def on_start(self, id: Id, out: Out):
        return ServerState(self.inner.on_start(id, out))

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        inner_cow = Cow(state.value.state)
        self.inner.on_msg(id, inner_cow, src, msg, out)
        if inner_cow.owned:
            state.set(ServerState(inner_cow.value))

    def on_timeout(self, id: Id, state: Cow, timer: Any, out: Out) -> None:
        inner_cow = Cow(state.value.state)
        self.inner.on_timeout(id, inner_cow, timer, out)
        if inner_cow.owned:
            state.set(ServerState(inner_cow.value))


def register_specs(default_value=DEFAULT_VALUE):
    """Device property specs for the register test-actor family
    (single-copy, ABD): the standard linearizable / value-chosen pair
    every register example checks (single-copy-register.rs:73-91,
    linearizable-register.rs:243-257), as actor-compiler specs
    (actor/compile.py) usable with any register-shaped ActorModel."""

    def linearizable(ctx, jnp):
        return (
            ctx.history_value(
                lambda h: int(h.serialized_history() is not None)
            )
            == 1
        )

    def value_chosen(ctx, jnp):
        return ctx.network_any(
            lambda env: isinstance(env.msg, GetOk)
            and env.msg.value != default_value
        )

    return {"linearizable": linearizable, "value chosen": value_chosen}
