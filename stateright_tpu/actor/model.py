"""``ActorModel``: compiles a system of actors into a checkable ``Model``.

Counterpart of stateright src/actor/model.rs:23-649. The model's
actions are message deliveries (plus drops on lossy networks), timer
firings, and crashes; transitions run the actor handlers with
copy-on-write no-op detection, update the network value per its
semantics, maintain the auxiliary history through the
``record_msg_in``/``record_msg_out`` hooks, and apply emitted commands.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Callable, Iterable, Optional, Sequence

from ..model import Expectation, Model, Property
from .base import (
    Actor,
    CancelTimer,
    Cow,
    Id,
    Out,
    Send,
    SetTimer,
    is_no_op,
    is_no_op_with_timer,
)
from .model_state import ActorModelState
from .network import Envelope, Network, Ordered

# -- actions (model.rs:43-55) -------------------------------------------


@dataclass(frozen=True)
class Deliver:
    src: Id
    dst: Id
    msg: Any


@dataclass(frozen=True)
class Drop:
    envelope: Envelope


@dataclass(frozen=True)
class Timeout:
    id: Id
    timer: Any


@dataclass(frozen=True)
class Crash:
    id: Id


ActorModelAction = Any  # Deliver | Drop | Timeout | Crash


class ActorModel(Model):
    """Builder + Model implementation (model.rs:23-39, 88-178, 214-649).

    ``record_msg_in``/``record_msg_out`` hooks have signature
    ``(cfg, history, envelope) -> Optional[new_history]`` — returning
    None leaves history unchanged (model.rs:151-169).
    """

    def __init__(self, cfg: Any = None, init_history: Any = ()):
        self.actors: list[Actor] = []
        self.cfg = cfg
        self.init_history = init_history
        self._init_network: Network = Network.new_unordered_duplicating()
        self.lossy_network = False
        self.max_crashes = 0
        self._properties: list[Property] = []
        self._record_msg_in: Callable = lambda cfg, h, env: None
        self._record_msg_out: Callable = lambda cfg, h, env: None
        self._within_boundary: Callable = lambda cfg, state: True

    # -- builder (model.rs:88-178) ---------------------------------------

    def actor(self, actor: Actor) -> "ActorModel":
        self.actors.append(actor)
        return self

    def add_actors(self, actors: Iterable[Actor]) -> "ActorModel":
        self.actors.extend(actors)
        return self

    def init_network(self, network: Network) -> "ActorModel":
        self._init_network = network
        return self

    def set_lossy_network(self, lossy: bool) -> "ActorModel":
        self.lossy_network = lossy
        return self

    def set_max_crashes(self, n: int) -> "ActorModel":
        self.max_crashes = n
        return self

    def property(
        self,
        expectation: Expectation,
        name: str,
        condition: Callable[["ActorModel", ActorModelState], bool],
    ) -> "ActorModel":
        self._properties.append(Property(expectation, name, condition))
        return self

    def record_msg_in(self, hook: Callable) -> "ActorModel":
        self._record_msg_in = hook
        return self

    def record_msg_out(self, hook: Callable) -> "ActorModel":
        self._record_msg_out = hook
        return self

    def within_boundary_fn(self, hook: Callable) -> "ActorModel":
        self._within_boundary = hook
        return self

    # -- Model implementation (model.rs:214-649) -------------------------

    def init_states(self) -> Sequence[ActorModelState]:
        state = ActorModelState(
            actor_states=(),
            network=self._init_network,
            timers_set=tuple(frozenset() for _ in self.actors),
            crashed=tuple(False for _ in self.actors),
            history=self.init_history,
        )
        for index, actor in enumerate(self.actors):
            out = Out()
            actor_state = actor.on_start(Id(index), out)
            state = replace(
                state, actor_states=state.actor_states + (actor_state,)
            )
            state = self._process_commands(Id(index), out, state)
        return [state]

    def actions(self, state: ActorModelState) -> Sequence[ActorModelAction]:
        actions: list[ActorModelAction] = []
        is_ordered = isinstance(self._init_network, Ordered)
        prev_channel = None
        for env in state.network.iter_deliverable():
            # Option 1: message is lost (model.rs:246-249).
            if self.lossy_network:
                actions.append(Drop(env))
            # Option 2: message is delivered; ordered networks deliver
            # only channel heads (model.rs:252-266).
            if int(env.dst) < len(self.actors):
                if is_ordered:
                    channel = (env.src, env.dst)
                    if prev_channel == channel:
                        continue
                    prev_channel = channel
                actions.append(Deliver(env.src, env.dst, env.msg))
        # Option 3: timer fires (model.rs:270-274).
        for index, timers in enumerate(state.timers_set):
            for timer in sorted(timers, key=repr):
                actions.append(Timeout(Id(index), timer))
        # Option 4: crash (model.rs:277-285).
        n_crashed = sum(state.crashed)
        if n_crashed < self.max_crashes:
            for index, crashed in enumerate(state.crashed):
                if not crashed:
                    actions.append(Crash(Id(index)))
        return actions

    def next_state(
        self, state: ActorModelState, action: ActorModelAction
    ) -> Optional[ActorModelState]:
        if isinstance(action, Drop):
            return replace(state, network=state.network.on_drop(action.envelope))

        if isinstance(action, Deliver):
            index = int(action.dst)
            if index >= len(state.actor_states):
                return None
            if state.crashed[index]:
                return None  # model.rs:307-309
            cow = Cow(state.actor_states[index])
            out = Out()
            self.actors[index].on_msg(
                Id(index), cow, action.src, action.msg, out
            )
            is_ordered = isinstance(self._init_network, Ordered)
            if is_no_op(cow, out) and not is_ordered:
                return None  # prune (model.rs:317-319)
            env = Envelope(action.src, action.dst, action.msg)
            history = self._record_msg_in(self.cfg, state.history, env)
            next_state = replace(state, network=state.network.on_deliver(env))
            if cow.owned:
                next_state = next_state.with_actor_state(index, cow.value)
            if history is not None:
                next_state = replace(next_state, history=history)
            return self._process_commands(Id(index), out, next_state)

        if isinstance(action, Timeout):
            index = int(action.id)
            cow = Cow(state.actor_states[index])
            out = Out()
            self.actors[index].on_timeout(Id(index), cow, action.timer, out)
            if is_no_op_with_timer(cow, out, action.timer):
                return None  # model.rs:358-360
            # The fired timer is no longer set (model.rs:364).
            next_state = state.with_timers(
                index, state.timers_set[index] - {action.timer}
            )
            if cow.owned:
                next_state = next_state.with_actor_state(index, cow.value)
            return self._process_commands(Id(index), out, next_state)

        if isinstance(action, Crash):
            index = int(action.id)
            next_state = state.with_timers(index, frozenset())
            crashed = (
                next_state.crashed[:index] + (True,) + next_state.crashed[index + 1:]
            )
            return replace(next_state, crashed=crashed)

        raise TypeError(f"unknown action {action!r}")

    def properties(self) -> Sequence[Property]:
        return list(self._properties)

    def within_boundary(self, state: ActorModelState) -> bool:
        return self._within_boundary(self.cfg, state)

    def format_action(self, action: ActorModelAction) -> str:
        if isinstance(action, Deliver):
            return f"{action.src!r} → {action.msg!r} → {action.dst!r}"
        return repr(action)

    def as_svg(self, path) -> Optional[str]:
        """Render the path as a message sequence chart (the Explorer's
        per-state diagram; counterpart of model.rs:476-640).

        One vertical lifeline per actor; each step row shows its
        action: Deliver as an arrow from the sender's lifeline at the
        step where the envelope first appeared in the network, Timeout
        and Crash as labeled marks on the affected lifeline.
        """
        from html import escape

        steps = path.steps
        states = [s for s, _ in steps]
        actions = [a for _, a in steps if a is not None]
        n_actors = len(states[0].actor_states)
        names = [
            f"{i} {a.name()}".strip() for i, a in enumerate(self.actors)
        ]
        spacing = max(100, 10 * max((len(n) for n in names), default=0))
        row_h = 30

        def x(actor: int) -> int:
            return actor * spacing

        def y(row: int) -> int:
            return row * row_h

        rows = len(actions) + 1
        width = x(n_actors) + 300
        height = y(rows) + 40
        out = [
            f"<svg version='1.1' baseProfile='full' width='{width}' "
            f"height='{height}' viewBox='-20 -20 {width + 20} "
            f"{height + 20}' xmlns='http://www.w3.org/2000/svg'>",
            "<defs><marker class='svg-event-shape' id='arrow' "
            "markerWidth='12' markerHeight='10' refX='12' refY='5' "
            "orient='auto'><polygon points='0 0, 12 5, 0 10' /></marker>"
            "</defs>",
        ]
        for i, name in enumerate(names):
            out.append(
                f"<line x1='{x(i)}' y1='{y(0)}' x2='{x(i)}' "
                f"y2='{y(rows)}' class='svg-actor-timeline' "
                "stroke='#aaa' />"
            )
            out.append(
                f"<text x='{x(i)}' y='{y(0) - 5}' "
                f"class='svg-actor-label'>{escape(name)}</text>"
            )

        def send_row(env: Envelope, deliver_row: int) -> int:
            # The arrow starts where the envelope first existed.
            for row in range(deliver_row, -1, -1):
                if env not in set(states[row].network.iter_all()):
                    return row + 1
            return 0

        for row, action in enumerate(actions, start=1):
            if isinstance(action, Deliver):
                env = Envelope(action.src, action.dst, action.msg)
                src_row = send_row(env, row - 1)
                out.append(
                    f"<line x1='{x(int(action.src))}' y1='{y(src_row)}' "
                    f"x2='{x(int(action.dst))}' y2='{y(row)}' "
                    "marker-end='url(#arrow)' class='svg-event-line' "
                    "stroke='#333' />"
                )
                out.append(
                    f"<text x='{x(int(action.dst)) + 6}' y='{y(row) - 4}' "
                    f"class='svg-event-label'>{escape(repr(action.msg))}"
                    "</text>"
                )
            elif isinstance(action, Timeout):
                out.append(
                    f"<circle cx='{x(int(action.id))}' cy='{y(row)}' "
                    "r='4' class='svg-event-shape' />"
                )
                out.append(
                    f"<text x='{x(int(action.id)) + 6}' y='{y(row) - 4}' "
                    f"class='svg-event-label'>timeout "
                    f"{escape(repr(action.timer))}</text>"
                )
            elif isinstance(action, Crash):
                out.append(
                    f"<text x='{x(int(action.id)) - 5}' y='{y(row)}' "
                    "class='svg-event-shape'>✗</text>"
                )
            elif isinstance(action, Drop):
                env = action.envelope
                out.append(
                    f"<text x='{x(int(env.src)) + 6}' y='{y(row) - 4}' "
                    f"class='svg-event-label'>drop "
                    f"{escape(repr(env.msg))}</text>"
                )
        out.append("</svg>")
        return "".join(out)

    # -- internals -------------------------------------------------------

    def _process_commands(
        self, id: Id, out: Out, state: ActorModelState
    ) -> ActorModelState:
        """Apply emitted commands: sends (with history recording) and
        timer arm/cancel (model.rs:181-211)."""
        index = int(id)
        for command in out.commands:
            if isinstance(command, Send):
                env = Envelope(id, command.dst, command.msg)
                history = self._record_msg_out(self.cfg, state.history, env)
                if history is not None:
                    state = replace(state, history=history)
                state = replace(state, network=state.network.send(env))
            elif isinstance(command, SetTimer):
                state = state.with_timers(
                    index, state.timers_set[index] | {command.timer}
                )
            elif isinstance(command, CancelTimer):
                state = state.with_timers(
                    index, state.timers_set[index] - {command.timer}
                )
            else:
                raise TypeError(f"unknown command {command!r}")
        return state
