"""Heterogeneous actor composition.

Counterpart of stateright src/actor.rs:343-549. The reference needs the
``Choice<A1, A2>`` machinery because Rust's ``ActorModel`` is generic
over a single actor type; this framework's ``ActorModel`` holds a plain
list of :class:`~stateright_tpu.actor.Actor` objects, so heterogeneous
systems work natively. ``Choice`` is still provided for API parity —
and because tagging states as L/R keeps *state types* disjoint the way
the reference's enum does, which matters when two actor kinds share a
state representation.

Also provides :class:`ScriptedActor`, the ``Vec<(Id, Msg)>`` scripted
client (actor.rs:515-549): it sends a fixed message sequence, advancing
on every delivery — useful for driving systems under test.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence, Tuple

from .base import Actor, Cow, Id, Out


@dataclass(frozen=True)
class L:
    """Left-variant state tag (actor.rs Choice::L)."""

    state: Any


@dataclass(frozen=True)
class R:
    """Right-variant state tag (actor.rs Choice::R)."""

    state: Any


class Choice(Actor):
    """One of two actor kinds, with tagged state (actor.rs:402-497)."""

    def __init__(self, actor: Actor, right: bool = False):
        self.actor = actor
        self.right = right

    @staticmethod
    def left(actor: Actor) -> "Choice":
        return Choice(actor, right=False)

    @staticmethod
    def right_of(actor: Actor) -> "Choice":
        return Choice(actor, right=True)

    def _tag(self, state: Any) -> Any:
        return R(state) if self.right else L(state)

    def name(self) -> str:
        return self.actor.name()

    def on_start(self, id: Id, out: Out) -> Any:
        return self._tag(self.actor.on_start(id, out))

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        inner = Cow(state.value.state)
        self.actor.on_msg(id, inner, src, msg, out)
        if inner.owned:
            state.set(self._tag(inner.value))

    def on_timeout(self, id: Id, state: Cow, timer: Any, out: Out) -> None:
        inner = Cow(state.value.state)
        self.actor.on_timeout(id, inner, timer, out)
        if inner.owned:
            state.set(self._tag(inner.value))


class ScriptedActor(Actor):
    """Sends ``script[i]`` messages in order, one per received message
    (actor.rs:515-549). State = next script index."""

    def __init__(self, script: Sequence[Tuple[Id, Any]]):
        self.script = list(script)

    def name(self) -> str:
        return ""

    def on_start(self, id: Id, out: Out) -> int:
        if self.script:
            dst, msg = self.script[0]
            out.send(dst, msg)
            return 1
        return 0

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        index = state.value
        if index < len(self.script):
            dst, next_msg = self.script[index]
            out.send(dst, next_msg)
            state.set(index + 1)
