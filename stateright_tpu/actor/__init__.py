"""Actor framework: model-checkable message-driven state machines.

Counterpart of stateright src/actor.rs and src/actor/*: the ``Actor``
protocol, the ``ActorModel`` bridge into the checkable ``Model``
protocol, pluggable network semantics, timers, crash/loss fault
injection, and a real UDP runtime (``spawn``) for the same actor code.
"""

from .base import (
    Actor,
    CancelTimer,
    Command,
    Cow,
    Id,
    Out,
    Send,
    SetTimer,
    is_no_op,
    is_no_op_with_timer,
    majority,
    model_peers,
    model_timeout,
)
from .network import Envelope, Network, Ordered, UnorderedDuplicating, UnorderedNonDuplicating
from .model import ActorModel, ActorModelAction, Crash, Deliver, Drop, Timeout
from .model_state import ActorModelState
from .choice import Choice, ScriptedActor
from .ordered_reliable_link import OrderedReliableLink

__all__ = [
    "Actor",
    "ActorModel",
    "ActorModelAction",
    "ActorModelState",
    "CancelTimer",
    "Choice",
    "OrderedReliableLink",
    "ScriptedActor",
    "Command",
    "Cow",
    "Crash",
    "Deliver",
    "Drop",
    "Envelope",
    "Id",
    "Network",
    "Ordered",
    "Out",
    "Send",
    "SetTimer",
    "Timeout",
    "UnorderedDuplicating",
    "UnorderedNonDuplicating",
    "is_no_op",
    "is_no_op_with_timer",
    "majority",
    "model_peers",
    "model_timeout",
]
