"""Pluggable network semantics for actor systems.

Counterpart of stateright src/actor/network.rs:47-68. A network value
is part of the model state, so all three semantics here are immutable
(operations return new networks) and stably hashable:

* :class:`UnorderedDuplicating` — a *set* of envelopes: delivery leaves
  the envelope in place (redeliverable — models duplication), dropping
  removes it forever (network.rs:51-52, 199-206, 252-254).
* :class:`UnorderedNonDuplicating` — a *multiset* (envelope → count):
  delivery decrements, dropping removes one instance
  (network.rs:55, 188-190, 207-220).
* :class:`Ordered` — per-directed-pair FIFO channels; only channel
  heads are deliverable, and empty flows are canonicalized away
  (network.rs:67, 191-196, 221-244).

Envelope iteration is sorted by a stable key so action enumeration is
deterministic across runs and processes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, Iterator, Tuple

from ..fingerprint import stable_hash
from .base import Id

Msg = Any


@dataclass(frozen=True)
class Envelope:
    """A message in flight (network.rs:25-29)."""

    src: Id
    dst: Id
    msg: Msg


def _env_sort_key(env: Envelope) -> tuple:
    return (int(env.src), int(env.dst), stable_hash(env.msg))


class Network:
    """Base class + constructors mirroring ``Network::new_*``
    (network.rs:47-68) and name-based CLI selection
    (network.rs:120-146, 296-309)."""

    @staticmethod
    def new_unordered_duplicating(envelopes: Iterable[Envelope] = ()) -> "UnorderedDuplicating":
        return UnorderedDuplicating(frozenset(envelopes))

    @staticmethod
    def new_unordered_nonduplicating(envelopes: Iterable[Envelope] = ()) -> "UnorderedNonDuplicating":
        counts: dict[Envelope, int] = {}
        for env in envelopes:
            counts[env] = counts.get(env, 0) + 1
        return UnorderedNonDuplicating(counts)

    @staticmethod
    def new_ordered(envelopes: Iterable[Envelope] = ()) -> "Ordered":
        flows: dict[Tuple[Id, Id], tuple] = {}
        for env in envelopes:
            key = (env.src, env.dst)
            flows[key] = flows.get(key, ()) + (env.msg,)
        return Ordered(flows)

    @staticmethod
    def names() -> list[str]:
        return [
            "ordered",
            "unordered_duplicating",
            "unordered_nonduplicating",
        ]

    @staticmethod
    def from_name(name: str) -> "Network":
        if name == "ordered":
            return Network.new_ordered()
        if name in ("unordered_duplicating", "duplicating"):
            return Network.new_unordered_duplicating()
        if name in ("unordered_nonduplicating", "nonduplicating", "unordered"):
            return Network.new_unordered_nonduplicating()
        raise ValueError(
            f"unknown network {name!r}; expected one of {Network.names()}"
        )

    # interface -----------------------------------------------------------

    def send(self, env: Envelope) -> "Network":
        raise NotImplementedError

    def on_deliver(self, env: Envelope) -> "Network":
        raise NotImplementedError

    def on_drop(self, env: Envelope) -> "Network":
        raise NotImplementedError

    def iter_deliverable(self) -> Iterator[Envelope]:
        """Distinct deliverable envelopes (network.rs:160-170)."""
        raise NotImplementedError

    def iter_all(self) -> Iterator[Envelope]:
        """All envelopes, counting duplicates (network.rs:149-157)."""
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class UnorderedDuplicating(Network):
    __slots__ = ("envelopes", "_digest")

    def __init__(self, envelopes: frozenset):
        self.envelopes = envelopes
        self._digest: int | None = None

    def send(self, env: Envelope) -> "UnorderedDuplicating":
        if env in self.envelopes:
            return self
        return UnorderedDuplicating(self.envelopes | {env})

    def on_deliver(self, env: Envelope) -> "UnorderedDuplicating":
        return self  # redeliverable: delivery is a no-op (network.rs:204-206)

    def on_drop(self, env: Envelope) -> "UnorderedDuplicating":
        return UnorderedDuplicating(self.envelopes - {env})

    def iter_deliverable(self) -> Iterator[Envelope]:
        return iter(sorted(self.envelopes, key=_env_sort_key))

    def iter_all(self) -> Iterator[Envelope]:
        return self.iter_deliverable()

    def __len__(self) -> int:
        return len(self.envelopes)

    def _stable_hash_(self) -> int:
        if self._digest is None:
            self._digest = stable_hash(("UnorderedDuplicating", self.envelopes))
        return self._digest

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, UnorderedDuplicating)
            and self.envelopes == other.envelopes
        )

    def __hash__(self) -> int:
        return self._stable_hash_()

    def __repr__(self) -> str:
        return f"UnorderedDuplicating({sorted(self.envelopes, key=_env_sort_key)!r})"


class UnorderedNonDuplicating(Network):
    __slots__ = ("counts", "_digest")

    def __init__(self, counts: dict):
        self.counts = counts
        self._digest: int | None = None

    def send(self, env: Envelope) -> "UnorderedNonDuplicating":
        counts = dict(self.counts)
        counts[env] = counts.get(env, 0) + 1
        return UnorderedNonDuplicating(counts)

    def on_deliver(self, env: Envelope) -> "UnorderedNonDuplicating":
        count = self.counts.get(env)
        if count is None:
            raise KeyError(f"envelope not in network: {env!r}")
        counts = dict(self.counts)
        if count == 1:
            del counts[env]
        else:
            counts[env] = count - 1
        return UnorderedNonDuplicating(counts)

    def on_drop(self, env: Envelope) -> "UnorderedNonDuplicating":
        return self.on_deliver(env)  # same multiset decrement

    def iter_deliverable(self) -> Iterator[Envelope]:
        return iter(sorted(self.counts.keys(), key=_env_sort_key))

    def iter_all(self) -> Iterator[Envelope]:
        for env in sorted(self.counts.keys(), key=_env_sort_key):
            for _ in range(self.counts[env]):
                yield env

    def __len__(self) -> int:
        return sum(self.counts.values())

    def _stable_hash_(self) -> int:
        if self._digest is None:
            self._digest = stable_hash(("UnorderedNonDuplicating", self.counts))
        return self._digest

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, UnorderedNonDuplicating)
            and self.counts == other.counts
        )

    def __hash__(self) -> int:
        return self._stable_hash_()

    def __repr__(self) -> str:
        inner = {env: n for env, n in sorted(self.counts.items(), key=lambda kv: _env_sort_key(kv[0]))}
        return f"UnorderedNonDuplicating({inner!r})"


class Ordered(Network):
    """Per-(src, dst) FIFO flows; flows are never empty (canonical form,
    network.rs:221-244)."""

    __slots__ = ("flows", "_digest")

    def __init__(self, flows: dict):
        self.flows = {k: v for k, v in flows.items() if v}
        self._digest: int | None = None

    def send(self, env: Envelope) -> "Ordered":
        flows = dict(self.flows)
        key = (env.src, env.dst)
        flows[key] = flows.get(key, ()) + (env.msg,)
        return Ordered(flows)

    def on_deliver(self, env: Envelope) -> "Ordered":
        key = (env.src, env.dst)
        flow = self.flows.get(key)
        if flow is None:
            raise KeyError(f"flow not found: {key!r}")
        try:
            i = flow.index(env.msg)
        except ValueError:
            raise KeyError(f"message not in flow {key!r}: {env.msg!r}")
        flows = dict(self.flows)
        remaining = flow[:i] + flow[i + 1:]
        if remaining:
            flows[key] = remaining
        else:
            del flows[key]
        return Ordered(flows)

    def on_drop(self, env: Envelope) -> "Ordered":
        return self.on_deliver(env)

    def iter_deliverable(self) -> Iterator[Envelope]:
        # All messages in flow order; the ActorModel delivers only
        # channel heads (model.rs:244-260 prev_channel logic).
        for (src, dst) in sorted(self.flows.keys()):
            for msg in self.flows[(src, dst)]:
                yield Envelope(src, dst, msg)

    def iter_all(self) -> Iterator[Envelope]:
        return self.iter_deliverable()

    def __len__(self) -> int:
        return sum(len(f) for f in self.flows.values())

    def _stable_hash_(self) -> int:
        if self._digest is None:
            self._digest = stable_hash(("Ordered", self.flows))
        return self._digest

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Ordered) and self.flows == other.flows

    def __hash__(self) -> int:
        return self._stable_hash_()

    def __repr__(self) -> str:
        return f"Ordered({dict(sorted(self.flows.items()))!r})"
