"""Ordered reliable link: exactly-once in-order delivery over lossy nets.

Counterpart of stateright src/actor/ordered_reliable_link.rs:32-207 —
an ``ActorWrapper`` that wraps any actor with

1. per source/destination-pair ordering,
2. resend of unacknowledged messages on a network timer, and
3. redelivery suppression via per-sender sequence numbers,

loosely based on the "perfect link" of Cachin, Guerraoui & Rodrigues,
with ordering added. Like the reference, it assumes actors do not
restart (ordered_reliable_link.rs:9-10) and does not yet forward the
wrapped actor's own timers (the reference ``todo!``s there too,
ordered_reliable_link.rs:191-196 — ours raises ``NotImplementedError``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

from ..utils import HashableMap
from .base import Actor, CancelTimer, Cow, Id, Out, Send, SetTimer, is_no_op


@dataclass(frozen=True)
class Deliver:
    """Payload carrying its sequence number (MsgWrapper::Deliver)."""

    seq: int
    msg: Any


@dataclass(frozen=True)
class Ack:
    """Acknowledgement of a sequence number (MsgWrapper::Ack)."""

    seq: int


@dataclass(frozen=True)
class NetworkTimer:
    """The resend timer (TimerWrapper::Network)."""


@dataclass(frozen=True)
class LinkState:
    """StateWrapper (ordered_reliable_link.rs:51-60)."""

    next_send_seq: int
    msgs_pending_ack: HashableMap  # seq -> (dst, msg)
    last_delivered_seqs: HashableMap  # src -> seq
    wrapped_state: Any


class OrderedReliableLink(Actor):
    """``ActorWrapper`` (ordered_reliable_link.rs:32-35)."""

    def __init__(
        self,
        wrapped_actor: Actor,
        resend_interval: Tuple[float, float] = (1.0, 2.0),
    ):
        self.wrapped_actor = wrapped_actor
        self.resend_interval = resend_interval

    def name(self) -> str:
        return self.wrapped_actor.name()

    def on_start(self, id: Id, out: Out) -> LinkState:
        out.set_timer(NetworkTimer(), self.resend_interval)
        wrapped_out = Out()
        state = LinkState(
            next_send_seq=1,
            msgs_pending_ack=HashableMap(),
            last_delivered_seqs=HashableMap(),
            wrapped_state=self.wrapped_actor.on_start(id, wrapped_out),
        )
        state, _ = _process_output(state, wrapped_out, out)
        return state

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        link: LinkState = state.value
        if isinstance(msg, Deliver):
            # Always ack to stop resends; drop if already delivered
            # (ordered_reliable_link.rs:109-121).
            out.send(src, Ack(msg.seq))
            if msg.seq <= link.last_delivered_seqs.get(src, 0):
                return
            wrapped_cow = Cow(link.wrapped_state)
            wrapped_out = Out()
            self.wrapped_actor.on_msg(id, wrapped_cow, src, msg.msg, wrapped_out)
            if is_no_op(wrapped_cow, wrapped_out):
                return
            new_link = LinkState(
                next_send_seq=link.next_send_seq,
                msgs_pending_ack=link.msgs_pending_ack,
                last_delivered_seqs=link.last_delivered_seqs.set(
                    src, msg.seq
                ),
                wrapped_state=wrapped_cow.value,
            )
            new_link, out_cmds = _process_output(new_link, wrapped_out, out)
            state.set(new_link)
        elif isinstance(msg, Ack):
            state.set(
                LinkState(
                    next_send_seq=link.next_send_seq,
                    msgs_pending_ack=link.msgs_pending_ack.remove(msg.seq),
                    last_delivered_seqs=link.last_delivered_seqs,
                    wrapped_state=link.wrapped_state,
                )
            )

    def on_timeout(self, id: Id, state: Cow, timer: Any, out: Out) -> None:
        link: LinkState = state.value
        if isinstance(timer, NetworkTimer):
            # Re-arm and resend everything unacked
            # (ordered_reliable_link.rs:157-163).
            out.set_timer(NetworkTimer(), self.resend_interval)
            for seq in sorted(link.msgs_pending_ack.keys()):
                dst, msg = link.msgs_pending_ack[seq]
                out.send(dst, Deliver(seq, msg))
        else:
            raise NotImplementedError(
                "wrapped-actor timers are not forwarded yet "
                "(ordered_reliable_link.rs:191-196 todo!)"
            )


def _process_output(
    link: LinkState, wrapped_out: Out, out: Out
) -> tuple[LinkState, None]:
    """Assign sequence numbers to the wrapped actor's sends and stage
    them for resend (ordered_reliable_link.rs:183-207)."""
    for command in wrapped_out:
        if isinstance(command, (SetTimer, CancelTimer)):
            raise NotImplementedError(
                "wrapped SetTimer/CancelTimer not supported "
                "(ordered_reliable_link.rs:191-196 todo!)"
            )
        assert isinstance(command, Send)
        seq = link.next_send_seq
        out.send(command.dst, Deliver(seq, command.msg))
        link = LinkState(
            next_send_seq=seq + 1,
            msgs_pending_ack=link.msgs_pending_ack.set(
                seq, (command.dst, command.msg)
            ),
            last_delivered_seqs=link.last_delivered_seqs,
            wrapped_state=link.wrapped_state,
        )
    return link, None
