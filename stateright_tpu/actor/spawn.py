"""Real-world actor execution over UDP.

Counterpart of stateright src/actor/spawn.rs:64-147: the *same*
``Actor`` subclasses the model checker verified run as real network
nodes — one thread per actor, a UDP socket bound to the address packed
in its ``Id`` (spawn.rs:81; packing in base.py mirrors spawn.rs:10-34),
and an event loop that waits for the earliest timer deadline, receives
and deserializes datagrams into ``on_msg``, fires ``on_timeout``, and
applies emitted commands (send / set-timer / cancel-timer,
spawn.rs:92-206).

Serialization is pluggable (``serialize``/``deserialize`` callables,
spawn.rs:64-67); :func:`json_serde` provides the JSON codec the
reference examples use (examples/paxos.rs:426-450), with JSON arrays
decoded as tuples so values round-trip into comparable Python shapes.
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time
from dataclasses import fields, is_dataclass
from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

from .base import Actor, CancelTimer, Cow, Id, Out, Send, SetTimer

#: Cancelled timers are parked ~500 years out (spawn.rs:36-39).
_PRACTICALLY_NEVER = 500 * 365 * 24 * 3600.0
_MAX_DATAGRAM = 65507


# -- serde ---------------------------------------------------------------


def json_serde(
    msg_types: Iterable[type],
) -> Tuple[Callable[[Any], bytes], Callable[[bytes], Any]]:
    """A JSON codec over a closed set of dataclass message types.

    Encoding: ``{"TypeName": [field, values]}`` for dataclasses
    (nested ones too), scalars as themselves, tuples as arrays.
    Decoding inverts it, turning arrays back into tuples — model
    states compare ballots and the like structurally, so tuple-ness
    must survive the round trip.
    """
    registry = {t.__name__: t for t in msg_types}

    def enc(obj: Any):
        if is_dataclass(obj) and type(obj).__name__ in registry:
            return {
                type(obj).__name__: [
                    enc(getattr(obj, f.name)) for f in fields(obj)
                ]
            }
        if isinstance(obj, (list, tuple)):
            return [enc(x) for x in obj]
        if isinstance(obj, Id):
            return int(obj)
        return obj

    def dec(obj: Any):
        if isinstance(obj, dict) and len(obj) == 1:
            (name, args), = obj.items()
            if name in registry:
                return registry[name](*(dec(a) for a in args))
        if isinstance(obj, list):
            return tuple(dec(x) for x in obj)
        return obj

    def serialize(msg: Any) -> bytes:
        return json.dumps(enc(msg)).encode()

    def deserialize(data: bytes) -> Any:
        return dec(json.loads(data.decode()))

    return serialize, deserialize


def register_msg_types() -> list[type]:
    """The register protocol + paxos internals — enough for the
    bundled spawnable examples."""
    from ..models import paxos as px
    from . import register as reg

    return [
        reg.Put,
        reg.Get,
        reg.PutOk,
        reg.GetOk,
        reg.Internal,
        px.Prepare,
        px.Prepared,
        px.Accept,
        px.Accepted,
        px.Decided,
    ]


# -- runtime -------------------------------------------------------------


class ActorHandle:
    """One running actor: its thread, socket, and live state."""

    def __init__(self, id: Id, actor: Actor):
        self.id = id
        self.actor = actor
        self._state_lock = threading.Lock()
        self._state: Any = None
        self._stop = threading.Event()
        self.thread: Optional[threading.Thread] = None
        self.events = 0  # messages + timeouts handled

    @property
    def state(self) -> Any:
        with self._state_lock:
            return self._state

    def _set_state(self, value: Any) -> None:
        with self._state_lock:
            self._state = value

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self.thread is not None:
            self.thread.join(timeout)


def spawn(
    serialize: Callable[[Any], bytes],
    deserialize: Callable[[bytes], Any],
    actors: Sequence[Tuple[Id, Actor]],
    daemon: bool = True,
) -> list[ActorHandle]:
    """Run each ``(id, actor)`` on its own thread + UDP socket
    (spawn.rs:64-147). Returns handles; call ``stop()``/``join()`` to
    shut down (the reference blocks forever; handles make the runtime
    testable and embeddable)."""
    handles = []
    for id, actor in actors:
        handle = ActorHandle(Id(id), actor)
        # Bind before any event loop starts: on_start sends race
        # sibling binds otherwise, and a dropped hello deadlocks
        # protocols without retry timers.
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(Id(id).to_addr())
        thread = threading.Thread(
            target=_event_loop,
            args=(handle, sock, serialize, deserialize),
            name=f"actor-{int(id)}",
            daemon=daemon,
        )
        handle.thread = thread
        handles.append(handle)
    for handle in handles:
        handle.thread.start()
    return handles


def _event_loop(handle: ActorHandle, sock, serialize, deserialize) -> None:
    id, actor = handle.id, handle.actor
    try:
        timers: dict[Any, float] = {}
        out = Out()
        state = actor.on_start(id, out)
        handle._set_state(state)
        _apply(sock, id, out, timers, serialize)
        while not handle._stop.is_set():
            now = time.monotonic()
            # Earliest timer deadline bounds the socket wait
            # (spawn.rs:95-101); capped so stop() stays responsive.
            deadline = min(timers.values(), default=now + _PRACTICALLY_NEVER)
            sock.settimeout(max(0.0, min(deadline - now, 0.1)))
            fired = None
            try:
                data, addr = sock.recvfrom(_MAX_DATAGRAM)
            except (socket.timeout, BlockingIOError):
                # settimeout(0.0) — a timer already due — makes the
                # socket non-blocking, and recvfrom then raises
                # BlockingIOError rather than socket.timeout.
                data = None
                now = time.monotonic()
                due = [(when, t) for t, when in timers.items() if when <= now]
                if due:
                    # Earliest deadline first (spawn.rs services the
                    # minimum deadline it waited on).
                    fired = min(due, key=lambda d: d[0])[1]
            cow = Cow(state)
            out = Out()
            if data is not None:
                try:
                    msg = deserialize(data)
                except Exception:
                    continue  # garbage datagram (spawn.rs:118-126)
                src = Id.from_addr(addr[0], addr[1])
                actor.on_msg(id, cow, src, msg, out)
                handle.events += 1
            elif fired is not None:
                del timers[fired]  # fired timers are no longer set
                actor.on_timeout(id, cow, fired, out)
                handle.events += 1
            else:
                continue
            if cow.owned:
                state = cow.value
                handle._set_state(state)
            _apply(sock, id, out, timers, serialize)
    finally:
        sock.close()


def _apply(sock, id: Id, out: Out, timers: dict, serialize) -> None:
    """Apply emitted commands (spawn.rs:150-206)."""
    for command in out:
        if isinstance(command, Send):
            try:
                sock.sendto(serialize(command.msg), command.dst.to_addr())
            except OSError:
                pass  # unreachable peer: UDP semantics, drop
        elif isinstance(command, SetTimer):
            duration = random.uniform(command.min_sec, command.max_sec)
            timers[command.timer] = time.monotonic() + duration
        elif isinstance(command, CancelTimer):
            # Parked, not deleted (spawn.rs:199-204 semantics); simply
            # removing it is equivalent here.
            timers.pop(command.timer, None)


# -- CLI spawn entry points (examples/paxos.rs:403-465 etc.) -------------


def _loopback_ids(base_port: int, count: int) -> list[Id]:
    return [Id.from_addr("127.0.0.1", base_port + i) for i in range(count)]


def spawn_paxos_cluster(base_port: int = 3000, block: bool = True):
    from ..models.paxos import PaxosActor
    from .register import RegisterServer

    ids = _loopback_ids(base_port, 3)
    serialize, deserialize = json_serde(register_msg_types())
    print("  A set of servers that implement Single Decree Paxos.")
    print("  You can interact via UDP, e.g. with netcat:")
    print(f"$ nc -u localhost {base_port}")
    print(serialize(_example_put()).decode())
    print(serialize(_example_get()).decode())
    handles = spawn(
        serialize,
        deserialize,
        [
            (
                ids[i],
                RegisterServer(
                    PaxosActor([ids[j] for j in range(3) if j != i])
                ),
            )
            for i in range(3)
        ],
    )
    if block:
        for handle in handles:
            handle.join()
    return handles


def spawn_single_copy_cluster(base_port: int = 3000, block: bool = True):
    from ..models.single_copy_register import SingleCopyActor

    ids = _loopback_ids(base_port, 1)
    serialize, deserialize = json_serde(register_msg_types())
    print("  A single-copy register server.")
    print(f"$ nc -u localhost {base_port}")
    print(serialize(_example_put()).decode())
    print(serialize(_example_get()).decode())
    handles = spawn(serialize, deserialize, [(ids[0], SingleCopyActor())])
    if block:
        for handle in handles:
            handle.join()
    return handles


def spawn_abd_cluster(base_port: int = 3000, block: bool = True):
    from ..models.linearizable_register import AbdActor
    from .register import RegisterServer

    ids = _loopback_ids(base_port, 2)
    serialize, deserialize = json_serde(
        register_msg_types() + _abd_msg_types()
    )
    print("  ABD algorithm servers for a linearizable register.")
    print(f"$ nc -u localhost {base_port}")
    print(serialize(_example_put()).decode())
    print(serialize(_example_get()).decode())
    handles = spawn(
        serialize,
        deserialize,
        [
            (ids[i], RegisterServer(AbdActor([ids[1 - i]])))
            for i in range(2)
        ],
    )
    if block:
        for handle in handles:
            handle.join()
    return handles


def _abd_msg_types() -> list[type]:
    from ..models import linearizable_register as abd

    return [abd.Query, abd.AckQuery, abd.Record, abd.AckRecord]


def _example_put():
    from .register import Put

    return Put(1, "X")


def _example_get():
    from .register import Get

    return Get(2)
