"""The sharded wave engine: BFS over a device mesh via ``shard_map``.

Design (the job_market.rs:66-147 replacement promised in SURVEY §2.5):

* Every device owns the fingerprint residues ``fp_lo % n_shards ==
  shard_index``: its slice of the visited table, the parent forest, and
  the frontier rows it discovered.
* A wave runs entirely inside one ``shard_map``-wrapped
  ``lax.while_loop``: each device expands its local frontier block
  (vmap step → property bitmaps → candidate compaction → vectorized
  fingerprints), then routes each candidate to its owner with one
  ``lax.all_to_all`` keyed by ``fp % n_shards`` — dedup (sort-unique +
  table insert) is thereafter shard-local, exactly the role DashMap
  sharding plays in the reference BFS (bfs.rs:28-29), but with the
  *communication* pattern chosen for ICI: one balanced collective per
  wave instead of work stealing.
* Termination, state counters, discovery folding, and overflow flags
  are ``psum``/``pmin`` reductions, so every device agrees on ``done``
  without touching the host (the distributed-termination condvar dance
  of job_market.rs:66-101 becomes a single all-reduce).
* The host syncs once per ``waves_per_sync`` waves via the same packed
  stats vector as the single-chip engine.

Shapes are per-shard: ``capacity``/``frontier_capacity``/
``cand_capacity`` size each device's slice. ``bucket_capacity`` bounds
the rows routed to any single destination per wave (the all_to_all's
fixed tile size); overflow is detected and reported, never silent.

On one device the shuffle degenerates to the identity and the engine
matches the single-chip one state for state; tests pin identical
results for shard counts 1/2/8 on the CPU mesh.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..checker import CheckerBuilder
from ..encoding import EncodedModel
from ..model import Expectation
from ..ops.fingerprint import fingerprint_u32v
from ..ops.hashset import DeviceHashSet, insert
from ..ops.u64 import U64, u64_add
from ..checkers.tpu import (
    TpuBfsChecker,
    expand_frontier,
    wave_hits,
)


def payload_tile_width(w: int, track_paths: bool) -> int:
    """Lanes of this engine's routed candidate payload (``E2`` in
    ``_build_programs``): state + (parent fp when tracked) + ebits +
    the candidate's own fp limbs. ONE formula for the device program
    and the lane config's ``dest_tile_lanes`` (what
    telemetry.shard_balance prices routed bytes with)."""
    return (w + 3 if track_paths else w + 1) + 2


class ShardedTpuBfsChecker(TpuBfsChecker):
    """``CheckerBuilder.spawn_tpu_sharded()`` — the wave engine over a
    ``jax.sharding.Mesh``. Inherits the whole result/reconstruction
    surface from the single-chip engine; only the device programs (and
    their shard_map wrapping) differ."""

    def __init__(
        self,
        builder: CheckerBuilder,
        encoded: Optional[EncodedModel] = None,
        mesh=None,
        n_shards: Optional[int] = None,
        capacity: int = 1 << 13,
        frontier_capacity: Optional[int] = None,
        track_paths: bool = True,
        waves_per_sync: int = 16,
        cand_capacity: Optional[int] = None,
        bucket_capacity: Optional[int] = None,
        probe_rounds: int = 16,
        **kwargs,
    ):
        import jax
        from jax.sharding import Mesh

        if mesh is None:
            devices = jax.devices()
            if n_shards is None:
                n_shards = len(devices)
            if n_shards > len(devices):
                raise ValueError(
                    f"n_shards={n_shards} > {len(devices)} available devices"
                )
            mesh = Mesh(np.array(devices[:n_shards]), ("shard",))
        if len(mesh.axis_names) != 1:
            raise ValueError(
                f"expected a 1-axis mesh, got axes {mesh.axis_names}"
            )
        self.mesh = mesh
        self.n_shards = int(mesh.devices.size)
        super().__init__(
            builder,
            encoded=encoded,
            capacity=capacity,
            frontier_capacity=frontier_capacity,
            track_paths=track_paths,
            waves_per_sync=waves_per_sync,
            cand_capacity=cand_capacity,
            probe_rounds=probe_rounds,
            **kwargs,
        )
        self.total_capacity = capacity * self.n_shards
        self.bucket_capacity = bucket_capacity
        #: live shard ids in ORIGINAL numbering (the degrade-and-
        #: continue layer: faultinject filters persistent shard
        #: faults against this, and a supervised degrade removes the
        #: dropped shard — checkers/tpu.py _degrade_shards).
        self._shard_ids = tuple(range(self.n_shards))

    def _cache_extras(self) -> tuple:
        # Mesh hashes by devices + axis names, so equivalent meshes
        # share compiled programs and distinct device sets never alias.
        # Traced runs carry the wave/shard logs: a different program.
        return (
            self.n_shards,
            self.bucket_capacity,
            self.mesh,
            self._wave_log_enabled(),
        )

    def _cand_overflow_message(self) -> str:
        return (
            "candidate/bucket overflow: a wave generated more successors "
            f"than fit the per-shard buffers (cand_capacity="
            f"{self.cand_capacity}, bucket_capacity={self.bucket_capacity});"
            " re-run with larger capacities (or None for never-overflow "
            "sizes)"
        )

    def _consume_extra_stats(self, extra: np.ndarray) -> None:
        if extra.size >= 2:
            self.metrics["shuffle_volume"] = int(extra[0]) | (
                int(extra[1]) << 32
            )

    # -- telemetry (stateright_tpu/telemetry.py) ---------------------------

    def _wave_log_rows(self, s: np.ndarray, n_props: int):
        if not self._wave_log_enabled():
            return None
        from ..telemetry import WAVE_LOG_LANES as WL

        off = 11 + 3 * n_props + 2  # scalars + discovery + sent lanes
        return s[off:off + self.waves_per_sync * WL].reshape(
            self.waves_per_sync, WL
        )

    def _wave_log_pairs_valid(self) -> bool:
        # Dense hash-table waves have no (row, slot) pair extraction;
        # the shard log's lane 1 carries the candidate count (the
        # single-chip dense convention) and back-fills the wave event.
        return False

    def _plan_sharded_names(self) -> tuple:
        # Mirrors the shard_map out_specs below: these carry leaves
        # are split across the mesh, so their ledger rows report
        # per_shard_bytes = bytes / n_shards (memplan.plan_entries).
        return ("t_lo", "t_hi", "p_lo_t", "p_hi_t", "frontier",
                "fval", "ebits", "slog", "u_loc")

    def _lane_config(self) -> dict:
        lane = super()._lane_config()
        lane.update(
            n_shards=self.n_shards,
            bucket_capacity=self.bucket_capacity,
            # routed-payload lanes (E2): what telemetry.shard_balance
            # prices routed-byte volume with (rows x lanes x 4 B)
            dest_tile_lanes=payload_tile_width(
                self.encoded.width, self.track_paths
            ),
            # open addressing: shard_balance's occupancy watch uses
            # the probe-pressure threshold, not exact-capacity
            # headroom (stateright_tpu/occupancy.py)
            visited_exact=False,
        )
        return lane

    # -- device programs ---------------------------------------------------

    def _build_programs(self, n0: int):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map  # jax >= 0.8
        except ImportError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map

        enc = self.encoded
        props = list(self.model.properties())
        n_props = len(props)
        evt_idx = [
            i for i, p in enumerate(props)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if evt_idx and max(evt_idx) >= 32:
            raise ValueError(
                "the TPU engine supports eventually properties only at "
                "property indices < 32; reorder properties() so eventually "
                f"properties come first (got index {max(evt_idx)})"
            )
        K, W, F = enc.max_actions, enc.width, self.frontier_capacity
        S = self.n_shards
        capacity = self.capacity
        B = min(self.cand_capacity or F * K, F * K)
        # Rows routable to one destination per wave. B is the
        # never-overflow bound (every local candidate bound for one
        # shard); the fingerprint split is near-uniform, so the default
        # gives each destination 4x its expected share (overflow is
        # detected, reported with the sizing knob, and never silent).
        if self.bucket_capacity is not None:
            Bd = min(self.bucket_capacity, B)
        elif S == 1:
            Bd = B
        else:
            Bd = min(B, max(128, (4 * B + S - 1) // S))
        probe_rounds = self.probe_rounds
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth
        waves_per_sync = self.waves_per_sync
        ebits_init = self._eventually_bits_init()
        track_paths = self.track_paths
        # Payload lanes: state + (parent fp) + ebits, + the candidate's
        # own fingerprint so owners don't re-hash after the shuffle.
        # All-zero rows mark unused bucket slots (fingerprints are
        # never 0, ops/fingerprint.py).
        E2 = payload_tile_width(W, track_paths)
        E = E2 - 2
        EB = E - 1
        mesh = self.mesh
        # Per-wave trace logs (telemetry.py, round 11): the GLOBAL
        # wave log (psum'd counters — this engine's body is monolithic,
        # so the row is assembled in place) and the PER-SHARD mesh log
        # (SHARD_LOG_FIELDS: local frontier/candidates, routed and
        # received rows, bucket fill vs the lossless Bd cap, local
        # new/visited). Gated on an active tracer and cache-keyed so
        # untraced programs compile exactly as before. ``u_loc`` (the
        # per-shard visited counter the log's last lane reports) only
        # exists on traced runs.
        from ..telemetry import SHARD_LOG_LANES as SL
        from ..telemetry import WAVE_LOG_LANES as WL

        trace_log = self._wave_log_enabled()

        def bool_any(x):
            """Global OR of per-shard bools (replicated result)."""
            return lax.psum(x.astype(jnp.uint32), "shard") > 0

        def seed_local(init_rows):
            me = lax.axis_index("shard").astype(jnp.uint32)
            lo0, hi0 = fingerprint_u32v(init_rows, jnp)
            mine = (lo0 % jnp.uint32(S)) == me
            pos = jnp.cumsum(mine) - 1
            sp = jnp.where(mine, pos, F)
            frontier = jnp.zeros((F, W), dtype=jnp.uint32).at[sp].set(
                init_rows, mode="drop"
            )
            n_mine = jnp.sum(mine)
            fval = jnp.arange(F) < n_mine
            ebits = jnp.where(fval, jnp.uint32(ebits_init), jnp.uint32(0))
            table = DeviceHashSet.empty(capacity, jnp)
            table, _, pending, _ = insert(
                table, lo0, hi0, mine, jnp, rounds=probe_rounds
            )
            overflow = bool_any(jnp.any(pending))
            return dict(
                **(
                    dict(
                        wlog=jnp.zeros((waves_per_sync, WL),
                                       jnp.uint32),
                        slog=jnp.zeros((waves_per_sync, SL),
                                       jnp.uint32),
                        u_loc=n_mine.astype(jnp.uint32).reshape(1),
                    )
                    if trace_log else {}
                ),
                t_lo=table.lo,
                t_hi=table.hi,
                p_lo_t=jnp.zeros(capacity if track_paths else 0, jnp.uint32),
                p_hi_t=jnp.zeros(capacity if track_paths else 0, jnp.uint32),
                frontier=frontier,
                fval=fval,
                ebits=ebits,
                depth=jnp.int32(1),
                wchunk=jnp.int32(0),
                waves=jnp.uint32(0),
                gen_lo=jnp.uint32(n0),
                gen_hi=jnp.uint32(0),
                new=jnp.uint32(n0),
                sent_lo=jnp.uint32(0),
                sent_hi=jnp.uint32(0),
                disc_found=jnp.zeros(n_props, dtype=bool),
                disc_lo=jnp.zeros(n_props, dtype=jnp.uint32),
                disc_hi=jnp.zeros(n_props, dtype=jnp.uint32),
                overflow=overflow,
                f_overflow=jnp.bool_(False),
                c_overflow=jnp.bool_(False),
                e_overflow=jnp.bool_(False),
                done=jnp.bool_(n0 == 0) | overflow,
            )

        def body(c):
            table = DeviceHashSet(c["t_lo"], c["t_hi"])
            ebits = c["ebits"]
            fval = c["fval"]
            me = lax.axis_index("shard").astype(jnp.uint32)
            if trace_log:
                n_f_loc = jnp.sum(fval, dtype=jnp.uint32)

            if target_depth is None:
                expand = jnp.bool_(True)
            else:
                expand = c["depth"] < target_depth

            ex = expand_frontier(
                enc, props, evt_idx, c["frontier"], fval, ebits, expand
            )
            e_overflow = c["e_overflow"] | bool_any(jnp.any(ex["trunc"]))

            # Discoveries: local per-wave hits, globally folded. The
            # winning fingerprint comes from the lowest shard index
            # that hit (any racing thread wins in the reference).
            if n_props:
                hits, los, his = wave_hits(props, ex, fval)
                ghits = bool_any(hits)
                pri = jnp.where(hits, me, jnp.uint32(S))
                winner = lax.pmin(pri, "shard")
                sel = hits & (pri == winner)
                g_lo = lax.psum(jnp.where(sel, los, jnp.uint32(0)), "shard")
                g_hi = lax.psum(jnp.where(sel, his, jnp.uint32(0)), "shard")
                fresh = ghits & ~c["disc_found"]
                disc_found = c["disc_found"] | ghits
                disc_lo = jnp.where(fresh, g_lo, c["disc_lo"])
                disc_hi = jnp.where(fresh, g_hi, c["disc_hi"])
            else:
                disc_found = c["disc_found"]
                disc_lo = c["disc_lo"]
                disc_hi = c["disc_hi"]

            # Local candidate compaction (identical to single-chip).
            n_cand = jnp.sum(ex["v"])
            parts = [ex["flat"]]
            if track_paths:
                parts += [ex["p_lo"][:, None], ex["p_hi"][:, None]]
            parts.append(ex["child_ebits"][:, None])
            ext = jnp.concatenate(parts, axis=1)
            if B < F * K:
                cpos = jnp.cumsum(ex["v"]) - 1
                csp = jnp.where(ex["v"], cpos, B)
                b_ext = jnp.zeros((B, E), jnp.uint32).at[csp].set(
                    ext, mode="drop"
                )
                b_val = jnp.arange(B) < n_cand
                c_overflow = c["c_overflow"] | bool_any(n_cand > B)
            else:
                b_ext = ext
                b_val = ex["v"]
                c_overflow = c["c_overflow"]

            b_lo, b_hi = fingerprint_u32v(b_ext[:, :W], jnp)
            owner = b_lo % jnp.uint32(S)
            payload = jnp.concatenate(
                [
                    b_ext,
                    jnp.where(b_val, b_lo, jnp.uint32(0))[:, None],
                    jnp.where(b_val, b_hi, jnp.uint32(0))[:, None],
                ],
                axis=1,
            )

            # Route: compact each destination's candidates into its
            # fixed Bd-row tile of the send buffer, then one all_to_all
            # swaps tiles so every candidate lands on its owner.
            send = jnp.zeros((S * Bd, E2), dtype=jnp.uint32)
            route_ovf = jnp.bool_(False)
            fill_peak = jnp.uint32(0)
            for d in range(S):
                m = b_val & (owner == d)
                pos = jnp.cumsum(m) - 1
                sp = jnp.where(m, d * Bd + pos, S * Bd)
                send = send.at[sp].set(payload, mode="drop")
                cnt_d = jnp.sum(m)
                route_ovf = route_ovf | (cnt_d > Bd)
                if trace_log:
                    # peak destination-bucket fill for the shard log
                    fill_peak = jnp.maximum(
                        fill_peak, cnt_d.astype(jnp.uint32)
                    )
            c_overflow = c_overflow | bool_any(route_ovf)
            cross = n_cand - jnp.sum(b_val & (owner == me))
            g_cross = lax.psum(cross.astype(jnp.uint32), "shard")
            sent = u64_add(
                U64(c["sent_lo"], c["sent_hi"]), U64(g_cross, jnp.uint32(0))
            )

            recv = lax.all_to_all(
                send, "shard", split_axis=0, concat_axis=0, tiled=True
            )

            # Owner-local insert-if-absent (bfs.rs:292-306 semantics,
            # with zero cross-shard contention by construction);
            # duplicate keys in the received batch resolve inside the
            # probe loop, so no sort-unique pass is needed.
            r_lo = recv[:, E]
            r_hi = recv[:, E + 1]
            r_val = (r_lo != 0) | (r_hi != 0)
            table, is_new, pending, slots = insert(
                table, r_lo, r_hi, r_val, jnp, rounds=probe_rounds
            )
            overflow = c["overflow"] | bool_any(jnp.any(pending))
            s_ext = recv

            if track_paths:
                par_idx = jnp.where(is_new, slots, jnp.uint32(capacity))
                p_lo_t = c["p_lo_t"].at[par_idx].set(
                    s_ext[:, W], mode="drop"
                )
                p_hi_t = c["p_hi_t"].at[par_idx].set(
                    s_ext[:, W + 1], mode="drop"
                )
            else:
                p_lo_t, p_hi_t = c["p_lo_t"], c["p_hi_t"]

            new_count = jnp.sum(is_new)
            pos = jnp.cumsum(is_new) - 1
            scatter_pos = jnp.where(is_new, pos, F)
            next_fe = jnp.zeros((F, E2), dtype=jnp.uint32).at[
                scatter_pos
            ].set(s_ext, mode="drop")
            next_frontier = next_fe[:, :W]
            next_ebits = next_fe[:, EB]
            next_fval = jnp.arange(F) < new_count
            f_overflow = c["f_overflow"] | bool_any(new_count > F)

            g_new = lax.psum(new_count.astype(jnp.uint32), "shard")
            g_cand = lax.psum(n_cand.astype(jnp.uint32), "shard")
            g = u64_add(
                U64(c["gen_lo"], c["gen_hi"]), U64(g_cand, jnp.uint32(0))
            )
            new = c["new"] + g_new

            all_disc = (
                jnp.all(disc_found) if n_props else jnp.bool_(False)
            )
            if target_states is None:
                target_hit = jnp.bool_(False)
            else:
                target_hit = new >= jnp.uint32(target_states)
            cont = (
                (g_new > 0)
                & ~all_disc
                & ~target_hit
                & ~overflow
                & ~f_overflow
                & ~c_overflow
                & ~e_overflow
            )
            trace_extra = {}
            if trace_log:
                u_loc = c["u_loc"] + new_count.astype(jnp.uint32)
                # GLOBAL wave row (replicated lanes only): lane 1 is 0
                # — the dense wave has no pair popcount; the host
                # back-fills the event from the shard log's candidate
                # lane (_wave_log_pairs_valid).
                row = jnp.stack(
                    [
                        lax.psum(n_f_loc, "shard"),
                        jnp.uint32(0),
                        g_cand,
                        g_new,
                        new,
                        c["depth"].astype(jnp.uint32),
                        jnp.uint32(0),  # no frontier ladder here
                        jnp.uint32(0),  # no visited ladder here
                    ]
                )
                # PER-SHARD mesh row (SHARD_LOG_FIELDS): never psum'd.
                srow = jnp.stack(
                    [
                        n_f_loc,
                        n_cand.astype(jnp.uint32),  # dense: candidates
                        n_cand.astype(jnp.uint32),
                        cross.astype(jnp.uint32),
                        jnp.sum(r_val, dtype=jnp.uint32),
                        fill_peak,
                        jnp.uint32(Bd),
                        new_count.astype(jnp.uint32),
                        u_loc[0],
                    ]
                )
                trace_extra = dict(
                    wlog=lax.dynamic_update_slice(
                        c["wlog"], row[None, :],
                        (c["wchunk"], jnp.int32(0)),
                    ),
                    slog=lax.dynamic_update_slice(
                        c["slog"], srow[None, :],
                        (c["wchunk"], jnp.int32(0)),
                    ),
                    u_loc=u_loc,
                )
            return dict(
                **trace_extra,
                t_lo=table.lo,
                t_hi=table.hi,
                p_lo_t=p_lo_t,
                p_hi_t=p_hi_t,
                frontier=next_frontier,
                fval=next_fval & cont,
                ebits=next_ebits,
                depth=jnp.where(cont, c["depth"] + 1, c["depth"]),
                wchunk=c["wchunk"] + 1,
                waves=c["waves"] + 1,
                gen_lo=g.lo,
                gen_hi=g.hi,
                new=new,
                sent_lo=sent.lo,
                sent_hi=sent.hi,
                disc_found=disc_found,
                disc_lo=disc_lo,
                disc_hi=disc_hi,
                overflow=overflow,
                f_overflow=f_overflow,
                c_overflow=c_overflow,
                e_overflow=e_overflow,
                done=~cont,
            )

        def cond(c):
            return ~c["done"] & (c["wchunk"] < waves_per_sync)

        # Memory ledger (memplan.py): no ladder here either — one
        # fixed-shape class per shard; the routed send/recv tiles are
        # the staging this engine adds over the single-chip one.
        from ..memplan import buffer_entry, plan_total

        _staging = [
            buffer_entry("cand_payload", (F * K, E), "uint32"),
            buffer_entry("cand_compact", (B, E2), "uint32"),
            buffer_entry("send_tiles", (S * Bd, E2), "uint32"),
            buffer_entry("recv_tiles", (S * Bd, E2), "uint32"),
        ]
        self._build_info = dict(
            classes=[dict(
                f_class=0, v_class=0, mode="hash-sharded",
                frontier_rows=F, visited_rows=capacity,
                dest_cap=Bd, staging=_staging,
                staging_bytes=plan_total(_staging),
            )],
            v_classes=[],
            engine_modes=[],
        )

        def chunk(carry):
            from jax import lax as _lax

            c = dict(carry, wchunk=jnp.int32(0))
            c = _lax.while_loop(cond, body, c)
            frontier_total = _lax.psum(
                jnp.sum(c["fval"]).astype(jnp.uint32), "shard"
            )
            scalars = jnp.stack(
                [
                    c["done"].astype(jnp.uint32),
                    c["overflow"].astype(jnp.uint32),
                    c["f_overflow"].astype(jnp.uint32),
                    c["depth"].astype(jnp.uint32),
                    c["waves"],
                    frontier_total,
                    c["gen_lo"],
                    c["gen_hi"],
                    c["new"],
                    c["c_overflow"].astype(jnp.uint32),
                    c["e_overflow"].astype(jnp.uint32),
                ]
            )
            parts = [
                scalars,
                c["disc_found"].astype(jnp.uint32),
                c["disc_lo"],
                c["disc_hi"],
                jnp.stack([c["sent_lo"], c["sent_hi"]]),
            ]
            if trace_log:
                parts.append(c["wlog"].reshape(-1))
            stats = jnp.concatenate(parts)
            if trace_log:
                # the per-shard mesh log: a second, shard-sharded
                # stats output — same dispatch, same sync point
                return c, stats, c["slog"].reshape(-1)
            return c, stats

        P_shard = P("shard")
        specs = dict(
            **(
                dict(wlog=P(), slog=P("shard", None), u_loc=P_shard)
                if trace_log else {}
            ),
            t_lo=P_shard,
            t_hi=P_shard,
            p_lo_t=P_shard,
            p_hi_t=P_shard,
            frontier=P("shard", None),
            fval=P_shard,
            ebits=P_shard,
            depth=P(),
            wchunk=P(),
            waves=P(),
            gen_lo=P(),
            gen_hi=P(),
            new=P(),
            sent_lo=P(),
            sent_hi=P(),
            disc_found=P(),
            disc_lo=P(),
            disc_hi=P(),
            overflow=P(),
            f_overflow=P(),
            c_overflow=P(),
            e_overflow=P(),
            done=P(),
        )
        # Older jax (no lax.pvary) has no replication rule for
        # while_loop inside shard_map: disable the rep checker there
        # (its named workaround; newer jax type-checks varying-ness,
        # which the vma promotions in ops/hashset.py satisfy).
        from jax import lax as _lax

        sm_kw = {} if hasattr(_lax, "pvary") else {"check_rep": False}
        # Checkpoint/resume (stateright_tpu/checkpoint.py): a resumed
        # run places snapshot buffers with these exact shardings.
        self._carry_pspecs = dict(specs)
        chunk_out = (
            (specs, P(), P_shard) if trace_log else (specs, P())
        )
        seed_sm = shard_map(
            seed_local, mesh=mesh, in_specs=P(), out_specs=specs,
            **sm_kw,
        )
        chunk_sm = shard_map(
            chunk, mesh=mesh, in_specs=(specs,), out_specs=chunk_out,
            **sm_kw,
        )
        # Tooling hook (analysis/comms.py): the shard_map-wrapped wave
        # body, re-traceable on the GLOBAL carry shapes — the hash
        # engine's analog of the sort-merge engines' ``_wave_body_sm``,
        # so comms-lint prices this engine's scatter-routed all_to_all
        # path too. Never called by the run loop: no behavioral change.
        self._wave_body_sm = shard_map(
            body, mesh=mesh, in_specs=(specs,), out_specs=specs,
            **sm_kw,
        )
        return jax.jit(seed_sm), jax.jit(chunk_sm, donate_argnums=0)
