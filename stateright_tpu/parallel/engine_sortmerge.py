"""The sharded SORT-MERGE wave engine: multi-chip BFS on the fast path.

Round 2 shipped two engines pulling in opposite directions: the
single-chip sort-merge engine (checkers/tpu_sortmerge.py) — dedup via
``lax.sort`` merges, ~10x faster on chip than scatter-based open
addressing — and a sharded engine (parallel/engine.py) whose
owner-local dedup still used the hash-table scatters. This module
closes that gap (VERDICT r2 item 4): the scale-out path now runs the
same sorted-visited-array dedup the repo benchmarks and recommends.

Per wave, inside one ``shard_map``-wrapped ``lax.while_loop``:

1. each shard expands its local frontier block (vmap step → property
   bitmaps → fingerprints),
2. a 4-lane ``lax.sort`` keyed ``(owner, fp_hi, fp_lo)`` groups valid
   candidates by destination shard — routing and compaction in ONE
   sort, no per-destination scatters (the job_market.rs:66-147
   replacement, with the communication pattern chosen for ICI),
3. each destination's contiguous run is sliced into its fixed-size
   tile of the send buffer (``dynamic_slice`` at the run offset —
   contiguous copies, never scatters) and one ``lax.all_to_all`` swaps
   tiles so every candidate lands on the shard owning
   ``fp_lo % n_shards``,
4. owner-local dedup is the streaming sort-merge (round 10, shared
   with the single-chip engine): each shard's visited array is kept
   INCREMENTALLY SORTED, one B-scale sort orders the received
   candidates, membership + the visited append are O(V + B) streaming
   passes (``ops/merge.py`` — the Pallas kernel or the sort-free XLA
   fallback, per the inherited ``merge_impl``) — the role DashMap
   sharding plays in the reference BFS (bfs.rs:28-29) with zero
   cross-shard contention by construction, and no per-wave O(C)-row
   sort anywhere,
5. the parent forest is a per-shard append-only (child, parent) log
   written with ``dynamic_update_slice`` — no scatters — drained
   lazily on the host only when a counterexample path is
   reconstructed,
6. termination, counters, discovery folding, and overflow flags are
   ``psum``/``pmin`` reductions: every device agrees on ``done``
   without touching the host.

**Adaptive classes (round 4).** Round 3's sharded waves compiled ONE
worst-case shape, re-importing the flat-wave cost profile whose
single-chip version caused the round-2 rm=8 cliff. Waves now dispatch
through the same frontier/visited class ladders as the single-chip
engine — every shard agrees on the class via ``lax.pmax`` over local
frontier/unique counts (collectives are collective: the ``lax.switch``
must take the same branch on every shard) — and the routing sort,
per-destination tiles, the ``all_to_all`` itself, and the merge all
scale with the running wave. Encodings implementing
``SparseEncodedModel`` get sparse action dispatch here too: pairs are
extracted and stepped shard-locally (the shared pipeline in
checkers/tpu_sortmerge.py — including the round-6 WORD-NATIVE enabled
predicate: encodings providing ``enabled_bits_vec`` never materialize
a dense ``[F, K]`` bool on any shard, hand paxos/2pc and the compiled
actor encodings alike), and only real candidates enter the routing
sort and the shuffle. This engine's invocation style of that pipeline
— inside ``shard_map`` with ``axis_name="shard"``, which changes the
traced program (``lax.pvary`` carry plumbing) — is pinned separately
by the kernel lint's ``engine:sharded`` trace
(stateright_tpu/analysis/lint.py, ``pytest -m lint``).

On one device the shuffle degenerates to the identity and results are
state-identical to the single-chip engines; tests pin identical
results for shard counts 1/2/8 on the CPU mesh, with
``track_paths=True`` paths replaying through the host model.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..checker import CheckerBuilder
from ..encoding import EncodedModel
from ..model import Expectation
from ..ops.fingerprint import fingerprint_u32v
from ..ops.merge import compact_winners, member_sorted, merge_sorted
from ..ops.u64 import U64, u64_add
from ..checkers.tpu import expand_frontier, wave_hits
from ..checkers.tpu_sortmerge import SortMergeTpuBfsChecker

_SENT = 0xFFFFFFFF


def dest_tile_width(w: int, track_paths: bool) -> int:
    """Lanes of a routed destination tile (see dest_tile_pack)."""
    return (w + 3 if track_paths else w + 1) + 2


def dest_tile_pack(jnp, state, par_lo, par_hi, ebits, key_lo, key_hi):
    """THE sharded routed-tile lane layout: ``[state 0:W | par_lo W |
    par_hi W+1 (paths only) | ebits E-1 | key_lo E | key_hi E+1]``
    with ``E = W+3`` (paths) or ``W+1`` — every ``dest_block`` variant
    packs through this helper, and ``merge_stage`` unpacks by the same
    offsets (``recv[:, E]``/``recv[:, EB]``), so the tile layout can't
    drift between the three pack sites and the post-shuffle merge.
    NOT the single-chip payload layout: ``payload_pack``
    (checkers/tpu_sortmerge.py) orders key limbs before parent meta
    and is unpacked by ``payload_unpack`` at the merge fetch.

    Columns accept 1-D ``[B]`` or already-sliced 2-D ``[B, 1]``
    arrays; ``par_lo``/``par_hi`` are None when paths are off."""

    def col(x):
        return x if x.ndim == 2 else x[:, None]

    parts = [state]
    if par_lo is not None:
        parts += [col(par_lo), col(par_hi)]
    parts += [col(ebits), col(key_lo), col(key_hi)]
    return jnp.concatenate(parts, axis=1)


class ShardedSortMergeTpuBfsChecker(SortMergeTpuBfsChecker):
    """``CheckerBuilder.spawn_tpu_sharded_sortmerge()`` — the sort-merge
    wave engine over a ``jax.sharding.Mesh``. Inherits the result /
    reconstruction surface (including the clamped host fingerprints)
    from the single-chip sort-merge engine; the device programs and the
    parent-log layout differ. It also inherits both reduction
    soundness-certificate gates (analysis/soundness.py): the symmetry
    gate fires in the base ``TpuBfsChecker.__init__`` and the ample
    gate in the base ``_resolve_ample_words``, so a sharded run can
    only arm ``--symmetry``/``--ample-set`` against a certified spec."""

    _engine_name = "spawn_tpu_sharded_sortmerge"

    def __init__(
        self,
        builder: CheckerBuilder,
        encoded: Optional[EncodedModel] = None,
        mesh=None,
        n_shards: Optional[int] = None,
        capacity: int = 1 << 13,
        frontier_capacity: Optional[int] = None,
        track_paths: bool = True,
        waves_per_sync: int = 16,
        cand_capacity: Optional[int] = None,
        bucket_capacity: Optional[int] = None,
        **kwargs,
    ):
        import jax
        from jax.sharding import Mesh

        if mesh is None:
            devices = jax.devices()
            if n_shards is None:
                n_shards = len(devices)
            if n_shards > len(devices):
                raise ValueError(
                    f"n_shards={n_shards} > {len(devices)} available devices"
                )
            mesh = Mesh(np.array(devices[:n_shards]), ("shard",))
        if len(mesh.axis_names) != 1:
            raise ValueError(
                f"expected a 1-axis mesh, got axes {mesh.axis_names}"
            )
        self.mesh = mesh
        self.n_shards = int(mesh.devices.size)
        if cand_capacity == "auto":
            raise ValueError(
                'cand_capacity="auto" is single-chip only: the sharded '
                "engine's budgets are per shard and its overflow "
                "message/metrics differ — pass explicit capacities "
                "(the single-chip auto run's persisted budget is a "
                "good starting point)"
            )
        super().__init__(
            builder,
            encoded=encoded,
            capacity=capacity,
            frontier_capacity=frontier_capacity,
            track_paths=track_paths,
            waves_per_sync=waves_per_sync,
            cand_capacity=cand_capacity,
            **kwargs,
        )
        self.total_capacity = capacity * self.n_shards
        self.bucket_capacity = bucket_capacity
        #: live shard ids in ORIGINAL numbering (the degrade-and-
        #: continue layer: faultinject filters persistent shard
        #: faults against this, and a supervised degrade removes the
        #: dropped shard — checkers/tpu.py _degrade_shards).
        self._shard_ids = tuple(range(self.n_shards))

    def _cache_extras(self) -> tuple:
        # Includes the single-chip extras: the ladder/sparse/tile knobs
        # shape the compiled sharded program too.
        return (
            "sharded-sortmerge",
            self.n_shards,
            self.bucket_capacity,
            self.mesh,
        ) + super()._cache_extras()

    def _cand_overflow_message(self) -> str:
        return (
            "candidate/bucket overflow: a wave generated more successors "
            f"than fit the per-shard buffers (cand_capacity="
            f"{self.cand_capacity}, bucket_capacity={self.bucket_capacity});"
            " re-run with larger capacities — the max_wave_candidates "
            "metric reports the observed per-shard peak"
        )

    def _consume_extra_stats(self, extra: np.ndarray) -> None:
        if extra.size >= 3:
            self.metrics["shuffle_volume"] = int(extra[0]) | (
                int(extra[1]) << 32
            )
            self.metrics["max_wave_candidates"] = int(extra[2])

    def _wave_log_pairs_valid(self) -> bool:
        # The sharded GLOBAL log wrapper can't see the enabled-pair
        # popcount (it lives inside the per-shard wave switch): lane 1
        # is 0. The per-shard mesh log DOES see it (swave lane 1), so
        # the tracer back-fills the wave event from the shard sum
        # instead of recording enabled_pairs=null.
        return False

    def _plan_sharded_names(self) -> tuple:
        # Mirrors the shard_map out_specs in _build_programs: these
        # carry leaves shard along their row axis, so their ledger
        # rows report per_shard_bytes = bytes / n_shards.
        return ("vkeys", "plog", "pl_n", "frontier", "fval", "ebits",
                "n_loc", "u_loc", "slog", "swave")

    def _lane_config(self) -> dict:
        lane = super()._lane_config()
        lane.update(
            n_shards=self.n_shards,
            bucket_capacity=self.bucket_capacity,
            # routed-tile lanes: what telemetry.shard_balance prices
            # routed-byte volume with (rows x lanes x 4 B)
            dest_tile_lanes=dest_tile_width(
                self.encoded.width, self.track_paths
            ),
            # sorted arrays work to exactly 100%: shard_balance's
            # occupancy watch uses the exact-capacity headroom
            # threshold (stateright_tpu/occupancy.py)
            visited_exact=True,
        )
        return lane

    # -- tiered visited set (stateright_tpu/tier.py) -----------------------
    #
    # The shared takeover loop lives on the single-chip base class;
    # these hooks adapt it to the mesh layout: per-shard hot counts
    # (h_loc), per-shard pend lanes, NamedSharding placement for the
    # carry surgery (spill reset, handoff lanes), and the per-shard
    # keep-mask upload.

    def _tier_resident_counts(self, carry) -> np.ndarray:
        return np.asarray(
            carry["u_loc"]
        ).astype(np.int64).reshape(-1)

    def _tier_hot_lane(self) -> str:
        return "h_loc"

    def _tier_zero_hot(self):
        return np.zeros(self.n_shards, np.uint32)

    def _tier_hot_value(self, h_np):
        return np.asarray(h_np, np.uint32).reshape(-1)

    def _tier_zero_pl(self):
        return np.zeros(self.n_shards, np.uint32)

    def _tier_pend_zero(self):
        return np.zeros(self.n_shards, np.uint32)

    def _tier_place(self, name, arr):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        spec = (getattr(self, "_tier_pspecs", None) or {}).get(name)
        if spec is None:
            spec = P()
        return jnp.copy(jax.device_put(
            np.asarray(arr), NamedSharding(self.mesh, spec)
        ))

    def _tier_mask_dev(self, mask_np: np.ndarray):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        return jax.device_put(
            np.ascontiguousarray(mask_np.reshape(-1)),
            NamedSharding(self.mesh, P("shard")),
        )

    def _tier_shard_rows(self, shard_log):
        if shard_log is None:
            return None
        from ..telemetry import SHARD_LOG_LANES as SL

        return np.asarray(shard_log).reshape(self.n_shards, 1, SL)

    def _tier_extend_trace(self, ext) -> None:
        from ..telemetry import SHARD_LOG_LANES as SL

        S = self.n_shards
        ext["slog"] = self._tier_place(
            "slog", np.zeros((S, SL), np.uint32)
        )
        ext["swave"] = self._tier_place(
            "swave", np.zeros(S * SL, np.uint32)
        )

    # -- device programs ---------------------------------------------------

    def _build_programs(self, n0: int, tiered: bool = False):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map  # jax >= 0.8
        except ImportError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map

        from ..checkers.tpu import frontier_props_t
        from ..checkers.tpu_sortmerge import (
            _divisor_at_least,
            _ladder,
            sparse_pair_candidates,
        )
        from ..encoding import (
            has_trivial_boundary,
            pair_step_seam,
            within_boundary_cols,
        )
        from ..ops.fingerprint import fingerprint_u32v_t

        tier_mode = bool(tiered)
        enc = self.encoded
        # Device symmetry (ops/canonical.py, see the single-chip
        # engine): fingerprints fold the CANONICAL block, the resident
        # frontier keeps concrete states. The routing ownership below
        # (k_lo % S) then hashes the canonical key, so all members of
        # an orbit route to ONE shard and the per-shard dedup is a
        # global orbit dedup — no cross-shard coordination added.
        sym = self.sym_spec
        if sym is not None:
            from ..ops.canonical import canonicalize_rows, canonicalize_t
        ample_words = self._resolve_ample_words()
        props = list(self.model.properties())
        n_props = len(props)
        # XLA:CPU needs a gather-arrangement workaround in the tile
        # build (see dest_block below).
        cpu_backend = jax.default_backend() == "cpu"
        evt_idx = [
            i for i, p in enumerate(props)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if evt_idx and max(evt_idx) >= 32:
            raise ValueError(
                "the TPU engine supports eventually properties only at "
                "property indices < 32; reorder properties() so eventually "
                f"properties come first (got index {max(evt_idx)})"
            )
        K, W, F = enc.max_actions, enc.width, self.frontier_capacity
        S = self.n_shards
        C = self.capacity
        B_user = min(self.cand_capacity or F * K, F * K)
        use_sparse = self._use_sparse()
        EV = self._pair_width() if use_sparse else 0
        sparse_has_trunc = sparse_boundary = False
        if use_sparse:
            sparse_has_trunc = isinstance(
                jax.eval_shape(
                    enc.step_slot_vec,
                    jax.ShapeDtypeStruct((W,), jnp.uint32),
                    jax.ShapeDtypeStruct((), jnp.uint32),
                ),
                tuple,
            )
            sparse_boundary = not has_trivial_boundary(enc)
            # Transposed pair step: [W, n] successor block out — the
            # shape the lane-major fingerprint fold consumes. The
            # input seam is the shared backend policy
            # (encoding.pair_step_seam, PERF.md §layout).
            step_cols, make_pair_states = pair_step_seam(
                enc, cpu_backend
            )
        if n0 > C:
            raise ValueError(
                f"per-shard capacity {C} < {n0} init states"
            )
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth
        waves_per_sync = self.waves_per_sync
        ebits_init = self._eventually_bits_init()
        track_paths = self.track_paths
        # Per-wave trace logs (telemetry.py). TWO of them since round
        # 11:
        # * the GLOBAL log (psum'd frontier rows, the replicated
        #   gen/new deltas), appended by a wrapper around the wave
        #   body — the inner wave/merge builders never see it, so the
        #   replicated row stays out of the shard-varying carry
        #   plumbing. The enabled-pair popcount is not visible at
        #   this level: lane 1 logs 0 (_wave_log_pairs_valid; the
        #   host back-fills the wave event from the shard log's sum);
        # * the PER-SHARD mesh log (SHARD_LOG_FIELDS) — NOT
        #   psum-collapsed: each shard's wave row (local frontier/
        #   pairs/candidates, routed and received rows, dest-tile
        #   fill vs the lossless Bd cap, local post-dedup new, local
        #   visited count) is assembled INSIDE the wave switch (where
        #   those quantities exist) as the ``swave`` carry lane, and
        #   the body wrapper appends it to ``slog``. Both logs ride
        #   the chunk carry and download at the existing per-chunk
        #   sync (slog as a second, shard-sharded stats output — same
        #   dispatch, same blocking point, no extra round trip).
        # Gated on an active tracer and cache-keyed (_cache_extras),
        # so untraced programs compile exactly as before.
        from ..telemetry import SHARD_LOG_LANES as SL
        from ..telemetry import WAVE_LOG_LANES as WL

        trace_log = self._wave_log_enabled()

        # Class ladders, agreed across shards per wave via lax.pmax
        # (collectives are collective: every shard must take the same
        # lax.switch branch or the all_to_all deadlocks).
        f_ladder = _ladder(self.f_min, F, self.ladder_step)
        v_ladder = _ladder(self.v_min, C, self.v_ladder_step)

        def class_params(fc: int):
            """Static per-frontier-class shapes (per shard)."""
            F_c = f_ladder[fc]
            if use_sparse:
                NPg = F_c * EV
                B_c = min(B_user, NPg)
                compaction = NPg > B_c
                want = -(-NPg // self.tile_rows)
                if compaction:
                    # Keep the packed-append headroom ≤ B_c/4 (see
                    # the single-chip engine's make_sparse_wave).
                    want = max(want, -(-(4 * NPg) // max(B_c, 1)))
                NT = _divisor_at_least(F_c, want) if compaction else 1
                T = F_c // NT
                R_src = (B_c + T * EV) if compaction else NPg
            else:
                NT = T = 1
                R_src = F_c * K
                B_c = min(B_user, R_src)
            if self.bucket_capacity is not None:
                Bd_c = min(self.bucket_capacity, B_c)
            elif S == 1:
                Bd_c = B_c
            else:
                # Near-uniform fingerprint split: 4x the expected share.
                Bd_c = min(B_c, max(128, (4 * B_c + S - 1) // S))
            return F_c, NT, T, R_src, B_c, Bd_c

        # Per-shard parent-log rows: every unique state a shard owns
        # (<= C) gets one entry; the append block is F rows (the
        # next-frontier width).
        L = C + F if track_paths else 0
        # Payload lanes: state + (parent fp) + ebits + own fp (owners
        # don't re-hash after the shuffle). All-zero fp lanes mark
        # unused bucket slots (fingerprints are never 0).
        # Routed-tile lane offsets, tied to dest_tile_pack's layout:
        # key limbs at [E, E+1], ebits at EB.
        E = dest_tile_width(W, track_paths) - 2
        EB = E - 1
        mesh = self.mesh

        def bool_any(x):
            return lax.psum(x.astype(jnp.uint32), "shard") > 0

        def clamp_keys(lo, hi):
            both = (lo == jnp.uint32(_SENT)) & (hi == jnp.uint32(_SENT))
            return lo, jnp.where(both, jnp.uint32(_SENT - 1), hi)

        # INCREMENTALLY SORTED per-shard visited arrays (round 10,
        # see the C_pad notes in checkers/tpu_sortmerge.py): rows
        # [0, u_loc) are a dense sorted run; each wave linear-merges
        # the shard's winner keys into the prefix. F rows of headroom
        # cover the [0, V_v + NF) merged-block write at V_v == C.
        C_pad = C + F

        def seed_local(init_rows):
            # Host upload boundary: rows arrive row-major and
            # transpose ONCE into the [W, F] resident layout
            # (PERF.md §layout).
            me = lax.axis_index("shard").astype(jnp.uint32)
            # canonical keys from wave zero (ownership included)
            fp_rows = (canonicalize_rows(sym, init_rows, jnp)
                       if sym is not None else init_rows)
            lo0, hi0 = fingerprint_u32v(fp_rows, jnp)
            lo0, hi0 = clamp_keys(lo0, hi0)
            mine = (lo0 % jnp.uint32(S)) == me
            pos = jnp.cumsum(mine) - 1
            sp = jnp.where(mine, pos, F)
            frontier = jnp.zeros((W, F), dtype=jnp.uint32).at[
                :, sp
            ].set(init_rows.T, mode="drop")
            n_mine = jnp.sum(mine).astype(jnp.uint32)
            fval = jnp.arange(F) < n_mine
            ebits = jnp.where(fval, jnp.uint32(ebits_init), jnp.uint32(0))
            # Compact this shard's init keys to a dense SORTED prefix
            # (the round-10 invariant: rows [0, u_loc) are a sorted
            # run) — validity bit leads the key so dropped rows sort
            # last, then (hi, lo) orders the kept prefix.
            mk = jnp.where(mine, jnp.uint32(0), jnp.uint32(1))
            _, sk_hi, sk_lo = lax.sort((mk, hi0, lo0), num_keys=3)
            live_pref = n_mine > jnp.arange(
                sk_lo.shape[0], dtype=jnp.uint32
            )
            sk_lo = jnp.where(live_pref, sk_lo, jnp.uint32(_SENT))
            sk_hi = jnp.where(live_pref, sk_hi, jnp.uint32(_SENT))
            pad = C_pad - sk_lo.shape[0]
            vkeys = jnp.stack([
                jnp.concatenate(
                    [sk_lo, jnp.full(pad, _SENT, jnp.uint32)]
                ),
                jnp.concatenate(
                    [sk_hi, jnp.full(pad, _SENT, jnp.uint32)]
                ),
            ])
            return dict(
                **(
                    dict(
                        wlog=jnp.zeros((waves_per_sync, WL),
                                       jnp.uint32),
                        slog=jnp.zeros((waves_per_sync, SL),
                                       jnp.uint32),
                        swave=jnp.zeros(SL, jnp.uint32),
                    )
                    if trace_log else {}
                ),
                vkeys=vkeys,
                plog=jnp.zeros((4, L), jnp.uint32),
                pl_n=jnp.zeros(1, jnp.uint32),
                frontier=frontier,
                fval=fval,
                ebits=ebits,
                n_loc=n_mine.reshape(1),
                u_loc=n_mine.reshape(1),
                depth=jnp.int32(1),
                wchunk=jnp.int32(0),
                waves=jnp.uint32(0),
                gen_lo=jnp.uint32(n0),
                gen_hi=jnp.uint32(0),
                new=jnp.uint32(n0),
                sent_lo=jnp.uint32(0),
                sent_hi=jnp.uint32(0),
                max_cand=jnp.uint32(0),
                disc_found=jnp.zeros(n_props, dtype=bool),
                disc_lo=jnp.zeros(n_props, dtype=jnp.uint32),
                disc_hi=jnp.zeros(n_props, dtype=jnp.uint32),
                overflow=jnp.bool_(n0 > C),
                f_overflow=jnp.bool_(False),
                c_overflow=jnp.bool_(False),
                e_overflow=jnp.bool_(False),
                done=jnp.bool_(n0 == 0),
            )

        def merge_stage(c, v_class, R_c, recv, n_cand, sent, disc, ovf,
                        shard_log=None):
            """Owner-local streaming-merge dedup (the DashMap-shard
            role, bfs.rs:28-29, on the TPU-fast path), round 10: the
            shard's visited array is incrementally sorted, so dedup is
            ONE R_c-row candidate order sort (B-scale; the old
            ``(V_v + R_c)``-row concat sort is gone) + a streaming
            membership pass, and the visited append is a linear merge
            of the ≤F winner keys (``ops/merge.py``, the inherited
            ``merge_impl``). Intra-wave duplicates resolve on the
            adjacent-equal check of the sorted candidates (stable
            sort ⇒ lowest received position wins — the old
            stable-concat winner).

            Class-collapsed (round 9, PERF.md §layout): the v-ladder
            switches' branch outputs stay small/single-buffer — the
            membership switch returns ``bool[R_c]``, the append
            switch returns ``vkeys`` alone — and the winner gather,
            frontier/ebits/plog writes, and carry assembly happen
            ONCE at wave level. Collectives (psum/pmax) stay out of
            the branches: every shard takes the same branch (the
            classes are pmax-agreed), but uniform collectives outside
            the switch are the simpler contract.

            ``shard_log`` (traced runs only) is the wave's
            ``(enabled_pairs, routed_rows, dest_fill_peak, dest_cap)``
            per-shard scalars from the routing stage; this stage adds
            the quantities it owns (received rows, post-dedup new,
            the visited total) and returns the assembled
            ``swave: uint32[SHARD_LOG_LANES]`` row in the carry — 36
            bytes of extra switch output, priced by the lint's
            sharded wave-body fixture (analysis/tables.py)."""
            disc_found, disc_lo, disc_hi = disc
            overflow0, f_overflow0, c_overflow, e_overflow = ovf

            r_lo = recv[:, E]
            r_hi = recv[:, E + 1]
            r_val = (r_lo != 0) | (r_hi != 0)
            ck_lo = jnp.where(r_val, r_lo, jnp.uint32(_SENT))
            ck_hi = jnp.where(r_val, r_hi, jnp.uint32(_SENT))

            NFs = min(F, R_c)
            cpos = jnp.arange(1, R_c + 1, dtype=jnp.uint32)
            s_hi, s_lo, s_pos = lax.sort(
                (ck_hi, ck_lo, cpos), num_keys=2
            )
            real = ~(
                (s_hi == jnp.uint32(_SENT))
                & (s_lo == jnp.uint32(_SENT))
            )
            prev_same = jnp.concatenate(
                [
                    jnp.zeros(1, bool),
                    (s_hi[1:] == s_hi[:-1])
                    & (s_lo[1:] == s_lo[:-1]),
                ]
            )
            fresh = real & ~prev_same

            def member_core(vc):
                V_v = v_ladder[vc]

                def br(_):
                    return member_sorted(
                        c["vkeys"][0, :V_v], c["vkeys"][1, :V_v],
                        s_lo, s_hi, impl=self.merge_impl,
                    )

                return br

            in_visited = lax.switch(
                v_class,
                [member_core(vc) for vc in range(len(v_ladder))],
                0,
            )
            is_new = fresh & ~in_visited
            new_count = jnp.sum(is_new)
            # Order-preserving winner compaction (ops/merge.py,
            # impl-adaptive: O(R_c) rank scatter on the XLA fallback,
            # one 4-lane R_c-scale sort on the Pallas/TPU path):
            # winners lead in KEY order, the order the routed-tile
            # gather, plog append, and visited merge all share.
            nf_pos, w_lo, w_hi = compact_winners(
                is_new, s_pos, s_lo, s_hi, NFs, impl=self.merge_impl
            )
            if R_c < F:
                nf_pos = jnp.concatenate(
                    [nf_pos, jnp.full(F - R_c, _SENT, jnp.uint32)]
                )

            if tier_mode:
                # the commit phase (next dispatch) owns the per-shard
                # capacity check against the HOT count
                overflow = overflow0
            else:
                overflow = overflow0 | bool_any(
                    c["u_loc"][0] + new_count.astype(jnp.uint32)
                    > jnp.uint32(C)
                )
            nf_valid = jnp.arange(F) < new_count
            f_overflow = f_overflow0 | bool_any(new_count > F)
            nf_row = jnp.where(nf_valid, nf_pos - 1, jnp.uint32(0))
            next_fe = recv[nf_row]
            # The winners come off the routed-tile row gather; one
            # seam transpose feeds the [W, F] resident frontier.
            next_frontier = jnp.where(
                nf_valid[:, None], next_fe[:, :W], jnp.uint32(0)
            ).T
            next_ebits = jnp.where(nf_valid, next_fe[:, EB], 0)

            # Visited append (sorted invariant): linear-merge the
            # sorted winner block into the shard's sorted prefix and
            # write it back as one class-local block at offset 0
            # (rows past V_v + NFs stay sentinel by the C_pad
            # headroom). vkeys is the branch's only output.
            def append_core(vc):
                V_v = v_ladder[vc]

                def br(_):
                    m_lo, m_hi = merge_sorted(
                        c["vkeys"][0, :V_v], c["vkeys"][1, :V_v],
                        w_lo, w_hi, impl=self.merge_impl,
                    )
                    return lax.dynamic_update_slice(
                        c["vkeys"],
                        jnp.stack([m_lo, m_hi]),
                        (jnp.uint32(0), jnp.uint32(0)),
                    )

                return br

            if tier_mode:
                vkeys_new = c["vkeys"]  # the commit phase merges
            else:
                vkeys_new = lax.switch(
                    v_class,
                    [append_core(vc) for vc in range(len(v_ladder))],
                    0,
                )

            pend_extra = {}
            if tier_mode and track_paths:
                # stage the parent limbs for the commit's append —
                # no false-new row ever reaches the parent-log drain
                plog_new = c["plog"]
                pl_n = c["pl_n"]
                pend_extra = dict(
                    pend_par=jnp.stack([
                        jnp.where(nf_valid, next_fe[:, W], 0),
                        jnp.where(nf_valid, next_fe[:, W + 1], 0),
                    ])
                )
            elif track_paths:
                # Parent AND child limbs (round 10): the sorted merge
                # re-orders vkeys rows every wave, so the round-9
                # positional child derivation is gone — the log is
                # the insertion-order record again (_build_generated).
                plog_new = lax.dynamic_update_slice(
                    c["plog"],
                    jnp.stack([
                        jnp.where(nf_valid, next_fe[:, W], 0),
                        jnp.where(nf_valid, next_fe[:, W + 1], 0),
                        jnp.where(nf_valid, next_fe[:, E], 0),
                        jnp.where(nf_valid, next_fe[:, E + 1], 0),
                    ]),
                    (jnp.uint32(0), c["pl_n"][0]),
                )
                # Clamp to the F rows the block write actually wrote
                # (on an f_overflow wave new_count can exceed F; _run
                # raises before reconstruction, but the live-count
                # invariant should hold regardless).
                pl_n = c["pl_n"] + jnp.minimum(
                    new_count.astype(jnp.uint32), jnp.uint32(F)
                )
            else:
                plog_new = c["plog"]
                pl_n = c["pl_n"]

            g_new = lax.psum(new_count.astype(jnp.uint32), "shard")
            g_cand = lax.psum(n_cand, "shard")
            g = u64_add(
                U64(c["gen_lo"], c["gen_hi"]),
                U64(g_cand, jnp.uint32(0)),
            )
            new = c["new"] + g_new
            max_cand = jnp.maximum(
                c["max_cand"], lax.pmax(n_cand, "shard")
            )

            if tier_mode:
                # DEFERRED COMMIT (stateright_tpu/tier.py): stage the
                # shard's provisional winners and leave vkeys, the
                # parent log, and every committed counter untouched —
                # the next dispatch's commit phase folds in the host's
                # per-shard cold-membership verdict. The staged key
                # block keeps compact_winners' (hi, lo) order with a
                # sentinel tail, exactly what the commit merge wants.
                nc_u32 = new_count.astype(jnp.uint32)
                pk_lo = lax.dynamic_update_slice(
                    jnp.full(F, _SENT, jnp.uint32), w_lo[:NFs], (0,)
                )
                pk_hi = lax.dynamic_update_slice(
                    jnp.full(F, _SENT, jnp.uint32), w_hi[:NFs], (0,)
                )
                trace_extra = {}
                if shard_log is not None:
                    wv_pairs, cross_rows, fill_peak, dest_cap = \
                        shard_log
                    # provisional lanes 7/8 — the commit patches them
                    # with the confirmed count before the slog write
                    trace_extra = dict(
                        swave=jnp.stack(
                            [
                                c["n_loc"][0],
                                wv_pairs.astype(jnp.uint32),
                                n_cand.astype(jnp.uint32),
                                cross_rows.astype(jnp.uint32),
                                jnp.sum(r_val, dtype=jnp.uint32),
                                fill_peak.astype(jnp.uint32),
                                dest_cap,
                                nc_u32,
                                c["u_loc"][0] + nc_u32,
                            ]
                        )
                    )
                return dict(
                    **trace_extra,
                    **pend_extra,
                    vkeys=vkeys_new,
                    plog=plog_new,
                    pl_n=pl_n,
                    frontier=next_frontier,
                    fval=nf_valid,
                    ebits=next_ebits,
                    n_loc=nc_u32.reshape(1),
                    u_loc=c["u_loc"],
                    h_loc=c["h_loc"],
                    pend_keys=jnp.stack([pk_lo, pk_hi]),
                    pend_n=nc_u32.reshape(1),
                    pend_valid=jnp.bool_(True),
                    depth=c["depth"],
                    wchunk=c["wchunk"] + 1,
                    waves=c["waves"],
                    gen_lo=g.lo,
                    gen_hi=g.hi,
                    new=c["new"],
                    sent_lo=sent.lo,
                    sent_hi=sent.hi,
                    max_cand=max_cand,
                    disc_found=disc_found,
                    disc_lo=disc_lo,
                    disc_hi=disc_hi,
                    overflow=overflow,
                    f_overflow=f_overflow,
                    c_overflow=c_overflow,
                    e_overflow=e_overflow,
                    done=c["done"],
                )

            all_disc = (
                jnp.all(disc_found) if n_props else jnp.bool_(False)
            )
            if target_states is None:
                target_hit = jnp.bool_(False)
            else:
                target_hit = new >= jnp.uint32(target_states)
            cont = (
                (g_new > 0)
                & ~all_disc
                & ~target_hit
                & ~overflow
                & ~f_overflow
                & ~c_overflow
                & ~e_overflow
            )
            nc_u32 = new_count.astype(jnp.uint32)
            trace_extra = {}
            if shard_log is not None:
                # The per-shard mesh wave row (SHARD_LOG_FIELDS),
                # assembled where the local quantities exist — lanes
                # 0-1 close the sharded enabled_pairs=null hole.
                wv_pairs, cross_rows, fill_peak, dest_cap = shard_log
                trace_extra = dict(
                    swave=jnp.stack(
                        [
                            c["n_loc"][0],
                            wv_pairs.astype(jnp.uint32),
                            n_cand.astype(jnp.uint32),
                            cross_rows.astype(jnp.uint32),
                            jnp.sum(r_val, dtype=jnp.uint32),
                            fill_peak.astype(jnp.uint32),
                            dest_cap,
                            nc_u32,
                            c["u_loc"][0] + nc_u32,
                        ]
                    )
                )
            return dict(
                **trace_extra,
                vkeys=vkeys_new,
                plog=plog_new,
                pl_n=pl_n,
                frontier=next_frontier,
                fval=nf_valid & cont,
                ebits=next_ebits,
                n_loc=jnp.where(
                    cont, nc_u32, jnp.uint32(0)
                ).reshape(1),
                u_loc=c["u_loc"] + nc_u32,
                depth=jnp.where(cont, c["depth"] + 1, c["depth"]),
                wchunk=c["wchunk"] + 1,
                waves=c["waves"] + 1,
                gen_lo=g.lo,
                gen_hi=g.hi,
                new=new,
                sent_lo=sent.lo,
                sent_hi=sent.hi,
                max_cand=max_cand,
                disc_found=disc_found,
                disc_lo=disc_lo,
                disc_hi=disc_hi,
                overflow=overflow,
                f_overflow=f_overflow,
                c_overflow=c_overflow,
                e_overflow=e_overflow,
                done=~cont,
            )

        def make_wave(fc: int, v_class):
            F_c, NT, T, R_src, B_c, Bd_c = class_params(fc)
            R_c = S * Bd_c

            def wave(c):
                frontier_t = c["frontier"][:, :F_c]
                fval_c = c["fval"][:F_c]
                ebits_c = c["ebits"][:F_c]
                me = lax.axis_index("shard").astype(jnp.uint32)

                if target_depth is None:
                    expand = jnp.bool_(True)
                else:
                    expand = c["depth"] < target_depth

                e_overflow = c["e_overflow"]
                c_overflow = c["c_overflow"]

                if use_sparse:
                    # Sparse action dispatch, shard-local: the shared
                    # pair pipeline (checkers/tpu_sortmerge.py), then
                    # per-pair transitions — only real candidates
                    # enter the routing sort and the shuffle.
                    cond, eb, fp_lo, fp_hi = frontier_props_t(
                        enc, props, evt_idx, frontier_t, fval_c,
                        ebits_c, sym_spec=sym,
                    )
                    (
                        pidx, live, pslot, cnt, n_pairs, pair_ovf, _tm,
                    ) = sparse_pair_candidates(
                        # full resident buffer + explicit class width
                        # (a strided column-prefix slice as a loop
                        # operand would copy per wave — see the
                        # n_rows note on the shared pipeline)
                        enc, c["frontier"], fval_c, expand,
                        EV=EV, B_p=B_c, NT=NT, T=T,
                        mask_budget_cells=self.mask_budget_cells,
                        Ba=R_src, axis_name="shard", n_rows=F_c,
                        ample_words=ample_words,
                    )
                    # Pair-state gather seam: the shared backend
                    # policy (encoding.pair_step_seam).
                    pair_states = make_pair_states(c["frontier"],
                                                   frontier_t)
                    c_overflow = c_overflow | bool_any(pair_ovf)
                    prow = pidx // jnp.uint32(EV)
                    needs_scan = sparse_boundary or sparse_has_trunc

                    def eval_pairs(pidx_b, live_b, slot_b):
                        prow_b = pidx_b // jnp.uint32(EV)
                        succ_t, ptr_b, hard_b = step_cols(
                            pair_states(prow_b), slot_b
                        )
                        # hard trunc (unrepresentable successor, e.g.
                        # an un-harvested history transition) is raised
                        # regardless of the boundary — the garbage
                        # successor can't faithfully evaluate it.
                        eov = jnp.bool_(False)
                        if hard_b is not None:
                            eov = jnp.any(live_b & hard_b)
                            live_b = live_b & ~hard_b
                        if sparse_boundary:
                            inb = within_boundary_cols(enc, succ_t)
                            ok = live_b & inb
                        else:
                            ok = live_b
                        if ptr_b is not None:
                            eov = eov | jnp.any(ok & ptr_b)
                            ok = ok & ~ptr_b
                        if sym is not None:
                            # canonical key, concrete successor block
                            canon_t = canonicalize_t(sym, succ_t, jnp)
                            lo, hi = fingerprint_u32v_t(canon_t, jnp)
                        else:
                            lo, hi = fingerprint_u32v_t(succ_t, jnp)
                        lo, hi = clamp_keys(lo, hi)
                        return succ_t, lo, hi, ok, prow_b, eov

                    # Memory-lean mode (mirrors the single-chip chunked
                    # path): when the [R_src, W] successor tensor would
                    # blow the flat budget, fingerprint pairs in chunks
                    # and RECOMPUTE the routed tiles' successors inside
                    # dest_tile (step_slot purity makes this exact).
                    row_pad = -(-W // 128) * 512
                    chunked = (
                        R_src * row_pad > self.flat_budget_bytes
                    )
                    if chunked:
                        NC = -(-(R_src * row_pad)
                               // self.flat_budget_bytes)
                        Bc = -(-R_src // NC)
                        pad = NC * Bc - R_src
                        pidx_p = jnp.pad(pidx, (0, pad))
                        live_p = jnp.pad(live, (0, pad))
                        pslot_p = jnp.pad(pslot, (0, pad))

                        def fchunk(ti, acc):
                            kl, kh, pok, nc, eov, rok = acc
                            off = ti * Bc
                            pb = lax.dynamic_slice(pidx_p, (off,), (Bc,))
                            lb = lax.dynamic_slice(live_p, (off,), (Bc,))
                            sb = lax.dynamic_slice(
                                pslot_p, (off,), (Bc,)
                            )
                            _, lo, hi, ok, prow_b, ev = eval_pairs(
                                pb, lb, sb
                            )
                            kl = lax.dynamic_update_slice(kl, lo, (off,))
                            kh = lax.dynamic_update_slice(kh, hi, (off,))
                            pok = lax.dynamic_update_slice(
                                pok, ok, (off,)
                            )
                            if needs_scan:
                                nc = nc + jnp.sum(ok, dtype=jnp.uint32)
                                rok = rok.at[
                                    jnp.where(
                                        ok, prow_b, jnp.uint32(F_c)
                                    )
                                ].max(jnp.uint32(1), mode="drop")
                            return kl, kh, pok, nc, eov | ev, rok

                        def pv(x):
                            # Older jax: no pvary, no unvarying carry
                            # typing — identity.
                            if not hasattr(lax, "pvary"):
                                return x
                            return lax.pvary(x, "shard")

                        kl, kh, pok, nc_acc, eov_acc, row_ok = (
                            lax.fori_loop(
                                0,
                                NC,
                                fchunk,
                                (
                                    pv(jnp.zeros(NC * Bc, jnp.uint32)),
                                    pv(jnp.zeros(NC * Bc, jnp.uint32)),
                                    pv(jnp.zeros(NC * Bc, bool)),
                                    pv(jnp.uint32(0)),
                                    pv(jnp.bool_(False)),
                                    pv(jnp.zeros(
                                        F_c if needs_scan else 1,
                                        jnp.uint32,
                                    )),
                                ),
                            )
                        )
                        k_lo = kl[:R_src]
                        k_hi = kh[:R_src]
                        pair_ok = pok[:R_src]
                        e_overflow = e_overflow | bool_any(eov_acc)
                        if needs_scan:
                            has_succ = row_ok != 0
                            n_cand = nc_acc
                        else:
                            has_succ = cnt > 0
                            n_cand = n_pairs
                        cand_state = None  # recomputed per dest_tile
                    else:
                        (succ_t, k_lo, k_hi, pair_ok, _,
                         eov) = eval_pairs(pidx, live, pslot)
                        e_overflow = e_overflow | bool_any(eov)
                        if needs_scan:
                            row_ok = jnp.zeros(F_c, jnp.uint32).at[
                                jnp.where(
                                    pair_ok, prow, jnp.uint32(F_c)
                                )
                            ].max(jnp.uint32(1), mode="drop")
                            has_succ = row_ok != 0
                            n_cand = jnp.sum(pair_ok, dtype=jnp.uint32)
                        else:
                            has_succ = cnt > 0
                            n_cand = n_pairs
                        # Routed-tile staging is a gather seam: the
                        # successor block transposes back to rows once
                        # (PERF.md §layout — row-major gathers win).
                        cand_state = succ_t.T
                    terminal = fval_c & ~has_succ & expand
                    evt_cex = terminal & (eb != 0)
                    ex = dict(
                        cond=cond, ebits=eb, evt_cex=evt_cex,
                        f_lo=fp_lo, f_hi=fp_hi,
                    )
                    cand_valid = pair_ok
                    cand_par = prow
                else:
                    # Dense expansion: one seam transpose of the class
                    # prefix (step_vec is the row contract).
                    frontier_rows = frontier_t.T
                    ex = expand_frontier(
                        enc, props, evt_idx, frontier_rows, fval_c,
                        ebits_c, expand, with_repeats=False,
                        sym_spec=sym,
                    )
                    e_overflow = e_overflow | bool_any(
                        jnp.any(ex["trunc"])
                    )
                    cand_state, cand_valid = ex["flat"], ex["v"]
                    n_cand = jnp.sum(cand_valid).astype(jnp.uint32)
                    fp_flat = (canonicalize_rows(sym, cand_state, jnp)
                               if sym is not None else cand_state)
                    k_lo, k_hi = fingerprint_u32v(fp_flat, jnp)
                    k_lo, k_hi = clamp_keys(k_lo, k_hi)
                    cand_par = None  # parent row = candidate // K

                # Discoveries: local per-wave hits, globally folded
                # (the lowest hitting shard index wins, mirroring
                # whichever racing thread lands first in the
                # reference).
                if n_props:
                    hits, los, his = wave_hits(props, ex, fval_c)
                    ghits = bool_any(hits)
                    pri = jnp.where(hits, me, jnp.uint32(S))
                    winner = lax.pmin(pri, "shard")
                    sel = hits & (pri == winner)
                    g_lo = lax.psum(
                        jnp.where(sel, los, jnp.uint32(0)), "shard"
                    )
                    g_hi = lax.psum(
                        jnp.where(sel, his, jnp.uint32(0)), "shard"
                    )
                    fresh = ghits & ~c["disc_found"]
                    disc_found = c["disc_found"] | ghits
                    disc_lo = jnp.where(fresh, g_lo, c["disc_lo"])
                    disc_hi = jnp.where(fresh, g_hi, c["disc_hi"])
                else:
                    disc_found = c["disc_found"]
                    disc_lo = c["disc_lo"]
                    disc_hi = c["disc_hi"]

                owner = jnp.where(
                    cand_valid, k_lo % jnp.uint32(S), jnp.uint32(S)
                )

                # Route+compact in ONE sort: order by (owner, key);
                # valid candidates form S contiguous destination runs
                # (invalid rows carry owner=S and sort last).
                rows = jnp.arange(R_src, dtype=jnp.uint32)
                s_owner, s_hi, s_lo, s_row = lax.sort(
                    (owner, k_hi, k_lo, rows), num_keys=3
                )
                # s_owner is sorted: all destination-run boundaries in
                # one searchsorted pass.
                edges = jnp.searchsorted(
                    s_owner, jnp.arange(S + 1, dtype=jnp.uint32)
                ).astype(jnp.uint32)
                starts = edges[:-1]
                counts = edges[1:] - starts
                route_ovf = jnp.any(counts > jnp.uint32(Bd_c))
                c_overflow = c_overflow | bool_any(route_ovf)

                # Build the send tiles from ONE routed payload gather +
                # per-destination SLICES (PERF.md §gathers: TPU gathers
                # cost ~12ns/row regardless of lane count, so the old
                # per-destination payload/fp/ebits/key gathers — ~6×
                # R_src rows per wave — collapse into a single
                # [R_src, E+2] multi-lane gather; slices are free).
                # Parent meta (ebits + parent fp) packs into the same
                # payload: broadcast for dense (candidate // K is a
                # K-fold repeat, no gather), one packed gather for
                # sparse. Buffers are padded by one tile so a
                # destination run ending at R_src slices without the
                # dynamic_slice start-clamp silently shifting live rows.
                fr_meta = jnp.stack(
                    [ex["ebits"]]
                    + ([ex["f_lo"], ex["f_hi"]] if track_paths else []),
                    axis=1,
                )
                if cand_par is None:
                    pmeta = jnp.broadcast_to(
                        fr_meta[:, None, :],
                        (F_c, K, fr_meta.shape[1]),
                    ).reshape(R_src, fr_meta.shape[1])
                else:
                    pmeta = fr_meta[cand_par]
                if cpu_backend:
                    # XLA:CPU workaround (round 5, mirrors the
                    # single-chip engine): gathering a CONCATENATED
                    # multi-lane payload livelocks the CPU thunk
                    # runtime inside the chunk while-loop with some
                    # encodings (observed with compiled actor
                    # encodings + paths). Same math, per-destination
                    # separate gathers.
                    def dest_block(start):
                        idx = jnp.clip(
                            start + jnp.arange(Bd_c, dtype=jnp.uint32),
                            0,
                            jnp.uint32(R_src - 1),
                        )
                        srow = s_row[idx]
                        if cand_par is None:
                            par = srow // jnp.uint32(K)
                        else:
                            par = cand_par[srow]
                        if cand_state is not None:
                            st = cand_state[srow]
                        else:
                            st_t, _, _ = step_cols(
                                pair_states(par), pslot[srow]
                            )
                            st = st_t.T
                        return dest_tile_pack(
                            jnp, st,
                            ex["f_lo"][par] if track_paths else None,
                            ex["f_hi"][par] if track_paths else None,
                            ex["ebits"][par], s_lo[idx], s_hi[idx],
                        )
                elif cand_state is not None:
                    cpay = dest_tile_pack(
                        jnp, cand_state,
                        pmeta[:, 1:2] if track_paths else None,
                        pmeta[:, 2:3] if track_paths else None,
                        pmeta[:, 0:1], k_lo, k_hi,
                    )
                    spay = jnp.pad(
                        cpay[s_row], ((0, Bd_c), (0, 0))
                    )

                    def dest_block(start):
                        return lax.dynamic_slice(
                            spay, (start, jnp.uint32(0)), (Bd_c, E + 2)
                        )
                else:
                    # Chunked sparse: successors are never materialized
                    # at [R_src, W]; recompute per destination from a
                    # packed (pair, slot, meta, key) gather.
                    mparts = [pidx[:, None], pslot[:, None], pmeta]
                    smeta = jnp.pad(
                        jnp.concatenate(mparts, axis=1)[s_row],
                        ((0, Bd_c), (0, 0)),
                    )
                    skeys = jnp.pad(
                        jnp.stack([s_lo, s_hi], axis=1),
                        ((0, Bd_c), (0, 0)),
                    )
                    NM = 2 + fr_meta.shape[1]

                    def dest_block(start):
                        z = jnp.uint32(0)
                        m = lax.dynamic_slice(
                            smeta, (start, z), (Bd_c, NM)
                        )
                        kk = lax.dynamic_slice(
                            skeys, (start, z), (Bd_c, 2)
                        )
                        par = m[:, 0] // jnp.uint32(EV)
                        succ_d_t, _, _ = step_cols(
                            pair_states(par), m[:, 1]
                        )
                        return dest_tile_pack(
                            jnp, succ_d_t.T,
                            m[:, 3:4] if track_paths else None,
                            m[:, 4:5] if track_paths else None,
                            m[:, 2:3], kk[:, 0:1], kk[:, 1:2],
                        )

                def dest_tile(d):
                    start = starts[d]
                    cnt_d = counts[d]
                    live_d = jnp.arange(Bd_c, dtype=jnp.uint32) < cnt_d
                    tile = dest_block(start)
                    return jnp.where(
                        live_d[:, None], tile, jnp.uint32(0)
                    )

                send = jnp.concatenate(
                    [dest_tile(d) for d in range(S)], axis=0
                )
                cross = n_cand - counts[me]
                g_cross = lax.psum(cross.astype(jnp.uint32), "shard")
                sent = u64_add(
                    U64(c["sent_lo"], c["sent_hi"]),
                    U64(g_cross, jnp.uint32(0)),
                )

                recv = lax.all_to_all(
                    send, "shard", split_axis=0, concat_axis=0,
                    tiled=True,
                )

                shard_log = None
                if trace_log:
                    # Routing-stage lanes of the per-shard log: the
                    # local enabled-pair popcount (the quantity the
                    # global log can't see; candidates on the dense
                    # path, mirroring the single-chip convention),
                    # rows routed off-shard, and the peak destination
                    # run vs this class's lossless tile cap.
                    shard_log = (
                        n_pairs if use_sparse else n_cand,
                        cross,
                        jnp.max(counts),
                        jnp.uint32(Bd_c),
                    )
                return merge_stage(
                    c, v_class, R_c, recv, n_cand, sent,
                    (disc_found, disc_lo, disc_hi),
                    (c["overflow"], c["f_overflow"],
                     c_overflow, e_overflow),
                    shard_log=shard_log,
                )

            return wave

        # Memory ledger (stateright_tpu/memplan.py): per-ladder-class
        # staging rows, PER SHARD (the shard_map body's view), from
        # the same class_params the wave programs compile from. The
        # chunked memory-lean gate mirrors make_wave's (R_src rows at
        # the padded ~512 B/row cost vs the flat budget); chunked
        # classes land an ``engine_mode`` record like the single-chip
        # engine's.
        from ..memplan import buffer_entry, plan_total

        from ..ops.bitmask import mask_words as _mask_words

        _row_pad = -(-W // 128) * 512
        _classes = []
        _modes = []
        for fc in range(len(f_ladder)):
            F_c, NT_c, _T_c, R_src, B_c, Bd_c = class_params(fc)
            staging = [
                buffer_entry("cand_keys", (2, R_src), "uint32"),
                buffer_entry("send_tiles", (S * Bd_c, E + 2),
                             "uint32"),
                buffer_entry("recv_tiles", (S * Bd_c, E + 2),
                             "uint32"),
            ]
            chunked_c = False
            if use_sparse:
                staging.insert(0, buffer_entry(
                    "enabled_bits", (F_c, _mask_words(K)), "uint32"
                ))
                staging.insert(1, buffer_entry(
                    "pair_index", (3, R_src), "uint32"
                ))
                chunked_c = R_src * _row_pad > self.flat_budget_bytes
                if chunked_c:
                    NC_c = -(-(R_src * _row_pad)
                             // self.flat_budget_bytes)
                    Bc_c = -(-R_src // NC_c)
                    staging.append(buffer_entry(
                        "succ_chunk", (W, Bc_c), "uint32"
                    ))
                    _modes.append(dict(
                        engine=type(self).__name__, mode="chunked",
                        f_class=fc, buffer_rows=R_src, chunks=NC_c,
                        chunk_rows=Bc_c, row_pad_bytes=_row_pad,
                        flat_budget_bytes=self.flat_budget_bytes,
                    ))
                else:
                    staging.append(buffer_entry(
                        "succ_t", (W, R_src), "uint32"
                    ))
            else:
                staging.insert(0, buffer_entry(
                    "succ_flat", (F_c * K, W), "uint32"
                ))
            _classes.append(dict(
                f_class=fc,
                mode=("chunked" if chunked_c
                      else "sparse" if use_sparse else "dense"),
                frontier_rows=F_c, budget_rows=B_c, tiles=NT_c,
                buffer_rows=R_src, dest_cap=Bd_c,
                staging=staging, staging_bytes=plan_total(staging),
            ))
        from ..memplan import v_class_entries

        _NFmax = min(F, max(c["buffer_rows"] for c in _classes))
        self._build_info = dict(
            classes=_classes,
            v_classes=v_class_entries(v_ladder, _NFmax),
            engine_modes=_modes,
        )

        def body(c):
            n_max = lax.pmax(c["n_loc"][0], "shard")
            # tiered runs dispatch the v-ladder on the HOT count (the
            # rows actually resident per shard), pmax-agreed like
            # every class decision
            u_max = lax.pmax(
                c["h_loc"][0] if tier_mode else c["u_loc"][0], "shard"
            )
            f_class = jnp.int32(0)
            for F_i in f_ladder[:-1]:
                f_class = f_class + (
                    n_max > jnp.uint32(F_i)
                ).astype(jnp.int32)
            v_class = jnp.int32(0)
            for V_i in v_ladder[:-1]:
                v_class = v_class + (
                    u_max > jnp.uint32(V_i)
                ).astype(jnp.int32)
            if trace_log:
                n_tot = lax.psum(c["n_loc"][0], "shard")
            ci = {k: v for k, v in c.items()
                  if k not in ("wlog", "slog", "pstash")}
            c2 = lax.switch(
                f_class,
                [make_wave(fc, v_class) for fc in range(len(f_ladder))],
                ci,
            )
            if trace_log and tier_mode:
                # the wave-log/shard-log rows can't be written yet —
                # the confirmed counts settle at the NEXT dispatch's
                # commit; stash the wave-time lanes for it (lane 1 is
                # 0 at the global level, as untiered: the tracer
                # back-fills enabled pairs from the shard rows)
                c2 = dict(
                    c2,
                    wlog=c["wlog"],
                    slog=c["slog"],
                    pstash=jnp.stack(
                        [
                            n_tot,
                            jnp.uint32(0),
                            c2["gen_lo"] - c["gen_lo"],
                            c["depth"].astype(jnp.uint32),
                            f_class.astype(jnp.uint32),
                            v_class.astype(jnp.uint32),
                            jnp.uint32(0),
                            jnp.uint32(0),
                        ]
                    ),
                )
                return c2
            if tier_mode:
                return c2
            if trace_log:
                # Every lane here is replicated (psum/pmax results and
                # the engine's replicated run counters), so the log
                # matches the stats' P() out-spec.
                row = jnp.stack(
                    [
                        n_tot,
                        jnp.uint32(0),  # enabled pairs: not visible
                        c2["gen_lo"] - c["gen_lo"],
                        c2["new"] - c["new"],
                        c2["new"],
                        c["depth"].astype(jnp.uint32),
                        f_class.astype(jnp.uint32),
                        v_class.astype(jnp.uint32),
                    ]
                )
                c2 = dict(
                    c2,
                    wlog=lax.dynamic_update_slice(
                        c["wlog"], row[None, :],
                        (c["wchunk"], jnp.int32(0)),
                    ),
                    # the per-shard row merge_stage assembled inside
                    # the wave switch (shard-varying, never psum'd)
                    slog=lax.dynamic_update_slice(
                        c["slog"], c2["swave"][None, :],
                        (c["wchunk"], jnp.int32(0)),
                    ),
                )
            return c2

        # Tiered dispatches run exactly ONE wave: the commit phase
        # needs the host's membership verdict between waves.
        wps_eff = 1 if tier_mode else waves_per_sync

        def cond(c):
            return ~c["done"] & (c["wchunk"] < wps_eff)

        def pack_stats(c):
            frontier_total = lax.psum(
                jnp.sum(c["fval"]).astype(jnp.uint32), "shard"
            )
            scalars = jnp.stack(
                [
                    c["done"].astype(jnp.uint32),
                    c["overflow"].astype(jnp.uint32),
                    c["f_overflow"].astype(jnp.uint32),
                    c["depth"].astype(jnp.uint32),
                    c["waves"],
                    frontier_total,
                    c["gen_lo"],
                    c["gen_hi"],
                    c["new"],
                    c["c_overflow"].astype(jnp.uint32),
                    c["e_overflow"].astype(jnp.uint32),
                ]
            )
            parts = [
                scalars,
                c["disc_found"].astype(jnp.uint32),
                c["disc_lo"],
                c["disc_hi"],
                jnp.stack(
                    [c["sent_lo"], c["sent_hi"], c["max_cand"]]
                ),
            ]
            if trace_log:
                parts.append(c["wlog"].reshape(-1))
            stats = jnp.concatenate(parts)
            if trace_log:
                # The per-shard mesh log returns as a SECOND stats
                # output, sharded along the device axis (the packed
                # stats stay replicated) — same dispatch, same sync.
                return c, stats, c["slog"].reshape(-1)
            return c, stats

        def chunk(carry):
            c = dict(carry, wchunk=jnp.int32(0))
            c = lax.while_loop(cond, body, c)
            return pack_stats(c)

        P_shard = P("shard")
        specs = dict(
            **(
                dict(wlog=P(), slog=P("shard", None), swave=P_shard)
                if trace_log else {}
            ),
            # SoA resident buffers shard along their ROW axis (axis 1
            # of the [lanes, rows] layout).
            vkeys=P(None, "shard"),
            plog=P(None, "shard"),
            pl_n=P_shard,
            frontier=P(None, "shard"),
            fval=P_shard,
            ebits=P_shard,
            n_loc=P_shard,
            u_loc=P_shard,
            depth=P(),
            wchunk=P(),
            waves=P(),
            gen_lo=P(),
            gen_hi=P(),
            new=P(),
            sent_lo=P(),
            sent_hi=P(),
            max_cand=P(),
            disc_found=P(),
            disc_lo=P(),
            disc_hi=P(),
            overflow=P(),
            f_overflow=P(),
            c_overflow=P(),
            e_overflow=P(),
            done=P(),
        )
        # Older jax (no lax.pvary) has no replication rule for
        # while_loop inside shard_map: disable the rep checker there
        # (its named workaround). Newer jax type-checks varying-ness
        # instead, which the pvary/pcast promotions satisfy.
        sm_kw = {} if hasattr(lax, "pvary") else {"check_rep": False}

        if tier_mode:
            # -- the tiered chunk program (stateright_tpu/tier.py) -------
            specs_t = dict(specs)
            specs_t["pend_keys"] = P(None, "shard")
            if track_paths:
                specs_t["pend_par"] = P(None, "shard")
            specs_t["pend_n"] = P_shard
            specs_t["pend_valid"] = P()
            specs_t["h_loc"] = P_shard
            if trace_log:
                specs_t["pstash"] = P()

            def tier_commit(c, keep):
                """Commit the previous wave's survivors, shard-local,
                under the host's per-shard ``keep`` mask — the mirror
                of the single-chip commit with the global verdicts
                (cont/done/new) psum-agreed like every other
                termination decision."""
                pv = c["pend_valid"]
                rowsF = jnp.arange(F, dtype=jnp.uint32)
                keepm = keep & (rowsF < c["pend_n"][0])
                conf = jnp.sum(keepm).astype(jnp.uint32)
                drop = jnp.where(keepm, jnp.uint32(0), jnp.uint32(1))
                _, perm = lax.sort((drop, rowsF), num_keys=1)
                confv = rowsF < conf
                front_c = jnp.where(
                    confv[None, :], c["frontier"][:, perm],
                    jnp.uint32(0),
                )
                eb_c = jnp.where(
                    confv, c["ebits"][perm], jnp.uint32(0)
                )
                k_lo = jnp.where(
                    confv, c["pend_keys"][0][perm], jnp.uint32(_SENT)
                )
                k_hi = jnp.where(
                    confv, c["pend_keys"][1][perm], jnp.uint32(_SENT)
                )

                h_max = lax.pmax(c["h_loc"][0], "shard")
                v_class = jnp.int32(0)
                for V_i in v_ladder[:-1]:
                    v_class = v_class + (
                        h_max > jnp.uint32(V_i)
                    ).astype(jnp.int32)

                def app(vc):
                    V_v = v_ladder[vc]

                    def br(_):
                        m_lo, m_hi = merge_sorted(
                            c["vkeys"][0, :V_v], c["vkeys"][1, :V_v],
                            k_lo, k_hi, impl=self.merge_impl,
                        )
                        return lax.dynamic_update_slice(
                            c["vkeys"],
                            jnp.stack([m_lo, m_hi]),
                            (jnp.uint32(0), jnp.uint32(0)),
                        )

                    return br

                vkeys_m = lax.switch(
                    v_class,
                    [app(vc) for vc in range(len(v_ladder))], 0,
                )

                def sel(a, b):
                    return jnp.where(pv, a, b)

                conf_g = lax.psum(conf, "shard")
                confp = jnp.where(pv, conf, jnp.uint32(0))
                confp_g = jnp.where(pv, conf_g, jnp.uint32(0))
                new2 = c["new"] + confp_g
                h_loc2 = c["h_loc"] + confp.reshape(1)
                u_loc2 = c["u_loc"] + confp.reshape(1)
                all_disc = (
                    jnp.all(c["disc_found"]) if n_props
                    else jnp.bool_(False)
                )
                if target_states is None:
                    target_hit = jnp.bool_(False)
                else:
                    target_hit = new2 >= jnp.uint32(target_states)
                overflow = c["overflow"] | (
                    pv & bool_any(h_loc2[0] > jnp.uint32(C))
                )
                cont = (
                    pv & (conf_g > 0) & ~all_disc & ~target_hit
                    & ~overflow & ~c["f_overflow"]
                    & ~c["c_overflow"] & ~c["e_overflow"]
                )
                out = dict(
                    c,
                    vkeys=sel(vkeys_m, c["vkeys"]),
                    frontier=sel(front_c, c["frontier"]),
                    ebits=sel(eb_c, c["ebits"]),
                    fval=sel(confv & cont, c["fval"]),
                    n_loc=sel(conf.reshape(1), c["n_loc"]),
                    h_loc=h_loc2,
                    u_loc=u_loc2,
                    new=new2,
                    depth=jnp.where(cont, c["depth"] + 1, c["depth"]),
                    waves=c["waves"] + jnp.where(
                        pv, jnp.uint32(1), jnp.uint32(0)
                    ),
                    overflow=overflow,
                    done=sel(~cont, c["done"]),
                    pend_valid=jnp.bool_(False),
                    pend_n=jnp.zeros(1, jnp.uint32),
                )
                if track_paths:
                    p_lo = jnp.where(
                        confv, c["pend_par"][0][perm], jnp.uint32(0)
                    )
                    p_hi = jnp.where(
                        confv, c["pend_par"][1][perm], jnp.uint32(0)
                    )
                    rows4 = jnp.stack([
                        p_lo,
                        p_hi,
                        jnp.where(confv, k_lo, jnp.uint32(0)),
                        jnp.where(confv, k_hi, jnp.uint32(0)),
                    ])
                    plog2 = lax.dynamic_update_slice(
                        c["plog"], rows4, (jnp.uint32(0), c["pl_n"][0])
                    )
                    out["plog"] = sel(plog2, c["plog"])
                    out["pl_n"] = c["pl_n"] + confp.reshape(1)
                if trace_log:
                    st = c["pstash"]
                    row = jnp.stack([
                        st[0], st[1], st[2], conf_g, new2,
                        st[3], st[4], st[5],
                    ])
                    out["wlog"] = lax.dynamic_update_slice(
                        c["wlog"], row[None, :],
                        (jnp.int32(0), jnp.int32(0)),
                    )
                    # patch the stashed per-shard row's confirmed
                    # lanes (7 = post-dedup new, 8 = cumulative
                    # per-shard visited) before the slog write
                    sw = jnp.concatenate([
                        c["swave"][:7],
                        jnp.stack([conf, u_loc2[0]]),
                    ])
                    out["slog"] = lax.dynamic_update_slice(
                        c["slog"], sw[None, :],
                        (jnp.int32(0), jnp.int32(0)),
                    )
                return out

            def tier_chunk(carry, keep):
                c = dict(carry, wchunk=jnp.int32(0))
                c = tier_commit(c, keep)
                c = lax.while_loop(cond, body, c)
                return pack_stats(c)

            self._tier_pspecs = dict(specs_t)
            chunk_out_t = (
                (specs_t, P(), P_shard) if trace_log
                else (specs_t, P())
            )
            tier_sm = shard_map(
                tier_chunk, mesh=mesh,
                in_specs=(specs_t, P_shard), out_specs=chunk_out_t,
                **sm_kw,
            )
            return jax.jit(tier_sm, donate_argnums=0)

        # Checkpoint/resume (stateright_tpu/checkpoint.py): a resumed
        # run places its snapshot buffers with these exact shardings —
        # kept beside the programs (rides the program cache via
        # _lookup_programs) so restore and carry layout can't drift.
        self._carry_pspecs = dict(specs)
        chunk_out = (
            (specs, P(), P_shard) if trace_log else (specs, P())
        )
        seed_sm = shard_map(
            seed_local, mesh=mesh, in_specs=P(), out_specs=specs,
            **sm_kw,
        )
        chunk_sm = shard_map(
            chunk, mesh=mesh, in_specs=(specs,), out_specs=chunk_out,
            **sm_kw,
        )
        # Tooling hook (analysis/lint.py): the shard_map-wrapped wave
        # body, re-traceable on the GLOBAL carry shapes — the sharded
        # analog of the single-chip engine's ``_wave_body`` (the lint's
        # sharded wave-body fixture prices the per-shard log path).
        self._wave_body_sm = shard_map(
            body, mesh=mesh, in_specs=(specs,), out_specs=specs,
            **sm_kw,
        )
        return jax.jit(seed_sm), jax.jit(chunk_sm, donate_argnums=0)

    # -- reconstruction ----------------------------------------------------

    def _capture_final(self, carry) -> None:
        self._final_tables = (
            carry["vkeys"],
            carry["plog"],
            carry["pl_n"],
            carry["u_loc"],
        )

    def _build_generated(self):
        """Concatenate each shard's append-only parent log. The SoA
        buffers come back concatenated along their sharded ROW axis
        ([2, S*C_pad] / [4, S*L]); ``pl_n[s]`` entries of shard ``s``
        are live. The log carries BOTH key pairs (round 10): parent
        limbs in lanes 0-1, child limbs in lanes 2-3 — the
        incrementally-sorted visited array re-orders its rows every
        wave, so the round-9 positional child derivation is gone."""
        if self.generated is None:
            tier = self._tier_generated_map()
            if tier is not None:
                # tiered runs drain the log host-side per dispatch
                # (stateright_tpu/tier.py)
                self.generated = tier
                return self.generated
            _vkeys, plog, pl_n, _u_loc = (
                np.asarray(a) for a in self._final_tables
            )
            S = self.n_shards
            L = plog.shape[1] // S
            generated: dict = {}
            for s in range(S):
                n = int(pl_n[s])
                psl = slice(s * L, s * L + n)
                child = (
                    plog[3, psl].astype(np.uint64) << np.uint64(32)
                ) | plog[2, psl].astype(np.uint64)
                parent = (
                    plog[1, psl].astype(np.uint64) << np.uint64(32)
                ) | plog[0, psl].astype(np.uint64)
                for ch, pa in zip(child.tolist(), parent.tolist()):
                    generated[int(ch)] = int(pa) if pa else None
            self.generated = generated
        return self.generated
