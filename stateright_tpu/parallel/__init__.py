"""Multi-chip scale-out for the wave engine.

The TPU-native replacement for the reference's work-stealing job market
(stateright src/job_market.rs:66-147): instead of threads stealing job
batches from a shared stack, every device owns one shard of the
fingerprint space and the BFS frontier, and each wave ends with a
balanced ``all_to_all`` shuffle that routes every candidate successor
to the device that owns its fingerprint — so dedup stays shard-local
and no shared mutable state exists at all. Termination and counters are
``psum`` reductions over the mesh (SURVEY.md §2.5 items 2-4).
"""

from .engine import ShardedTpuBfsChecker
from .engine_sortmerge import ShardedSortMergeTpuBfsChecker

__all__ = ["ShardedTpuBfsChecker", "ShardedSortMergeTpuBfsChecker"]
