"""Shared backtracking serializer for consistency testers.

Implements the search at the heart of the reference's
``LinearizabilityTester::serialize`` (src/semantics/linearizability.rs:
196-284) and its sequential-consistency sibling
(sequential_consistency.rs:179-240): interleave per-thread operation
histories into a total order that the sequential spec accepts,
respecting program order always and (for linearizability) the recorded
happens-before snapshots. In-flight operations may linearize — taking
whatever return the spec produces — or be left out entirely.

Adds memoization over (positions, consumed-in-flight, spec digest)
configurations, a sound pruning absent from the reference (identical
configurations always produce identical outcomes).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..fingerprint import stable_hash
from .spec import SequentialSpec


def serialize_history(
    init_spec: SequentialSpec,
    completed: Dict[Any, List[Tuple[tuple, Any, Any]]],
    in_flight: Dict[Any, Tuple[tuple, Any]],
    real_time: bool,
) -> Optional[List[Tuple[Any, Any]]]:
    """Return a legal total order of (op, ret), or None.

    ``completed[t]`` is thread t's in-order list of
    ``(snapshot, op, ret)``; ``snapshot`` is a tuple of
    ``(peer, last_completed_index)`` pairs captured at invoke time
    (empty and unused when ``real_time`` is False).
    """
    threads = sorted(set(completed) | set(in_flight))
    total = {t: len(completed.get(t, [])) for t in threads}
    failed: set = set()

    def violates(snapshot: tuple, pos: Dict[Any, int]) -> bool:
        # Op cannot linearize until every op it happened-after has
        # (linearizability.rs:225-238, 252-265).
        return any(pos.get(peer, 0) <= min_time for peer, min_time in snapshot)

    def rec(
        pos: Dict[Any, int],
        consumed: frozenset,
        spec: SequentialSpec,
        acc: List[Tuple[Any, Any]],
    ) -> Optional[List[Tuple[Any, Any]]]:
        if all(pos[t] == total[t] for t in threads):
            return acc  # in-flight ops may remain unlinearized
        key = (
            tuple(pos[t] for t in threads),
            consumed,
            stable_hash(spec),
        )
        if key in failed:
            return None
        for t in threads:
            if pos[t] < total[t]:
                snapshot, op, ret = completed[t][pos[t]]
                if real_time and violates(snapshot, pos):
                    continue
                next_spec = spec.is_valid_step(op, ret)
                if next_spec is None:
                    continue
                result = rec(
                    {**pos, t: pos[t] + 1}, consumed, next_spec, acc + [(op, ret)]
                )
                if result is not None:
                    return result
            elif t in in_flight and t not in consumed:
                snapshot, op = in_flight[t]
                if real_time and violates(snapshot, pos):
                    continue
                next_spec, ret = spec.invoke(op)
                result = rec(
                    pos, consumed | {t}, next_spec, acc + [(op, ret)]
                )
                if result is not None:
                    return result
        failed.add(key)
        return None

    return rec({t: 0 for t in threads}, frozenset(), init_spec, [])
