"""Sequential-consistency tester.

Counterpart of stateright src/semantics/sequential_consistency.rs:
55-240 — the :class:`~stateright_tpu.semantics.linearizability.
LinearizabilityTester` skeleton minus the cross-thread real-time
constraints: only per-thread program order and the sequential spec
constrain the total order.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, List, Optional, Tuple

from ..fingerprint import stable_hash
from ._serialize import serialize_history
from .spec import SequentialSpec

_CACHE: dict = {}
_CACHE_CAP = 1 << 16


@dataclass(frozen=True)
class SequentialConsistencyTester:
    init_ref_obj: SequentialSpec
    #: sorted ((thread, ((op, ret), ...)), ...)
    history_by_thread: Tuple = ()
    #: sorted ((thread, op), ...)
    in_flight_by_thread: Tuple = ()
    is_valid: bool = True

    def on_invoke(self, thread: Any, op: Any) -> "SequentialConsistencyTester":
        if not self.is_valid:
            return self
        in_flight = dict(self.in_flight_by_thread)
        if thread in in_flight:
            return replace(self, is_valid=False)
        in_flight[thread] = op
        history = dict(self.history_by_thread)
        history.setdefault(thread, ())
        return replace(
            self,
            history_by_thread=tuple(sorted(history.items())),
            in_flight_by_thread=tuple(sorted(in_flight.items())),
        )

    def on_return(self, thread: Any, ret: Any) -> "SequentialConsistencyTester":
        if not self.is_valid:
            return self
        in_flight = dict(self.in_flight_by_thread)
        if thread not in in_flight:
            return replace(self, is_valid=False)
        op = in_flight.pop(thread)
        history = dict(self.history_by_thread)
        history[thread] = history.get(thread, ()) + ((op, ret),)
        return replace(
            self,
            history_by_thread=tuple(sorted(history.items())),
            in_flight_by_thread=tuple(sorted(in_flight.items())),
        )

    def __len__(self) -> int:
        return len(self.in_flight_by_thread) + sum(
            len(ops) for _, ops in self.history_by_thread
        )

    def serialized_history(self) -> Optional[List[Tuple[Any, Any]]]:
        if not self.is_valid:
            return None
        key = stable_hash(self)
        if key in _CACHE:
            return _CACHE[key]
        result = serialize_history(
            self.init_ref_obj,
            {
                t: [((), op, ret) for op, ret in ops]
                for t, ops in self.history_by_thread
            },
            {t: ((), op) for t, op in self.in_flight_by_thread},
            real_time=False,
        )
        if len(_CACHE) >= _CACHE_CAP:
            _CACHE.clear()
        _CACHE[key] = result
        return result

    def is_consistent(self) -> bool:
        return self.serialized_history() is not None
