"""Stack/vector reference object.

Counterpart of stateright src/semantics/vec.rs:22-50: push/pop/len
with stack semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

from .spec import SequentialSpec


@dataclass(frozen=True)
class Push:
    value: Any


@dataclass(frozen=True)
class Pop:
    pass


@dataclass(frozen=True)
class Len:
    pass


@dataclass(frozen=True)
class PushOk:
    pass


@dataclass(frozen=True)
class PopOk:
    value: Optional[Any]


@dataclass(frozen=True)
class LenOk:
    length: int


@dataclass(frozen=True)
class Vec(SequentialSpec):
    values: Tuple[Any, ...] = ()

    def invoke(self, op: Any) -> Tuple["Vec", Any]:
        if isinstance(op, Push):
            return Vec(self.values + (op.value,)), PushOk()
        if isinstance(op, Pop):
            if not self.values:
                return self, PopOk(None)
            return Vec(self.values[:-1]), PopOk(self.values[-1])
        if isinstance(op, Len):
            return self, LenOk(len(self.values))
        raise TypeError(f"unknown vec op {op!r}")
