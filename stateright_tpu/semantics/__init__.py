"""Consistency semantics: sequential specs and concurrent-history testers.

Counterpart of stateright src/semantics.rs and src/semantics/*:
reference objects (:class:`~stateright_tpu.semantics.register.Register`,
write-once register, vector) define *sequential* semantics via
:class:`SequentialSpec`; the linearizability / sequential-consistency
testers record a concurrent operation history and decide whether some
legal total order explains it.

Unlike the reference's mutable testers, these are **immutable**: in
actor models the tester is the auxiliary history and therefore part of
the fingerprinted model state (see SURVEY.md §2.3), so recording
returns a new tester value.
"""

from .spec import SequentialSpec
from .linearizability import LinearizabilityTester
from .sequential_consistency import SequentialConsistencyTester
from .register import Register, ReadOp, ReadOk, WriteOp, WriteOk
from .write_once_register import WORegister, WriteFail
from .vec import Vec, Push, Pop, Len, PushOk, PopOk, LenOk

__all__ = [
    "SequentialSpec",
    "LinearizabilityTester",
    "SequentialConsistencyTester",
    "Register",
    "ReadOp",
    "ReadOk",
    "WriteOp",
    "WriteOk",
    "WORegister",
    "WriteFail",
    "Vec",
    "Push",
    "Pop",
    "Len",
    "PushOk",
    "PopOk",
    "LenOk",
]
