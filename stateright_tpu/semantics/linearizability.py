"""Linearizability tester.

Counterpart of stateright src/semantics/linearizability.rs:57-284.
Records a concurrent per-thread operation history; each invocation
snapshots the index of the last completed operation of every *other*
thread, encoding the real-time happens-before order; a history is
linearizable iff some total order consistent with program order, the
snapshots, and the sequential spec explains it.

Immutable: ``on_invoke``/``on_return`` return new testers, because in
actor models the tester is the auxiliary history inside the
fingerprinted model state (reference pattern: the tester *is* the
``ActorModel`` history ``H``, SURVEY.md §2.3).

Protocol errors (double invoke, return without invoke) mark the
history invalid, after which ``is_consistent`` is False — matching the
reference's ``is_valid_history`` flag (linearizability.rs:100-165).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, List, Optional, Tuple

from ..fingerprint import stable_hash
from ._serialize import serialize_history
from .spec import SequentialSpec

# serialized_history is invoked per explored state while identical
# tester values recur across huge regions of the state space; memoize
# by structural digest (same 64-bit collision budget as the checker).
_CACHE: dict = {}
_CACHE_CAP = 1 << 16


@dataclass(frozen=True)
class LinearizabilityTester:
    init_ref_obj: SequentialSpec
    #: sorted ((thread, ((snapshot, op, ret), ...)), ...)
    history_by_thread: Tuple = ()
    #: sorted ((thread, (snapshot, op)), ...)
    in_flight_by_thread: Tuple = ()
    is_valid: bool = True

    # -- recording (ConsistencyTester interface) -------------------------

    def on_invoke(self, thread: Any, op: Any) -> "LinearizabilityTester":
        if not self.is_valid:
            return self
        in_flight = dict(self.in_flight_by_thread)
        if thread in in_flight:
            return replace(self, is_valid=False)
        history = dict(self.history_by_thread)
        snapshot = tuple(
            sorted(
                (peer, len(ops) - 1)
                for peer, ops in history.items()
                if peer != thread and ops
            )
        )
        in_flight[thread] = (snapshot, op)
        history.setdefault(thread, ())
        return replace(
            self,
            history_by_thread=tuple(sorted(history.items())),
            in_flight_by_thread=tuple(sorted(in_flight.items())),
        )

    def on_return(self, thread: Any, ret: Any) -> "LinearizabilityTester":
        if not self.is_valid:
            return self
        in_flight = dict(self.in_flight_by_thread)
        if thread not in in_flight:
            return replace(self, is_valid=False)
        snapshot, op = in_flight.pop(thread)
        history = dict(self.history_by_thread)
        history[thread] = history.get(thread, ()) + ((snapshot, op, ret),)
        return replace(
            self,
            history_by_thread=tuple(sorted(history.items())),
            in_flight_by_thread=tuple(sorted(in_flight.items())),
        )

    def __len__(self) -> int:
        return len(self.in_flight_by_thread) + sum(
            len(ops) for _, ops in self.history_by_thread
        )

    # -- checking --------------------------------------------------------

    def serialized_history(self) -> Optional[List[Tuple[Any, Any]]]:
        if not self.is_valid:
            return None
        key = stable_hash(self)
        if key in _CACHE:
            return _CACHE[key]
        result = serialize_history(
            self.init_ref_obj,
            {t: list(ops) for t, ops in self.history_by_thread},
            dict(self.in_flight_by_thread),
            real_time=True,
        )
        if len(_CACHE) >= _CACHE_CAP:
            _CACHE.clear()
        _CACHE[key] = result
        return result

    def is_consistent(self) -> bool:
        return self.serialized_history() is not None
