"""``SequentialSpec``: operational semantics of a reference object.

Counterpart of stateright src/semantics.rs:73-98, immutably: a spec
value is a snapshot of the reference object's state; ``invoke``
returns ``(next_spec, ret)`` instead of mutating.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple


class SequentialSpec:
    """Subclasses implement ``invoke``; ``is_valid_step`` defaults to
    invoke-and-compare (semantics.rs:84-98)."""

    def invoke(self, op: Any) -> Tuple["SequentialSpec", Any]:
        raise NotImplementedError

    def is_valid_step(self, op: Any, ret: Any) -> Optional["SequentialSpec"]:
        """Return the successor spec if ``op`` may return ``ret`` here,
        else None."""
        next_spec, actual = self.invoke(op)
        return next_spec if actual == ret else None

    def is_valid_history(self, history: Sequence[Tuple[Any, Any]]) -> bool:
        """Whether a sequential (op, ret) history is legal
        (semantics.rs:90-98)."""
        spec: Optional[SequentialSpec] = self
        for op, ret in history:
            spec = spec.is_valid_step(op, ret)
            if spec is None:
                return False
        return True
