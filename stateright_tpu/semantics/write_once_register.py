"""Write-once register reference object.

Counterpart of stateright src/semantics/write_once_register.rs:9-57:
the first write wins; later writes return ``WriteFail``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

from .spec import SequentialSpec
from .register import ReadOk, ReadOp, WriteOk, WriteOp


@dataclass(frozen=True)
class WriteFail:
    pass


@dataclass(frozen=True)
class WORegister(SequentialSpec):
    value: Optional[Any] = None
    written: bool = False

    def invoke(self, op: Any) -> Tuple["WORegister", Any]:
        if isinstance(op, WriteOp):
            if self.written:
                return self, WriteFail()
            return WORegister(op.value, True), WriteOk()
        if isinstance(op, ReadOp):
            return self, ReadOk(self.value)
        raise TypeError(f"unknown write-once register op {op!r}")
