"""Read/write register reference object.

Counterpart of stateright src/semantics/register.rs:9-49:
``Register(value)`` with ``WriteOp``/``ReadOp`` returning
``WriteOk``/``ReadOk(value)``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

from .spec import SequentialSpec


@dataclass(frozen=True)
class WriteOp:
    value: Any


@dataclass(frozen=True)
class ReadOp:
    pass


@dataclass(frozen=True)
class WriteOk:
    pass


@dataclass(frozen=True)
class ReadOk:
    value: Any


@dataclass(frozen=True)
class Register(SequentialSpec):
    value: Any

    def invoke(self, op: Any) -> Tuple["Register", Any]:
        if isinstance(op, WriteOp):
            return Register(op.value), WriteOk()
        if isinstance(op, ReadOp):
            return self, ReadOk(self.value)
        raise TypeError(f"unknown register op {op!r}")
