"""Memory observability: the resident-buffer ledger, compiled-program
memory analysis, and live watermark polling.

Run telemetry (telemetry.py) and mesh telemetry made *time* and
*counters* first-class; this module does the same for the axis that
gates the next two ROADMAP directions — **memory**. The tiered visited
set (direction 1b, "bounded by host memory, not HBM") and the
HBM-staged merge kernel (direction 2b, "once V outgrows VMEM
residency") are capacity decisions: GPUexplore's scalability study
(arXiv:1801.05857) frames device-memory capacity as the binding
constraint on state-space throughput, and the elastic-resource framing
of arXiv:1203.6806 assumes occupancy is *observable* before it can be
tiered. Until now both numbers lived as hand arithmetic in PERF.md.

Three layers, all threaded through the seams the tracer already owns
(untraced programs stay byte-identical — nothing here changes a
compiled program or adds a device sync):

* **Resident-buffer ledger** — each engine declares its resident chunk
  carry (frontier ``[W, F]``, ``vkeys [2, C_pad]``, ``plog``, ebits,
  the wave/shard device logs) with dtype/shape/bytes, derived from
  ``jax.eval_shape`` over the engine's OWN seed program — so the
  declaration cannot drift from the allocation (the plan-vs-``nbytes``
  test pins it on real device arrays). Per-wave *staging* (candidate
  buffers, payloads, mask words) is declared per **ladder class**: the
  plan is a function of the (f, v) class the adaptive ladder
  dispatches, not just the peak — the number that prices what the next
  class step costs. Emitted as a schema-validated ``memory_plan``
  telemetry event (telemetry.py) and kept on the checker
  (``checker.memory_plan``) for untraced consumers (bench.py lane
  details).
* **Compiled-program analysis** — ``Compiled.memory_analysis()``
  (temp/argument/output/alias bytes — XLA's own accounting of the wave
  program) captured at the existing ``compile`` span via an AOT
  lower+compile that the persistent XLA cache dedups, cached here (in
  process and on disk beside the XLA cache) so one traced run per
  config pays it, degrading to ``None`` where the backend doesn't
  report it.
* **Live watermarks** — device bytes-in-use polled ONLY at the
  existing per-chunk sync (no new syncs: the readback already blocked;
  ``device.memory_stats()`` where the backend reports it — TPU/GPU —
  and live-array accounting on CPU, where ``memory_stats()`` is None),
  recorded per chunk and summarized as a ``memory_watermark`` event:
  run peak, host-side visited bytes, observed-peak-vs-capacity
  headroom joined from the persisted auto-budget store, and the
  **capacity projection** — predicted resident bytes at the next
  visited ladder class, the number that decides when V stops fitting
  VMEM.

``tools/mem_report.py`` renders the plan/watermark/headroom table over
a TRACE and writes auto-numbered ``MEM_r*.json`` artifacts;
``tools/trace_diff.py`` aligns the memory counters between two traces
(plan shapes exactly, measured temp/live bytes under the relative
threshold so jax-version skew doesn't false-positive).

Import-light by design (numpy only): tools and tests read traces
without jax; everything touching a device imports jax lazily.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Optional

import numpy as np


# -- the ledger -----------------------------------------------------------


def buffer_entry(name: str, shape, dtype) -> dict:
    """One ledger row: ``{name, shape, dtype, bytes}``. ``bytes`` is
    the unpadded logical size (``prod(shape) * itemsize``) — exactly
    what a device array's ``nbytes`` reports, which is what the
    plan-vs-``nbytes`` consistency test compares against. (TPU tile
    PADDING — the ceil-to-(8,128) tax PERF.md §tile-padding measures —
    is a multiplier on top; the report prints logical bytes and leaves
    padding to the compiled-program analysis, which sees post-layout
    sizes.)"""
    shape = tuple(int(s) for s in shape)
    itemsize = np.dtype(dtype).itemsize
    n = 1
    for s in shape:
        n *= s
    return dict(
        name=name,
        shape=list(shape),
        dtype=str(np.dtype(dtype)),
        bytes=int(n * itemsize),
    )


def plan_entries(spec: dict, *, sharded=(), n_shards: int = 1) -> list:
    """Ledger rows for a carry pytree (a dict of arrays or
    ``ShapeDtypeStruct``s — the output of ``jax.eval_shape`` over an
    engine's seed program). Shapes are GLOBAL; entries named in
    ``sharded`` additionally carry ``per_shard_bytes = bytes /
    n_shards`` (their leading/sharded axis is split across the mesh),
    replicated entries carry their full size per shard."""
    out = []
    for name in sorted(spec):
        leaf = spec[name]
        e = buffer_entry(name, leaf.shape, leaf.dtype)
        if n_shards > 1:
            e["per_shard_bytes"] = (
                e["bytes"] // n_shards if name in sharded else e["bytes"]
            )
            e["sharded"] = name in sharded
        out.append(e)
    return out


def plan_total(entries) -> int:
    return int(sum(e["bytes"] for e in entries))


def session_resident_bytes(checker) -> dict:
    """Pre-run admission pricing for the resident service
    (stateright_tpu/serve.py): the dominant resident-buffer rows of a
    device checker, derivable from CONFIG ALONE — no program build, no
    device work — so the service can refuse an oversized session
    BEFORE it touches the device. Prices the same quantities the full
    ledger declares (visited keys + parent forest via the engine's own
    ``_visited_bytes_per_row``, the frontier block, the candidate
    buffer), as a documented FLOOR: per-ladder-class staging and
    compiled temp bytes land on top once programs build, which is why
    admission compares against a budget the operator sets with
    headroom. Returns ``{visited_bytes, frontier_bytes, cand_bytes,
    total_bytes}``."""
    bpr = int(checker._visited_bytes_per_row())
    n_shards = int(getattr(checker, "n_shards", 1))
    W = int(checker.encoded.width)
    K = int(checker.encoded.max_actions)
    F = int(checker.frontier_capacity)
    visited = int(checker.total_capacity) * bpr
    frontier = n_shards * F * W * 4
    cand = checker.cand_capacity
    if cand in (None, "auto"):
        cand = F * K  # the no-compaction static bound
    cand_bytes = n_shards * int(cand) * W * 4
    return dict(
        visited_bytes=int(visited),
        frontier_bytes=int(frontier),
        cand_bytes=int(cand_bytes),
        total_bytes=int(visited + frontier + cand_bytes),
    )


def fused_session_bytes(fused, n_sessions: int) -> dict:
    """Admission pricing for a FUSED multi-session plan
    (stateright_tpu/batch.py): :func:`session_resident_bytes` over the
    fused engine's config, plus the per-session amortized share — the
    number `CheckService._admit` compares against the device budget
    when deciding whether N sessions fuse or spill to the solo FIFO.
    Config-only, same as the solo pricing: no program build, no
    device work."""
    plan = session_resident_bytes(fused)
    plan["n_sessions"] = int(n_sessions)
    plan["per_session_bytes"] = plan["total_bytes"] // max(
        1, int(n_sessions)
    )
    return plan


def v_class_entries(v_ladder, nf_max: int) -> list:
    """Per-VISITED-ladder-class merge-scratch rows, shared by both
    sort-merge engines' ``_build_info`` (one pricing, no drift): the
    streaming member/merge passes read ``[0, V_v)`` and write the
    merged ``[0, V_v + NF)`` block back — two uint32 key limbs per
    row — so this is what a v-class step costs in class-local
    scratch."""
    return [
        dict(v_class=vc, visited_rows=int(v),
             merge_scratch_bytes=int((v + nf_max) * 8))
        for vc, v in enumerate(v_ladder)
    ]


def decide_hot_rows(capacity: int, v_min: int, v_ladder_step: int,
                    frontier_capacity: int,
                    budget_bytes: int) -> int:
    """The hot/cold split of the tiered visited set (ROADMAP direction
    1b, stateright_tpu/tier.py), decided by the SAME pricing the
    capacity projection reports (``next_vkeys_bytes`` +
    ``next_merge_scratch_bytes``, both ``(V + F) * 8``): the largest
    visited-ladder class whose resident vkeys block PLUS merge
    scratch fit ``budget_bytes`` becomes the hot-tier ceiling —
    everything past it spills to host DRAM.

    Returns ``capacity`` itself when the whole ladder fits (the tier
    stays dormant: the spill watermark is never crossed), and the
    ladder bottom ``v_min`` when even that class exceeds the budget
    (the engine still runs; the hot tier is just minimal). This is
    the ``tier_hot_rows="auto"`` policy — the projection is exactly
    the signal, as the round-12 ledger promised."""
    F = int(frontier_capacity)
    hot = int(min(v_min, capacity))
    v = hot
    while v < capacity:
        v = min(v * v_ladder_step, capacity)
        if 2 * (v + F) * 8 > budget_bytes:
            break
        hot = v
    return hot


def tier_frontier_headroom(capacity: int, frontier_capacity: int,
                           cand_capacity) -> dict:
    """The tiered-mode frontier-headroom bound (the PR 12 known
    bound), pre-checked from the SAME numbers the resident-buffer
    ledger declares — BEFORE any device work, instead of surfacing
    mid-run as an f_overflow message:

    in tiered mode the frontier bound applies to a wave's
    PROVISIONAL winners (hot-tier-new rows before the cold membership
    pass retires spilled duplicates), which can exceed the resident
    run's post-dedup new counts. The only static ceiling on
    provisional winners is the candidate budget ``B``
    (cand_capacity): when ``B <= F`` the bound PROVABLY holds — no
    tiered wave can overflow a frontier the candidate buffer can't
    outproduce; when ``B > F`` the bound is load-dependent and a
    frontier that fits the all-resident run may need headroom once
    the hot tier spills.

    Returns ``{holds, frontier_capacity, cand_capacity,
    required_frontier, message}`` — ``holds`` is True (provable),
    False (violated, ``message`` carries the pinned refuse/warn text
    and ``required_frontier`` the F that makes it provable, = B), or
    None when the budget is still unresolved (a literal ``"auto"``
    not yet replaced by the persisted/heuristic budget — nothing is
    provable or refutable yet, and no message is emitted: a false
    "None exceeds F" claim is worse than silence). Callers with
    ``cand_capacity=None`` (no compaction) should pass the true
    static bound ``F x K`` instead — the engines' ``_pre_run_check``
    does. The engines consume this through ``tier_headroom_policy``
    ("warn" — the documented PR 12 behavior, now surfaced BEFORE
    device work; "bump" — raise frontier_capacity to
    ``required_frontier`` before programs build; "refuse" — raise
    instead of risking a mid-run overflow)."""
    C = int(capacity)
    F = int(frontier_capacity)
    if cand_capacity in (None, "auto"):
        return dict(
            holds=None,
            capacity=C,
            frontier_capacity=F,
            cand_capacity=cand_capacity,
            required_frontier=None,
            message=None,
        )
    B = int(cand_capacity)
    holds = B <= F
    message = None
    if not holds:
        message = (
            "tiered-mode frontier-headroom bound: provisional "
            "winners (hot-tier-new rows before the cold membership "
            "pass) are bounded only by the candidate budget "
            f"cand_capacity={B}, which exceeds "
            f"frontier_capacity={F} — a frontier that fits the "
            "all-resident run may overflow once the hot tier "
            f"spills. Raise frontier_capacity to {B} "
            "(tier_headroom_policy='bump' does this before device "
            "work), or accept the mid-run f_overflow risk "
            "(tier_headroom_policy='warn', the default)."
        )
    return dict(
        holds=holds,
        capacity=C,
        frontier_capacity=F,
        cand_capacity=cand_capacity,
        required_frontier=B,
        message=message,
    )


# -- live watermarks ------------------------------------------------------


def device_bytes_in_use() -> tuple[Optional[int], Optional[str]]:
    """``(bytes, source)`` for the default device, polled at a point
    where the caller has ALREADY synced (the per-chunk stats readback)
    — this function never blocks on device work itself.

    * ``("memory_stats")`` — the backend reports allocator stats
      (TPU/GPU ``device.memory_stats()["bytes_in_use"]``);
    * ``("live_arrays")`` — CPU fallback: ``memory_stats()`` is None
      there, so sum ``nbytes`` over the process's live jax arrays
      (logical bytes; close enough to watch growth and headroom);
    * ``(None, None)`` — neither answerable (never raises)."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_in_use") is not None:
            return int(stats["bytes_in_use"]), "memory_stats"
        total = 0
        for a in jax.live_arrays():
            total += int(getattr(a, "nbytes", 0))
        return total, "live_arrays"
    except Exception:
        return None, None


# -- compiled-program memory analysis -------------------------------------

#: the CompiledMemoryStats fields the ledger keeps (XLA's accounting of
#: one compiled wave program: scratch/temp, donated-alias, argument and
#: output buffers, plus the executable itself).
COMPILED_FIELDS = (
    "temp_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)

_ANALYSIS_CACHE: dict = {}


def compiled_memory(compiled) -> Optional[dict]:
    """Normalize one ``Compiled.memory_analysis()`` result to a plain
    dict of :data:`COMPILED_FIELDS`, or None where the backend doesn't
    report it (older jax, stripped runtimes — degrade, never raise)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    any_real = False
    for k in COMPILED_FIELDS:
        v = getattr(ma, k, None)
        if v is None:
            out[k] = None
        else:
            out[k] = int(v)
            any_real = True
    return out if any_real else None


def _analysis_store() -> str:
    return os.path.expanduser(
        "~/.cache/stateright_tpu_memory_analysis.json"
    )


def compiled_memory_analysis(chunk_fn, carry_spec,
                             cache_token,
                             on_build=None) -> Optional[dict]:
    """``memory_analysis()`` of an engine's compiled chunk program,
    via an AOT ``lower().compile()`` the persistent XLA compile cache
    dedups against the dispatch-path compile. Results are cached in
    process AND persisted beside the XLA cache (keyed by the engine's
    program cache token + backend), so one traced run per
    configuration pays the AOT pass and later runs — including the
    overhead-measurement pools — read it back. A backend that can't
    REPORT the analysis caches its None (that answer is stable); a
    FAILED lower/compile returns None without caching, so a
    transient failure (interrupted process, device busy) doesn't
    permanently disable the lane for that config.

    ``on_build`` (round 14, the compile-cache ledger): called exactly
    once per RESOLVED lookup as ``on_build(tier, wall_sec)`` —
    ``"in_process"`` / ``"disk"`` for this module's result caches,
    ``"aot"`` when the AOT lower+compile actually ran (the caller
    refines that tier from its compile monitor: the AOT pass itself
    may hit the persistent XLA cache). Not called on the degrade
    paths (no jax, failed compile) — those produced nothing to
    ledger."""
    import time as _time

    t0 = _time.monotonic()
    try:
        import jax

        key = hashlib.sha1(
            f"{jax.default_backend()}/{cache_token!r}".encode()
        ).hexdigest()
    except Exception:
        return None
    if key in _ANALYSIS_CACHE:
        if on_build is not None:
            on_build("in_process", _time.monotonic() - t0)
        return _ANALYSIS_CACHE[key]
    # disk: survives processes the way the XLA cache does
    store = _analysis_store()
    try:
        with open(store) as fh:
            disk = json.load(fh)
        if key in disk:
            _ANALYSIS_CACHE[key] = disk[key]
            if on_build is not None:
                on_build("disk", _time.monotonic() - t0)
            return disk[key]
    except (OSError, ValueError):
        pass
    try:
        compiled = chunk_fn.lower(carry_spec).compile()
    except Exception:
        return None  # transient: retry on the next traced run
    if on_build is not None:
        on_build("aot", _time.monotonic() - t0)
    result = compiled_memory(compiled)
    _ANALYSIS_CACHE[key] = result
    try:
        os.makedirs(os.path.dirname(store), exist_ok=True)
        data = {}
        try:
            with open(store) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            pass
        data[key] = result
        tmp = store + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(data, fh)
        os.replace(tmp, store)
    except OSError:
        pass
    return result


# -- rendering helpers ----------------------------------------------------


def format_bytes(n) -> str:
    """Human-readable byte count ('-' for None)."""
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return (f"{n:,.0f} {unit}" if unit == "B"
                    else f"{n:,.2f} {unit}")
        n /= 1024.0
    return f"{n:,.2f} GB"


# -- MEM artifacts --------------------------------------------------------


def write_memory_artifact(summary: dict, root: Optional[str] = None,
                          ) -> str:
    """Write one auto-numbered ``MEM_r*.json`` artifact (the memory
    summary of one traced run, tools/mem_report.py's ``--json``
    output). MEM numbers in its OWN round sequence (``MEM_r01`` first)
    rather than the shared BENCH/LINT/TRACE sequence: a MEM artifact
    is *derived from* a TRACE and names it (``summary["trace"]``), so
    the cross-reference — not a shared counter — is what pairs it with
    a perf round. Numbering still flows through the one home in
    artifacts.py."""
    from .artifacts import artifact_path, next_round, provenance, repo_root

    root = repo_root() if root is None else root
    path = artifact_path(
        "MEM", "json", root=root,
        round=next_round(root, stems=("MEM",)),
    )
    doc = dict(summary)
    doc.setdefault("provenance", provenance())
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path
