"""Progress reporting for checkers.

Mirrors stateright src/report.rs:10-98: a ``Reporter`` receives periodic
``ReportData`` snapshots while a checker runs, then the final snapshot
and the discovery set. ``WriteReporter`` reproduces the reference's text
protocol (``Checking. states=… unique=… depth=…`` / ``Done. … sec=…``,
then each discovery with its encoded fingerprint path) so CLI output is
drop-in familiar.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import IO, TYPE_CHECKING

if TYPE_CHECKING:
    from .checker import Checker


@dataclass
class ReportData:
    """Snapshot of checker progress (report.rs:10-21)."""

    total_states: int
    unique_states: int
    max_depth: int
    duration_sec: float
    done: bool


class Reporter:
    """Periodic progress sink (report.rs:35-48)."""

    def delay(self) -> float:
        """Seconds between ``report_checking`` calls (report.rs:45-48)."""
        return 1.0

    def report_checking(self, data: ReportData) -> None:
        pass

    def report_discoveries(self, checker: "Checker") -> None:
        pass


class WriteReporter(Reporter):
    """Text reporter matching the reference format (report.rs:60-98)."""

    def __init__(self, out: IO[str] | None = None):
        self.out = out if out is not None else sys.stdout

    def report_checking(self, data: ReportData) -> None:
        if data.done:
            self.out.write(
                f"Done. states={data.total_states}, "
                f"unique={data.unique_states}, depth={data.max_depth}, "
                f"sec={data.duration_sec:.3f}\n"
            )
        else:
            self.out.write(
                f"Checking. states={data.total_states}, "
                f"unique={data.unique_states}, depth={data.max_depth}\n"
            )
        self.out.flush()

    def report_discoveries(self, checker: "Checker") -> None:
        # Fingerprint-only engines (track_paths=False, simulation)
        # report the discovery fingerprint instead of a replayable
        # path; full-path checkers keep the reference format.
        fp_only = getattr(checker, "discovery_fingerprints", None)
        track_paths = getattr(checker, "track_paths", True)
        if fp_only is not None and not track_paths:
            for name, fp in sorted(fp_only().items()):
                classification = checker.discovery_classification(name)
                self.out.write(
                    f"Discovered \"{name}\" {classification.value} "
                    f"{fp:#018x} (fingerprint only; re-run with "
                    "track_paths=True for the trace)\n"
                )
            self.out.flush()
            return
        for name, path in sorted(checker.discoveries().items()):
            classification = checker.discovery_classification(name)
            self.out.write(
                f"Discovered \"{name}\" {classification.value} {path.encode()}\n"
            )
            for state, action in path.steps:
                if action is not None:
                    self.out.write(f"{state!r}\n")
                    self.out.write(
                        f"-- {checker.model.format_action(action)} -->\n"
                    )
                else:
                    self.out.write(f"{state!r}\n")
        self.out.flush()
