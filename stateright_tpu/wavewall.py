"""Wave-wall profiler: attribute the OUT-OF-STAGE share of wave time.

The round-5 stage profile (PERF.md) showed per-stage compute at
paxos-4 shapes summing to only ~0.4-0.8s of the 2.14s end-to-end wall
— the majority of wave time sat BETWEEN the stages: ``lax.switch``
ladder carry movement, class-quantization waste, and XLA layout
copies. Nothing in the repo measured that term directly; this module
does, three ways, all runnable on CPU:

* **wall vs stages** — re-time ONE full wave body (the engine exposes
  it as ``checker._wave_body``) on a captured mid-run carry,
  REPS-amortized inside a single jitted ``fori_loop`` with EVERY wave
  input (frontier, fval, the visited array and its unique count,
  ebits, parent-log offset) reset per repetition so each rep repeats
  the captured wave exactly (rep 1 appends its winners to the visited
  set, so an un-reset loop would dedup rep 2's candidates to nothing
  and time REPS-1 degenerate waves);
* **switch-ladder carry baseline** — the same class-ladder
  ``lax.switch`` dispatch with IDENTITY branches over the same carry:
  pure carry movement through the conditional, the term the class-
  local-carry rework (round 6, checkers/tpu_sortmerge.py make_fetch)
  attacks;
* **HLO category breakdown** — lower-and-compile the one-wave program
  and classify every optimized-HLO instruction with
  :func:`hlo_category` (the same category vocabulary the round-5
  device-trace analysis used: data formatting, carry/slice movement,
  quantization padding, sort, gather, fusion), summing op counts and
  output bytes per category. Bytes of copy/pad/slice traffic are the
  static fingerprint of the wave wall — they move with the carry
  rework even when wall-clock on CPU is noisy.

Used by ``tools/profile_stages.py --wave-wall`` (prints the report
next to the per-stage sums) and pinned on CPU by
tests/test_wavewall.py.

The opcode→category tables live in
:mod:`stateright_tpu.analysis.tables` (round 7) — one table shared
with the kernel-lint rules and the codegen-shape tests, so the
profiler's attribution vocabulary and the lint's carry-movement
pricing cannot drift. :func:`hlo_category` and
:func:`parse_hlo_categories` stay importable from here.
"""

from __future__ import annotations

import time

import numpy as np

from .analysis.tables import (  # noqa: F401 — the shared tables
    hlo_category,
    parse_hlo_categories,
)


def _timed_loop(jit_fn, args) -> float:
    """Best-of-3 seconds for one jitted call (which internally loops
    its reps); the caller divides by the rep count."""
    import jax

    out = jit_fn(*args)
    jax.block_until_ready(out)  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.monotonic()
        jax.block_until_ready(jit_fn(*args))
        best = min(best, time.monotonic() - t0)
    return best


def _ladder_classes(checker):
    from .checkers.tpu_sortmerge import _ladder

    f_ladder = _ladder(
        checker.f_min, checker.frontier_capacity, checker.ladder_step
    )
    v_ladder = _ladder(
        checker.v_min, checker.capacity, checker.v_ladder_step
    )
    return f_ladder, v_ladder


def wave_wall_report(checker, reps: int = 8) -> dict:
    """Measure one wave's wall vs its carry-movement baseline on the
    checker's captured final carry, and statically attribute the
    compiled one-wave program's ops/bytes per HLO category.

    The checker must have run with ``keep_final_carry = True`` (the
    tools/profile_stages.py capture protocol: set a
    ``target_state_count`` so the final carry is a genuine mid-growth
    wave). Returns a dict with ``wave_ms``, ``switch_carry_ms``,
    ``loop_floor_ms``, ``n_rows``, and ``categories``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    carry = getattr(checker, "_final_carry", None)
    if carry is None:
        raise ValueError(
            "run the checker with keep_final_carry=True before "
            "profiling (spawn, set the attribute, join)"
        )
    if not hasattr(checker, "_wave_body"):
        # Programs came from the chunk cache: rebuild (cheap — builds
        # python closures; tracing happens only at jit time below;
        # the wave body itself is independent of the init count).
        checker._build_programs(1)
    body = checker._wave_body

    n_rows = int(np.asarray(carry["n_frontier"]))
    F = checker.frontier_capacity
    fval0 = jnp.arange(F) < jnp.uint32(max(n_rows, 1))
    base = dict(
        carry,
        fval=fval0,
        n_frontier=jnp.uint32(max(n_rows, 1)),
        done=jnp.bool_(False),
        wchunk=jnp.int32(0),
    )

    def checksum(c):
        # Consume element [0] of EVERY carry leaf — returning a lone
        # counter lets XLA dead-code-eliminate the entire wave (the
        # round-5 profiler bug, see tools/profile_stages._timed_raw);
        # the dynamic-offset block writes and the rep-to-rep carry
        # chain keep the full stages live through these folds.
        return sum(
            jnp.sum(jnp.ravel(v)[:1].astype(jnp.uint32))
            for v in c.values()
        )

    def run_waves(c):
        def rep(i, c2):
            # Reset EVERY wave input from the loop-invariant captured
            # carry `c` — frontier/fval, the visited array and its
            # unique count (rep 1 appends its winners; an un-reset
            # chain would dedup all of rep 2's candidates to nothing
            # and bump the visited ladder class, so reps 2..N would
            # time non-representative waves), ebits, and the
            # parent-log offset. Counters (waves/depth/gen) chain
            # through c2; the perturbed frontier cell makes each rep's
            # inputs distinct.
            fr = c["frontier"].at[0, 0].set(
                c["frontier"][0, 0] ^ i.astype(jnp.uint32)
            )
            return body(
                dict(
                    c2,
                    frontier=fr,
                    fval=fval0,
                    ebits=c["ebits"],
                    n_frontier=base["n_frontier"],
                    vkeys=c["vkeys"],
                    new=c["new"],
                    pl_n=c["pl_n"],
                    done=jnp.bool_(False),
                )
            )

        return checksum(lax.fori_loop(0, reps, rep, c))

    f_ladder, _ = _ladder_classes(checker)

    def run_switch_identity(c):
        def rep(i, c2):
            # Same class selection as the engine's body; each branch
            # only bumps the wave counter (keeps the loop sequential),
            # so the measured time is the switch's carry movement.
            f_class = jnp.int32(0)
            for F_i in f_ladder[:-1]:
                f_class = f_class + (
                    c2["n_frontier"] > jnp.uint32(F_i)
                ).astype(jnp.int32)
            return lax.switch(
                f_class,
                [
                    (lambda x, _fc=fc: dict(
                        x, waves=x["waves"] + jnp.uint32(1)
                    ))
                    for fc in range(len(f_ladder))
                ],
                c2,
            )

        return checksum(lax.fori_loop(0, reps, rep, c))

    def run_empty(c):
        return checksum(lax.fori_loop(0, reps, lambda i, c2: c2, c))

    wave_s = _timed_loop(jax.jit(run_waves), (base,))
    sw_s = _timed_loop(jax.jit(run_switch_identity), (base,))
    empty_s = _timed_loop(jax.jit(run_empty), (base,))

    hlo = (
        jax.jit(body)
        .lower(base)
        .compile()
        .as_text()
    )
    categories = parse_hlo_categories(hlo)

    return dict(
        n_rows=n_rows,
        reps=reps,
        wave_ms=wave_s / reps * 1000.0,
        switch_carry_ms=(sw_s - empty_s) / reps * 1000.0,
        loop_floor_ms=empty_s / reps * 1000.0,
        categories=categories,
    )


def format_report(rep: dict, stage_sum_ms: float | None = None) -> str:
    """Human-readable wave-wall report (the tools/ CLI prints this)."""
    lines = [
        f"wave wall: {rep['wave_ms']:.2f} ms/wave over "
        f"{rep['n_rows']} frontier rows "
        f"(loop floor {rep['loop_floor_ms']:.2f} ms, "
        f"identity-switch carry movement "
        f"{rep['switch_carry_ms']:.2f} ms)",
    ]
    if stage_sum_ms is not None:
        lines.append(
            f"  stage compute sum {stage_sum_ms:.2f} ms -> "
            f"out-of-stage wall "
            f"{max(rep['wave_ms'] - stage_sum_ms, 0.0):.2f} ms"
        )
    lines.append(
        f"  {'hlo category':26s} {'ops':>6s} {'MB(out)':>9s}"
    )
    cats = sorted(
        rep["categories"].items(),
        key=lambda kv: -kv[1]["bytes"],
    )
    for name, s in cats:
        lines.append(
            f"  {name:26s} {s['ops']:6d} {s['bytes'] / 1e6:9.2f}"
        )
    return "\n".join(lines)
