"""Wave-wall profiler: attribute the OUT-OF-STAGE share of wave time.

The round-5 stage profile (PERF.md) showed per-stage compute at
paxos-4 shapes summing to only ~0.4-0.8s of the 2.14s end-to-end wall
— the majority of wave time sat BETWEEN the stages: ``lax.switch``
ladder carry movement, class-quantization waste, and XLA layout
copies. Nothing in the repo measured that term directly; this module
does, three ways, all runnable on CPU:

* **wall vs stages** — re-time ONE full wave body (the engine exposes
  it as ``checker._wave_body``) on a captured mid-run carry,
  REPS-amortized inside a single jitted ``fori_loop`` with EVERY wave
  input (frontier, fval, the visited array and its unique count,
  ebits, parent-log offset) reset per repetition so each rep repeats
  the captured wave exactly (rep 1 appends its winners to the visited
  set, so an un-reset loop would dedup rep 2's candidates to nothing
  and time REPS-1 degenerate waves);
* **switch-ladder carry baseline** — the same class-ladder
  ``lax.switch`` dispatch with IDENTITY branches over the same carry:
  pure carry movement through the conditional, the term the class-
  local-carry rework (round 6, checkers/tpu_sortmerge.py make_fetch)
  attacks;
* **HLO category breakdown** — lower-and-compile the one-wave program
  and classify every optimized-HLO instruction with
  :func:`hlo_category` (the same category vocabulary the round-5
  device-trace analysis used: data formatting, carry/slice movement,
  quantization padding, sort, gather, fusion), summing op counts and
  output bytes per category. Bytes of copy/pad/slice traffic are the
  static fingerprint of the wave wall — they move with the carry
  rework even when wall-clock on CPU is noisy.

Used by ``tools/profile_stages.py --wave-wall`` (prints the report
next to the per-stage sums) and pinned on CPU by
tests/test_wavewall.py.

The opcode→category tables live in
:mod:`stateright_tpu.analysis.tables` (round 7) — one table shared
with the kernel-lint rules and the codegen-shape tests, so the
profiler's attribution vocabulary and the lint's carry-movement
pricing cannot drift. :func:`hlo_category` and
:func:`parse_hlo_categories` stay importable from here.
"""

from __future__ import annotations

import time

import numpy as np

from .analysis.tables import (  # noqa: F401 — the shared tables
    hlo_category,
    parse_hlo_categories,
)


def _timed_loop(jit_fn, args) -> float:
    """Best-of-3 seconds for one jitted call (which internally loops
    its reps); the caller divides by the rep count."""
    import jax

    out = jit_fn(*args)
    jax.block_until_ready(out)  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.monotonic()
        jax.block_until_ready(jit_fn(*args))
        best = min(best, time.monotonic() - t0)
    return best


def _ladder_classes(checker):
    from .checkers.tpu_sortmerge import _ladder

    f_ladder = _ladder(
        checker.f_min, checker.frontier_capacity, checker.ladder_step
    )
    v_ladder = _ladder(
        checker.v_min, checker.capacity, checker.v_ladder_step
    )
    return f_ladder, v_ladder


def wave_wall_report(checker, reps: int = 8) -> dict:
    """Measure one wave's wall vs its carry-movement baseline on the
    checker's captured final carry, and statically attribute the
    compiled one-wave program's ops/bytes per HLO category.

    The checker must have run with ``keep_final_carry = True`` (the
    tools/profile_stages.py capture protocol: set a
    ``target_state_count`` so the final carry is a genuine mid-growth
    wave). Returns a dict with ``wave_ms``, ``switch_carry_ms``,
    ``loop_floor_ms``, ``n_rows``, and ``categories``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    carry = getattr(checker, "_final_carry", None)
    if carry is None:
        raise ValueError(
            "run the checker with keep_final_carry=True before "
            "profiling (spawn, set the attribute, join)"
        )
    if not hasattr(checker, "_wave_body"):
        # Programs came from the chunk cache: rebuild (cheap — builds
        # python closures; tracing happens only at jit time below;
        # the wave body itself is independent of the init count).
        checker._build_programs(1)
    body = checker._wave_body

    n_rows = int(np.asarray(carry["n_frontier"]))
    F = checker.frontier_capacity
    fval0 = jnp.arange(F) < jnp.uint32(max(n_rows, 1))
    base = dict(
        carry,
        fval=fval0,
        n_frontier=jnp.uint32(max(n_rows, 1)),
        done=jnp.bool_(False),
        wchunk=jnp.int32(0),
    )

    def checksum(c):
        # Consume element [0] of EVERY carry leaf — returning a lone
        # counter lets XLA dead-code-eliminate the entire wave (the
        # round-5 profiler bug, see tools/profile_stages._timed_raw);
        # the dynamic-offset block writes and the rep-to-rep carry
        # chain keep the full stages live through these folds.
        return sum(
            jnp.sum(jnp.ravel(v)[:1].astype(jnp.uint32))
            for v in c.values()
        )

    def run_waves(c):
        def rep(i, c2):
            # Reset EVERY wave input from the loop-invariant captured
            # carry `c` — frontier/fval, the visited array and its
            # unique count (rep 1 appends its winners; an un-reset
            # chain would dedup all of rep 2's candidates to nothing
            # and bump the visited ladder class, so reps 2..N would
            # time non-representative waves), ebits, and the
            # parent-log offset. Counters (waves/depth/gen) chain
            # through c2; the perturbed frontier cell makes each rep's
            # inputs distinct.
            fr = c["frontier"].at[0, 0].set(
                c["frontier"][0, 0] ^ i.astype(jnp.uint32)
            )
            return body(
                dict(
                    c2,
                    frontier=fr,
                    fval=fval0,
                    ebits=c["ebits"],
                    n_frontier=base["n_frontier"],
                    vkeys=c["vkeys"],
                    new=c["new"],
                    pl_n=c["pl_n"],
                    done=jnp.bool_(False),
                )
            )

        return checksum(lax.fori_loop(0, reps, rep, c))

    f_ladder, _ = _ladder_classes(checker)

    def run_switch_identity(c):
        def rep(i, c2):
            # Same class selection as the engine's body; each branch
            # only bumps the wave counter (keeps the loop sequential),
            # so the measured time is the switch's carry movement.
            f_class = jnp.int32(0)
            for F_i in f_ladder[:-1]:
                f_class = f_class + (
                    c2["n_frontier"] > jnp.uint32(F_i)
                ).astype(jnp.int32)
            return lax.switch(
                f_class,
                [
                    (lambda x, _fc=fc: dict(
                        x, waves=x["waves"] + jnp.uint32(1)
                    ))
                    for fc in range(len(f_ladder))
                ],
                c2,
            )

        return checksum(lax.fori_loop(0, reps, rep, c))

    def run_empty(c):
        return checksum(lax.fori_loop(0, reps, lambda i, c2: c2, c))

    wave_s = _timed_loop(jax.jit(run_waves), (base,))
    sw_s = _timed_loop(jax.jit(run_switch_identity), (base,))
    empty_s = _timed_loop(jax.jit(run_empty), (base,))

    hlo = (
        jax.jit(body)
        .lower(base)
        .compile()
        .as_text()
    )
    categories = parse_hlo_categories(hlo)

    return dict(
        n_rows=n_rows,
        reps=reps,
        wave_ms=wave_s / reps * 1000.0,
        switch_carry_ms=(sw_s - empty_s) / reps * 1000.0,
        loop_floor_ms=empty_s / reps * 1000.0,
        categories=categories,
    )


def merge_stage_estimate(checker, reps: int = 4,
                         unique: int | None = None) -> dict:
    """``merge_kernel`` stage attribution (round 10): time the
    visited-dedup stage in isolation at the checker's converged class
    shapes on synthetic key data — the B-row candidate order sort,
    the streaming membership pass, the winner merge append — next to
    the RETIRED rebuild path (the ``(V_v + B)``-row 3-lane concat
    sort + the ``(V_v + B)``-row winner-position sort) as the A/B
    denominator. Consumed by bench.py, which records each lane's
    ``merge_impl`` and merge-stage share next to its states/sec so
    the pending BENCH_r06 chip run can A/B the kernel with
    trace_diff. (``tools/profile_stages.py`` times the same stage
    set with a different method — REPS-amortized in-jit loops over a
    REAL captured mid-run carry; this estimator trades that fidelity
    for needing nothing but the checker object, which is what lets
    bench attribute every lane cheaply.)

    Synthetic sorted uint32 keys at the real (V_v, B, NF) shapes: the
    dedup stage is key-value-oblivious, so shape-correct random keys
    time the same program the engine runs — no captured carry
    needed, which is what lets bench attribute every lane cheaply.
    ``unique`` overrides the visited fill (defaults to the checker's
    final unique count)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from .ops.merge import compact_winners, member_sorted, merge_sorted

    SENT = 0xFFFFFFFF
    u = unique if unique is not None else checker.unique_state_count()
    _, v_ladder = _ladder_classes(checker)
    V_v = next(v for v in v_ladder if v >= min(u, checker.capacity))
    F = checker.frontier_capacity
    K = checker.encoded.max_actions
    B = min(checker.cand_capacity or F * K, F * K)
    NF = min(F, B)
    impl = checker.merge_impl

    rng = np.random.default_rng(0)

    def synth(n_real, n_total, sort=False):
        v = rng.integers(0, 1 << 62, size=n_real, dtype=np.uint64)
        if sort:
            v = np.sort(v)
        lo = np.full(n_total, SENT, np.uint32)
        hi = np.full(n_total, SENT, np.uint32)
        lo[:n_real] = (v & 0xFFFFFFFF).astype(np.uint32)
        hi[:n_real] = (v >> 32).astype(np.uint32)
        return jnp.asarray(lo), jnp.asarray(hi)

    v_lo, v_hi = synth(min(u, V_v), V_v, sort=True)
    c_lo, c_hi = synth(int(B * 0.7), B)
    w_lo, w_hi = synth(min(int(B * 0.2), NF), NF, sort=True)

    def timed(fn, args):
        def run(*a):
            def rep(i, acc):
                # perturb one input element per rep (loop-invariant
                # bodies hoist) and fold EVERY output (partially
                # consumed stages DCE) — the profile_stages.py
                # discipline.
                a0 = a[0].at[0].set(a[0][0] ^ i.astype(jnp.uint32))
                out = fn(a0, *a[1:])
                return acc + sum(
                    jnp.sum(o.astype(jnp.uint32)) for o in out
                )

            return lax.fori_loop(0, reps, rep, jnp.uint32(0))

        return _timed_loop(jax.jit(run), args) / reps * 1000.0

    def s_sort(cl, ch):
        pos = jnp.arange(1, B + 1, dtype=jnp.uint32)
        return lax.sort((ch, cl, pos), num_keys=2)

    sh, sl, _ = jax.jit(s_sort)(c_lo, c_hi)

    def s_member(vl, vh, ql, qh):
        return (member_sorted(vl, vh, ql, qh, impl=impl),)

    def s_wcompact(sp, nw, sl2, sh2):
        # the order-preserving winner compaction (ops/merge.py,
        # impl-adaptive: O(B) rank scatter on the XLA fallback, one
        # 4-lane B-row sort on Pallas/TPU) — part of the new path's
        # per-wave bill, so the A/B counts it
        return compact_winners(nw, sp, sl2, sh2, NF, impl=impl)

    def s_append(vl, vh, bl, bh):
        return merge_sorted(vl, vh, bl, bh, impl=impl)

    def s_rebuild(vl, vh, cl, ch):
        m_hi = jnp.concatenate([vh, ch])
        m_lo = jnp.concatenate([vl, cl])
        m_pos = jnp.concatenate([
            jnp.zeros(V_v, jnp.uint32),
            jnp.arange(1, B + 1, dtype=jnp.uint32),
        ])
        m_hi, m_lo, m_pos = lax.sort((m_hi, m_lo, m_pos), num_keys=2)
        (nf_pos,) = lax.sort((m_pos,), num_keys=1)
        return m_hi, nf_pos

    isnew = jnp.arange(B, dtype=jnp.uint32) % 5 != 0
    spos = jnp.arange(1, B + 1, dtype=jnp.uint32)

    sort_ms = timed(s_sort, (c_lo, c_hi))
    member_ms = timed(s_member, (v_lo, v_hi, sl, sh))
    wcompact_ms = timed(s_wcompact, (spos, isnew, sl, sh))
    append_ms = timed(s_append, (v_lo, v_hi, w_lo, w_hi))
    rebuild_ms = timed(s_rebuild, (v_lo, v_hi, c_lo, c_hi))
    return dict(
        impl=impl,
        V_v=V_v,
        B=B,
        NF=NF,
        cand_sort_ms=round(sort_ms, 3),
        member_ms=round(member_ms, 3),
        winner_compact_ms=round(wcompact_ms, 3),
        append_ms=round(append_ms, 3),
        dedup_ms=round(
            sort_ms + member_ms + wcompact_ms + append_ms, 3
        ),
        rebuild_sort_ms=round(rebuild_ms, 3),
    )


def format_report(rep: dict, stage_sum_ms: float | None = None) -> str:
    """Human-readable wave-wall report (the tools/ CLI prints this)."""
    lines = [
        f"wave wall: {rep['wave_ms']:.2f} ms/wave over "
        f"{rep['n_rows']} frontier rows "
        f"(loop floor {rep['loop_floor_ms']:.2f} ms, "
        f"identity-switch carry movement "
        f"{rep['switch_carry_ms']:.2f} ms)",
    ]
    if stage_sum_ms is not None:
        lines.append(
            f"  stage compute sum {stage_sum_ms:.2f} ms -> "
            f"out-of-stage wall "
            f"{max(rep['wave_ms'] - stage_sum_ms, 0.0):.2f} ms"
        )
    lines.append(
        f"  {'hlo category':26s} {'ops':>6s} {'MB(out)':>9s}"
    )
    cats = sorted(
        rep["categories"].items(),
        key=lambda kv: -kv[1]["bytes"],
    )
    for name, s in cats:
        lines.append(
            f"  {name:26s} {s['ops']:6d} {s['bytes'] / 1e6:9.2f}"
        )
    return "\n".join(lines)
