"""Checker visitors: callbacks over every evaluated state's path.

Mirrors stateright src/checker/visitor.rs:19-111 (``CheckerVisitor``,
``PathRecorder``, ``StateRecorder``). Plain callables are accepted
wherever a visitor is, matching the reference's closure impl.
"""

from __future__ import annotations

from typing import Callable, Optional, Protocol, runtime_checkable

from .model import Model, State
from .path import Path


@runtime_checkable
class CheckerVisitor(Protocol):
    def visit(self, model: Model, path: Path) -> None: ...


class FnVisitor:
    """Wrap a plain callable as a visitor (visitor.rs:27-31)."""

    def __init__(self, fn: Callable[[Model, Path], None]):
        self._fn = fn

    def visit(self, model: Model, path: Path) -> None:
        self._fn(model, path)


def as_visitor(v) -> Optional[CheckerVisitor]:
    if v is None:
        return None
    if callable(v) and not hasattr(v, "visit"):
        return FnVisitor(v)
    return v


class PathRecorder:
    """Records the set of all visited paths (visitor.rs:47-73).

    Doubles as a replayability oracle in tests: ``Path.from_fingerprints``
    raises on unreplayable traces, which is how symmetry-reduction bugs
    surface (reference dfs.rs:559-563).
    """

    def __init__(self):
        self.paths: set[Path] = set()

    def visit(self, model: Model, path: Path) -> None:
        self.paths.add(path)


class StateRecorder:
    """Records the final state of each visited path (visitor.rs:87-111)."""

    def __init__(self):
        self.states: list[State] = []

    def visit(self, model: Model, path: Path) -> None:
        self.states.append(path.last_state())
