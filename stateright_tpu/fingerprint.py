"""Stable 64-bit structural fingerprinting.

TPU-native analog of the reference's fixed-key stable hasher
(stateright src/lib.rs:329-375): state digests must be identical across
runs, processes, and machines so that unique-state counts and encoded
counterexample paths are reproducible. Python's builtin ``hash`` is
salted per-process, so we implement our own xxhash-style 64-bit mixer
with hard-coded keys.

Two fingerprint domains exist in this framework:

* **Structural fingerprints** (this module): hash arbitrary host state
  objects by canonical traversal. Used by the host checkers (BFS / DFS /
  simulation / on-demand), mirroring ``fingerprint<T: Hash>`` in the
  reference (src/lib.rs:329-337).
* **Vector fingerprints** (:mod:`stateright_tpu.ops.fingerprint`): hash
  fixed-width ``uint32`` state vectors on device. Used by the TPU engine.

Unordered collections (sets / dicts) are hashed order-independently by
sorting element digests before folding, the same trick the reference
uses for ``HashableHashSet``/``HashableHashMap`` (src/util.rs:137-159).
"""

from __future__ import annotations

import dataclasses
import struct
from enum import Enum
from typing import Any

_M64 = (1 << 64) - 1

# Fixed keys: stability across runs is the whole point
# (reference: const KEY1..KEY4, src/lib.rs:362-374).
_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5
_SEED = 0x5EED_5EED_5EED_5EED

# Type tags keep values of different types from colliding
# (1 vs "1" vs (1,) vs {1}).
_T_NONE = 0x01
_T_BOOL = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR = 0x05
_T_BYTES = 0x06
_T_TUPLE = 0x07
_T_LIST = 0x08
_T_SET = 0x09
_T_DICT = 0x0A
_T_DATACLASS = 0x0B
_T_ENUM = 0x0C
_T_OBJECT = 0x0D
_T_NDARRAY = 0x0E


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def _round(acc: int, v: int) -> int:
    acc = (acc + v * _P2) & _M64
    acc = _rotl(acc, 31)
    return (acc * _P1) & _M64


def _avalanche(h: int) -> int:
    h ^= h >> 33
    h = (h * _P2) & _M64
    h ^= h >> 29
    h = (h * _P3) & _M64
    h ^= h >> 32
    return h


def _fold(h: int, tag: int, words: tuple[int, ...] | list[int]) -> int:
    h = _round(h, tag)
    for w in words:
        h = _round(h, w)
    return h


def _hash_value(h: int, obj: Any) -> int:
    """Fold one value into accumulator ``h`` (canonical traversal)."""
    if obj is None:
        return _round(h, _T_NONE)
    if obj is True:
        return _fold(h, _T_BOOL, (1,))
    if obj is False:
        return _fold(h, _T_BOOL, (0,))
    t = type(obj)
    if t is int:
        if 0 <= obj <= _M64:
            return _fold(h, _T_INT, (0, obj))
        sign = 1 if obj < 0 else 0
        mag = -obj if sign else obj
        h = _fold(h, _T_INT, (sign,))
        while mag:
            h = _round(h, mag & _M64)
            mag >>= 64
        return h
    if t is float:
        (bits,) = struct.unpack("<Q", struct.pack("<d", obj))
        return _fold(h, _T_FLOAT, (bits,))
    if t is str:
        data = obj.encode("utf-8")
        h = _fold(h, _T_STR, (len(data),))
        return _fold_bytes(h, data)
    if t is bytes:
        h = _fold(h, _T_BYTES, (len(obj),))
        return _fold_bytes(h, obj)
    if t is tuple or t is list:
        h = _fold(h, _T_TUPLE if t is tuple else _T_LIST, (len(obj),))
        for item in obj:
            h = _hash_value(h, item)
        return h
    if t is frozenset or t is set:
        # Order-independent: sorted element digests (util.rs:137-159).
        digests = sorted(_avalanche(_hash_value(_SEED, item)) for item in obj)
        return _fold(h, _T_SET, (len(obj), *digests))
    if t is dict:
        digests = sorted(
            _avalanche(_hash_value(_hash_value(_SEED, k), v))
            for k, v in obj.items()
        )
        return _fold(h, _T_DICT, (len(obj), *digests))
    if isinstance(obj, Enum):
        h = _fold(h, _T_ENUM, ())
        h = _hash_value(h, type(obj).__qualname__)
        return _hash_value(h, obj.value)
    if dataclasses.is_dataclass(obj):
        h = _fold(h, _T_DATACLASS, ())
        h = _hash_value(h, type(obj).__qualname__)
        for f in dataclasses.fields(obj):
            h = _hash_value(h, getattr(obj, f.name))
        return h
    stable = getattr(obj, "_stable_hash_", None)
    if stable is not None:
        return _fold(h, _T_OBJECT, (stable() & _M64,))
    # Subclass fallbacks (e.g. actor Id subclasses int and must digest
    # identically to the plain int it equals). Conversions bypass
    # overridable __int__/__str__ so the digest matches the value the
    # subclass *equals*, then recurse through the exact-type paths.
    if isinstance(obj, int):
        return _hash_value(h, int.__index__(obj))
    if isinstance(obj, str):
        return _hash_value(h, str.__str__(obj))
    if isinstance(obj, tuple):
        return _hash_value(h, tuple(obj))
    if isinstance(obj, (frozenset, set)):
        return _hash_value(h, frozenset(obj))
    if isinstance(obj, dict):
        import collections

        if isinstance(obj, collections.OrderedDict):
            # OrderedDict equality is order-sensitive; hashing it as an
            # unordered dict would alias unequal states.
            raise TypeError(
                "cannot stably hash OrderedDict (order-sensitive equality); "
                "use a tuple of items or a plain dict"
            )
        return _hash_value(h, dict(obj))
    if hasattr(obj, "__array_interface__") or type(obj).__module__ == "numpy":
        import numpy as np

        arr = np.asarray(obj)
        h = _fold(h, _T_NDARRAY, (len(arr.shape), *arr.shape))
        h = _hash_value(h, str(arr.dtype))
        return _fold_bytes(h, arr.tobytes())
    raise TypeError(
        f"cannot stably hash {type(obj).__qualname__}; implement "
        f"_stable_hash_() or use tuples/frozensets/dataclasses"
    )


def _fold_bytes(h: int, data: bytes) -> int:
    n = len(data)
    full = n - (n % 8)
    for i in range(0, full, 8):
        (w,) = struct.unpack_from("<Q", data, i)
        h = _round(h, w)
    if full < n:
        tail = int.from_bytes(data[full:], "little")
        h = _round(h, tail)
    return h


def stable_hash(obj: Any) -> int:
    """Deterministic 64-bit structural hash of ``obj``."""
    return _avalanche(_hash_value(_SEED, obj))


def fingerprint(obj: Any) -> int:
    """Nonzero stable 64-bit digest of a model state.

    Mirrors ``fingerprint()`` returning ``NonZeroU64`` in the reference
    (src/lib.rs:329-337): zero is reserved as the empty slot marker in
    visited tables, so a zero hash maps to 1.
    """
    return stable_hash(obj) or 1
