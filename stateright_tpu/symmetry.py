"""Symmetry reduction: canonical representatives via sort permutations.

Counterpart of stateright src/checker/{representative,rewrite,
rewrite_plan}.rs. Many models are invariant under permutations of
identical participants (threads, resource managers, servers); mapping
each state to a canonical member of its equivalence class before
visited-set insertion can shrink the explored space dramatically
(2pc with 5 RMs: 8,832 → 665 states, examples/2pc.rs:162-169). The
approach follows "Symmetric Spin" (representative.rs:7-16): sort the
symmetric collection and rewrite every embedded index accordingly.

Usage: give states a ``representative()`` method (the
:class:`Representative` protocol) built from a :class:`RewritePlan`,
then enable ``CheckerBuilder.symmetry()``. Only the DFS and simulation
checkers support symmetry, as in the reference (dfs.rs:300-311,
simulation.rs:252-256) — the visited key is the representative's
fingerprint while the search continues from the original state, so
counterexample paths stay replayable.

On the TPU engine the analogous canonicalization is a per-wave gather:
``reindex`` is ``jnp.take`` and index rewriting is a lookup into the
inverse permutation — see stateright_tpu/ops.
"""

from __future__ import annotations

from typing import Any, Iterable, Protocol, Sequence, TypeVar, runtime_checkable

T = TypeVar("T")


@runtime_checkable
class Representative(Protocol):
    """States supporting canonicalization (representative.rs:65-68)."""

    def representative(self) -> "Representative": ...


class RewritePlan:
    """The permutation that sorts a collection, plus its inverse
    (rewrite_plan.rs:19-39, 81-106).

    ``reindex(xs)`` permutes a parallel collection into the sorted
    order (rewrite_plan.rs:110-123); ``rewrite(i)`` maps an old index
    to its new position — use it for indices *embedded inside* state
    (message fields, maps keyed by id, ...).
    """

    __slots__ = ("perm", "inverse")

    def __init__(self, perm: Sequence[int]):
        self.perm = tuple(perm)
        inverse = [0] * len(self.perm)
        for new_index, old_index in enumerate(self.perm):
            inverse[old_index] = new_index
        self.inverse = tuple(inverse)

    @staticmethod
    def from_values_to_sort(values: Sequence[Any]) -> "RewritePlan":
        """Plan that stably sorts ``values`` (rewrite_plan.rs:81-106)."""
        perm = sorted(range(len(values)), key=lambda i: values[i])
        return RewritePlan(perm)

    def reindex(self, values: Sequence[T]) -> list[T]:
        if len(values) != len(self.perm):
            raise ValueError(
                f"reindex length mismatch: {len(values)} != {len(self.perm)}"
            )
        return [values[i] for i in self.perm]

    def rewrite(self, old_index: int) -> int:
        return self.inverse[old_index]


def sorted_representative_key(values: Iterable[Any]) -> tuple:
    """Helper: canonical key for fully-interchangeable values with no
    embedded indices — just the sorted tuple."""
    return tuple(sorted(values))


def actor_state_representative(state):
    """Canonicalize an ``ActorModelState`` by sorting actor states and
    rewriting ids embedded in the network/timers (model_state.rs:115-132).

    Requires all actors to be interchangeable; models with distinct
    roles (e.g. servers vs clients) should define their own
    representative over the symmetric sub-range instead.
    """
    from dataclasses import replace

    from .actor.model_state import ActorModelState
    from .actor.network import Envelope
    from .fingerprint import stable_hash

    assert isinstance(state, ActorModelState)
    plan = RewritePlan.from_values_to_sort(
        [stable_hash(s) for s in state.actor_states]
    )

    def rewrite_id(id_):
        return type(id_)(plan.rewrite(int(id_)))

    network = state.network
    new_network = type(network).__new__(type(network))
    # Rebuild the network with rewritten envelope endpoints.
    from .actor.network import (
        Ordered,
        UnorderedDuplicating,
        UnorderedNonDuplicating,
    )

    if isinstance(network, UnorderedDuplicating):
        new_network = UnorderedDuplicating(
            frozenset(
                Envelope(rewrite_id(e.src), rewrite_id(e.dst), e.msg)
                for e in network.envelopes
            )
        )
    elif isinstance(network, UnorderedNonDuplicating):
        new_network = UnorderedNonDuplicating(
            {
                Envelope(rewrite_id(e.src), rewrite_id(e.dst), e.msg): n
                for e, n in network.counts.items()
            }
        )
    elif isinstance(network, Ordered):
        new_network = Ordered(
            {
                (rewrite_id(src), rewrite_id(dst)): msgs
                for (src, dst), msgs in network.flows.items()
            }
        )
    else:
        raise TypeError(f"unknown network type {type(network)!r}")

    return replace(
        state,
        actor_states=tuple(plan.reindex(state.actor_states)),
        timers_set=tuple(plan.reindex(state.timers_set)),
        crashed=tuple(plan.reindex(state.crashed)),
        network=new_network,
    )
