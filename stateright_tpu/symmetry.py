"""Symmetry reduction: canonical representatives via sort permutations.

Counterpart of stateright src/checker/{representative,rewrite,
rewrite_plan}.rs. Many models are invariant under permutations of
identical participants (threads, resource managers, servers); mapping
each state to a canonical member of its equivalence class before
visited-set insertion can shrink the explored space dramatically
(2pc with 5 RMs: 8,832 → 665 states, examples/2pc.rs:162-169). The
approach follows "Symmetric Spin" (representative.rs:7-16): sort the
symmetric collection and rewrite every embedded index accordingly.

Usage: give states a ``representative()`` method (the
:class:`Representative` protocol) built from a :class:`RewritePlan`,
then enable ``CheckerBuilder.symmetry()``. The host DFS and simulation
checkers take any such callable, as in the reference (dfs.rs:300-311,
simulation.rs:252-256) — the visited key is the representative's
fingerprint while the search continues from the original state, so
counterexample paths stay replayable.

On the TPU wave engines the analogous canonicalization is the
GATHER-FREE vectorized kernel in stateright_tpu/ops/canonical.py: an
encoding declares a ``DeviceRewriteSpec`` (the strided bit-field
layout of its interchangeable limb group) and the engines canonicalize
every candidate block before the fingerprint fold. One caveat the
device path surfaces that the host default hides: a representative
that sorts on a strict SUBSET of the per-member state (e.g. 2pc's
rm_state-only sort) is not constant on orbits, so the reduced visited
count depends on search order — the reference's pinned 665 for 2pc
rm=5 is a DFS-order artifact (a BFS with the same representative
visits 508). The device spec therefore sorts on the FULL per-member
tuple, a perfect canonicalizer whose count (314 for 2pc rm=5) is
order-independent and agrees between the wave BFS and a host DFS
given the matching ``representative_full`` oracle.

Since round 21 the full-tuple requirement is not prose: the reduction
soundness analyzer (stateright_tpu/analysis/soundness.py) proves it
statically per declared spec — a partial sort key fails its
``orbit-structure`` obligation and the engines refuse the spec at
spawn (the certificate gate), so the 665-style order-dependence
cannot re-enter through a new encoding.
"""

from __future__ import annotations

import enum
from dataclasses import fields, is_dataclass
from dataclasses import replace as dc_replace
from typing import Any, Iterable, Protocol, Sequence, TypeVar, runtime_checkable

#: lazily bound by rewrite_value (import cycle: utils.hashable is free of
#: cycles, actor.base imports nothing from here — but keep symmetry
#: importable without the actor package)
_ID_TYPE = None
_HASHABLE_TYPES = None

T = TypeVar("T")


@runtime_checkable
class Representative(Protocol):
    """States supporting canonicalization (representative.rs:65-68)."""

    def representative(self) -> "Representative": ...


class RewritePlan:
    """The permutation that sorts a collection, plus its inverse
    (rewrite_plan.rs:19-39, 81-106).

    ``reindex(xs)`` permutes a parallel collection into the sorted
    order (rewrite_plan.rs:110-123); ``rewrite(i)`` maps an old index
    to its new position — use it for indices *embedded inside* state
    (message fields, maps keyed by id, ...).
    """

    __slots__ = ("perm", "inverse")

    def __init__(self, perm: Sequence[int]):
        self.perm = tuple(perm)
        inverse = [0] * len(self.perm)
        for new_index, old_index in enumerate(self.perm):
            inverse[old_index] = new_index
        self.inverse = tuple(inverse)

    @staticmethod
    def from_values_to_sort(values: Sequence[Any]) -> "RewritePlan":
        """Plan that stably sorts ``values`` (rewrite_plan.rs:81-106)."""
        perm = sorted(range(len(values)), key=lambda i: values[i])
        return RewritePlan(perm)

    def reindex(self, values: Sequence[T]) -> list[T]:
        if len(values) != len(self.perm):
            raise ValueError(
                f"reindex length mismatch: {len(values)} != {len(self.perm)}"
            )
        return [values[i] for i in self.perm]

    def rewrite(self, old_index: int) -> int:
        return self.inverse[old_index]


def sorted_representative_key(values: Iterable[Any]) -> tuple:
    """Helper: canonical key for fully-interchangeable values with no
    embedded indices — just the sorted tuple."""
    return tuple(sorted(values))


def rewrite_value(value: Any, plan: RewritePlan) -> Any:
    """Recursively rewrite every embedded :class:`~stateright_tpu.actor.Id`
    inside ``value`` — the counterpart of the reference's ``Rewrite``
    trait impls (rewrite.rs:24-163): scalars pass through, containers
    and (frozen) dataclasses recurse, ``Id``s map through the plan.

    Soundness note (shared with the reference): an actor id stored as a
    PLAIN int is indistinguishable from data and passes through
    unrewritten — models must use the ``Id`` type for embedded ids, as
    the reference must use its ``Id`` newtype. Types this function does
    not understand raise rather than silently passing through; give
    them a ``_rewrite_ids_(plan)`` method.
    """
    global _ID_TYPE, _HASHABLE_TYPES
    if _ID_TYPE is None:
        from .actor.base import Id

        _ID_TYPE = Id
    Id = _ID_TYPE

    hook = getattr(value, "_rewrite_ids_", None)
    if hook is not None:
        return hook(plan)
    if isinstance(value, Id):
        return Id(plan.rewrite(int(value)))
    if isinstance(value, enum.Enum) or isinstance(
        value, (bool, int, float, complex, str, bytes, type(None))
    ):
        return value
    if is_dataclass(value) and not isinstance(value, type):
        return dc_replace(
            value,
            **{
                f.name: rewrite_value(getattr(value, f.name), plan)
                for f in fields(value)
            },
        )
    if isinstance(value, tuple):
        return tuple(rewrite_value(v, plan) for v in value)
    if isinstance(value, list):
        return [rewrite_value(v, plan) for v in value]
    if isinstance(value, (set, frozenset)):
        return frozenset(rewrite_value(v, plan) for v in value)
    if isinstance(value, dict):
        return {
            rewrite_value(k, plan): rewrite_value(v, plan)
            for k, v in value.items()
        }
    if _HASHABLE_TYPES is None:
        from .utils.hashable import HashableMap, HashableSet

        globals()["_HASHABLE_TYPES"] = (HashableMap, HashableSet)
    HashableMap, HashableSet = _HASHABLE_TYPES
    if isinstance(value, HashableMap):
        return HashableMap(
            {
                rewrite_value(k, plan): rewrite_value(v, plan)
                for k, v in value.items()
            }
        )
    if isinstance(value, HashableSet):
        return HashableSet(rewrite_value(v, plan) for v in value)
    raise TypeError(
        f"cannot rewrite actor ids inside {type(value).__name__!r}; "
        "generic actor symmetry would silently collapse distinct states "
        "— implement _rewrite_ids_(plan) on the type or use a "
        "model-specific representative"
    )


def actor_state_representative(state):
    """Canonicalize an ``ActorModelState`` by sorting actor states and
    rewriting ids embedded EVERYWHERE — actor states, message payloads,
    network endpoints, timers, and history — mirroring the reference's
    recursive ``Rewrite`` (model_state.rs:115-132, rewrite.rs:146-163,
    network.rs:311-324).

    Requires all actors to be interchangeable; models with distinct
    roles (e.g. servers vs clients) should define their own
    representative over the symmetric sub-range instead.
    """
    from dataclasses import replace

    from .actor.model_state import ActorModelState
    from .actor.network import (
        Envelope,
        Ordered,
        UnorderedDuplicating,
        UnorderedNonDuplicating,
    )
    from .fingerprint import stable_hash

    assert isinstance(state, ActorModelState)
    plan = RewritePlan.from_values_to_sort(
        [stable_hash(s) for s in state.actor_states]
    )

    def rw(value):
        return rewrite_value(value, plan)

    network = state.network
    if isinstance(network, UnorderedDuplicating):
        new_network = UnorderedDuplicating(
            frozenset(
                Envelope(rw(e.src), rw(e.dst), rw(e.msg))
                for e in network.envelopes
            )
        )
    elif isinstance(network, UnorderedNonDuplicating):
        new_network = UnorderedNonDuplicating(
            {
                Envelope(rw(e.src), rw(e.dst), rw(e.msg)): n
                for e, n in network.counts.items()
            }
        )
    elif isinstance(network, Ordered):
        new_network = Ordered(
            {
                (rw(src), rw(dst)): tuple(rw(m) for m in msgs)
                for (src, dst), msgs in network.flows.items()
            }
        )
    else:
        raise TypeError(f"unknown network type {type(network)!r}")

    return replace(
        state,
        actor_states=tuple(rw(s) for s in plan.reindex(state.actor_states)),
        timers_set=tuple(
            frozenset(rw(t) for t in ts)
            for ts in plan.reindex(state.timers_set)
        ),
        crashed=tuple(plan.reindex(state.crashed)),
        network=new_network,
        history=rw(state.history),
    )
