"""Checkpoint/resume with elastic re-shard: durability for the wave
engines.

The memory ledger (memplan.py) already declares exactly which buffers
constitute a run — every engine's chunk carry is a named pytree the
seed program's ``eval_shape`` spec pins. This module is the other half
ROADMAP direction 1(c) names: *serialize* that declared carry at the
existing per-chunk sync (the stats readback already blocked — no new
device syncs, just a piggybacked download at the same seam), and
*restore* it so a preemption, OOM, or crash costs one chunk of
progress instead of the whole search (the elastic/preemptible
execution framing of arXiv:1203.6806's checking-as-a-cloud-service).

**Snapshot format.** One file: an ``.npz`` container holding every
chunk-carry leaf (visited ``vkeys``/hash tables, frontier, ebits, the
parent log, counters, and the cumulative discovery lanes) plus a JSON
manifest under the reserved ``__manifest__`` entry — version, git
SHA, encoding fingerprint, engine family, shard count, per-shard
capacities, wave/depth/unique at capture, the persisted auto-budget
state, and a per-buffer CRC-32. Writes are atomic: temp file →
flush → fsync → ``os.replace`` — a crash mid-write leaves the
previous snapshot intact, and a genuinely torn file (truncation,
bit rot) fails the zip/CRC checks and raises
:class:`SnapshotCorruptError` on load. No pickle anywhere
(``allow_pickle`` stays False): a snapshot is data, not code.

**Resume** (:func:`resume_from`):

* SAME configuration — direct upload: every leaf shape-checked
  against the current seed program's ``eval_shape`` spec, trace-gated
  leaves (the wave/shard logs) synthesized to match the resuming
  run's tracer state, sharded leaves placed with the engine's own
  ``PartitionSpec``\\ s;
* DIFFERENT shard count / capacity (the sort-merge family) — elastic
  **re-shard**: per-shard visited prefixes, frontier blocks, and
  parent-log entries are concatenated and re-routed host-side through
  the exact (owner, fp) ordering the mesh wave's routing sort uses
  (owner = ``fp_lo % S``, keys ordered ``(hi, lo)`` — the
  ``lax.sort`` seam of parallel/engine_sortmerge.py, as
  ``np.lexsort``), then re-uploaded at the new layout. Shard count
  becomes a resume-time choice, not a run-time constant; single-chip
  ⇄ sharded conversions ride the same path (single-chip is the
  S=1 layout). The hash-table family resumes same-config only
  (re-inserting an open-addressed table is a different primitive) and
  refuses loudly otherwise.

Staleness is refused, never guessed around: a manifest whose encoding
fingerprint disagrees with the target checker, or whose git SHA
differs from HEAD (override with ``allow_sha_mismatch=True`` when you
know the carry layout didn't change), raises
:class:`SnapshotStaleError` — the fault-injection matrix
(stateright_tpu/faultinject.py + tools/crash_matrix.py) pins all four
failure modes on recover-or-refuse-loudly, none on silent wrong
answers.

**Supervision** (:func:`supervised_run`): engines route ``_run``
through here. With checkpointing configured, a failed chunk — device
error, injected fault, OOM, watchdog hang — retries from the last
snapshot with bounded exponential backoff instead of dying; repeated
OOMs degrade the sort-merge engines to their CHUNKED memory-lean
classes (``_degrade_memory_lean``) before the next attempt. Engine
overflow errors are NOT supervised: the auto-budget retry
(tpu_sortmerge.py) owns those, one layer out.

**Degrade-and-continue** (the round-17 policy layer): every
supervised failure is CLASSIFIED by a :class:`FailurePolicy`
(:func:`classify_failure` — transient / oom / hang / shard_fault,
from the exception and the health layer's straggler evidence), and a
fault that persists on the same shard escalates — under
``degrade_on_fault`` — to an automatic elastic degrade: the shard is
dropped from the mesh and the last snapshot re-shards onto the
survivors through the same (owner, fp) seam, so a dead chip costs
capacity, not the run. :func:`watchdog_deadline` derives the
hung-dispatch watchdog's per-chunk deadline (checkers/tpu.py) from
the run's own measured chunk walls; a breach is a supervised
``hang`` that recovers from the snapshot or — when the runtime
can't be cancelled — refuses loudly with the latency attribution.
"""

from __future__ import annotations

import json
import os
import time
import warnings
import zlib
from typing import Any, Optional

import numpy as np

SNAPSHOT_VERSION = 1

_SENT = 0xFFFFFFFF

#: trace-gated carry leaves resume may synthesize (zeros) when the
#: snapshot and the resuming run disagree on tracer state or
#: waves_per_sync — their content is telemetry, rewritten inside the
#: chunk before any row is read.
_SYNTH_LEAVES = frozenset({"wlog", "slog", "swave", "wv_pairs",
                           "wv_canon", "pstash"})

#: tiered-mode carry leaves a snapshot may carry on top of the
#: untiered spec (the deferred-commit staging of stateright_tpu/
#: tier.py). Resume FOLDS them host-side — the pending wave is
#: committed through the same cold-membership pass the device commit
#: would have run — so the restored carry is always the untiered
#: shape and re-shard sees only confirmed state.
_TIER_LEAVES = ("pend_keys", "pend_par", "pend_n", "pend_valid",
                "n_hot", "h_loc", "pstash")


def auto_cadence(snapshot_sec: float, chunk_sec: float,
                 target: float = 0.05, lo: int = 1,
                 hi: int = 256) -> int:
    """``--checkpoint-every=auto``: the cadence (chunks per snapshot)
    that keeps checkpoint overhead under ``target`` of run wall,
    from the two walls the run itself measures — the snapshot write
    wall and the per-chunk wall. Every N chunks, one snapshot costs
    ``snapshot_sec / (N * chunk_sec)`` relative overhead, so the
    smallest N meeting the target is
    ``ceil(snapshot_sec / (target * chunk_sec))``, clamped to
    ``[lo, hi]`` (hi bounds the progress lost to a crash; lo is
    every-chunk). Degenerate inputs answer conservatively: an
    unmeasured snapshot wall checkpoints every chunk, an unmeasured
    chunk wall checkpoints at the cap."""
    import math

    if not snapshot_sec or snapshot_sec <= 0:
        return lo
    if not chunk_sec or chunk_sec <= 0:
        return hi
    n = math.ceil(snapshot_sec / (target * chunk_sec))
    return max(lo, min(hi, int(n)))


class SnapshotError(RuntimeError):
    """Base of every named checkpoint/resume refusal."""


class SnapshotCorruptError(SnapshotError):
    """The snapshot file is torn or corrupt (failed zip read, missing
    buffer, or a per-buffer CRC mismatch)."""


class SnapshotStaleError(SnapshotError):
    """The manifest doesn't match the resuming checker (wrong encoding
    fingerprint or wrong git SHA)."""


class SnapshotIncompatibleError(SnapshotError):
    """The snapshot can't be restored into this engine configuration
    (family mismatch, track_paths flip, a target capacity too small
    for the carried state, or a hash-family re-shard)."""


# -- identity -------------------------------------------------------------


def encoding_fingerprint(checker) -> str:
    """The stable identity of what a snapshot's carry MEANS: the
    encoding (class, declared cache key, width, action count) plus the
    property list and eventually-bit seed. Two checkers with equal
    fingerprints interpret the same carry identically; anything else
    is a stale snapshot, not a resumable one."""
    enc = checker.encoded
    key_fn = getattr(enc, "cache_key", None)
    ident = repr(key_fn()) if key_fn is not None else ""
    props = tuple(
        (p.name, p.expectation.name)
        for p in checker.model.properties()
    )
    return (
        f"{type(enc).__name__}/{ident}/W{enc.width}/K{enc.max_actions}"
        f"/props{props!r}/ebits{checker._eventually_bits_init()}"
    )


def _git_sha() -> Optional[str]:
    from .artifacts import _git_sha as sha, repo_root

    return sha(repo_root())


def _engine_kind(checker) -> str:
    return "sharded" if getattr(checker, "mesh", None) is not None \
        else "single"


# -- file format ----------------------------------------------------------


def _write_file(path: str, manifest: dict, buffers: dict) -> None:
    """Atomic snapshot write: temp + fsync + rename. The manifest
    rides the same npz as a reserved uint8 entry so one rename commits
    both (a separate sidecar file could tear independently), and
    carries its own CRC-32 (``manifest_crc32``) — buffer bytes are
    covered by the per-buffer checksums, this covers the metadata
    region itself."""
    tmp = f"{path}.tmp.{os.getpid()}"
    manifest = dict(manifest)
    manifest.pop("manifest_crc32", None)
    manifest["manifest_crc32"] = (
        zlib.crc32(json.dumps(manifest, sort_keys=True).encode())
        & 0xFFFFFFFF
    )
    m = json.dumps(manifest, sort_keys=True).encode()
    try:
        with open(tmp, "wb") as fh:
            np.savez(
                fh,
                __manifest__=np.frombuffer(m, dtype=np.uint8),
                **buffers,
            )
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _read_raw(path: str) -> tuple[dict, dict]:
    """Parse the container WITHOUT checksum verification (the
    stale-manifest injection helper rewrites manifests through this;
    everyone else goes through :func:`load_snapshot`)."""
    if not os.path.exists(path):
        raise SnapshotError(f"no snapshot at {path}")
    try:
        with np.load(path, allow_pickle=False) as z:
            if "__manifest__" not in z.files:
                raise SnapshotCorruptError(
                    f"{path}: no manifest entry — not a snapshot, or "
                    "torn before the manifest landed"
                )
            manifest = json.loads(bytes(z["__manifest__"].tobytes()))
            buffers = {
                k: np.array(z[k]) for k in z.files
                if k != "__manifest__"
            }
    except SnapshotError:
        raise
    except Exception as exc:
        # zipfile.BadZipFile, ValueError, OSError, EOFError, json
        # decode errors — every torn-file shape lands here, named.
        raise SnapshotCorruptError(
            f"{path}: torn or corrupt snapshot ({type(exc).__name__}: "
            f"{exc})"
        ) from exc
    return manifest, buffers


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def load_snapshot(path: str) -> tuple[dict, dict]:
    """Read + verify a snapshot: container integrity, version, and the
    manifest's per-buffer CRC-32 over the loaded bytes. Raises the
    named errors; never returns partially-verified data."""
    manifest, buffers = _read_raw(path)
    declared_crc = manifest.pop("manifest_crc32", None)
    actual_crc = (
        zlib.crc32(json.dumps(manifest, sort_keys=True).encode())
        & 0xFFFFFFFF
    )
    if declared_crc != actual_crc:
        raise SnapshotCorruptError(
            f"{path}: manifest failed its CRC-32 check (bit rot or "
            "a hand edit — the metadata region is checksummed too)"
        )
    version = manifest.get("version")
    if version != SNAPSHOT_VERSION:
        raise SnapshotIncompatibleError(
            f"{path}: snapshot version {version} != reader "
            f"{SNAPSHOT_VERSION}"
        )
    declared = manifest.get("buffers") or {}
    for name, meta in declared.items():
        if name not in buffers:
            raise SnapshotCorruptError(
                f"{path}: buffer {name!r} declared in manifest but "
                "missing from the container"
            )
        arr = buffers[name]
        if list(arr.shape) != list(meta["shape"]) \
                or str(arr.dtype) != meta["dtype"]:
            raise SnapshotCorruptError(
                f"{path}: buffer {name!r} is {arr.dtype}"
                f"{list(arr.shape)}, manifest declares {meta['dtype']}"
                f"{meta['shape']}"
            )
        if _crc(arr) != int(meta["crc32"]):
            raise SnapshotCorruptError(
                f"{path}: buffer {name!r} failed its CRC-32 check "
                "(bit rot or a torn write)"
            )
    for name in buffers:
        if name not in declared:
            raise SnapshotCorruptError(
                f"{path}: undeclared buffer {name!r} in the container"
            )
    return manifest, buffers


# -- capture --------------------------------------------------------------


def write_snapshot(checker, carry, path: str, *, chunk: int,
                   wave: int, depth: int, unique: int,
                   tier=None, tier_plog=None) -> dict:
    """Serialize one chunk carry to an atomic on-disk snapshot. Called
    at the existing per-chunk sync (checkers/tpu.py) — the stats
    readback already blocked, so the carry download adds transfer, not
    a sync point. Returns the manifest; emits a ``checkpoint``
    telemetry event (which the tracer→metrics bridge folds into
    ``stpu_checkpoints_total`` / ``stpu_checkpoint_bytes_total`` —
    snapshot cadence and size are live signals on ``GET /.metrics``).

    ``tier`` (tiered-visited-set runs, stateright_tpu/tier.py) is the
    engine's :class:`~stateright_tpu.tier.ColdStore`: its sorted
    immutable runs ride the same npz as ``tier_run{shard}_{i}_lo/hi``
    buffers and the manifest gains a ``tier`` block (hot ceiling,
    spill count, per-run row counts) — a snapshot of a tiered run is
    the whole visited set, both tiers."""
    from . import telemetry

    t0 = time.monotonic()
    buffers = {k: np.asarray(v) for k, v in carry.items()}
    tier_block = None
    if tier is not None:
        runs = tier.snapshot_runs()
        run_rows = []
        for s, shard in enumerate(runs):
            rows_s = []
            for i, (lo, hi) in enumerate(shard):
                buffers[f"tier_run{s}_{i}_lo"] = lo
                buffers[f"tier_run{s}_{i}_hi"] = hi
                rows_s.append(int(lo.size))
            run_rows.append(rows_s)
        plog_host = 0
        if tier_plog:
            # the host-drained parent-log accumulation (tiered runs
            # rewind the device log's cursor — these rows exist only
            # host-side and must survive the process)
            blk = np.concatenate(
                [np.asarray(b, np.uint32) for b in tier_plog], axis=1
            )
            buffers["tier_plog"] = blk
            plog_host = int(blk.shape[1])
        tier_block = dict(
            hot_rows=int(getattr(checker, "_tier_hot_ceiling", 0)
                         or 0) or None,
            max_runs=int(tier.max_runs),
            spills=int(tier.spills),
            run_rows=run_rows,
            plog_host_rows=plog_host,
            cold_rows_total=int(tier.rows()),
            cold_bytes_total=int(tier.bytes()),
        )
    total = int(sum(b.nbytes for b in buffers.values()))
    manifest = dict(
        version=SNAPSHOT_VERSION,
        created_at=time.time(),
        git_sha=_git_sha(),
        engine=type(checker).__name__,
        family=checker._checkpoint_family(),
        kind=_engine_kind(checker),
        encoding=encoding_fingerprint(checker),
        width=int(checker.encoded.width),
        n_shards=int(getattr(checker, "n_shards", 1)),
        capacity=int(checker.capacity),
        frontier_capacity=int(checker.frontier_capacity),
        track_paths=bool(checker.track_paths),
        waves_per_sync=int(checker.waves_per_sync),
        chunk=int(chunk),
        wave=int(wave),
        depth=int(depth),
        unique=int(unique),
        budget=dict(
            cand_capacity=checker.cand_capacity,
            pair_width=getattr(checker, "pair_width", None),
            auto_budget=bool(getattr(checker, "auto_budget", False)),
        ),
        merge_impl=getattr(checker, "merge_impl", None),
        tier=tier_block,
        snapshot_bytes=total,
        buffers={
            k: dict(shape=list(b.shape), dtype=str(b.dtype),
                    crc32=_crc(b))
            for k, b in buffers.items()
        },
    )
    _write_file(path, manifest, buffers)
    checker._last_snapshot = path
    telemetry.emit(
        "checkpoint", path=os.path.basename(path), chunk=int(chunk),
        wave=int(wave), depth=int(depth), unique=int(unique),
        snapshot_bytes=total,
        wall_sec=round(time.monotonic() - t0, 6),
    )
    return manifest


def retain_final_snapshot(checker, path: str) -> Optional[dict]:
    """The warm-start half of the resident service's incremental
    re-check (ROADMAP direction 4, stateright_tpu/serve.py): package a
    COMPLETED device run's final chunk carry as an ordinary snapshot.
    The carry of a finished search holds the whole visited set, the
    parent forest, the discovery lanes, and ``done=True`` — so a later
    checker whose :func:`encoding_fingerprint` matches can
    :func:`resume_from` it and settle in one chunk with zero new waves
    dispatched, counts bit-identical to the cold run (the same
    validation/re-shard seam applies: an EDITED model changes the
    fingerprint and refuses, which is the service's cue to run cold).

    Requires the run to have kept its final carry
    (``checker.keep_final_carry = True`` before join — the existing
    tools/profile_stages.py capture hook). Returns the manifest, or
    None when there is nothing retainable: no final carry or a run
    that raised. A TIERED run retains BOTH tiers — the snapshot
    format already carries the cold runs (``tier_run*`` buffers) and
    the host-drained parent log beside the device carry, so a tiered
    re-check warm-starts exactly like a flat one (the forced-spill
    regression test settles with zero new waves). Only a tiered run
    whose ColdStore is gone (spills recorded but ``_tier_state``
    cleared) still refuses: retaining the device carry alone would
    warm-start from a subset and silently re-explore.
    """
    carry = getattr(checker, "_final_carry", None)
    if carry is None or checker._run_error is not None:
        return None
    metrics = getattr(checker, "metrics", None) or {}
    tier = getattr(checker, "_tier_state", None)
    if metrics.get("tier_spills") and tier is None:
        return None
    lat = getattr(checker, "_lat", None) or {}
    return write_snapshot(
        checker, carry, path,
        chunk=int(lat.get("chunks") or 0),
        wave=int(metrics.get("waves") or 0),
        depth=int(checker._max_depth),
        unique=int(checker._unique_states),
        tier=tier,
        tier_plog=getattr(checker, "_tier_plog_rows", None),
    )


# -- resume ---------------------------------------------------------------


def resume_from(checker, path: str, *,
                allow_sha_mismatch: bool = False) -> dict:
    """Validate a snapshot against ``checker`` and stage it for the
    next run: the engine's ``_run_attempt`` builds its initial carry
    from the staged buffers instead of the seed program. Re-shards
    through the (owner, fp) seam when the sort-merge target's layout
    differs; refuses loudly (named errors) on corruption, staleness,
    or an incompatible target. Returns the manifest."""
    manifest, buffers = load_snapshot(path)

    enc_fp = encoding_fingerprint(checker)
    if manifest.get("encoding") != enc_fp:
        raise SnapshotStaleError(
            f"{path}: snapshot encodes "
            f"{manifest.get('encoding')!r}, this checker expects "
            f"{enc_fp!r} — a snapshot is only resumable into the "
            "same model/encoding"
        )
    head = _git_sha()
    snap_sha = manifest.get("git_sha")
    if (snap_sha is not None and head is not None
            and snap_sha != head and not allow_sha_mismatch):
        raise SnapshotStaleError(
            f"{path}: snapshot was written at git {snap_sha[:12]}, "
            f"HEAD is {head[:12]} — the carry layout may have "
            "changed; pass allow_sha_mismatch=True (CLI: "
            "--resume-any-sha) to resume anyway"
        )
    family = checker._checkpoint_family()
    if manifest.get("family") != family:
        raise SnapshotIncompatibleError(
            f"{path}: snapshot is from the {manifest.get('family')!r} "
            f"engine family, this checker is {family!r} — the visited "
            "structures are not interconvertible"
        )
    if bool(manifest.get("track_paths")) != bool(checker.track_paths):
        raise SnapshotIncompatibleError(
            f"{path}: snapshot track_paths="
            f"{manifest.get('track_paths')}, checker "
            f"track_paths={checker.track_paths} — the parent log "
            "exists on one side only"
        )

    same_layout = (
        int(manifest.get("n_shards", 1))
        == int(getattr(checker, "n_shards", 1))
        and int(manifest.get("capacity")) == int(checker.capacity)
        and int(manifest.get("frontier_capacity"))
        == int(checker.frontier_capacity)
        and manifest.get("kind") == _engine_kind(checker)
    )
    # Tiered snapshots (stateright_tpu/tier.py): fold the deferred-
    # commit staging host-side — the pending wave commits through the
    # SAME cold-membership verdict the device commit would have run —
    # so everything downstream (direct upload, the (owner, fp)
    # re-shard) sees only confirmed, untiered-shaped state; the cold
    # runs then re-route by the same owner seam.
    tier_m = manifest.get("tier")
    checker._tier_resume_state = None
    hot_src = None
    cold_src = None
    if tier_m:
        buffers, cold_src, hot_src, plog_host = _fold_tier_snapshot(
            checker, manifest, buffers, tier_m
        )
    if not same_layout:
        if family == "sortmerge":
            buffers = reshard_sortmerge(
                manifest, buffers, checker, visited_counts=hot_src
            )
        elif (family == "hash"
                and manifest.get("kind") == "sharded"
                and _engine_kind(checker) == "sharded"):
            # sharded-hash -> sharded-hash: the per-shard tables
            # rebuild host-side by re-INSERTION of the snapshot's key
            # set through the same (owner, fp) route the sort-merge
            # re-shard uses (the degrade path needs this so the hash
            # family can drop a shard too).
            buffers = reshard_hash(manifest, buffers, checker)
        else:
            raise SnapshotIncompatibleError(
                f"{path}: shard/capacity re-layout (snapshot "
                f"S={manifest.get('n_shards')} "
                f"kind={manifest.get('kind')} "
                f"C={manifest.get('capacity')}, target "
                f"S={getattr(checker, 'n_shards', 1)} "
                f"kind={_engine_kind(checker)} "
                f"C={checker.capacity}) is supported on the "
                "sort-merge family (all directions) and on "
                "sharded-hash -> sharded-hash only — the hash "
                "family's single-chip ⇄ sharded conversions are "
                "not implemented; resume on the original kind, or "
                "use the sort-merge family for fully elastic layouts"
            )
    if tier_m:
        buffers = _route_tier_target(
            checker, path, manifest, buffers, cold_src, hot_src,
            same_layout, plog_host,
        )

    checker._resume = (manifest, buffers)
    checker._resume_path = path
    # remembered for the supervisor's retry re-stage: a run resumed
    # with allow_sha_mismatch must recover under the same policy
    checker._resume_allow_sha = allow_sha_mismatch
    return manifest


def _fold_tier_snapshot(checker, manifest: dict, buffers: dict,
                        tier_m: dict):
    """Restore a tiered snapshot's host state and COMMIT its pending
    wave host-side: rebuild the :class:`~stateright_tpu.tier.ColdStore`
    from the serialized runs, run the batched sort-merge membership
    over the staged provisional winners (exactly the verdict the next
    device dispatch would have received as its keep mask), and fold
    the survivors into the carry — hot-prefix merge, frontier
    compaction, parent-log append, counters — so the buffers leave
    here as a valid UNTIERED carry at the source layout whose visited
    prefix holds only the hot tier. Returns ``(buffers, cold_store,
    hot_counts_per_source_shard, host_plog_block_or_None)``."""
    from .tier import ColdStore

    W = int(manifest["width"])
    track_paths = bool(manifest["track_paths"])
    S_a = int(manifest.get("n_shards", 1))
    C_a = int(manifest["capacity"])
    F_a = int(manifest["frontier_capacity"])
    kind_a = manifest.get("kind", "single")
    C_pad_a = C_a + F_a
    L_a = C_a + F_a if track_paths else 0

    run_rows = tier_m.get("run_rows") or []
    per_shard_runs = []
    for s in range(S_a):
        shard = []
        rows_s = run_rows[s] if s < len(run_rows) else []
        for i, n in enumerate(rows_s):
            lo = buffers.pop(f"tier_run{s}_{i}_lo")
            hi = buffers.pop(f"tier_run{s}_{i}_hi")
            if int(n) != int(lo.size):
                raise SnapshotCorruptError(
                    f"tier run {s}/{i}: manifest declares {n} rows, "
                    f"buffer has {lo.size}"
                )
            shard.append((lo, hi))
        per_shard_runs.append(shard)
    cold = ColdStore.from_runs(
        per_shard_runs,
        max_runs=int(tier_m.get("max_runs") or 8),
        spills=int(tier_m.get("spills") or 0),
    )

    plog_host = buffers.pop("tier_plog", None)
    # pop the tiered-mode staging leaves (absent only if the snapshot
    # landed before the first tiered dispatch)
    staged = {k: buffers.pop(k) for k in _TIER_LEAVES
              if k in buffers}
    if kind_a == "sharded":
        hot = np.atleast_1d(
            staged.get("h_loc", buffers["u_loc"])
        ).astype(np.int64).reshape(-1).copy()
    else:
        h = staged.get("n_hot", buffers["new"])
        hot = np.array([int(h)], np.int64)

    pend_valid = bool(staged.get("pend_valid", False))
    if pend_valid:
        pend_n = np.atleast_1d(
            staged["pend_n"]
        ).astype(np.int64).reshape(-1)
        pend_keys = staged["pend_keys"]
        pend_par = staged.get("pend_par")
        vkeys = buffers["vkeys"]
        frontier = buffers["frontier"]
        ebits = buffers["ebits"]
        fval = buffers["fval"]
        plog = buffers.get("plog")
        pl_n = (np.atleast_1d(buffers["pl_n"]).astype(np.int64)
                .reshape(-1).copy() if track_paths else None)
        n_loc = np.zeros(S_a, np.int64)
        confs = np.zeros(S_a, np.int64)
        for s in range(S_a):
            n_p = int(pend_n[s]) if s < pend_n.size else 0
            fb = s * F_a
            frontier_blk = frontier[:, fb:fb + F_a].copy()
            eb_blk = ebits[fb:fb + F_a].copy()
            frontier[:, fb:fb + F_a] = 0
            ebits[fb:fb + F_a] = 0
            fval[fb:fb + F_a] = False
            if n_p == 0:
                continue
            sl = slice(fb, fb + n_p)
            klo = np.asarray(pend_keys[0, sl])
            khi = np.asarray(pend_keys[1, sl])
            keep = ~cold.member(s, klo, khi)
            conf = int(keep.sum())
            confs[s] = conf
            if conf == 0:
                continue
            h = int(hot[s])
            base = s * C_pad_a
            mlo = np.concatenate([vkeys[0, base:base + h], klo[keep]])
            mhi = np.concatenate([vkeys[1, base:base + h], khi[keep]])
            order = np.lexsort((mlo, mhi))
            vkeys[0, base:base + h + conf] = mlo[order]
            vkeys[1, base:base + h + conf] = mhi[order]
            hot[s] = h + conf
            frontier[:, fb:fb + conf] = frontier_blk[:, :n_p][:, keep]
            ebits[fb:fb + conf] = eb_blk[:n_p][keep]
            n_loc[s] = conf
            if track_paths and pend_par is not None:
                pl = int(pl_n[s])
                lb = s * L_a
                plog[0, lb + pl:lb + pl + conf] = \
                    np.asarray(pend_par[0, sl])[keep]
                plog[1, lb + pl:lb + pl + conf] = \
                    np.asarray(pend_par[1, sl])[keep]
                plog[2, lb + pl:lb + pl + conf] = klo[keep]
                plog[3, lb + pl:lb + pl + conf] = khi[keep]
                pl_n[s] = pl + conf
        conf_total = int(confs.sum())
        new_after = int(buffers["new"]) + conf_total
        n_props = int(np.asarray(buffers["disc_found"]).size)
        all_disc = (bool(np.asarray(buffers["disc_found"]).all())
                    if n_props else False)
        target = checker.builder._target_state_count
        target_hit = target is not None and new_after >= int(target)
        cont = conf_total > 0 and not all_disc and not target_hit
        for s in range(S_a):
            if cont and confs[s]:
                fval[s * F_a:s * F_a + int(confs[s])] = True
        buffers["new"] = np.uint32(new_after)
        buffers["waves"] = np.uint32(int(buffers["waves"]) + 1)
        if cont:
            buffers["depth"] = np.int32(int(buffers["depth"]) + 1)
        buffers["done"] = np.bool_(not cont)
        if track_paths:
            buffers["pl_n"] = (
                pl_n.astype(np.uint32) if kind_a == "sharded"
                else np.uint32(pl_n[0])
            )
        if kind_a == "sharded":
            buffers["n_loc"] = n_loc.astype(np.uint32)
        else:
            buffers["n_frontier"] = np.uint32(n_loc[0])
        # the manifest's capture point moves past the folded commit
        manifest["wave"] = int(buffers["waves"])
        manifest["depth"] = int(buffers["depth"])
        manifest["unique"] = new_after

    # the source-layout visited prefixes now hold HOT rows only; the
    # re-shard (if any) must slice by these, not the cumulative count
    if kind_a == "sharded":
        buffers["u_loc"] = hot.astype(np.uint32)
    return buffers, cold, hot, plog_host


def _route_tier_target(checker, path: str, manifest: dict,
                       buffers: dict, cold, hot_src, same_layout,
                       plog_host=None):
    """Land a folded tiered snapshot on the TARGET: re-route the cold
    runs by the new owner seam (``lo % S_new`` — filtering a sorted
    run preserves its order, so every piece stays a sorted immutable
    run), then either stage the tier for the resuming engine (tiering
    configured on the target: ``checker._tier_resume_state``) or
    UN-TIER — merge the cold rows back into the resident prefix when
    the target capacity holds the whole set and the target didn't ask
    for tiering. Refuses loudly when neither fits."""
    S_b = int(getattr(checker, "n_shards", 1))
    C_b = int(checker.capacity)
    F_b = int(checker.frontier_capacity)
    C_pad_b = C_b + F_b
    kind_b = _engine_kind(checker)

    cold_t = (cold if same_layout and cold.n_shards == S_b
              else cold.repartitioned(S_b))
    if kind_b == "sharded":
        hot_t = np.atleast_1d(buffers["u_loc"]).astype(
            np.int64
        ).reshape(-1).copy()
    else:
        hot_t = np.array([int(hot_src.sum())], np.int64)
    cold_rows = np.array(cold_t.shard_rows(), np.int64)

    tier_on = getattr(checker, "tier_hot_rows", None) is not None
    if not tier_on:
        # un-tier: the whole set must fit the target residency
        total = hot_t + cold_rows
        if int(total.max(initial=0)) > C_b:
            raise SnapshotIncompatibleError(
                f"{path}: tiered snapshot holds "
                f"{int(total.sum()):,} visited keys "
                f"({int(cold_rows.sum()):,} cold) but the target's "
                f"per-shard capacity is {C_b:,} and tiering is off — "
                "raise the capacity, or resume with tier_hot_rows "
                "set to keep the cold tier"
            )
        vkeys = buffers["vkeys"]
        from .tier import pack_u64

        for d in range(S_b):
            base = d * C_pad_b
            h = int(hot_t[d])
            lo = vkeys[0, base:base + h]
            hi = vkeys[1, base:base + h]
            packed = [pack_u64(lo, hi)]
            for run in cold_t.runs[d]:
                packed.append(run)
            merged = np.sort(np.concatenate(packed))
            n = merged.size
            vkeys[0, base:base + n] = (
                merged & np.uint64(0xFFFFFFFF)
            ).astype(np.uint32)
            vkeys[1, base:base + n] = (
                merged >> np.uint64(32)
            ).astype(np.uint32)
            hot_t[d] = n
        if kind_b == "sharded":
            buffers["u_loc"] = hot_t.astype(np.uint32)
        if plog_host is not None and plog_host.shape[1]:
            # re-home the host-drained parent-log rows into the
            # device log, per owner shard (row order within a shard
            # is irrelevant: every child appears exactly once)
            track_paths = bool(manifest["track_paths"])
            if track_paths:
                L_b = C_b + F_b
                plog = buffers["plog"]
                pl_n = np.atleast_1d(
                    buffers["pl_n"]
                ).astype(np.int64).reshape(-1).copy()
                owner = (
                    plog_host[2] % np.uint32(max(S_b, 1))
                ).astype(np.int64)
                for d in range(S_b):
                    rows_d = plog_host[:, owner == d]
                    n_d = rows_d.shape[1]
                    pl = int(pl_n[d] if d < pl_n.size else 0)
                    if pl + n_d > L_b:
                        raise SnapshotIncompatibleError(
                            f"{path}: un-tiering needs "
                            f"{pl + n_d:,} parent-log rows on shard "
                            f"{d} but the per-shard log holds "
                            f"{L_b:,} — raise the target capacity"
                        )
                    lb = d * L_b
                    plog[:, lb + pl:lb + pl + n_d] = rows_d
                    pl_n[d] = pl + n_d
                buffers["pl_n"] = (
                    pl_n.astype(np.uint32) if kind_b == "sharded"
                    else np.uint32(pl_n[0])
                )
        return buffers

    # stay tiered: per-shard cumulative counts join hot + owned cold
    if kind_b == "sharded":
        buffers["u_loc"] = (hot_t + cold_rows).astype(np.uint32)
    checker._tier_resume_state = dict(
        cold=cold_t, hot=hot_t.astype(np.int64),
        plog_rows=([plog_host] if plog_host is not None
                   and plog_host.shape[1] else []),
    )
    return buffers


def reshard_sortmerge(manifest: dict, buffers: dict,
                      checker, visited_counts=None) -> dict:
    """The elastic re-shard: rebuild the sort-merge carry at the
    target (shard count, per-shard capacity) layout by re-routing
    every row through the (owner, fp) seam the mesh wave's routing
    sort already defines — owner = ``fp_lo % S`` (the all_to_all
    destination function, parallel/engine_sortmerge.py
    ``seed_local``/``make_wave``), keys ordered ``(hi, lo)`` (the
    ``lax.sort(num_keys=2)`` the incrementally-sorted visited
    invariant is built on). Host-side ``np.lexsort`` implements the
    identical ordering, so the rebuilt per-shard prefixes satisfy the
    engine's sorted invariant by construction.

    Handles single-chip ⇄ sharded in both directions (single-chip is
    the S=1 layout with scalar counters) and capacity changes at the
    same shard count. Raises :class:`SnapshotIncompatibleError` when
    the carried state does not fit the target layout (per-shard
    visited/frontier/parent-log overflow) — loudly, before any device
    work."""
    W = int(manifest["width"])
    track_paths = bool(manifest["track_paths"])
    S_a = int(manifest.get("n_shards", 1))
    C_a = int(manifest["capacity"])
    F_a = int(manifest["frontier_capacity"])
    kind_a = manifest.get("kind", "single")
    C_pad_a = C_a + F_a
    L_a = C_a + F_a if track_paths else 0

    S_b = int(getattr(checker, "n_shards", 1))
    C_b = int(checker.capacity)
    F_b = int(checker.frontier_capacity)
    kind_b = _engine_kind(checker)
    C_pad_b = C_b + F_b
    L_b = C_b + F_b if track_paths else 0

    # -- extract the global state from the source layout ------------------
    if kind_a == "sharded":
        u_src = buffers["u_loc"].astype(np.int64).reshape(-1)
        n_src = buffers["n_loc"].astype(np.int64).reshape(-1)
        pl_src = buffers["pl_n"].astype(np.int64).reshape(-1)
    else:
        u_src = np.array([int(buffers["new"])], np.int64)
        n_src = np.array([int(buffers["n_frontier"])], np.int64)
        pl_src = np.array(
            [int(buffers["pl_n"])] if track_paths else [0], np.int64
        )
    if visited_counts is not None:
        # tiered snapshots (stateright_tpu/tier.py): the resident
        # prefix holds the HOT tier only — the cumulative counters
        # ("new") include spilled rows and must not size the slice
        u_src = np.asarray(visited_counts, np.int64).reshape(-1)

    vkeys = buffers["vkeys"]
    keys_lo = np.concatenate([
        vkeys[0, s * C_pad_a: s * C_pad_a + int(u_src[s])]
        for s in range(S_a)
    ])
    keys_hi = np.concatenate([
        vkeys[1, s * C_pad_a: s * C_pad_a + int(u_src[s])]
        for s in range(S_a)
    ])

    frontier = buffers["frontier"]
    ebits = buffers["ebits"]
    fr_cols = np.concatenate([
        frontier[:, s * F_a: s * F_a + int(n_src[s])]
        for s in range(S_a)
    ], axis=1)
    fr_ebits = np.concatenate([
        ebits[s * F_a: s * F_a + int(n_src[s])] for s in range(S_a)
    ])

    if track_paths:
        plog = buffers["plog"]
        pl_entries = np.concatenate([
            plog[:, s * L_a: s * L_a + int(pl_src[s])]
            for s in range(S_a)
        ], axis=1)
    else:
        pl_entries = np.zeros((4, 0), np.uint32)

    # -- route by the (owner, fp) seam -------------------------------------
    from .ops.fingerprint import fingerprint_u32v

    key_owner = (keys_lo % np.uint32(max(S_b, 1))).astype(np.int64)
    fr_lo, fr_hi = fingerprint_u32v(fr_cols.T, np)
    fr_owner = (fr_lo % np.uint32(max(S_b, 1))).astype(np.int64)
    pl_owner = (
        pl_entries[2] % np.uint32(max(S_b, 1))
    ).astype(np.int64)

    vkeys_t = np.full((2, S_b * C_pad_b), _SENT, np.uint32)
    frontier_t = np.zeros((W, S_b * F_b), np.uint32)
    ebits_t = np.zeros(S_b * F_b, np.uint32)
    fval_t = np.zeros(S_b * F_b, bool)
    plog_t = np.zeros((4, S_b * L_b), np.uint32)
    u_t = np.zeros(S_b, np.uint32)
    n_t = np.zeros(S_b, np.uint32)
    pl_t = np.zeros(S_b, np.uint32)
    for d in range(S_b):
        sel = key_owner == d
        kl, kh = keys_lo[sel], keys_hi[sel]
        if kl.size > C_b:
            raise SnapshotIncompatibleError(
                f"re-shard: shard {d} of {S_b} would own {kl.size:,} "
                f"visited keys but per-shard capacity is {C_b:,} — "
                "raise the target capacity"
            )
        order = np.lexsort((kl, kh))  # (hi, lo): the routing sort
        vkeys_t[0, d * C_pad_b: d * C_pad_b + kl.size] = kl[order]
        vkeys_t[1, d * C_pad_b: d * C_pad_b + kl.size] = kh[order]
        u_t[d] = kl.size

        fsel = fr_owner == d
        n_d = int(fsel.sum())
        if n_d > F_b:
            raise SnapshotIncompatibleError(
                f"re-shard: shard {d} of {S_b} would own {n_d:,} "
                f"frontier rows but frontier_capacity is {F_b:,} — "
                "raise the target frontier_capacity"
            )
        cols = fr_cols[:, fsel]
        eb = fr_ebits[fsel]
        # deterministic per-shard order (row order never affects
        # exploration — any order covers the same states — but a
        # stable layout keeps re-shard bit-reproducible)
        forder = np.lexsort((fr_lo[fsel], fr_hi[fsel]))
        frontier_t[:, d * F_b: d * F_b + n_d] = cols[:, forder]
        ebits_t[d * F_b: d * F_b + n_d] = eb[forder]
        fval_t[d * F_b: d * F_b + n_d] = True
        n_t[d] = n_d

        if track_paths:
            psel = pl_owner == d
            p_d = int(psel.sum())
            if p_d > L_b:
                raise SnapshotIncompatibleError(
                    f"re-shard: shard {d} of {S_b} would own "
                    f"{p_d:,} parent-log entries but the per-shard "
                    f"log holds {L_b:,} — raise the target capacity"
                )
            plog_t[:, d * L_b: d * L_b + p_d] = pl_entries[:, psel]
            pl_t[d] = p_d

    def src(name, default):
        b = buffers.get(name)
        return np.array(b) if b is not None else default

    out = dict(
        vkeys=vkeys_t,
        plog=plog_t,
        frontier=frontier_t,
        fval=fval_t,
        ebits=ebits_t,
        depth=np.int32(buffers["depth"]),
        wchunk=np.int32(0),
        waves=np.uint32(buffers["waves"]),
        gen_lo=np.uint32(buffers["gen_lo"]),
        gen_hi=np.uint32(buffers["gen_hi"]),
        new=np.uint32(buffers["new"]),
        disc_found=np.array(buffers["disc_found"], bool),
        disc_lo=np.uint32(buffers["disc_lo"]),
        disc_hi=np.uint32(buffers["disc_hi"]),
        overflow=np.bool_(buffers["overflow"]),
        f_overflow=np.bool_(buffers["f_overflow"]),
        c_overflow=np.bool_(buffers["c_overflow"]),
        e_overflow=np.bool_(buffers["e_overflow"]),
        done=np.bool_(buffers["done"]),
        max_cand=src("max_cand", np.uint32(0)),
    )
    if kind_b == "sharded":
        out.update(
            pl_n=pl_t,
            n_loc=n_t,
            u_loc=u_t,
            sent_lo=src("sent_lo", np.uint32(0)),
            sent_hi=src("sent_hi", np.uint32(0)),
        )
    else:
        # single-chip target: S_b is 1 (no n_shards attr → 1), so the
        # "per-shard" blocks above are one dense block already
        out.update(
            pl_n=np.uint32(pl_t.sum()),
            n_frontier=np.uint32(n_t.sum()),
            max_tile_cand=src("max_tile_cand", np.uint32(0)),
            max_rowen=src("max_rowen", np.uint32(0)),
        )
    return out


def reshard_hash(manifest: dict, buffers: dict, checker) -> dict:
    """The hash-family elastic re-shard (sharded -> sharded only):
    rebuild the per-shard open-addressed tables host-side by
    re-INSERTING the snapshot's occupied key set through the same
    (owner, fp) route the mesh wave uses — owner = ``fp_lo % S_new``,
    insertion via the numpy path of :func:`ops.hashset.insert`, which
    retraces the exact triangular probe sequence the device insert
    compiled, so the rebuilt tables are ones the device could have
    built itself. Parent-forest entries (slot-indexed side tables)
    move with their keys to the new slots; frontier rows re-route by
    their fingerprints. Refuses loudly BEFORE device work when the
    target tables can't absorb the keys (probe exhaustion at the
    target capacity) or a shard's frontier share overflows."""
    from .ops.fingerprint import fingerprint_u32v
    from .ops.hashset import DeviceHashSet, insert

    W = int(manifest["width"])
    track_paths = bool(manifest["track_paths"])
    S_a = int(manifest.get("n_shards", 1))
    C_a = int(manifest["capacity"])
    F_a = int(manifest["frontier_capacity"])
    S_b = int(getattr(checker, "n_shards", 1))
    C_b = int(checker.capacity)
    F_b = int(checker.frontier_capacity)

    t_lo = buffers["t_lo"].reshape(S_a * C_a)
    t_hi = buffers["t_hi"].reshape(S_a * C_a)
    occupied = (t_lo != 0) | (t_hi != 0)
    keys_lo = t_lo[occupied]
    keys_hi = t_hi[occupied]
    if track_paths:
        par_lo = buffers["p_lo_t"].reshape(S_a * C_a)[occupied]
        par_hi = buffers["p_hi_t"].reshape(S_a * C_a)[occupied]

    key_owner = (keys_lo % np.uint32(max(S_b, 1))).astype(np.int64)
    t_lo_t = np.zeros(S_b * C_b, np.uint32)
    t_hi_t = np.zeros(S_b * C_b, np.uint32)
    p_lo_t = np.zeros(S_b * C_b if track_paths else 0, np.uint32)
    p_hi_t = np.zeros(S_b * C_b if track_paths else 0, np.uint32)
    for d in range(S_b):
        sel = key_owner == d
        kl, kh = keys_lo[sel], keys_hi[sel]
        if kl.size > C_b:
            raise SnapshotIncompatibleError(
                f"hash re-shard: shard {d} of {S_b} would own "
                f"{kl.size:,} visited keys but per-shard capacity is "
                f"{C_b:,} — raise the target capacity"
            )
        table = DeviceHashSet.empty(C_b, np)
        table, _, ovf, slots = insert(
            table, kl, kh, np.ones(kl.size, bool), np,
            rounds=int(checker.probe_rounds),
        )
        if bool(np.any(ovf)):
            raise SnapshotIncompatibleError(
                f"hash re-shard: shard {d} of {S_b} exhausted "
                f"{checker.probe_rounds} probe rounds re-inserting "
                f"{kl.size:,} keys at capacity {C_b:,} — raise the "
                "target capacity or probe_rounds"
            )
        base = d * C_b
        t_lo_t[base:base + C_b] = table.lo
        t_hi_t[base:base + C_b] = table.hi
        if track_paths:
            p_lo_t[base + slots.astype(np.int64)] = par_lo[sel]
            p_hi_t[base + slots.astype(np.int64)] = par_hi[sel]

    # frontier rows re-route by their own fingerprints (dense [F, W]
    # row-major blocks on the hash family), deterministically ordered
    # per shard so the re-shard stays bit-reproducible
    frontier = buffers["frontier"].reshape(S_a * F_a, W)
    fval = buffers["fval"].reshape(S_a * F_a).astype(bool)
    ebits = buffers["ebits"].reshape(S_a * F_a)
    rows = frontier[fval]
    eb = ebits[fval]
    fr_lo, fr_hi = fingerprint_u32v(rows, np)
    fr_owner = (fr_lo % np.uint32(max(S_b, 1))).astype(np.int64)
    frontier_t = np.zeros((S_b * F_b, W), np.uint32)
    fval_t = np.zeros(S_b * F_b, bool)
    ebits_t = np.zeros(S_b * F_b, np.uint32)
    for d in range(S_b):
        fsel = fr_owner == d
        n_d = int(fsel.sum())
        if n_d > F_b:
            raise SnapshotIncompatibleError(
                f"hash re-shard: shard {d} of {S_b} would own "
                f"{n_d:,} frontier rows but frontier_capacity is "
                f"{F_b:,} — raise the target frontier_capacity"
            )
        forder = np.lexsort((fr_lo[fsel], fr_hi[fsel]))
        base = d * F_b
        frontier_t[base:base + n_d] = rows[fsel][forder]
        ebits_t[base:base + n_d] = eb[fsel][forder]
        fval_t[base:base + n_d] = True

    def src(name, default):
        b = buffers.get(name)
        return np.array(b) if b is not None else default

    return dict(
        t_lo=t_lo_t,
        t_hi=t_hi_t,
        p_lo_t=p_lo_t,
        p_hi_t=p_hi_t,
        frontier=frontier_t,
        fval=fval_t,
        ebits=ebits_t,
        depth=np.int32(buffers["depth"]),
        wchunk=np.int32(0),
        waves=np.uint32(buffers["waves"]),
        gen_lo=np.uint32(buffers["gen_lo"]),
        gen_hi=np.uint32(buffers["gen_hi"]),
        new=np.uint32(buffers["new"]),
        sent_lo=src("sent_lo", np.uint32(0)),
        sent_hi=src("sent_hi", np.uint32(0)),
        disc_found=np.array(buffers["disc_found"], bool),
        disc_lo=np.uint32(buffers["disc_lo"]),
        disc_hi=np.uint32(buffers["disc_hi"]),
        overflow=np.bool_(buffers["overflow"]),
        f_overflow=np.bool_(buffers["f_overflow"]),
        c_overflow=np.bool_(buffers["c_overflow"]),
        e_overflow=np.bool_(buffers["e_overflow"]),
        done=np.bool_(buffers["done"]),
    )


def build_resume_carry(checker, manifest: dict, buffers: dict,
                       spec: dict) -> dict:
    """Assemble the initial device carry for a resumed run from staged
    snapshot buffers, against the CURRENT seed program's eval_shape
    ``spec`` (so a restore can never hand the compiled chunk program a
    carry it wasn't built for): snapshot leaves are shape/dtype
    checked, trace-gated leaves are synthesized to the resuming run's
    shapes, and sharded leaves are placed with the engine's
    ``PartitionSpec``\\ s when available (plain arrays otherwise — jit
    re-shards uncommitted inputs)."""
    synth = set(_SYNTH_LEAVES)
    if checker._checkpoint_family() == "hash":
        # the hash engine's u_loc is a trace-only metric lane
        synth.add("u_loc")
    carry_np: dict[str, np.ndarray] = {}
    for name in spec:
        leaf = spec[name]
        want_shape = tuple(int(s) for s in leaf.shape)
        want_dtype = np.dtype(leaf.dtype)
        have = buffers.get(name)
        if name == "wchunk":
            carry_np[name] = np.zeros(want_shape, want_dtype)
            continue
        if have is not None and tuple(have.shape) == want_shape:
            carry_np[name] = np.asarray(have, dtype=want_dtype)
            continue
        if name in synth:
            carry_np[name] = np.zeros(want_shape, want_dtype)
            continue
        raise SnapshotIncompatibleError(
            f"resume: carry leaf {name!r} expects "
            f"{want_dtype}{list(want_shape)}, snapshot has "
            + (f"{have.dtype}{list(have.shape)}" if have is not None
               else "no such buffer")
            + " — the engine configuration differs from the snapshot"
        )

    # The restored leaves must be JAX-OWNED copies: the chunk program
    # donates its carry (donate_argnums=0), and on CPU a zero-copy
    # device_put/asarray of a numpy buffer can ALIAS the host memory
    # — donating an aliased buffer lets XLA reuse memory numpy still
    # references (observed as off-by-a-few duplicate counts and
    # occasional runtime crashes on the first resumed chunk). One
    # explicit on-device copy per leaf severs the alias; resume pays
    # it once.
    pspecs = getattr(checker, "_carry_pspecs", None)
    mesh = getattr(checker, "mesh", None)
    import jax
    import jax.numpy as jnp

    if pspecs is not None and mesh is not None:
        from jax.sharding import NamedSharding

        out = {}
        for k, v in carry_np.items():
            if k in pspecs:
                arr = jax.device_put(
                    v, NamedSharding(mesh, pspecs[k])
                )
                out[k] = jnp.copy(arr)
            else:
                out[k] = jnp.copy(jnp.asarray(v))
        return out
    return {k: jnp.copy(jnp.asarray(v)) for k, v in carry_np.items()}


# -- failure policy (the degrade-and-continue round) ----------------------
#
# PR 11's supervisor could only retry the same layout or refuse; this
# layer closes the loop ROADMAP direction 1 needs for multi-hour mesh
# runs, where the failure model is "a shard dies, a collective wedges,
# a dispatch hangs forever" (the worker-loss-as-first-class-event
# framing of arXiv:1203.6806 and arXiv:0901.0179): every supervised
# failure is CLASSIFIED (transient / persistent per-shard / OOM /
# hang) from the exception and the run's own health signals, and a
# fault that persists on the same shard across the bounded-backoff
# retries escalates to an automatic elastic degrade — the last
# snapshot re-shards onto the surviving shard count through the exact
# (owner, fp) seam PR 11 proved, cold tier and drained parent log
# included, so the degraded run reproduces bit-exact counts.

#: what a supervised failure classifies as (FailurePolicy.classify).
FAILURE_CLASSES = ("transient", "oom", "hang", "shard_fault",
                   "unsupervised")


class WatchdogTimeout(RuntimeError):
    """A chunk dispatch/sync exceeded its derived watchdog deadline
    (checkers/tpu.py ``watchdog_factor``) — the hung-dispatch shape of
    the bisected XLA:CPU thunk-runtime livelock family (ROADMAP
    §carried), which no exception path ever surfaces. Supervised: the
    policy classifies it ``hang`` and retries from the last snapshot
    where checkpointing allows (CPython cannot cancel a wedged XLA
    sync — the hung worker thread is abandoned as a daemon — so
    in-process recovery re-dispatches and a genuinely wedged runtime
    exhausts the retry budget and raises this error through:
    refuse-loudly-with-diagnosis, the contract). ``attribution``
    carries the run's full latency split at the breach."""

    def __init__(self, chunk: int, deadline_sec: float,
                 attribution: Optional[dict] = None):
        super().__init__(
            f"watchdog: chunk {chunk} exceeded its derived deadline "
            f"of {deadline_sec:.2f}s with no sync — a hung dispatch "
            "(the thunk-runtime livelock shape). The dispatch thread "
            "is abandoned (XLA offers no cancellation); recover from "
            "the last snapshot or investigate the attribution."
        )
        self.chunk = int(chunk)
        self.deadline_sec = float(deadline_sec)
        self.attribution = attribution or {}


def watchdog_deadline(rolling_max_sec: Optional[float],
                      factor: float = 8.0, *,
                      floor_sec: float = 2.0,
                      cap_sec: float = 600.0,
                      first_grace_sec: float = 300.0) -> float:
    """The per-chunk watchdog deadline, re-derived per chunk from the
    run's OWN measured chunk walls (the auto_cadence pattern):
    ``clamp(factor x rolling max chunk wall)`` to ``[floor, cap]``.
    A run with no measured wall yet (chunk 0, where the lazy jit
    compile or a persistent-cache disk fetch lands inside the first
    dispatch — a 17.9 s retrieval was measured in TRACE_r21) gets
    ``first_grace_sec`` instead, so a cold compile is never
    misclassified as a hang; the engine additionally feeds the roll
    chunk walls NET of ledger-attributed build time for the same
    reason."""
    if not factor or factor <= 0:
        raise ValueError(f"watchdog factor must be > 0: {factor}")
    if rolling_max_sec is None:
        # None means UNMEASURED (chunk 0); a measured-but-tiny wall
        # (e.g. fully attributed to a compile fetch) is a real
        # measurement and gets the floor, not the grace — otherwise a
        # fast first chunk would re-grant the grace forever
        return float(first_grace_sec)
    return float(min(
        cap_sec, max(floor_sec, factor * max(rolling_max_sec, 0.0))
    ))


def classify_failure(exc: BaseException,
                     straggler_shards=()) -> tuple:
    """``(class, shard | None)`` for one supervised failure — the
    classification table FailurePolicy keys escalation on:

    * :class:`WatchdogTimeout` -> ``hang`` (never shard-attributed:
      a wedged sync has no shard signal);
    * an OOM-shaped error -> ``oom`` (the memory-lean degrade path);
    * :class:`~stateright_tpu.faultinject.InjectedShardFault` ->
      ``shard_fault`` with its shard id — the persistent per-shard
      class real per-chip ECC/interconnect faults land in;
    * any other supervised fault -> ``transient``, attributed to a
      shard only when the health layer's sustained-straggler evidence
      names exactly ONE suspect (an ambiguous signal attributes
      nothing — degrading the wrong shard helps nobody);
    * everything else -> ``unsupervised`` (the supervisor re-raises
      before classification normally; this row exists for the policy
      unit tests)."""
    from .faultinject import InjectedShardFault

    if isinstance(exc, WatchdogTimeout):
        return "hang", None
    if isinstance(exc, InjectedShardFault):
        return "shard_fault", exc.shard
    if _is_oom(exc):
        return "oom", None
    if is_supervised_fault(exc):
        shard = (int(straggler_shards[0])
                 if len(straggler_shards) == 1 else None)
        return "transient", shard
    return "unsupervised", None


class FailurePolicy:
    """Per-run failure bookkeeping for the supervisor: classify each
    failure, count shard-attributed strikes, and decide when a fault
    is PERSISTENT — the same shard failing ``persist_threshold``
    times — at which point :func:`supervised_run` escalates from
    retry-same-layout to an automatic elastic degrade onto the
    surviving shards."""

    def __init__(self, persist_threshold: int = 2):
        if persist_threshold < 1:
            raise ValueError(
                f"persist_threshold must be >= 1: {persist_threshold}"
            )
        self.persist_threshold = int(persist_threshold)
        #: (class, shard) per classified failure, in order.
        self.history: list[tuple] = []
        #: shard id -> consecutive attributed failures.
        self.strikes: dict[int, int] = {}

    def classify(self, exc: BaseException,
                 straggler_shards=()) -> tuple:
        """Classify AND record one failure. A shard-attributed
        failure strikes its shard; a failure attributed to no shard
        resets nothing (evidence about one shard is not evidence the
        others recovered)."""
        kind, shard = classify_failure(exc, straggler_shards)
        self.history.append((kind, shard))
        if shard is not None:
            self.strikes[shard] = self.strikes.get(shard, 0) + 1
        return kind, shard

    def should_degrade(self) -> Optional[int]:
        """The shard to drop (most strikes first), or None while no
        shard has reached the persistence threshold."""
        over = [(n, s) for s, n in self.strikes.items()
                if n >= self.persist_threshold]
        if not over:
            return None
        return max(over)[1]

    def degraded(self, shard: int) -> None:
        """The run dropped this shard — its strikes go with it."""
        self.strikes.pop(shard, None)


# -- supervision ----------------------------------------------------------


def is_supervised_fault(exc: BaseException) -> bool:
    """Whether the supervisor may retry this failure from a snapshot:
    injected faults, OOMs, and runtime errors surfacing from the XLA
    dispatch/readback path. Engine overflow errors (plain
    RuntimeErrors with sizing advice) are NOT supervised — the
    auto-budget retry owns those, and re-running them from a snapshot
    would loop."""
    from .faultinject import InjectedFault

    if isinstance(exc, (InjectedFault, MemoryError, WatchdogTimeout)):
        return True
    name = type(exc).__name__
    if name in ("XlaRuntimeError", "JaxRuntimeError", "InternalError"):
        return True
    s = str(exc)
    return "RESOURCE_EXHAUSTED" in s or "Out of memory" in s


def _is_oom(exc: BaseException) -> bool:
    if isinstance(exc, MemoryError):
        return True
    s = str(exc)
    return "RESOURCE_EXHAUSTED" in s or "Out of memory" in s


def _interruptible_backoff(delay: float, checker) -> None:
    """The supervisor's backoff sleep, in small slices so a cancel
    event (the hybrid racer) ends it early — and with the trace run
    bracket CLOSED on KeyboardInterrupt: a ^C mid-backoff used to die
    mid-sleep with the run_begin left dangling (the checker's
    ``_ensure_run`` catches ``Exception`` only, so the BaseException
    escaped without a run_end), leaving the partial trace unreadable
    by the run-aligned tools."""
    from . import telemetry

    deadline = time.monotonic() + delay
    try:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            ev = getattr(checker, "cancel_event", None)
            if ev is not None and ev.is_set():
                return
            time.sleep(min(remaining, 0.05))
    except KeyboardInterrupt:
        tracer = telemetry.current_tracer()
        if tracer is not None:
            tracer.end_run(
                error="KeyboardInterrupt: interrupted during "
                "supervised backoff"
            )
        raise


def supervised_run(checker, reporter=None) -> None:
    """The retry loop around one engine run (``checker._run`` routes
    here) — since the degrade-and-continue round, a POLICY ENGINE:
    every supervised fault (device error, injected fault, OOM,
    watchdog hang) is classified by a :class:`FailurePolicy` from the
    exception and the health layer's straggler evidence, then

    * **retried** from the last snapshot (or the seed when the fault
      landed before the first snapshot) with bounded exponential
      backoff — the PR 11 behavior, now the ``transient`` class;
    * after two ``oom``-classified failures the engine degrades to
      its CHUNKED memory-lean classes before the next attempt;
    * a fault that PERSISTS on the same shard across retries (the
      ``shard_fault`` class, or ``transient`` faults the straggler
      evidence attributes) escalates — when ``degrade_on_fault`` is
      set on a multi-shard engine — to an automatic ELASTIC DEGRADE:
      the faulted shard is dropped from the mesh and the last
      snapshot re-shards onto the survivors through the (owner, fp)
      seam (cold tier runs and the drained parent log carry through
      resume_from's existing paths), recorded as a ``fault_degrade``
      event. The degraded run continues to bit-exact counts;
    * a ``hang`` (WatchdogTimeout) retries from the snapshot like a
      device error — a genuinely wedged runtime exhausts the retry
      budget and the WatchdogTimeout raises through with its latency
      attribution: refuse-loudly-with-diagnosis.

    Unsupervised errors — and supervised ones past
    ``max_fault_retries`` — raise through unchanged."""
    from . import telemetry

    policy = FailurePolicy(
        persist_threshold=getattr(
            checker, "fault_persist_threshold", 2
        )
    )
    attempts = 0
    ooms = 0
    while True:
        try:
            return checker._run_attempt(reporter)
        except Exception as exc:
            if not is_supervised_fault(exc):
                raise
            kind, shard = policy.classify(
                exc, straggler_shards=checker._sustained_stragglers()
            )
            snap = (getattr(checker, "_last_snapshot", None)
                    or getattr(checker, "_resume_path", None))
            retries = getattr(checker, "max_fault_retries", 3)
            if (not checker.checkpoint_every and snap is None) \
                    or attempts >= retries:
                raise
            attempts += 1
            if kind == "oom":
                ooms += 1
            victim = None
            if (getattr(checker, "degrade_on_fault", False)
                    and checker._can_degrade_shards()):
                victim = policy.should_degrade()
            delay = min(
                getattr(checker, "retry_backoff_sec", 0.5)
                * (2 ** (attempts - 1)),
                30.0,
            )
            warnings.warn(
                f"supervised recovery [{kind}"
                + (f", shard {shard}" if shard is not None else "")
                + f"]: {type(exc).__name__} on chunk execution "
                f"({exc}); "
                + (f"DEGRADING: dropping shard {victim} "
                   f"({checker.n_shards} -> {checker.n_shards - 1} "
                   "shards) and " if victim is not None else "")
                + f"retry {attempts}/{retries} from "
                + (f"snapshot {os.path.basename(snap)}" if snap
                   else "the seed")
                + f" after {delay:.2f}s backoff",
                RuntimeWarning,
                stacklevel=2,
            )
            telemetry.emit(
                "fault_recovery",
                attempt=attempts,
                error=f"{type(exc).__name__}: {exc}",
                snapshot=(os.path.basename(snap) if snap else None),
                backoff_sec=round(delay, 3),
                oom=(kind == "oom"),
                failure_class=kind,
                shard=shard,
            )
            if ooms >= 2:
                checker._degrade_memory_lean()
            _interruptible_backoff(delay, checker)
            checker._reset_for_resume()
            old_shards = int(getattr(checker, "n_shards", 1))
            if victim is not None:
                checker._degrade_shards(exclude_shard=victim)
                policy.degraded(victim)
            manifest = None
            if snap is not None:
                manifest = resume_from(
                    checker, snap,
                    # the caller's staleness policy carries over: a
                    # run started with allow_sha_mismatch must not
                    # die on the same check mid-recovery
                    allow_sha_mismatch=getattr(
                        checker, "_resume_allow_sha", False
                    ),
                )
            if victim is not None:
                telemetry.emit(
                    "fault_degrade",
                    from_shards=old_shards,
                    to_shards=int(checker.n_shards),
                    excluded_shard=int(victim),
                    reason=kind,
                    wave=(int(manifest["wave"])
                          if manifest is not None else 0),
                    rerouted_rows=(int(manifest["unique"])
                                   if manifest is not None else 0),
                    snapshot=(os.path.basename(snap) if snap
                              else None),
                )
