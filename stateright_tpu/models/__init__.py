"""Example model-checked systems (counterparts of the reference's
examples/ and actor test fixtures)."""
