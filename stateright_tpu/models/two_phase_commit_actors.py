"""Actor-model two-phase commit — the COMPILED 2pc encoding's source.

The flagship 2pc workload (models/two_phase_commit.py) is a plain
``Model`` with a hand-written device encoding; the actor→encoding
compiler (actor/compile.py) can't see it. This module reformulates the
protocol as actors so 2pc joins the compiled path (ROADMAP direction
5: the compiled path held to the hand-encoding bar — the kernel-lint
registry runs the full codegen rule set over this encoding,
analysis/registry.py ``compiled-2pc-actors-rm2``):

* each RM arms two timers at start: ``prepare`` (WORKING → PREPARED,
  announce to the TM) and ``abort`` (WORKING → ABORTED silently) — the
  two spontaneous RM actions of the TLA+ original;
* the TM tallies ``Prepared`` announcements and holds two timers:
  ``commit`` fires only when every RM has prepared (broadcast
  ``Commit``), re-arming itself otherwise (the re-arm-only firing is
  pruned by ``is_no_op_with_timer``, so the option stays open at zero
  state-space cost), and ``abort`` (broadcast ``Abort``) while
  undecided;
* RMs obey the decision: ``Commit`` lands only on PREPARED rows,
  ``Abort`` on anything undecided.

NOT count-comparable to ``TwoPhaseSys``: message passing is explicit
here (the plain model's ``msgs`` set is a shared bag), so the spaces
differ by construction — the properties, not the counts, are the
shared contract. The model is deliberately history-free
(``init_history=None``), which doubles as the regression fixture for
the compile.py history-table sentinel fix (a ``None`` history value
used to read as "un-harvested" and hard-truncate every delivery).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..actor import Actor, ActorModel, Cow, Id, Network, Out
from ..actor.base import model_timeout
from ..model import Expectation

#: RM local states (int-encoded: actor domains stay tiny and the
#: device property specs compare codes directly).
RM_WORKING, RM_PREPARED, RM_ABORTED, RM_COMMITTED = 0, 1, 2, 3
#: TM phase codes (TM local state is ``(phase, prepared_bitmask)``).
TM_INIT, TM_COMMITTED, TM_ABORTED = 0, 1, 2


@dataclass(frozen=True)
class Prepared:
    rm: int


@dataclass(frozen=True)
class Commit:
    pass


@dataclass(frozen=True)
class Abort:
    pass


class RmActor(Actor):
    """One resource manager: spontaneous prepare/abort via timers,
    decision messages from the TM."""

    def __init__(self, tm_id: Id, index: int):
        self.tm_id = tm_id
        self.index = index

    def on_start(self, id: Id, out: Out) -> int:
        out.set_timer("prepare", model_timeout())
        out.set_timer("abort", model_timeout())
        return RM_WORKING

    def on_timeout(self, id: Id, state: Cow, timer, out: Out) -> None:
        s = state.value
        if timer == "prepare" and s == RM_WORKING:
            out.send(self.tm_id, Prepared(self.index))
            state.set(RM_PREPARED)
        elif timer == "abort" and s == RM_WORKING:
            state.set(RM_ABORTED)
        # decided states: plain no-op, pruned

    def on_msg(self, id: Id, state: Cow, src: Id, msg, out: Out) -> None:
        s = state.value
        if isinstance(msg, Commit) and s == RM_PREPARED:
            state.set(RM_COMMITTED)
        elif isinstance(msg, Abort) and s in (RM_WORKING, RM_PREPARED):
            state.set(RM_ABORTED)


class TmActor(Actor):
    """The transaction manager: tallies Prepared, decides by timer."""

    def __init__(self, rm_ids: list[Id]):
        self.rm_ids = rm_ids

    def on_start(self, id: Id, out: Out):
        out.set_timer("commit", model_timeout())
        out.set_timer("abort", model_timeout())
        return (TM_INIT, 0)

    def on_msg(self, id: Id, state: Cow, src: Id, msg, out: Out) -> None:
        tm, mask = state.value
        if isinstance(msg, Prepared) and tm == TM_INIT:
            state.set((tm, mask | (1 << msg.rm)))

    def on_timeout(self, id: Id, state: Cow, timer, out: Out) -> None:
        tm, mask = state.value
        full = (1 << len(self.rm_ids)) - 1
        if timer == "commit":
            if tm == TM_INIT and mask == full:
                out.broadcast(self.rm_ids, Commit())
                state.set((TM_COMMITTED, mask))
            else:
                # keep the commit option armed; the re-arm-only firing
                # is pruned (is_no_op_with_timer)
                out.set_timer("commit", model_timeout())
        elif timer == "abort" and tm == TM_INIT:
            out.broadcast(self.rm_ids, Abort())
            state.set((TM_ABORTED, mask))


def two_phase_actor_model(rm_count: int) -> ActorModel:
    """``rm_count`` RM actors (ids 0..rm_count-1) + the TM (last id).
    ``cfg`` is the RM count, so host properties can slice
    ``actor_states[:cfg]``."""
    tm = Id(rm_count)
    model = ActorModel(cfg=rm_count, init_history=None)
    model.add_actors(RmActor(tm, i) for i in range(rm_count))
    model = model.actor(TmActor([Id(i) for i in range(rm_count)]))
    return (
        model.init_network(Network.new_unordered_nonduplicating())
        .property(
            Expectation.ALWAYS,
            "consistent",
            lambda m, s: not (
                any(x == RM_ABORTED for x in s.actor_states[: m.cfg])
                and any(
                    x == RM_COMMITTED for x in s.actor_states[: m.cfg]
                )
            ),
        )
        .property(
            Expectation.SOMETIMES,
            "all commit",
            lambda m, s: all(
                x == RM_COMMITTED for x in s.actor_states[: m.cfg]
            ),
        )
        .property(
            Expectation.SOMETIMES,
            "some abort",
            lambda m, s: any(
                x == RM_ABORTED for x in s.actor_states[: m.cfg]
            ),
        )
    )


class SysRmActor(Actor):
    """Resource manager of the COUNT-COMPARABLE reformulation (see
    ``two_phase_sys_actor_model``): the timers are armed exactly while
    WORKING and every transition out of WORKING cancels both, so the
    timer bits are a function of the RM state and add no states."""

    def __init__(self, tm_id: Id, index: int):
        self.tm_id = tm_id
        self.index = index

    def on_start(self, id: Id, out: Out) -> int:
        out.set_timer("prepare", model_timeout())
        out.set_timer("abort", model_timeout())
        return RM_WORKING

    def on_timeout(self, id: Id, state: Cow, timer, out: Out) -> None:
        s = state.value
        if timer == "prepare" and s == RM_WORKING:
            # rm_prepare: the Prepared announcement IS the plain
            # model's ("prepared", rm) bag entry (dup network: the
            # envelope bit is never consumed)
            out.send(self.tm_id, Prepared(self.index))
            out.cancel_timer("abort")
            state.set(RM_PREPARED)
        elif timer == "abort" and s == RM_WORKING:
            # rm_choose_abort: silent
            out.cancel_timer("prepare")
            state.set(RM_ABORTED)

    def on_msg(self, id: Id, state: Cow, src: Id, msg, out: Out) -> None:
        s = state.value
        # rm_rcv_commit / rm_rcv_abort fire from ANY undecided state
        # in the plain model; the self-loops at the target state are
        # pruned no-ops there and here
        if isinstance(msg, Commit) and s != RM_COMMITTED:
            if s == RM_WORKING:
                out.cancel_timer("prepare")
                out.cancel_timer("abort")
            state.set(RM_COMMITTED)
        elif isinstance(msg, Abort) and s != RM_ABORTED:
            if s == RM_WORKING:
                out.cancel_timer("prepare")
                out.cancel_timer("abort")
            state.set(RM_ABORTED)


class SysTmActor(Actor):
    """Transaction manager of the count-comparable reformulation: the
    ``(phase, prepared-mask)`` local state mirrors the plain model's
    ``(tm_state, tm_prepared)`` exactly; decision timers are armed
    exactly while INIT."""

    def __init__(self, rm_ids: list[Id]):
        self.rm_ids = rm_ids

    def on_start(self, id: Id, out: Out):
        out.set_timer("commit", model_timeout())
        out.set_timer("abort", model_timeout())
        return (TM_INIT, 0)

    def on_msg(self, id: Id, state: Cow, src: Id, msg, out: Out) -> None:
        tm, mask = state.value
        if isinstance(msg, Prepared) and tm == TM_INIT:
            # tm_rcv_prepared: unconditional set — when the bit is
            # already up this is the plain model's self-loop (Cow.set
            # marks owned, so the transition exists and dedups away)
            state.set((tm, mask | (1 << msg.rm)))

    def on_timeout(self, id: Id, state: Cow, timer, out: Out) -> None:
        tm, mask = state.value
        full = (1 << len(self.rm_ids)) - 1
        if timer == "commit":
            if tm == TM_INIT and mask == full:
                # tm_commit: the atomic broadcast is the single
                # ("commit",) bag entry — all envelope bits rise
                # together and are never consumed, one bit of info
                out.broadcast(self.rm_ids, Commit())
                out.cancel_timer("abort")
                state.set((TM_COMMITTED, mask))
            else:
                out.set_timer("commit", model_timeout())
        elif timer == "abort" and tm == TM_INIT:
            # tm_abort
            out.broadcast(self.rm_ids, Abort())
            out.cancel_timer("commit")
            state.set((TM_ABORTED, mask))


def two_phase_sys_actor_model(rm_count: int) -> ActorModel:
    """The COUNT-COMPARABLE actor reformulation of ``TwoPhaseSys``
    (round 23, ROADMAP direction 5): over the UNORDERED DUPLICATING
    network the compiled state space bijects with the plain model's —
    ``(rm_state*, tm_state, tm_prepared)`` map to the local states,
    the append-only ``msgs`` bag maps to the never-consumed envelope
    presence bits, and the timer bits are functions of the local
    states — so the pinned counts (288 / 1,568 / 8,832 / 50,816 /
    296,448 at rm=3..7) reproduce bit-identically and the hand
    encoding serves as a differential ORACLE for the compiled path
    (tests/test_compiled_parity.py). Property names match the plain
    model's so verdicts compare by name."""
    tm = Id(rm_count)
    model = ActorModel(cfg=rm_count, init_history=None)
    model.add_actors(SysRmActor(tm, i) for i in range(rm_count))
    model = model.actor(SysTmActor([Id(i) for i in range(rm_count)]))
    return (
        model.init_network(Network.new_unordered_duplicating())
        .property(
            Expectation.SOMETIMES,
            "abort agreement",
            lambda m, s: all(
                x == RM_ABORTED for x in s.actor_states[: m.cfg]
            ),
        )
        .property(
            Expectation.SOMETIMES,
            "commit agreement",
            lambda m, s: all(
                x == RM_COMMITTED for x in s.actor_states[: m.cfg]
            ),
        )
        .property(
            Expectation.ALWAYS,
            "consistent",
            lambda m, s: not (
                any(x == RM_ABORTED for x in s.actor_states[: m.cfg])
                and any(
                    x == RM_COMMITTED for x in s.actor_states[: m.cfg]
                )
            ),
        )
    )


def two_phase_sys_device_specs(rm_count: int) -> dict:
    """Device property specs for ``two_phase_sys_actor_model`` — same
    predicates as the plain model, evaluated over the RM actor codes."""

    def rm_codes(ctx, jnp):
        return ctx.actor_values(
            lambda i, s: s if i < rm_count else 0
        )[:rm_count]

    def abort_agreement(ctx, jnp):
        return jnp.all(rm_codes(ctx, jnp) == RM_ABORTED)

    def commit_agreement(ctx, jnp):
        return jnp.all(rm_codes(ctx, jnp) == RM_COMMITTED)

    def consistent(ctx, jnp):
        v = rm_codes(ctx, jnp)
        return ~(
            jnp.any(v == RM_ABORTED) & jnp.any(v == RM_COMMITTED)
        )

    return dict(
        properties={
            "abort agreement": abort_agreement,
            "commit agreement": commit_agreement,
            "consistent": consistent,
        }
    )


def two_phase_sys_compiled_encoded(rm_count: int, **kw):
    """One-call compiled encoding of the count-comparable model
    (overapprox closure: the tiny per-actor domains need no host
    exploration at any rm count).

    ``pair_width_hint`` defaults to the hand encoding's per-row
    enabled peak (two_phase_commit_tpu.py): the model is a
    state-for-state bijection with TwoPhaseSys and the compiled
    enabled bits are a subset of the hand slots' (no-op self-loops
    prune), so the hand bound carries over — and the sparse engines'
    peel-overflow guard warns and resize-retries if it ever breaks.
    Without it EV defaults to K = 2+5*rm and the pair peel pays for
    slots that never co-occur (PERF.md §compiled-parity)."""
    from ..actor.compile import compile_actor_model

    kw.setdefault(
        "pair_width_hint", max(3 * rm_count, 2 * rm_count + 2)
    )
    return compile_actor_model(
        two_phase_sys_actor_model(rm_count),
        **two_phase_sys_device_specs(rm_count),
        **kw,
    )


def two_phase_actor_device_specs(rm_count: int) -> dict:
    """Device property specs for ``compile_actor_model`` — the exact
    counterparts of the host properties above (the compiler requires a
    spec per host property)."""

    def rm_codes(ctx, jnp):
        # per-actor state code; the TM (last actor, tuple-state
        # domain) maps to 0 and is sliced off
        return ctx.actor_values(
            lambda i, s: s if i < rm_count else 0
        )[:rm_count]

    def consistent(ctx, jnp):
        v = rm_codes(ctx, jnp)
        return ~(
            jnp.any(v == RM_ABORTED) & jnp.any(v == RM_COMMITTED)
        )

    def all_commit(ctx, jnp):
        return jnp.all(rm_codes(ctx, jnp) == RM_COMMITTED)

    def some_abort(ctx, jnp):
        return jnp.any(rm_codes(ctx, jnp) == RM_ABORTED)

    return dict(
        properties={
            "consistent": consistent,
            "all commit": all_commit,
            "some abort": some_abort,
        }
    )
