"""N-client write-once register: the first encoding the soundness
analyzer unlocked.

``n_clients`` interchangeable clients race to write one write-once
register, then read it back. Each client runs a three-step program —
idle → wrote → done — recording whether its write WON (the register
was still empty) and the value its read observed. All clients write
the same value, so the only interesting state is the race outcome:
exactly one client wins, every read after a write observes it.

This family exists as the second ``DeviceRewriteSpec``-declaring
encoding (ROADMAP 4(a) named "more declaring encodings" as remaining
work): clients occupy uniformly strided 4-bit blocks, and the spec's
soundness is certified by the static analyzer
(stateright_tpu/analysis/soundness.py) rather than argued by hand —
the whole point of the analyzer is that a new declaring encoding
lands without a bespoke proof.

Closed-form counts (pinned by tests/test_soundness.py):
  raw unique states   = 1 + 2n·3^(n-1)   (n=2: 13, n=3: 55, n=4: 217)
  canonical orbits    = 1 + n(n+1)       (n=2: 7,  n=3: 13, n=4: 21)
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Sequence, Tuple

from ..model import Model, Property
from ..symmetry import RewritePlan

#: per-client program counter
_IDLE, _WROTE, _DONE = 0, 1, 2


@dataclass(frozen=True)
class NClientRegState:
    #: per-client (pc, won, rv) — program counter, did-my-write-win,
    #: read value; the register itself is ``reg``
    clients: Tuple[Tuple[int, int, int], ...]
    reg: int

    def representative(self) -> "NClientRegState":
        """Canonicalize under client permutation: stable-sort the
        FULL per-client tuple, so the canonicalizer is constant on
        orbits (search-order-independent counts — see symmetry.py on
        why partial sort keys are not)."""
        plan = RewritePlan.from_values_to_sort(list(self.clients))
        return NClientRegState(
            clients=tuple(plan.reindex(self.clients)), reg=self.reg
        )

    def representative_full(self) -> "NClientRegState":
        """Already the full-tuple sort: the host oracle for the
        device canonicalization (ops/canonical.py) coincides with
        ``representative()``."""
        return self.representative()


@dataclass
class NClientRegSys(Model):
    """``n_clients`` clients, one write-once register."""

    n_clients: int

    def to_encoded(self):
        """The TPU-engine encoding (spawn_tpu discovers this hook)."""
        from .nclient_register_tpu import NClientRegEncoded

        return NClientRegEncoded(self.n_clients)

    def init_states(self) -> Sequence[NClientRegState]:
        return [
            NClientRegState(
                clients=tuple((_IDLE, 0, 0) for _ in range(self.n_clients)),
                reg=0,
            )
        ]

    def actions(self, state: NClientRegState):
        actions = []
        for c, (pc, _won, _rv) in enumerate(state.clients):
            if pc == _IDLE:
                actions.append(("write", c))
            elif pc == _WROTE:
                actions.append(("read", c))
        return actions

    def next_state(
        self, state: NClientRegState, action
    ) -> Optional[NClientRegState]:
        kind, c = action
        pc, won, rv = state.clients[c]
        if kind == "write":
            client = (_WROTE, int(state.reg == 0), rv)
            return replace(
                state, clients=self._with(state, c, client), reg=1
            )
        if kind == "read":
            client = (_DONE, won, state.reg)
            return replace(state, clients=self._with(state, c, client))
        raise ValueError(f"unknown action {action!r}")

    @staticmethod
    def _with(state: NClientRegState, c: int, client):
        return state.clients[:c] + (client,) + state.clients[c + 1:]

    def properties(self):
        return [
            Property.sometimes(
                "all done",
                lambda m, s: all(pc == _DONE for pc, _, _ in s.clients),
            ),
            Property.sometimes(
                "lost write",
                lambda m, s: any(
                    pc != _IDLE and won == 0 for pc, won, _ in s.clients
                ),
            ),
            Property.always(
                "at most one winner",
                lambda m, s: sum(won for _, won, _ in s.clients) <= 1,
            ),
            Property.always(
                "reads see the write",
                lambda m, s: all(
                    rv == 1 for pc, _, rv in s.clients if pc == _DONE
                ),
            ),
        ]
