"""Modeling user interaction / external inputs with actors.

Counterpart of reference examples/interaction.rs: a heterogeneous
system — a ``Client`` that drives inputs through self-armed timers and
a ``Counter`` service — whose states do not evolve autonomously. The
client's ``ClientInput`` timer sends an increment request and arms
``ClientQuery``, whose firing asks the counter to report; a reply at
or above the threshold flips ``success``.

The reference wires the two actor types through its ``choice!`` macro
(heterogeneous ``ActorModel``s need a sum type in Rust); Python actor
lists are heterogeneous natively, and :mod:`stateright_tpu.actor.choice`
exists for API parity. The space is loosely bounded (wait_cycles
grows), so checking uses ``target_max_depth(30)`` exactly as
interaction.rs:44 does.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..actor import Actor, ActorModel, Cow, Id, Out
from ..actor.base import model_timeout
from ..model import Expectation


@dataclass(frozen=True)
class IncrementRequest:
    n: int


@dataclass(frozen=True)
class ReportRequest:
    pass


@dataclass(frozen=True)
class ReplyCount:
    n: int


@dataclass(frozen=True)
class CounterState:
    addr: Id
    counter: int


class Counter(Actor):
    """interaction.rs Counter: increments on request, reports on ask."""

    def __init__(self, initial_state: CounterState):
        self.initial_state = initial_state

    def on_start(self, id: Id, out: Out) -> CounterState:
        return self.initial_state

    def on_msg(self, id: Id, state: Cow, src: Id, msg, out: Out) -> None:
        if isinstance(msg, IncrementRequest):
            s = state.value
            state.set(CounterState(s.addr, s.counter + msg.n))
        elif isinstance(msg, ReportRequest):
            out.send(src, ReplyCount(state.value.counter))


@dataclass(frozen=True)
class InputState:
    wait_cycles: int
    success: bool


class Client(Actor):
    """interaction.rs Client: timers drive the interaction script."""

    def __init__(self, threshold: int, counter_addr: Id):
        self.threshold = threshold
        self.counter_addr = counter_addr

    def on_start(self, id: Id, out: Out) -> InputState:
        out.set_timer("ClientInput", model_timeout())
        return InputState(wait_cycles=0, success=False)

    def on_msg(self, id: Id, state: Cow, src: Id, msg, out: Out) -> None:
        if isinstance(msg, ReplyCount) and msg.n >= self.threshold:
            s = state.value
            state.set(InputState(s.wait_cycles, True))

    def on_timeout(self, id: Id, state: Cow, timer, out: Out) -> None:
        s = state.value
        if timer == "ClientInput":
            out.set_timer("ClientQuery", model_timeout())
            out.send(self.counter_addr, IncrementRequest(3))
            state.set(InputState(s.wait_cycles + 1, s.success))
        elif timer == "ClientQuery":
            out.send(self.counter_addr, ReportRequest())
            state.set(InputState(s.wait_cycles + 1, s.success))


def interaction_model(threshold: int = 3) -> ActorModel:
    model = ActorModel()
    model.actor(Client(threshold=threshold, counter_addr=Id(1)))
    model.actor(Counter(CounterState(addr=Id(1), counter=0)))
    model.property(
        Expectation.EVENTUALLY,
        "success",
        lambda m, s: any(
            isinstance(a, InputState) and a.success for a in s.actor_states
        ),
    )
    return model
