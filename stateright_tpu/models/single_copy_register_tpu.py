"""Vectorized single-copy register: second actor-model TPU encoding.

Encodes the full actor-model state of
:mod:`stateright_tpu.models.single_copy_register` (reference
examples/single-copy-register.rs, pinned at 93 states for 2 clients /
1 server) — server value, register clients, the 12-envelope network as
a bitmask, and the in-state ``LinearizabilityTester`` — into 3 uint32
lanes.

Unlike paxos (models/paxos_tpu.py), BOTH clients complete operations
here, so the tester's cross-thread snapshots (linearizability.rs:
114-126) are live data: each client's read invocation records how many
of the peer's operations had completed. The tester state per client is
(phase, read-value, read-snapshot) — 36 combinations — so the
serializer verdict is a 1296-entry truth table precomputed by the REAL
serializer over directly-constructed tester states. This demonstrates
the device-filters/host-precomputes pattern generalizing beyond the
empty-snapshot special case.

Layout (width = 3):
  lane 0: server value (2b) | client actor phases (2b each)
  lane 1: per client 6 bits of tester state: phase(2) rv(2) snapR(2)
  lane 2: network bitmask (12 envelopes)
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..actor import Id
from ..actor.register import Get, GetOk, Put, PutOk
from ..encoding import EncodedModelBase
from ..semantics import LinearizabilityTester, Register
from ..semantics.register import ReadOk, ReadOp, WriteOk, WriteOp
from .single_copy_register import (
    SingleCopyRegisterCfg,
    single_copy_register_model,
)

class SingleCopyEncoded(EncodedModelBase):
    def __init__(self, cfg: SingleCopyRegisterCfg, network=None):
        if cfg.server_count != 1 or cfg.put_count != 1:
            raise ValueError(
                f"SingleCopyEncoded supports 1 server, put_count=1 (got {cfg})"
            )
        if not (1 <= cfg.client_count <= 2):
            raise ValueError("SingleCopyEncoded supports 1-2 clients")
        if network is not None and type(network).__name__ != (
            "UnorderedNonDuplicating"
        ):
            raise ValueError(
                "SingleCopyEncoded models the unordered non-duplicating "
                "network"
            )
        self.cfg = cfg
        self.C = cfg.client_count
        self.clients = list(range(1, 1 + self.C))
        self.values = [chr(ord("A") + i - 1) for i in self.clients]
        self.P = len(self.values)
        self.host_model = single_copy_register_model(cfg)
        self.universe = self._build_universe()
        self.index = {e: k for k, e in enumerate(self.universe)}
        self.K = len(self.universe)
        self.width = 3
        self.max_actions = self.K
        self._lin_table = self._build_lin_table()

    def cache_key(self):
        return (self.C,)

    # -- universe ----------------------------------------------------------
    # Envelope key: (src, dst, kind, arg) with kind put|get|putok|getok.

    def _build_universe(self) -> list:
        u = []
        for j, c in enumerate(self.clients):
            u.append((c, 0, "put", j + 1))
        for c in self.clients:
            u.append((c, 0, "get", 0))
        for j, c in enumerate(self.clients):
            u.append((0, c, "putok", j + 1))
        for c in self.clients:
            for v in range(self.P + 1):  # '\x00' readable before any write
                u.append((0, c, "getok", v))
        return u

    def _value_code(self, value: str) -> int:
        if value == "\x00":
            return 0
        try:
            return 1 + self.values.index(value)
        except ValueError:
            raise ValueError(f"value outside universe: {value!r}")

    def _msg_key(self, src: int, dst: int, msg) -> tuple:
        if isinstance(msg, Put):
            return (src, dst, "put", self._value_code(msg.value))
        if isinstance(msg, Get):
            return (src, dst, "get", 0)
        if isinstance(msg, PutOk):
            j = self.clients.index(msg.req_id)
            return (src, dst, "putok", j + 1)
        if isinstance(msg, GetOk):
            return (src, dst, "getok", self._value_code(msg.value))
        raise ValueError(f"message outside universe: {msg!r}")

    # -- encode ------------------------------------------------------------

    def encode(self, state) -> np.ndarray:
        vec = np.zeros(self.width, dtype=np.uint32)
        server_value = state.actor_states[0].state
        lane0 = self._value_code(server_value)
        for j, c in enumerate(self.clients):
            cs = state.actor_states[c]
            if cs.awaiting == c and cs.op_count == 1:
                phase = 0
            elif cs.awaiting == 2 * c and cs.op_count == 2:
                phase = 1
            elif cs.awaiting is None and cs.op_count == 3:
                phase = 2
            else:
                raise ValueError(f"client state outside universe: {cs!r}")
            lane0 |= phase << (2 + 2 * j)
        vec[0] = lane0
        lane1 = 0
        for j, c in enumerate(self.clients):
            hphase, rv, snap = self._history_fields(state.history, c)
            lane1 |= (hphase | (rv << 2) | (snap << 4)) << (6 * j)
        vec[1] = lane1
        from collections import Counter

        for env, count in Counter(state.network.iter_all()).items():
            if count != 1:
                raise ValueError(
                    f"envelope multiplicity {count} outside universe"
                )
            k = self.index.get(
                self._msg_key(int(env.src), int(env.dst), env.msg)
            )
            if k is None:
                raise ValueError(f"envelope outside universe: {env!r}")
            vec[2] |= np.uint32(1 << k)
        if any(state.crashed) or any(t for t in state.timers_set):
            raise ValueError("crashes/timers outside the universe")
        return vec

    def _history_fields(self, history, c: int) -> Tuple[int, int, int]:
        if not history.is_valid:
            raise ValueError("invalid history outside universe")
        thread = Id(c)
        peer = Id(self.clients[1 - self.clients.index(c)]) if self.C == 2 else None
        completed = dict(history.history_by_thread).get(thread, ())
        in_flight = dict(history.in_flight_by_thread).get(thread)
        j = self.clients.index(c)
        wv = self.values[j]

        def check_w(entry):
            snap, op = entry[0], entry[1]
            if snap != () or not isinstance(op, WriteOp) or op.value != wv:
                raise ValueError(f"history outside universe: {entry!r}")

        def snap_code(snap) -> int:
            if snap == ():
                return 0
            if (
                self.C == 2
                and len(snap) == 1
                and snap[0][0] == peer
                and snap[0][1] in (0, 1)
            ):
                return snap[0][1] + 1
            raise ValueError(f"snapshot outside universe: {snap!r}")

        rv = 0
        snap = 0
        if len(completed) == 0 and in_flight is not None:
            check_w(in_flight)
            phase = 0
        elif len(completed) >= 1:
            check_w(completed[0])
            if not isinstance(completed[0][2], WriteOk):
                raise ValueError(f"history outside universe: {completed!r}")
            if len(completed) == 1 and in_flight is None:
                phase = 1
            elif len(completed) == 1:
                if not isinstance(in_flight[1], ReadOp):
                    raise ValueError(
                        f"history outside universe: {in_flight!r}"
                    )
                snap = snap_code(in_flight[0])
                phase = 2
            elif len(completed) == 2 and in_flight is None:
                s, op, ret = completed[1]
                if not isinstance(op, ReadOp) or not isinstance(ret, ReadOk):
                    raise ValueError(
                        f"history outside universe: {completed!r}"
                    )
                snap = snap_code(s)
                rv = self._value_code(ret.value)
                phase = 3
            else:
                raise ValueError(f"history outside universe: {completed!r}")
        else:
            raise ValueError(f"history outside universe: thread {c}")
        return phase, rv, snap

    def init_vecs(self) -> np.ndarray:
        return np.stack(
            [self.encode(s) for s in self.host_model.init_states()]
        )

    # -- linearizability truth table --------------------------------------

    def _tester_for(self, combos) -> Optional[LinearizabilityTester]:
        """Directly construct the tester state for per-client
        (phase, rv, snap) triples; None if structurally impossible."""
        history = {}
        in_flight = {}
        for j, (phase, rv, snap) in enumerate(combos):
            t = Id(self.clients[j])
            peer = (
                Id(self.clients[1 - j]) if self.C == 2 else None
            )
            wv = self.values[j]
            snap_t = () if snap == 0 else ((peer, snap - 1),)
            if snap != 0 and peer is None:
                return None
            w_done = ((), WriteOp(wv), WriteOk())
            if phase == 0:
                history[t] = ()
                in_flight[t] = ((), WriteOp(wv))
            elif phase == 1:
                if rv or snap:
                    return None
                history[t] = (w_done,)
            elif phase == 2:
                if rv:
                    return None
                history[t] = (w_done,)
                in_flight[t] = (snap_t, ReadOp())
            else:
                v = "\x00" if rv == 0 else self.values[rv - 1]
                history[t] = (
                    w_done,
                    (snap_t, ReadOp(), ReadOk(v)),
                )
        return LinearizabilityTester(
            init_ref_obj=Register("\x00"),
            history_by_thread=tuple(sorted(history.items())),
            in_flight_by_thread=tuple(sorted(in_flight.items())),
        )

    def _build_lin_table(self) -> np.ndarray:
        import itertools

        size = 36 ** self.C
        table = np.zeros(size, dtype=bool)
        for combo in itertools.product(
            range(4), range(3), range(3), repeat=self.C
        ):
            triples = [
                (combo[3 * j], combo[3 * j + 1], combo[3 * j + 2])
                for j in range(self.C)
            ]
            idx = 0
            for ph, rv, sn in triples:
                idx = idx * 36 + (ph * 3 + rv) * 3 + sn
            tester = self._tester_for(triples)
            table[idx] = (
                tester is not None
                and tester.serialized_history() is not None
            )
        return table

    # -- device step -------------------------------------------------------

    def _client_fields(self, vec, j, xp):
        phase = (vec[0] >> xp.uint32(2 + 2 * j)) & xp.uint32(3)
        h = (vec[1] >> xp.uint32(6 * j)) & xp.uint32(0x3F)
        return phase, h & 3, (h >> xp.uint32(2)) & 3, h >> xp.uint32(4)

    def step_vec(self, vec):
        import jax.numpy as jnp

        succs, valids = [], []
        for k, env in enumerate(self.universe):
            s, valid = self._deliver(vec, k, env, jnp)
            succs.append(s)
            valids.append(valid)
        return jnp.stack(succs), jnp.stack(valids)

    def _net(self, vec, k, xp):
        return ((vec[2] >> xp.uint32(k)) & xp.uint32(1)) != 0

    def _deliver(self, vec, k, env, xp):
        src, dst, kind, arg = env
        present = self._net(vec, k, xp)
        net = vec[2] & ~xp.uint32(1 << k)
        if kind == "put":
            # Server: set value, reply PutOk (always handled).
            new0 = (vec[0] & ~xp.uint32(3)) | xp.uint32(arg)
            out = vec.at[0].set(new0)
            ok_bit = self.index[(0, src, "putok", arg)]
            out = out.at[2].set(net | xp.uint32(1 << ok_bit))
            return out, present
        if kind == "get":
            value = vec[0] & xp.uint32(3)
            reply = net
            for v in range(self.P + 1):
                bit = self.index[(0, src, "getok", v)]
                reply = reply | xp.where(
                    value == v, xp.uint32(1 << bit), xp.uint32(0)
                )
            return vec.at[2].set(reply), present
        j = self.clients.index(dst)
        phase, hphase, rv, snap = self._client_fields(vec, j, xp)
        if kind == "putok":
            handled = phase == 0
            new0 = (vec[0] & ~xp.uint32(3 << (2 + 2 * j))) | xp.uint32(
                1 << (2 + 2 * j)
            )
            # History: W returns, R invoked; the snapshot records the
            # peer's completed-op count right now.
            if self.C == 2:
                _, peer_h, _, _ = self._client_fields(vec, 1 - j, xp)
                peer_done = xp.where(
                    peer_h == 0, 0, xp.where(peer_h == 3, 2, 1)
                ).astype(xp.uint32)
            else:
                peer_done = xp.uint32(0)
            h = xp.uint32(2) | (peer_done << xp.uint32(4))  # phase 2, rv 0
            new1 = (
                vec[1] & ~xp.uint32(0x3F << (6 * j))
            ) | (h << xp.uint32(6 * j))
            # The client follows up with its Get (register.rs:144-236).
            get_bit = self.index[(dst, 0, "get", 0)]
            net = net | xp.where(
                handled, xp.uint32(1 << get_bit), xp.uint32(0)
            )
            out = vec.at[0].set(xp.where(handled, new0, vec[0]))
            out = out.at[1].set(xp.where(handled, new1, vec[1]))
            out = out.at[2].set(net)
            return out, present & handled
        if kind == "getok":
            handled = phase == 1
            new0 = (vec[0] & ~xp.uint32(3 << (2 + 2 * j))) | xp.uint32(
                2 << (2 + 2 * j)
            )
            h = (
                xp.uint32(3)
                | (xp.uint32(arg) << xp.uint32(2))
                | (snap << xp.uint32(4))
            )
            new1 = (
                vec[1] & ~xp.uint32(0x3F << (6 * j))
            ) | (h << xp.uint32(6 * j))
            out = vec.at[0].set(xp.where(handled, new0, vec[0]))
            out = out.at[1].set(xp.where(handled, new1, vec[1]))
            out = out.at[2].set(net)
            return out, present & handled
        raise AssertionError(kind)

    # -- properties --------------------------------------------------------

    def property_conditions_vec(self, vec):
        import jax.numpy as jnp

        idx = jnp.uint32(0)
        for j in range(self.C):
            _, hphase, rv, snap = self._client_fields(vec, j, jnp)
            idx = idx * 36 + (hphase * 3 + rv) * 3 + snap
        # The envelope universe is closed (proved by the exhaustive
        # per-state differential test), so no poison guard is needed.
        table = jnp.asarray(self._lin_table)
        linearizable = table[idx]
        chosen = jnp.bool_(False)
        for v in range(1, self.P + 1):
            for c in self.clients:
                bit = self.index[(0, c, "getok", v)]
                chosen = chosen | self._net(vec, bit, jnp)
        return jnp.stack([linearizable, chosen])
