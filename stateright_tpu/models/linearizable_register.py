"""ABD replicated atomic register (Attiya, Bar-Noy, Dolev).

Counterpart of stateright examples/linearizable-register.rs: a
query/record two-phase quorum protocol providing a linearizable
read/write register without consensus. Reference-pinned: 2 clients /
2 servers = 544 unique states (linearizable-register.rs:286, 313).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Optional, Tuple

from ..model import Expectation
from ..actor import (
    Actor,
    ActorModel,
    Cow,
    Id,
    Network,
    Out,
    majority,
    model_peers,
)
from ..actor.register import (
    DEFAULT_VALUE,
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
    record_invocations,
    record_returns,
)
from ..semantics import LinearizabilityTester, Register
from ..utils import HashableMap, HashableSet

# Seq = (logical_clock, writer_id): totally ordered, writer id breaks ties.


@dataclass(frozen=True)
class Query:
    req_id: int


@dataclass(frozen=True)
class AckQuery:
    req_id: int
    seq: Tuple
    value: Any


@dataclass(frozen=True)
class Record:
    req_id: int
    seq: Tuple
    value: Any


@dataclass(frozen=True)
class AckRecord:
    req_id: int


@dataclass(frozen=True)
class Phase1:
    request_id: int
    requester_id: Id
    write: Optional[Any]  # Some(value) for Put, None for Get
    responses: HashableMap  # Id -> (seq, value)


@dataclass(frozen=True)
class Phase2:
    request_id: int
    requester_id: Id
    read: Optional[Any]  # Some(value) for Get, None for Put
    acks: HashableSet


@dataclass(frozen=True)
class AbdState:
    seq: Tuple
    val: Any
    phase: Optional[Any]  # None | Phase1 | Phase2


class AbdActor(Actor):
    def __init__(self, peers: list[Id]):
        self.peers = peers

    def name(self) -> str:
        return "AbdServer"

    def on_start(self, id: Id, out: Out) -> AbdState:
        return AbdState(seq=(0, id), val=DEFAULT_VALUE, phase=None)

    def on_msg(self, id: Id, cow: Cow, src: Id, msg: Any, out: Out) -> None:
        state: AbdState = cow.value

        if isinstance(msg, (Put, Get)) and state.phase is None:
            write = msg.value if isinstance(msg, Put) else None
            out.broadcast(self.peers, Internal(Query(msg.req_id)))
            cow.set(
                replace(
                    state,
                    phase=Phase1(
                        request_id=msg.req_id,
                        requester_id=src,
                        write=write,
                        responses=HashableMap({id: (state.seq, state.val)}),
                    ),
                )
            )

        elif isinstance(msg, Internal) and isinstance(msg.msg, Query):
            out.send(
                src, Internal(AckQuery(msg.msg.req_id, state.seq, state.val))
            )

        elif (
            isinstance(msg, Internal)
            and isinstance(msg.msg, AckQuery)
            and isinstance(state.phase, Phase1)
            and state.phase.request_id == msg.msg.req_id
        ):
            phase = state.phase
            responses = phase.responses.set(src, (msg.msg.seq, msg.msg.value))
            if len(responses) == majority(len(self.peers) + 1):
                # Quorum: adopt the max (seq, value), bump for writes,
                # move to the record phase (linearizable-register.rs:
                # 123-170).
                seq, val = max(responses.values(), key=lambda sv: sv[0])
                read = None
                if phase.write is not None:
                    seq = (seq[0] + 1, id)
                    val = phase.write
                else:
                    read = val
                out.broadcast(
                    self.peers, Internal(Record(phase.request_id, seq, val))
                )
                new_state = state
                if seq > state.seq:
                    new_state = replace(new_state, seq=seq, val=val)
                cow.set(
                    replace(
                        new_state,
                        phase=Phase2(
                            request_id=phase.request_id,
                            requester_id=phase.requester_id,
                            read=read,
                            acks=HashableSet([id]),
                        ),
                    )
                )
            else:
                cow.set(
                    replace(state, phase=replace(phase, responses=responses))
                )

        elif isinstance(msg, Internal) and isinstance(msg.msg, Record):
            out.send(src, Internal(AckRecord(msg.msg.req_id)))
            if msg.msg.seq > state.seq:
                cow.set(replace(state, seq=msg.msg.seq, val=msg.msg.value))

        elif (
            isinstance(msg, Internal)
            and isinstance(msg.msg, AckRecord)
            and isinstance(state.phase, Phase2)
            and state.phase.request_id == msg.msg.req_id
            and src not in state.phase.acks
        ):
            phase = state.phase
            acks = phase.acks.add(src)
            if len(acks) == majority(len(self.peers) + 1):
                if phase.read is not None:
                    out.send(
                        phase.requester_id,
                        GetOk(phase.request_id, phase.read),
                    )
                else:
                    out.send(phase.requester_id, PutOk(phase.request_id))
                cow.set(replace(state, phase=None))
            else:
                cow.set(replace(state, phase=replace(phase, acks=acks)))
        # else: ignored → no-op → pruned


@dataclass(frozen=True)
class AbdModelCfg:
    client_count: int = 2
    server_count: int = 2
    put_count: int = 1


def abd_model(cfg: AbdModelCfg, network: Network | None = None) -> ActorModel:
    if network is None:
        network = Network.new_unordered_nonduplicating()

    def value_chosen(model: ActorModel, state) -> bool:
        for env in state.network.iter_deliverable():
            if isinstance(env.msg, GetOk) and env.msg.value != DEFAULT_VALUE:
                return True
        return False

    model = ActorModel(
        cfg=cfg, init_history=LinearizabilityTester(Register(DEFAULT_VALUE))
    )
    model.add_actors(
        RegisterServer(AbdActor(model_peers(i, cfg.server_count)))
        for i in range(cfg.server_count)
    )
    model.add_actors(
        RegisterClient(put_count=cfg.put_count, server_count=cfg.server_count)
        for _ in range(cfg.client_count)
    )
    model.init_network(network)
    model.property(
        Expectation.ALWAYS,
        "linearizable",
        lambda m, s: s.history.serialized_history() is not None,
    )
    model.property(Expectation.SOMETIMES, "value chosen", value_chosen)
    model.record_msg_in(record_returns)
    model.record_msg_out(record_invocations)
    model.to_encoded = lambda: abd_encoded(model)
    return model


def abd_queue_bounds(cfg: AbdModelCfg):
    """Declared FIFO queue bounds for ABD over an ordered network
    (closure_queue_bound; VERDICT r4 item 4 — lets the ordered
    encoding compile with NO host exploration).

    Protocol reasoning (register.py client loop +
    linearizable-register.rs:123-170 server phases): a client blocks
    awaiting each op's reply, so client↔server channels hold ≤1
    message. A server→server channel (i→j) carries (a) Query+Record
    broadcasts from ops i coordinates — ≤2 per op, and the Phase2
    quorum never requires a PARTICULAR peer, so j can lag i's whole
    op sequence — plus (b) AckQuery+AckRecord replies from i to ops j
    coordinates — ≤2 per op of j. Ops per server are exact from the
    client round-robin (client c's k-th op goes to server (c+k) mod
    server_count, register.py:117-136), giving
    ``2·ops(i) + 2·ops(j)``. The bound only needs to be SAFE, not
    tight: over-declaring costs queue bits (the compiler caps a
    declared bound to what fits the 32-bit lane, with a warning),
    under-declaring raises the engines' truncation flag — never a
    silent truncation.
    """
    S, P = cfg.server_count, cfg.put_count + 1
    ops = [0] * S
    for c in range(S, S + cfg.client_count):
        for k in range(P):
            ops[(c + k) % S] += 1

    def bound(src: int, dst: int) -> int:
        if src >= S or dst >= S:
            return 1  # client↔server: one in-flight op
        return 2 * ops[src] + 2 * ops[dst]

    return bound


def abd_encoded(model: ActorModel, closure: str | None = None,
                queue_bound=None, max_domain: int | None = None):
    """TPU encoding via the generic actor→encoding compiler — ABD has
    no hand-written device code at all. ABD's logical clocks are
    bounded only by system reachability (a write bumps the max quorum
    clock), so the UNBOUNDED overapproximating closure diverges. The
    default mode here is bounded overapproximation (VERDICT r3 #5): the
    protocol invariant "a logical clock never exceeds the number of
    writes issued" (each Put bumps the adopted quorum max by exactly
    one, linearizable-register.rs:123-170) gives
    ``seq[0] <= client_count * put_count``, and the client loop gives
    ``ops per thread <= put_count + 1`` — with those two bounds the
    component fixpoint converges WITHOUT any host exploration, so the
    device does all the search work and the compile cost no longer
    scales with the state space (the round-3 "reachable" mode ran a
    full host BFS at compile time — circular at scale). Soundness of
    the bounds is pinned by the count differentials in
    tests/test_actor_compile.py; ``closure="reachable"`` remains
    available as the harvest/bootstrap mode.
    """
    from ..actor.compile import compile_actor_model
    from ..actor.network import Ordered

    def linearizable(ctx, jnp):
        return (
            ctx.history_value(
                lambda h: int(h.serialized_history() is not None)
            )
            == 1
        )

    def value_chosen_vec(ctx, jnp):
        return ctx.network_any(
            lambda env: isinstance(env.msg, GetOk)
            and env.msg.value != DEFAULT_VALUE
        )

    cfg = model.cfg
    ordered = isinstance(model._init_network, Ordered)
    if closure is None:
        # Bounded overapproximation everywhere: ordered networks get
        # DECLARED queue bounds (abd_queue_bounds) instead of the
        # round-4 reachable-mode fallback, whose compile-time host BFS
        # of the full space was circular at scale (VERDICT r4 item 4).
        closure = "overapprox"
    if ordered and closure == "overapprox" and queue_bound is None:
        queue_bound = abd_queue_bounds(cfg)
    w_max = cfg.client_count * cfg.put_count

    def seq_ok(seq) -> bool:
        return seq[0] <= w_max

    def actor_bound(i: int, s) -> bool:
        if i >= cfg.server_count:
            return True  # clients: op_count is self-bounded
        inner = s.state  # RegisterServer wraps AbdState
        if not seq_ok(inner.seq):
            return False
        ph = inner.phase
        if isinstance(ph, Phase1):
            return all(seq_ok(sv[0]) for sv in ph.responses.values())
        return True

    def history_bound(h) -> bool:
        per_thread = dict(h.history_by_thread)
        in_flight = dict(h.in_flight_by_thread)
        for t, completed in per_thread.items():
            ops = len(completed) + (1 if in_flight.get(t) else 0)
            if ops > cfg.put_count + 1:
                return False
        # Reachable ABD histories are linearizable (the ALWAYS property
        # this model checks). Bounding EXPANSION to linearizable
        # histories is sound for that property: a bounded-out history
        # is kept un-expanded, so the first non-linearizable history —
        # were one ever reachable — still enters the domain and trips
        # the property. This is what tames the overapprox tester-state
        # combinatorics at 3 clients.
        return h.serialized_history() is not None

    if max_domain is None:
        # The bounded history domain (≤ put_count+1 ops per thread,
        # linearizable-expansion) converges but GROWS steeply with
        # client count: 2c fits the 32k default; the driver config
        # `linearizable-register check 4 ordered` (BASELINE.md:32)
        # needs a wider divergence guard, not a different bound.
        # Measured closure wall time on the build box's single CPU
        # core (round 5): 2c/3s ordered ≈ 2s, 3c/3s ≈ 120s, 4c/3s
        # exceeded 2h without finishing (each client multiplies the
        # serializer-checked history domain ~60x) — the 4c closure is
        # a batch job, and its run needs the sharded mesh anyway
        # (PERF.md §ordered).
        max_domain = 1 << 15 if cfg.client_count <= 2 else 1 << 22
    return compile_actor_model(
        model,
        properties={
            "linearizable": linearizable,
            "value chosen": value_chosen_vec,
        },
        closure=closure,
        closure_actor_bound=actor_bound,
        closure_history_bound=history_bound,
        closure_queue_bound=queue_bound,
        max_domain=max_domain,
    )
