"""Vectorized N-client write-once register.

Encodes :class:`~stateright_tpu.models.nclient_register.NClientRegSys`
as packed fixed-width uint32 vectors, the second encoding to declare a
``DeviceRewriteSpec`` — and the first whose symmetry/reduction
soundness is established by the static analyzer
(stateright_tpu/analysis/soundness.py) instead of a hand argument.

Packed layout (``width = 2`` for up to 8 clients):
  lane 0: bit 0 — the write-once register
  lane 1: per-client 4-bit block at shift ``4c``:
          bits 0-1 pc (0=idle 1=wrote 2=done), bit 2 won, bit 3 rv

Actions (``max_actions = 2n``): slot ``2c`` = write(c), slot
``2c + 1`` = read(c). Every slot guard is a 2-bit field compare, so
the sparse dispatch path assembles the packed enabled words from
``2n`` condition-gated host class masks — scalar extracts only, no
gather, no dense ``bool[K]`` (the 2pc idiom, ops/bitmask.py).

The client blocks are uniformly strided with every bit in the sort
key, so ``device_rewrite_spec()`` is a full-tuple (perfect)
canonicalizer; the host oracle is
``NClientRegState.representative_full``.
"""

from __future__ import annotations

import numpy as np

from ..encoding import EncodedModelBase
from .nclient_register import NClientRegState, NClientRegSys

_IDLE, _WROTE, _DONE = 0, 1, 2


class NClientRegEncoded(EncodedModelBase):
    def __init__(self, n_clients: int):
        if n_clients > 8:
            raise ValueError(
                "packed register encoding supports up to 8 clients "
                f"(got {n_clients})"
            )
        self.n_clients = n_clients
        self.width = 2
        self.max_actions = 2 * n_clients
        self.host_model = NClientRegSys(n_clients=n_clients)
        #: each client enables at most ONE of its two slots (write
        #: xor read, by pc), so a row peaks at n enabled slots.
        self.pair_width_hint = max(1, n_clients)

    def cache_key(self):
        """Compiled-wave sharing identity (see checkers/tpu.py)."""
        return self.n_clients

    # -- device symmetry -------------------------------------------------

    def device_rewrite_spec(self):
        """Client permutation symmetry: one strided 4-bit field on
        lane 1 holding the FULL per-client tuple (pc, won, rv), all
        of it in the sort key — a perfect canonicalizer, certified by
        ``stateright_tpu analyze soundness register`` (SOUND_r*)."""
        if self.n_clients < 2:
            return None
        from ..ops.canonical import DeviceRewriteSpec, MemberField

        return DeviceRewriteSpec(
            n_members=self.n_clients,
            fields=(
                MemberField(
                    lane=1, shift=0, stride=4, width=4, sort_key=True
                ),
            ),
        )

    # -- host side -------------------------------------------------------

    def encode(self, state: NClientRegState) -> np.ndarray:
        lane1 = 0
        for c, (pc, won, rv) in enumerate(state.clients):
            lane1 |= (pc | (won << 2) | (rv << 3)) << (4 * c)
        return np.array([state.reg, lane1], dtype=np.uint32)

    def decode(self, vec: np.ndarray) -> NClientRegState:
        vec = np.asarray(vec)
        lane0, lane1 = int(vec[0]), int(vec[1])
        clients = []
        for c in range(self.n_clients):
            block = (lane1 >> (4 * c)) & 0xF
            clients.append((block & 3, (block >> 2) & 1, (block >> 3) & 1))
        return NClientRegState(clients=tuple(clients), reg=lane0 & 1)

    def init_vecs(self) -> np.ndarray:
        return np.stack(
            [self.encode(s) for s in self.host_model.init_states()]
        )

    # -- device side -----------------------------------------------------

    def step_vec(self, vec):
        """uint32[2] -> (uint32[K, 2], bool[K]); branchless bitfield
        updates mirroring NClientRegSys.next_state()."""
        import jax.numpy as jnp

        lane0, lane1 = vec[0], vec[1]
        reg = lane0 & jnp.uint32(1)
        won_new = (jnp.uint32(1) - reg) & jnp.uint32(1)

        succs = []
        valids = []
        for c in range(self.n_clients):
            sh = 4 * c
            block = (lane1 >> jnp.uint32(sh)) & jnp.uint32(0xF)
            pc = block & jnp.uint32(3)
            clear = lane1 & ~jnp.uint32(0xF << sh)

            # write(c): pc 0→1, won := (reg was 0), register set.
            wr_block = (
                (block & ~jnp.uint32(0x7))
                | jnp.uint32(_WROTE)
                | (won_new << jnp.uint32(2))
            )
            succs.append(
                jnp.stack([lane0 | jnp.uint32(1),
                           clear | (wr_block << jnp.uint32(sh))])
            )
            valids.append(pc == _IDLE)

            # read(c): pc 1→2, rv := reg, won kept.
            rd_block = (
                (block & ~jnp.uint32(0xB))
                | jnp.uint32(_DONE)
                | (reg << jnp.uint32(3))
            )
            succs.append(
                jnp.stack([lane0,
                           clear | (rd_block << jnp.uint32(sh))])
            )
            valids.append(pc == _WROTE)

        return jnp.stack(succs), jnp.stack(valids)

    # -- sparse action dispatch (SparseEncodedModel) ----------------------

    def _bits_word_tables(self) -> dict:
        """Host-constant per-slot masks (the 2pc idiom): slot ``2c``
        gated on pc==idle, slot ``2c+1`` on pc==wrote."""
        if hasattr(self, "_bw"):
            return self._bw
        from ..ops.bitmask import slot_mask_host

        K = self.max_actions
        self._bw = dict(
            write={
                c: slot_mask_host(K, [2 * c])
                for c in range(self.n_clients)
            },
            read={
                c: slot_mask_host(K, [2 * c + 1])
                for c in range(self.n_clients)
            },
        )
        return self._bw

    def enabled_bits_vec(self, vec):
        """``uint32[ceil(K/32)]`` packed enabled mask from ``2n``
        condition-gated host class masks — scalar extracts + [L]-word
        selects, gather-free."""
        import jax.numpy as jnp

        from ..ops.bitmask import mask_words, or_class_words

        t = self._bits_word_tables()
        lane1 = vec[1]
        classes = []
        for c in range(self.n_clients):
            pc = (lane1 >> jnp.uint32(4 * c)) & jnp.uint32(3)
            classes.append((pc == _IDLE, t["write"][c]))
            classes.append((pc == _WROTE, t["read"][c]))
        return or_class_words(
            jnp, classes, mask_words(self.max_actions)
        )

    def enabled_mask_vec(self, vec):
        """bool[K]: the dense view of :meth:`enabled_bits_vec` (the
        words are the source of truth, so the two cannot drift)."""
        import jax.numpy as jnp

        from ..ops.bitmask import words_to_mask

        return words_to_mask(
            jnp, self.enabled_bits_vec(vec), self.max_actions
        )

    def step_slot_vec(self, vec, slot):
        """Successor for one enabled (state, slot) pair — branchless
        selects over the slot arithmetic (``c = slot >> 1``, action
        kind ``slot & 1``), 1-D lane ops only, zero gathers."""
        import jax.numpy as jnp

        lane0, lane1 = vec[0], vec[1]
        slot = slot.astype(jnp.uint32)
        c = slot >> jnp.uint32(1)
        j = slot & jnp.uint32(1)
        sh = jnp.uint32(4) * c

        reg = lane0 & jnp.uint32(1)
        won_new = (jnp.uint32(1) - reg) & jnp.uint32(1)
        block = (lane1 >> sh) & jnp.uint32(0xF)
        clear = lane1 & ~(jnp.uint32(0xF) << sh)

        wr_block = (
            (block & ~jnp.uint32(0x7))
            | jnp.uint32(_WROTE)
            | (won_new << jnp.uint32(2))
        )
        rd_block = (
            (block & ~jnp.uint32(0xB))
            | jnp.uint32(_DONE)
            | (reg << jnp.uint32(3))
        )
        nb = jnp.where(j == 0, wr_block, rd_block)
        l0 = jnp.where(j == 0, lane0 | jnp.uint32(1), lane0)
        l1 = clear | (nb << sh)
        return jnp.stack([l0, l1])

    def property_conditions_vec(self, vec):
        """[sometimes all done, sometimes lost write, always at most
        one winner, always reads see the write] — order matches
        NClientRegSys.properties(). Every predicate is a reduction
        over the uniformly extracted per-client blocks, so the
        soundness analyzer proves group invariance statically."""
        import jax.numpy as jnp

        n = self.n_clients
        blocks = (
            vec[1] >> (4 * jnp.arange(n, dtype=jnp.uint32))
        ) & jnp.uint32(0xF)
        pc = blocks & jnp.uint32(3)
        won = (blocks >> jnp.uint32(2)) & jnp.uint32(1)
        rv = (blocks >> jnp.uint32(3)) & jnp.uint32(1)
        all_done = jnp.all(pc == _DONE)
        lost = jnp.any((pc != _IDLE) & (won == 0))
        at_most_one = jnp.sum(won) <= jnp.uint32(1)
        reads_ok = jnp.all((pc != _DONE) | (rv == 1))
        return jnp.stack([all_done, lost, at_most_one, reads_ok])
