"""Vectorized two-phase commit: the TPU-engine proving ground.

Encodes :class:`~stateright_tpu.models.two_phase_commit.TwoPhaseSys`
(reference examples/2pc.rs) as PACKED fixed-width uint32 vectors, with
the whole action set generated branchlessly per state — the
``#[derive(TpuState)]`` pattern from the north star, done by hand
(SURVEY.md §7 step 2 names 2pc as the proving ground).

Packed layout (``width = 2`` for up to 10 RMs):
  lane 0: rm_state enum, 2 bits per RM (0=Working 1=Prepared
          2=Committed 3=Aborted)
  lane 1: bits 0-1   tm_state enum (0=Init 1=Committed 2=Aborted)
          bits 2..   tm_prepared bitmask (N bits)
          then       message-set bitmask: commit, abort, prepared(rm)

Width drives the engine's hot-loop cost directly — the flat successor
tensor is ``F*K*W`` lanes and the splitmix64 fingerprint does one
u64 fold per lane — so the packed layout is ~6x cheaper per wave than
a lane-per-RM layout at rm=9/10 benchmark scale.

Actions (``max_actions = 2 + 5*N``), mirroring 2pc.rs actions():
  0: tm_commit        1: tm_abort
  per rm: tm_rcv_prepared, rm_prepare, rm_choose_abort,
          rm_rcv_commit, rm_rcv_abort

Sparse action dispatch (round 6): the encoding also implements
``SparseEncodedModel`` with a WORD-NATIVE ``enabled_bits_vec`` — every
slot guard is a small function of one 2-bit RM field or a TM/message
bit, so the packed ``uint32[ceil(K/32)]`` mask assembles from
``4 + 2N`` condition-gated host-constant class masks (ops/bitmask.py
builders) and no dense ``bool[K]`` row ever materializes. The engine's
enabled-predicate pass therefore runs on K/32 word lanes for 2pc the
same way it does for paxos and the compiled actor encodings.
"""

from __future__ import annotations

import numpy as np

from ..encoding import EncodedModelBase
from .two_phase_commit import RmState, TmState, TwoPhaseState, TwoPhaseSys

_WORKING, _PREPARED, _COMMITTED, _ABORTED = 0, 1, 2, 3
_INIT, _TM_COMMITTED, _TM_ABORTED = 0, 1, 2


class TwoPhaseSysEncoded(EncodedModelBase):
    def __init__(self, rm_count: int):
        if rm_count > 10:
            raise ValueError(
                f"packed 2pc encoding supports up to 10 RMs (got {rm_count})"
            )
        self.rm_count = rm_count
        self.width = 2
        self.max_actions = 2 + 5 * rm_count
        #: lane-1 bit offsets
        self._prep_shift = 2
        self._msgs_shift = 2 + rm_count
        self.host_model = TwoPhaseSys(rm_count=rm_count)
        #: exact per-row enabled-slot peak: after tm_abort a working RM
        #: enables prepare + choose_abort + rcv_abort (3 each, 3N);
        #: under TM Init a row caps at 2 TM slots + 2 per RM (working
        #: and prepared-msg slots are exclusive per RM). The engine
        #: detects overflow loudly if this reasoning ever breaks.
        self.pair_width_hint = min(
            max(3 * rm_count, 2 * rm_count + 2), self.max_actions
        )

    def cache_key(self):
        """Compiled-wave sharing identity (see checkers/tpu.py)."""
        return self.rm_count

    # -- device symmetry / reduction hooks -------------------------------

    def device_rewrite_spec(self):
        """RM permutation symmetry as strided bit-fields: member
        ``m``'s tuple is (rm_state lane0 bits [2m, 2m+2),
        tm_prepared lane1 bit _prep_shift+m, prepared-msg lane1 bit
        _msgs_shift+2+m). ALL three fields are sort keys — the FULL
        per-member tuple — so the canonicalizer is constant on orbits
        and the reduced count is search-order-independent (rm=5:
        8,832 → 314; the reference's 665 is a DFS-order artifact of
        its rm_state-only sort, see symmetry.py). The host oracle is
        ``TwoPhaseState.representative_full``, which sorts the same
        tuple in the same encoded order."""
        if self.rm_count < 2:
            return None
        from ..ops.canonical import DeviceRewriteSpec, MemberField

        return DeviceRewriteSpec(
            n_members=self.rm_count,
            fields=(
                MemberField(
                    lane=0, shift=0, stride=2, width=2, sort_key=True
                ),
                MemberField(
                    lane=1, shift=self._prep_shift, stride=1, width=1,
                    sort_key=True,
                ),
                MemberField(
                    lane=1, shift=self._msgs_shift + 2, stride=1,
                    width=1, sort_key=True,
                ),
            ),
        )

    def ample_mask_host(self):
        """Static partial-order ample-set filter: keep
        ``rm_choose_abort`` (slot 4+5·rm) only for rm 0, drop it for
        rm ≥ 1.

        Soundness for THIS property set (all state predicates, no
        EVENTUALLY liveness): spontaneous aborts of distinct RMs
        commute with every other action and with each other, and each
        property's witness states stay reachable with only rm 0's
        spontaneous abort available — "abort agreement" is reachable
        via tm_abort + rm_rcv_abort alone, "commit agreement" via the
        all-prepare path (which never needs choose_abort), and the
        ALWAYS property "consistent" is checked on every state the
        filtered search DOES reach, a subset of the full space, so it
        can produce no false violation; a missed violation would need
        a state whose every path uses a choose_abort by rm ≥ 1, and by
        RM symmetry such a path maps to one using rm 0's. Combining
        this filter with --symmetry is safe here because the mask is
        NOT group-invariant pointwise but the symmetry argument above
        already quotients by the group; for other encodings the
        engines make no such inference — the encoding owns the
        argument."""
        from ..ops.bitmask import pack_bits_host

        keep = np.ones(self.max_actions, dtype=bool)
        for rm in range(1, self.rm_count):
            keep[4 + 5 * rm] = False
        return pack_bits_host(keep)

    # -- host side -------------------------------------------------------

    def encode(self, state: TwoPhaseState) -> np.ndarray:
        n = self.rm_count
        lane0 = 0
        for i, rm in enumerate(state.rm_state):
            lane0 |= rm.value << (2 * i)
        lane1 = state.tm_state.value
        for i, p in enumerate(state.tm_prepared):
            if p:
                lane1 |= 1 << (self._prep_shift + i)
        for m in state.msgs:
            if m == ("commit",):
                lane1 |= 1 << self._msgs_shift
            elif m == ("abort",):
                lane1 |= 1 << (self._msgs_shift + 1)
            else:
                lane1 |= 1 << (self._msgs_shift + 2 + m[1])
        return np.array([lane0, lane1], dtype=np.uint32)

    def decode(self, vec: np.ndarray) -> TwoPhaseState:
        n = self.rm_count
        vec = np.asarray(vec)
        lane0, lane1 = int(vec[0]), int(vec[1])
        msgs = set()
        m = lane1 >> self._msgs_shift
        if m & 1:
            msgs.add(("commit",))
        if m & 2:
            msgs.add(("abort",))
        for i in range(n):
            if m & (1 << (2 + i)):
                msgs.add(("prepared", i))
        return TwoPhaseState(
            rm_state=tuple(
                RmState((lane0 >> (2 * i)) & 3) for i in range(n)
            ),
            tm_state=TmState(lane1 & 3),
            tm_prepared=tuple(
                bool(lane1 & (1 << (self._prep_shift + i)))
                for i in range(n)
            ),
            msgs=frozenset(msgs),
        )

    def init_vecs(self) -> np.ndarray:
        return np.stack(
            [self.encode(s) for s in self.host_model.init_states()]
        )

    # -- device side -----------------------------------------------------

    def step_vec(self, vec):
        """uint32[2] -> (uint32[K, 2], bool[K]); mirrors 2pc.rs
        actions()/next_state() as branchless bitfield updates."""
        import jax.numpy as jnp

        n = self.rm_count
        ps, ms = self._prep_shift, self._msgs_shift
        lane0, lane1 = vec[0], vec[1]
        tm = lane1 & jnp.uint32(3)
        prep = (lane1 >> jnp.uint32(ps)) & jnp.uint32((1 << n) - 1)
        commit_bit = jnp.uint32(1 << ms)
        abort_bit = jnp.uint32(1 << (ms + 1))
        full_prep = jnp.uint32((1 << n) - 1)

        succs = []
        valids = []

        def with_tm(l1, value):
            return (l1 & ~jnp.uint32(3)) | jnp.uint32(value)

        # tm_commit: all prepared & TM still deciding.
        succs.append(
            jnp.stack([lane0, with_tm(lane1, _TM_COMMITTED) | commit_bit])
        )
        valids.append((tm == _INIT) & (prep == full_prep))

        # tm_abort
        succs.append(
            jnp.stack([lane0, with_tm(lane1, _TM_ABORTED) | abort_bit])
        )
        valids.append(tm == _INIT)

        for rm in range(n):
            rm_state = (lane0 >> jnp.uint32(2 * rm)) & jnp.uint32(3)
            rm_working = rm_state == _WORKING
            prepared_bit = jnp.uint32(1 << (ms + 2 + rm))

            def with_rm(l0, value):
                return (l0 & ~jnp.uint32(3 << (2 * rm))) | jnp.uint32(
                    value << (2 * rm)
                )

            # tm_rcv_prepared(rm)
            succs.append(
                jnp.stack([lane0, lane1 | jnp.uint32(1 << (ps + rm))])
            )
            valids.append((tm == _INIT) & ((lane1 & prepared_bit) != 0))

            # rm_prepare(rm)
            succs.append(
                jnp.stack([with_rm(lane0, _PREPARED), lane1 | prepared_bit])
            )
            valids.append(rm_working)

            # rm_choose_abort(rm)
            succs.append(jnp.stack([with_rm(lane0, _ABORTED), lane1]))
            valids.append(rm_working)

            # rm_rcv_commit(rm)
            succs.append(jnp.stack([with_rm(lane0, _COMMITTED), lane1]))
            valids.append((lane1 & commit_bit) != 0)

            # rm_rcv_abort(rm)
            succs.append(jnp.stack([with_rm(lane0, _ABORTED), lane1]))
            valids.append((lane1 & abort_bit) != 0)

        return jnp.stack(succs), jnp.stack(valids)

    # -- sparse action dispatch (SparseEncodedModel, round 6) -------------

    def _bits_word_tables(self) -> dict:
        """Host-constant guard-class masks (see the module docstring):
        slots sharing one enabling condition share one packed mask."""
        if hasattr(self, "_bw"):
            return self._bw
        from ..ops.bitmask import slot_mask_host

        n, K = self.rm_count, self.max_actions
        self._bw = dict(
            tm_commit=slot_mask_host(K, [0]),
            tm_abort=slot_mask_host(K, [1]),
            rcv_commit=slot_mask_host(
                K, [5 + 5 * rm for rm in range(n)]
            ),
            rcv_abort=slot_mask_host(
                K, [6 + 5 * rm for rm in range(n)]
            ),
            working={
                rm: slot_mask_host(K, [3 + 5 * rm, 4 + 5 * rm])
                for rm in range(n)
            },
            rcv_prep={
                rm: slot_mask_host(K, [2 + 5 * rm]) for rm in range(n)
            },
        )
        return self._bw

    def enabled_bits_vec(self, vec):
        """``uint32[ceil(K/32)]`` packed enabled mask, word-native: an
        OR of ``4 + 2N`` condition-gated host class masks — pure
        scalar field extracts plus [L]-word selects, no gather, no
        dense ``bool[K]``."""
        import jax.numpy as jnp

        from ..ops.bitmask import mask_words, or_class_words

        t = self._bits_word_tables()
        n = self.rm_count
        ps, ms = self._prep_shift, self._msgs_shift
        lane0, lane1 = vec[0], vec[1]
        tm_init = (lane1 & jnp.uint32(3)) == 0
        prep = (lane1 >> jnp.uint32(ps)) & jnp.uint32((1 << n) - 1)
        classes = [
            (tm_init & (prep == jnp.uint32((1 << n) - 1)),
             t["tm_commit"]),
            (tm_init, t["tm_abort"]),
            ((lane1 & jnp.uint32(1 << ms)) != 0, t["rcv_commit"]),
            ((lane1 & jnp.uint32(1 << (ms + 1))) != 0, t["rcv_abort"]),
        ]
        for rm in range(n):
            working = (
                (lane0 >> jnp.uint32(2 * rm)) & jnp.uint32(3)
            ) == 0
            prepared_msg = (
                lane1 & jnp.uint32(1 << (ms + 2 + rm))
            ) != 0
            classes.append((working, t["working"][rm]))
            classes.append((tm_init & prepared_msg, t["rcv_prep"][rm]))
        return or_class_words(
            jnp, classes, mask_words(self.max_actions)
        )

    def enabled_mask_vec(self, vec):
        """bool[K]: the dense view of :meth:`enabled_bits_vec` (the
        words are the source of truth, so the two cannot drift) —
        equals ``step_vec``'s validity, pinned exhaustively by
        tests/test_sortmerge.py over the rm=3 space."""
        import jax.numpy as jnp

        from ..ops.bitmask import words_to_mask

        return words_to_mask(
            jnp, self.enabled_bits_vec(vec), self.max_actions
        )

    def step_slot_vec(self, vec, slot):
        """Successor for one enabled (state, slot) pair — branchless
        selects over the slot arithmetic (``rm = (slot-2) // 5``,
        action kind ``(slot-2) % 5``), 1-D lane ops only, zero
        gathers (the per-slot constants are arithmetic in the slot
        index, so no table is needed at all)."""
        import jax.numpy as jnp

        ps, ms = self._prep_shift, self._msgs_shift
        lane0, lane1 = vec[0], vec[1]
        slot = slot.astype(jnp.uint32)
        rmslot = jnp.where(slot >= 2, slot - jnp.uint32(2),
                           jnp.uint32(0))
        rm = rmslot // jnp.uint32(5)
        j = rmslot % jnp.uint32(5)
        sh2 = jnp.uint32(2) * rm

        # TM verdicts (slots 0/1).
        tm_clear = lane1 & ~jnp.uint32(3)
        l1_commit = tm_clear | jnp.uint32(_TM_COMMITTED) | jnp.uint32(
            1 << ms
        )
        l1_abort = tm_clear | jnp.uint32(_TM_ABORTED) | jnp.uint32(
            1 << (ms + 1)
        )
        # Per-RM lane updates (slots 2+5rm+j), shift amounts traced.
        prepared_bit = jnp.uint32(1) << (jnp.uint32(ms + 2) + rm)
        l1_rcv_prep = lane1 | (jnp.uint32(1) << (jnp.uint32(ps) + rm))
        rm_clear = lane0 & ~(jnp.uint32(3) << sh2)
        l0_prepared = rm_clear | (jnp.uint32(_PREPARED) << sh2)
        l0_committed = rm_clear | (jnp.uint32(_COMMITTED) << sh2)
        l0_aborted = rm_clear | (jnp.uint32(_ABORTED) << sh2)

        l0_rm = jnp.where(
            j == 1,
            l0_prepared,
            jnp.where(
                j == 3,
                l0_committed,
                jnp.where((j == 2) | (j == 4), l0_aborted, lane0),
            ),
        )
        l1_rm = jnp.where(
            j == 0,
            l1_rcv_prep,
            jnp.where(j == 1, lane1 | prepared_bit, lane1),
        )
        tm_slot = slot < 2
        l0 = jnp.where(tm_slot, lane0, l0_rm)
        l1 = jnp.where(
            slot == 0,
            l1_commit,
            jnp.where(slot == 1, l1_abort, l1_rm),
        )
        return jnp.stack([l0, l1])

    def property_conditions_vec(self, vec):
        """[sometimes abort agreement, sometimes commit agreement,
        always consistent] — order matches TwoPhaseSys.properties()."""
        import jax.numpy as jnp

        n = self.rm_count
        rms = (
            vec[0] >> (2 * jnp.arange(n, dtype=jnp.uint32))
        ) & jnp.uint32(3)
        all_aborted = jnp.all(rms == _ABORTED)
        all_committed = jnp.all(rms == _COMMITTED)
        consistent = ~(
            jnp.any(rms == _ABORTED) & jnp.any(rms == _COMMITTED)
        )
        return jnp.stack([all_aborted, all_committed, consistent])
