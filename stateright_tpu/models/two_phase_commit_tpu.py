"""Vectorized two-phase commit: the TPU-engine proving ground.

Encodes :class:`~stateright_tpu.models.two_phase_commit.TwoPhaseSys`
(reference examples/2pc.rs) as PACKED fixed-width uint32 vectors, with
the whole action set generated branchlessly per state — the
``#[derive(TpuState)]`` pattern from the north star, done by hand
(SURVEY.md §7 step 2 names 2pc as the proving ground).

Packed layout (``width = 2`` for up to 10 RMs):
  lane 0: rm_state enum, 2 bits per RM (0=Working 1=Prepared
          2=Committed 3=Aborted)
  lane 1: bits 0-1   tm_state enum (0=Init 1=Committed 2=Aborted)
          bits 2..   tm_prepared bitmask (N bits)
          then       message-set bitmask: commit, abort, prepared(rm)

Width drives the engine's hot-loop cost directly — the flat successor
tensor is ``F*K*W`` lanes and the splitmix64 fingerprint does one
u64 fold per lane — so the packed layout is ~6x cheaper per wave than
a lane-per-RM layout at rm=9/10 benchmark scale.

Actions (``max_actions = 2 + 5*N``), mirroring 2pc.rs actions():
  0: tm_commit        1: tm_abort
  per rm: tm_rcv_prepared, rm_prepare, rm_choose_abort,
          rm_rcv_commit, rm_rcv_abort
"""

from __future__ import annotations

import numpy as np

from ..encoding import EncodedModelBase
from .two_phase_commit import RmState, TmState, TwoPhaseState, TwoPhaseSys

_WORKING, _PREPARED, _COMMITTED, _ABORTED = 0, 1, 2, 3
_INIT, _TM_COMMITTED, _TM_ABORTED = 0, 1, 2


class TwoPhaseSysEncoded(EncodedModelBase):
    def __init__(self, rm_count: int):
        if rm_count > 10:
            raise ValueError(
                f"packed 2pc encoding supports up to 10 RMs (got {rm_count})"
            )
        self.rm_count = rm_count
        self.width = 2
        self.max_actions = 2 + 5 * rm_count
        #: lane-1 bit offsets
        self._prep_shift = 2
        self._msgs_shift = 2 + rm_count
        self.host_model = TwoPhaseSys(rm_count=rm_count)

    def cache_key(self):
        """Compiled-wave sharing identity (see checkers/tpu.py)."""
        return self.rm_count

    # -- host side -------------------------------------------------------

    def encode(self, state: TwoPhaseState) -> np.ndarray:
        n = self.rm_count
        lane0 = 0
        for i, rm in enumerate(state.rm_state):
            lane0 |= rm.value << (2 * i)
        lane1 = state.tm_state.value
        for i, p in enumerate(state.tm_prepared):
            if p:
                lane1 |= 1 << (self._prep_shift + i)
        for m in state.msgs:
            if m == ("commit",):
                lane1 |= 1 << self._msgs_shift
            elif m == ("abort",):
                lane1 |= 1 << (self._msgs_shift + 1)
            else:
                lane1 |= 1 << (self._msgs_shift + 2 + m[1])
        return np.array([lane0, lane1], dtype=np.uint32)

    def decode(self, vec: np.ndarray) -> TwoPhaseState:
        n = self.rm_count
        vec = np.asarray(vec)
        lane0, lane1 = int(vec[0]), int(vec[1])
        msgs = set()
        m = lane1 >> self._msgs_shift
        if m & 1:
            msgs.add(("commit",))
        if m & 2:
            msgs.add(("abort",))
        for i in range(n):
            if m & (1 << (2 + i)):
                msgs.add(("prepared", i))
        return TwoPhaseState(
            rm_state=tuple(
                RmState((lane0 >> (2 * i)) & 3) for i in range(n)
            ),
            tm_state=TmState(lane1 & 3),
            tm_prepared=tuple(
                bool(lane1 & (1 << (self._prep_shift + i)))
                for i in range(n)
            ),
            msgs=frozenset(msgs),
        )

    def init_vecs(self) -> np.ndarray:
        return np.stack(
            [self.encode(s) for s in self.host_model.init_states()]
        )

    # -- device side -----------------------------------------------------

    def step_vec(self, vec):
        """uint32[2] -> (uint32[K, 2], bool[K]); mirrors 2pc.rs
        actions()/next_state() as branchless bitfield updates."""
        import jax.numpy as jnp

        n = self.rm_count
        ps, ms = self._prep_shift, self._msgs_shift
        lane0, lane1 = vec[0], vec[1]
        tm = lane1 & jnp.uint32(3)
        prep = (lane1 >> jnp.uint32(ps)) & jnp.uint32((1 << n) - 1)
        commit_bit = jnp.uint32(1 << ms)
        abort_bit = jnp.uint32(1 << (ms + 1))
        full_prep = jnp.uint32((1 << n) - 1)

        succs = []
        valids = []

        def with_tm(l1, value):
            return (l1 & ~jnp.uint32(3)) | jnp.uint32(value)

        # tm_commit: all prepared & TM still deciding.
        succs.append(
            jnp.stack([lane0, with_tm(lane1, _TM_COMMITTED) | commit_bit])
        )
        valids.append((tm == _INIT) & (prep == full_prep))

        # tm_abort
        succs.append(
            jnp.stack([lane0, with_tm(lane1, _TM_ABORTED) | abort_bit])
        )
        valids.append(tm == _INIT)

        for rm in range(n):
            rm_state = (lane0 >> jnp.uint32(2 * rm)) & jnp.uint32(3)
            rm_working = rm_state == _WORKING
            prepared_bit = jnp.uint32(1 << (ms + 2 + rm))

            def with_rm(l0, value):
                return (l0 & ~jnp.uint32(3 << (2 * rm))) | jnp.uint32(
                    value << (2 * rm)
                )

            # tm_rcv_prepared(rm)
            succs.append(
                jnp.stack([lane0, lane1 | jnp.uint32(1 << (ps + rm))])
            )
            valids.append((tm == _INIT) & ((lane1 & prepared_bit) != 0))

            # rm_prepare(rm)
            succs.append(
                jnp.stack([with_rm(lane0, _PREPARED), lane1 | prepared_bit])
            )
            valids.append(rm_working)

            # rm_choose_abort(rm)
            succs.append(jnp.stack([with_rm(lane0, _ABORTED), lane1]))
            valids.append(rm_working)

            # rm_rcv_commit(rm)
            succs.append(jnp.stack([with_rm(lane0, _COMMITTED), lane1]))
            valids.append((lane1 & commit_bit) != 0)

            # rm_rcv_abort(rm)
            succs.append(jnp.stack([with_rm(lane0, _ABORTED), lane1]))
            valids.append((lane1 & abort_bit) != 0)

        return jnp.stack(succs), jnp.stack(valids)

    def property_conditions_vec(self, vec):
        """[sometimes abort agreement, sometimes commit agreement,
        always consistent] — order matches TwoPhaseSys.properties()."""
        import jax.numpy as jnp

        n = self.rm_count
        rms = (
            vec[0] >> (2 * jnp.arange(n, dtype=jnp.uint32))
        ) & jnp.uint32(3)
        all_aborted = jnp.all(rms == _ABORTED)
        all_committed = jnp.all(rms == _COMMITTED)
        consistent = ~(
            jnp.any(rms == _ABORTED) & jnp.any(rms == _COMMITTED)
        )
        return jnp.stack([all_aborted, all_committed, consistent])
