"""Vectorized two-phase commit: the TPU-engine proving ground.

Encodes :class:`~stateright_tpu.models.two_phase_commit.TwoPhaseSys`
(reference examples/2pc.rs) as fixed-width uint32 vectors, with the
whole action set generated branchlessly per state — the
``#[derive(TpuState)]`` pattern from the north star, done by hand
(SURVEY.md §7 step 2 names 2pc as the proving ground).

Layout (``width = rm_count + 3`` lanes):
  [0 .. N-1]  rm_state enum (0=Working 1=Prepared 2=Committed 3=Aborted)
  [N]         tm_state enum (0=Init 1=Committed 2=Aborted)
  [N+1]       tm_prepared bitmask
  [N+2]       message-set bitmask: bit0=commit, bit1=abort,
              bit (2+rm)=prepared(rm)

Every dynamic host structure (the message *set*) is a bitmask here, so
equal host states encode to identical vectors canonically.

Actions (``max_actions = 2 + 5*N``), mirroring 2pc.rs actions():
  0: tm_commit        1: tm_abort
  per rm: tm_rcv_prepared, rm_prepare, rm_choose_abort,
          rm_rcv_commit, rm_rcv_abort
"""

from __future__ import annotations

import numpy as np

from ..encoding import EncodedModelBase
from .two_phase_commit import RmState, TmState, TwoPhaseState, TwoPhaseSys

_WORKING, _PREPARED, _COMMITTED, _ABORTED = 0, 1, 2, 3
_INIT, _TM_COMMITTED, _TM_ABORTED = 0, 1, 2


class TwoPhaseSysEncoded(EncodedModelBase):
    def __init__(self, rm_count: int):
        self.rm_count = rm_count
        self.width = rm_count + 3
        self.max_actions = 2 + 5 * rm_count
        self.host_model = TwoPhaseSys(rm_count=rm_count)

    def cache_key(self):
        """Compiled-wave sharing identity (see checkers/tpu.py)."""
        return self.rm_count

    # -- host side -------------------------------------------------------

    def encode(self, state: TwoPhaseState) -> np.ndarray:
        n = self.rm_count
        vec = np.zeros(self.width, dtype=np.uint32)
        for i, rm in enumerate(state.rm_state):
            vec[i] = rm.value
        vec[n] = state.tm_state.value
        prep = 0
        for i, p in enumerate(state.tm_prepared):
            if p:
                prep |= 1 << i
        vec[n + 1] = prep
        msgs = 0
        for m in state.msgs:
            if m == ("commit",):
                msgs |= 1
            elif m == ("abort",):
                msgs |= 2
            else:
                msgs |= 1 << (2 + m[1])
        vec[n + 2] = msgs
        return vec

    def decode(self, vec: np.ndarray) -> TwoPhaseState:
        n = self.rm_count
        vec = np.asarray(vec)
        msgs = set()
        m = int(vec[n + 2])
        if m & 1:
            msgs.add(("commit",))
        if m & 2:
            msgs.add(("abort",))
        for i in range(n):
            if m & (1 << (2 + i)):
                msgs.add(("prepared", i))
        return TwoPhaseState(
            rm_state=tuple(RmState(int(vec[i])) for i in range(n)),
            tm_state=TmState(int(vec[n])),
            tm_prepared=tuple(
                bool(int(vec[n + 1]) & (1 << i)) for i in range(n)
            ),
            msgs=frozenset(msgs),
        )

    def init_vecs(self) -> np.ndarray:
        return np.stack(
            [self.encode(s) for s in self.host_model.init_states()]
        )

    # -- device side -----------------------------------------------------

    def step_vec(self, vec):
        """uint32[W] -> (uint32[K, W], bool[K]); mirrors 2pc.rs
        actions()/next_state() as branchless lane updates."""
        import jax.numpy as jnp

        n = self.rm_count
        tm = vec[n]
        prep = vec[n + 1]
        msgs = vec[n + 2]
        full_prep = jnp.uint32((1 << n) - 1)

        def set_lane(v, lane, value):
            return v.at[lane].set(jnp.uint32(value))

        succs = []
        valids = []

        # tm_commit: all prepared & TM still deciding.
        s = set_lane(vec, n, _TM_COMMITTED)
        s = s.at[n + 2].set(msgs | jnp.uint32(1))
        succs.append(s)
        valids.append((tm == _INIT) & (prep == full_prep))

        # tm_abort
        s = set_lane(vec, n, _TM_ABORTED)
        s = s.at[n + 2].set(msgs | jnp.uint32(2))
        succs.append(s)
        valids.append(tm == _INIT)

        for rm in range(n):
            rm_working = vec[rm] == _WORKING
            prepared_bit = jnp.uint32(1 << (2 + rm))

            # tm_rcv_prepared(rm)
            s = vec.at[n + 1].set(prep | jnp.uint32(1 << rm))
            succs.append(s)
            valids.append((tm == _INIT) & ((msgs & prepared_bit) != 0))

            # rm_prepare(rm)
            s = set_lane(vec, rm, _PREPARED)
            s = s.at[n + 2].set(msgs | prepared_bit)
            succs.append(s)
            valids.append(rm_working)

            # rm_choose_abort(rm)
            succs.append(set_lane(vec, rm, _ABORTED))
            valids.append(rm_working)

            # rm_rcv_commit(rm)
            succs.append(set_lane(vec, rm, _COMMITTED))
            valids.append((msgs & jnp.uint32(1)) != 0)

            # rm_rcv_abort(rm)
            succs.append(set_lane(vec, rm, _ABORTED))
            valids.append((msgs & jnp.uint32(2)) != 0)

        return jnp.stack(succs), jnp.stack(valids)

    def property_conditions_vec(self, vec):
        """[sometimes abort agreement, sometimes commit agreement,
        always consistent] — order matches TwoPhaseSys.properties()."""
        import jax.numpy as jnp

        n = self.rm_count
        rms = vec[:n]
        all_aborted = jnp.all(rms == _ABORTED)
        all_committed = jnp.all(rms == _COMMITTED)
        consistent = ~(
            jnp.any(rms == _ABORTED) & jnp.any(rms == _COMMITTED)
        )
        return jnp.stack([all_aborted, all_committed, consistent])
