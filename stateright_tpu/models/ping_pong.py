"""Ping-pong actor fixture with all five property flavors.

Counterpart of stateright src/actor/actor_test_util.rs:4-126: two
actors volley an incrementing counter; the model exercises lossy /
duplicating networks, history recording, boundaries, and properties of
every expectation. Reference-pinned state counts (actor/model.rs:688,
847, 887): lossy-dup max_nat=1 → 14; lossy-dup max_nat=5 → 4,094;
lossless-nondup max_nat=5 → 11.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..model import Expectation
from ..actor import Actor, ActorModel, Cow, Id, Network, Out


@dataclass(frozen=True)
class Ping:
    value: int


@dataclass(frozen=True)
class Pong:
    value: int


class PingPongActor(Actor):
    def __init__(self, serve_to: Id | None):
        self.serve_to = serve_to

    def on_start(self, id: Id, out: Out) -> int:
        if self.serve_to is not None:
            out.send(self.serve_to, Ping(0))
        return 0

    def on_msg(self, id: Id, state: Cow, src: Id, msg, out: Out) -> None:
        count = state.value
        if isinstance(msg, Pong) and count == msg.value:
            out.send(src, Ping(msg.value + 1))
            state.set(count + 1)
        elif isinstance(msg, Ping) and count == msg.value:
            out.send(src, Pong(msg.value))
            state.set(count + 1)
        # else: ignored → no-op → transition pruned


@dataclass(frozen=True)
class PingPongCfg:
    maintains_history: bool = False
    max_nat: int = 5


def ping_pong_model(cfg: PingPongCfg) -> ActorModel:
    """History = (#messages in, #messages out) when maintained."""

    def record_in(c: PingPongCfg, history, env):
        if c.maintains_history:
            msg_in, msg_out = history
            return (msg_in + 1, msg_out)
        return None

    def record_out(c: PingPongCfg, history, env):
        if c.maintains_history:
            msg_in, msg_out = history
            return (msg_in, msg_out + 1)
        return None

    return (
        ActorModel(cfg=cfg, init_history=(0, 0))
        .actor(PingPongActor(serve_to=Id(1)))
        .actor(PingPongActor(serve_to=None))
        .record_msg_in(record_in)
        .record_msg_out(record_out)
        .within_boundary_fn(
            lambda c, state: all(count <= c.max_nat for count in state.actor_states)
        )
        .property(
            Expectation.ALWAYS,
            "delta within 1",
            lambda m, s: max(s.actor_states) - min(s.actor_states) <= 1,
        )
        .property(
            Expectation.SOMETIMES,
            "can reach max",
            lambda m, s: any(c == m.cfg.max_nat for c in s.actor_states),
        )
        .property(
            Expectation.EVENTUALLY,
            "must reach max",
            lambda m, s: any(c == m.cfg.max_nat for c in s.actor_states),
        )
        .property(
            # Falsifiable due to the boundary.
            Expectation.EVENTUALLY,
            "must exceed max",
            lambda m, s: any(c == m.cfg.max_nat + 1 for c in s.actor_states),
        )
        .property(
            Expectation.ALWAYS,
            "#in <= #out",
            lambda m, s: s.history[0] <= s.history[1],
        )
        .property(
            Expectation.EVENTUALLY,
            "#out <= #in + 1",
            lambda m, s: s.history[1] <= s.history[0] + 1,
        )
    )


def ping_pong_device_specs(cfg: PingPongCfg) -> dict:
    """Device property/boundary specs for ``compile_actor_model`` —
    the device counterparts of every host property above, plus the
    boundary and closure bounds. One copy shared by the actor-compile
    tests, the codegen-shape tests, and the kernel-lint encoding
    registry (stateright_tpu/analysis/registry.py)."""
    counts = lambda ctx: ctx.actor_values(lambda i, s: s)  # noqa: E731

    def in_le_out(ctx, jnp):
        return ctx.history_value(lambda h: int(h[0] <= h[1])) == 1

    def out_le_in1(ctx, jnp):
        return ctx.history_value(lambda h: int(h[1] <= h[0] + 1)) == 1

    return dict(
        properties={
            "delta within 1": lambda ctx, jnp: (
                jnp.max(counts(ctx)) - jnp.min(counts(ctx)) <= 1
            ),
            "can reach max": lambda ctx, jnp: jnp.any(
                counts(ctx) == cfg.max_nat
            ),
            "must reach max": lambda ctx, jnp: jnp.any(
                counts(ctx) == cfg.max_nat
            ),
            "must exceed max": lambda ctx, jnp: jnp.any(
                counts(ctx) == cfg.max_nat + 1
            ),
            "#in <= #out": in_le_out,
            "#out <= #in + 1": out_le_in1,
        },
        boundary=lambda ctx, jnp: jnp.all(counts(ctx) <= cfg.max_nat),
        closure_actor_bound=lambda i, s: s <= cfg.max_nat,
        # History counters only advance on non-no-op deliveries, which
        # the actor-state bound caps at max_nat+1 per actor; beyond
        # that the (in, out) pairs only occur outside the boundary.
        closure_history_bound=lambda h: max(h) <= 2 * (cfg.max_nat + 2),
    )
