"""The pinger/timer workload: the dedicated timer-semantics example.

Counterpart of reference examples/timers.rs: each of N pingers arms
three named timers at start. ``Even``/``Odd`` timers re-arm themselves
and ping the even-/odd-indexed peers (counting sends); ``NoOp``
re-arms itself and does nothing else — which is exactly the
``is_no_op_with_timer`` pruning case (actor.rs:254-264): a handler
that only re-arms the fired timer produces no transition.

The state space is unbounded (send/receive counters grow), as in the
reference, whose CLI runs it without a boundary; tests bound it with
``target_max_depth``.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..actor import Actor, ActorModel, Cow, Id, Network, Out, model_peers
from ..actor.base import model_timeout
from ..model import Expectation


@dataclass(frozen=True)
class Ping:
    pass


@dataclass(frozen=True)
class Pong:
    pass


@dataclass(frozen=True)
class PingerState:
    sent: int
    received: int


class PingerActor(Actor):
    """timers.rs PingerActor: Even/Odd/NoOp self-re-arming timers."""

    def __init__(self, peer_ids: list[Id]):
        self.peer_ids = peer_ids

    def on_start(self, id: Id, out: Out) -> PingerState:
        out.set_timer("Even", model_timeout())
        out.set_timer("Odd", model_timeout())
        out.set_timer("NoOp", model_timeout())
        return PingerState(sent=0, received=0)

    def on_msg(self, id: Id, state: Cow, src: Id, msg, out: Out) -> None:
        if isinstance(msg, Ping):
            out.send(src, Pong())
        elif isinstance(msg, Pong):
            s = state.value
            state.set(PingerState(s.sent, s.received + 1))

    def on_timeout(self, id: Id, state: Cow, timer, out: Out) -> None:
        if timer == "Even":
            out.set_timer("Even", model_timeout())
            s = state.value
            for dst in self.peer_ids:
                if int(dst) % 2 == 0:
                    s = PingerState(s.sent + 1, s.received)
                    out.send(dst, Ping())
            if s is not state.value:
                state.set(s)
        elif timer == "Odd":
            out.set_timer("Odd", model_timeout())
            s = state.value
            for dst in self.peer_ids:
                if int(dst) % 2 != 0:
                    s = PingerState(s.sent + 1, s.received)
                    out.send(dst, Ping())
            if s is not state.value:
                state.set(s)
        elif timer == "NoOp":
            # Re-arming ONLY the fired timer is a no-op transition
            # (actor.rs:254-264) — pruned by the model.
            out.set_timer("NoOp", model_timeout())


@dataclass(frozen=True)
class PingerModelCfg:
    server_count: int = 3


def pinger_model(
    cfg: PingerModelCfg, network: Network | None = None
) -> ActorModel:
    if network is None:
        network = Network.new_unordered_nonduplicating()
    model = ActorModel(cfg=cfg)
    for i in range(cfg.server_count):
        model.actor(PingerActor(model_peers(i, cfg.server_count)))
    model.init_network(network)
    # timers.rs:112 checks the trivially-true invariant.
    model.property(Expectation.ALWAYS, "true", lambda m, s: True)
    return model
