"""Single-copy register servers (no consensus) + linearizability check.

Counterpart of stateright examples/single-copy-register.rs: each server
holds one value; Put overwrites, Get reads. With one server the system
is linearizable (reference-pinned 93 unique states for 2 clients /
1 server, single-copy-register.rs:110); with two servers it is not.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..model import Expectation
from ..actor import Actor, ActorModel, Cow, Id, Network, Out
from ..actor.register import (
    DEFAULT_VALUE,
    Get,
    GetOk,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
    record_invocations,
    record_returns,
)
from ..semantics import LinearizabilityTester, Register


class SingleCopyActor(Actor):
    def on_start(self, id: Id, out: Out) -> str:
        return DEFAULT_VALUE

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        if isinstance(msg, Put):
            state.set(msg.value)
            out.send(src, PutOk(msg.req_id))
        elif isinstance(msg, Get):
            out.send(src, GetOk(msg.req_id, state.value))


@dataclass(frozen=True)
class SingleCopyRegisterCfg:
    client_count: int = 2
    server_count: int = 1
    put_count: int = 1


def single_copy_register_model(
    cfg: SingleCopyRegisterCfg, network: Network | None = None
) -> ActorModel:
    if network is None:
        network = Network.new_unordered_nonduplicating()

    def value_chosen(model: ActorModel, state) -> bool:
        # An observable non-default read exists in flight
        # (single-copy-register.rs:73-82).
        for env in state.network.iter_deliverable():
            if isinstance(env.msg, GetOk) and env.msg.value != DEFAULT_VALUE:
                return True
        return False

    model = ActorModel(
        cfg=cfg, init_history=LinearizabilityTester(Register(DEFAULT_VALUE))
    )

    def to_encoded():
        from ..actor.network import UnorderedNonDuplicating

        if cfg.client_count <= 2 and isinstance(
            model._init_network, UnorderedNonDuplicating
        ):
            from .single_copy_register_tpu import SingleCopyEncoded

            return SingleCopyEncoded(cfg, network)
        # Configurations beyond the hand encoding's envelope (e.g. the
        # driver's `single-copy-register check 3`) go through the
        # generic actor→encoding compiler with the register specs; the
        # client loop bounds ops per thread at put_count+1, and the
        # linearizable-expansion bound (see abd_encoded's
        # history_bound rationale — sound for the ALWAYS property
        # because a bounded-out history still enters the domain and
        # trips the property before expansion stops) tames the
        # tester-state combinatorics at 3 clients.
        from ..actor.compile import compile_actor_model
        from ..actor.register import register_specs

        def history_bound(h) -> bool:
            per_thread = dict(h.history_by_thread)
            in_flight = dict(h.in_flight_by_thread)
            for t, completed in per_thread.items():
                ops = len(completed) + (1 if in_flight.get(t) else 0)
                if ops > cfg.put_count + 1:
                    return False
            return h.serialized_history() is not None

        return compile_actor_model(
            model,
            properties=register_specs(DEFAULT_VALUE),
            closure_history_bound=history_bound,
        )

    model.to_encoded = to_encoded
    model.add_actors(
        RegisterServer(SingleCopyActor()) for _ in range(cfg.server_count)
    )
    model.add_actors(
        RegisterClient(put_count=cfg.put_count, server_count=cfg.server_count)
        for _ in range(cfg.client_count)
    )
    return (
        model.init_network(network)
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda m, s: s.history.serialized_history() is not None,
        )
        .property(Expectation.SOMETIMES, "value chosen", value_chosen)
        .record_msg_in(record_returns)
        .record_msg_out(record_invocations)
    )
