"""Vectorized single-decree Paxos: the north-star TPU workload.

Encodes the full actor-model state of :mod:`stateright_tpu.models.paxos`
(reference examples/paxos.rs + actor/model_state.rs) — three server
``PaxosState``s, the register clients, the unordered-nonduplicating
network, and the in-state ``LinearizabilityTester`` history — as a
7-lane ``uint32`` vector, with every deliverable envelope compiled to a
branchless lane-update (SURVEY.md §7 step 5: the actor→encoding
compilation this framework exists for).

Three structural discoveries (validated by exhaustive host-model probes
over the pinned 16,668-state space) make a tight encoding possible:

1. **The envelope universe is finite and small.** With ``put_count=1``
   the reachable (src, dst, msg) alphabet is 68 envelopes; the
   provably-sound overapproximation enumerated here (coexistence +
   choosable-proposal closure over ballots) has 70. Every envelope is
   one bit: the network — a multiset in the reference
   (network.rs:55) — degenerates to a *set* here (max multiplicity 1,
   probe-verified), so three ``uint32`` lanes hold it canonically and
   "deliver envelope k" is a static per-bit transition: src, dst and
   message content are compile-time constants folded into each of the
   K=70 action slots.

2. **History phases.** The model prunes actor-no-op deliveries before
   the history hook runs (model.rs:317-319), so stale ``PutOk``/
   ``GetOk`` never corrupt the tester: each client's tester state
   follows the strict progression ``W in-flight → W done + R in-flight
   → W+R done``, and — because only one proposal is ever decided — the
   cross-thread snapshots of linearizability.rs:114-126 are always
   empty. Two bits of phase + two bits of read-value per client encode
   the tester exactly.

3. **The linearizability verdict is a 144-entry truth table.** Because
   the tester state is (phase, read_value) per client, the reference's
   backtracking serializer (linearizability.rs:196-284) has only
   ``(4*3)^2`` possible inputs. The table is precomputed host-side *by
   the real serializer* at encoding-build time and the device-side
   ``always linearizable`` condition is a single gather — the
   device-filters/host-confirms split SURVEY §7 step 6 calls for,
   taken to its limit.

Unreachable-by-proof code paths (e.g. a Put at ballot round ≥ 2, an
out-of-universe ``last_accepted``) set a poison bit that perturbs the
fingerprint, so any soundness gap surfaces as a differential-test
failure instead of a silent wrong answer; ``encode()`` raises on any
host state outside the bounded universe.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import numpy as np

from ..actor import Id
from ..actor.register import Get, GetOk, Internal, Put, PutOk
from ..encoding import EncodedModelBase
from ..ops.bitmask import mask_words
from ..semantics.register import ReadOk, ReadOp, WriteOk, WriteOp
from .paxos import (
    Accept,
    Accepted,
    Decided,
    PaxosModelCfg,
    Prepare,
    Prepared,
    paxos_model,
)

# -- lane layout ---------------------------------------------------------
# The layout is COMPUTED per configuration (bit widths grow with the
# client count): each server gets a main lane [ballot enum | proposal
# code | accepted la-code | is_decided | accepts id-mask | prepares]
# and, when the prepares map no longer fits (client_count=4), a second
# per-server lane holding prepares alone. The client/history lane packs
# per client j at bit j*stride:
#   +0 (2b)     actor phase: 0 awaiting PutOk, 1 awaiting GetOk, 2 done
#   +2 (2b)     history phase: 0 W-inflight, 1 W-done, 2 +R-inflight,
#               3 done
#   +4 (W_RV b) read value code (0 '\x00', 1+ value index)
_B_POISON = 30


def _bits(n: int) -> int:
    """Bits to hold values 0..n."""
    return max(1, n.bit_length())


def _field(lane, shift, width, xp):
    return (lane >> xp.uint32(shift)) & xp.uint32((1 << width) - 1)


def _set_field(lane, shift, width, value, xp):
    mask = xp.uint32(((1 << width) - 1) << shift)
    return (lane & ~mask) | (
        (value.astype(xp.uint32) if hasattr(value, "astype") else xp.uint32(value))
        << xp.uint32(shift)
    ) & mask


@dataclass(frozen=True)
class EnvSpec:
    """One envelope of the bounded universe; all fields are host-side
    constants folded into the compiled transition."""

    src: int
    dst: int
    kind: str  # put|get|putok|getok|prepare|prepared|accept|accepted|decided
    ballot: int = 0      # ballot enum
    prop: int = 0        # proposal code
    la: int = 0          # last_accepted la-code (prepared)
    value: int = 0       # value code (getok)


class PaxosEncoded(EncodedModelBase):
    """EncodedModel for ``paxos_model(PaxosModelCfg(...))``.

    Supports the reference benchmark shapes: 3 servers, 1 put per
    client, 1-4 clients (``paxos check N``, examples/paxos.rs:352-465;
    2c/3s pinned at 16,668, paxos.rs:325). The lane layout, ballot
    universe, and coexistence closure are computed per configuration —
    client_count=4 puts two proposals on leader 0 and moves the
    prepares maps to dedicated per-server lanes.
    """

    def __init__(self, cfg: PaxosModelCfg, network=None):
        if cfg.server_count != 3 or cfg.put_count != 1:
            raise ValueError(
                "PaxosEncoded supports server_count=3, put_count=1 "
                f"(got {cfg})"
            )
        if not (1 <= cfg.client_count <= 5):
            raise ValueError(
                f"PaxosEncoded supports 1-5 clients (got {cfg.client_count})"
            )
        if network is not None and type(network).__name__ != (
            "UnorderedNonDuplicating"
        ):
            raise ValueError(
                "PaxosEncoded models the unordered non-duplicating network"
            )
        self.cfg = cfg
        self.S = cfg.server_count
        self.C = cfg.client_count
        self.clients = list(range(self.S, self.S + self.C))
        self.host_model = paxos_model(cfg)

        # Proposals: client i's single put (req_id=i, requester=i,
        # value chr(ord('A')+i-S)); code = 1 + index (req-id order).
        self.values = [chr(ord("A") + i - self.S) for i in self.clients]
        self.proposals = [
            (i, Id(i), self.values[j]) for j, i in enumerate(self.clients)
        ]
        self.P = len(self.proposals)

        # Ballots. Leaders = put-target servers (client i -> i % S);
        # with 4 clients on 3 servers, leader 0 serves two clients but
        # still Puts at most once (proposal-None guard), so each leader
        # owns exactly one put-ballot and rounds cap at the LEADER
        # count: a Put at round r requires the server to have adopted
        # some round-(r-1) ballot first, and the support chain
        # 1, 2, ..., r needs r distinct leaders.
        self.leaders = sorted({i % self.S for i in self.clients})
        ballots = [(r, l) for r in range(1, len(self.leaders) + 1)
                   for l in self.leaders]
        ballots.sort()
        #: ballot enum: 0 = initial (0, Id(0)); 1.. = sorted reachable
        self.ballots = ballots
        self.ballot_enum = {(0, Id(0)): 0}
        for n, (r, l) in enumerate(ballots):
            self.ballot_enum[(r, Id(l))] = n + 1
        self.NB = len(ballots)

        # Joint feasibility: an assignment round[l] (or None = l never
        # Put) is realizable iff every assigned round r >= 2 is
        # supported by some OTHER leader assigned exactly r-1. Two
        # ballots coexist iff some realizable assignment contains both
        # — computed by brute force over the <= (R+1)^|leaders|
        # assignments instead of a hand-derived pair rule (the round-2
        # rule was specific to two leaders).
        import itertools as _it

        R = len(self.leaders)
        feasible_pairs: set = set()
        for rounds_assign in _it.product(
            [None] + list(range(1, R + 1)), repeat=R
        ):
            ok = True
            for l_idx, r in enumerate(rounds_assign):
                if r is not None and r >= 2 and not any(
                    r2 == r - 1
                    for l2_idx, r2 in enumerate(rounds_assign)
                    if l2_idx != l_idx and r2 is not None
                ):
                    ok = False
                    break
            if not ok:
                continue
            assigned = [
                self.ballot_enum[(r, Id(self.leaders[l_idx]))]
                for l_idx, r in enumerate(rounds_assign)
                if r is not None
            ]
            for b1 in assigned:
                for b2 in assigned:
                    feasible_pairs.add((b1, b2))

        def coexists(b1: int, b2: int) -> bool:
            """May ballot enums b1 < b2 both exist in one run?"""
            return (b1, b2) in feasible_pairs

        # choosable(b): proposals a leader can drive under ballot b —
        # its own put, or any adoptable last_accepted from a lower
        # coexisting ballot (closure).
        own_prop = {}
        for j, i in enumerate(self.clients):
            own_prop.setdefault(i % self.S, []).append(j + 1)
        choosable: dict[int, set] = {}
        la_universe: dict[int, list] = {}
        for b in range(1, self.NB + 1):
            _, l = ballots[b - 1]
            ch = set(own_prop.get(l, []))
            las = [0]
            for b2 in range(1, b):
                if coexists(b2, b):
                    for p in sorted(choosable[b2]):
                        las.append(1 + (b2 - 1) * self.P + (p - 1))
                        ch.add(p)
            choosable[b] = ch
            la_universe[b] = las
        self.choosable = {b: sorted(ch) for b, ch in choosable.items()}
        self.la_universe = la_universe

        # -- computed lane layout (widths scale with NB and P) -----------
        la_max = self.NB * self.P          # la codes 0..la_max
        self.W_BALLOT = _bits(self.NB)
        self.W_PROP = _bits(self.P)
        self.W_ACC = _bits(la_max)
        self.W_ACCEPTS = self.S
        self.W_PREP = _bits(1 + la_max)    # prepares entry: 0 | 1+la
        self.B_BALLOT = 0
        self.B_PROP = self.B_BALLOT + self.W_BALLOT
        self.B_ACC = self.B_PROP + self.W_PROP
        self.B_DEC = self.B_ACC + self.W_ACC
        self.B_ACCEPTS = self.B_DEC + 1
        main_bits = self.B_ACCEPTS + self.W_ACCEPTS
        # prepares ride in the main lane when they fit, else each
        # server gets a dedicated prepares lane (client_count=4).
        self.two_lane = main_bits + self.S * self.W_PREP > 32
        self.B_PREP = 0 if self.two_lane else main_bits
        #: client/history lane stride and read-value width
        self.W_RV = _bits(self.P)
        self.CST = 4 + self.W_RV
        #: clients per client-lane (bit 30 of lane 0 is the poison
        #: bit); 5 clients spill onto a second client lane.
        self.CPL = _B_POISON // self.CST
        self.n_client_lanes = -(-self.C // self.CPL)
        #: linearizability-table radix per client: phase * TBV + rv
        self.TBV = self.P + 1
        self.TB = 4 * self.TBV

        self.universe = self._build_universe()
        self.index = {self._env_key(e): k for k, e in enumerate(self.universe)}
        self.K = len(self.universe)
        self.net_lanes = mask_words(self.K)
        self.n_state_lanes = (
            self.S * (2 if self.two_lane else 1) + self.n_client_lanes
        )
        self.width = self.n_state_lanes + self.net_lanes
        self.max_actions = self.K
        self._lin_table = self._build_lin_table()

    # -- computed-layout accessors ----------------------------------------

    def _clane_index(self, j: int = 0) -> int:
        """Lane of client j's fields (j // CPL picks the client lane);
        the poison bit lives on client lane 0."""
        return self.S * (2 if self.two_lane else 1) + j // self.CPL

    def _coff(self, j: int) -> int:
        """Bit offset of client j inside its client lane."""
        return (j % self.CPL) * self.CST

    def _prep_lane(self, server: int) -> int:
        return self.S + server if self.two_lane else server

    def cache_key(self):
        return (self.C, self.S, self.cfg.put_count)

    # -- universe ----------------------------------------------------------

    def _build_universe(self) -> list:
        u: list[EnvSpec] = []
        S, P = self.S, self.P
        # Puts and Gets (register.rs:144-236 request scheme).
        for j, c in enumerate(self.clients):
            u.append(EnvSpec(c, c % S, "put", prop=j + 1))
        for j, c in enumerate(self.clients):
            u.append(EnvSpec(c, (c + 1) % S, "get"))
        # PutOk from any leader that can drive this client's proposal.
        for l in self.leaders:
            for j, c in enumerate(self.clients):
                if any(j + 1 in self.choosable[b]
                       for b in range(1, self.NB + 1)
                       if self.ballots[b - 1][1] == l):
                    u.append(EnvSpec(l, c, "putok", prop=j + 1))
        # GetOk from the get-target server, any decided value.
        for j, c in enumerate(self.clients):
            for v in range(1, P + 1):
                u.append(EnvSpec((c + 1) % S, c, "getok", value=v))
        # Internal protocol messages.
        for b in range(1, self.NB + 1):
            _, l = self.ballots[b - 1]
            peers = [d for d in range(S) if d != l]
            for d in peers:
                u.append(EnvSpec(l, d, "prepare", ballot=b))
            for d in peers:
                for la in self.la_universe[b]:
                    u.append(EnvSpec(d, l, "prepared", ballot=b, la=la))
            for p in self.choosable[b]:
                for d in peers:
                    u.append(EnvSpec(l, d, "accept", ballot=b, prop=p))
            for d in peers:
                u.append(EnvSpec(d, l, "accepted", ballot=b))
            for p in self.choosable[b]:
                for d in peers:
                    u.append(EnvSpec(l, d, "decided", ballot=b, prop=p))
        return u

    def _env_key(self, e: EnvSpec) -> tuple:
        return (e.src, e.dst, e.kind, e.ballot, e.prop, e.la, e.value)

    # -- host <-> codes ----------------------------------------------------

    def _ballot_code(self, ballot: Tuple) -> int:
        code = self.ballot_enum.get((ballot[0], ballot[1]))
        if code is None:
            raise ValueError(f"ballot outside universe: {ballot!r}")
        return code

    def _prop_code(self, proposal: Optional[Tuple]) -> int:
        if proposal is None:
            return 0
        for j, p in enumerate(self.proposals):
            if p == proposal:
                return j + 1
        raise ValueError(f"proposal outside universe: {proposal!r}")

    def _la_code(self, la: Optional[Tuple]) -> int:
        if la is None:
            return 0
        b = self._ballot_code(la[0])
        p = self._prop_code(la[1])
        if b == 0 or p == 0:
            raise ValueError(f"last_accepted outside universe: {la!r}")
        return 1 + (b - 1) * self.P + (p - 1)

    def _value_code(self, value: str) -> int:
        if value == "\x00":
            return 0
        try:
            return 1 + self.values.index(value)
        except ValueError:
            raise ValueError(f"value outside universe: {value!r}")

    def _msg_env_key(self, src: int, dst: int, msg: Any) -> tuple:
        if isinstance(msg, Put):
            return (src, dst, "put", 0, self._prop_code((msg.req_id, Id(src), msg.value)), 0, 0)
        if isinstance(msg, Get):
            return (src, dst, "get", 0, 0, 0, 0)
        if isinstance(msg, PutOk):
            j = self.clients.index(msg.req_id)  # first-op req_id == client id
            return (src, dst, "putok", 0, j + 1, 0, 0)
        if isinstance(msg, GetOk):
            return (src, dst, "getok", 0, 0, 0, self._value_code(msg.value))
        if isinstance(msg, Internal):
            m = msg.msg
            if isinstance(m, Prepare):
                return (src, dst, "prepare", self._ballot_code(m.ballot), 0, 0, 0)
            if isinstance(m, Prepared):
                return (
                    src, dst, "prepared", self._ballot_code(m.ballot),
                    0, self._la_code(m.last_accepted), 0,
                )
            if isinstance(m, Accept):
                return (
                    src, dst, "accept", self._ballot_code(m.ballot),
                    self._prop_code(m.proposal), 0, 0,
                )
            if isinstance(m, Accepted):
                return (src, dst, "accepted", self._ballot_code(m.ballot), 0, 0, 0)
            if isinstance(m, Decided):
                return (
                    src, dst, "decided", self._ballot_code(m.ballot),
                    self._prop_code(m.proposal), 0, 0,
                )
        raise ValueError(f"message outside universe: {msg!r}")

    # -- encode ------------------------------------------------------------

    def encode(self, state) -> np.ndarray:
        vec = np.zeros(self.width, dtype=np.uint32)
        for i in range(self.S):
            s = state.actor_states[i].state
            lane = 0
            lane |= self._ballot_code(s.ballot) << self.B_BALLOT
            lane |= self._prop_code(s.proposal) << self.B_PROP
            lane |= self._la_code(s.accepted) << self.B_ACC
            lane |= (1 if s.is_decided else 0) << self.B_DEC
            mask = 0
            for sid in s.accepts:
                mask |= 1 << int(sid)
            lane |= mask << self.B_ACCEPTS
            prep = 0
            for sid, la in s.prepares.items():
                prep |= (1 + self._la_code(la)) << (
                    self.B_PREP + self.W_PREP * int(sid)
                )
            if self.two_lane:
                vec[self._prep_lane(i)] = prep
                vec[i] = lane
            else:
                vec[i] = lane | prep
        for j, c in enumerate(self.clients):
            cs = state.actor_states[c]
            if cs.awaiting == c and cs.op_count == 1:
                phase = 0
            elif cs.awaiting == 2 * c and cs.op_count == 2:
                phase = 1
            elif cs.awaiting is None and cs.op_count == 3:
                phase = 2
            else:
                raise ValueError(f"client state outside universe: {cs!r}")
            hphase, rval = self._history_phase(state.history, Id(c))
            off = self._coff(j)
            vec[self._clane_index(j)] |= np.uint32(
                (phase << off) | (hphase << (off + 2))
                | (rval << (off + 4))
            )
        for env, count in self._network_items(state.network):
            if count != 1:
                raise ValueError(
                    f"envelope multiplicity {count} outside universe: {env!r}"
                )
            key = self._msg_env_key(int(env.src), int(env.dst), env.msg)
            k = self.index.get(key)
            if k is None:
                raise ValueError(f"envelope outside universe: {env!r}")
            vec[self.n_state_lanes + k // 32] |= np.uint32(1 << (k % 32))
        if any(state.crashed) or any(t for t in state.timers_set):
            raise ValueError("crashes/timers outside the paxos universe")
        return vec

    def _network_items(self, network):
        from collections import Counter

        return Counter(network.iter_all()).items()

    def _history_phase(self, history, thread: Id) -> tuple[int, int]:
        if not history.is_valid:
            raise ValueError("invalid history outside universe")
        completed = dict(history.history_by_thread).get(thread, ())
        in_flight = dict(history.in_flight_by_thread).get(thread)
        j = self.clients.index(int(thread))
        wv = self.values[j]
        rval = 0
        if len(completed) == 0 and in_flight is not None:
            snap, op = in_flight
            if snap != () or not isinstance(op, WriteOp) or op.value != wv:
                raise ValueError(f"history outside universe: {in_flight!r}")
            phase = 0
        elif len(completed) >= 1:
            snap, op, ret = completed[0]
            if (
                snap != ()
                or not isinstance(op, WriteOp)
                or op.value != wv
                or not isinstance(ret, WriteOk)
            ):
                raise ValueError(f"history outside universe: {completed!r}")
            if len(completed) == 1 and in_flight is None:
                phase = 1
            elif len(completed) == 1:
                snap, op = in_flight
                if snap != () or not isinstance(op, ReadOp):
                    raise ValueError(f"history outside universe: {in_flight!r}")
                phase = 2
            elif len(completed) == 2 and in_flight is None:
                snap, op, ret = completed[1]
                if snap != () or not isinstance(op, ReadOp) or not isinstance(ret, ReadOk):
                    raise ValueError(f"history outside universe: {completed!r}")
                phase = 3
                rval = self._value_code(ret.value)
            else:
                raise ValueError(f"history outside universe: {completed!r}")
        else:
            raise ValueError(f"history outside universe: thread {thread!r}")
        return phase, rval

    def init_vecs(self) -> np.ndarray:
        return np.stack(
            [self.encode(s) for s in self.host_model.init_states()]
        )

    # -- linearizability truth table --------------------------------------

    def _build_lin_table(self) -> np.ndarray:
        """Evaluate the REAL serializer on every (phase, rval) combo.

        Reachable states have at most one client past phase 0 (only one
        proposal is ever decided); combos with both clients progressed
        are marked not-linearizable so that, were one ever produced,
        it would surface as a loud counterexample rather than pass
        silently.
        """
        from ..semantics import LinearizabilityTester, Register

        size = self.TB ** self.C
        table = np.zeros(size, dtype=bool)

        def fill(phases, rvals):
            idx = 0
            for ph, rv in zip(phases, rvals):
                idx = idx * self.TB + ph * self.TBV + rv
            tester = LinearizabilityTester(Register("\x00"))
            for j in range(self.C):
                tester = tester.on_invoke(
                    Id(self.clients[j]), WriteOp(self.values[j])
                )
            for j in range(self.C):
                t = Id(self.clients[j])
                ph, rv = phases[j], rvals[j]
                if ph >= 1:
                    tester = tester.on_return(t, WriteOk())
                if ph >= 2:
                    tester = tester.on_invoke(t, ReadOp())
                if ph >= 3:
                    v = "\x00" if rv == 0 else self.values[rv - 1]
                    tester = tester.on_return(t, ReadOk(v))
            table[idx] = tester.serialized_history() is not None

        # Only all-zero and single-progressed combos can be reached
        # (single decree: one proposal is ever decided, so one client
        # ever advances); everything else stays False so it would
        # surface as a loud counterexample — and the fill is C*12
        # serializer runs instead of (4*TBV)^C (8M at 5 clients).
        fill([0] * self.C, [0] * self.C)
        for j in range(self.C):
            for ph in (1, 2, 3):
                for rv in range(self.TBV):
                    phases = [0] * self.C
                    rvals = [0] * self.C
                    phases[j] = ph
                    rvals[j] = rv
                    fill(phases, rvals)
        return table

    # -- device step -------------------------------------------------------

    def _bit(self, vec, k, xp):
        lane = vec[self.n_state_lanes + k // 32]
        return ((lane >> xp.uint32(k % 32)) & xp.uint32(1)) != 0

    def _net_update(self, vec, clear_k, send_masks, xp):
        """Clear bit ``clear_k``; OR per-lane ``send_masks`` in."""
        out = vec
        for ln in range(self.net_lanes):
            lane = vec[self.n_state_lanes + ln]
            if clear_k // 32 == ln:
                lane = lane & ~xp.uint32(1 << (clear_k % 32))
            m = send_masks.get(ln)
            if m is not None:
                lane = lane | m
            out = out.at[self.n_state_lanes + ln].set(lane)
        return out

    def _const_mask(self, keys) -> dict:
        """Per-lane OR mask for a set of universe keys (host consts)."""
        masks: dict[int, int] = {}
        for key in keys:
            k = self.index[key]
            masks[k // 32] = masks.get(k // 32, 0) | (1 << (k % 32))
        return masks

    def step_vec(self, vec):
        import jax.numpy as jnp

        succs, valids = [], []
        for k, e in enumerate(self.universe):
            s, valid = self._deliver(vec, k, e, jnp)
            succs.append(s)
            valids.append(valid)
        return jnp.stack(succs), jnp.stack(valids)

    def _deliver(self, vec, k, e: EnvSpec, xp):
        present = self._bit(vec, k, xp)
        handler = getattr(self, f"_on_{e.kind}")
        new_vec, handled = handler(vec, k, e, xp)
        return new_vec, present & handled

    # Per-kind handlers: return (successor_vec, handled). All message
    # fields are Python constants; only lane contents are traced.

    def _on_put(self, vec, k, e: EnvSpec, xp):
        lane = vec[e.dst]
        decided = _field(lane, self.B_DEC, 1, xp) != 0
        prop = _field(lane, self.B_PROP, self.W_PROP, xp)
        ballot = _field(lane, self.B_BALLOT, self.W_BALLOT, xp)
        acc = _field(lane, self.B_ACC, self.W_ACC, xp)
        handled = (~decided) & (prop == 0)
        # New ballot: (round+1, dst). Rounds for this leader:
        rounds = sorted(
            r for (r, l) in self.ballots if l == e.dst
        )
        round_of = xp.asarray(
            [0] + [r for (r, _) in self.ballots], dtype=xp.uint32
        )
        cur_round = round_of[ballot]
        nb = xp.uint32(0)
        poison = handled & xp.bool_(True)
        for r in rounds:
            hit = cur_round == (r - 1)
            nb = xp.where(hit, xp.uint32(self.ballot_enum[(r, Id(e.dst))]), nb)
            poison = poison & ~hit
        new_lane = xp.uint32(0)
        new_lane = new_lane | (nb << self.B_BALLOT)
        new_lane = new_lane | (xp.uint32(e.prop) << self.B_PROP)
        new_lane = new_lane | (acc << self.B_ACC)
        # Put RESETS prepares to {self: accepted} (paxos.rs:160-176).
        prep = (acc + 1) << xp.uint32(self.W_PREP * e.dst)
        # Sends: Prepare(nb) to both peers — select the mask by round.
        masks: dict = {}
        for r in rounds:
            b = self.ballot_enum[(r, Id(e.dst))]
            keys = [
                (e.dst, d, "prepare", b, 0, 0, 0)
                for d in range(self.S)
                if d != e.dst
            ]
            cm = self._const_mask(keys)
            hit = cur_round == (r - 1)
            for ln, m in cm.items():
                masks[ln] = masks.get(ln, xp.uint32(0)) | xp.where(
                    hit, xp.uint32(m), xp.uint32(0)
                )
        if self.two_lane:
            out = vec.at[e.dst].set(xp.where(handled, new_lane, lane))
            pl = self._prep_lane(e.dst)
            out = out.at[pl].set(xp.where(handled, prep, vec[pl]))
        else:
            new_lane = new_lane | (prep << xp.uint32(self.B_PREP))
            out = vec.at[e.dst].set(xp.where(handled, new_lane, lane))
        out = self._poison(out, poison, xp)
        out = self._net_update(out, k, masks, xp)
        return out, handled

    def _on_get(self, vec, k, e: EnvSpec, xp):
        lane = vec[e.dst]
        decided = _field(lane, self.B_DEC, 1, xp) != 0
        acc = _field(lane, self.B_ACC, self.W_ACC, xp)
        handled = decided
        # Reply GetOk(value of accepted proposal).
        val = xp.where(acc > 0, ((acc - 1) % xp.uint32(self.P)) + 1, 0)
        masks: dict = {}
        for v in range(1, self.P + 1):
            key = (e.dst, e.src, "getok", 0, 0, 0, v)
            if key not in self.index:
                continue
            cm = self._const_mask([key])
            hit = handled & (val == v)
            for ln, m in cm.items():
                masks[ln] = masks.get(ln, xp.uint32(0)) | xp.where(
                    hit, xp.uint32(m), xp.uint32(0)
                )
        out = self._net_update(vec, k, masks, xp)
        return out, handled

    def _on_putok(self, vec, k, e: EnvSpec, xp):
        j = self.clients.index(e.dst)
        cl = self._clane_index(j)
        off = self._coff(j)
        lane = vec[cl]
        phase = _field(lane, off, 2, xp)
        handled = phase == 0
        new_lane = _set_field(lane, off, 2, xp.uint32(1), xp)
        # History: W returns, R invoked (phases 0 -> 2).
        new_lane = _set_field(
            new_lane, off + 2, 2, xp.uint32(2), xp
        )
        out = vec.at[cl].set(xp.where(handled, new_lane, lane))
        get_key = (e.dst, (e.dst + 1) % self.S, "get", 0, 0, 0, 0)
        cm = self._const_mask([get_key])
        masks = {
            ln: xp.where(handled, xp.uint32(m), xp.uint32(0))
            for ln, m in cm.items()
        }
        out = self._net_update(out, k, masks, xp)
        return out, handled

    def _on_getok(self, vec, k, e: EnvSpec, xp):
        j = self.clients.index(e.dst)
        cl = self._clane_index(j)
        off = self._coff(j)
        lane = vec[cl]
        phase = _field(lane, off, 2, xp)
        handled = phase == 1
        new_lane = _set_field(lane, off, 2, xp.uint32(2), xp)
        new_lane = _set_field(
            new_lane, off + 2, 2, xp.uint32(3), xp
        )
        new_lane = _set_field(
            new_lane, off + 4, self.W_RV, xp.uint32(e.value), xp
        )
        out = vec.at[cl].set(xp.where(handled, new_lane, lane))
        out = self._net_update(out, k, {}, xp)
        return out, handled

    def _on_prepare(self, vec, k, e: EnvSpec, xp):
        lane = vec[e.dst]
        decided = _field(lane, self.B_DEC, 1, xp) != 0
        ballot = _field(lane, self.B_BALLOT, self.W_BALLOT, xp)
        acc = _field(lane, self.B_ACC, self.W_ACC, xp)
        handled = (~decided) & (ballot < e.ballot)
        new_lane = _set_field(
            lane, self.B_BALLOT, self.W_BALLOT, xp.uint32(e.ballot), xp
        )
        # Send Prepared(b, la=accepted) to the leader; select the
        # envelope by the acceptor's current accepted code.
        masks: dict = {}
        covered = handled & xp.bool_(False)
        for la in self.la_universe[e.ballot]:
            key = (e.dst, e.src, "prepared", e.ballot, 0, la, 0)
            cm = self._const_mask([key])
            hit = handled & (acc == la)
            covered = covered | hit
            for ln, m in cm.items():
                masks[ln] = masks.get(ln, xp.uint32(0)) | xp.where(
                    hit, xp.uint32(m), xp.uint32(0)
                )
        poison = handled & ~covered
        out = vec.at[e.dst].set(xp.where(handled, new_lane, lane))
        out = self._poison(out, poison, xp)
        out = self._net_update(out, k, masks, xp)
        return out, handled

    def _on_prepared(self, vec, k, e: EnvSpec, xp):
        l = e.dst
        lane = vec[l]
        plane = vec[self._prep_lane(l)]
        decided = _field(lane, self.B_DEC, 1, xp) != 0
        ballot = _field(lane, self.B_BALLOT, self.W_BALLOT, xp)
        prop = _field(lane, self.B_PROP, self.W_PROP, xp)
        handled = (~decided) & (ballot == e.ballot)
        # prepares[src] = 1 + la.
        new_plane = _set_field(
            plane, self.B_PREP + self.W_PREP * e.src, self.W_PREP,
            xp.uint32(1 + e.la), xp,
        )
        entries = [
            _field(new_plane, self.B_PREP + self.W_PREP * i,
                   self.W_PREP, xp)
            for i in range(self.S)
        ]
        count = sum((en != 0).astype(xp.uint32) for en in entries)
        fire = handled & (count == 2)  # majority(3) (paxos.rs:144)
        # best la among present entries (la codes order by (ballot,
        # proposal), None lowest — matches _accepted_sort_key).
        best = xp.uint32(0)
        for en in entries:
            la = xp.where(en != 0, en - 1, 0)
            best = xp.maximum(best, la)
        chosen = xp.where(
            best > 0, ((best - 1) % xp.uint32(self.P)) + 1, prop
        )
        acc_code = 1 + (e.ballot - 1) * self.P + (chosen - 1)
        fired_lane = lane
        fired_lane = _set_field(
            fired_lane, self.B_PROP, self.W_PROP, chosen, xp
        )
        fired_lane = _set_field(
            fired_lane, self.B_ACC, self.W_ACC, acc_code, xp
        )
        fired_lane = _set_field(
            fired_lane, self.B_ACCEPTS, self.W_ACCEPTS,
            xp.uint32(1 << l), xp,
        )
        new_lane = xp.where(fire, fired_lane, lane)
        masks: dict = {}
        covered = fire & xp.bool_(False)
        for p in self.choosable[e.ballot]:
            keys = [
                (l, d, "accept", e.ballot, p, 0, 0)
                for d in range(self.S)
                if d != l
            ]
            cm = self._const_mask(keys)
            hit = fire & (chosen == p)
            covered = covered | hit
            for ln, m in cm.items():
                masks[ln] = masks.get(ln, xp.uint32(0)) | xp.where(
                    hit, xp.uint32(m), xp.uint32(0)
                )
        poison = fire & ~covered
        if self.two_lane:
            out = vec.at[l].set(xp.where(handled, new_lane, lane))
            out = out.at[self._prep_lane(l)].set(
                xp.where(handled, new_plane, plane)
            )
        else:
            # Main lane and prepares share one lane: merge the updated
            # prepares field range into the (possibly fired) main bits.
            pmask = xp.uint32(
                ((1 << (self.S * self.W_PREP)) - 1) << self.B_PREP
            )
            merged = (new_lane & ~pmask) | (new_plane & pmask)
            out = vec.at[l].set(xp.where(handled, merged, lane))
        out = self._poison(out, poison, xp)
        out = self._net_update(out, k, masks, xp)
        return out, handled

    def _on_accept(self, vec, k, e: EnvSpec, xp):
        lane = vec[e.dst]
        decided = _field(lane, self.B_DEC, 1, xp) != 0
        ballot = _field(lane, self.B_BALLOT, self.W_BALLOT, xp)
        handled = (~decided) & (ballot <= e.ballot)
        acc_code = 1 + (e.ballot - 1) * self.P + (e.prop - 1)
        new_lane = _set_field(
            lane, self.B_BALLOT, self.W_BALLOT, xp.uint32(e.ballot), xp
        )
        new_lane = _set_field(
            new_lane, self.B_ACC, self.W_ACC, xp.uint32(acc_code), xp
        )
        out = vec.at[e.dst].set(xp.where(handled, new_lane, lane))
        cm = self._const_mask([(e.dst, e.src, "accepted", e.ballot, 0, 0, 0)])
        masks = {
            ln: xp.where(handled, xp.uint32(m), xp.uint32(0))
            for ln, m in cm.items()
        }
        out = self._net_update(out, k, masks, xp)
        return out, handled

    def _on_accepted(self, vec, k, e: EnvSpec, xp):
        l = e.dst
        lane = vec[l]
        decided = _field(lane, self.B_DEC, 1, xp) != 0
        ballot = _field(lane, self.B_BALLOT, self.W_BALLOT, xp)
        prop = _field(lane, self.B_PROP, self.W_PROP, xp)
        handled = (~decided) & (ballot == e.ballot)
        accepts = _field(
            lane, self.B_ACCEPTS, self.W_ACCEPTS, xp
        ) | xp.uint32(1 << e.src)
        count = sum(
            ((accepts >> xp.uint32(i)) & 1).astype(xp.uint32)
            for i in range(self.S)
        )
        fire = handled & (count == 2)
        new_lane = _set_field(
            lane, self.B_ACCEPTS, self.W_ACCEPTS, accepts, xp
        )
        new_lane = xp.where(
            fire, new_lane | xp.uint32(1 << self.B_DEC), new_lane
        )
        masks: dict = {}
        covered = fire & xp.bool_(False)
        for p in self.choosable[e.ballot]:
            keys = [
                (l, d, "decided", e.ballot, p, 0, 0)
                for d in range(self.S)
                if d != l
            ]
            # PutOk to the proposal's requester.
            keys.append((l, self.clients[p - 1], "putok", 0, p, 0, 0))
            cm = self._const_mask(keys)
            hit = fire & (prop == p)
            covered = covered | hit
            for ln, m in cm.items():
                masks[ln] = masks.get(ln, xp.uint32(0)) | xp.where(
                    hit, xp.uint32(m), xp.uint32(0)
                )
        poison = fire & ~covered
        out = vec.at[l].set(xp.where(handled, new_lane, lane))
        out = self._poison(out, poison, xp)
        out = self._net_update(out, k, masks, xp)
        return out, handled

    def _on_decided(self, vec, k, e: EnvSpec, xp):
        lane = vec[e.dst]
        decided = _field(lane, self.B_DEC, 1, xp) != 0
        handled = ~decided
        acc_code = 1 + (e.ballot - 1) * self.P + (e.prop - 1)
        new_lane = _set_field(
            lane, self.B_BALLOT, self.W_BALLOT, xp.uint32(e.ballot), xp
        )
        new_lane = _set_field(
            new_lane, self.B_ACC, self.W_ACC, xp.uint32(acc_code), xp
        )
        new_lane = new_lane | xp.uint32(1 << self.B_DEC)
        out = vec.at[e.dst].set(xp.where(handled, new_lane, lane))
        out = self._net_update(out, k, {}, xp)
        return out, handled

    def _poison(self, vec, cond, xp):
        cl = self._clane_index(0)
        lane = vec[cl]
        return vec.at[cl].set(
            xp.where(cond, lane | xp.uint32(1 << _B_POISON), lane)
        )

    # -- sparse action dispatch (SparseEncodedModel) -----------------------
    #
    # The dense step_vec pays for all K slots per frontier row; with
    # K=284 at check 3 that is ~200x padding (PERF.md §paxos). The
    # sparse interface gives the engine (a) a cheap per-slot enabled
    # predicate — the envelope's presence bit AND the handler's guard,
    # which for every paxos handler is a small function of the DST
    # actor's fields — and (b) a table-driven per-pair transition where
    # every per-slot constant of the dense handlers (_on_*) becomes a
    # gather by slot index. Send masks unify into one [K*SEL]-row table
    # indexed by (slot, selector): the selector is the single
    # state-dependent value each handler's send depends on (put: dst's
    # ballot enum; get: read value; prepare: acceptor's accepted code;
    # prepared: chosen proposal; accepted: leader's proposal).

    _KINDS = (
        "put", "get", "putok", "getok", "prepare", "prepared", "accept",
        "accepted", "decided",
    )

    #: Measured max enabled slots per reachable state: 5 (1c), 8 (2c),
    #: 8 (3c d<=9, 4c d<=7) — 16 gives 2x headroom; the engine detects
    #: overflow loudly.
    pair_width_hint = 16

    def _sparse_tables(self) -> dict:
        if hasattr(self, "_sp"):
            return self._sp
        K, S, P, NB = self.K, self.S, self.P, self.NB
        la_max = NB * P
        SEL = max(NB, P, la_max) + 1
        kindno = {k: n for n, k in enumerate(self._KINDS)}
        kind = np.zeros(K, np.uint32)
        dst = np.zeros(K, np.uint32)
        src = np.zeros(K, np.uint32)
        ballot = np.zeros(K, np.uint32)
        prop = np.zeros(K, np.uint32)
        la = np.zeros(K, np.uint32)
        value = np.zeros(K, np.uint32)
        dst_srv = np.zeros(K, np.uint32)
        dst_cli = np.zeros(K, np.uint32)
        prep_lane = np.zeros(K, np.uint32)
        send = np.zeros((K * SEL, self.net_lanes), np.uint32)
        poison = np.zeros(K * SEL, np.uint32)
        aux = np.zeros(K * SEL, np.uint32)

        def orkey(row: int, key: tuple) -> None:
            kk = self.index[key]
            send[row, kk // 32] |= np.uint32(1 << (kk % 32))

        for k, e in enumerate(self.universe):
            kind[k] = kindno[e.kind]
            dst[k], src[k] = e.dst, e.src
            ballot[k], prop[k], la[k], value[k] = (
                e.ballot, e.prop, e.la, e.value,
            )
            dst_srv[k] = min(e.dst, S - 1)
            dst_cli[k] = (
                self.clients.index(e.dst) if e.dst in self.clients else 0
            )
            prep_lane[k] = self._prep_lane(int(dst_srv[k]))
            row0 = k * SEL
            if e.kind == "put":
                rounds = sorted(r for (r, l) in self.ballots if l == e.dst)
                round_of = [0] + [r for (r, _) in self.ballots]
                for sel in range(NB + 1):
                    nr = round_of[sel] + 1
                    if nr in rounds:
                        b = self.ballot_enum[(nr, Id(e.dst))]
                        aux[row0 + sel] = b
                        for d in range(S):
                            if d != e.dst:
                                orkey(
                                    row0 + sel,
                                    (e.dst, d, "prepare", b, 0, 0, 0),
                                )
                    else:
                        poison[row0 + sel] = 1
            elif e.kind == "get":
                for v in range(1, P + 1):
                    key = (e.dst, e.src, "getok", 0, 0, 0, v)
                    if key in self.index:
                        orkey(row0 + v, key)
            elif e.kind == "putok":
                orkey(
                    row0, (e.dst, (e.dst + 1) % S, "get", 0, 0, 0, 0)
                )
            elif e.kind == "prepare":
                las = set(self.la_universe[e.ballot])
                for sel in range(la_max + 1):
                    if sel in las:
                        orkey(
                            row0 + sel,
                            (e.dst, e.src, "prepared", e.ballot, 0, sel, 0),
                        )
                    else:
                        poison[row0 + sel] = 1
            elif e.kind == "prepared":
                ch = set(self.choosable[e.ballot])
                for sel in range(P + 1):
                    if sel in ch:
                        for d in range(S):
                            if d != e.dst:
                                orkey(
                                    row0 + sel,
                                    (e.dst, d, "accept", e.ballot, sel,
                                     0, 0),
                                )
                    else:
                        poison[row0 + sel] = 1
            elif e.kind == "accept":
                orkey(
                    row0, (e.dst, e.src, "accepted", e.ballot, 0, 0, 0)
                )
            elif e.kind == "accepted":
                ch = set(self.choosable[e.ballot])
                for sel in range(P + 1):
                    if sel in ch:
                        for d in range(S):
                            if d != e.dst:
                                orkey(
                                    row0 + sel,
                                    (e.dst, d, "decided", e.ballot, sel,
                                     0, 0),
                                )
                        orkey(
                            row0 + sel,
                            (e.dst, self.clients[sel - 1], "putok", 0,
                             sel, 0, 0),
                        )
                    else:
                        poison[row0 + sel] = 1
        # Pack per-slot params into ONE table row and the (slot, sel)
        # tables into another: per-pair fetches then cost two row
        # gathers instead of twelve scalar gathers (~10ns/row each on
        # TPU regardless of table size — measured 95ms/wave at 1M
        # pairs before packing).
        params = np.stack(
            [kind, dst_srv, dst_cli, src, ballot, prop, la, value,
             prep_lane],
            axis=1,
        )
        sendtab = np.concatenate(
            [send, poison[:, None], aux[:, None]], axis=1
        )
        self._sp = dict(
            SEL=SEL, kind=kind, ballot=ballot,
            dst_srv=dst_srv, dst_cli=dst_cli,
            k_lane=(np.arange(K) // 32).astype(np.uint32),
            k_shift=(np.arange(K) % 32).astype(np.uint32),
            params=params, sendtab=sendtab,
        )
        return self._sp

    def _bits_word_tables(self) -> dict:
        """Host-constant guard-CLASS masks for the word-native enabled
        predicate (ops/bitmask.py builders): each slot's handler guard
        depends on host constants (kind, dst, ballot) and a SMALL
        state-dependent selector of its destination actor — so slots
        group into classes sharing one enabling condition, and the
        packed mask is an OR of condition-gated class masks instead of
        a per-slot evaluation."""
        if hasattr(self, "_bw"):
            return self._bw
        from ..ops.bitmask import slot_mask_host

        K, S, NB = self.K, self.S, self.NB
        get_s = {d: [] for d in range(S)}
        put_s = {d: [] for d in range(S)}
        dec_s = {d: [] for d in range(S)}
        bal_s = {d: [[] for _ in range(NB + 1)] for d in range(S)}
        putok_c = {j: [] for j in range(self.C)}
        getok_c = {j: [] for j in range(self.C)}
        for k, e in enumerate(self.universe):
            if e.kind == "put":
                put_s[e.dst].append(k)
            elif e.kind == "get":
                get_s[e.dst].append(k)
            elif e.kind == "putok":
                putok_c[self.clients.index(e.dst)].append(k)
            elif e.kind == "getok":
                getok_c[self.clients.index(e.dst)].append(k)
            elif e.kind == "decided":
                dec_s[e.dst].append(k)
            else:
                # Ballot-relation kinds, all guarded by ~decided[dst]:
                # tabulate, per destination server and per possible
                # adopted-ballot value v, the slots whose relation
                # holds — the runtime then SELECTS one [L]-word row by
                # the server's ballot field.
                bt = e.ballot
                for v in range(NB + 1):
                    if (
                        (e.kind == "prepare" and v < bt)
                        or (e.kind == "prepared" and v == bt)
                        or (e.kind == "accept" and v <= bt)
                        or (e.kind == "accepted" and v == bt)
                    ):
                        bal_s[e.dst][v].append(k)
        self._bw = dict(
            # decided-kind slots merge into every ballot row: both are
            # gated by ~decided[dst], so one select covers them.
            nd={
                d: tuple(
                    slot_mask_host(K, bal_s[d][v] + dec_s[d])
                    for v in range(NB + 1)
                )
                for d in range(S)
            },
            get={d: slot_mask_host(K, get_s[d]) for d in range(S)},
            put={d: slot_mask_host(K, put_s[d]) for d in range(S)},
            putok={j: slot_mask_host(K, putok_c[j])
                   for j in range(self.C)},
            getok={j: slot_mask_host(K, getok_c[j])
                   for j in range(self.C)},
        )
        return self._bw

    def enabled_bits_vec(self, vec):
        """``uint32[ceil(K/32)]`` packed enabled mask, built
        WORD-NATIVE (round 6): the net lanes already hold the envelope
        presence bitmap in the ops/bitmask.py layout (slot k at bit
        k%32 of word k//32 — the same layout ``orkey`` packs), and the
        handler guard assembles from O(S·NB + C) condition-gated
        host-constant class masks. No gather, no dense ``bool[K]``
        anywhere — a vmapped caller stays ``[N, L]``-shaped, so the
        engine's [F, K] predicate pass (the largest in-stage term at
        paxos-4 shapes, PERF.md §wave-wall) collapses to [F, L] word
        lanes."""
        import jax.numpy as jnp

        from ..ops.bitmask import (
            const_words,
            or_class_words,
            select_words_host,
        )

        t = self._bits_word_tables()
        net = vec[self.n_state_lanes:]
        handled = None
        for d in range(self.S):
            lane = vec[d]
            dec = ((lane >> jnp.uint32(self.B_DEC)) & jnp.uint32(1)) != 0
            bal = (lane >> jnp.uint32(self.B_BALLOT)) & jnp.uint32(
                (1 << self.W_BALLOT) - 1
            )
            # Undecided guards: the ballot-relation row selected by
            # this server's adopted ballot (decided-kind bits ride the
            # same rows), plus its put slots when no proposal is open.
            w = select_words_host(jnp, t["nd"][d], bal)
            if any(t["put"][d]):
                prp = (lane >> jnp.uint32(self.B_PROP)) & jnp.uint32(
                    (1 << self.W_PROP) - 1
                )
                w = w | jnp.where(
                    prp == 0,
                    const_words(jnp, t["put"][d]),
                    jnp.uint32(0),
                )
            w = jnp.where(dec, const_words(jnp, t["get"][d]), w)
            handled = w if handled is None else handled | w
        cls = []
        for j in range(self.C):
            ph = (
                vec[self._clane_index(j)] >> jnp.uint32(self._coff(j))
            ) & jnp.uint32(3)
            cls += [(ph == 0, t["putok"][j]), (ph == 1, t["getok"][j])]
        handled = handled | or_class_words(jnp, cls, self.net_lanes)
        return net & handled

    def enabled_mask_vec(self, vec):
        """bool[K]: the dense view of :meth:`enabled_bits_vec` (the
        words are the source of truth, so the two cannot drift) — must
        match ``step_vec``'s validity exactly (pinned by an exhaustive
        differential test over the 2-client space)."""
        import jax.numpy as jnp

        from ..ops.bitmask import words_to_mask

        return words_to_mask(
            jnp, self.enabled_bits_vec(vec), self.max_actions
        )

    def step_slot_vec(self, vec, slot):
        """Successor for one enabled (state, slot) pair; every dense
        handler's per-slot constant is a table gather, every branch a
        select — one straight-line program, no lax.switch (all branches
        would execute under vmap anyway; sharing the gathered params
        across kinds is cheaper)."""
        import jax.numpy as jnp

        t = self._sparse_tables()
        xp = jnp
        SEL = t["SEL"]
        P, S = self.P, self.S
        slot = slot.astype(xp.uint32)
        prow = xp.asarray(t["params"])[slot]
        kind, dsrv, dcli, src, bt, pt, lat, vt, pl_idx = (
            prow[i] for i in range(9)
        )

        is_put = kind == 0
        is_get = kind == 1
        is_putok = kind == 2
        is_getok = kind == 3
        is_prepare = kind == 4
        is_prepared = kind == 5
        is_accept = kind == 6
        is_accepted = kind == 7
        is_decided = kind == 8

        def fget(lane, shift, width):
            return (lane >> shift) & xp.uint32((1 << width) - 1)

        def fset(lane, shift, width, val):
            mask = xp.uint32((1 << width) - 1) << shift
            return (lane & ~mask) | (
                (val.astype(xp.uint32) & xp.uint32((1 << width) - 1))
                << shift
            )

        def u(x):
            return xp.uint32(x)

        # Dynamic-index reads also become static selects (same TPU
        # lowering hazard class as the writes below).
        lane = vec[0]
        for j in range(1, self.S):
            lane = xp.where(dsrv == j, vec[j], lane)
        if self.two_lane:
            plane = vec[self.S]
            for j in range(self.S + 1, 2 * self.S):
                plane = xp.where(pl_idx == j, vec[j], plane)
        else:
            plane = lane  # prepares share the main lane
        # Client lane for this pair's dst client: traced dcli picks
        # lane cl0 + dcli//CPL and offset (dcli%CPL)*CST — static
        # per-lane selects (never dynamic-index reads; PERF.md).
        cl0 = self._clane_index(0)
        cl_rel = dcli // u(self.CPL)
        clane = vec[cl0]
        for q in range(1, self.n_client_lanes):
            clane = xp.where(cl_rel == q, vec[cl0 + q], clane)
        dec = fget(lane, u(self.B_DEC), 1) != 0
        bal = fget(lane, u(self.B_BALLOT), self.W_BALLOT)
        prp = fget(lane, u(self.B_PROP), self.W_PROP)
        acc = fget(lane, u(self.B_ACC), self.W_ACC)
        accepts = fget(lane, u(self.B_ACCEPTS), self.W_ACCEPTS)

        # prepared: record prepares[src] = 1 + la, majority fire.
        pshift = u(self.B_PREP) + u(self.W_PREP) * src
        new_plane = fset(plane, pshift, self.W_PREP, u(1) + lat)
        entries = [
            fget(new_plane, u(self.B_PREP + self.W_PREP * i), self.W_PREP)
            for i in range(S)
        ]
        pcount = sum((en != 0).astype(xp.uint32) for en in entries)
        fire = ~dec & (bal == bt) & (pcount == 2)
        best = u(0)
        for en in entries:
            best = xp.maximum(best, xp.where(en != 0, en - 1, u(0)))
        chosen = xp.where(best > 0, ((best - 1) % u(P)) + 1, prp)

        # accepted: accepts |= 1 << src, majority fire.
        acc2 = accepts | (u(1) << src)
        acount = sum(
            ((acc2 >> u(i)) & u(1)) for i in range(S)
        ).astype(xp.uint32)
        fire_acc = ~dec & (bal == bt) & (acount == 2)

        # get: value of the accepted proposal.
        val = xp.where(acc > 0, ((acc - 1) % u(P)) + 1, u(0))

        # Unified (slot, selector) tables: sends, poison, put's new
        # ballot. Gate: prepared/accepted send+poison only on fire.
        sel = xp.where(
            is_put, bal,
            xp.where(
                is_get, val,
                xp.where(
                    is_prepare, acc,
                    xp.where(
                        is_prepared, chosen,
                        xp.where(is_accepted, prp, u(0)),
                    ),
                ),
            ),
        )
        gate = xp.where(
            is_prepared, fire, xp.where(is_accepted, fire_acc, True)
        )
        trow = slot * u(SEL) + sel
        srow = xp.asarray(t["sendtab"])[trow]
        send_row = xp.where(gate, srow[: self.net_lanes], u(0))
        poison = gate & (srow[self.net_lanes] != 0)
        nb = srow[self.net_lanes + 1]

        # Per-kind server-lane updates (branchless, selected by kind).
        put_prep = (acc + 1) << (u(self.W_PREP) * dsrv)
        put_lane = (
            (nb << u(self.B_BALLOT))
            | (pt << u(self.B_PROP))
            | (acc << u(self.B_ACC))
        )
        prepare_lane = fset(lane, u(self.B_BALLOT), self.W_BALLOT, bt)
        acc_code_cb = u(1) + (bt - 1) * u(P) + (chosen - 1)
        fired_lane = fset(lane, u(self.B_PROP), self.W_PROP, chosen)
        fired_lane = fset(fired_lane, u(self.B_ACC), self.W_ACC,
                          acc_code_cb)
        fired_lane = fset(
            fired_lane, u(self.B_ACCEPTS), self.W_ACCEPTS, u(1) << dsrv
        )
        prepared_lane = xp.where(fire, fired_lane, lane)
        acc_code_bp = u(1) + (bt - 1) * u(P) + (pt - 1)
        accept_lane = fset(lane, u(self.B_BALLOT), self.W_BALLOT, bt)
        accept_lane = fset(accept_lane, u(self.B_ACC), self.W_ACC,
                           acc_code_bp)
        accepted_lane = fset(
            lane, u(self.B_ACCEPTS), self.W_ACCEPTS, acc2
        )
        accepted_lane = xp.where(
            fire_acc, accepted_lane | u(1 << self.B_DEC), accepted_lane
        )
        decided_lane = fset(lane, u(self.B_BALLOT), self.W_BALLOT, bt)
        decided_lane = fset(decided_lane, u(self.B_ACC), self.W_ACC,
                            acc_code_bp)
        decided_lane = decided_lane | u(1 << self.B_DEC)

        srv_lane = lane
        if not self.two_lane:
            # Prepares share the main lane: merge field updates.
            pmask = u(((1 << (S * self.W_PREP)) - 1) << self.B_PREP)
            put_lane = put_lane | (put_prep << u(self.B_PREP))
            prepared_lane = (prepared_lane & ~pmask) | (new_plane & pmask)
        srv_lane = xp.where(is_put, put_lane, srv_lane)
        srv_lane = xp.where(is_prepare, prepare_lane, srv_lane)
        srv_lane = xp.where(is_prepared, prepared_lane, srv_lane)
        srv_lane = xp.where(is_accept, accept_lane, srv_lane)
        srv_lane = xp.where(is_accepted, accepted_lane, srv_lane)
        srv_lane = xp.where(is_decided, decided_lane, srv_lane)

        # Compose the output with STATIC per-lane selects, never a
        # dynamic-index write: ``vec.at[dsrv].set(...)`` vmapped over
        # multi-million-row pair batches was observed to DROP the
        # scatter on TPU (XLA lowering hazard; correct on CPU) — the
        # repro is a padded 2M-pair wave where the server lane kept its
        # old value while the net lanes updated. W is tiny (~13), so
        # W selects are cheap and fusion-friendly anyway.
        lanes_out = []
        for j in range(self.n_state_lanes):
            lane_j = vec[j]
            if j < self.S:
                lane_j = xp.where(dsrv == j, srv_lane, lane_j)
            if self.two_lane and self.S <= j < 2 * self.S:
                plane_new = xp.where(
                    is_put, put_prep,
                    xp.where(is_prepared, new_plane, plane),
                )
                lane_j = xp.where(pl_idx == j, plane_new, lane_j)
            lanes_out.append(lane_j)
        out = vec
        for j, lane_j in enumerate(lanes_out):
            out = out.at[j].set(lane_j)

        # Client-lane updates (putok/getok) + the poison bit (which
        # always lives on client lane 0).
        cst = u(self.CST) * (dcli % u(self.CPL))
        putok_clane = fset(clane, cst, 2, u(1))
        putok_clane = fset(putok_clane, cst + u(2), 2, u(2))
        getok_clane = fset(clane, cst, 2, u(2))
        getok_clane = fset(getok_clane, cst + u(2), 2, u(3))
        getok_clane = fset(getok_clane, cst + u(4), self.W_RV, vt)
        clane_upd = xp.where(
            is_putok, putok_clane, xp.where(is_getok, getok_clane, clane)
        )
        upd = is_putok | is_getok
        for q in range(self.n_client_lanes):
            lane_q = xp.where(upd & (cl_rel == q), clane_upd, out[cl0 + q])
            if q == 0:
                lane_q = xp.where(
                    poison, lane_q | u(1 << _B_POISON), lane_q
                )
            out = out.at[cl0 + q].set(lane_q)

        # Network: clear the delivered bit, OR the (gated) sends in.
        for ln in range(self.net_lanes):
            idx = self.n_state_lanes + ln
            lane_v = out[idx]
            clear = xp.where(
                (slot >> u(5)) == u(ln),
                u(1) << (slot & u(31)),
                u(0),
            )
            out = out.at[idx].set((lane_v & ~clear) | send_row[ln])
        return out

    # -- properties --------------------------------------------------------

    def property_conditions_vec(self, vec):
        import jax.numpy as jnp

        idx = jnp.uint32(0)
        for j in range(self.C):
            clane = vec[self._clane_index(j)]
            off = self._coff(j)
            ph = _field(clane, off + 2, 2, jnp)
            rv = _field(clane, off + 4, self.W_RV, jnp)
            idx = idx * self.TB + ph * self.TBV + rv
        table = jnp.asarray(self._lin_table)
        linearizable = table[idx] & (
            _field(vec[self._clane_index(0)], _B_POISON, 1, jnp) == 0
        )
        # "value chosen": a deliverable GetOk with a non-default value.
        masks = self._const_mask(
            [
                self._env_key(e)
                for e in self.universe
                if e.kind == "getok" and e.value != 0
            ]
        )
        chosen = jnp.bool_(False)
        for ln, m in masks.items():
            chosen = chosen | (
                (vec[self.n_state_lanes + ln] & jnp.uint32(m)) != 0
            )
        return jnp.stack([linearizable, chosen])


# Round 6: the TUNED_ENGINE_CAPS budget table is retired (VERDICT r5
# item 6) — per-wave budgets auto-size from measured peaks
# (``cand_capacity="auto"``, checkers/tpu_sortmerge.py) and the
# pair-width default comes from ``pair_width_hint`` above. The
# round-5 measured reference points the table carried — enabled-pair
# peaks 3c 343,235 / 4c 686,045 / 5c 1,371,240, max 8-9 enabled slots
# per row at every client count — now live in the auto-budget store
# after one run, and in PERF.md for the record.

#: STRUCTURAL engine sizes per client count — NOT tuning: capacity
#: holds the pinned unique-state counts (265 / 16,668 / 1,194,428 /
#: 2,372,188 / 4,711,569), frontier the measured wave peaks, and the
#: 4c/5c memory knobs the padded-HBM sizing rules (PERF.md). Shared
#: by bench.py, cli.py, and tools/profile_stages.py so a resize lands
#: in exactly one place (the retune-drift property the retired budget
#: table also served).
STRUCTURAL_SIZES = {
    1: dict(capacity=1 << 10, frontier_capacity=1 << 8),
    2: dict(capacity=1 << 15, frontier_capacity=1 << 12),
    3: dict(capacity=5 << 18, frontier_capacity=1 << 18),
    4: dict(capacity=5 << 19, frontier_capacity=1 << 19,
            tile_rows=1 << 17),
    5: dict(capacity=3 << 21, frontier_capacity=3 << 19,
            tile_rows=1 << 17, f_min=1 << 16,
            flat_budget_bytes=2 << 30, mask_budget_cells=1 << 26),
}


def paxos_encoded(
    client_count: int = 2, server_count: int = 3, put_count: int = 1
) -> PaxosEncoded:
    return PaxosEncoded(
        PaxosModelCfg(
            client_count=client_count,
            server_count=server_count,
            put_count=put_count,
        )
    )
