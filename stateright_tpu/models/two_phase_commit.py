"""Two-phase commit (subset of Gray & Lamport's "Consensus on
Transaction Commit").

Counterpart of stateright examples/2pc.rs: resource managers (RMs)
prepare/abort, a transaction manager commits once all are prepared.
Reference-pinned counts: 3 RMs → 288 unique states, 5 RMs → 8,832
(665 with symmetry reduction) (2pc.rs:151-170).

This model is also the TPU proving ground: see
:mod:`stateright_tpu.models.two_phase_commit_tpu` for the vectorized
encoding checked by the device engine.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from enum import Enum
from typing import Optional, Sequence, Tuple

from ..model import Model, Property
from ..symmetry import RewritePlan


class RmState(Enum):
    WORKING = 0
    PREPARED = 1
    COMMITTED = 2
    ABORTED = 3


class TmState(Enum):
    INIT = 0
    COMMITTED = 1
    ABORTED = 2


# Messages (2pc.rs Message): ("prepared", rm) | ("commit",) | ("abort",)


@dataclass(frozen=True)
class TwoPhaseState:
    rm_state: Tuple[RmState, ...]
    tm_state: TmState
    tm_prepared: Tuple[bool, ...]
    msgs: frozenset

    def representative(self) -> "TwoPhaseState":
        """Canonicalize under RM permutation symmetry (2pc.rs:203-222)."""
        plan = RewritePlan.from_values_to_sort(
            [(s.value,) for s in self.rm_state]
        )
        return TwoPhaseState(
            rm_state=tuple(plan.reindex(self.rm_state)),
            tm_state=self.tm_state,
            tm_prepared=tuple(plan.reindex(self.tm_prepared)),
            msgs=frozenset(
                ("prepared", plan.rewrite(m[1])) if m[0] == "prepared" else m
                for m in self.msgs
            ),
        )

    def representative_full(self) -> "TwoPhaseState":
        """Perfect canonicalizer: stable-sort RMs by their FULL
        per-member tuple ``(rm_state, tm_prepared, prepared-msg)``.

        ``representative()`` above sorts on rm_state alone (like the
        reference), which is not constant on orbits — the reduced
        visited count then depends on search order (DFS 665 vs BFS 508
        at rm=5). This variant is constant on orbits, so host DFS and
        the device wave BFS agree exactly (rm=5: 314 classes); it is
        the host oracle for the TPU engines' DeviceRewriteSpec
        canonicalization (ops/canonical.py), which sorts the same
        tuple in the same encoded order."""
        prep_bits = [
            int(("prepared", i) in self.msgs)
            for i in range(len(self.rm_state))
        ]
        plan = RewritePlan.from_values_to_sort(
            [
                (s.value, int(p), b)
                for s, p, b in zip(
                    self.rm_state, self.tm_prepared, prep_bits
                )
            ]
        )
        return TwoPhaseState(
            rm_state=tuple(plan.reindex(self.rm_state)),
            tm_state=self.tm_state,
            tm_prepared=tuple(plan.reindex(self.tm_prepared)),
            msgs=frozenset(
                ("prepared", plan.rewrite(m[1])) if m[0] == "prepared" else m
                for m in self.msgs
            ),
        )


@dataclass
class TwoPhaseSys(Model):
    """``rm_count`` resource managers plus one transaction manager."""

    rm_count: int

    def to_encoded(self):
        """The TPU-engine encoding (spawn_tpu discovers this hook)."""
        from .two_phase_commit_tpu import TwoPhaseSysEncoded

        return TwoPhaseSysEncoded(self.rm_count)

    def init_states(self) -> Sequence[TwoPhaseState]:
        return [
            TwoPhaseState(
                rm_state=tuple(RmState.WORKING for _ in range(self.rm_count)),
                tm_state=TmState.INIT,
                tm_prepared=tuple(False for _ in range(self.rm_count)),
                msgs=frozenset(),
            )
        ]

    def actions(self, state: TwoPhaseState):
        actions = []
        if state.tm_state == TmState.INIT and all(state.tm_prepared):
            actions.append(("tm_commit",))
        if state.tm_state == TmState.INIT:
            actions.append(("tm_abort",))
        for rm in range(self.rm_count):
            if (
                state.tm_state == TmState.INIT
                and ("prepared", rm) in state.msgs
            ):
                actions.append(("tm_rcv_prepared", rm))
            if state.rm_state[rm] == RmState.WORKING:
                actions.append(("rm_prepare", rm))
                actions.append(("rm_choose_abort", rm))
            if ("commit",) in state.msgs:
                actions.append(("rm_rcv_commit", rm))
            if ("abort",) in state.msgs:
                actions.append(("rm_rcv_abort", rm))
        return actions

    def next_state(
        self, state: TwoPhaseState, action
    ) -> Optional[TwoPhaseState]:
        kind = action[0]
        if kind == "tm_rcv_prepared":
            rm = action[1]
            prepared = (
                state.tm_prepared[:rm] + (True,) + state.tm_prepared[rm + 1:]
            )
            return replace(state, tm_prepared=prepared)
        if kind == "tm_commit":
            return replace(
                state,
                tm_state=TmState.COMMITTED,
                msgs=state.msgs | {("commit",)},
            )
        if kind == "tm_abort":
            return replace(
                state,
                tm_state=TmState.ABORTED,
                msgs=state.msgs | {("abort",)},
            )
        rm = action[1]
        if kind == "rm_prepare":
            return replace(
                state,
                rm_state=self._with_rm(state, rm, RmState.PREPARED),
                msgs=state.msgs | {("prepared", rm)},
            )
        if kind == "rm_choose_abort":
            return replace(state, rm_state=self._with_rm(state, rm, RmState.ABORTED))
        if kind == "rm_rcv_commit":
            return replace(state, rm_state=self._with_rm(state, rm, RmState.COMMITTED))
        if kind == "rm_rcv_abort":
            return replace(state, rm_state=self._with_rm(state, rm, RmState.ABORTED))
        raise ValueError(f"unknown action {action!r}")

    @staticmethod
    def _with_rm(state: TwoPhaseState, rm: int, value: RmState):
        return state.rm_state[:rm] + (value,) + state.rm_state[rm + 1:]

    def properties(self):
        return [
            Property.sometimes(
                "abort agreement",
                lambda m, s: all(x == RmState.ABORTED for x in s.rm_state),
            ),
            Property.sometimes(
                "commit agreement",
                lambda m, s: all(x == RmState.COMMITTED for x in s.rm_state),
            ),
            Property.always(
                "consistent",
                lambda m, s: not (
                    RmState.ABORTED in s.rm_state
                    and RmState.COMMITTED in s.rm_state
                ),
            ),
        ]
