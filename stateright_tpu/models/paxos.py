"""Single-decree Paxos serving a linearizable register.

Counterpart of stateright examples/paxos.rs: each Put starts a new
ballot (phase 1 prepare/prepared, phase 2 accept/accepted, then a
decided broadcast); Gets answer only once decided. Checked against
linearizability with 2 clients / 3 servers = 16,668 unique states
(reference-pinned, paxos.rs:325, 349).

This is also the flagship TPU workload: the vectorized encoding lives
in :mod:`stateright_tpu.models.paxos_tpu`.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Optional, Tuple

from ..model import Expectation
from ..actor import (
    Actor,
    ActorModel,
    Cow,
    Id,
    Network,
    Out,
    majority,
    model_peers,
)
from ..actor.register import (
    DEFAULT_VALUE,
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
    record_invocations,
    record_returns,
)
from ..semantics import LinearizabilityTester, Register
from ..utils import HashableMap, HashableSet

# Ballot = (round, leader_id); Proposal = (req_id, requester_id, value).


@dataclass(frozen=True)
class Prepare:
    ballot: Tuple


@dataclass(frozen=True)
class Prepared:
    ballot: Tuple
    last_accepted: Optional[Tuple]  # None | (ballot, proposal)


@dataclass(frozen=True)
class Accept:
    ballot: Tuple
    proposal: Tuple


@dataclass(frozen=True)
class Accepted:
    ballot: Tuple


@dataclass(frozen=True)
class Decided:
    ballot: Tuple
    proposal: Tuple


@dataclass(frozen=True)
class PaxosState:
    ballot: Tuple
    proposal: Optional[Tuple]
    prepares: HashableMap  # Id -> Optional[(ballot, proposal)]
    accepts: HashableSet  # set of Ids
    accepted: Optional[Tuple]  # None | (ballot, proposal)
    is_decided: bool


def _accepted_sort_key(last_accepted: Optional[Tuple]):
    # Rust Option ordering: None < Some; Some by (ballot, proposal).
    return (0,) if last_accepted is None else (1,) + last_accepted


class PaxosActor(Actor):
    def __init__(self, peer_ids: list[Id]):
        self.peer_ids = peer_ids

    def name(self) -> str:
        return "Paxos Server"

    def on_start(self, id: Id, out: Out) -> PaxosState:
        return PaxosState(
            ballot=(0, Id(0)),
            proposal=None,
            prepares=HashableMap(),
            accepts=HashableSet(),
            accepted=None,
            is_decided=False,
        )

    def on_msg(self, id: Id, cow: Cow, src: Id, msg: Any, out: Out) -> None:
        state: PaxosState = cow.value
        if state.is_decided:
            if isinstance(msg, Get):
                # Reply only when decided; stay silent otherwise — a
                # value might have been decided elsewhere
                # (paxos.rs:142-155).
                _ballot, (_req, _src, value) = state.accepted
                out.send(src, GetOk(msg.req_id, value))
            return

        if isinstance(msg, Put) and state.proposal is None:
            ballot = (state.ballot[0] + 1, id)
            # Simulate Prepare + Prepared self-sends (paxos.rs:160-176).
            cow.set(
                replace(
                    state,
                    proposal=(msg.req_id, src, msg.value),
                    prepares=HashableMap({id: state.accepted}),
                    accepts=HashableSet(),
                    ballot=ballot,
                )
            )
            out.broadcast(self.peer_ids, Internal(Prepare(ballot)))

        elif isinstance(msg, Internal) and isinstance(msg.msg, Prepare):
            if state.ballot < msg.msg.ballot:
                cow.set(replace(state, ballot=msg.msg.ballot))
                out.send(
                    src,
                    Internal(Prepared(msg.msg.ballot, state.accepted)),
                )

        elif isinstance(msg, Internal) and isinstance(msg.msg, Prepared):
            if msg.msg.ballot == state.ballot:
                prepares = state.prepares.set(src, msg.msg.last_accepted)
                new_state = replace(state, prepares=prepares)
                if len(prepares) == majority(len(self.peer_ids) + 1):
                    # Leadership handoff: drive the most recently
                    # accepted proposal if any (paxos.rs:188-221).
                    best = max(
                        prepares.values(), key=_accepted_sort_key
                    )
                    proposal = (
                        best[1] if best is not None else state.proposal
                    )
                    ballot = state.ballot
                    new_state = replace(
                        new_state,
                        proposal=proposal,
                        accepted=(ballot, proposal),
                        accepts=HashableSet([id]),
                    )
                    out.broadcast(
                        self.peer_ids, Internal(Accept(ballot, proposal))
                    )
                cow.set(new_state)

        elif isinstance(msg, Internal) and isinstance(msg.msg, Accept):
            if state.ballot <= msg.msg.ballot:
                cow.set(
                    replace(
                        state,
                        ballot=msg.msg.ballot,
                        accepted=(msg.msg.ballot, msg.msg.proposal),
                    )
                )
                out.send(src, Internal(Accepted(msg.msg.ballot)))

        elif isinstance(msg, Internal) and isinstance(msg.msg, Accepted):
            if msg.msg.ballot == state.ballot:
                accepts = state.accepts.add(src)
                new_state = replace(state, accepts=accepts)
                if len(accepts) == majority(len(self.peer_ids) + 1):
                    proposal = state.proposal
                    new_state = replace(new_state, is_decided=True)
                    out.broadcast(
                        self.peer_ids,
                        Internal(Decided(state.ballot, proposal)),
                    )
                    req_id, requester_id, _value = proposal
                    out.send(requester_id, PutOk(req_id))
                cow.set(new_state)

        elif isinstance(msg, Internal) and isinstance(msg.msg, Decided):
            cow.set(
                replace(
                    state,
                    ballot=msg.msg.ballot,
                    accepted=(msg.msg.ballot, msg.msg.proposal),
                    is_decided=True,
                )
            )
        # else: ignored → no-op → pruned


@dataclass(frozen=True)
class PaxosModelCfg:
    client_count: int = 2
    server_count: int = 3
    put_count: int = 1


def paxos_model(cfg: PaxosModelCfg, network: Network | None = None) -> ActorModel:
    if network is None:
        network = Network.new_unordered_nonduplicating()

    def value_chosen(model: ActorModel, state) -> bool:
        for env in state.network.iter_deliverable():
            if isinstance(env.msg, GetOk) and env.msg.value != DEFAULT_VALUE:
                return True
        return False

    model = ActorModel(
        cfg=cfg, init_history=LinearizabilityTester(Register(DEFAULT_VALUE))
    )

    def to_encoded():
        from .paxos_tpu import PaxosEncoded

        return PaxosEncoded(cfg, network)

    model.to_encoded = to_encoded
    model.add_actors(
        RegisterServer(PaxosActor(model_peers(i, cfg.server_count)))
        for i in range(cfg.server_count)
    )
    model.add_actors(
        RegisterClient(put_count=cfg.put_count, server_count=cfg.server_count)
        for _ in range(cfg.client_count)
    )
    return (
        model.init_network(network)
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda m, s: s.history.serialized_history() is not None,
        )
        .property(Expectation.SOMETIMES, "value chosen", value_chosen)
        .record_msg_in(record_returns)
        .record_msg_out(record_invocations)
    )


def paxos_device_specs() -> dict:
    """Device property specs for compiling the ACTOR paxos model
    (``compile_actor_model(paxos_model(cfg), **paxos_device_specs(),
    closure="reachable")``) — the same history-table/network-scan
    idiom as ABD's specs (models/linearizable_register.py). The hand
    encoding (models/paxos_tpu.py) stays the production path; the
    compiled encoding exists so the kernel-lint registry holds the
    compiled paxos codegen to the hand-encoding bar (ROADMAP
    direction 5, analysis/registry.py)."""

    def linearizable(ctx, jnp):
        return (
            ctx.history_value(
                lambda h: int(h.serialized_history() is not None)
            )
            == 1
        )

    def value_chosen_vec(ctx, jnp):
        return ctx.network_any(
            lambda env: isinstance(env.msg, GetOk)
            and env.msg.value != DEFAULT_VALUE
        )

    return dict(
        properties={
            "linearizable": linearizable,
            "value chosen": value_chosen_vec,
        }
    )


def paxos_compiled_encoded(cfg: PaxosModelCfg,
                           network: Network | None = None, **kw):
    """The compiled paxos encoding: the actor model through the
    generic actor→encoding compiler, zero hand-written device code.
    ``closure="reachable"`` (the harvest/bootstrap mode): paxos
    ballots and the linearizability-tester history are bounded only by
    system reachability, so the overapproximating fixpoint has no
    protocol bound to converge on — the host explores once at compile
    time, which is exactly the right trade for the small registry
    fixture configs this exists for."""
    from ..actor.compile import compile_actor_model

    return compile_actor_model(
        paxos_model(cfg, network),
        **paxos_device_specs(),
        closure="reachable",
        **kw,
    )
