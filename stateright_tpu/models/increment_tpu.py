"""Vectorized shared-counter models (increment / increment_lock).

Encodes :mod:`stateright_tpu.models.increment` (reference
examples/increment.rs + examples/increment_lock.rs) for the TPU wave
engines. Layout (``width = 1 + ceil(N/4)`` lanes):

  lane 0:       bits 0-3 shared counter i, bit 4 lock flag
  lanes 1..:    threads packed 8 bits each: t (4b) | pc (3b)

Each thread has at most one enabled action at any pc, so
``max_actions = thread_count`` — action k is "thread k takes its
enabled step".
"""

from __future__ import annotations

import numpy as np

from ..encoding import EncodedModelBase
from .increment import Increment, IncrementLock, IncrementState, ProcState


class _IncrementEncodedBase(EncodedModelBase):
    #: lock-guarded program or racy program
    locked: bool

    def __init__(self, host_model, thread_count: int):
        if thread_count > 8:
            raise ValueError("encoding supports at most 8 threads")
        self.n = thread_count
        self.width = 1 + (thread_count + 3) // 4
        self.max_actions = thread_count
        self.host_model = host_model

    def cache_key(self):
        return (type(self).__name__, self.n)

    # -- host side -------------------------------------------------------

    def encode(self, state: IncrementState) -> np.ndarray:
        vec = np.zeros(self.width, dtype=np.uint32)
        vec[0] = state.i | (int(state.lock) << 4)
        for tid, proc in enumerate(state.s):
            lane, shift = 1 + tid // 4, (tid % 4) * 8
            vec[lane] |= (proc.t | (proc.pc << 4)) << shift
        return vec

    def decode(self, vec: np.ndarray) -> IncrementState:
        vec = np.asarray(vec)
        procs = []
        for tid in range(self.n):
            lane, shift = 1 + tid // 4, (tid % 4) * 8
            raw = (int(vec[lane]) >> shift) & 0xFF
            procs.append(ProcState(t=raw & 0xF, pc=raw >> 4))
        return IncrementState(
            i=int(vec[0]) & 0xF,
            lock=bool(int(vec[0]) & 0x10),
            s=tuple(procs),
        )

    def init_vecs(self) -> np.ndarray:
        return np.stack(
            [self.encode(s) for s in self.host_model.init_states()]
        )

    # -- device side -----------------------------------------------------

    def _thread_fields(self, vec, tid, jnp):
        lane, shift = 1 + tid // 4, (tid % 4) * 8
        raw = (vec[lane] >> jnp.uint32(shift)) & jnp.uint32(0xFF)
        return raw & jnp.uint32(0xF), raw >> jnp.uint32(4)

    def _with_thread(self, vec, tid, t, pc, jnp):
        lane, shift = 1 + tid // 4, (tid % 4) * 8
        cleared = vec[lane] & ~jnp.uint32(0xFF << shift)
        raw = (t | (pc << jnp.uint32(4))) << jnp.uint32(shift)
        return vec.at[lane].set(cleared | raw)

    def step_vec(self, vec):
        import jax.numpy as jnp

        i = vec[0] & jnp.uint32(0xF)
        lock = (vec[0] & jnp.uint32(0x10)) != 0
        succs, valids = [], []
        for tid in range(self.n):
            t, pc = self._thread_fields(vec, tid, jnp)
            if self.locked:
                # pc 0 -lock-> 1 -read-> 2 -write-> 3 -release-> 4
                valid = (
                    ((pc == 0) & ~lock)
                    | (pc == 1)
                    | (pc == 2)
                    | ((pc == 3) & lock)
                )
            else:
                # pc 1 -read-> 2 -write-> 3
                valid = (pc == 1) | (pc == 2)
            # Branchless next state per pc.
            read = pc == 1
            write = pc == 2
            new_t = jnp.where(read, i, t)
            new_pc = pc + 1
            s = self._with_thread(vec, tid, new_t, new_pc, jnp)
            new_i = jnp.where(write, t + 1, i)
            new_lock = jnp.where(
                pc == 0, True, jnp.where(pc == 3, False, lock)
            )
            s = s.at[0].set(
                new_i | (new_lock.astype(jnp.uint32) << jnp.uint32(4))
            )
            succs.append(s)
            valids.append(valid)
        return jnp.stack(succs), jnp.stack(valids)

    def _counts(self, vec, jnp):
        i = vec[0] & jnp.uint32(0xF)
        done = jnp.uint32(0)
        critical = jnp.uint32(0)
        for tid in range(self.n):
            _, pc = self._thread_fields(vec, tid, jnp)
            done = done + (pc >= 3).astype(jnp.uint32)
            critical = critical + ((pc >= 1) & (pc < 4)).astype(jnp.uint32)
        return i, done, critical


class IncrementLockEncoded(_IncrementEncodedBase):
    locked = True

    def __init__(self, thread_count: int):
        super().__init__(IncrementLock(thread_count), thread_count)

    def property_conditions_vec(self, vec):
        import jax.numpy as jnp

        i, done, critical = self._counts(vec, jnp)
        return jnp.stack([done == i, critical <= 1])  # fin, mutex


class IncrementEncoded(_IncrementEncodedBase):
    locked = False

    def __init__(self, thread_count: int):
        super().__init__(Increment(thread_count), thread_count)

    def property_conditions_vec(self, vec):
        import jax.numpy as jnp

        i, done, _ = self._counts(vec, jnp)
        return jnp.stack([done == i])  # fin
