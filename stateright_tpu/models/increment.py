"""Racy and lock-guarded shared-counter models.

Counterparts of stateright examples/increment.rs and
examples/increment_lock.rs: N threads perform a non-atomic
read-then-write increment of a shared counter. Without a lock the
final count can drop updates (the "fin" invariant fails — this model
is itself a race detector); with a lock both "fin" and "mutex" hold.
The reference pins 13 unique states (8 with symmetry) for the racy
2-thread version (increment.rs module docs).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Sequence, Tuple

from ..model import Model, Property
from ..symmetry import RewritePlan


@dataclass(frozen=True)
class ProcState:
    t: int  # thread-local copy
    pc: int  # program counter


@dataclass(frozen=True)
class IncrementState:
    i: int  # shared counter
    lock: bool
    s: Tuple[ProcState, ...]

    def representative(self) -> "IncrementState":
        # Threads are interchangeable: sort them (increment_lock.rs:35-45).
        return replace(self, s=tuple(sorted(self.s, key=lambda p: (p.t, p.pc))))


class IncrementLock(Model):
    """Lock-guarded increment: pc 0 --Lock--> 1 --Read--> 2 --Write-->
    3 --Release--> 4 (increment_lock.rs)."""

    def __init__(self, thread_count: int = 3):
        self.thread_count = thread_count

    def to_encoded(self):
        """The TPU-engine encoding (spawn_tpu* discovers this hook)."""
        from .increment_tpu import IncrementLockEncoded

        return IncrementLockEncoded(self.thread_count)

    def init_states(self) -> Sequence[IncrementState]:
        return [
            IncrementState(
                i=0,
                lock=False,
                s=tuple(ProcState(0, 0) for _ in range(self.thread_count)),
            )
        ]

    def actions(self, state: IncrementState):
        actions = []
        for tid in range(self.thread_count):
            pc = state.s[tid].pc
            if pc == 0 and not state.lock:
                actions.append(("lock", tid))
            elif pc == 1:
                actions.append(("read", tid))
            elif pc == 2:
                actions.append(("write", tid))
            elif pc == 3 and state.lock:
                actions.append(("release", tid))
        return actions

    def next_state(self, state: IncrementState, action) -> Optional[IncrementState]:
        kind, tid = action
        proc = state.s[tid]
        if kind == "lock":
            return self._set(state, tid, replace(proc, pc=1), lock=True)
        if kind == "read":
            return self._set(state, tid, replace(proc, pc=2, t=state.i))
        if kind == "write":
            return self._set(state, tid, replace(proc, pc=3), i=proc.t + 1)
        if kind == "release":
            return self._set(state, tid, replace(proc, pc=4), lock=False)
        raise ValueError(f"unknown action {action!r}")

    @staticmethod
    def _set(state, tid, proc, **updates):
        s = state.s[:tid] + (proc,) + state.s[tid + 1:]
        return replace(state, s=s, **updates)

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda m, s: sum(1 for p in s.s if p.pc >= 3) == s.i,
            ),
            Property.always(
                "mutex",
                lambda m, s: sum(1 for p in s.s if 1 <= p.pc < 4) <= 1,
            ),
        ]


class Increment(Model):
    """Unguarded racy increment: pc 1 --Read--> 2 --Write--> 3
    (increment.rs); finds the classic lost update."""

    def __init__(self, thread_count: int = 2):
        self.thread_count = thread_count

    def to_encoded(self):
        """The TPU-engine encoding (spawn_tpu* discovers this hook)."""
        from .increment_tpu import IncrementEncoded

        return IncrementEncoded(self.thread_count)

    def init_states(self) -> Sequence[IncrementState]:
        return [
            IncrementState(
                i=0,
                lock=False,
                s=tuple(ProcState(0, 1) for _ in range(self.thread_count)),
            )
        ]

    def actions(self, state: IncrementState):
        actions = []
        for tid in range(self.thread_count):
            pc = state.s[tid].pc
            if pc == 1:
                actions.append(("read", tid))
            elif pc == 2:
                actions.append(("write", tid))
        return actions

    def next_state(self, state: IncrementState, action) -> Optional[IncrementState]:
        kind, tid = action
        proc = state.s[tid]
        if kind == "read":
            return IncrementLock._set(state, tid, replace(proc, pc=2, t=state.i))
        if kind == "write":
            return IncrementLock._set(state, tid, replace(proc, pc=3), i=proc.t + 1)
        raise ValueError(f"unknown action {action!r}")

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda m, s: sum(1 for p in s.s if p.pc >= 3) == s.i,
            ),
        ]
