"""Explorer HTTP server and view builders.

Mirrors stateright src/checker/explorer.rs:

* ``serve`` (explorer.rs:79-99): attach a 4-second recent-path
  snapshot visitor, spawn the on-demand checker, serve HTTP.
* ``GET /.status`` → ``StatusView`` JSON (explorer.rs:16-24, 171-190).
* ``GET /.states/{fp[/fp...]}`` → a ``StateView`` per enumerated
  action of the state reached by replaying the fingerprint path
  (explorer.rs:224-320); each visited fingerprint is also fed to
  ``check_fingerprint`` so browsing steers the on-demand search.
* ``POST /.runtocompletion`` → flips to exhaustive search
  (explorer.rs:144, 192-202).

Views are plain functions over ``(checker, snapshot)`` so tests can
call them without HTTP, exactly as explorer.rs:322-593 does.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path as FsPath
from typing import Optional

from .. import telemetry
from ..checker import CheckerBuilder
from ..fingerprint import fingerprint
from ..model import Expectation
from ..path import Path

_EXPECTATION = {
    Expectation.ALWAYS: "Always",
    Expectation.SOMETIMES: "Sometimes",
    Expectation.EVENTUALLY: "Eventually",
}

_UI_DIR = FsPath(__file__).parent / "ui"
_UI_FILES = {
    "/": ("index.htm", "text/html"),
    "/app.css": ("app.css", "text/css"),
    "/app.js": ("app.js", "text/javascript"),
}


class Snapshot:
    """Samples one recently-visited path every ``refresh_sec`` seconds
    (explorer.rs:61-77, 88-94) to display search progress."""

    def __init__(self, refresh_sec: float = 4.0):
        self.refresh_sec = refresh_sec
        self._armed = True
        self._last_arm = time.monotonic()
        self._recent: Optional[str] = None
        self._lock = threading.Lock()

    def visit(self, model, path: Path) -> None:
        with self._lock:
            now = time.monotonic()
            if not self._armed and now - self._last_arm >= self.refresh_sec:
                self._armed = True
                self._last_arm = now
            if not self._armed:
                return
            self._armed = False
            self._recent = repr([model.format_action(a) for a in path.actions()])

    def recent_path(self) -> Optional[str]:
        with self._lock:
            return self._recent


def get_properties(checker) -> list:
    """``[expectation, name, encoded discovery path | null]`` triples
    (explorer.rs:13, 206-222) — the UI's property contract."""
    out = []
    for prop in checker.model.properties():
        disc = checker.discovery(prop.name)
        out.append(
            [
                _EXPECTATION[prop.expectation],
                prop.name,
                disc.encode() if disc is not None else None,
            ]
        )
    return out


def status_view(checker, snapshot: Optional[Snapshot] = None) -> dict:
    """``StatusView`` (explorer.rs:16-24, 171-190)."""
    return {
        "done": checker.is_done(),
        "model": type(checker.model).__name__,
        "state_count": checker.state_count(),
        "unique_state_count": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "properties": get_properties(checker),
        "recent_path": snapshot.recent_path() if snapshot else None,
    }


def _live_status_view(checker, snapshot: Optional[Snapshot]) -> dict:
    """The HTTP handler's status snapshot: live counter/discovery
    attributes only — no accessor, so no ``_ensure_run`` trigger and
    no need for the checker lock. A status poll during an in-flight
    ``run_to_completion`` must show incremental progress instead of
    queueing behind the whole exhaustive search (and before the
    handler lock existed, the accessor path could re-enter the
    running search from another thread). Reads of live attributes
    are GIL-atomic; the values are a consistent-enough snapshot for
    a progress display.

    This view covers ONE checker (the mounted Explorer model). It
    used to be the server's whole status; under the resident service
    (stateright_tpu/serve.py) the same lock-free-snapshot rule
    extends to the multi-session registry — ``make_server`` appends
    ``registry.status_block()`` (every session's live state, the
    program-LRU bytes) as the ``service`` field, so the single-
    checker assumption lives only here, not in the HTTP surface."""
    props = []
    for prop in checker.model.properties():
        disc = checker._discoveries.get(prop.name)
        props.append([
            _EXPECTATION[prop.expectation],
            prop.name,
            disc.encode() if disc is not None else None,
        ])
    return {
        "done": checker.is_done(),
        "model": type(checker.model).__name__,
        "state_count": checker._total_states,
        "unique_state_count": checker._unique_states,
        "max_depth": checker._max_depth,
        "properties": props,
        "recent_path": snapshot.recent_path() if snapshot else None,
    }


def state_views(checker, fp_path: str):
    """``GET /.states{fp_path}`` (explorer.rs:224-320).

    Returns ``(views, None)`` or ``(None, error_message)``.
    """
    model = checker.model
    fps_str = fp_path.strip("/")
    fps: list[int] = []
    if fps_str:
        for part in fps_str.split("/"):
            try:
                fps.append(int(part))
            except ValueError:
                return None, f"Unable to parse fingerprints {fps_str}"

    views = []
    if not fps:
        for state in model.init_states():
            fp = fingerprint(state)
            checker.check_fingerprint(fp)
            views.append(_state_view(model, None, None, state, fp, checker, [fp]))
        return views, None

    last_state = Path.final_state_of(model, fps)
    if last_state is None:
        return None, f"Unable to find state following fingerprints {fps_str}"
    for action in model.actions(last_state):
        outcome = model.format_step(last_state, action)
        next_state = model.next_state(last_state, action)
        if next_state is None:
            # "Action ignored" still returned for debugging
            # (explorer.rs:303-311).
            views.append(
                {
                    "action": model.format_action(action),
                    "properties": get_properties(checker),
                }
            )
            continue
        fp = fingerprint(next_state)
        checker.check_fingerprint(fp)
        views.append(
            _state_view(
                model,
                model.format_action(action),
                outcome,
                next_state,
                fp,
                checker,
                fps + [fp],
            )
        )
    return views, None


def _state_view(model, action, outcome, state, fp, checker, fps) -> dict:
    view = {
        "state": repr(state),
        "fingerprint": str(fp),
        "properties": get_properties(checker),
    }
    if action is not None:
        view["action"] = action
    if outcome is not None:
        view["outcome"] = outcome
    svg = model.as_svg(Path.from_fingerprints(model, fps))
    if svg is not None:
        view["svg"] = svg
    return view


def serve(builder: CheckerBuilder, addr: str):
    """``CheckerBuilder.serve`` (checker.rs:139-146, explorer.rs:79-99).

    Blocks serving the Explorer; returns the checker on shutdown.
    """
    snapshot = Snapshot()
    checker = builder.visitor(snapshot.visit).spawn_on_demand()
    host, _, port = addr.partition(":")
    server = make_server(checker, snapshot, host or "localhost", int(port or 3000))
    print(f"Exploring. Navigate to http://{addr}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return checker


def make_server(checker, snapshot, host: str, port: int,
                registry=None) -> ThreadingHTTPServer:
    """Build (without starting) the HTTP server — separable for tests.

    Single-checker by default (``registry=None``: the historical
    Explorer server, byte-identical behavior — the smoke tests pin
    it). The resident service (stateright_tpu/serve.py) passes itself
    as ``registry`` to mount BOTH tenancies on one server; the
    protocol is three methods:

    * ``handle_request(handler, method, path) -> bool`` — service
      routes (``POST /.check``, ``GET /.serve/sessions``, ...), tried
      BEFORE the Explorer's; True means handled.
    * ``request_scope() -> context manager`` — installed around each
      Explorer request, so the service's explorer-session tracer
      meters the per-request spans instead of the process tracer.
    * ``status_block() -> dict`` — appended to ``/.status`` as
      ``sessions``: the lock-free snapshot rule that view documents
      generalizes from one checker's live counters to the service's
      whole session registry (GIL-atomic attribute reads on both
      sides, so progress polls keep answering mid-run).

    ``checker`` may be None only with a registry (a service with no
    Explorer mounted): explorer routes then 404 while service routes
    still answer."""
    if checker is None and registry is None:
        raise ValueError(
            "make_server needs a checker, a registry, or both"
        )

    # One lock serializes every handler section that touches checker
    # state: the on-demand checker's dicts are not thread-safe under
    # ThreadingHTTPServer's per-request threads, and the round-14
    # cache-hit derivation (unique-count before/after) would misread
    # a concurrent request's exploration as its own cache miss.
    checker_lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _err(self, msg, code=404):
            body = msg.encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        # Request telemetry (round 14, the first metering brick for
        # ROADMAP direction 4's resident service): every handler runs
        # inside an ``explorer_request`` span — per-request wall plus
        # the cache-hit state (whether the request was served entirely
        # from already-explored states or pulled new ones into the
        # on-demand search). The span API's no-op path keeps untraced
        # serving cost-free; with a tracer active each request lands
        # as one span event in the TRACE artifact — the service's
        # request_scope routes them into its explorer session.

        def _dispatch(self, method):
            if registry is not None:
                if registry.handle_request(self, method, self.path):
                    return
                scope = registry.request_scope()
            else:
                scope = None
            if checker is None:
                self._err("not found")
                return
            if scope is None:
                self._explorer_request(method)
            else:
                with scope:
                    self._explorer_request(method)

        def _explorer_request(self, method):
            with telemetry.span(
                "explorer_request", method=method,
                path=self.path.split("?", 1)[0],
            ) as meta:
                if method == "GET":
                    self._get(meta)
                else:
                    self._post(meta)

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

        def _get(self, meta):
            if self.path in _UI_FILES:
                meta["kind"] = "ui"
                name, ctype = _UI_FILES[self.path]
                data = (_UI_DIR / name).read_bytes()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path == "/.status":
                # a status poll never explores: always a cache hit —
                # and deliberately LOCK-FREE (live attributes only,
                # and the registry's own lock-free snapshot), so
                # progress polls keep answering while a
                # run_to_completion holds the checker lock or a
                # service session holds the device
                meta["kind"], meta["cache_hit"] = "status", True
                view = _live_status_view(checker, snapshot)
                if registry is not None:
                    view["service"] = registry.status_block()
                self._json(view)
            elif self.path == "/.metrics":
                # plain Explorer servers (no service registry — the
                # registry's own /.metrics is tried first in
                # _dispatch): render the PROCESS-active registry
                # (stateright_tpu/metrics.py activate()), or an empty
                # exposition — a scraper sees 200 either way, the
                # same lock-free answer-while-busy rule as /.status
                meta["kind"], meta["cache_hit"] = "metrics", True
                from ..metrics import active_registry

                reg = active_registry()
                body = (reg.render_prometheus() if reg is not None
                        else "").encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/.states"):
                meta["kind"] = "states"
                # ``_unique_states`` is a live attribute (no run
                # trigger): unchanged across the handler means every
                # browsed fingerprint was already explored (the lock
                # keeps a concurrent request's exploration out of
                # this request's delta)
                with checker_lock:
                    before = checker._unique_states
                    views, err = state_views(
                        checker, self.path[len("/.states"):]
                    )
                    meta["cache_hit"] = (
                        checker._unique_states == before
                    )
                if err is not None:
                    meta["error"] = err
                    self._err(err)
                else:
                    meta["states"] = len(views)
                    self._json(views)
            else:
                meta["error"] = "not found"
                self._err("not found")

        def _post(self, meta):
            if self.path == "/.runtocompletion":
                meta["kind"] = "run_to_completion"
                with checker_lock:
                    before = checker._unique_states
                    checker.run_to_completion()
                    meta["cache_hit"] = (
                        checker._unique_states == before
                    )
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()
            else:
                meta["error"] = "not found"
                self._err("not found")

    return ThreadingHTTPServer((host, port), Handler)
