"""Explorer: interactive state-space browser over the on-demand checker.

Counterpart of stateright src/checker/explorer.rs + ui/: an HTTP server
exposing ``GET /.status``, ``GET /.states/{fp[/fp...]}`` and
``POST /.runtocompletion``, plus a small single-page UI for stepping
through the state graph.
"""

from .server import serve, state_views, status_view

__all__ = ["serve", "state_views", "status_view"]
