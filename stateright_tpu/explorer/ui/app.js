// Explorer SPA. Speaks the same JSON protocol as the reference UI
// (GET /.status, GET /.states/<fp/fp/...>, POST /.runtocompletion);
// re-written from scratch in dependency-free vanilla JS.

"use strict";

const $ = (id) => document.getElementById(id);

// Path of fingerprints from an init state to the current state, and
// the steps (actions) taken, aligned one action per fingerprint.
let path = [];        // [{fingerprint, action}]
let steps = [];       // current next-step views from the server
let selected = 0;

function pathUrl(extra) {
  const fps = path.map((p) => p.fingerprint);
  if (extra !== undefined) fps.push(extra);
  return "/.states/" + fps.join("/");
}

async function fetchStatus() {
  try {
    const r = await fetch("/.status");
    const s = await r.json();
    $("status").textContent =
      `${s.model} — states=${s.state_count} unique=${s.unique_state_count}` +
      ` depth=${s.max_depth}${s.done ? " (done)" : ""}`;
    renderProperties(s.properties, s.done);
  } catch (e) {
    $("status").textContent = "server unreachable";
  }
}

function renderProperties(props, done) {
  const ul = $("properties");
  ul.innerHTML = "";
  for (const [expectation, name, discovery] of props) {
    const li = document.createElement("li");
    const wantDiscovery = expectation === "Sometimes";
    let cls, text;
    if (discovery) {
      cls = wantDiscovery ? "prop-ok" : "prop-bad";
      text = `${expectation} "${name}": ${wantDiscovery ? "example" : "counterexample"} found`;
    } else if (done) {
      cls = wantDiscovery ? "prop-bad" : "prop-ok";
      text = `${expectation} "${name}": ${wantDiscovery ? "no example" : "holds"}`;
    } else {
      cls = "prop-search";
      text = `${expectation} "${name}": searching`;
    }
    li.className = cls;
    li.textContent = text;
    if (discovery) {
      const a = document.createElement("span");
      a.className = "prop-link";
      a.textContent = " [open]";
      a.onclick = () => loadDiscovery(discovery);
      li.appendChild(a);
      li.style.cursor = "pointer";
    }
    ul.appendChild(li);
  }
}

async function loadDiscovery(encoded) {
  // encoded = "fp/fp/fp"; walk it from the root, recording actions.
  const fps = encoded.split("/");
  path = [];
  let views = await (await fetch("/.states/")).json();
  for (const fp of fps) {
    const v = views.find((x) => x.fingerprint === fp);
    path.push({
      fingerprint: fp,
      action: v ? v.action || "(init)" : "?",
      state: v ? v.state : "",
    });
    views = await (await fetch(pathUrl())).json();
  }
  steps = views;
  selected = 0;
  render(stateOfLast());
}

let lastStateText = "";
function stateOfLast() { return lastStateText; }

async function loadSteps(stateText) {
  const r = await fetch(pathUrl());
  if (!r.ok) { $("state").textContent = await r.text(); return; }
  steps = await r.json();
  selected = 0;
  render(stateText);
}

function render(stateText) {
  lastStateText = stateText || "";
  $("state").textContent = lastStateText;
  const ol = $("path");
  ol.innerHTML = "";
  path.forEach((p, i) => {
    const li = document.createElement("li");
    li.textContent = p.action || "(init)";
    li.title = p.fingerprint;
    li.onclick = () => truncateTo(i);
    ol.appendChild(li);
  });
  const ul = $("steps");
  ul.innerHTML = "";
  steps.forEach((s, i) => {
    const li = document.createElement("li");
    const ignored = s.fingerprint === undefined;
    li.textContent = (s.action || "(init)") + (ignored ? " — ignored" : "");
    li.className = (i === selected ? "selected" : "") + (ignored ? " ignored" : "");
    if (!ignored) li.onclick = () => choose(i);
    ul.appendChild(li);
  });
  const svg = steps[selected] && steps[selected].svg;
  $("svg").innerHTML = svg || "";
  fetchStatus();
}

async function choose(i) {
  const s = steps[i];
  if (!s || s.fingerprint === undefined) return;
  path.push({ fingerprint: s.fingerprint, action: s.action || "(init)", state: s.state });
  await loadSteps(s.state);
}

function currentStateText() {
  return path.length ? path[path.length - 1].state || "" : "";
}

async function truncateTo(i) {
  path = path.slice(0, i + 1);
  await loadSteps(currentStateText());
}

async function up() {
  if (path.length === 0) return;
  path.pop();
  await loadSteps(currentStateText());
}

async function init() {
  path = [];
  await loadSteps("");
}

document.addEventListener("keydown", (e) => {
  if (e.key === "j") { selected = Math.min(selected + 1, steps.length - 1); render(lastStateText); }
  else if (e.key === "k") { selected = Math.max(selected - 1, 0); render(lastStateText); }
  else if (e.key === "Enter") { choose(selected); }
  else if (e.key === "Backspace") { e.preventDefault(); up(); }
});

$("run").onclick = async () => {
  await fetch("/.runtocompletion", { method: "POST" });
  fetchStatus();
};

setInterval(fetchStatus, 2000);
init();
