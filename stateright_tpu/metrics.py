"""Live metrics + SLO layer (ROADMAP direction 2(c)'s signal plane).

Every observability layer before this one is *post-hoc*: wave
telemetry, the memory/latency/shard ledgers all land in per-run TRACE
files and are read by offline report tools. The resident service
(stateright_tpu/serve.py) needs a LIVE, aggregated view — queue wait,
admission refusals, time-to-verdict percentiles — the signals an
autoscaling policy loop actuates on and the "p50/p99 holds under a
traffic spike" done-criterion measures. This module is that plane:

* **Registry** (:class:`MetricsRegistry`): thread-safe counters,
  gauges, and fixed log-bucket streaming histograms
  (:data:`SECONDS_BUCKETS`, sub-ms to minutes), labeled. Families are
  get-or-create by name so instrumentation sites never coordinate.
* **Zero overhead when inactive**: the module-level hooks
  (:func:`counter` / :func:`gauge` / :func:`histogram`) mirror
  telemetry's ``current_tracer() is None`` discipline — with no
  registry activated they return one shared no-op singleton
  (:data:`_NULL`, ``__slots__ = ()``), so an unmetered path allocates
  no per-call Python objects and programs compile byte-identically.
  The engines themselves carry NO metrics calls at all: engine signals
  arrive through the bridge, post-hoc per session.
* **Tracer→metrics bridge** (:func:`bridge_events`): folds any
  schema-validated telemetry event stream (chunk walls, program_build
  tiers, tier_spill, checkpoint, watchdog_timeout, fault_degrade,
  shard_health, program/snapshot evictions, batch occupancy, the
  verdict timeline, session brackets) into registry families — zero
  new engine code, and the SAME function serves live feeding (the
  service bridges each session's tracer at settle) and offline replay
  (a committed TRACE reproduces the exact counters, pinned by the
  reconciliation test in tests/test_metrics.py).
* **Export**: Prometheus text format (:meth:`MetricsRegistry.
  render_prometheus`, served as ``GET /.metrics`` beside ``/.status``),
  periodic JSONL rollups (:class:`Rollup`, one ``metrics_rollup``
  event per tick — loads and validates through telemetry's
  load_trace/validate_events), and a JSON snapshot embedded in
  SERVE_r*/bench provenance.
* **Shared quantile math**: :func:`quantile` (exact, small-N linear
  interpolation — the one implementation serve_report and
  serve_loadtest both use) and :func:`bucket_quantile` (the streaming
  bucket-interpolated estimate over histogram counts), pinned against
  each other by a unit test.
* **SLO layer**: a declarative spec (:data:`SLO_OBJECTIVES` — p50/p99
  time-to-verdict, max refusal rate, max queue wait, min cache-hit
  rate), :func:`evaluate_slo` over an observed block derived from a
  registry/rollup/live endpoint (:func:`slo_observed`), and the
  ``SLO_r*`` artifact family (:func:`write_slo_artifact`, own round
  sequence like MEM/LAT/SERVE; cross-referenced by bench provenance
  via ``artifacts.latest_slo_summary``). tools/slo_report.py
  exit-code-gates on the evaluation like trace_diff.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Callable, Optional

#: fixed log-bucket upper bounds (seconds) every streaming histogram
#: defaults to: the 1-2.5-5 decade ladder from 100 µs (the sub-ms
#: dispatch/queue lanes) to 5 minutes (cold-compile time-to-verdict
#: tails past the 60 s mark), +Inf implicit as the overflow bucket.
#: Fixed — not per-family — so two histograms are always comparable
#: bucket-for-bucket and a rollup diff never re-bins.
SECONDS_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0, 120.0,
    300.0,
)


# -- shared quantile math (serve_report + serve_loadtest + SLO) -----------


def quantile(values, q: float) -> Optional[float]:
    """Exact linear-interpolated quantile of a small in-memory sample
    (no numpy dependency for the report paths). THE shared
    implementation: tools/serve_report.py and tools/serve_loadtest.py
    both route here instead of growing private copies."""
    if not values:
        return None
    xs = sorted(values)
    pos = q * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    return round(xs[lo] + (xs[hi] - xs[lo]) * (pos - lo), 6)


def bucket_quantile(edges, counts, q: float,
                    vmin: Optional[float] = None,
                    vmax: Optional[float] = None) -> Optional[float]:
    """Streaming quantile estimate over histogram bucket counts
    (``len(counts) == len(edges) + 1``, last bucket is the +Inf
    overflow): find the bucket the rank lands in, interpolate linearly
    inside it. The observed ``vmin``/``vmax`` (tracked by
    :class:`Histogram`) tighten the first/overflow buckets and clamp
    the estimate — without them the overflow bucket degrades to the
    highest finite edge, the Prometheus ``histogram_quantile``
    convention."""
    total = sum(counts)
    if total == 0:
        return None
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= target:
            lower = edges[i - 1] if i > 0 else (
                vmin if vmin is not None else 0.0
            )
            if i < len(edges):
                upper = edges[i]
            else:
                upper = vmax if vmax is not None else edges[-1]
            if upper < lower:
                upper = lower
            frac = (target - cum) / c
            est = lower + (upper - lower) * frac
            if vmin is not None:
                est = max(est, vmin)
            if vmax is not None:
                est = min(est, vmax)
            return round(est, 6)
        cum += c
    return None


# -- metric families ------------------------------------------------------


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class _Family:
    """One named metric family: a dict of label-set -> value cell,
    guarded by the owning registry's lock. Subclasses define the cell
    shape and the mutators."""

    kind = "untyped"

    def __init__(self, name: str, help: str, lock: threading.Lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._cells: "OrderedDict[tuple, object]" = OrderedDict()

    def label_sets(self) -> list:
        with self._lock:
            return [dict(k) for k in self._cells]


class Counter(_Family):
    """Monotonic counter (Prometheus ``counter``)."""

    kind = "counter"

    def inc(self, n: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._cells[key] = self._cells.get(key, 0.0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._cells.get(_label_key(labels), 0.0))

    def total(self) -> float:
        """Sum across every label set — the reconciliation view."""
        with self._lock:
            return float(sum(self._cells.values()))


class Gauge(_Family):
    """Set/inc/dec point-in-time value (Prometheus ``gauge``)."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._cells[_label_key(labels)] = float(v)

    def inc(self, n: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._cells[key] = self._cells.get(key, 0.0) + n

    def dec(self, n: float = 1.0, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._cells.get(_label_key(labels), 0.0))


class _HistCell:
    __slots__ = ("counts", "sum", "count", "min", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets
        self.sum = 0.0
        self.count = 0
        self.min: Optional[float] = None
        self.max: Optional[float] = None


class Histogram(_Family):
    """Fixed log-bucket streaming histogram: per label set, one count
    per bucket plus exact sum/count and the observed min/max (which
    tighten :func:`bucket_quantile`'s first/overflow buckets). The
    bucket layout is :data:`SECONDS_BUCKETS` unless pinned at
    creation."""

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.Lock,
                 buckets=SECONDS_BUCKETS):
        super().__init__(name, help, lock)
        self.buckets = tuple(buckets)

    def _bucket_index(self, v: float) -> int:
        for i, edge in enumerate(self.buckets):
            if v <= edge:
                return i
        return len(self.buckets)

    def observe(self, v: float, **labels) -> None:
        if v is None or not math.isfinite(v):
            return
        key = _label_key(labels)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _HistCell(
                    len(self.buckets) + 1
                )
            cell.counts[self._bucket_index(v)] += 1
            cell.sum += v
            cell.count += 1
            cell.min = v if cell.min is None else min(cell.min, v)
            cell.max = v if cell.max is None else max(cell.max, v)

    def _cell(self, labels) -> Optional[_HistCell]:
        return self._cells.get(_label_key(labels))

    def count(self, **labels) -> int:
        with self._lock:
            cell = self._cell(labels)
            return cell.count if cell is not None else 0

    def sum(self, **labels) -> float:
        with self._lock:
            cell = self._cell(labels)
            return cell.sum if cell is not None else 0.0

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Bucket-interpolated streaming quantile (the pair of the
        exact :func:`quantile`, pinned against it by the metrics
        tests)."""
        with self._lock:
            cell = self._cell(labels)
            if cell is None:
                return None
            counts = list(cell.counts)
            vmin, vmax = cell.min, cell.max
        return bucket_quantile(self.buckets, counts, q,
                               vmin=vmin, vmax=vmax)


# -- the registry ---------------------------------------------------------


class MetricsRegistry:
    """Thread-safe process- or service-wide metric registry: families
    are get-or-create by name (a kind conflict raises — one name, one
    type, the Prometheus contract), snapshots are JSON-able, and the
    text rendering is the Prometheus exposition format ``GET
    /.metrics`` serves."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: "OrderedDict[str, _Family]" = OrderedDict()
        self._t0 = time.monotonic()

    def _get(self, cls, name: str, help: str, **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, self._lock, **kw)
                self._families[name] = fam
                return fam
        if not isinstance(fam, cls):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, "
                f"not {cls.kind}"
            )
        return fam

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=SECONDS_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    # -- value helpers (the /.status compact block, SLO derivation) ---

    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            fam = self._families.get(name)
        return fam.value(**labels) if isinstance(fam, Counter) else 0.0

    def gauge_value(self, name: str, **labels) -> float:
        with self._lock:
            fam = self._families.get(name)
        return fam.value(**labels) if isinstance(fam, Gauge) else 0.0

    def histogram_quantile(self, name: str, q: float,
                           **labels) -> Optional[float]:
        with self._lock:
            fam = self._families.get(name)
        if not isinstance(fam, Histogram):
            return None
        return fam.quantile(q, **labels)

    # -- export -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able dump of every family — the rollup payload and
        the block SERVE_r*/bench provenance embeds."""
        out: dict = {}
        with self._lock:
            fams = list(self._families.items())
        for name, fam in fams:
            with self._lock:
                cells = list(fam._cells.items())
            entry: dict = dict(kind=fam.kind, help=fam.help)
            if isinstance(fam, Histogram):
                entry["buckets"] = list(fam.buckets)
                entry["values"] = [
                    dict(labels=dict(k), counts=list(c.counts),
                         sum=round(c.sum, 6), count=c.count,
                         min=c.min, max=c.max)
                    for k, c in cells
                ]
            else:
                entry["values"] = [
                    dict(labels=dict(k), value=v) for k, v in cells
                ]
            out[name] = entry
        return out

    def rollup_event(self, t: Optional[float] = None) -> dict:
        """One ``metrics_rollup`` telemetry event: the snapshot under
        the schema telemetry.validate_events checks (registered in
        telemetry._REQUIRED), so rollup JSONL files load and validate
        exactly like TRACE artifacts."""
        if t is None:
            t = time.monotonic() - self._t0
        return dict(ev="metrics_rollup", t=round(t, 6),
                    families=self.snapshot())

    def render_prometheus(self) -> str:
        """The Prometheus text exposition format (version 0.0.4):
        HELP/TYPE headers, ``_bucket``/``_sum``/``_count`` expansion
        for histograms with cumulative ``le`` buckets, escaped label
        values."""
        lines: list[str] = []
        with self._lock:
            fams = list(self._families.items())
        for name, fam in fams:
            if fam.help:
                lines.append(f"# HELP {name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {name} {fam.kind}")
            with self._lock:
                cells = list(fam._cells.items())
            if isinstance(fam, Histogram):
                for key, cell in cells:
                    base = dict(key)
                    cum = 0
                    for edge, c in zip(fam.buckets, cell.counts):
                        cum += c
                        lines.append(
                            f"{name}_bucket"
                            f"{_render_labels(base, le=_fmt_num(edge))}"
                            f" {cum}"
                        )
                    cum += cell.counts[-1]
                    lines.append(
                        f"{name}_bucket"
                        f"{_render_labels(base, le='+Inf')} {cum}"
                    )
                    lines.append(
                        f"{name}_sum{_render_labels(base)} "
                        f"{_fmt_num(cell.sum)}"
                    )
                    lines.append(
                        f"{name}_count{_render_labels(base)} "
                        f"{cell.count}"
                    )
            else:
                if not cells:
                    continue
                for key, v in cells:
                    lines.append(
                        f"{name}{_render_labels(dict(key))} "
                        f"{_fmt_num(v)}"
                    )
        return "\n".join(lines) + "\n"


def _fmt_num(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (str(s).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(labels: dict, **extra) -> str:
    items = list(labels.items()) + list(extra.items())
    if not items:
        return ""
    body = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in items
    )
    return "{" + body + "}"


def parse_prometheus(text: str) -> dict:
    """Parse the exposition format BACK into a snapshot-shaped
    families dict — the live-endpoint half of tools/slo_report.py
    (scrape ``GET /.metrics``, evaluate the SLO against it). Handles
    exactly what :meth:`MetricsRegistry.render_prometheus` emits:
    TYPE headers, escaped labels, cumulative histogram buckets
    (de-cumulated into per-bucket counts; observed min/max are not in
    the text format, so quantiles from a scrape interpolate on edges
    alone)."""
    kinds: dict = {}
    samples: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            kinds[name] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        name, labels, value = _parse_sample(line)
        samples.setdefault(name, []).append((labels, value))
    out: dict = {}
    for name, kind in kinds.items():
        if kind == "histogram":
            out[name] = _assemble_histogram(name, samples)
        else:
            out[name] = dict(kind=kind, help="", values=[
                dict(labels=labels, value=value)
                for labels, value in samples.get(name, [])
            ])
    return out


def _parse_sample(line: str) -> tuple:
    if "{" in line:
        name, _, rest = line.partition("{")
        body, _, tail = rest.rpartition("}")
        labels = _parse_labels(body)
        value = float(tail.strip())
    else:
        name, _, tail = line.partition(" ")
        labels = {}
        value = float(tail.strip())
    return name, labels, value


def _parse_labels(body: str) -> dict:
    labels: dict = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        key = body[i:eq].strip().lstrip(",").strip()
        assert body[eq + 1] == '"', body
        j = eq + 2
        val = []
        while body[j] != '"':
            if body[j] == "\\":
                nxt = body[j + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}
                           .get(nxt, nxt))
                j += 2
            else:
                val.append(body[j])
                j += 1
        labels[key] = "".join(val)
        i = j + 1
    return labels


def _assemble_histogram(name: str, samples: dict) -> dict:
    cells: dict = {}
    edges: list = []
    for labels, value in samples.get(f"{name}_bucket", []):
        le = labels.pop("le", None)
        key = _label_key(labels)
        cell = cells.setdefault(
            key, dict(labels=labels, cum=[], sum=0.0, count=0)
        )
        edge = math.inf if le == "+Inf" else float(le)
        cell["cum"].append((edge, value))
        if edge is not math.inf and edge not in edges:
            edges.append(edge)
    for labels, value in samples.get(f"{name}_sum", []):
        cells.setdefault(
            _label_key(labels),
            dict(labels=labels, cum=[], sum=0.0, count=0),
        )["sum"] = value
    for labels, value in samples.get(f"{name}_count", []):
        cells.setdefault(
            _label_key(labels),
            dict(labels=labels, cum=[], sum=0.0, count=0),
        )["count"] = int(value)
    edges.sort()
    values = []
    for cell in cells.values():
        cum = [v for _, v in sorted(cell["cum"],
                                    key=lambda p: p[0])]
        counts = [
            int(cum[i] - (cum[i - 1] if i else 0))
            for i in range(len(cum))
        ]
        values.append(dict(
            labels=cell["labels"], counts=counts,
            sum=cell["sum"], count=cell["count"],
            min=None, max=None,
        ))
    return dict(kind="histogram", help="", buckets=edges,
                values=values)


# -- near-zero-overhead module hooks (the tracer discipline) --------------

_ACTIVE: Optional[MetricsRegistry] = None
_ACTIVE_LOCK = threading.Lock()


def active_registry() -> Optional[MetricsRegistry]:
    """The process-activated registry, or None (the common,
    zero-overhead case — the module hooks guard on this exactly like
    telemetry.current_tracer)."""
    return _ACTIVE


@contextmanager
def activate(registry: MetricsRegistry):
    """Install ``registry`` as the process-active registry for the
    block (one at a time, the RunTracer.activate contract)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None and _ACTIVE is not registry:
            raise RuntimeError(
                "another MetricsRegistry is already active"
            )
        _ACTIVE = registry
    try:
        yield registry
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None


class _NullMetric:
    """The shared no-op metric: every mutator is a pass, every reader
    answers zero/None, and ``__slots__ = ()`` pins that the unmetered
    fast path allocates no per-call Python objects (the regression
    test in tests/test_metrics.py)."""

    __slots__ = ()

    def inc(self, n=1.0, **labels):
        pass

    def dec(self, n=1.0, **labels):
        pass

    def set(self, v, **labels):
        pass

    def observe(self, v, **labels):
        pass

    def value(self, **labels):
        return 0.0

    def total(self):
        return 0.0

    def count(self, **labels):
        return 0

    def sum(self, **labels):
        return 0.0

    def quantile(self, q, **labels):
        return None


_NULL = _NullMetric()


def counter(name: str, help: str = ""):
    """Module-level hook: the active registry's counter, or the
    shared no-op singleton — call sites never need a registry
    reference or an if."""
    reg = _ACTIVE
    return _NULL if reg is None else reg.counter(name, help)


def gauge(name: str, help: str = ""):
    reg = _ACTIVE
    return _NULL if reg is None else reg.gauge(name, help)


def histogram(name: str, help: str = ""):
    reg = _ACTIVE
    return _NULL if reg is None else reg.histogram(name, help)


# -- the tracer -> metrics bridge -----------------------------------------

#: the bridge's family names (tests assert /.metrics serves these
#: under load; slo_observed derives the SLO block from them)
BRIDGE_FAMILIES = (
    "stpu_sessions_total",
    "stpu_queue_wait_seconds",
    "stpu_admission_wait_seconds",
    "stpu_admitted_bytes_total",
    "stpu_warm_start_sessions_total",
    "stpu_time_to_verdict_seconds",
    "stpu_verdicts_total",
    "stpu_program_builds_total",
    "stpu_program_build_seconds",
    "stpu_chunks_total",
    "stpu_chunk_dispatch_seconds",
    "stpu_chunk_fetch_seconds",
    "stpu_waves_total",
    "stpu_new_states_total",
    "stpu_tier_spills_total",
    "stpu_tier_spill_rows_total",
    "stpu_checkpoints_total",
    "stpu_checkpoint_bytes_total",
    "stpu_watchdog_timeouts_total",
    "stpu_fault_degrades_total",
    "stpu_shard_health_events_total",
    "stpu_program_evictions_total",
    "stpu_program_evicted_bytes_total",
    "stpu_snapshot_evictions_total",
    "stpu_snapshot_evicted_bytes_total",
    "stpu_batched_sessions_total",
    "stpu_batch_occupancy",
)


def bridge_events(events, registry: Optional[MetricsRegistry] = None,
                  ) -> MetricsRegistry:
    """Fold a telemetry event stream into metric families — the
    tracer→metrics bridge. Pure over its input: feeding the SAME
    events twice doubles the counters, so callers feed each stream
    exactly once (the service bridges a session's tracer at settle;
    the rollup thread rebuilds a fresh registry per tick).

    Derivations mirror the offline tools so the bridge can never
    silently disagree with them (pinned by the TRACE_r30/r31
    reconciliation test): per-run time-to-verdict is the max verdict
    ``round(t - run_begin.t, 6)`` — exactly serve_summary's
    ``t_since_run`` — and the per-tier build counts aggregate the
    same ``program_build`` rows serve_report tables."""
    reg = registry if registry is not None else MetricsRegistry()
    c_sessions = reg.counter(
        "stpu_sessions_total", "settled sessions by final state"
    )
    h_queue = reg.histogram(
        "stpu_queue_wait_seconds",
        "per-session accumulated FIFO device-gate wait",
    )
    h_adm_wait = reg.histogram(
        "stpu_admission_wait_seconds",
        "submit-to-admit wait per session",
    )
    c_adm_bytes = reg.counter(
        "stpu_admitted_bytes_total",
        "priced resident bytes admitted across sessions",
    )
    c_warm = reg.counter(
        "stpu_warm_start_sessions_total",
        "sessions resumed from a retained warm snapshot",
    )
    h_ttv = reg.histogram(
        "stpu_time_to_verdict_seconds",
        "per-run wall from run begin to the last verdict",
    )
    c_verdicts = reg.counter(
        "stpu_verdicts_total", "property verdicts by kind"
    )
    c_builds = reg.counter(
        "stpu_program_builds_total",
        "compile-cache ledger rows by tier",
    )
    h_build = reg.histogram(
        "stpu_program_build_seconds", "program build-or-fetch walls"
    )
    c_chunks = reg.counter("stpu_chunks_total", "device chunks")
    h_disp = reg.histogram(
        "stpu_chunk_dispatch_seconds", "per-chunk dispatch walls"
    )
    h_fetch = reg.histogram(
        "stpu_chunk_fetch_seconds", "per-chunk host fetch walls"
    )
    c_waves = reg.counter("stpu_waves_total", "BFS waves")
    c_new = reg.counter(
        "stpu_new_states_total", "post-dedup new states"
    )
    c_spills = reg.counter(
        "stpu_tier_spills_total", "hot->cold visited-set spills"
    )
    c_spill_rows = reg.counter(
        "stpu_tier_spill_rows_total", "rows moved hot->cold"
    )
    c_ckpt = reg.counter("stpu_checkpoints_total", "snapshots written")
    c_ckpt_bytes = reg.counter(
        "stpu_checkpoint_bytes_total", "snapshot bytes written"
    )
    c_watchdog = reg.counter(
        "stpu_watchdog_timeouts_total", "hung-dispatch deadline hits"
    )
    c_degrade = reg.counter(
        "stpu_fault_degrades_total", "elastic shard degrades"
    )
    c_health = reg.counter(
        "stpu_shard_health_events_total",
        "shard-health verdicts by kind",
    )
    c_pevict = reg.counter(
        "stpu_program_evictions_total", "program-LRU evictions"
    )
    c_pevict_b = reg.counter(
        "stpu_program_evicted_bytes_total", "program bytes evicted"
    )
    c_sevict = reg.counter(
        "stpu_snapshot_evictions_total", "snapshot-spool evictions"
    )
    c_sevict_b = reg.counter(
        "stpu_snapshot_evicted_bytes_total", "snapshot bytes evicted"
    )
    c_batched = reg.counter(
        "stpu_batched_sessions_total",
        "sessions that rode a fused dispatch",
    )
    h_occupancy = reg.histogram(
        "stpu_batch_occupancy",
        "fused group size per batched session",
        buckets=(1, 2, 4, 8, 16, 32),
    )
    run_t0: dict = {}
    run_ttv: dict = {}
    for ev in events:
        kind = ev.get("ev")
        if kind == "run_begin":
            run_t0[ev.get("run")] = ev.get("t", 0.0)
        elif kind == "session_begin":
            wait = ev.get("admission_wait_sec")
            if wait is not None:
                h_adm_wait.observe(wait)
            if ev.get("admitted_bytes"):
                c_adm_bytes.inc(ev["admitted_bytes"])
        elif kind == "session_end":
            c_sessions.inc(state=str(ev.get("state")))
            if ev.get("queue_wait_sec") is not None:
                h_queue.observe(ev["queue_wait_sec"])
            if ev.get("warm_start"):
                c_warm.inc()
        elif kind == "verdict":
            c_verdicts.inc(kind=str(ev.get("kind")))
            run = ev.get("run")
            t_since = round(
                ev.get("t", 0.0) - run_t0.get(run, 0.0), 6
            )
            prev = run_ttv.get(run)
            if prev is None or t_since > prev:
                run_ttv[run] = t_since
        elif kind == "program_build":
            c_builds.inc(tier=str(ev.get("tier")))
            if ev.get("wall_sec") is not None:
                h_build.observe(ev["wall_sec"])
        elif kind == "chunk":
            c_chunks.inc()
            if ev.get("dispatch_sec") is not None:
                h_disp.observe(ev["dispatch_sec"])
            if ev.get("fetch_sec") is not None:
                h_fetch.observe(ev["fetch_sec"])
        elif kind == "wave":
            c_waves.inc()
            if ev.get("new_states") is not None:
                c_new.inc(ev["new_states"])
        elif kind == "tier_spill":
            c_spills.inc()
            if ev.get("rows") is not None:
                c_spill_rows.inc(ev["rows"])
        elif kind == "checkpoint":
            c_ckpt.inc()
            if ev.get("snapshot_bytes"):
                c_ckpt_bytes.inc(ev["snapshot_bytes"])
        elif kind == "watchdog_timeout":
            c_watchdog.inc()
        elif kind == "fault_degrade":
            c_degrade.inc()
        elif kind == "shard_health":
            c_health.inc(kind=str(ev.get("kind")))
        elif kind == "program_evict":
            c_pevict.inc()
            if ev.get("bytes"):
                c_pevict_b.inc(ev["bytes"])
        elif kind == "snapshot_evict":
            c_sevict.inc()
            if ev.get("bytes"):
                c_sevict_b.inc(ev["bytes"])
        elif kind == "batch":
            c_batched.inc()
            if ev.get("size"):
                h_occupancy.observe(ev["size"])
    for run in sorted(run_ttv):
        h_ttv.observe(run_ttv[run])
    return reg


# -- periodic JSONL rollup (--metrics-interval=N) -------------------------


class Rollup:
    """Append one ``metrics_rollup`` JSONL line every ``interval_sec``
    (plus a final one at :meth:`stop`): the headless/long-mesh-run
    export — no HTTP server, no scrape loop, just a file that loads
    and validates through telemetry's load_trace/validate_events.
    ``source`` returns the registry to snapshot each tick: the serve
    daemon passes its live service registry, the CLI check lanes pass
    a closure that rebuilds one from the active tracer through the
    bridge (cumulative-since-start, so successive lines diff like
    counters)."""

    def __init__(self, path: str, interval_sec: float,
                 source: Callable[[], MetricsRegistry]):
        if interval_sec <= 0:
            raise ValueError(
                f"metrics interval must be > 0, got {interval_sec}"
            )
        self.path = path
        self.interval_sec = float(interval_sec)
        self._source = source
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _write(self) -> None:
        reg = self._source()
        ev = reg.rollup_event(t=time.monotonic() - self._t0)
        with open(self.path, "a") as fh:
            fh.write(json.dumps(ev, sort_keys=True) + "\n")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_sec):
            try:
                self._write()
            except Exception:
                # the rollup is an export, never a run failure
                pass

    def start(self) -> "Rollup":
        self._thread = threading.Thread(
            target=self._loop, name="metrics-rollup", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the ticker and write one final rollup (so even a run
        shorter than the interval leaves a line)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        try:
            self._write()
        except Exception:
            pass


def load_rollup(path: str) -> dict:
    """The LAST ``metrics_rollup`` line of a rollup JSONL file (the
    cumulative totals), validated through telemetry's loader — raises
    ValueError when the file carries none."""
    from .telemetry import load_trace, validate_events

    events = load_trace(path)
    validate_events(events)
    rollups = [e for e in events if e.get("ev") == "metrics_rollup"]
    if not rollups:
        raise ValueError(f"{path}: no metrics_rollup events")
    return rollups[-1]


# -- the SLO layer --------------------------------------------------------

#: the declarative objective vocabulary: spec key -> (observed key,
#: comparison, unit). A spec is a plain dict using these keys (any
#: subset; unknown keys are refused loudly by evaluate_slo).
SLO_OBJECTIVES = {
    "max_ttv_p50_sec": ("ttv_p50_sec", "<=", "s"),
    "max_ttv_p99_sec": ("ttv_p99_sec", "<=", "s"),
    "max_refusal_rate": ("refusal_rate", "<=", ""),
    "max_queue_wait_p99_sec": ("queue_wait_p99_sec", "<=", "s"),
    "min_cache_hit_rate": ("cache_hit_rate", ">=", ""),
}


def slo_observed(families: dict) -> dict:
    """Derive the observed SLO block from a families snapshot (a
    registry :meth:`~MetricsRegistry.snapshot`, a rollup line's
    ``families``, or a parsed ``/.metrics`` scrape): time-to-verdict
    and queue-wait percentiles from the histogram buckets, the
    refusal rate from the admission counters, the cache-hit rate from
    the warm/cold split. Missing families observe as None
    (unmeasured), never raise."""

    def hist_quantile(name, q):
        fam = families.get(name)
        if not isinstance(fam, dict) or fam.get("kind") != "histogram":
            return None
        edges = fam.get("buckets") or []
        best = None
        for cell in fam.get("values") or []:
            est = bucket_quantile(
                edges, cell.get("counts") or [], q,
                vmin=cell.get("min"), vmax=cell.get("max"),
            )
            if est is not None and (best is None or est > best):
                best = est
        return best

    def counter_sum(name, **labels):
        fam = families.get(name)
        if not isinstance(fam, dict):
            return 0.0
        total = 0.0
        for cell in fam.get("values") or []:
            cl = cell.get("labels") or {}
            if all(cl.get(k) == v for k, v in labels.items()):
                total += cell.get("value") or 0.0
        return total

    accepted = counter_sum(
        "stpu_serve_admission_total", decision="accepted"
    )
    refused = counter_sum(
        "stpu_serve_admission_total", decision="refused"
    )
    warm = counter_sum("stpu_serve_warm_hits_total", result="warm")
    cold = counter_sum("stpu_serve_warm_hits_total", result="cold")
    queue_p99 = hist_quantile("stpu_serve_queue_wait_seconds", 0.99)
    if queue_p99 is None:
        queue_p99 = hist_quantile("stpu_queue_wait_seconds", 0.99)
    return dict(
        ttv_p50_sec=hist_quantile("stpu_time_to_verdict_seconds", 0.5),
        ttv_p99_sec=hist_quantile(
            "stpu_time_to_verdict_seconds", 0.99
        ),
        refusal_rate=(
            round(refused / (accepted + refused), 6)
            if accepted + refused > 0 else None
        ),
        queue_wait_p99_sec=queue_p99,
        cache_hit_rate=(
            round(warm / (warm + cold), 6)
            if warm + cold > 0 else None
        ),
    )


def evaluate_slo(spec: dict, observed: dict) -> dict:
    """Evaluate a declarative SLO spec against an observed block
    (:func:`slo_observed`). Per objective: ``ok`` / ``violated`` /
    ``unmeasured`` (the signal exists in the spec but not in the
    data — a gate cannot claim a pass it didn't measure, so
    unmeasured fails the overall verdict too). Unknown spec keys
    raise ValueError (a typo must not silently gate nothing)."""
    objectives = []
    ok = True
    for key, threshold in sorted(spec.items()):
        if key not in SLO_OBJECTIVES:
            raise ValueError(
                f"unknown SLO objective {key!r} "
                f"(known: {', '.join(sorted(SLO_OBJECTIVES))})"
            )
        if threshold is None:
            continue
        obs_key, op, unit = SLO_OBJECTIVES[key]
        value = observed.get(obs_key)
        if value is None:
            status = "unmeasured"
            ok = False
        elif (value <= threshold if op == "<="
              else value >= threshold):
            status = "ok"
        else:
            status = "violated"
            ok = False
        objectives.append(dict(
            objective=key, threshold=threshold,
            observed=value, op=op, unit=unit, status=status,
        ))
    return dict(ok=ok, objectives=objectives)


def write_slo_artifact(doc: dict, root: Optional[str] = None) -> str:
    """Write one auto-numbered ``SLO_r*.json`` (own round sequence
    like MEM/LAT/SERVE — the gate evaluation over one load test or
    rollup, cross-referenced BY bench provenance via
    ``artifacts.latest_slo_summary``)."""
    from .artifacts import artifact_path, next_round, provenance, \
        repo_root

    root = repo_root() if root is None else root
    path = artifact_path(
        "SLO", "json", root=root,
        round=next_round(root, stems=("SLO",)),
    )
    out = dict(doc)
    out.setdefault("provenance", provenance())
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path
