"""The core ``Model`` abstraction and temporal properties.

TPU-native re-design of the reference's central trait
(stateright src/lib.rs:156-255): a model describes a nondeterministic
state machine via ``init_states`` / ``actions`` / ``next_state`` plus
temporal ``properties``. Everything else in the framework — host
checkers, the TPU wave engine, the actor layer, the Explorer — consumes
this protocol.

Differences from the reference, by design:

* ``actions(state)`` returns a list (no out-param; idiomatic Python).
* A model may additionally provide a *vectorized encoding*
  (:class:`stateright_tpu.encoding.schema.EncodedModel`) which the TPU
  checker uses; the host protocol here remains the semantic ground
  truth and differential oracle.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Iterable, Optional, Sequence

State = Any
Action = Any


class Expectation(Enum):
    """How a property is expected to hold (src/lib.rs:319-326)."""

    #: Holds in every reachable state; a violating state is a counterexample.
    ALWAYS = "always"
    #: Holds in at least one reachable state; such a state is an example.
    SOMETIMES = "sometimes"
    #: Holds at some point along every path; a terminal path that never
    #: satisfied it is a counterexample.
    EVENTUALLY = "eventually"


@dataclass(frozen=True)
class Property:
    """A named temporal property over model states (src/lib.rs:262-326)."""

    expectation: Expectation
    name: str
    condition: Callable[["Model", State], bool]

    @staticmethod
    def always(name: str, condition: Callable[["Model", State], bool]) -> "Property":
        return Property(Expectation.ALWAYS, name, condition)

    @staticmethod
    def sometimes(name: str, condition: Callable[["Model", State], bool]) -> "Property":
        return Property(Expectation.SOMETIMES, name, condition)

    @staticmethod
    def eventually(name: str, condition: Callable[["Model", State], bool]) -> "Property":
        return Property(Expectation.EVENTUALLY, name, condition)


class Model:
    """A nondeterministic state machine with temporal properties.

    Subclasses implement ``init_states``, ``actions``, ``next_state``
    and ``properties`` (mirroring the reference trait's required and
    provided methods, src/lib.rs:156-255).
    """

    def init_states(self) -> Sequence[State]:
        raise NotImplementedError

    def actions(self, state: State) -> Sequence[Action]:
        raise NotImplementedError

    def next_state(self, state: State, action: Action) -> Optional[State]:
        raise NotImplementedError

    def properties(self) -> Sequence[Property]:
        return []

    def within_boundary(self, state: State) -> bool:
        """Bounded-exploration hook (src/lib.rs:243-245)."""
        return True

    # -- display hooks (src/lib.rs Model display methods) ----------------

    def format_action(self, action: Action) -> str:
        return str(action)

    def format_step(self, last_state: State, action: Action) -> Optional[str]:
        next_state = self.next_state(last_state, action)
        return None if next_state is None else repr(next_state)

    def as_svg(self, path: Any) -> Optional[str]:
        """Optional visualization of a path for the Explorer."""
        return None

    # -- provided helpers (src/lib.rs next_steps/next_states) ------------

    def next_steps(self, state: State) -> list[tuple[Action, State]]:
        steps = []
        for action in self.actions(state):
            next_state = self.next_state(state, action)
            if next_state is not None:
                steps.append((action, next_state))
        return steps

    def next_states(self, state: State) -> list[State]:
        return [s for _, s in self.next_steps(state)]

    def property_by_name(self, name: str) -> Property:
        for prop in self.properties():
            if prop.name == name:
                return prop
        raise KeyError(f"no property named {name!r}")

    def checker(self) -> "CheckerBuilder":
        """Entry point to model checking (src/lib.rs:248-254)."""
        from .checker import CheckerBuilder

        return CheckerBuilder(self)
