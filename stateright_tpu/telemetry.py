"""Run-level wave telemetry: structured trace events for real check
runs.

The engines' observability story used to be three ad-hoc peak counters
and a wave-wall profiler that re-times ONE wave offline
(stateright_tpu/wavewall.py) — a real ``paxos check 4`` left no record
of what each wave actually did, so every chip measurement was a number
typed into PERF.md with no diffable artifact behind it. This module is
the missing layer (the per-iteration frontier/dedup telemetry that
GPUexplore's scalability study and cloud-scale exploration both lean
on — PAPERS.md: arXiv 1801.05857, 1203.6806):

* **Per-wave events** — wave index, frontier rows, enabled-pair
  popcount, candidate count, post-dedup new-state count, running
  unique total, depth, and the (frontier, visited) class the adaptive
  ladder dispatched. Assembled from a small device-side wave log the
  sort-merge engines append inside the chunk ``while_loop``
  (8 uint32 lanes × waves_per_sync rows, downloaded WITH the packed
  stats — one readback per chunk, so the default path keeps async
  dispatch and the <5% overhead bar; see WAVE_LOG_LANES).
* **Chunk events** — the host-side wall split the engine can measure
  without extra syncs: device dispatch (the async ``chunk_fn`` call)
  vs host fetch (the blocking stats readback, which at the default
  level includes the device wait). ``level="deep"`` adds the extra
  syncs the default path refuses: the engine forces one wave per
  chunk and blocks on the carry before the fetch, so every wave gets
  a REAL wall time and a device/fetch split.
* **Host-phase spans** — compile, seed upload, counterexample
  reconstruction, symmetry canonicalization, property checks — via
  the context-manager API (:func:`span` / :meth:`RunTracer.phase_acc`)
  used by checker.py and the host checkers. When no tracer is active
  every hook is a no-op.
* **Exporters** — JSONL (``TRACE_r*.jsonl``, auto-numbered beside the
  BENCH/LINT artifacts via :mod:`stateright_tpu.artifacts`) and
  Chrome-trace/Perfetto JSON (``TRACE_r*.trace.json``), plus the
  wave-aligned differ behind ``tools/trace_diff.py`` — the mechanism
  A/B rounds (chip re-measure, carry rework) record their
  before/after through.

Activation is explicit and process-global: CLI/bench/tools build a
:class:`RunTracer` and run the checker inside ``tracer.activate()``;
engines pick it up with :func:`current_tracer` at ``_run`` time (a
plain global, not a contextvar — the hybrid racer's device side runs
in a worker thread and must see it).
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from typing import Any, Optional

#: bump when an event type gains/loses REQUIRED fields.
SCHEMA_VERSION = 1

#: device wave-log lane layout (uint32[waves_per_sync, WAVE_LOG_LANES]
#: in the chunk carry; the engines write one row per wave, the host
#: unpacks rows into ``wave`` events). Lane 1 is 0 on engines that
#: can't see the enabled popcount from the log wrapper (the sharded
#: engine) — those pass ``pairs_valid=False`` and the event carries
#: ``enabled_pairs: null``.
WAVE_LOG_LANES = 8
WAVE_LOG_FIELDS = (
    "frontier_rows",   # live rows entering the wave
    "enabled_pairs",   # enabled-bitmap popcount (sparse single-chip)
    "candidates",      # surviving candidates (what the gen counter adds)
    "new_states",      # post-dedup winners appended to visited
    "unique_total",    # running unique count AFTER the wave
    "depth",           # depth entering the wave
    "f_class",         # frontier ladder class dispatched
    "v_class",         # visited ladder class dispatched
)

_ACTIVE: Optional["RunTracer"] = None
_ACTIVE_LOCK = threading.Lock()


def current_tracer() -> Optional["RunTracer"]:
    """The process-active tracer, or None (the common, zero-overhead
    case — every instrumentation site guards on this)."""
    return _ACTIVE


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def span(phase: str, **meta):
    """Module-level span hook: a real span on the active tracer, a
    shared no-op context manager otherwise — call sites never need a
    tracer reference or an if."""
    tracer = _ACTIVE
    if tracer is None:
        return _NULL_SPAN
    return tracer.span(phase, **meta)


def emit(ev: str, **fields) -> None:
    """Module-level instant-event hook (no-op without a tracer)."""
    tracer = _ACTIVE
    if tracer is not None:
        tracer.event(ev, **fields)


class _PhaseAcc:
    """Reusable accumulating timer for phases that run once per STATE
    (property checks, symmetry canonicalization): entering/exiting
    adds to a per-run total instead of emitting an event per state —
    one ``phase_total`` event lands at run end. Create once, reuse in
    the hot loop."""

    __slots__ = ("tracer", "phase", "_t0")

    def __init__(self, tracer: "RunTracer", phase: str):
        self.tracer = tracer
        self.phase = phase
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.tracer._accumulate(self.phase, time.monotonic() - self._t0)
        return False


class RunTracer:
    """Collects one process's trace events; see the module docstring.

    ``level`` is ``"default"`` (no extra device syncs: exact per-wave
    COUNTS from the chunk wave log, per-chunk wall split, per-wave
    times estimated by even division and flagged ``t_est``) or
    ``"deep"`` (engines force waves_per_sync=1 and block on the carry:
    real per-wave walls and a device/fetch split, at per-wave sync
    cost)."""

    def __init__(self, level: str = "default"):
        if level not in ("default", "deep"):
            raise ValueError(f"unknown trace level {level!r}")
        self.level = level
        self.events: list[dict] = []
        self._t_base = time.monotonic()
        self._lock = threading.Lock()
        self._run_idx = -1
        self._run_open = False
        self._phase_totals: dict[str, list] = {}

    # -- activation ------------------------------------------------------

    @contextmanager
    def activate(self):
        """Install as the process-active tracer for the block."""
        global _ACTIVE
        with _ACTIVE_LOCK:
            if _ACTIVE is not None and _ACTIVE is not self:
                raise RuntimeError("another RunTracer is already active")
            _ACTIVE = self
        try:
            yield self
        finally:
            with _ACTIVE_LOCK:
                _ACTIVE = None

    # -- event plumbing --------------------------------------------------

    def _now(self) -> float:
        return time.monotonic() - self._t_base

    def _append(self, ev: dict) -> None:
        with self._lock:
            self.events.append(ev)

    def event(self, ev: str, **fields) -> None:
        """Instant event (auto-budget retries, level overrides, ...)."""
        self._append(
            dict(ev=ev, run=self._run_idx, t=round(self._now(), 6),
                 **fields)
        )

    # -- runs ------------------------------------------------------------

    def begin_run(self, lane: dict | None = None) -> int:
        """Open a run (one checker execution). Embeds provenance —
        the satellite contract: every TRACE artifact names the
        toolchain/device/SHA/lane it measured."""
        from .artifacts import provenance

        with self._lock:
            self._run_idx += 1
            self._run_open = True
            self._phase_totals = {}
            self.events.append(
                dict(
                    ev="run_begin",
                    run=self._run_idx,
                    t=round(self._now(), 6),
                    schema=SCHEMA_VERSION,
                    level=self.level,
                    provenance=provenance(),
                    lane=lane or {},
                )
            )
            return self._run_idx

    def end_run(self, *, error: str | None = None, **stats) -> None:
        if not self._run_open:
            return
        for phase, (dur, count) in sorted(self._phase_totals.items()):
            self._append(
                dict(ev="phase_total", run=self._run_idx, phase=phase,
                     dur=round(dur, 6), count=count)
            )
        self.event("run_end", error=error,
                   **{k: v for k, v in stats.items()})
        self._run_open = False

    # -- spans / accumulators -------------------------------------------

    @contextmanager
    def span(self, phase: str, **meta):
        t0 = self._now()
        try:
            yield self
        finally:
            t1 = self._now()
            self._append(
                dict(ev="span", run=self._run_idx, phase=phase,
                     t0=round(t0, 6), t1=round(t1, 6),
                     dur=round(t1 - t0, 6), **meta)
            )

    def phase_acc(self, phase: str) -> _PhaseAcc:
        return _PhaseAcc(self, phase)

    def _accumulate(self, phase: str, dur: float) -> None:
        tot = self._phase_totals.setdefault(phase, [0.0, 0])
        tot[0] += dur
        tot[1] += 1

    # -- engine chunk/wave ingestion -------------------------------------

    def record_chunk(
        self,
        *,
        chunk: int,
        wave0: int,
        t0: float,
        t1: float,
        dispatch_sec: float,
        fetch_sec: float,
        device_sec: float | None = None,
        n_waves: int | None = None,
        wave_rows=None,
        pairs_valid: bool = True,
    ) -> None:
        """One chunk sync: the host wall split plus the downloaded
        device wave-log rows (``wave_rows``: int array
        [n_waves, WAVE_LOG_LANES]; None for engines without a wave
        log — the chunk event still lands). ``t0``/``t1`` are absolute
        ``time.monotonic()`` stamps bracketing dispatch→fetch."""
        rt0 = t0 - self._t_base
        rt1 = t1 - self._t_base
        if wave_rows is not None and n_waves is None:
            n_waves = len(wave_rows)
        self._append(
            dict(
                ev="chunk", run=self._run_idx, chunk=chunk,
                wave0=wave0, waves=n_waves,
                t0=round(rt0, 6), t1=round(rt1, 6),
                dispatch_sec=round(dispatch_sec, 6),
                device_sec=(None if device_sec is None
                            else round(device_sec, 6)),
                fetch_sec=round(fetch_sec, 6),
            )
        )
        if wave_rows is None or n_waves is None or n_waves == 0:
            return
        # Default level: the chunk ran async, so per-wave walls don't
        # exist — spread the chunk interval evenly and flag the
        # estimate. Deep level (1 wave/chunk): the division is exact.
        per = (rt1 - rt0) / n_waves
        est = not (self.level == "deep" and n_waves == 1)
        for i in range(n_waves):
            row = [int(x) for x in wave_rows[i]]
            fields = dict(zip(WAVE_LOG_FIELDS, row))
            if not pairs_valid:
                fields["enabled_pairs"] = None
            self._append(
                dict(
                    ev="wave", run=self._run_idx, wave=wave0 + i,
                    chunk=chunk,
                    t0=round(rt0 + i * per, 6),
                    t1=round(rt0 + (i + 1) * per, 6),
                    t_est=est,
                    **fields,
                )
            )

    # -- exporters -------------------------------------------------------

    def write_jsonl(self, path: str) -> str:
        with open(path, "w") as fh:
            with self._lock:
                for ev in self.events:
                    fh.write(json.dumps(ev, sort_keys=True) + "\n")
        return path

    def write_chrome_trace(self, path: str) -> str:
        """Chrome-trace / Perfetto JSON: host phases, device chunks,
        and waves on three named tracks, plus counter tracks for the
        frontier/new-state curves (``chrome://tracing`` or
        ui.perfetto.dev)."""
        with self._lock:
            events = list(self.events)
        out: list[dict] = []
        for pid, name in ((0, "stateright_tpu"),):
            out.append(dict(ph="M", pid=pid, name="process_name",
                            args=dict(name=name)))
        for tid, name in ((0, "host phases"), (1, "device chunks"),
                          (2, "waves")):
            out.append(dict(ph="M", pid=0, tid=tid, name="thread_name",
                            args=dict(name=name)))

        def us(t):
            return round(t * 1e6, 1)

        for ev in events:
            kind = ev.get("ev")
            if kind == "span":
                out.append(
                    dict(ph="X", pid=0, tid=0, name=ev["phase"],
                         ts=us(ev["t0"]), dur=us(ev["dur"]),
                         args={k: v for k, v in ev.items()
                               if k not in ("ev", "t0", "t1", "dur")})
                )
            elif kind == "chunk":
                out.append(
                    dict(ph="X", pid=0, tid=1,
                         name=f"chunk {ev['chunk']}",
                         ts=us(ev["t0"]),
                         dur=us(ev["t1"] - ev["t0"]),
                         args={k: ev[k] for k in
                               ("run", "waves", "dispatch_sec",
                                "device_sec", "fetch_sec")})
                )
            elif kind == "wave":
                args = {k: ev[k] for k in WAVE_LOG_FIELDS}
                args["t_est"] = ev["t_est"]
                out.append(
                    dict(ph="X", pid=0, tid=2,
                         name=f"wave {ev['wave']}",
                         ts=us(ev["t0"]),
                         dur=us(ev["t1"] - ev["t0"]), args=args)
                )
                out.append(
                    dict(ph="C", pid=0, name="frontier_rows",
                         ts=us(ev["t0"]),
                         args=dict(rows=ev["frontier_rows"]))
                )
                out.append(
                    dict(ph="C", pid=0, name="new_states",
                         ts=us(ev["t0"]),
                         args=dict(new=ev["new_states"]))
                )
            elif kind in ("run_begin", "run_end", "phase_total"):
                out.append(
                    dict(ph="i", pid=0, tid=0, s="g", name=kind,
                         ts=us(ev.get("t", ev.get("dur", 0.0))
                               if kind != "phase_total"
                               else events[0].get("t", 0.0)),
                         args={k: v for k, v in ev.items()
                               if k != "ev"})
                )
            else:  # instant engine events (auto_budget_retry, ...)
                out.append(
                    dict(ph="i", pid=0, tid=1, s="t", name=kind,
                         ts=us(ev.get("t", 0.0)),
                         args={k: v for k, v in ev.items()
                               if k not in ("ev", "t")})
                )
        with open(path, "w") as fh:
            json.dump(dict(traceEvents=out, displayTimeUnit="ms"), fh)
        return path


def write_artifacts(tracer: RunTracer, root: str | None = None,
                    round: int | None = None) -> tuple[str, str]:
    """Write the auto-numbered artifact PAIR (JSONL + Chrome trace)
    into one round slot beside the BENCH/LINT artifacts."""
    from .artifacts import artifact_path, next_round, repo_root

    root = repo_root() if root is None else root
    if round is None:
        round = next_round(root)
    jsonl = tracer.write_jsonl(
        artifact_path("TRACE", "jsonl", root=root, round=round)
    )
    chrome = tracer.write_chrome_trace(
        artifact_path("TRACE", "trace.json", root=root, round=round)
    )
    return jsonl, chrome


# -- trace loading / validation / diff -----------------------------------
#
# The logic behind tools/trace_diff.py lives here so tests import it the
# way the lint tests import stateright_tpu.analysis.

_REQUIRED = {
    "run_begin": ("run", "schema", "level", "provenance", "lane"),
    "run_end": ("run", "t"),
    "span": ("run", "phase", "t0", "t1", "dur"),
    "phase_total": ("run", "phase", "dur", "count"),
    "chunk": ("run", "chunk", "wave0", "t0", "t1", "dispatch_sec",
              "fetch_sec"),
    "wave": ("run", "wave", "chunk", "t0", "t1", "t_est")
    + WAVE_LOG_FIELDS,
}


def load_trace(path: str) -> list[dict]:
    """Parse a TRACE_r*.jsonl file; raises ValueError on malformed
    lines."""
    events = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError as exc:
                raise ValueError(
                    f"{path}:{lineno}: not JSON: {exc}"
                ) from exc
            if not isinstance(ev, dict) or "ev" not in ev:
                raise ValueError(
                    f"{path}:{lineno}: event without an 'ev' field"
                )
            events.append(ev)
    return events


def validate_events(events: list[dict]) -> None:
    """Schema check: every known event type carries its required
    fields, runs open with run_begin, and wave counters are internally
    consistent (unique_total is the running post-dedup sum). A wave
    index that does NOT advance marks an auto-budget retry restart
    (the resized attempt re-explores from wave 0 inside the same run)
    — the running-sum check resets there instead of rejecting the
    legitimate artifact. Raises ValueError on the first violation."""
    open_runs: set[int] = set()
    last_unique: dict[int, int] = {}
    last_wave: dict[int, int] = {}
    for i, ev in enumerate(events):
        kind = ev["ev"]
        for field in _REQUIRED.get(kind, ()):
            if field not in ev:
                raise ValueError(
                    f"event {i} ({kind}): missing field {field!r}"
                )
        if kind == "run_begin":
            if ev["schema"] > SCHEMA_VERSION:
                raise ValueError(
                    f"event {i}: schema {ev['schema']} newer than "
                    f"reader ({SCHEMA_VERSION})"
                )
            open_runs.add(ev["run"])
        elif kind == "wave":
            run = ev["run"]
            if run not in open_runs:
                raise ValueError(
                    f"event {i}: wave outside an open run"
                )
            if run in last_wave and ev["wave"] <= last_wave[run]:
                last_unique.pop(run, None)  # retry restart
            prev = last_unique.get(run)
            if prev is not None and ev["unique_total"] != (
                prev + ev["new_states"]
            ):
                raise ValueError(
                    f"event {i}: wave {ev['wave']} unique_total "
                    f"{ev['unique_total']} != previous {prev} + "
                    f"new_states {ev['new_states']}"
                )
            last_unique[run] = ev["unique_total"]
            last_wave[run] = ev["wave"]


def _runs(events: list[dict]) -> list[int]:
    return sorted({ev["run"] for ev in events if ev["ev"] == "run_begin"})


def _run_view(events: list[dict], run: int) -> dict:
    view: dict = dict(run=run, begin=None, end=None, waves=[],
                      chunks=[], spans=[], phase_totals={})
    for ev in events:
        if ev.get("run") != run:
            continue
        kind = ev["ev"]
        if kind == "run_begin":
            view["begin"] = ev
        elif kind == "run_end":
            view["end"] = ev
        elif kind == "wave":
            view["waves"].append(ev)
        elif kind == "chunk":
            view["chunks"].append(ev)
        elif kind == "span":
            view["spans"].append(ev)
        elif kind == "phase_total":
            view["phase_totals"][ev["phase"]] = ev
    view["waves"].sort(key=lambda w: w["wave"])
    return view


def _phase_durations(view: dict) -> dict[str, float]:
    """Per-phase wall totals for one run: named spans, accumulated
    phase totals, the chunk-level dispatch/fetch split, and the wave
    wall sum."""
    out: dict[str, float] = {}
    for s in view["spans"]:
        out[s["phase"]] = out.get(s["phase"], 0.0) + s["dur"]
    for phase, ev in view["phase_totals"].items():
        out[phase] = out.get(phase, 0.0) + ev["dur"]
    disp = sum(c["dispatch_sec"] for c in view["chunks"])
    fetch = sum(c["fetch_sec"] for c in view["chunks"])
    dev = sum(c["device_sec"] or 0.0 for c in view["chunks"])
    if view["chunks"]:
        out["device_dispatch"] = disp
        out["host_fetch"] = fetch
        if dev:
            out["device_wait"] = dev
    if view["waves"]:
        out["waves_wall"] = sum(
            w["t1"] - w["t0"] for w in view["waves"]
        )
    end = view["end"]
    if end is not None and end.get("duration_sec") is not None:
        out["run_total"] = end["duration_sec"]
    return out


#: wave counters trace_diff requires to MATCH between the two sides —
#: two traces of the same workload must explore the same space.
DIFF_COUNTERS = ("frontier_rows", "candidates", "new_states",
                 "unique_total")


def diff_traces(
    a_events: list[dict],
    b_events: list[dict],
    *,
    run_a: int | None = None,
    run_b: int | None = None,
    threshold: float = 0.10,
    min_sec: float = 0.05,
) -> dict:
    """Align two traces wave-by-wave and price the per-phase deltas.

    Returns a report dict:
      ``divergences`` — per-wave counter mismatches (a traced A/B of
        one workload must have identical exploration; any mismatch
        fails the gate),
      ``phases`` — {phase: {a, b, delta, rel}},
      ``regressions`` — phases where B exceeds A by more than
        ``threshold`` (relative), ignoring phases under ``min_sec``
        on the A side (noise floor),
      ``ok`` — True iff no divergence and no regression.

    ``run_a``/``run_b`` default to the LAST run in each file (bench
    traces warm-run-last)."""
    va = _run_view(a_events, _runs(a_events)[-1] if run_a is None
                   else run_a)
    vb = _run_view(b_events, _runs(b_events)[-1] if run_b is None
                   else run_b)

    divergences = []
    wa = {w["wave"]: w for w in va["waves"]}
    wb = {w["wave"]: w for w in vb["waves"]}
    for i in sorted(set(wa) | set(wb)):
        if i not in wa or i not in wb:
            divergences.append(
                dict(wave=i, field="present",
                     a=i in wa, b=i in wb)
            )
            continue
        for field in DIFF_COUNTERS:
            if wa[i][field] != wb[i][field]:
                divergences.append(
                    dict(wave=i, field=field,
                         a=wa[i][field], b=wb[i][field])
                )

    pa = _phase_durations(va)
    pb = _phase_durations(vb)
    phases = {}
    regressions = []
    for phase in sorted(set(pa) | set(pb)):
        a = pa.get(phase, 0.0)
        b = pb.get(phase, 0.0)
        rel = (b - a) / a if a > 0 else (float("inf") if b > 0 else 0.0)
        phases[phase] = dict(a=round(a, 6), b=round(b, 6),
                             delta=round(b - a, 6),
                             rel=round(rel, 4) if rel != float("inf")
                             else None)
        if a >= min_sec and rel > threshold:
            regressions.append(phase)

    return dict(
        run_a=va["run"], run_b=vb["run"],
        waves_a=len(va["waves"]), waves_b=len(vb["waves"]),
        divergences=divergences,
        phases=phases,
        regressions=regressions,
        threshold=threshold,
        min_sec=min_sec,
        ok=not divergences and not regressions,
    )


def format_diff(report: dict) -> str:
    lines = [
        f"trace diff: run A#{report['run_a']} "
        f"({report['waves_a']} waves) vs run B#{report['run_b']} "
        f"({report['waves_b']} waves)",
    ]
    if report["divergences"]:
        lines.append(
            f"WAVE DIVERGENCE ({len(report['divergences'])} "
            "mismatches) — the two traces did not explore the same "
            "space:"
        )
        for d in report["divergences"][:10]:
            lines.append(
                f"  wave {d['wave']:5d} {d['field']:14s} "
                f"A={d['a']} B={d['b']}"
            )
        if len(report["divergences"]) > 10:
            lines.append(
                f"  ... {len(report['divergences']) - 10} more"
            )
    lines.append(
        f"{'phase':28s} {'A sec':>10s} {'B sec':>10s} "
        f"{'delta':>10s} {'rel':>8s}"
    )
    for phase, p in report["phases"].items():
        rel = "n/a" if p["rel"] is None else f"{p['rel']:+.1%}"
        flag = "  <-- REGRESSION" if phase in report["regressions"] \
            else ""
        lines.append(
            f"{phase:28s} {p['a']:10.4f} {p['b']:10.4f} "
            f"{p['delta']:+10.4f} {rel:>8s}{flag}"
        )
    verdict = "OK" if report["ok"] else (
        "FAIL: wave divergence" if report["divergences"]
        else f"FAIL: {len(report['regressions'])} phase(s) past "
             f"+{report['threshold']:.0%}"
    )
    lines.append(f"verdict: {verdict}")
    return "\n".join(lines)
