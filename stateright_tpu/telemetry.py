"""Run-level wave telemetry: structured trace events for real check
runs.

The engines' observability story used to be three ad-hoc peak counters
and a wave-wall profiler that re-times ONE wave offline
(stateright_tpu/wavewall.py) — a real ``paxos check 4`` left no record
of what each wave actually did, so every chip measurement was a number
typed into PERF.md with no diffable artifact behind it. This module is
the missing layer (the per-iteration frontier/dedup telemetry that
GPUexplore's scalability study and cloud-scale exploration both lean
on — PAPERS.md: arXiv 1801.05857, 1203.6806):

* **Per-wave events** — wave index, frontier rows, enabled-pair
  popcount, candidate count, post-dedup new-state count, running
  unique total, depth, and the (frontier, visited) class the adaptive
  ladder dispatched. Assembled from a small device-side wave log the
  sort-merge engines append inside the chunk ``while_loop``
  (8 uint32 lanes × waves_per_sync rows, downloaded WITH the packed
  stats — one readback per chunk, so the default path keeps async
  dispatch and the <5% overhead bar; see WAVE_LOG_LANES).
* **Per-shard wave events** (round 11, the mesh observability layer)
  — the sharded engines keep a second device log that is NOT
  psum-collapsed (``SHARD_LOG_FIELDS``: local frontier/enabled/
  candidate counts, routed and received row counts, dest-tile fill
  vs the lossless ``Bd`` cap, per-shard post-dedup new and visited
  totals), downloaded in the same per-chunk sync and emitted as one
  ``shard_wave`` event per (wave, shard). :func:`shard_balance`
  derives the skew/routing/occupancy summary ROADMAP direction 1
  needs (tools/shard_report.py renders it; the dryrun/bench lanes
  embed it).
* **Chunk events** — the host-side wall split the engine can measure
  without extra syncs: device dispatch (the async ``chunk_fn`` call)
  vs host fetch (the blocking stats readback, which at the default
  level includes the device wait). ``level="deep"`` adds the extra
  syncs the default path refuses: the engine forces one wave per
  chunk and blocks on the carry before the fetch, so every wave gets
  a REAL wall time and a device/fetch split.
* **Host-phase spans** — compile, seed upload, counterexample
  reconstruction, symmetry canonicalization, property checks — via
  the context-manager API (:func:`span` / :meth:`RunTracer.phase_acc`)
  used by checker.py and the host checkers. When no tracer is active
  every hook is a no-op.
* **Latency events** (round 14, the latency observability layer —
  where the *wall-clock* goes, the axis the counters above don't
  cover): ``program_build`` events from the compile-cache ledger in
  checkers/tpu.py (every build-or-fetch at the ``_programs`` cache
  seam, the dispatch-path XLA compiles, and the AOT memory-analysis
  compile — hit tier in_process / disk / cold with the measured cold
  wall, via ``jax.monitoring``), per-property ``verdict`` events
  (discovery vs exhaustion, settle wave/depth, wall since run start —
  the time-to-verdict metric ROADMAP direction 4 declares
  first-class), and a run-end ``latency_profile`` event the tracer
  derives ITSELF in :meth:`RunTracer.end_run` from the run's chunk /
  span / build events (time-to-first-wave, the dispatch / sync-floor
  wall split and shares, compile attribution) — so every engine that
  records chunks gets the profile with zero engine-side code.
  :func:`latency_summary` derives the report tools/latency_report.py
  renders (``LAT_r*.json``, own round sequence like MEM/COMM);
  :func:`diff_traces` aligns ``latency_profile`` lanes and
  per-property time-to-verdict under the threshold — sides without
  latency events skip, so pre-round-14 baselines keep diffing.
* **Memory events** (round 12, the memory observability layer —
  stateright_tpu/memplan.py): one schema-validated ``memory_plan``
  event per run (the resident-buffer ledger + per-ladder-class
  staging + ``Compiled.memory_analysis()``), per-chunk device
  bytes-in-use on the ``chunk`` event (polled at the existing sync —
  no new syncs), and a ``memory_watermark`` event at run end (peak,
  host visited bytes, budget-store headroom, and the capacity
  projection). :func:`memory_summary` derives the report
  tools/mem_report.py renders; :func:`diff_traces` aligns the plan
  exactly and the measured temp/live bytes under the relative
  threshold.
* **Exporters** — JSONL (``TRACE_r*.jsonl``, auto-numbered beside the
  BENCH/LINT artifacts via :mod:`stateright_tpu.artifacts`) and
  Chrome-trace/Perfetto JSON (``TRACE_r*.trace.json``), plus the
  wave-aligned differ behind ``tools/trace_diff.py`` — the mechanism
  A/B rounds (chip re-measure, carry rework) record their
  before/after through.

Activation is explicit and process-global: CLI/bench/tools build a
:class:`RunTracer` and run the checker inside ``tracer.activate()``;
engines pick it up with :func:`current_tracer` at ``_run`` time (a
plain global, not a contextvar — the hybrid racer's device side runs
in a worker thread and must see it).
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from typing import Any, Optional

#: bump when an event type gains/loses REQUIRED fields.
SCHEMA_VERSION = 1

#: device wave-log lane layout (uint32[waves_per_sync, WAVE_LOG_LANES]
#: in the chunk carry; the engines write one row per wave, the host
#: unpacks rows into ``wave`` events). Lane 1 is 0 on engines that
#: can't see the enabled popcount from the log wrapper (the sharded
#: engine) — those pass ``pairs_valid=False`` and the event carries
#: ``enabled_pairs: null``.
WAVE_LOG_LANES = 9
WAVE_LOG_FIELDS = (
    "frontier_rows",   # live rows entering the wave
    "enabled_pairs",   # enabled-bitmap popcount (sparse single-chip)
    "candidates",      # surviving candidates (what the gen counter adds)
    "new_states",      # post-dedup winners appended to visited
    "unique_total",    # running unique count AFTER the wave
    "depth",           # depth entering the wave
    "f_class",         # frontier ladder class dispatched
    "v_class",         # visited ladder class dispatched
)
#: OPTIONAL trailing lanes past the required WAVE_LOG_FIELDS — lanes
#: an engine writes only when the matching feature is on (writers
#: that stack the 8 required lanes leave the tail zero via the
#: dynamic_update_slice into the [wps, WAVE_LOG_LANES] log, and rows
#: shorter than the lane count simply omit the field from the wave
#: event). ``canonical_hits``: candidates this wave whose canonical
#: form differed from the raw successor (device symmetry reduction,
#: ops/canonical.py) — the per-wave measure of how much symmetry is
#: folding. NOT in the trace-validation REQUIRED set: pre-symmetry
#: traces and engines without the pass stay valid.
WAVE_LOG_OPT_FIELDS = (
    "canonical_hits",  # candidates remapped by canonicalization
)

#: per-SHARD device wave-log lane layout (the round-11 mesh
#: observability layer): the sharded engines additionally keep a
#: ``uint32[waves_per_sync, SHARD_LOG_LANES]`` log PER SHARD that is
#: NOT psum-collapsed — it rides the chunk carry next to the global
#: log and is downloaded with the packed stats (one extra device
#: array in the same sync; no extra round trip). The host unpacks
#: rows into ``shard_wave`` events, one per (wave, shard).
#: ``enabled_pairs`` here is measured INSIDE the wave switch, so it is
#: real on the sharded engine too (the global log's lane 1 can't see
#: it and records null; :meth:`RunTracer.record_chunk` back-fills the
#: global ``wave`` event from the shard sum). On dense paths — which
#: have no (row, slot) pair extraction — the lane holds the candidate
#: count, mirroring the single-chip dense wave's convention.
SHARD_LOG_LANES = 9
SHARD_LOG_FIELDS = (
    "frontier_rows",   # live rows entering the wave on this shard
    "enabled_pairs",   # local enabled-pair popcount (candidates on dense)
    "candidates",      # surviving local candidates
    "routed_rows",     # rows this shard sent to OTHER shards (send side)
    "recv_rows",       # valid rows received after the all_to_all
    "dest_fill_peak",  # peak per-destination send-tile fill this wave
    "dest_cap",        # the lossless per-destination tile cap (Bd_c)
    "new_states",      # post-dedup winners this shard appended
    "visited_total",   # this shard's visited count AFTER the wave
)

#: compile-cache hit tiers a ``program_build`` event may carry
#: (the round-14 compile-cache ledger, checkers/tpu.py):
#: ``in_process`` — served from a same-process cache (the engine's
#: ``_programs`` cache, jit's executable cache, or the memory-analysis
#: result cache) with no XLA work; ``disk`` — the persistent XLA
#: compile cache loaded the executable (wall = retrieval);
#: ``cold`` — a real backend compile (wall = the multi-second cost
#: warm/cold A/Bs attribute); ``mixed`` — one seam covered both
#: (e.g. seed cold + chunk disk in one window); ``unknown`` — the
#: ``jax.monitoring`` hooks were unavailable, tier undecidable.
BUILD_TIERS = ("in_process", "disk", "cold", "mixed", "unknown")

#: what a ``verdict`` event settles as: ``discovery`` — the property
#: found its example/counterexample state; ``exhaustion`` — the search
#: completed without one (an always-property that HOLDS settles only
#: here, which is why time-to-verdict != time-to-first-hit).
VERDICT_KINDS = ("discovery", "exhaustion")

_ACTIVE: Optional["RunTracer"] = None
_ACTIVE_LOCK = threading.Lock()
#: thread-scoped tracer override (the resident service,
#: stateright_tpu/serve.py): each session runs its checker on its own
#: thread with its OWN tracer installed here, so concurrent sessions
#: record into disjoint event streams with zero cross-session bleed —
#: while single-query processes (CLI --trace, bench) keep using the
#: process-global activation, and threads with no override (the hybrid
#: racer's device worker) still see the global tracer.
_TLS = threading.local()


def current_tracer() -> Optional["RunTracer"]:
    """The active tracer for THIS thread — the thread-scoped override
    when one is installed (``RunTracer.activate_thread``), else the
    process-global one — or None (the common, zero-overhead case —
    every instrumentation site guards on this)."""
    tracer = getattr(_TLS, "tracer", None)
    if tracer is not None:
        return tracer
    return _ACTIVE


class _DiscardMeta(dict):
    """The no-op span's meta sink: span bodies may attach fields
    discovered mid-span (the Explorer request handlers' cache-hit
    state) — with no tracer active, writes are discarded outright so
    the shared instance never grows and the untraced hot loops (one
    ``with _NULL_SPAN`` per explored state in the host checkers)
    stay allocation-free."""

    __slots__ = ()

    def __setitem__(self, key, value):
        pass


_NULL_META = _DiscardMeta()


class _NullSpan:
    def __enter__(self):
        return _NULL_META

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def span(phase: str, **meta):
    """Module-level span hook: a real span on the active tracer
    (thread-scoped override first — see :func:`current_tracer`), a
    shared no-op context manager otherwise — call sites never need a
    tracer reference or an if."""
    tracer = current_tracer()
    if tracer is None:
        return _NULL_SPAN
    return tracer.span(phase, **meta)


def emit(ev: str, **fields) -> None:
    """Module-level instant-event hook (no-op without a tracer)."""
    tracer = current_tracer()
    if tracer is not None:
        tracer.event(ev, **fields)


class _PhaseAcc:
    """Reusable accumulating timer for phases that run once per STATE
    (property checks, symmetry canonicalization): entering/exiting
    adds to a per-run total instead of emitting an event per state —
    one ``phase_total`` event lands at run end. Create once, reuse in
    the hot loop."""

    __slots__ = ("tracer", "phase", "_t0")

    def __init__(self, tracer: "RunTracer", phase: str):
        self.tracer = tracer
        self.phase = phase
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.tracer._accumulate(self.phase, time.monotonic() - self._t0)
        return False


class RunTracer:
    """Collects one process's trace events; see the module docstring.

    ``level`` is ``"default"`` (no extra device syncs: exact per-wave
    COUNTS from the chunk wave log, per-chunk wall split, per-wave
    times estimated by even division and flagged ``t_est``) or
    ``"deep"`` (engines force waves_per_sync=1 and block on the carry:
    real per-wave walls and a device/fetch split, at per-wave sync
    cost)."""

    def __init__(self, level: str = "default"):
        if level not in ("default", "deep"):
            raise ValueError(f"unknown trace level {level!r}")
        self.level = level
        self.events: list[dict] = []
        self._t_base = time.monotonic()
        self._lock = threading.Lock()
        self._run_idx = -1
        self._run_open = False
        self._phase_totals: dict[str, list] = {}

    # -- activation ------------------------------------------------------

    @contextmanager
    def activate(self):
        """Install as the process-active tracer for the block."""
        global _ACTIVE
        with _ACTIVE_LOCK:
            if _ACTIVE is not None and _ACTIVE is not self:
                raise RuntimeError("another RunTracer is already active")
            _ACTIVE = self
        try:
            yield self
        finally:
            with _ACTIVE_LOCK:
                _ACTIVE = None

    @contextmanager
    def activate_thread(self):
        """Install as THIS THREAD's tracer for the block (the resident
        service's per-session scope, stateright_tpu/serve.py): every
        instrumentation site reached from this thread — engine chunk
        loops, checkpoint/restore events, Explorer request spans —
        records here instead of the process-global tracer, so
        concurrent sessions trace into disjoint streams. Nests: the
        previous thread-scoped tracer (if any) is restored on exit.
        Threads the session spawns itself (the hybrid racer's worker)
        do NOT inherit the override — they fall back to the global
        tracer, exactly the pre-existing contract."""
        prev = getattr(_TLS, "tracer", None)
        _TLS.tracer = self
        try:
            yield self
        finally:
            _TLS.tracer = prev

    # -- event plumbing --------------------------------------------------

    def _now(self) -> float:
        return time.monotonic() - self._t_base

    def _append(self, ev: dict) -> None:
        with self._lock:
            self.events.append(ev)

    def event(self, ev: str, **fields) -> None:
        """Instant event (auto-budget retries, level overrides, ...)."""
        self._append(
            dict(ev=ev, run=self._run_idx, t=round(self._now(), 6),
                 **fields)
        )

    # -- runs ------------------------------------------------------------

    def begin_run(self, lane: dict | None = None) -> int:
        """Open a run (one checker execution). Embeds provenance —
        the satellite contract: every TRACE artifact names the
        toolchain/device/SHA/lane it measured."""
        from .artifacts import provenance

        with self._lock:
            self._run_idx += 1
            self._run_open = True
            self._phase_totals = {}
            self.events.append(
                dict(
                    ev="run_begin",
                    run=self._run_idx,
                    t=round(self._now(), 6),
                    schema=SCHEMA_VERSION,
                    level=self.level,
                    provenance=provenance(),
                    lane=lane or {},
                )
            )
            return self._run_idx

    def end_run(self, *, error: str | None = None, **stats) -> None:
        if not self._run_open:
            return
        for phase, (dur, count) in sorted(self._phase_totals.items()):
            self._append(
                dict(ev="phase_total", run=self._run_idx, phase=phase,
                     dur=round(dur, 6), count=count)
            )
        prof = self._derive_latency_profile(stats.get("duration_sec"))
        if prof is not None:
            self._append(
                dict(ev="latency_profile", run=self._run_idx,
                     t=round(self._now(), 6), **prof)
            )
        self.event("run_end", error=error,
                   **{k: v for k, v in stats.items()})
        self._run_open = False

    def _derive_latency_profile(self, run_wall) -> Optional[dict]:
        """The run-end wall-clock attribution (the round-14 latency
        layer), derived here — the one place every engine passes
        through — from the run's own chunk / span / ``program_build``
        events, so any engine that records chunks gets the profile
        with zero engine-side accumulation. None for runs without
        chunk events (host checkers: their wall lives in spans and
        phase totals already).

        The lanes are ATTRIBUTIONS over one run wall, not a disjoint
        partition: a cold chunk compile is counted in the compile
        block AND physically sits inside chunk 0's ``dispatch_sec``
        (``dispatch_net_sec`` is dispatch with the ledger-attributed
        compile walls subtracted — the lane trace_diff compares, so a
        forced cold compile flags as compile, not as dispatch).

        The profile covers the WHOLE run — including auto-budget
        retry attempts, whose recompiles and re-explored chunks are
        genuinely where the run's wall went (``attempts`` counts
        them, from chunks restarting at wave 0). The untraced
        ``checker.latency_accounting()`` deliberately differs: it
        resets per attempt and reports the FINAL one (the bench
        lane's converged-budget number)."""
        with self._lock:
            evs = [e for e in self.events
                   if e.get("run") == self._run_idx]
        chunks = [e for e in evs if e["ev"] == "chunk"]
        if not chunks:
            return None
        begin = next((e for e in evs if e["ev"] == "run_begin"), None)
        t_run0 = (begin or {}).get("t", 0.0)
        disp = sum(c["dispatch_sec"] for c in chunks)
        fetch = sum(c["fetch_sec"] for c in chunks)
        dev = sum(c.get("device_sec") or 0.0 for c in chunks)
        chunk_wall = sum(c["t1"] - c["t0"] for c in chunks)
        waves = sum(c.get("waves") or 0 for c in chunks)
        if run_wall is None:
            run_wall = max(
                max((c["t1"] for c in chunks)) - t_run0, 0.0
            )
        builds = [e for e in evs if e["ev"] == "program_build"]
        tiers: dict[str, int] = {}
        for b in builds:
            tiers[b["tier"]] = tiers.get(b["tier"], 0) + 1
        cold = sum(b.get("cold_sec") or 0.0 for b in builds)
        build_wall = sum(b.get("wall_sec") or 0.0 for b in builds)
        chunk_compile = sum(
            b.get("wall_sec") or 0.0 for b in builds
            if b.get("program") == "chunk"
        )
        compile_span = sum(
            s["dur"] for s in evs
            if s["ev"] == "span" and s["phase"] == "compile"
        )
        restores = [e for e in evs if e["ev"] == "restore"]
        return dict(
            chunks=len(chunks),
            waves=waves,
            attempts=sum(
                1 for c in chunks if c.get("wave0") == 0
            ) or 1,
            # set on a run restored from a snapshot: its wave stream
            # (and every wall below) covers the search FROM this wave
            # — time_to_first_wave is time to the first RESUMED
            # wave's visibility, not the killed process's first wave
            # (tools/latency_report.py prints it; the resumed-trace
            # report tests pin that nothing here misattributes)
            resumed_from_wave=(
                min(int(r.get("wave") or 0) for r in restores)
                if restores else None
            ),
            run_wall_sec=round(run_wall, 6),
            # when the FIRST wave's results became host-visible: the
            # end of chunk 0's blocking readback, relative to
            # run_begin (covers compile + seed upload + first chunk)
            time_to_first_wave_sec=round(chunks[0]["t1"] - t_run0, 6),
            dispatch_sec=round(disp, 6),
            dispatch_net_sec=round(max(disp - chunk_compile, 0.0), 6),
            # the sync floor: host wall blocked at the per-chunk
            # stats readback (at level="default" this includes the
            # device wait hidden behind the sync — the honest number
            # for "what the host paid at the sync seam")
            fetch_sec=round(fetch, 6),
            fetch_min_sec=round(
                min(c["fetch_sec"] for c in chunks), 6
            ),
            device_sec=(round(dev, 6) if dev else None),
            chunk_wall_sec=round(chunk_wall, 6),
            # host wall OUTSIDE the chunk brackets: per-chunk host
            # bookkeeping, reporter callbacks, compile/seed spans
            interchunk_sec=round(max(run_wall - chunk_wall, 0.0), 6),
            sync_share=(round(fetch / run_wall, 4)
                        if run_wall else None),
            dispatch_share=(round(disp / run_wall, 4)
                            if run_wall else None),
            overlap_share=(round(dev / chunk_wall, 4)
                           if dev and chunk_wall else None),
            compile=dict(
                span_sec=round(compile_span, 6),
                build_wall_sec=round(build_wall, 6),
                cold_sec=round(cold, 6),
                builds=tiers,
                share=(round((compile_span + build_wall) / run_wall, 4)
                       if run_wall else None),
            ),
        )

    # -- spans / accumulators -------------------------------------------

    @contextmanager
    def span(self, phase: str, **meta):
        """Yields the span's meta dict: fields added to it inside the
        block land on the emitted event (for meta only known mid-span,
        e.g. a request handler's cache-hit state)."""
        t0 = self._now()
        try:
            yield meta
        finally:
            t1 = self._now()
            self._append(
                dict(ev="span", run=self._run_idx, phase=phase,
                     t0=round(t0, 6), t1=round(t1, 6),
                     dur=round(t1 - t0, 6), **meta)
            )

    def phase_acc(self, phase: str) -> _PhaseAcc:
        return _PhaseAcc(self, phase)

    def _accumulate(self, phase: str, dur: float) -> None:
        tot = self._phase_totals.setdefault(phase, [0.0, 0])
        tot[0] += dur
        tot[1] += 1

    # -- engine chunk/wave ingestion -------------------------------------

    def record_chunk(
        self,
        *,
        chunk: int,
        wave0: int,
        t0: float,
        t1: float,
        dispatch_sec: float,
        fetch_sec: float,
        device_sec: float | None = None,
        n_waves: int | None = None,
        wave_rows=None,
        pairs_valid: bool = True,
        shard_rows=None,
        mem_bytes: int | None = None,
    ) -> None:
        """One chunk sync: the host wall split plus the downloaded
        device wave-log rows (``wave_rows``: int array
        [n_waves, WAVE_LOG_LANES]; None for engines without a wave
        log — the chunk event still lands). ``shard_rows`` is the
        per-shard mesh log (int array
        [n_shards, n_waves, SHARD_LOG_LANES]; None off the sharded
        engines) — it lands as one ``shard_wave`` event per
        (wave, shard), and when the GLOBAL log can't see the
        enabled-pair popcount (``pairs_valid=False``) the wave event's
        ``enabled_pairs`` is back-filled from the shard sum, closing
        the sharded ``enabled_pairs=null`` hole. ``t0``/``t1`` are
        absolute ``time.monotonic()`` stamps bracketing
        dispatch→fetch. ``mem_bytes`` is the device bytes-in-use the
        engine polled at this sync (memplan.device_bytes_in_use —
        OPTIONAL so pre-round-12 traces stay valid; None means not
        polled)."""
        rt0 = t0 - self._t_base
        rt1 = t1 - self._t_base
        if wave_rows is not None and n_waves is None:
            n_waves = len(wave_rows)
        self._append(
            dict(
                ev="chunk", run=self._run_idx, chunk=chunk,
                wave0=wave0, waves=n_waves,
                t0=round(rt0, 6), t1=round(rt1, 6),
                dispatch_sec=round(dispatch_sec, 6),
                device_sec=(None if device_sec is None
                            else round(device_sec, 6)),
                fetch_sec=round(fetch_sec, 6),
                **({"mem_bytes": int(mem_bytes)}
                   if mem_bytes is not None else {}),
            )
        )
        if wave_rows is None or n_waves is None or n_waves == 0:
            if shard_rows is not None and n_waves != 0:
                # loud, not silent: shard rows borrow their wave's
                # identity (and its Chrome interval) — an engine that
                # logs per-shard without a global log is a contract
                # violation, not an empty trace
                raise ValueError(
                    "record_chunk: shard_rows without wave_rows — "
                    "the per-shard mesh log requires the global wave "
                    "log (shard_wave events hang off wave events)"
                )
            return
        # Default level: the chunk ran async, so per-wave walls don't
        # exist — spread the chunk interval evenly and flag the
        # estimate. Deep level (1 wave/chunk): the division is exact.
        per = (rt1 - rt0) / n_waves
        est = not (self.level == "deep" and n_waves == 1)
        for i in range(n_waves):
            row = [int(x) for x in wave_rows[i]]
            fields = dict(zip(WAVE_LOG_FIELDS, row))
            for j, name in enumerate(WAVE_LOG_OPT_FIELDS):
                k = len(WAVE_LOG_FIELDS) + j
                if k < len(row):
                    fields[name] = row[k]
            if not pairs_valid:
                if shard_rows is not None:
                    # lane 1 of SHARD_LOG_FIELDS, summed over shards
                    fields["enabled_pairs"] = int(
                        sum(int(sr[i][1]) for sr in shard_rows)
                    )
                else:
                    fields["enabled_pairs"] = None
            self._append(
                dict(
                    ev="wave", run=self._run_idx, wave=wave0 + i,
                    chunk=chunk,
                    t0=round(rt0 + i * per, 6),
                    t1=round(rt0 + (i + 1) * per, 6),
                    t_est=est,
                    **fields,
                )
            )
            if shard_rows is not None:
                for s, srows in enumerate(shard_rows):
                    self._append(
                        dict(
                            ev="shard_wave", run=self._run_idx,
                            wave=wave0 + i, chunk=chunk, shard=s,
                            **dict(zip(SHARD_LOG_FIELDS,
                                       [int(x) for x in srows[i]])),
                        )
                    )

    # -- exporters -------------------------------------------------------

    def write_jsonl(self, path: str) -> str:
        with open(path, "w") as fh:
            with self._lock:
                for ev in self.events:
                    fh.write(json.dumps(ev, sort_keys=True) + "\n")
        return path

    def write_chrome_trace(self, path: str) -> str:
        """Chrome-trace / Perfetto JSON: host phases, device chunks,
        and waves on three named tracks — plus one track PER SHARD
        when the trace carries ``shard_wave`` events (tid 3+shard, so
        a mesh run's per-shard load renders side by side) — and
        counter tracks for the frontier/new-state curves
        (``chrome://tracing`` or ui.perfetto.dev)."""
        with self._lock:
            events = list(self.events)
        out: list[dict] = []
        for pid, name in ((0, "stateright_tpu"),):
            out.append(dict(ph="M", pid=pid, name="process_name",
                            args=dict(name=name)))
        tracks = [(0, "host phases"), (1, "device chunks"),
                  (2, "waves")]
        shards = sorted({ev["shard"] for ev in events
                         if ev.get("ev") == "shard_wave"})
        tracks += [(3 + s, f"shard {s}") for s in shards]
        for tid, name in tracks:
            out.append(dict(ph="M", pid=0, tid=tid, name="thread_name",
                            args=dict(name=name)))
        # shard_wave events carry no walls of their own: they borrow
        # their wave's interval, so index those intervals first.
        wave_span = {
            (ev["run"], ev["wave"]): (ev["t0"], ev["t1"])
            for ev in events if ev.get("ev") == "wave"
        }

        def us(t):
            return round(t * 1e6, 1)

        for ev in events:
            kind = ev.get("ev")
            if kind == "shard_wave":
                t0, t1 = wave_span.get(
                    (ev["run"], ev["wave"]), (0.0, 0.0)
                )
                out.append(
                    dict(ph="X", pid=0, tid=3 + ev["shard"],
                         name=f"wave {ev['wave']}",
                         ts=us(t0), dur=us(t1 - t0),
                         args={k: ev[k] for k in SHARD_LOG_FIELDS})
                )
            elif kind == "span":
                out.append(
                    dict(ph="X", pid=0, tid=0, name=ev["phase"],
                         ts=us(ev["t0"]), dur=us(ev["dur"]),
                         args={k: v for k, v in ev.items()
                               if k not in ("ev", "t0", "t1", "dur")})
                )
            elif kind == "chunk":
                out.append(
                    dict(ph="X", pid=0, tid=1,
                         name=f"chunk {ev['chunk']}",
                         ts=us(ev["t0"]),
                         dur=us(ev["t1"] - ev["t0"]),
                         args={k: ev[k] for k in
                               ("run", "waves", "dispatch_sec",
                                "device_sec", "fetch_sec")})
                )
                if ev.get("mem_bytes") is not None:
                    # memory watermark as a counter track: the
                    # bytes-in-use curve next to the frontier curve
                    out.append(
                        dict(ph="C", pid=0, name="mem_bytes",
                             ts=us(ev["t1"]),
                             args=dict(bytes=ev["mem_bytes"]))
                    )
                # sync-floor counter track (round 14): the per-chunk
                # host-blocked wall next to the frontier curve, so a
                # sync-floor regression is visible as a raised floor
                out.append(
                    dict(ph="C", pid=0, name="host_blocked_ms",
                         ts=us(ev["t1"]),
                         args=dict(ms=round(ev["fetch_sec"] * 1e3, 3)))
                )
            elif kind == "wave":
                args = {k: ev[k] for k in WAVE_LOG_FIELDS}
                args["t_est"] = ev["t_est"]
                out.append(
                    dict(ph="X", pid=0, tid=2,
                         name=f"wave {ev['wave']}",
                         ts=us(ev["t0"]),
                         dur=us(ev["t1"] - ev["t0"]), args=args)
                )
                out.append(
                    dict(ph="C", pid=0, name="frontier_rows",
                         ts=us(ev["t0"]),
                         args=dict(rows=ev["frontier_rows"]))
                )
                out.append(
                    dict(ph="C", pid=0, name="new_states",
                         ts=us(ev["t0"]),
                         args=dict(new=ev["new_states"]))
                )
            elif kind == "verdict":
                # verdicts as global instants on the host track: the
                # per-property settle moments read directly off the
                # timeline (the time-to-verdict markers)
                out.append(
                    dict(ph="i", pid=0, tid=0, s="g",
                         name=f"verdict {ev['property']}",
                         ts=us(ev.get("t", 0.0)),
                         args={k: v for k, v in ev.items()
                               if k not in ("ev", "t")})
                )
            elif kind in ("run_begin", "run_end", "phase_total"):
                out.append(
                    dict(ph="i", pid=0, tid=0, s="g", name=kind,
                         ts=us(ev.get("t", ev.get("dur", 0.0))
                               if kind != "phase_total"
                               else events[0].get("t", 0.0)),
                         args={k: v for k, v in ev.items()
                               if k != "ev"})
                )
            else:  # instant engine events (auto_budget_retry, ...)
                out.append(
                    dict(ph="i", pid=0, tid=1, s="t", name=kind,
                         ts=us(ev.get("t", 0.0)),
                         args={k: v for k, v in ev.items()
                               if k not in ("ev", "t")})
                )
        with open(path, "w") as fh:
            json.dump(dict(traceEvents=out, displayTimeUnit="ms"), fh)
        return path


def write_artifacts(tracer: RunTracer, root: str | None = None,
                    round: int | None = None) -> tuple[str, str]:
    """Write the auto-numbered artifact PAIR (JSONL + Chrome trace)
    into one round slot beside the BENCH/LINT artifacts."""
    from .artifacts import artifact_path, next_round, repo_root

    root = repo_root() if root is None else root
    if round is None:
        round = next_round(root)
    jsonl = tracer.write_jsonl(
        artifact_path("TRACE", "jsonl", root=root, round=round)
    )
    chrome = tracer.write_chrome_trace(
        artifact_path("TRACE", "trace.json", root=root, round=round)
    )
    return jsonl, chrome


# -- trace loading / validation / diff -----------------------------------
#
# The logic behind tools/trace_diff.py lives here so tests import it the
# way the lint tests import stateright_tpu.analysis.

_REQUIRED = {
    "run_begin": ("run", "schema", "level", "provenance", "lane"),
    "run_end": ("run", "t"),
    "span": ("run", "phase", "t0", "t1", "dur"),
    "phase_total": ("run", "phase", "dur", "count"),
    "chunk": ("run", "chunk", "wave0", "t0", "t1", "dispatch_sec",
              "fetch_sec"),
    "wave": ("run", "wave", "chunk", "t0", "t1", "t_est")
    + WAVE_LOG_FIELDS,
    "shard_wave": ("run", "wave", "chunk", "shard")
    + SHARD_LOG_FIELDS,
    # The memory observability layer (round 12, memplan.py). ``chunk``
    # events gained an OPTIONAL ``mem_bytes`` lane (not listed above:
    # pre-round-12 traces must stay valid); these two are whole new
    # event types, so their contracts are required outright.
    "memory_plan": ("run", "engine", "resident", "resident_bytes",
                    "classes", "compiled", "total_bytes"),
    "memory_watermark": ("run", "source", "device_peak_bytes",
                         "headroom", "projection"),
    # The latency observability layer (round 14). ``program_build`` —
    # one compile-cache ledger row per build-or-fetch (checkers/
    # tpu.py); ``verdict`` — one per property settle (device chunk
    # loop, host _discover, and the run-end exhaustion sweep);
    # ``latency_profile`` — the run-end wall attribution the tracer
    # derives itself (RunTracer._derive_latency_profile). All three
    # are whole new event types, so their contracts are required
    # outright; pre-round-14 traces simply don't carry them.
    "program_build": ("run", "program", "tier", "wall_sec"),
    "verdict": ("run", "property", "expectation", "kind", "t"),
    "latency_profile": ("run", "chunks", "waves", "run_wall_sec",
                        "time_to_first_wave_sec", "dispatch_sec",
                        "fetch_sec", "sync_share", "compile"),
    # The durability layer (checkpoint/resume, stateright_tpu/
    # checkpoint.py + faultinject.py): ``checkpoint`` — one atomic
    # snapshot written at the per-chunk sync; ``restore`` — a run
    # began from a snapshot instead of the seed (its wave stream
    # starts at ``wave``, which the resume-aware trace_diff alignment
    # reads); ``fault_injected`` — a deterministic harness fault
    # fired; ``fault_recovery`` — the supervisor retried from a
    # snapshot after a supervised failure.
    "checkpoint": ("run", "path", "chunk", "wave", "depth",
                   "snapshot_bytes"),
    "restore": ("run", "wave", "depth", "from_shards", "to_shards"),
    "fault_injected": ("run", "site", "chunk", "action"),
    "fault_recovery": ("run", "attempt", "error"),
    # The tiered visited set (stateright_tpu/tier.py): one event per
    # hot->cold spill — rows/bytes moved this spill, the cold tier's
    # running totals, the hot rows before the reset, and the worker-
    # side ingest wall (overlapped with the next dispatch). Counts
    # and totals are EXACT exploration facts (trace_diff compares
    # them exactly between two tiered runs); walls are timing lanes.
    "tier_spill": ("run", "rows", "hot_rows_before", "cold_rows_total",
                   "cold_bytes_total", "runs", "spill_index"),
    # The degrade-and-continue layer (checkpoint.FailurePolicy + the
    # hung-dispatch watchdog + the health layer): ``shard_health`` —
    # one per straggler verdict (telemetry.detect_stragglers over the
    # existing per-shard wave log); ``fault_degrade`` — the
    # supervisor dropped a persistently-faulting shard and re-sharded
    # the last snapshot onto the survivors (old -> new shard count,
    # re-routed row total); ``watchdog_timeout`` — a chunk
    # dispatch+sync exceeded its derived deadline (full latency
    # attribution rides the event).
    "shard_health": ("run", "shard", "wave", "kind", "factor"),
    "fault_degrade": ("run", "from_shards", "to_shards", "reason"),
    "watchdog_timeout": ("run", "chunk", "deadline_sec"),
    # The resident checking service (stateright_tpu/serve.py):
    # ``session_begin`` — a query was admitted (kind check/explorer,
    # the admission pricing, the wait from submit to admit);
    # ``session_end`` — the query settled (state, counts, the total
    # device-queue wait, warm-start flag, program-cache key);
    # ``program_evict`` — the compiled-program LRU dropped an entry
    # to stay under its byte budget (keyed like the ``_programs``/XLA
    # cache, priced by the memplan ledger). These land in the
    # service's MERGED trace export (one run index per session), which
    # tools/serve_report.py derives SERVE_r* artifacts from.
    "session_begin": ("run", "session", "kind", "t"),
    "session_end": ("run", "session", "state", "t"),
    "program_evict": ("run", "key", "bytes", "t"),
    # The wave batcher (stateright_tpu/batch.py): ``batch`` — this
    # session's run was a lane of a fused multi-session dispatch
    # (group id, fused size, this session's lane index); its chunk
    # walls carry the 1/N_active amortized shares and its
    # program_build rows are re-emitted 1/N-amortized with a
    # ``batch`` marker. ``snapshot_evict`` — the retained-warm-start
    # snapshot spool dropped an entry to stay under its byte budget
    # (the snapshot analogue of ``program_evict``; the next re-check
    # of that fingerprint runs cold, counts unaffected).
    "batch": ("run", "group", "size", "index", "t"),
    "snapshot_evict": ("run", "key", "bytes", "t"),
    # The live metrics plane (stateright_tpu/metrics.py): one
    # cumulative registry snapshot per ``--metrics-interval`` tick —
    # the headless JSONL export (Rollup). ``families`` is the full
    # JSON-able family dump (counters/gauges/histogram buckets); the
    # file loads and validates exactly like a TRACE artifact, which is
    # what lets tools/slo_report.py gate on it.
    "metrics_rollup": ("t", "families"),
}


def load_trace(path: str) -> list[dict]:
    """Parse a TRACE_r*.jsonl file; raises ValueError on malformed
    lines."""
    events = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError as exc:
                raise ValueError(
                    f"{path}:{lineno}: not JSON: {exc}"
                ) from exc
            if not isinstance(ev, dict) or "ev" not in ev:
                raise ValueError(
                    f"{path}:{lineno}: event without an 'ev' field"
                )
            events.append(ev)
    return events


def validate_events(events: list[dict]) -> None:
    """Schema check: every known event type carries its required
    fields, runs open with run_begin, and wave counters are internally
    consistent (unique_total is the running post-dedup sum). A wave
    index that does NOT advance marks an auto-budget retry restart
    (the resized attempt re-explores from wave 0 inside the same run)
    — the running-sum check resets there instead of rejecting the
    legitimate artifact. Raises ValueError on the first violation."""
    open_runs: set[int] = set()
    last_unique: dict[int, int] = {}
    last_wave: dict[int, int] = {}
    # per (run, shard): the same running-sum check over the per-shard
    # visited counter (visited_total is u_loc AFTER the wave)
    last_visited: dict[tuple, int] = {}
    last_shard_wave: dict[tuple, int] = {}
    for i, ev in enumerate(events):
        kind = ev["ev"]
        for field in _REQUIRED.get(kind, ()):
            if field not in ev:
                raise ValueError(
                    f"event {i} ({kind}): missing field {field!r}"
                )
        if kind == "run_begin":
            if ev["schema"] > SCHEMA_VERSION:
                raise ValueError(
                    f"event {i}: schema {ev['schema']} newer than "
                    f"reader ({SCHEMA_VERSION})"
                )
            open_runs.add(ev["run"])
        elif kind == "restore":
            # an in-process supervised retry restored from a snapshot
            # mid-run: the running sums re-seed — the resumed segment
            # restarts behind the failed attempt's furthest wave, and
            # a DEGRADED restore additionally re-routes rows between
            # shards, so per-shard visited totals are legitimately
            # discontinuous across this point
            run = ev["run"]
            last_unique.pop(run, None)
            last_wave.pop(run, None)
            for key in [k for k in last_visited if k[0] == run]:
                last_visited.pop(key)
            for key in [k for k in last_shard_wave if k[0] == run]:
                last_shard_wave.pop(key)
        elif kind == "wave":
            run = ev["run"]
            if run not in open_runs:
                raise ValueError(
                    f"event {i}: wave outside an open run"
                )
            if run in last_wave and ev["wave"] <= last_wave[run]:
                last_unique.pop(run, None)  # retry restart
            prev = last_unique.get(run)
            if prev is not None and ev["unique_total"] != (
                prev + ev["new_states"]
            ):
                raise ValueError(
                    f"event {i}: wave {ev['wave']} unique_total "
                    f"{ev['unique_total']} != previous {prev} + "
                    f"new_states {ev['new_states']}"
                )
            last_unique[run] = ev["unique_total"]
            last_wave[run] = ev["wave"]
        elif kind == "shard_wave":
            run = ev["run"]
            if run not in open_runs:
                raise ValueError(
                    f"event {i}: shard_wave outside an open run"
                )
            key = (run, ev["shard"])
            if (key in last_shard_wave
                    and ev["wave"] <= last_shard_wave[key]):
                last_visited.pop(key, None)  # retry restart
            prev = last_visited.get(key)
            if prev is not None and ev["visited_total"] != (
                prev + ev["new_states"]
            ):
                raise ValueError(
                    f"event {i}: shard {ev['shard']} wave "
                    f"{ev['wave']} visited_total "
                    f"{ev['visited_total']} != previous {prev} + "
                    f"new_states {ev['new_states']}"
                )
            last_visited[key] = ev["visited_total"]
            last_shard_wave[key] = ev["wave"]
        elif kind == "memory_plan":
            # the ledger's own sum must hold: a plan whose total
            # disagrees with its rows is a hand-edited artifact
            tot = sum(int(e["bytes"]) for e in ev["resident"])
            if int(ev["resident_bytes"]) != tot:
                raise ValueError(
                    f"event {i}: memory_plan resident_bytes "
                    f"{ev['resident_bytes']} != sum of resident "
                    f"entry bytes {tot}"
                )
        elif kind == "program_build":
            if ev["tier"] not in BUILD_TIERS:
                raise ValueError(
                    f"event {i}: program_build tier {ev['tier']!r} "
                    f"not in {BUILD_TIERS}"
                )
        elif kind == "verdict":
            if ev["kind"] not in VERDICT_KINDS:
                raise ValueError(
                    f"event {i}: verdict kind {ev['kind']!r} "
                    f"not in {VERDICT_KINDS}"
                )


def _runs(events: list[dict]) -> list[int]:
    return sorted({ev["run"] for ev in events if ev["ev"] == "run_begin"})


def _run_view(events: list[dict], run: int) -> dict:
    view: dict = dict(run=run, begin=None, end=None, waves=[],
                      chunks=[], spans=[], phase_totals={},
                      shard_waves={}, memory_plan=None,
                      memory_watermark=None, latency_profile=None,
                      builds=[], verdicts=[], restores=[],
                      tier_spills=[], degrades=[], watchdogs=[],
                      health=[])
    seen_shard_pairs: set = set()
    for ev in events:
        if ev.get("run") != run:
            continue
        kind = ev["ev"]
        if kind == "run_begin":
            view["begin"] = ev
        elif kind == "run_end":
            view["end"] = ev
        elif kind in ("memory_plan", "memory_watermark",
                      "latency_profile"):
            view[kind] = ev  # one per run; last occurrence wins
        elif kind == "program_build":
            view["builds"].append(ev)
        elif kind == "verdict":
            view["verdicts"].append(ev)
        elif kind == "restore":
            view["restores"].append(ev)
        elif kind == "tier_spill":
            view["tier_spills"].append(ev)
        elif kind == "fault_degrade":
            view["degrades"].append(ev)
        elif kind == "watchdog_timeout":
            view["watchdogs"].append(ev)
        elif kind == "shard_health":
            view["health"].append(ev)
        elif kind == "wave":
            view["waves"].append(ev)
        elif kind == "shard_wave":
            # keyed (wave, shard), last occurrence wins — the same
            # last-attempt alignment the global wave dict gets from
            # its keyed overwrite. A supervised RETRY re-explores
            # waves it already logged: when a (wave, shard) pair
            # repeats, every stored wave >= it belongs to the dead
            # attempt and is purged, so a DEGRADED retry (fewer
            # shards) can't leave the old attempt's extra shard rows
            # mixed into the re-explored waves.
            key = (ev["wave"], ev["shard"])
            if key in seen_shard_pairs:
                for w in [w for w in view["shard_waves"]
                          if w >= ev["wave"]]:
                    del view["shard_waves"][w]
                seen_shard_pairs = {
                    p for p in seen_shard_pairs if p[0] < ev["wave"]
                }
            seen_shard_pairs.add(key)
            view["shard_waves"].setdefault(
                ev["wave"], {}
            )[ev["shard"]] = ev
        elif kind == "chunk":
            view["chunks"].append(ev)
        elif kind == "span":
            view["spans"].append(ev)
        elif kind == "phase_total":
            view["phase_totals"][ev["phase"]] = ev
    view["waves"].sort(key=lambda w: w["wave"])
    return view


def _phase_durations(view: dict) -> dict[str, float]:
    """Per-phase wall totals for one run: named spans, accumulated
    phase totals, the chunk-level dispatch/fetch split, and the wave
    wall sum."""
    out: dict[str, float] = {}
    for s in view["spans"]:
        out[s["phase"]] = out.get(s["phase"], 0.0) + s["dur"]
    for phase, ev in view["phase_totals"].items():
        out[phase] = out.get(phase, 0.0) + ev["dur"]
    disp = sum(c["dispatch_sec"] for c in view["chunks"])
    fetch = sum(c["fetch_sec"] for c in view["chunks"])
    dev = sum(c["device_sec"] or 0.0 for c in view["chunks"])
    if view["chunks"]:
        out["device_dispatch"] = disp
        out["host_fetch"] = fetch
        if dev:
            out["device_wait"] = dev
    if view["waves"]:
        out["waves_wall"] = sum(
            w["t1"] - w["t0"] for w in view["waves"]
        )
    end = view["end"]
    if end is not None and end.get("duration_sec") is not None:
        out["run_total"] = end["duration_sec"]
    return out


# -- mesh observability: derived balance / routing metrics ----------------


def _skew(xs: list) -> Optional[float]:
    """max/mean over shards (1.0 = perfectly balanced, n_shards =
    one shard carries everything); None for an all-zero wave."""
    tot = sum(xs)
    if tot == 0:
        return None
    return round(max(xs) * len(xs) / tot, 4)


#: a wave whose per-shard work median is below this many rows yields
#: no straggler verdicts: a 1-row seed wave on an 8-shard mesh puts
#: every loaded shard "factor x median" over an empty one, which is
#: startup shape, not shard health.
STRAGGLER_MIN_MEDIAN_ROWS = 16


def detect_stragglers(wave_rows, factor: float,
                      min_median_rows: int = STRAGGLER_MIN_MEDIAN_ROWS,
                      ) -> list[dict]:
    """The health layer's per-wave straggler verdict over ONE wave's
    per-shard log rows (``[n_shards, SHARD_LOG_LANES]`` — the
    existing mesh wave log, telemetry round 11): a shard whose work
    (its ``candidates`` lane, the wave's per-shard cost driver)
    exceeds ``factor`` x the shard MEDIAN is a straggler. On an SPMD
    mesh every shard leaves a wave together, so a persistent work
    imbalance is the host-visible shadow of a slow or failing chip —
    the engines emit one ``shard_health`` event per verdict and feed
    SUSTAINED stragglers to checkpoint.classify_failure as pre-fault
    evidence.

    Pure host math over the log rows (unit-tested in ``pytest -m
    fault``). Returns ``[{shard, value, median, ratio}, ...]``; empty
    when the mesh is a single shard (no median signal), the wave's
    median work is under ``min_median_rows`` (seed/drain waves), or
    nothing exceeds the factor."""
    import numpy as _np

    if factor is None or factor <= 1.0:
        raise ValueError(
            f"straggler factor must be > 1 (got {factor}): at 1.0 "
            "every shard above the median would flag"
        )
    rows = _np.asarray(wave_rows)
    if rows.ndim != 2 or rows.shape[0] < 2:
        return []
    work = rows[:, SHARD_LOG_FIELDS.index("candidates")].astype(
        _np.int64
    )
    median = float(_np.median(work))
    if median < min_median_rows:
        return []
    out = []
    for s in range(work.shape[0]):
        v = int(work[s])
        if v > factor * median:
            out.append(dict(
                shard=s, value=v, median=median,
                ratio=(v / median if median else float("inf")),
            ))
    return out


def shard_balance(events: list[dict], run: int | None = None,
                  ) -> Optional[dict]:
    """Derive the mesh balance/routing summary from one run's
    ``shard_wave`` events — the numbers that decide whether the
    (owner, fp)-sort shuffle scales (ROADMAP direction 1): per-wave
    frontier/candidate skew (max/mean), routed shuffle volume,
    dest-tile fill vs the lossless ``Bd`` cap, and the per-shard
    visited occupancy trajectory. Returns None when the run carries
    no shard events (an unsharded or untraced run).

    ``run`` defaults to the LAST run in the event stream (bench/dryrun
    trace warm-run-last). Worst-skew bookkeeping ignores waves whose
    total is below the shard count — a 1-row seed wave on an 8-shard
    mesh is "maximally imbalanced" by arithmetic, not by scheduling.

    Headroom warnings come from the shared formatter
    (stateright_tpu/occupancy.py): per-shard visited occupancy and
    dest-tile fill past ``HEADROOM_THRESHOLD``, plus a skew warning
    past 2x. The ``per_wave`` list carries the full trajectory for
    tools/shard_report.py."""
    from .occupancy import (
        HEADROOM_THRESHOLD,
        PROBE_PRESSURE_THRESHOLD,
        occupancy_warning,
    )

    runs = _runs(events)
    if not runs:
        return None
    view = _run_view(events, runs[-1] if run is None else run)
    sw = view["shard_waves"]
    if not sw:
        return None
    lane = (view["begin"] or {}).get("lane") or {}
    tile_lanes = lane.get("dest_tile_lanes")
    per_shard_capacity = lane.get("capacity")
    # per-entry byte costs from the lane config (the memory ledger's
    # numbers, round 12): headroom warnings price the fill in bytes
    visited_row_bytes = lane.get("visited_row_bytes")
    tile_row_bytes = int(tile_lanes) * 4 if tile_lanes else None
    # Visited-set semantics come from the lane config: the sort-merge
    # engines' sorted arrays work to exactly 100% (headroom watch),
    # the hash engine's open addressing degrades from ~70% (probe
    # pressure — its own threshold and failure mode).
    visited_exact = bool(lane.get("visited_exact", True))

    per_wave: list[dict] = []
    routed_total = recv_total = 0
    bound_rows_total = 0  # sum of S x dest_cap over waves (static cap)
    worst_frontier = worst_cand = None  # (skew, wave)
    worst_fill = None  # (util, fill, cap, wave)
    skew_wsum = skew_weight = 0.0  # size-weighted frontier skew
    final_visited: dict[int, int] = {}
    n_shards = 0
    for w in sorted(sw):
        rows = [sw[w][s] for s in sorted(sw[w])]
        n_shards = max(n_shards, len(rows))
        fr = [r["frontier_rows"] for r in rows]
        cand = [r["candidates"] for r in rows]
        new = [r["new_states"] for r in rows]
        routed = sum(r["routed_rows"] for r in rows)
        recv = sum(r["recv_rows"] for r in rows)
        fill = max(r["dest_fill_peak"] for r in rows)
        cap = max(r["dest_cap"] for r in rows)
        util = round(fill / cap, 4) if cap else None
        m = dict(
            wave=w,
            shards=len(rows),
            frontier_total=sum(fr),
            frontier_skew=_skew(fr),
            candidates_total=sum(cand),
            candidate_skew=_skew(cand),
            new_total=sum(new),
            routed_rows=routed,
            recv_rows=recv,
            dest_fill_peak=fill,
            dest_cap=cap,
            dest_util=util,
        )
        per_wave.append(m)
        routed_total += routed
        recv_total += recv
        bound_rows_total += len(rows) * cap
        if sum(fr) >= len(rows) and m["frontier_skew"] is not None:
            if worst_frontier is None or m["frontier_skew"] > \
                    worst_frontier[0]:
                worst_frontier = (m["frontier_skew"], w)
            skew_wsum += m["frontier_skew"] * sum(fr)
            skew_weight += sum(fr)
        if sum(cand) >= len(rows) and m["candidate_skew"] is not None:
            if worst_cand is None or m["candidate_skew"] > \
                    worst_cand[0]:
                worst_cand = (m["candidate_skew"], w)
        if util is not None and (worst_fill is None
                                 or util > worst_fill[0]):
            worst_fill = (util, fill, cap, w)
        for r in rows:
            final_visited[r["shard"]] = r["visited_total"]

    visited = [final_visited[s] for s in sorted(final_visited)]
    weighted = (
        round(skew_wsum / skew_weight, 4) if skew_weight else None
    )
    warnings: list[str] = []
    # the imbalance warning keys on the SIZE-WEIGHTED skew: the first
    # BFS waves of any run are a handful of rows and always look
    # maximally skewed, but they carry ~no work — a warning should
    # mean the big waves (where the wall lives) are imbalanced.
    if weighted is not None and weighted > 2.0:
        warnings.append(
            f"frontier imbalance: size-weighted skew {weighted:.2f}x "
            f"(worst wave {worst_frontier[1]}: "
            f"{worst_frontier[0]:.2f}x its fair share on one shard) — "
            "the (owner, fp) partition is not spreading this "
            "workload; sharding buys less than 1/S"
        )
    if worst_fill is not None:
        msg = occupancy_warning(
            worst_fill[0],
            kind=f"dest tile (wave {worst_fill[3]})",
            threshold=HEADROOM_THRESHOLD,
            used=worst_fill[1],
            capacity=worst_fill[2],
            bytes_per_row=tile_row_bytes,
            consequence=(
                "a destination run past the lossless Bd cap trips "
                "c_overflow — raise bucket_capacity before the next "
                "skewed wave does"
            ),
        )
        if msg:
            warnings.append(msg)
    occ_max = None
    if per_shard_capacity and visited:
        occ_max = round(max(visited) / per_shard_capacity, 4)
        if visited_exact:
            occ_threshold = HEADROOM_THRESHOLD
            occ_consequence = (
                "the sorted visited array overflows exactly at "
                "100% — raise the per-shard capacity"
            )
        else:
            occ_threshold = PROBE_PRESSURE_THRESHOLD
            occ_consequence = (
                "open addressing degrades before it fills — probe "
                "failures become likely past ~85%; raise the "
                "per-shard capacity"
            )
        for s in sorted(final_visited):
            msg = occupancy_warning(
                final_visited[s] / per_shard_capacity,
                kind=f"shard {s} visited array",
                threshold=occ_threshold,
                used=final_visited[s],
                capacity=per_shard_capacity,
                bytes_per_row=visited_row_bytes,
                consequence=occ_consequence,
            )
            if msg:
                warnings.append(msg)

    return dict(
        run=view["run"],
        n_shards=n_shards,
        waves=len(per_wave),
        frontier_skew_worst=(
            dict(skew=worst_frontier[0], wave=worst_frontier[1])
            if worst_frontier else None
        ),
        frontier_skew_weighted=weighted,
        candidate_skew_worst=(
            dict(skew=worst_cand[0], wave=worst_cand[1])
            if worst_cand else None
        ),
        routed_rows_total=routed_total,
        recv_rows_total=recv_total,
        routed_bytes_total=(
            routed_total * int(tile_lanes) * 4
            if tile_lanes else None
        ),
        # Static-vs-runtime comms reconciliation (round 13, PERF.md
        # §comms-lint): the static side of the routed-byte accounting.
        # row_bytes is the per-row price comms-lint derives from the
        # compiled all_to_all operand (dest_tile_lanes x 4 — the COMM
        # artifact's all_to_all_row_bytes; tests pin the two equal),
        # so measured routed bytes ARE routed_rows x row_bytes, and
        # bytes_bound_total is the static per-wave ceiling (S x
        # dest_cap rows every wave — what the all_to_all physically
        # exchanges regardless of fill). bound_util says how much of
        # the static exchange carried real rows: the estimate vs
        # measured bound the reconciliation states.
        comms_static=(
            dict(
                row_bytes=tile_row_bytes,
                bound_rows_total=bound_rows_total,
                bytes_bound_total=bound_rows_total * tile_row_bytes,
                measured_routed_bytes=(
                    routed_total * tile_row_bytes
                ),
                bound_util=(
                    round(routed_total / bound_rows_total, 4)
                    if bound_rows_total else None
                ),
            )
            if tile_row_bytes else None
        ),
        dest_fill_worst=(
            dict(util=worst_fill[0], fill=worst_fill[1],
                 cap=worst_fill[2], wave=worst_fill[3])
            if worst_fill else None
        ),
        visited_per_shard=visited,
        visited_skew=_skew(visited) if visited else None,
        shard_capacity=per_shard_capacity,
        occupancy_max=occ_max,
        warnings=warnings,
        per_wave=per_wave,
    )


# -- memory observability: the derived plan/watermark summary -------------


def _strip_ev(ev: Optional[dict]) -> Optional[dict]:
    if ev is None:
        return None
    return {k: v for k, v in ev.items()
            if k not in ("ev", "run", "t")}


def memory_summary(events: list[dict], run: int | None = None,
                   ) -> Optional[dict]:
    """Derive one run's memory view from its ``memory_plan`` /
    ``memory_watermark`` events and the per-chunk ``mem_bytes`` lane —
    the data behind tools/mem_report.py and the ``MEM_r*.json``
    artifacts (memplan.write_memory_artifact). Returns None when the
    run carries no memory events (a pre-round-12 trace, or an engine
    without the ledger) — mem_report exits 2 on that.

    ``run`` defaults to the LAST run in the event stream (bench/CLI
    trace warm-run-last, so the default view is the warm one)."""
    runs = _runs(events)
    if not runs:
        return None
    view = _run_view(events, runs[-1] if run is None else run)
    plan = view["memory_plan"]
    wm = view["memory_watermark"]
    chunk_mem = [
        dict(chunk=c["chunk"], bytes=c["mem_bytes"])
        for c in view["chunks"] if c.get("mem_bytes") is not None
    ]
    if plan is None and wm is None and not chunk_mem:
        return None
    lane = (view["begin"] or {}).get("lane") or {}
    modes = [
        _strip_ev(ev) for ev in events
        if ev.get("ev") == "engine_mode" and ev.get("run") == view["run"]
    ]
    return dict(
        run=view["run"],
        engine=(plan or {}).get("engine") or lane.get("engine"),
        lane={k: lane[k] for k in
              ("engine", "model", "encoding", "capacity",
               "frontier_capacity", "cand_capacity", "n_shards",
               "track_paths", "merge_impl", "tier_hot_rows")
              if k in lane},
        plan=_strip_ev(plan),
        watermark=_strip_ev(wm),
        chunk_mem=chunk_mem,
        engine_modes=modes,
        tier_spills=[_strip_ev(ev) for ev in view["tier_spills"]],
    )


# -- latency observability: the derived ledger/floor/verdict summary -----


def latency_summary(events: list[dict], run: int | None = None,
                    ) -> Optional[dict]:
    """Derive one run's latency view from its ``latency_profile`` /
    ``program_build`` / ``verdict`` events and the host-phase spans —
    the data behind tools/latency_report.py and the ``LAT_r*.json``
    artifacts. Returns None when the run carries no latency events (a
    pre-round-14 trace) — latency_report exits 2 on that.

    ``run`` defaults to the LAST run in the event stream (bench/CLI
    trace warm-run-last, so the default view is the warm one).
    Verdict walls are re-based to the run's own start (``t_since_run``)
    so time-to-verdict reads per run, not per process."""
    runs = _runs(events)
    if not runs:
        return None
    view = _run_view(events, runs[-1] if run is None else run)
    prof = view["latency_profile"]
    builds = view["builds"]
    verdicts = view["verdicts"]
    if prof is None and not builds and not verdicts:
        return None
    lane = (view["begin"] or {}).get("lane") or {}
    t0 = (view["begin"] or {}).get("t", 0.0)
    vrows = [
        dict(
            {k: v for k, v in ev.items()
             if k not in ("ev", "run", "t")},
            t_since_run=round(ev["t"] - t0, 6),
        )
        for ev in verdicts
    ]
    phases = {
        k: round(v, 6) for k, v in _phase_durations(view).items()
    }
    return dict(
        run=view["run"],
        engine=lane.get("engine"),
        lane={k: lane[k] for k in
              ("engine", "model", "encoding", "capacity",
               "frontier_capacity", "cand_capacity", "n_shards",
               "waves_per_sync", "track_paths", "merge_impl")
              if k in lane},
        profile=_strip_ev(prof),
        builds=[_strip_ev(b) for b in builds],
        verdicts=vrows,
        phases=phases,
        # the degrade-and-continue layer's wall-clock events ride the
        # latency view: watchdog breaches carry the full attribution,
        # degrades mark where the run changed shape mid-stream
        watchdogs=[_strip_ev(w) for w in view["watchdogs"]],
        degrades=[_strip_ev(d) for d in view["degrades"]],
        error=(view["end"] or {}).get("error"),
    )


def write_latency_artifact(summary: dict, root: str | None = None,
                           ) -> str:
    """Write one auto-numbered ``LAT_r*.json`` artifact (the latency
    summary of one traced run, tools/latency_report.py's ``--json``
    output). LAT numbers in its OWN round sequence (``LAT_r01`` first)
    like MEM/COMM: a LAT artifact is *derived from* a TRACE and names
    it in its ``trace`` field, so the cross-reference — not a shared
    counter — pairs it with a perf round."""
    from .artifacts import artifact_path, next_round, provenance, \
        repo_root

    root = repo_root() if root is None else root
    path = artifact_path(
        "LAT", "json", root=root,
        round=next_round(root, stems=("LAT",)),
    )
    doc = dict(summary)
    doc.setdefault("provenance", provenance())
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


#: wave counters trace_diff requires to MATCH between the two sides —
#: two traces of the same workload must explore the same space.
DIFF_COUNTERS = ("frontier_rows", "candidates", "new_states",
                 "unique_total")

#: per-shard counters trace_diff compares — as a MULTISET of per-shard
#: rows per wave, not positionally: the (owner, fp) partition is
#: deterministic up to shard numbering, so a mesh relabeling (device
#: enumeration order, a different host) permutes the rows without
#: changing the set; positional comparison would false-positive.
#: ``dest_cap`` is excluded: it is CONFIG (the class's Bd tile size),
#: not exploration — a bucket_capacity-only A/B (the tuning diff this
#: tool exists for) must compare on timing, not fail as divergence.
SHARD_DIFF_COUNTERS = tuple(
    f for f in SHARD_LOG_FIELDS if f != "dest_cap"
)


def _resume_wave(view: dict) -> Optional[int]:
    """The wave a resumed run restarted from (its ``restore`` event),
    or None for an uninterrupted run. Waves BELOW this are expected
    absent from the resumed side — they ran in the killed process,
    whose trace died with it — so the diff alignment compares the
    overlap only; every counter in the overlap (including the running
    ``unique_total``, which carries the pre-kill history) must still
    match the baseline exactly."""
    rs = view.get("restores") or []
    if not rs:
        return None
    return min(int(r.get("wave") or 0) for r in rs)


def _missing_ok(i: int, in_a: bool, in_b: bool,
                rw_a: Optional[int], rw_b: Optional[int]) -> bool:
    """Whether wave ``i`` being on one side only is explained by the
    other side's resume point (pre-resume waves are expected absent)."""
    if not in_a and rw_a is not None and i < rw_a:
        return True
    if not in_b and rw_b is not None and i < rw_b:
        return True
    return False


def _reshard_points(view: dict) -> list[tuple]:
    """``[(wave, to_shards), ...]`` where this run legitimately
    changed shard count mid-stream: supervised elastic degrades
    (``fault_degrade`` events) and elastic re-shard resumes (a
    ``restore`` whose from/to shard counts differ)."""
    pts = []
    for ev in view.get("degrades") or []:
        pts.append((int(ev.get("wave") or 0), int(ev["to_shards"])))
    for ev in view.get("restores") or []:
        if ev.get("from_shards") != ev.get("to_shards"):
            pts.append((int(ev.get("wave") or 0),
                        int(ev["to_shards"])))
    return sorted(pts)


def _shard_divergences(va: dict, vb: dict) -> list[dict]:
    """Shard-aware wave alignment (the mesh observability layer): for
    every wave BOTH sides have per-shard rows for, the multisets of
    per-shard counter tuples must match — shard RENUMBERING is fine
    (the multiset is invariant), a different partition of the same
    global counts is not. A wave with shard rows on exactly one side
    also diverges (one run was sharded-traced, the other not — they
    are not comparable as a mesh A/B).

    DEGRADE-aware (the degrade-and-continue layer): a run that
    elastically degraded (``fault_degrade``) or resumed onto a
    different shard count (``restore``) legitimately changes its
    per-wave shard count at the re-shard wave. Shard lanes compare
    within each shard-COUNT segment — waves where the two sides'
    counts differ because one side re-sharded are skipped on the
    shard lanes (the GLOBAL counters stay fully enforced, which is
    exactly the degraded-run bit-exactness proof)."""
    from collections import Counter

    def expected_at(view, pts, wave, default):
        cur = default
        for w, s in pts:
            if wave >= w:
                cur = s
        return cur

    out: list[dict] = []
    sa, sb = va["shard_waves"], vb["shard_waves"]
    if not sa and not sb:
        return out
    rw_a, rw_b = _resume_wave(va), _resume_wave(vb)
    pts_a, pts_b = _reshard_points(va), _reshard_points(vb)
    resharded = bool(pts_a or pts_b)
    # each side's baseline shard count, from its own run_begin lane
    # (falls back to the observed row count on traces without one)
    base_a = ((va["begin"] or {}).get("lane") or {}).get("n_shards")
    base_b = ((vb["begin"] or {}).get("lane") or {}).get("n_shards")
    for i in sorted(set(sa) | set(sb)):
        if (i in sa) != (i in sb):
            if _missing_ok(i, i in sa, i in sb, rw_a, rw_b):
                continue  # pre-resume wave: expected absent
            out.append(
                dict(wave=i, field="shard_present",
                     a=i in sa, b=i in sb)
            )
            continue

        def rows(view_waves):
            return Counter(
                tuple(ev[f] for f in SHARD_DIFF_COUNTERS)
                for ev in view_waves[i].values()
            )

        if len(sa[i]) != len(sb[i]) and resharded:
            # different shard-COUNT segments are incomparable on the
            # shard lanes by design — but ONLY when each side's count
            # is exactly what its own degrade/re-shard history
            # predicts for this wave; a row count the history does
            # NOT explain (a genuinely lost shard row) still diverges
            ea = expected_at(va, pts_a, i,
                             base_a if base_a else len(sa[i]))
            eb = expected_at(vb, pts_b, i,
                             base_b if base_b else len(sb[i]))
            if ea != eb and len(sa[i]) == ea and len(sb[i]) == eb:
                continue  # global counters carry the proof here
        ca, cb = rows(sa), rows(sb)
        if len(sa[i]) != len(sb[i]):
            out.append(
                dict(wave=i, field="shard_count",
                     a=len(sa[i]), b=len(sb[i]))
            )
        if ca != cb:
            only_a = next(iter((ca - cb).elements()), None)
            only_b = next(iter((cb - ca).elements()), None)
            out.append(
                dict(
                    wave=i, field="shard_multiset",
                    a="/".join(map(str, only_a))
                    if only_a else None,
                    b="/".join(map(str, only_b))
                    if only_b else None,
                )
            )
    return out


#: measured-byte lanes smaller than this on the A side are ignored by
#: the memory regression check (the byte analog of ``min_sec``: a
#: few-KB toy trace's live-array noise is not a regression signal).
MEM_DIFF_MIN_BYTES = 1 << 20


def _memory_diff(va: dict, vb: dict, threshold: float) -> dict:
    """Memory-counter alignment between two runs (the round-12 layer):
    the PLAN is config — resident shapes/dtypes/bytes and the
    per-class staging ledger must match EXACTLY (a changed resident
    layout is a different engine, not a perf delta) — while MEASURED
    bytes (compiled temp bytes, the live watermark peak) are compared
    RELATIVE under ``threshold``, so jax-version allocator skew
    doesn't false-positive a counter gate. Byte lanes compare only
    when both sides measured them the same way (equal ``source`` /
    both reported) — and a side with NO memory events at all (a
    pre-round-12 baseline trace) is simply not comparable on this
    axis: the diff skips it rather than failing the gate, so chip
    A/Bs against committed pre-round-12 baselines keep working."""
    divs: list[dict] = []
    lanes: dict = {}
    regressions: list[str] = []
    pa, pb = va["memory_plan"], vb["memory_plan"]
    if pa is not None and pb is not None:
        ra = {e["name"]: (tuple(e["shape"]), e["dtype"], e["bytes"])
              for e in pa["resident"]}
        rb = {e["name"]: (tuple(e["shape"]), e["dtype"], e["bytes"])
              for e in pb["resident"]}
        for name in sorted(set(ra) | set(rb)):
            if ra.get(name) != rb.get(name):
                divs.append(dict(
                    field="memory_plan", name=name,
                    a=("/".join(map(str, ra[name]))
                       if name in ra else None),
                    b=("/".join(map(str, rb[name]))
                       if name in rb else None),
                ))
        if len(pa["classes"]) != len(pb["classes"]):
            divs.append(dict(field="memory_plan_classes",
                             name="ladder depth",
                             a=len(pa["classes"]),
                             b=len(pb["classes"])))
        else:
            # name the class AND the field that moved — bare
            # 'A=5 B=5' class counts would be unactionable
            for i, (ca_c, cb_c) in enumerate(zip(pa["classes"],
                                                 pb["classes"])):
                if ca_c == cb_c:
                    continue
                keys = sorted(
                    k for k in set(ca_c) | set(cb_c)
                    if k != "staging" and ca_c.get(k) != cb_c.get(k)
                ) or ["staging"]
                for k in keys:
                    divs.append(dict(
                        field="memory_plan_classes",
                        name=f"class {i}.{k}",
                        a=(len(ca_c.get("staging", []))
                           if k == "staging" else ca_c.get(k)),
                        b=(len(cb_c.get("staging", []))
                           if k == "staging" else cb_c.get(k)),
                    ))

    def byte_lane(name, a, b):
        if a is None or b is None:
            return
        rel = (b - a) / a if a > 0 else (
            float("inf") if b > 0 else 0.0
        )
        lanes[name] = dict(
            a=int(a), b=int(b), delta=int(b - a),
            rel=round(rel, 4) if rel != float("inf") else None,
        )
        if a >= MEM_DIFF_MIN_BYTES and rel > threshold:
            regressions.append(name)

    if pa is not None and pb is not None:
        ca, cb = pa.get("compiled"), pb.get("compiled")
        if ca is not None and cb is not None:
            byte_lane("compiled_temp_bytes",
                      ca.get("temp_size_in_bytes"),
                      cb.get("temp_size_in_bytes"))
    wa, wb = va["memory_watermark"], vb["memory_watermark"]
    if (wa is not None and wb is not None
            and wa.get("source") == wb.get("source")):
        byte_lane("device_peak_bytes",
                  wa.get("device_peak_bytes"),
                  wb.get("device_peak_bytes"))
    return dict(divergences=divs, bytes=lanes,
                regressions=regressions)


#: latency_profile lanes _latency_diff compares (flat float fields;
#: the compile block gets its own lanes below). ``dispatch_net_sec``
#: — not raw dispatch — is the regression lane: a forced cold compile
#: physically sits inside chunk 0's dispatch, and the ledger
#: subtraction is what lets the diff attribute it to compile instead.
LATENCY_DIFF_LANES = (
    "time_to_first_wave_sec",
    "dispatch_net_sec",
    "fetch_sec",
    "chunk_wall_sec",
    "interchunk_sec",
    "run_wall_sec",
)


def _latency_diff(va: dict, vb: dict, threshold: float,
                  min_sec: float) -> dict:
    """Latency alignment between two runs (the round-14 layer): the
    ``latency_profile`` wall lanes and the compile attribution compare
    RELATIVE under ``threshold``, and per-property time-to-verdict
    lanes ride along — with the verdict KIND (discovery vs exhaustion)
    treated as a counter: two runs of one workload must settle every
    property the same way, so a kind flip is a divergence, not a
    timing delta.

    A side with NO latency events at all (a pre-round-14 baseline
    trace) is simply not comparable on this axis: the diff skips it
    rather than failing the gate, so A/Bs against committed old
    baselines keep working — the memory diff's compatibility contract.

    The regression rule differs from the phase table's on purpose:
    a lane regresses when ``b - a > max(min_sec, threshold * a)`` —
    the relative bar everywhere, but an ABSOLUTE ``min_sec`` growth is
    enough on a near-zero baseline (a 0.3 s injected sync stall on a
    10 ms warm fetch floor, a multi-second cold compile against a
    0-second warm ledger: both must flag, and pure a>=min_sec gating
    would skip exactly those)."""
    pa, pb = va["latency_profile"], vb["latency_profile"]
    lanes: dict = {}
    regressions: list[str] = []
    divergences: list[dict] = []

    def lane(name, a, b):
        if a is None or b is None:
            return
        rel = (b - a) / a if a > 0 else (
            float("inf") if b > 0 else 0.0
        )
        lanes[name] = dict(
            a=round(a, 6), b=round(b, 6), delta=round(b - a, 6),
            rel=round(rel, 4) if rel != float("inf") else None,
        )
        if b - a > max(min_sec, threshold * a):
            regressions.append(name)

    if pa is not None and pb is not None:
        for name in LATENCY_DIFF_LANES:
            lane(name, pa.get(name), pb.get(name))
        ca, cb = pa.get("compile") or {}, pb.get("compile") or {}
        lane("compile_cold_sec", ca.get("cold_sec"),
             cb.get("cold_sec"))
        lane("compile_total_sec",
             (ca.get("span_sec", 0.0) + ca.get("build_wall_sec", 0.0)
              if ca else None),
             (cb.get("span_sec", 0.0) + cb.get("build_wall_sec", 0.0)
              if cb else None))

    # per-property time-to-verdict: last settle per property wins
    # (auto-budget retries re-settle inside one run; the final
    # attempt's verdict is the run's answer)
    def vmap_of(view):
        t0 = (view["begin"] or {}).get("t", 0.0)
        out = {}
        for ev in view["verdicts"]:
            out[ev["property"]] = (ev["kind"],
                                   round(ev["t"] - t0, 6))
        return out

    va_v, vb_v = vmap_of(va), vmap_of(vb)
    if va_v and vb_v:
        for prop in sorted(set(va_v) | set(vb_v)):
            if (prop in va_v) != (prop in vb_v):
                divergences.append(dict(
                    field="verdict_present", property=prop,
                    a=prop in va_v, b=prop in vb_v,
                ))
                continue
            (ka, ta), (kb, tb) = va_v[prop], vb_v[prop]
            if ka != kb:
                divergences.append(dict(
                    field="verdict_kind", property=prop, a=ka, b=kb,
                ))
                continue
            lane(f"verdict:{prop}", ta, tb)

    return dict(divergences=divergences, lanes=lanes,
                regressions=regressions)


def _tier_diff(va: dict, vb: dict, threshold: float,
               min_sec: float) -> dict:
    """Tier-spill alignment between two runs (the tiered-visited-set
    layer, stateright_tpu/tier.py): spill COUNTS and cold-tier
    rows/bytes are exploration facts — two tiered runs of one
    workload at the same hot ceiling spill identically, so any
    mismatch is a divergence — while the spill/ingest WALLS compare
    relative under the ``b - a > max(min_sec, threshold * a)`` bar
    the latency lanes use.

    A side with NO tier events simply skips the block (a forced-spill
    run diffed against the all-resident baseline — the exact A/B this
    layer's acceptance artifact records — must compare on the WAVE
    counters, which stay fully enforced, not fail here; pre-tier
    baseline traces keep diffing the same way)."""
    sa, sb = va["tier_spills"], vb["tier_spills"]
    divergences: list[dict] = []
    lanes: dict = {}
    regressions: list[str] = []
    if not sa or not sb:
        return dict(divergences=divergences, lanes=lanes,
                    regressions=regressions,
                    skipped=(not sa) != (not sb))

    def counter(name, a, b):
        if a != b:
            divergences.append(dict(field=name, a=a, b=b))

    counter("tier_spill_count", len(sa), len(sb))
    counter("tier_rows_spilled",
            sum(int(ev["rows"]) for ev in sa),
            sum(int(ev["rows"]) for ev in sb))
    counter("tier_cold_rows_final",
            int(sa[-1]["cold_rows_total"]),
            int(sb[-1]["cold_rows_total"]))
    counter("tier_cold_bytes_final",
            int(sa[-1]["cold_bytes_total"]),
            int(sb[-1]["cold_bytes_total"]))

    def lane(name, a, b):
        if a is None or b is None:
            return
        rel = (b - a) / a if a > 0 else (
            float("inf") if b > 0 else 0.0
        )
        lanes[name] = dict(
            a=round(a, 6), b=round(b, 6), delta=round(b - a, 6),
            rel=round(rel, 4) if rel != float("inf") else None,
        )
        if b - a > max(min_sec, threshold * a):
            regressions.append(name)

    def wall(evs, field):
        vals = [ev.get(field) for ev in evs]
        if any(v is None for v in vals):
            return None
        return float(sum(vals))

    lane("tier_spill_wall_sec", wall(sa, "wall_sec"),
         wall(sb, "wall_sec"))
    lane("tier_ingest_wall_sec", wall(sa, "ingest_sec"),
         wall(sb, "ingest_sec"))
    return dict(divergences=divergences, lanes=lanes,
                regressions=regressions, skipped=False)


def diff_traces(
    a_events: list[dict],
    b_events: list[dict],
    *,
    run_a: int | None = None,
    run_b: int | None = None,
    threshold: float = 0.10,
    min_sec: float = 0.05,
) -> dict:
    """Align two traces wave-by-wave and price the per-phase deltas.

    Returns a report dict:
      ``divergences`` — per-wave counter mismatches (a traced A/B of
        one workload must have identical exploration; any mismatch
        fails the gate),
      ``phases`` — {phase: {a, b, delta, rel}},
      ``regressions`` — phases where B exceeds A by more than
        ``threshold`` (relative), ignoring phases under ``min_sec``
        on the A side (noise floor),
      ``memory`` — the memory-counter alignment (:func:`_memory_diff`:
        plan shapes exact, measured temp/live bytes under
        ``threshold``),
      ``latency`` — the latency alignment (:func:`_latency_diff`:
        latency_profile wall lanes + per-property time-to-verdict
        under ``threshold``; verdict-kind flips are divergences;
        sides without latency events skip),
      ``ok`` — True iff no divergence and no regression (timing,
        memory, OR latency).

    ``run_a``/``run_b`` default to the LAST run in each file (bench
    traces warm-run-last)."""
    va = _run_view(a_events, _runs(a_events)[-1] if run_a is None
                   else run_a)
    vb = _run_view(b_events, _runs(b_events)[-1] if run_b is None
                   else run_b)

    divergences = []
    # Certificate-status flip (analysis/soundness.py): the run_begin
    # lane config carries soundness_certified on reduction runs. A
    # certified↔refused flip between two traces of one workload means
    # the reductions being compared do NOT carry the same soundness
    # guarantee — that is a divergence, not a timing delta.
    cert_a = ((va["begin"] or {}).get("lane")
              or {}).get("soundness_certified")
    cert_b = ((vb["begin"] or {}).get("lane")
              or {}).get("soundness_certified")
    if cert_a is not None and cert_b is not None and cert_a != cert_b:
        divergences.append(
            dict(wave=None, field="soundness_certified",
                 a=cert_a, b=cert_b)
        )
    wa = {w["wave"]: w for w in va["waves"]}
    wb = {w["wave"]: w for w in vb["waves"]}
    # Resume-aware alignment (the durability layer): a RESUMED run's
    # wave stream legitimately begins at its restore wave — the
    # pre-kill waves died with the killed process's trace. Waves both
    # sides have must still match on EVERY counter, and the running
    # unique_total carries the pre-kill history, so "zero counter
    # divergence over the overlap" is exactly the kill/resume parity
    # proof (tools/crash_matrix.py's CKPT artifact verdict).
    rw_a, rw_b = _resume_wave(va), _resume_wave(vb)
    for i in sorted(set(wa) | set(wb)):
        if i not in wa or i not in wb:
            if _missing_ok(i, i in wa, i in wb, rw_a, rw_b):
                continue  # pre-resume wave: expected absent
            divergences.append(
                dict(wave=i, field="present",
                     a=i in wa, b=i in wb)
            )
            continue
        for field in DIFF_COUNTERS:
            if wa[i][field] != wb[i][field]:
                divergences.append(
                    dict(wave=i, field=field,
                         a=wa[i][field], b=wb[i][field])
                )
    divergences.extend(_shard_divergences(va, vb))

    pa = _phase_durations(va)
    pb = _phase_durations(vb)
    phases = {}
    regressions = []
    for phase in sorted(set(pa) | set(pb)):
        a = pa.get(phase, 0.0)
        b = pb.get(phase, 0.0)
        rel = (b - a) / a if a > 0 else (float("inf") if b > 0 else 0.0)
        phases[phase] = dict(a=round(a, 6), b=round(b, 6),
                             delta=round(b - a, 6),
                             rel=round(rel, 4) if rel != float("inf")
                             else None)
        if a >= min_sec and rel > threshold:
            regressions.append(phase)

    memory = _memory_diff(va, vb, threshold)
    latency = _latency_diff(va, vb, threshold, min_sec)
    tier = _tier_diff(va, vb, threshold, min_sec)
    deg_a = [dict(wave=int(d.get("wave") or 0),
                  from_shards=int(d["from_shards"]),
                  to_shards=int(d["to_shards"]),
                  reason=d.get("reason"))
             for d in va["degrades"]]
    deg_b = [dict(wave=int(d.get("wave") or 0),
                  from_shards=int(d["from_shards"]),
                  to_shards=int(d["to_shards"]),
                  reason=d.get("reason"))
             for d in vb["degrades"]]
    if (rw_a is None) != (rw_b is None) \
            or bool(deg_a) != bool(deg_b):
        # One side resumed (or DEGRADED) mid-run: its walls cover a
        # PARTIAL search (plus a fresh process's compile fetches), so
        # timing/byte lanes are not comparable to the uninterrupted
        # side — only the counters are, and those stay fully enforced
        # above. The lanes still print; the regression flags are
        # cleared.
        regressions = []
        memory["regressions"] = []
        latency["regressions"] = []
        tier["regressions"] = []
        if bool(deg_a) != bool(deg_b):
            # a degraded run legitimately re-declared its resident
            # layout at the surviving shard count — the plan-exact
            # gate compares configs that are SUPPOSED to differ;
            # the global wave counters stay the exactness proof
            memory["divergences"] = []
        # spill-event counts are also not comparable across a resume:
        # the pre-kill spills died with the killed process's trace
        # (the cold-total lanes would match, but the per-event counts
        # legitimately differ) — wave counters stay fully enforced
        tier["divergences"] = []
    return dict(
        run_a=va["run"], run_b=vb["run"],
        waves_a=len(va["waves"]), waves_b=len(vb["waves"]),
        resume_wave_a=rw_a, resume_wave_b=rw_b,
        degrades_a=deg_a, degrades_b=deg_b,
        divergences=divergences,
        phases=phases,
        regressions=regressions,
        memory=memory,
        latency=latency,
        tier=tier,
        threshold=threshold,
        min_sec=min_sec,
        ok=(not divergences and not regressions
            and not memory["divergences"]
            and not memory["regressions"]
            and not latency["divergences"]
            and not latency["regressions"]
            and not tier["divergences"]
            and not tier["regressions"]),
    )


def format_diff(report: dict) -> str:
    lines = [
        f"trace diff: run A#{report['run_a']} "
        f"({report['waves_a']} waves) vs run B#{report['run_b']} "
        f"({report['waves_b']} waves)",
    ]
    for side in ("a", "b"):
        rw = report.get(f"resume_wave_{side}")
        if rw is not None:
            lines.append(
                f"run {side.upper()} RESUMED at wave {rw}: "
                "pre-resume waves excluded from alignment; timing "
                "lanes informational (partial-run walls)"
            )
        for d in report.get(f"degrades_{side}") or ():
            lines.append(
                f"run {side.upper()} DEGRADED at wave {d['wave']}: "
                f"S={d['from_shards']} -> S={d['to_shards']} "
                f"({d.get('reason')}) — shard lanes compare within "
                "each shard-count segment; global counters fully "
                "enforced"
            )
    if report["divergences"]:
        lines.append(
            f"WAVE DIVERGENCE ({len(report['divergences'])} "
            "mismatches) — the two traces did not explore the same "
            "space:"
        )
        for d in report["divergences"][:10]:
            lines.append(
                f"  wave {d['wave']:5d} {d['field']:14s} "
                f"A={d['a']} B={d['b']}"
            )
        if len(report["divergences"]) > 10:
            lines.append(
                f"  ... {len(report['divergences']) - 10} more"
            )
    lines.append(
        f"{'phase':28s} {'A sec':>10s} {'B sec':>10s} "
        f"{'delta':>10s} {'rel':>8s}"
    )
    for phase, p in report["phases"].items():
        rel = "n/a" if p["rel"] is None else f"{p['rel']:+.1%}"
        flag = "  <-- REGRESSION" if phase in report["regressions"] \
            else ""
        lines.append(
            f"{phase:28s} {p['a']:10.4f} {p['b']:10.4f} "
            f"{p['delta']:+10.4f} {rel:>8s}{flag}"
        )
    mem = report.get("memory") or {}
    if mem.get("divergences"):
        lines.append(
            f"MEMORY-PLAN DIVERGENCE ({len(mem['divergences'])} "
            "mismatches) — the two runs declared different resident "
            "layouts:"
        )
        for d in mem["divergences"][:10]:
            lines.append(
                f"  {d['field']:20s} {d.get('name', ''):14s} "
                f"A={d['a']} B={d['b']}"
            )
    for name, p in (mem.get("bytes") or {}).items():
        rel = "n/a" if p["rel"] is None else f"{p['rel']:+.1%}"
        flag = ("  <-- REGRESSION"
                if name in mem.get("regressions", ()) else "")
        lines.append(
            f"{name:28s} {p['a']:10d} {p['b']:10d} "
            f"{p['delta']:+10d} {rel:>8s}{flag}"
        )
    tier = report.get("tier") or {}
    if tier.get("skipped"):
        lines.append(
            "tier: one side has no tier_spill events (an all-resident"
            " baseline) — cold-tier lanes skipped"
        )
    if tier.get("divergences"):
        lines.append(
            f"TIER DIVERGENCE ({len(tier['divergences'])} "
            "mismatches) — the two runs spilled differently:"
        )
        for d in tier["divergences"][:10]:
            lines.append(
                f"  {d['field']:22s} A={d['a']} B={d['b']}"
            )
    for name, p in (tier.get("lanes") or {}).items():
        rel = "n/a" if p["rel"] is None else f"{p['rel']:+.1%}"
        flag = ("  <-- REGRESSION"
                if name in tier.get("regressions", ()) else "")
        lines.append(
            f"{name:28s} {p['a']:10.4f} {p['b']:10.4f} "
            f"{p['delta']:+10.4f} {rel:>8s}{flag}"
        )
    lat = report.get("latency") or {}
    if lat.get("divergences"):
        lines.append(
            f"VERDICT DIVERGENCE ({len(lat['divergences'])} "
            "mismatches) — the two runs settled properties "
            "differently:"
        )
        for d in lat["divergences"][:10]:
            lines.append(
                f"  {d['field']:16s} {d.get('property', ''):24s} "
                f"A={d['a']} B={d['b']}"
            )
    for name, p in (lat.get("lanes") or {}).items():
        rel = "n/a" if p["rel"] is None else f"{p['rel']:+.1%}"
        flag = ("  <-- REGRESSION"
                if name in lat.get("regressions", ()) else "")
        lines.append(
            f"{name:28s} {p['a']:10.4f} {p['b']:10.4f} "
            f"{p['delta']:+10.4f} {rel:>8s}{flag}"
        )
    mem_regs = mem.get("regressions") or []
    lat_regs = lat.get("regressions") or []
    tier_regs = tier.get("regressions") or []
    verdict = "OK" if report["ok"] else (
        "FAIL: wave divergence" if report["divergences"]
        else "FAIL: memory-plan divergence" if mem.get("divergences")
        else "FAIL: verdict divergence" if lat.get("divergences")
        else "FAIL: tier divergence" if tier.get("divergences")
        else f"FAIL: {len(report['regressions']) + len(mem_regs) + len(lat_regs) + len(tier_regs)} "
             f"lane(s) past +{report['threshold']:.0%}"
    )
    lines.append(f"verdict: {verdict}")
    return "\n".join(lines)
