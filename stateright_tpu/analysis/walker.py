"""Jaxpr traversal for the kernel-lint rules.

One walk, every consumer: :func:`iter_eqns` yields each equation of a
(closed) jaxpr together with the stack of enclosing higher-order
primitives — ``scan``/``while`` bodies, ``cond``/``switch`` branches
(with the branch index), ``pjit``/``custom_jvp`` call bodies, anything
that stores sub-jaxprs in its params — so a rule can ask "is this pad
inside a switch branch?" without re-implementing the descent. The
codegen-shape audit (:func:`audit_jaxpr`) and the lint rules
(:mod:`.rules`) both run on this stream.

Source attribution: every yielded equation carries its jax
``source_info``; :func:`source_of` renders it as ``file:line (fn)``
(the innermost non-jax user frame), which is what a lint finding
prints so a flagged op points at the encoding/engine line that traced
it, not at the walker.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator


@dataclass(frozen=True)
class EqnSite:
    """One equation plus where the walk found it.

    ``stack`` is a tuple of ``(enclosing_primitive_name, branch_index
    or None)`` from outermost to innermost — e.g. a pad inside the
    third branch of the class-ladder switch inside the wave while-loop
    walks in with ``(("while", None), ("cond", 2))``. ``jaxpr`` is the
    (sub-)jaxpr the equation belongs to, so a rule can ask whether an
    equation's result is one of its jaxpr's OUTPUTS (a branch
    returning a rebuilt buffer as its carry) versus an internal
    temporary (a sort lane that never leaves the branch).
    """

    eqn: Any
    stack: tuple
    jaxpr: Any = None

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name

    def in_branch(self) -> bool:
        """True when the equation sits inside a ``cond``/``switch``
        branch computation at any depth."""
        return any(name == "cond" for name, _ in self.stack)

    def reaches_output(self) -> bool:
        """True when one of the equation's results is returned by its
        enclosing (sub-)jaxpr — directly, or through a chain of
        value-preserving unary ops (tables.PASSTHROUGH_PRIMS: a
        ``.astype(...)``/reshape between a rebuilt buffer and the
        branch return must not hide it). For an equation inside a
        branch, reaching the output means the value is part of the
        branch's returned carry.

        Known limitation: a value laundered through a BINARY ALU
        identity (``x | 0``, ``x + 0``) is not followed — treating
        ALU ops as passthrough would over-approximate reachability
        and flag legitimate in-branch compute whose result happens
        to be returned."""
        from .tables import PASSTHROUGH_PRIMS

        jx = self.jaxpr
        if jx is None:
            return False
        outs = set(map(id, jx.outvars))
        frontier = {id(v) for v in self.eqn.outvars}
        if frontier & outs:
            return True
        # follow pure passthroughs forward (the jaxpr is
        # topologically ordered, so one linear scan covers chains)
        for e in jx.eqns:
            if e.primitive.name not in PASSTHROUGH_PRIMS:
                continue
            if any(id(v) in frontier for v in e.invars
                   if hasattr(v, "count")):
                for v in e.outvars:
                    frontier.add(id(v))
                    if id(v) in outs:
                        return True
        return False

    def branch_path(self) -> str:
        return "/".join(
            name if idx is None else f"{name}[{idx}]"
            for name, idx in self.stack
        )


def _sub_jaxprs(eqn) -> Iterator[tuple]:
    """Yield ``(sub_jaxpr, branch_index or None)`` for every sub-jaxpr
    stored in an equation's params. ``cond``'s ``branches`` param (the
    jaxpr form of both ``lax.cond`` and ``lax.switch``) is the one
    list whose position is meaningful — branch indices let the
    branch-shape rules name the offending class."""
    for key, p in eqn.params.items():
        if hasattr(p, "jaxpr"):
            yield p.jaxpr, None
        elif hasattr(p, "eqns"):
            # an open Jaxpr stored directly (e.g. shard_map's param)
            yield p, None
        elif isinstance(p, (list, tuple)):
            for i, q in enumerate(p):
                if hasattr(q, "jaxpr"):
                    yield q.jaxpr, (i if key == "branches" else None)
                elif hasattr(q, "eqns"):
                    yield q, (i if key == "branches" else None)


def iter_eqns(jaxpr, _stack: tuple = ()) -> Iterator[EqnSite]:
    """Depth-first over every equation of ``jaxpr`` (a ``Jaxpr`` — pass
    ``closed.jaxpr`` for a ``ClosedJaxpr``) including all sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield EqnSite(eqn, _stack, jaxpr)
        name = eqn.primitive.name
        for sub, branch in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, _stack + ((name, branch),))


def source_of(eqn) -> str:
    """``file:line (function)`` of the user frame that traced the
    equation — the attribution a finding prints."""
    si = getattr(eqn, "source_info", None)
    if si is None:
        return "<unknown>"
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(si)
    except Exception:
        return "<unknown>"


# -- the shared per-eqn shape predicates -----------------------------------
# One implementation each, consumed by BOTH the declarative rules
# (analysis/rules.py) and the codegen-shape audit below — the
# detection logic cannot drift between the lint and the tests.

def eqn_dense_bool_k(eqn, k: int) -> bool:
    """Any 2-D bool output whose LAST dim is ``k`` — the dense
    ``[rows, K]`` mask at any row count (frontier rows, pair-buffer
    rows, tile rows alike)."""
    import numpy as np

    for v in eqn.outvars:
        sh = getattr(v.aval, "shape", None)
        if (
            sh is not None
            and len(sh) == 2
            and sh[1] == k
            and getattr(v.aval, "dtype", None) == np.bool_
        ):
            return True
    return False


def eqn_alu_n1(eqn, n: int) -> bool:
    """An ALU primitive with a ``[n, 1]``-shaped output — real
    compute at 128x lane padding."""
    from .tables import ALU_PRIMS

    if eqn.primitive.name not in ALU_PRIMS:
        return False
    return any(
        getattr(v.aval, "shape", None) == (n, 1) for v in eqn.outvars
    )


def eqn_wide_concat_n1(eqn, n: int) -> int:
    """Count of ``[n, 1]`` operands when the eqn is a concatenate of
    ≥3 of them (the stack-of-lane-scalars pattern); else 0."""
    if eqn.primitive.name != "concatenate":
        return 0
    n1_ops = sum(
        1 for v in eqn.invars
        if getattr(v.aval, "shape", None) == (n, 1)
    )
    return n1_ops if n1_ops >= 3 else 0


def audit_jaxpr(closed, *, n: int, k: int):
    """The codegen-shape audit the tests calibrated (round 5/6),
    run over the shared walk and predicates: gather count,
    ``[n, 1]``-shaped ALU outputs, dense ``[*, k]`` bool outputs (any
    row count — tile- and pair-buffer-shaped dense masks count too),
    and concatenates of ≥3 ``[n, 1]`` operands (the
    stack-of-lane-scalars pattern).

    Returns ``dict(gathers, alu_n1, wide_concat_n1, bool_nk)`` with
    the same keys tests/test_codegen_shapes.py always asserted on,
    plus ``gather_sites`` / ``bool_nk_sites`` / ``alu_n1_sites``
    (``(primitive, source)`` pairs) so a failure names the traced
    line.
    """
    from .tables import is_gather

    stats = dict(
        gathers=0, alu_n1=[], wide_concat_n1=0, bool_nk=[],
        gather_sites=[], alu_n1_sites=[], bool_nk_sites=[],
        wide_concat_n1_sites=[],
    )
    for site in iter_eqns(closed.jaxpr):
        eqn = site.eqn
        name = site.primitive
        if is_gather(name):
            stats["gathers"] += 1
            stats["gather_sites"].append((name, source_of(eqn)))
        if eqn_wide_concat_n1(eqn, n):
            stats["wide_concat_n1"] += 1
            stats["wide_concat_n1_sites"].append(
                (name, source_of(eqn))
            )
        if eqn_alu_n1(eqn, n):
            stats["alu_n1"].append(name)
            stats["alu_n1_sites"].append((name, source_of(eqn)))
        if eqn_dense_bool_k(eqn, k):
            stats["bool_nk"].append(name)
            stats["bool_nk_sites"].append((name, source_of(eqn)))
    return stats
