"""Jaxpr traversal for the kernel-lint rules.

One walk, every consumer: :func:`iter_eqns` yields each equation of a
(closed) jaxpr together with the stack of enclosing higher-order
primitives — ``scan``/``while`` bodies, ``cond``/``switch`` branches
(with the branch index), ``pjit``/``custom_jvp`` call bodies, anything
that stores sub-jaxprs in its params — so a rule can ask "is this pad
inside a switch branch?" without re-implementing the descent. The
codegen-shape audit (:func:`audit_jaxpr`) and the lint rules
(:mod:`.rules`) both run on this stream.

Source attribution: every yielded equation carries its jax
``source_info``; :func:`source_of` renders it as ``file:line (fn)``
(the innermost non-jax user frame), which is what a lint finding
prints so a flagged op points at the encoding/engine line that traced
it, not at the walker.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator


@dataclass(frozen=True)
class EqnSite:
    """One equation plus where the walk found it.

    ``stack`` is a tuple of ``(enclosing_primitive_name, branch_index
    or None, enclosing_eqn)`` from outermost to innermost — e.g. a pad
    inside the third branch of the class-ladder switch inside the wave
    while-loop walks in with ``(("while", None, <while eqn>),
    ("cond", 2, <switch eqn>))``. The enclosing eqn (round 13) is what
    lets the comms rules read a switch's INDEX operand — "is this
    collective under a shard-uniform switch?" needs the ``cond`` eqn
    itself, not just its name. ``jaxpr`` is the (sub-)jaxpr the
    equation belongs to, so a rule can ask whether an equation's
    result is one of its jaxpr's OUTPUTS (a branch returning a rebuilt
    buffer as its carry) versus an internal temporary (a sort lane
    that never leaves the branch).
    """

    eqn: Any
    stack: tuple
    jaxpr: Any = None

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name

    def in_branch(self) -> bool:
        """True when the equation sits inside a ``cond``/``switch``
        branch computation at any depth."""
        return any(name == "cond" for name, _, _ in self.stack)

    def enclosing_conds(self):
        """The ``cond``/``switch`` eqns this site is nested under,
        outermost first, as ``(cond_eqn, branch_index)`` pairs."""
        return [
            (eqn, idx)
            for name, idx, eqn in self.stack
            if name == "cond"
        ]

    def reaches_output(self) -> bool:
        """True when one of the equation's results is returned by its
        enclosing (sub-)jaxpr — directly, or through a chain of
        value-preserving unary ops (tables.PASSTHROUGH_PRIMS: a
        ``.astype(...)``/reshape between a rebuilt buffer and the
        branch return must not hide it). For an equation inside a
        branch, reaching the output means the value is part of the
        branch's returned carry.

        Known limitation: a value laundered through a BINARY ALU
        identity (``x | 0``, ``x + 0``) is not followed — treating
        ALU ops as passthrough would over-approximate reachability
        and flag legitimate in-branch compute whose result happens
        to be returned."""
        from .tables import PASSTHROUGH_PRIMS

        jx = self.jaxpr
        if jx is None:
            return False
        outs = set(map(id, jx.outvars))
        frontier = {id(v) for v in self.eqn.outvars}
        if frontier & outs:
            return True
        # follow pure passthroughs forward (the jaxpr is
        # topologically ordered, so one linear scan covers chains)
        for e in jx.eqns:
            if e.primitive.name not in PASSTHROUGH_PRIMS:
                continue
            if any(id(v) in frontier for v in e.invars
                   if hasattr(v, "count")):
                for v in e.outvars:
                    frontier.add(id(v))
                    if id(v) in outs:
                        return True
        return False

    def branch_path(self) -> str:
        return "/".join(
            name if idx is None else f"{name}[{idx}]"
            for name, idx, _ in self.stack
        )


def _sub_jaxprs(eqn) -> Iterator[tuple]:
    """Yield ``(sub_jaxpr, branch_index or None)`` for every sub-jaxpr
    stored in an equation's params. ``cond``'s ``branches`` param (the
    jaxpr form of both ``lax.cond`` and ``lax.switch``) is the one
    list whose position is meaningful — branch indices let the
    branch-shape rules name the offending class."""
    for key, p in eqn.params.items():
        if hasattr(p, "jaxpr"):
            yield p.jaxpr, None
        elif hasattr(p, "eqns"):
            # an open Jaxpr stored directly (e.g. shard_map's param)
            yield p, None
        elif isinstance(p, (list, tuple)):
            for i, q in enumerate(p):
                if hasattr(q, "jaxpr"):
                    yield q.jaxpr, (i if key == "branches" else None)
                elif hasattr(q, "eqns"):
                    yield q, (i if key == "branches" else None)


def iter_eqns(jaxpr, _stack: tuple = ()) -> Iterator[EqnSite]:
    """Depth-first over every equation of ``jaxpr`` (a ``Jaxpr`` — pass
    ``closed.jaxpr`` for a ``ClosedJaxpr``) including all sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield EqnSite(eqn, _stack, jaxpr)
        name = eqn.primitive.name
        for sub, branch in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, _stack + ((name, branch, eqn),))


# -- whole-jaxpr dataflow (the comms rules' shared analyses) ---------------
#
# Two questions the collective rules ask need more than one equation's
# shapes: "is this switch's index shard-UNIFORM?" (a collective under a
# shard-varying switch deadlocks — branches diverge across the mesh)
# and "is this all_to_all's operand derived from the routing seam?"
# (an unsorted operand ships unrouted candidates). Both are forward
# dataflow marks over the whole (closed) jaxpr, sub-jaxprs included.
#
# Sub-jaxpr boundaries are mapped PRECISELY where jax fixes the
# convention — ``cond`` (invars[0] is the index, operands map 1:1 to
# every branch's invars, outvars positionally) and call-like
# primitives with matching arity — and OVER-APPROXIMATED elsewhere
# (scan/while carries: any marked operand marks all sub invars, any
# marked sub outvar marks all eqn outvars). Over-approximation is in
# the mark-MORE direction for both analyses, which errs toward
# flagging in the uniformity rule (a "maybe-varying" switch index
# flags) and toward NOT flagging in the seam rule (a "maybe-routed"
# operand passes); the deliberate-regression tests pin that both
# still catch the real defect shapes. The marking runs to fixpoint,
# so taint that only develops through a loop-carry round trip is not
# missed.

#: collectives whose RESULT is identical on every shard regardless of
#: operand variance — the uniformity analysis clears taint through
#: these (the engines' pmax class agreement is exactly this: a
#: shard-varying count goes in, a mesh-uniform class comes out).
_UNIFORM_RESULT_COLLECTIVES = frozenset({
    "psum", "psum2", "pmax", "pmin", "all_gather",
    "all_gather_invariant",
})

#: call-like primitives whose sub-jaxpr I/O maps positionally.
_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "remat", "checkpoint",
})


def _mark(marked: set, v) -> bool:
    if not hasattr(v, "count"):  # Literal
        return False
    if id(v) in marked:
        return False
    marked.add(id(v))
    return True


def _flow(jaxpr, marked: set, *, seeds, clears: frozenset,
          shard_map_seeds: bool) -> bool:
    """One forward pass over ``jaxpr`` and its sub-jaxprs; returns
    True when any new var was marked (the fixpoint driver re-runs
    until False)."""
    changed = False
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        tainted_in = any(
            hasattr(v, "count") and id(v) in marked
            for v in eqn.invars
        )
        subs = list(_sub_jaxprs(eqn))
        for sub, _branch in subs:
            if shard_map_seeds and name == "shard_map":
                # entering the mesh region: every per-shard view is a
                # taint source
                for sv in sub.invars:
                    changed |= _mark(marked, sv)
            elif name == "cond":
                for ev, sv in zip(eqn.invars[1:], sub.invars):
                    if hasattr(ev, "count") and id(ev) in marked:
                        changed |= _mark(marked, sv)
            elif name in _CALL_PRIMS and len(sub.invars) == len(
                eqn.invars
            ):
                for ev, sv in zip(eqn.invars, sub.invars):
                    if hasattr(ev, "count") and id(ev) in marked:
                        changed |= _mark(marked, sv)
            elif tainted_in:
                for sv in sub.invars:
                    changed |= _mark(marked, sv)
            changed |= _flow(
                sub, marked, seeds=seeds, clears=clears,
                shard_map_seeds=shard_map_seeds,
            )
            sub_out_marked = any(
                hasattr(sv, "count") and id(sv) in marked
                for sv in sub.outvars
            )
            # LOOP-CARRY FEEDBACK: in a while/scan body the outputs
            # feed the next iteration's inputs, so a mark born INSIDE
            # the body (an axis_index, a nested source) must taint the
            # carried invars too — without this edge, taint that only
            # develops through a loop round trip never reaches a
            # switch index read from the carry (over-approx: all sub
            # invars, since the carry position mapping is
            # primitive-specific). The global fixpoint then re-runs
            # the body with the carry tainted.
            if name in ("while", "scan") and sub_out_marked:
                for sv in sub.invars:
                    changed |= _mark(marked, sv)
            # sub outputs back to the eqn's outputs
            if name == "cond" or (
                name in _CALL_PRIMS
                and len(sub.outvars) == len(eqn.outvars)
            ):
                for sv, ev in zip(sub.outvars, eqn.outvars):
                    if hasattr(sv, "count") and id(sv) in marked:
                        changed |= _mark(marked, ev)
            elif sub_out_marked:
                for ev in eqn.outvars:
                    changed |= _mark(marked, ev)
        if name in clears:
            # result independent of operand variance (e.g. a psum is
            # mesh-uniform no matter what went in)
            continue
        if seeds(eqn) or tainted_in:
            for v in eqn.outvars:
                changed |= _mark(marked, v)
    return changed


def _fixpoint(closed, *, seeds, clears=frozenset(),
              shard_map_seeds=False) -> set:
    marked: set = set()
    while _flow(closed.jaxpr, marked, seeds=seeds, clears=clears,
                shard_map_seeds=shard_map_seeds):
        pass
    return marked


def shard_varying_vars(closed) -> set:
    """ids of vars that may differ across shards: everything flowing
    from a ``shard_map`` region's per-shard inputs or an
    ``axis_index``, EXCEPT through the uniform-result collectives
    (psum/pmax/pmin/all_gather), whose outputs every shard agrees on.
    The complement — a var NOT in this set — is provably mesh-uniform,
    which is what makes a ``lax.switch`` on it collective-safe."""
    return _fixpoint(
        closed,
        seeds=lambda eqn: eqn.primitive.name == "axis_index",
        clears=_UNIFORM_RESULT_COLLECTIVES,
        shard_map_seeds=True,
    )


def seam_derived_vars(closed, kind: str) -> set:
    """ids of vars data-dependent on the routing seam: ``kind="sort"``
    marks forward from multi-key ``sort`` eqns (the sharded sort-merge
    engine's (owner, fp) routing sort — ``num_keys >= 2`` excludes
    incidental single-key value sorts), ``kind="scatter"`` from
    scatter eqns (the hash engine's owner-position tile build). An
    ``all_to_all`` operand outside this set never went through the
    routing stage."""
    if kind == "sort":
        def seeds(eqn):
            return (
                eqn.primitive.name == "sort"
                and eqn.params.get("num_keys", 1) >= 2
            )
    elif kind == "scatter":
        def seeds(eqn):
            return eqn.primitive.name.startswith("scatter")
    else:
        raise ValueError(f"unknown routing seam kind {kind!r}")
    return _fixpoint(closed, seeds=seeds)


class SiteWalk(list):
    """The materialized equation walk of one closed jaxpr, plus the
    lazily-computed whole-jaxpr dataflow marks the comms rules share
    (one walk and at most one fixpoint per analysis per traced path —
    rules never re-run the traversal)."""

    def __init__(self, closed):
        super().__init__(iter_eqns(closed.jaxpr))
        self.closed = closed
        self._marks: dict = {}

    def shard_varying(self) -> set:
        if "varying" not in self._marks:
            self._marks["varying"] = shard_varying_vars(self.closed)
        return self._marks["varying"]

    def seam_derived(self, kind: str) -> set:
        key = f"seam:{kind}"
        if key not in self._marks:
            self._marks[key] = seam_derived_vars(self.closed, kind)
        return self._marks[key]


def source_of(eqn) -> str:
    """``file:line (function)`` of the user frame that traced the
    equation — the attribution a finding prints."""
    si = getattr(eqn, "source_info", None)
    if si is None:
        return "<unknown>"
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(si)
    except Exception:
        return "<unknown>"


# -- the shared per-eqn shape predicates -----------------------------------
# One implementation each, consumed by BOTH the declarative rules
# (analysis/rules.py) and the codegen-shape audit below — the
# detection logic cannot drift between the lint and the tests.

def eqn_dense_bool_k(eqn, k: int) -> bool:
    """Any 2-D bool output whose LAST dim is ``k`` — the dense
    ``[rows, K]`` mask at any row count (frontier rows, pair-buffer
    rows, tile rows alike)."""
    import numpy as np

    for v in eqn.outvars:
        sh = getattr(v.aval, "shape", None)
        if (
            sh is not None
            and len(sh) == 2
            and sh[1] == k
            and getattr(v.aval, "dtype", None) == np.bool_
        ):
            return True
    return False


def eqn_alu_n1(eqn, n: int) -> bool:
    """An ALU primitive with a ``[n, 1]``-shaped output — real
    compute at 128x lane padding."""
    from .tables import ALU_PRIMS

    if eqn.primitive.name not in ALU_PRIMS:
        return False
    return any(
        getattr(v.aval, "shape", None) == (n, 1) for v in eqn.outvars
    )


def eqn_wide_concat_n1(eqn, n: int) -> int:
    """Count of ``[n, 1]`` operands when the eqn is a concatenate of
    ≥3 of them (the stack-of-lane-scalars pattern); else 0."""
    if eqn.primitive.name != "concatenate":
        return 0
    n1_ops = sum(
        1 for v in eqn.invars
        if getattr(v.aval, "shape", None) == (n, 1)
    )
    return n1_ops if n1_ops >= 3 else 0


def audit_jaxpr(closed, *, n: int, k: int):
    """The codegen-shape audit the tests calibrated (round 5/6),
    run over the shared walk and predicates: gather count,
    ``[n, 1]``-shaped ALU outputs, dense ``[*, k]`` bool outputs (any
    row count — tile- and pair-buffer-shaped dense masks count too),
    and concatenates of ≥3 ``[n, 1]`` operands (the
    stack-of-lane-scalars pattern).

    Returns ``dict(gathers, alu_n1, wide_concat_n1, bool_nk)`` with
    the same keys tests/test_codegen_shapes.py always asserted on,
    plus ``gather_sites`` / ``bool_nk_sites`` / ``alu_n1_sites``
    (``(primitive, source)`` pairs) so a failure names the traced
    line.
    """
    from .tables import is_gather

    stats = dict(
        gathers=0, alu_n1=[], wide_concat_n1=0, bool_nk=[],
        gather_sites=[], alu_n1_sites=[], bool_nk_sites=[],
        wide_concat_n1_sites=[],
    )
    for site in iter_eqns(closed.jaxpr):
        eqn = site.eqn
        name = site.primitive
        if is_gather(name):
            stats["gathers"] += 1
            stats["gather_sites"].append((name, source_of(eqn)))
        if eqn_wide_concat_n1(eqn, n):
            stats["wide_concat_n1"] += 1
            stats["wide_concat_n1_sites"].append(
                (name, source_of(eqn))
            )
        if eqn_alu_n1(eqn, n):
            stats["alu_n1"].append(name)
            stats["alu_n1_sites"].append((name, source_of(eqn)))
        if eqn_dense_bool_k(eqn, k):
            stats["bool_nk"].append(name)
            stats["bool_nk_sites"].append((name, source_of(eqn)))
    return stats
