"""The lint's encoding registry: every encoding the sparse engines
run, with its calibrated audit allowances.

The codegen contract is per-encoding-CLASS: hand encodings
(models/paxos_tpu.py, models/two_phase_commit_tpu.py) factor their
guards into host-constant word masks; compiled encodings
(actor/compile.py) generate the same word-native paths from harvested
tables. The lint runs the SAME rules over all of them × both sparse
engine pipelines, with only the declared table-gather allowance
varying:

* hand 2pc gathers NOTHING on the step path (its per-slot constants
  are arithmetic in the slot index),
* hand paxos fetches its two packed table rows (≤ 4 gathers under
  vmap),
* compiled encodings fetch at most the four intended table rows
  (params, flat transition, packed history, crash mask).

Round 13 (ROADMAP direction 5, first step) registers the COMPILED
paxos and 2pc encodings beside the hand ones — the two flagship
protocols are now held to the hand-encoding codegen bar through the
same gate, and the comms rules (analysis/comms.py) run over every
entry's sharded pipeline.

Adding an encoding to the engines means adding a spec here — the
``pytest -m lint`` gate then pins its codegen automatically.

**Pipeline layout (round 9, PERF.md §layout).** The engines keep
resident state in the transposed ``[W, N]`` layout, so the engine
pipelines below (:data:`ENGINE_LAYOUT`) are traced with a ``[W, N]``
frontier — there is no row-major resident path left to trace — and
every encoding's contract paths are traced in BOTH invocation styles:
the row-major vmap-over-rows contract view (``bits`` / ``step``) and
the transposed axis-1 batched invocation the engines actually run
(:data:`TRANSPOSED_PATHS`: ``bits[t]`` plus the pair step in BOTH
backend seams, ``step[t]`` row-states-in / ``step[t1]``
column-states-in, via encoding.py's ``*_cols`` adapters). All five
gated rules run over each.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

#: resident layout of every traced engine pipeline: frontier
#: ``uint32[W, N]`` (minor dim = rows), matching what the sort-merge
#: engines pass to ``sparse_pair_candidates`` since round 9.
ENGINE_LAYOUT = "[W,N]"

#: per-encoding transposed contract paths the lint driver traces in
#: addition to the row-major views — the ``[W, N]`` invocation of the
#: mask and step kernels (enabled_bits_cols / step_slot_cols_fn).
TRANSPOSED_PATHS = ("bits[t]", "step[t]", "step[t1]")

#: the streaming-merge dedup invocations (round 10, ops/merge.py)
#: the lint traces alongside the encodings: both ops (membership,
#: visited append) × both implementations, at production-shaped
#: sorted fixtures. Encoding-independent — the kernels see only
#: 2-limb key lanes — so they trace once, not per encoding; the
#: engines' use of them is additionally covered by the wave-body
#: fixture, which run_lint traces once per implementation so the
#: five gated rules AND the carry-copy-bytes budget price the full
#: wave body in BOTH merge invocation styles
#: (tables.CARRY_COPY_BYTE_BUDGETS keys both fixture names).
MERGE_KERNEL_PATHS = (
    "merge:member:xla", "merge:append:xla",
    "merge:member:pallas", "merge:append:pallas",
)

#: the symmetry-canonicalization kernel paths (ops/canonical.py) the
#: lint traces for every encoding that declares a
#: ``DeviceRewriteSpec`` (encoding.device_rewrite_spec — the same
#: capability probe the engines use, so a newly symmetric encoding is
#: audited the moment the engines would canonicalize it): the
#: row-major contract view, the transposed ``[W, N]`` invocation the
#: engines actually run between step and fingerprint, and that same
#: invocation under ``shard_map`` (the sharded engine canonicalizes
#: BEFORE the (owner, fp) routing seam, so whole orbits route to one
#: shard). All three are held to the bits-path bar: gather-free
#: (rank-by-comparison-counts + one-hot select-sums, never a
#: permutation gather) and no lane-padded ALU.
CANONICAL_PATHS = ("canon", "canon[t]", "canon:sharded")

#: the sharded engine's TRACED wave-body fixture (round 11): the full
#: per-wave program of parallel/engine_sortmerge.py — routing sort,
#: dest tiles, ``all_to_all``, merge switches — with the per-shard
#: mesh log (``slog``/``swave``, telemetry.SHARD_LOG_FIELDS) compiled
#: in, exactly as a traced mesh run executes it. Registering the log
#: path here means kernel-lint's five gated rules AND the
#: carry-copy-bytes budget (tables.CARRY_COPY_BYTE_BUDGETS keys this
#: name) run over it: a telemetry change that re-grows a gather, a
#: dense mask, or a fat switch carry on the sharded wave path fails
#: the lint before it reaches a mesh.
SHARDED_WAVE_BODY_FIXTURE = "engine-fixture(2pc-rm3,sharded+slog)"


@dataclass(frozen=True)
class EncodingSpec:
    """One registered encoding and its calibrated allowances."""

    name: str
    #: "hand" | "compiled"
    kind: str
    #: () -> SparseEncodedModel (deferred: building a compiled
    #: encoding runs the component closure)
    factory: Callable
    #: gathers allowed on the step path — the table-row fetch
    #: allowance the tests calibrated
    max_step_gathers: int = 4


def _hand_paxos():
    from ..models.paxos import PaxosModelCfg
    from ..models.paxos_tpu import PaxosEncoded

    return PaxosEncoded(PaxosModelCfg(client_count=2, server_count=3))


def _hand_2pc():
    from ..models.two_phase_commit_tpu import TwoPhaseSysEncoded

    return TwoPhaseSysEncoded(4)


def _hand_register():
    from ..models.nclient_register_tpu import NClientRegEncoded

    return NClientRegEncoded(4)


def _compiled_abd_ordered():
    from ..actor import Network
    from ..models.linearizable_register import AbdModelCfg, abd_model

    model = abd_model(
        AbdModelCfg(client_count=2, server_count=2),
        Network.new_ordered(),
    )
    return model.to_encoded()


def _compiled_ping_pong():
    from ..actor import Network
    from ..actor.compile import compile_actor_model
    from ..models.ping_pong import (
        PingPongCfg,
        ping_pong_device_specs,
        ping_pong_model,
    )

    cfg = PingPongCfg(max_nat=3)
    model = ping_pong_model(cfg).init_network(
        Network.new_unordered_nonduplicating()
    )
    return compile_actor_model(model, **ping_pong_device_specs(cfg))


def _compiled_paxos():
    # The COMPILED paxos encoding (round 13, ROADMAP direction 5: the
    # compiled path held to the hand-encoding bar): the actor paxos
    # model through the generic compiler, zero hand device code — the
    # same protocol whose HAND encoding is the registry's calibration
    # source. 2c/2s keeps the reachable-mode harvest (the
    # linearizability-tester history domain) registry-sized.
    from ..models.paxos import PaxosModelCfg, paxos_compiled_encoded

    return paxos_compiled_encoded(
        PaxosModelCfg(client_count=2, server_count=2, put_count=1)
    )


def _compiled_2pc_actors():
    # The COMPILED 2pc encoding (round 13): the actor-model
    # reformulation (models/two_phase_commit_actors.py) through the
    # compiler — 2pc's hand encoding finally has a compiled
    # counterpart under the same gate.
    from ..actor.compile import compile_actor_model
    from ..models.two_phase_commit_actors import (
        two_phase_actor_device_specs,
        two_phase_actor_model,
    )

    return compile_actor_model(
        two_phase_actor_model(2), **two_phase_actor_device_specs(2)
    )


def _compiled_2pc_sys_rm5():
    # The PRODUCTION-SHAPE compiled 2pc (round 23): the
    # count-comparable system actor model at the bench parity lane's
    # rm=5 (8,832 states — the hand "2pc rm=5" denominator's exact
    # space) through the codegen OPTIMIZER (actor/compile.py
    # _optimize_codegen, on by default). max_step_gathers=2 pins the
    # optimizer's gather elision: params + flat table rows only — the
    # history and crash gathers provably fold away for this model.
    # The other compiled entries above keep linting the optimizer's
    # output for their families (ordered / lossy / non-trivial
    # history) at registry shapes; this one holds the production
    # shape to the calibrated hand-encoding bar.
    from ..models.two_phase_commit_actors import (
        two_phase_sys_compiled_encoded,
    )

    return two_phase_sys_compiled_encoded(5)


#: every encoding the sparse engines are pinned for. Order is the
#: report order (hand encodings — the calibration sources — first).
ENCODINGS: tuple = (
    EncodingSpec(
        name="hand-paxos-2c3s",
        kind="hand",
        factory=_hand_paxos,
        max_step_gathers=4,
    ),
    EncodingSpec(
        name="hand-2pc-rm4",
        kind="hand",
        factory=_hand_2pc,
        max_step_gathers=0,
    ),
    EncodingSpec(
        name="hand-register-n4",
        kind="hand",
        factory=_hand_register,
        max_step_gathers=0,
    ),
    EncodingSpec(
        name="compiled-abd-ordered-2c2s",
        kind="compiled",
        factory=_compiled_abd_ordered,
        max_step_gathers=4,
    ),
    EncodingSpec(
        name="compiled-ping-pong-nondup",
        kind="compiled",
        factory=_compiled_ping_pong,
        max_step_gathers=4,
    ),
    EncodingSpec(
        name="compiled-paxos-2c2s",
        kind="compiled",
        factory=_compiled_paxos,
        max_step_gathers=4,
    ),
    EncodingSpec(
        name="compiled-2pc-actors-rm2",
        kind="compiled",
        factory=_compiled_2pc_actors,
        max_step_gathers=4,
    ),
    EncodingSpec(
        name="compiled-2pc-sys-rm5",
        kind="compiled",
        factory=_compiled_2pc_sys_rm5,
        max_step_gathers=2,
    ),
)


def _soundness_2pc(count):
    from ..models.two_phase_commit_tpu import TwoPhaseSysEncoded

    return TwoPhaseSysEncoded(count if count is not None else 4)


def _soundness_register(count):
    from ..models.nclient_register_tpu import NClientRegEncoded

    return NClientRegEncoded(count if count is not None else 4)


#: the ``analyze soundness`` targets: every reduction-declaring
#: encoding the soundness analyzer certifies into ``SOUND_r*.json``
#: (analysis/soundness.py). Each factory takes the optional CLI
#: member count (rm_count / n_clients; None = the registry default).
SOUNDNESS_TARGETS: tuple = (
    ("2pc", _soundness_2pc),
    ("register", _soundness_register),
)


def get_encoding_spec(name: str) -> EncodingSpec:
    for spec in ENCODINGS:
        if spec.name == name:
            return spec
    raise KeyError(
        f"unknown encoding {name!r}; registered: "
        f"{[s.name for s in ENCODINGS]}"
    )
