"""Reduction soundness analyzer: statically certify symmetry specs
and ample masks before the device path trusts them.

Round 20 landed device symmetry + ample-set reduction with the
soundness argument carried in prose (the 2pc encoding's
``ample_mask_host`` docstring). That scales to exactly one encoding:
every new ``DeviceRewriteSpec`` meant a fresh hand proof, which is why
only 2pc declared one. This module converts the proof burden into a
static pass — no state-space enumeration — that discharges the
standard sufficient obligations and emits a machine-readable
certificate (``SOUND_r*.json``, the LINT_r*/COMM_r* shape
conventions). The engines consult the certificate at spawn: a
certified spec runs, an uncertifiable one refuses loudly with the
failed obligation (checkers/common.soundness_refusal), and
``--unsound-ok`` / ``CheckerBuilder.unsound_ok()`` preserves research
workflows.

Obligations (each is one certificate record; names are stable — the
refusal message and the tests key on them):

symmetry scope (a declared ``DeviceRewriteSpec``):
  ``group-closure``           the rewrite set is a permutation-group
                              ACTION on the limb layout: structural
                              bounds (ops/canonical.validate_spec)
                              plus cross-field per-lane bit
                              disjointness — overlapping fields make
                              the "permutation" non-bijective, so the
                              orbit map is not an action at all;
  ``orbit-structure``         canonicalization is idempotent and maps
                              each row to a MEMBER PERMUTATION of
                              itself (member-tuple multiset preserved,
                              non-group bits untouched) with every
                              declared field in the sort key — the
                              perfect-canonicalizer contract
                              (constant on orbits);
  ``fingerprint-invariance``  the canonical form — hence the
                              fingerprint fold over it — is invariant
                              under every generator transposition;
  ``property-invariance``     every registered Property predicate is
                              group-invariant: a STATIC member-uniform
                              bit-footprint check over the predicate
                              jaxprs (walked via analysis/walker.py,
                              abstract bit-level interpretation) plus
                              a semantic P(τ·v) == P(v) battery;
  ``transition-equivariance`` the successor SET commutes with the
                              group: multiset{τ·succ(v)} ==
                              multiset{succ(τ·v)} per battery row.

ample scope (a declared ``ample_mask_host``):
  ``ample-enabledness``       enabledness preservation (the C0-style
                              condition): whenever a dropped slot is
                              enabled, some KEPT slot is enabled —
                              proven by exhaustive enumeration over
                              the union guard-footprint cone (the
                              guards provably depend on no other
                              bits), sampled when the cone is large;
  ``ample-non-suppression``   no property-relevant transition is
                              suppressed: every dropped slot whose
                              WRITE footprint meets a property READ
                              footprint must have a symmetric kept
                              image — a kept slot ``k`` and a group
                              element π with g_d(v) == g_k(π·v) and
                              succ_d(v) == π·succ_k(π·v) on the
                              battery (the "by symmetry such a path
                              maps to one using rm 0's" step of the
                              round-20 hand argument, made checkable).

The bit-level abstract interpreter evaluates the encoding's traced
jaxprs over a domain of per-bit codes (CONST0/CONST1, "equals input
bit b", or "depends on mask D") — precise through the shift/mask/
select idiom every encoding path is written in (the lint rules pin
those paths gather-free, which is exactly what keeps this analysis
exact), and soundly over-approximate elsewhere: an unsupported
primitive collapses to depends-on-everything, which can only REFUSE a
sound spec, never certify an unsound one.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..encoding import ample_mask_host as _probe_ample
from ..encoding import device_rewrite_spec as _probe_spec
from .rules import Finding
from .walker import SiteWalk, source_of

#: per-bit abstract codes: >= 0 is "provably equals input bit
#: lane*32+bit"; the negatives are unknown-but-bounded.
_DEP, _CONST0, _CONST1 = -1, -2, -3

#: ample-enabledness cones up to this many bits enumerate
#: exhaustively (2^bits rows); larger cones fall back to sampling and
#: the certificate records method="sampled".
_EXHAUSTIVE_CONE_BITS = 12
_SAMPLE_ROWS = 2048

#: memoized certificates — the engines' spawn gates run per checker
#: construction, and tier-1 constructs hundreds.
_CERT_CACHE: dict = {}


class _Abs:
    """One abstract array: ``codes`` int64[S + (32,)] per-bit codes,
    ``deps`` uint32[S + (32, W)] per-bit input-bit dependency masks
    (an over-approximation; CONST bits carry empty masks)."""

    __slots__ = ("codes", "deps")

    def __init__(self, codes, deps):
        self.codes = codes
        self.deps = deps


def _seed(W: int) -> _Abs:
    codes = (
        np.arange(W, dtype=np.int64)[:, None] * 32
        + np.arange(32, dtype=np.int64)[None, :]
    )
    deps = np.zeros((W, 32, W), np.uint32)
    for lane in range(W):
        deps[lane, :, lane] = np.uint32(1) << np.arange(
            32, dtype=np.uint32
        )
    return _Abs(codes, deps)


def _const_abs(val, W: int) -> _Abs:
    v = np.asarray(val)
    u = v.astype(np.int64) & 0xFFFFFFFF
    bits = (u[..., None] >> np.arange(32, dtype=np.int64)) & 1
    codes = np.where(bits == 1, _CONST1, _CONST0).astype(np.int64)
    deps = np.zeros(v.shape + (32, W), np.uint32)
    return _Abs(codes, deps)


#: primitives interpreted per-element (result depends on the whole
#: element, never on individual bit structure) — arithmetic and
#: comparisons collapse to element-level dependency masks.
_ELEMWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "neg",
    "sign", "abs", "max", "min", "eq", "ne", "lt", "le", "gt", "ge",
    "floor", "ceil", "round", "clamp", "population_count", "clz",
})

_REDUCE = frozenset({
    "reduce_and", "reduce_or", "reduce_xor", "reduce_sum",
    "reduce_max", "reduce_min", "reduce_prod", "argmax", "argmin",
})

_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "remat", "checkpoint",
    "custom_jvp_call", "custom_vjp_call",
})

_IDENTITY = frozenset({"copy", "stop_gradient", "device_put"})


class _BitInterp:
    """Abstract interpreter over one encoding's traced jaxprs."""

    def __init__(self, W: int):
        self.W = W
        #: primitive names we collapsed on (recorded in the
        #: certificate so "certified via over-approximation" is
        #: visible)
        self.collapsed: list = []

    # -- plumbing --------------------------------------------------------

    def lift(self, x) -> _Abs:
        return x if isinstance(x, _Abs) else _const_abs(x, self.W)

    def _bcast(self, vals, shape):
        out = []
        for v in vals:
            a = self.lift(v)
            out.append(_Abs(
                np.broadcast_to(a.codes, tuple(shape) + (32,)),
                np.broadcast_to(a.deps,
                                tuple(shape) + (32, self.W)),
            ))
        return out

    def _elem_deps(self, a) -> np.ndarray:
        """Per-element dependency mask: OR over the 32 bit slots —
        shape S + (W,)."""
        if not isinstance(a, _Abs):
            return np.zeros(np.shape(a) + (self.W,), np.uint32)
        return np.bitwise_or.reduce(a.deps, axis=-2)

    def _all_deps(self, vals) -> np.ndarray:
        acc = np.zeros(self.W, np.uint32)
        for v in vals:
            if isinstance(v, _Abs):
                ed = self._elem_deps(v)
                acc |= np.bitwise_or.reduce(
                    ed.reshape(-1, self.W), axis=0
                ) if ed.size else 0
        return acc

    def _dep_abs(self, shape, elem_deps, dtype=None) -> _Abs:
        """All-bits-DEP output with one dependency mask per element
        (``elem_deps`` shape S + (W,)); bool dtypes keep bits 1..31
        CONST0 — the value is 0 or 1."""
        shape = tuple(shape)
        codes = np.full(shape + (32,), _DEP, np.int64)
        deps = np.broadcast_to(
            elem_deps[..., None, :], shape + (32, self.W)
        ).copy()
        if dtype is not None and np.dtype(dtype) == np.bool_:
            codes[..., 1:] = _CONST0
            deps[..., 1:, :] = 0
        return _Abs(codes, deps)

    def collapse(self, eqn, invals) -> list:
        self.collapsed.append(eqn.primitive.name)
        alldeps = self._all_deps(invals)
        outs = []
        for ov in eqn.outvars:
            sh = tuple(getattr(ov.aval, "shape", ()) or ())
            ed = np.broadcast_to(alldeps, sh + (self.W,))
            outs.append(self._dep_abs(
                sh, ed, getattr(ov.aval, "dtype", None)
            ))
        return outs

    # -- evaluation ------------------------------------------------------

    def run_closed(self, closed, args) -> list:
        return self.run(closed.jaxpr, closed.consts, args)

    def run(self, jaxpr, consts, args) -> list:
        env: dict = {}

        def read(v):
            if not hasattr(v, "count"):  # Literal
                return np.asarray(v.val)
            return env[id(v)]

        for v, c in zip(jaxpr.constvars, consts):
            env[id(v)] = np.asarray(c)
        for v, a in zip(jaxpr.invars, args):
            env[id(v)] = a
        for eqn in jaxpr.eqns:
            invals = [read(v) for v in eqn.invars]
            outvals = self.eval_eqn(eqn, invals)
            for v, o in zip(eqn.outvars, outvals):
                env[id(v)] = o
        return [read(v) for v in jaxpr.outvars]

    def eval_eqn(self, eqn, invals) -> list:
        name = eqn.primitive.name

        if name in _CALL_PRIMS or name == "cond":
            return self._eval_control(eqn, name, invals)

        if all(not isinstance(v, _Abs) for v in invals):
            # constant folding: bind eagerly (this is how the slot
            # arithmetic of a concrete-slot step trace folds away)
            try:
                import jax.numpy as jnp

                res = eqn.primitive.bind(
                    *[jnp.asarray(v) for v in invals], **eqn.params
                )
                res = (list(res) if eqn.primitive.multiple_results
                       else [res])
                return [np.asarray(r) for r in res]
            except Exception:
                return self.collapse(eqn, invals)

        if name in ("and", "or", "xor"):
            return [self._bitwise(name, invals[0], invals[1])]
        if name == "not":
            return [self._not(invals[0])]
        if name in ("shift_left", "shift_right_logical",
                    "shift_right_arithmetic"):
            return self._shift(eqn, name, invals)
        if name == "select_n":
            return [self._select(eqn, invals)]
        if name in _ELEMWISE:
            out = eqn.outvars[0]
            sh = tuple(getattr(out.aval, "shape", ()) or ())
            ed = np.zeros(sh + (self.W,), np.uint32)
            for v in invals:
                ed = ed | np.broadcast_to(
                    self._elem_deps(v), sh + (self.W,)
                )
            return [self._dep_abs(sh, ed, out.aval.dtype)]
        if name in _REDUCE:
            return [self._reduce(eqn, invals[0])]
        if name == "convert_element_type":
            return [self._convert(eqn, invals[0])]
        if name in _IDENTITY:
            return [invals[0]]
        if name in ("broadcast_in_dim", "reshape", "squeeze", "slice",
                    "concatenate", "transpose", "rev",
                    "expand_dims"):
            return self._structural(eqn, name, invals)
        return self.collapse(eqn, invals)

    def _eval_control(self, eqn, name, invals) -> list:
        if name == "cond":
            pred, ops = invals[0], invals[1:]
            branches = eqn.params["branches"]
            if not isinstance(pred, _Abs):
                idx = int(np.asarray(pred).reshape(()))
                idx = min(max(idx, 0), len(branches) - 1)
                b = branches[idx]
                return self.run(b.jaxpr, b.consts, ops)
            results = [
                self.run(b.jaxpr, b.consts, ops) for b in branches
            ]
            pd = np.bitwise_or.reduce(
                self._elem_deps(pred).reshape(-1, self.W), axis=0
            )
            return [
                self._join(list(vals), pd)
                for vals in zip(*results)
            ]
        sub = None
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            p = eqn.params.get(key)
            if p is not None:
                sub = p
                break
        if sub is None:
            return self.collapse(eqn, invals)
        inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        consts = getattr(sub, "consts", [])
        if len(inner.invars) != len(invals):
            return self.collapse(eqn, invals)
        return self.run(inner, consts, invals)

    def _join(self, vals, extra_deps) -> Any:
        """Join abstract values from alternative branches: bits whose
        codes agree everywhere keep the code (no predicate
        dependency — the value is the same either way); disagreeing
        bits go DEP and inherit the predicate's dependency mask."""
        if all(not isinstance(v, _Abs) for v in vals):
            arrs = [np.asarray(v) for v in vals]
            if all(np.array_equal(arrs[0], a) for a in arrs[1:]):
                return arrs[0]
        shape = np.broadcast_shapes(*[
            (v.codes.shape[:-1] if isinstance(v, _Abs)
             else np.shape(v))
            for v in vals
        ])
        bs = self._bcast(vals, shape)
        codes = bs[0].codes.copy()
        deps = bs[0].deps.copy()
        for b in bs[1:]:
            same = codes == b.codes
            deps = np.where(
                same[..., None], deps | b.deps,
                deps | b.deps | extra_deps,
            )
            codes = np.where(same, codes, _DEP)
        deps = np.where(
            codes[..., None] == _DEP, deps | extra_deps, deps
        )
        deps[(codes == _CONST0) | (codes == _CONST1)] = 0
        return _Abs(codes, deps)

    def _bitwise(self, name, a, b) -> _Abs:
        shape = np.broadcast_shapes(
            (a.codes.shape[:-1] if isinstance(a, _Abs)
             else np.shape(a)),
            (b.codes.shape[:-1] if isinstance(b, _Abs)
             else np.shape(b)),
        )
        a, b = self._bcast([a, b], shape)
        ca, cb, da, db = a.codes, b.codes, a.deps, b.deps
        both = da | db
        if name == "and":
            zero = (ca == _CONST0) | (cb == _CONST0)
            codes = np.where(
                zero, _CONST0,
                np.where(cb == _CONST1, ca,
                         np.where(ca == _CONST1, cb,
                                  np.where((ca == cb) & (ca >= 0),
                                           ca, _DEP))))
            deps = np.where(
                zero[..., None], 0,
                np.where((cb == _CONST1)[..., None], da,
                         np.where((ca == _CONST1)[..., None], db,
                                  np.where(((ca == cb)
                                            & (ca >= 0))[..., None],
                                           da, both))))
        elif name == "or":
            one = (ca == _CONST1) | (cb == _CONST1)
            codes = np.where(
                one, _CONST1,
                np.where(cb == _CONST0, ca,
                         np.where(ca == _CONST0, cb,
                                  np.where((ca == cb) & (ca >= 0),
                                           ca, _DEP))))
            deps = np.where(
                one[..., None], 0,
                np.where((cb == _CONST0)[..., None], da,
                         np.where((ca == _CONST0)[..., None], db,
                                  np.where(((ca == cb)
                                            & (ca >= 0))[..., None],
                                           da, both))))
        else:  # xor
            same_id = (ca == cb) & (ca >= 0)
            both_const = ((ca == _CONST0) | (ca == _CONST1)) & (
                (cb == _CONST0) | (cb == _CONST1)
            )
            codes = np.where(
                same_id, _CONST0,
                np.where(both_const,
                         np.where(ca == cb, _CONST0, _CONST1),
                         np.where(cb == _CONST0, ca,
                                  np.where(ca == _CONST0, cb,
                                           _DEP))))
            deps = np.where(
                (same_id | both_const)[..., None], 0,
                np.where((cb == _CONST0)[..., None], da,
                         np.where((ca == _CONST0)[..., None], db,
                                  both)))
        return _Abs(codes.astype(np.int64), deps.astype(np.uint32))

    def _not(self, a) -> _Abs:
        a = self.lift(a)
        codes = np.where(
            a.codes == _CONST0, _CONST1,
            np.where(a.codes == _CONST1, _CONST0, _DEP)
        ).astype(np.int64)
        deps = np.where(
            (codes == _DEP)[..., None], a.deps, 0
        ).astype(np.uint32)
        return _Abs(codes, deps)

    def _shift(self, eqn, name, invals) -> list:
        a, s = invals
        if isinstance(s, _Abs):
            out = eqn.outvars[0]
            sh = tuple(getattr(out.aval, "shape", ()) or ())
            ed = np.broadcast_to(self._elem_deps(a), sh + (self.W,)) \
                | np.broadcast_to(self._elem_deps(s),
                                  sh + (self.W,))
            return [self._dep_abs(sh, ed, out.aval.dtype)]
        a = self.lift(a)
        shape = np.broadcast_shapes(a.codes.shape[:-1], np.shape(s))
        (a,) = self._bcast([a], shape)
        s = np.broadcast_to(np.asarray(s).astype(np.int64), shape)
        arith = name == "shift_right_arithmetic"
        signed = np.dtype(eqn.outvars[0].aval.dtype).kind == "i"
        codes = np.empty(tuple(shape) + (32,), np.int64)
        deps = np.empty(tuple(shape) + (32, self.W), np.uint32)
        for idx in np.ndindex(*shape):
            sh_amt = int(s[idx]) & 63
            c, d = a.codes[idx], a.deps[idx]
            oc = np.full(32, _CONST0, np.int64)
            od = np.zeros((32, self.W), np.uint32)
            if sh_amt < 32:
                if name == "shift_left":
                    oc[sh_amt:] = c[:32 - sh_amt]
                    od[sh_amt:] = d[:32 - sh_amt]
                else:
                    oc[:32 - sh_amt] = c[sh_amt:]
                    od[:32 - sh_amt] = d[sh_amt:]
                    if arith and signed and sh_amt:
                        oc[32 - sh_amt:] = c[31]
                        od[32 - sh_amt:] = d[31]
            elif arith and signed:
                oc[:] = c[31]
                od[:] = d[31]
            codes[idx], deps[idx] = oc, od
        return [_Abs(codes, deps)]

    def _select(self, eqn, invals) -> Any:
        pred, cases = invals[0], invals[1:]
        out = eqn.outvars[0]
        shape = tuple(getattr(out.aval, "shape", ()) or ())
        if not isinstance(pred, _Abs):
            idx = np.broadcast_to(
                np.asarray(pred).astype(np.int64), shape
            )
            if all(not isinstance(c, _Abs) for c in cases):
                stacked = np.stack(
                    [np.broadcast_to(np.asarray(c), shape)
                     for c in cases]
                )
                return np.take_along_axis(
                    stacked, idx[None], axis=0
                )[0]
            bs = self._bcast(cases, shape)
            codes = bs[0].codes.copy()
            deps = bs[0].deps.copy()
            for i in range(1, len(bs)):
                m = idx == i
                codes[m] = bs[i].codes[m]
                deps[m] = bs[i].deps[m]
            return _Abs(codes, deps)
        pd = self._elem_deps(pred)
        pd = np.broadcast_to(pd, shape + (self.W,))
        bs = self._bcast(cases, shape)
        codes = bs[0].codes.copy()
        deps = bs[0].deps.copy()
        for b in bs[1:]:
            same = codes == b.codes
            deps = np.where(
                same[..., None], deps | b.deps,
                deps | b.deps | pd[..., None, :],
            )
            codes = np.where(same, codes, _DEP)
        deps = np.where(
            codes[..., None] == _DEP,
            deps | pd[..., None, :], deps,
        )
        deps[(codes == _CONST0) | (codes == _CONST1)] = 0
        return _Abs(codes.astype(np.int64), deps.astype(np.uint32))

    def _reduce(self, eqn, a) -> _Abs:
        out = eqn.outvars[0]
        sh = tuple(getattr(out.aval, "shape", ()) or ())
        axes = tuple(eqn.params.get("axes", ()))
        ed = self._elem_deps(a)
        if axes:
            ed = np.bitwise_or.reduce(
                ed, axis=tuple(a for a in axes)
            ) if len(axes) == 1 else ed
            if len(axes) > 1:
                ed = self._elem_deps(a)
                for ax in sorted(axes, reverse=True):
                    ed = np.bitwise_or.reduce(ed, axis=ax)
        ed = np.broadcast_to(ed.reshape(sh + (self.W,)),
                             sh + (self.W,))
        return self._dep_abs(sh, ed, out.aval.dtype)

    def _convert(self, eqn, a) -> _Abs:
        a = self.lift(a)
        nd = np.dtype(eqn.params["new_dtype"])
        od = np.dtype(eqn.invars[0].aval.dtype)
        shape = a.codes.shape[:-1]
        if nd == np.bool_:
            high0 = (a.codes[..., 1:] == _CONST0).all(-1)
            ed = self._elem_deps(a)
            codes = np.full(shape + (32,), _CONST0, np.int64)
            deps = np.zeros(shape + (32, self.W), np.uint32)
            codes[..., 0] = np.where(high0, a.codes[..., 0], _DEP)
            deps[..., 0, :] = np.where(
                high0[..., None], a.deps[..., 0, :], ed
            )
            return _Abs(codes, deps)
        if nd.kind in "ui" and od.kind in "uib":
            if od.kind == "i" and nd.itemsize > od.itemsize:
                # sign extension of a possibly-negative value —
                # collapse rather than model it
                ed = self._elem_deps(a)
                return self._dep_abs(shape, ed, nd)
            keep = min(32, nd.itemsize * 8)
            codes = np.full(shape + (32,), _CONST0, np.int64)
            deps = np.zeros(shape + (32, self.W), np.uint32)
            codes[..., :keep] = a.codes[..., :keep]
            deps[..., :keep, :] = a.deps[..., :keep, :]
            return _Abs(codes, deps)
        ed = self._elem_deps(a)
        return self._dep_abs(shape, ed, nd)

    def _structural(self, eqn, name, invals) -> list:
        a = self.lift(invals[0])
        p = eqn.params
        if name == "broadcast_in_dim":
            shape = tuple(p["shape"])
            bdims = tuple(p["broadcast_dimensions"])
            ns = [1] * len(shape)
            for i, d in enumerate(bdims):
                ns[d] = a.codes.shape[:-1][i]
            codes = np.broadcast_to(
                a.codes.reshape(tuple(ns) + (32,)), shape + (32,)
            )
            deps = np.broadcast_to(
                a.deps.reshape(tuple(ns) + (32, self.W)),
                shape + (32, self.W),
            )
            return [_Abs(codes, deps)]
        if name == "reshape":
            if p.get("dimensions") is not None:
                return self.collapse(eqn, invals)
            shape = tuple(p["new_sizes"])
            return [_Abs(a.codes.reshape(shape + (32,)),
                         a.deps.reshape(shape + (32, self.W)))]
        if name == "squeeze":
            dims = tuple(p["dimensions"])
            return [_Abs(np.squeeze(a.codes, axis=dims),
                         np.squeeze(a.deps, axis=dims))]
        if name == "expand_dims":
            dims = tuple(p["dimensions"])
            c, d = a.codes, a.deps
            for ax in sorted(dims):
                c = np.expand_dims(c, ax)
                d = np.expand_dims(d, ax)
            return [_Abs(c, d)]
        if name == "slice":
            sl = tuple(
                slice(int(s), int(l), int(st))
                for s, l, st in zip(
                    p["start_indices"], p["limit_indices"],
                    p["strides"] or [1] * len(p["start_indices"]),
                )
            )
            return [_Abs(a.codes[sl], a.deps[sl])]
        if name == "concatenate":
            dim = int(p["dimension"])
            bs = [self.lift(v) for v in invals]
            return [_Abs(
                np.concatenate([b.codes for b in bs], axis=dim),
                np.concatenate([b.deps for b in bs], axis=dim),
            )]
        if name == "transpose":
            perm = tuple(p["permutation"])
            n = len(perm)
            return [_Abs(
                np.transpose(a.codes, perm + (n,)),
                np.transpose(a.deps, perm + (n, n + 1)),
            )]
        if name == "rev":
            dims = tuple(p["dimensions"])
            return [_Abs(np.flip(a.codes, axis=dims),
                         np.flip(a.deps, axis=dims))]
        return self.collapse(eqn, invals)


# -- footprint extraction ---------------------------------------------------

def _make_closed(fn, *examples):
    import jax

    return jax.make_jaxpr(fn)(*examples)


def _abs_eval(enc, fn):
    """Trace ``fn`` on one zero example state, walk the jaxpr
    (analysis/walker.py — branches and closed-over constants
    included), then abstract-interpret it from the input-bit seed.
    Returns ``(outputs, interp, walk)``."""
    closed = _make_closed(fn, np.zeros(enc.width, np.uint32))
    walk = SiteWalk(closed)
    interp = _BitInterp(enc.width)
    outs = interp.run_closed(closed, [_seed(enc.width)])
    return outs, interp, walk


def _mask_of_bits(a: _Abs, lane_bits) -> np.ndarray:
    """OR the deps of the listed ``(index...)`` bit positions."""
    acc = np.zeros(a.deps.shape[-1], np.uint32)
    for idx in lane_bits:
        acc |= a.deps[idx]
    return acc


def guard_footprints(enc) -> tuple:
    """Per-slot guard read-footprints (uint32[W] masks) from the
    packed ``enabled_bits_vec`` words, plus the interpreter (for its
    collapse record)."""
    import jax.numpy as jnp  # noqa: F401 — encoding paths trace jnp

    outs, interp, walk = _abs_eval(enc, enc.enabled_bits_vec)
    words = outs[0]
    W, K = enc.width, enc.max_actions
    fps = []
    for k in range(K):
        if isinstance(words, _Abs):
            fps.append(np.array(words.deps[k // 32, k % 32],
                                np.uint32))
        else:
            fps.append(np.zeros(W, np.uint32))
    return fps, interp, walk


def property_footprints(enc) -> tuple:
    """Per-property read-footprints over
    ``property_conditions_vec``."""
    outs, interp, walk = _abs_eval(enc, enc.property_conditions_vec)
    props = outs[0]
    names = [p.name for p in enc.host_model.properties()]
    fps = []
    for p in range(len(names)):
        if isinstance(props, _Abs):
            fps.append(np.bitwise_or.reduce(props.deps[p], axis=0))
        else:
            fps.append(np.zeros(enc.width, np.uint32))
    return names, fps, interp, walk


def step_slot_footprints(enc, slot: int) -> tuple:
    """``(write_mask, read_mask)`` uint32[W] for one concrete slot:
    bits the transition may CHANGE (abstract code differs from the
    identity) and bits it may READ."""
    import jax.numpy as jnp

    outs, interp, _walk = _abs_eval(
        enc, lambda v: enc.step_slot_vec(v, jnp.uint32(slot))
    )
    succ = outs[0]
    W = enc.width
    write = np.zeros(W, np.uint32)
    read = np.zeros(W, np.uint32)
    ident = _seed(W).codes
    if isinstance(succ, _Abs):
        changed = succ.codes != ident
        shifts = np.arange(32, dtype=np.uint64)
        for lane in range(W):
            write[lane] = np.uint32(
                (changed[lane].astype(np.uint64) << shifts).sum()
                & 0xFFFFFFFF
            )
        read = np.bitwise_or.reduce(
            succ.deps.reshape(-1, W), axis=0
        )
    else:
        # a constant successor: writes everything it disagrees on;
        # unknowable statically — treat all bits written
        write[:] = np.uint32(0xFFFFFFFF)
    for extra in outs[1:]:
        if isinstance(extra, _Abs):
            read = read | np.bitwise_or.reduce(
                extra.deps.reshape(-1, W), axis=0
            )
    return write, read


# -- permutations -----------------------------------------------------------

def apply_member_permutation(spec, rows, perm) -> np.ndarray:
    """Relabel members of encoded rows: output member ``p`` takes
    input member ``perm[p]``'s field values; non-group bits pass
    through. Pure numpy, any leading batch shape."""
    rows = np.asarray(rows, np.uint32)
    out = rows.copy()
    R = spec.n_members
    for f in spec.fields:
        fm = (1 << f.width) - 1
        fieldmask = 0
        for m in range(R):
            fieldmask |= fm << (f.shift + m * f.stride)
        lane = rows[..., f.lane]
        acc = out[..., f.lane] & np.uint32(~fieldmask & 0xFFFFFFFF)
        for p in range(R):
            src = perm[p]
            v = (lane >> np.uint32(f.shift + src * f.stride)) \
                & np.uint32(fm)
            acc = acc | (v << np.uint32(f.shift + p * f.stride))
        out[..., f.lane] = acc
    return out


def permute_mask(spec, mask, perm) -> np.ndarray:
    """Relabel a uint32[W] bit-mask the same way a state row would
    be (footprints live in the state's bit layout)."""
    return apply_member_permutation(
        spec, np.asarray(mask, np.uint32)[None, :], perm
    )[0]


def _transpositions(R: int) -> list:
    perms = []
    for a in range(R):
        for b in range(a + 1, R):
            p = list(range(R))
            p[a], p[b] = p[b], p[a]
            perms.append(tuple(p))
    return perms


def _generators(R: int) -> list:
    """Adjacent transpositions — they generate S_R, and invariance
    under generators composes to the whole group."""
    gens = []
    for a in range(R - 1):
        p = list(range(R))
        p[a], p[a + 1] = p[a + 1], p[a]
        gens.append(tuple(p))
    return gens


def _member_tuples(spec, row) -> list:
    out = []
    for m in range(spec.n_members):
        t = []
        for f in spec.fields:
            fm = (1 << f.width) - 1
            t.append(
                (int(row[f.lane]) >> (f.shift + m * f.stride)) & fm
            )
        out.append(tuple(t))
    return out


def _group_mask(spec, W: int) -> np.ndarray:
    gm = np.zeros(W, np.uint32)
    for f in spec.fields:
        fm = (1 << f.width) - 1
        for m in range(spec.n_members):
            gm[f.lane] |= np.uint32(
                fm << (f.shift + m * f.stride)
            )
    return gm


# -- the battery ------------------------------------------------------------

def battery_rows(enc, spec, extra_masks=()) -> np.ndarray:
    """Deterministic semantic-check battery: zeros, single-bit rows
    for every group-field and footprint bit, distinct-value member
    sweeps, and fixed-seed pseudorandom rows. Semantic obligations
    hold on EVERY uint32 state (the encodings are branchless total
    functions), so unreachable rows only make the check stronger."""
    W = enc.width
    rows = [np.zeros(W, np.uint32)]
    bits = set()
    if spec is not None:
        for f in spec.fields:
            for m in range(spec.n_members):
                for b in range(f.width):
                    bits.add((f.lane, f.shift + m * f.stride + b))
    for mask in extra_masks:
        for lane in range(W):
            mm = int(mask[lane])
            for j in range(32):
                if (mm >> j) & 1:
                    bits.add((lane, j))
    for lane, b in sorted(bits):
        r = np.zeros(W, np.uint32)
        r[lane] = np.uint32(1) << np.uint32(b)
        rows.append(r)
    if spec is not None:
        for salt in (1, 2):
            r = np.zeros(W, np.uint32)
            for f in spec.fields:
                fm = (1 << f.width) - 1
                for m in range(spec.n_members):
                    v = (m * salt + salt) & fm
                    r[f.lane] |= np.uint32(
                        v << (f.shift + m * f.stride)
                    )
            rows.append(r)
    rng = np.random.default_rng(0xC0FFEE)
    rows.extend(list(
        rng.integers(0, 1 << 32, size=(24, W), dtype=np.uint64)
        .astype(np.uint32)
    ))
    uniq, seen = [], set()
    for r in rows:
        key = tuple(int(x) for x in r)
        if key not in seen:
            seen.add(key)
            uniq.append(r)
    return np.stack(uniq)


# -- obligation checks ------------------------------------------------------

def _finding(enc_name, rule, ok, message, **data) -> Finding:
    return Finding(
        rule=rule,
        severity="info" if ok else "error",
        encoding=enc_name,
        path="soundness",
        message=message,
        data=data,
    )


def _enc_name(enc) -> str:
    key = getattr(enc, "cache_key", None)
    suffix = f"({key()})" if callable(key) else ""
    return type(enc).__qualname__ + suffix


def _check_group_closure(name, enc, spec) -> Finding:
    from ..ops.canonical import validate_spec

    try:
        validate_spec(spec, width=enc.width)
    except ValueError as e:
        return _finding(
            name, "group-closure", False,
            f"structural validation failed: {e}",
            scope="symmetry",
        )
    # cross-field bit disjointness per lane: overlapping fields make
    # the member relabeling non-bijective (two fields write one bit),
    # so the rewrite set is not a group action on the layout.
    R = spec.n_members
    for lane in sorted({f.lane for f in spec.fields}):
        occupied = 0
        for fi, f in enumerate(spec.fields):
            if f.lane != lane:
                continue
            fmask = 0
            fm = (1 << f.width) - 1
            for m in range(R):
                fmask |= fm << (f.shift + m * f.stride)
            if occupied & fmask:
                return _finding(
                    name, "group-closure", False,
                    f"fields overlap on lane {lane} (field {fi}: "
                    f"shift={f.shift} stride={f.stride} "
                    f"width={f.width} collides with an earlier "
                    "field's member bits) — the member relabeling "
                    "is not a bijection, so the rewrite set is not "
                    "a permutation-group action on the limb layout",
                    scope="symmetry", lane=lane,
                )
            occupied |= fmask
    return _finding(
        name, "group-closure", True,
        f"permutation-group action over {R} members proven: "
        "structural bounds hold and all member fields are pairwise "
        "bit-disjoint (bijective relabeling)",
        scope="symmetry",
    )


def _check_orbit_structure(name, enc, spec, rows) -> Finding:
    from ..ops.canonical import canonicalize_rows

    non_keys = [i for i, f in enumerate(spec.fields)
                if not f.sort_key]
    if non_keys:
        return _finding(
            name, "orbit-structure", False,
            f"fields {non_keys} are not in the sort key — a partial "
            "key is not constant on orbits, so the visited count "
            "becomes search-order-dependent (symmetry.py); declare "
            "the FULL per-member tuple as the key",
            scope="symmetry",
        )
    canon = canonicalize_rows(spec, rows, np)
    again = canonicalize_rows(spec, canon, np)
    if not np.array_equal(canon, again):
        bad = int(np.nonzero(
            (canon != again).any(axis=-1)
        )[0][0])
        return _finding(
            name, "orbit-structure", False,
            "canonicalization is not idempotent (battery row "
            f"{bad}: canon(canon(v)) != canon(v)) — the orbit map "
            "has no well-defined representatives",
            scope="symmetry", row=bad,
        )
    gm = _group_mask(spec, enc.width)
    for i in range(rows.shape[0]):
        if not np.array_equal(rows[i] & ~gm, canon[i] & ~gm):
            return _finding(
                name, "orbit-structure", False,
                f"canonicalization changed non-group bits on "
                f"battery row {i} — the rewrite leaks outside the "
                "declared member fields",
                scope="symmetry", row=i,
            )
        if sorted(_member_tuples(spec, rows[i])) != sorted(
            _member_tuples(spec, canon[i])
        ):
            return _finding(
                name, "orbit-structure", False,
                f"canonical form of battery row {i} is not a member "
                "permutation of the row (member-tuple multiset "
                "changed) — orbits are malformed over the declared "
                "field table",
                scope="symmetry", row=i,
            )
    return _finding(
        name, "orbit-structure", True,
        "well-formed orbit structure proven on the battery: full "
        "sort key, idempotent canonicalization, member-tuple "
        "multiset preserved, non-group bits untouched",
        scope="symmetry", battery_rows=int(rows.shape[0]),
    )


def _check_fingerprint_invariance(name, enc, spec, rows) -> Finding:
    from ..ops.canonical import canonicalize_rows

    base = canonicalize_rows(spec, rows, np)
    for g in _generators(spec.n_members):
        permuted = canonicalize_rows(
            spec, apply_member_permutation(spec, rows, g), np
        )
        if not np.array_equal(base, permuted):
            bad = int(np.nonzero(
                (base != permuted).any(axis=-1)
            )[0][0])
            return _finding(
                name, "fingerprint-invariance", False,
                f"canonical form (the fingerprint field-selection) "
                f"is NOT invariant under member transposition "
                f"{g} (battery row {bad}) — two states of one orbit "
                "fingerprint differently and the visited set "
                "under-merges",
                scope="symmetry", generator=list(g), row=bad,
            )
    return _finding(
        name, "fingerprint-invariance", True,
        "canonical form invariant under every generator "
        "transposition — orbit members share one fingerprint",
        scope="symmetry",
    )


def _check_property_invariance(name, enc, spec, rows,
                               prop_names, prop_fps) -> Finding:
    # static: each property's read footprint must be member-uniform
    # over every spec field — reading member 0's sub-field without
    # the others' is the asymmetric-predicate defect.
    for p, fp in zip(prop_names, prop_fps):
        for fi, f in enumerate(spec.fields):
            fm = (1 << f.width) - 1
            subs = [
                (int(fp[f.lane]) >> (f.shift + m * f.stride)) & fm
                for m in range(spec.n_members)
            ]
            if len(set(subs)) > 1:
                readers = [m for m, s in enumerate(subs) if s]
                return _finding(
                    name, "property-invariance", False,
                    f"property {p!r} reads member field {fi} "
                    f"asymmetrically (members {readers} of "
                    f"{spec.n_members} in its bit footprint) — the "
                    "predicate is not group-invariant, so quotient "
                    "counts would silently drop its witnesses",
                    scope="symmetry", property=p, field=fi,
                    members=readers,
                )
    # semantic: P(tau . v) == P(v) on the battery
    import jax
    import jax.numpy as jnp

    ref = np.asarray(jax.vmap(enc.property_conditions_vec)(
        jnp.asarray(rows)
    ))
    for g in _generators(spec.n_members):
        got = np.asarray(jax.vmap(enc.property_conditions_vec)(
            jnp.asarray(apply_member_permutation(spec, rows, g))
        ))
        if not np.array_equal(ref, got):
            bad = np.nonzero((ref != got).any(axis=-1))[0]
            pidx = int(np.nonzero(
                (ref[bad[0]] != got[bad[0]])
            )[0][0])
            return _finding(
                name, "property-invariance", False,
                f"property {prop_names[pidx]!r} changes truth "
                f"value under member transposition {g} (battery "
                f"row {int(bad[0])}) — not group-invariant",
                scope="symmetry", property=prop_names[pidx],
                generator=list(g),
            )
    return _finding(
        name, "property-invariance", True,
        f"all {len(prop_names)} properties group-invariant: "
        "member-uniform static footprints and semantic agreement "
        "under every generator",
        scope="symmetry", properties=list(prop_names),
    )


def _step_all(enc, rows):
    import jax
    import jax.numpy as jnp

    res = jax.vmap(enc.step_vec)(jnp.asarray(rows))
    succs = np.asarray(res[0])
    valids = np.asarray(res[1])
    return succs, valids


def _check_transition_equivariance(name, enc, spec, rows) -> Finding:
    succs, valids = _step_all(enc, rows)
    for g in _generators(spec.n_members):
        prows = apply_member_permutation(spec, rows, g)
        psuccs, pvalids = _step_all(enc, prows)
        for i in range(rows.shape[0]):
            a = apply_member_permutation(
                spec, succs[i][valids[i]], g
            )
            b = psuccs[i][pvalids[i]]
            a_sorted = sorted(map(tuple, a.tolist()))
            b_sorted = sorted(map(tuple, b.tolist()))
            if a_sorted != b_sorted:
                return _finding(
                    name, "transition-equivariance", False,
                    f"successor set does not commute with member "
                    f"transposition {g} on battery row {i}: "
                    "tau(succ(v)) != succ(tau(v)) as multisets — "
                    "the quotient graph is not the graph of the "
                    "quotient",
                    scope="symmetry", generator=list(g), row=i,
                )
    return _finding(
        name, "transition-equivariance", True,
        "successor sets commute with every generator transposition "
        "on the battery",
        scope="symmetry", battery_rows=int(rows.shape[0]),
    )


def _mask_bits(mask) -> list:
    out = []
    for lane in range(len(mask)):
        mm = int(mask[lane])
        for j in range(32):
            if (mm >> j) & 1:
                out.append((lane, j))
    return out


def _guard_values(enc, rows, slots) -> np.ndarray:
    """bool[rows, slots] — the packed guard words evaluated on each
    row, extracted at the listed slots."""
    import jax
    import jax.numpy as jnp

    words = np.asarray(jax.vmap(enc.enabled_bits_vec)(
        jnp.asarray(rows)
    ))
    out = np.zeros((rows.shape[0], len(slots)), bool)
    for i, s in enumerate(slots):
        out[:, i] = (words[:, s // 32] >> (s % 32)) & 1
    return out


def _cone_rows(enc, cone_bits, rng) -> tuple:
    """Assignment rows over a footprint cone: exhaustive when small
    (the guards provably depend on no other bits, so one zero
    background decides the implication), sampled otherwise."""
    W = enc.width
    if len(cone_bits) <= _EXHAUSTIVE_CONE_BITS:
        n = 1 << len(cone_bits)
        rows = np.zeros((n, W), np.uint32)
        for i in range(n):
            for j, (lane, b) in enumerate(cone_bits):
                if (i >> j) & 1:
                    rows[i, lane] |= np.uint32(1) << np.uint32(b)
        return rows, "exhaustive"
    rows = np.zeros((_SAMPLE_ROWS, W), np.uint32)
    picks = rng.integers(
        0, 2, size=(_SAMPLE_ROWS, len(cone_bits)), dtype=np.uint64
    )
    for j, (lane, b) in enumerate(cone_bits):
        rows[:, lane] |= (
            picks[:, j].astype(np.uint32) << np.uint32(b)
        )
    return rows, "sampled"


def _check_ample_enabledness(name, enc, mask_words,
                             guard_fps) -> Finding:
    K = enc.max_actions
    dropped = [
        k for k in range(K)
        if not (int(mask_words[k // 32]) >> (k % 32)) & 1
    ]
    kept = [
        k for k in range(K)
        if (int(mask_words[k // 32]) >> (k % 32)) & 1
    ]
    rng = np.random.default_rng(0xA3B1E)
    methods = set()
    for d in dropped:
        fpd = guard_fps[d]
        # candidates ordered by guard-footprint overlap with the
        # dropped slot (identical footprints first: 2pc's
        # rm_prepare shares choose_abort's guard exactly)
        ranked = sorted(
            kept,
            key=lambda k: (
                not np.array_equal(guard_fps[k], fpd),
                -int(sum(
                    bin(int(guard_fps[k][w] & fpd[w])).count("1")
                    for w in range(enc.width)
                )),
                k,
            ),
        )
        proven = False
        for k in ranked[:8]:
            cone = _mask_bits(fpd | guard_fps[k])
            rows, method = _cone_rows(enc, cone, rng)
            g = _guard_values(enc, rows, [d, k])
            if not np.any(g[:, 0] & ~g[:, 1]):
                methods.add(method)
                proven = True
                break
        if not proven:
            return _finding(
                name, "ample-enabledness", False,
                f"dropped slot {d} can be enabled while NO kept "
                "slot implied by its guard is (no kept slot k with "
                "g_d => g_k over the guard footprint cone) — the "
                "filtered search can stall in a state the full "
                "search would leave (enabledness preservation "
                "fails)",
                scope="ample", slot=d,
            )
    return _finding(
        name, "ample-enabledness", True,
        f"enabledness preserved: each of the {len(dropped)} "
        "dropped slots implies a kept slot's guard over its "
        "footprint cone",
        scope="ample", dropped=dropped,
        method=sorted(methods) or ["exhaustive"],
    )


def _step_slot_batch(enc, rows, slot: int) -> np.ndarray:
    import jax
    import jax.numpy as jnp

    from ..encoding import normalize_step_slot_result

    res = jax.vmap(
        lambda v: enc.step_slot_vec(v, jnp.uint32(slot))
    )(jnp.asarray(rows))
    succ, _t, _h = normalize_step_slot_result(res)
    return np.asarray(succ)


def _check_ample_non_suppression(name, enc, spec, mask_words,
                                 guard_fps, prop_fps,
                                 rows) -> Finding:
    K = enc.max_actions
    dropped = [
        k for k in range(K)
        if not (int(mask_words[k // 32]) >> (k % 32)) & 1
    ]
    kept = [
        k for k in range(K)
        if (int(mask_words[k // 32]) >> (k % 32)) & 1
    ]
    prop_read = np.zeros(enc.width, np.uint32)
    for fp in prop_fps:
        prop_read |= fp
    perms = [tuple(range(spec.n_members))] + _transpositions(
        spec.n_members
    ) if spec is not None else [()]
    write_fps: dict = {}

    def wfp(slot):
        if slot not in write_fps:
            write_fps[slot] = step_slot_footprints(enc, slot)[0]
        return write_fps[slot]

    guards_b = _guard_values(enc, rows, list(range(K)))
    relevant = [
        d for d in dropped if np.any(wfp(d) & prop_read)
    ]
    for d in relevant:
        ok = False
        ranked = sorted(
            kept,
            key=lambda k: (
                not np.array_equal(guard_fps[k], guard_fps[d]), k
            ),
        )
        succ_d = None
        for k in ranked:
            for pi in perms:
                if spec is not None and not np.array_equal(
                    permute_mask(spec, wfp(k), pi), wfp(d)
                ):
                    continue
                if spec is None and not np.array_equal(
                    wfp(k), wfp(d)
                ):
                    continue
                prows = (
                    apply_member_permutation(spec, rows, pi)
                    if spec is not None else rows
                )
                g_k = _guard_values(enc, prows, [k])[:, 0]
                if not np.array_equal(guards_b[:, d], g_k):
                    continue
                en = np.nonzero(guards_b[:, d])[0]
                if en.size == 0:
                    ok = True
                    break
                if succ_d is None:
                    succ_d = _step_slot_batch(enc, rows[en], d)
                succ_k = _step_slot_batch(enc, prows[en], k)
                mapped = (
                    apply_member_permutation(spec, succ_k, pi)
                    if spec is not None else succ_k
                )
                if np.array_equal(succ_d, mapped):
                    ok = True
                    break
            if ok:
                break
        if not ok:
            return _finding(
                name, "ample-non-suppression", False,
                f"dropped slot {d} is property-relevant (its write "
                "footprint meets a property read footprint) and has "
                "NO symmetric kept image — no kept slot k and group "
                "element pi with g_d(v) == g_k(pi.v) and succ_d(v) "
                "== pi.succ_k(pi.v) on the battery — the mask "
                "suppresses an enabled property-relevant transition",
                scope="ample", slot=d,
            )
    return _finding(
        name, "ample-non-suppression", True,
        f"{len(relevant)} property-relevant dropped slots each have "
        "a symmetric kept image (guard and successor agree under a "
        "group element on the battery)",
        scope="ample", relevant=relevant, dropped=dropped,
    )


# -- certification ----------------------------------------------------------

@dataclass
class SoundnessResult:
    """The certificate for one encoding's declared reductions."""

    encoding: str
    #: None when the encoding declares no DeviceRewriteSpec
    sym_certified: Optional[bool]
    #: None when the encoding declares no ample mask
    ample_certified: Optional[bool]
    obligations: list = field(default_factory=list)
    #: primitives the abstract interpreter over-approximated
    collapsed: list = field(default_factory=list)
    analyzer_sec: float = 0.0

    @property
    def certified(self) -> bool:
        return (self.sym_certified is not False
                and self.ample_certified is not False)

    def failed(self, scope: Optional[str] = None):
        """The first failed obligation Finding (optionally within one
        scope), or None."""
        for f in self.obligations:
            if f.severity != "error":
                continue
            if scope is None or f.data.get("scope") == scope:
                return f
        return None

    def as_dict(self) -> dict:
        return dict(
            encoding=self.encoding,
            status="certified" if self.certified else "refused",
            symmetry=self.sym_certified,
            ample=self.ample_certified,
            analyzer_sec=round(self.analyzer_sec, 4),
            collapsed_primitives=sorted(set(self.collapsed)),
            obligations=[f.as_dict() for f in self.obligations],
        )


def certify_encoding(enc, use_cache: bool = True) -> SoundnessResult:
    """Run every applicable obligation over one encoding. Memoized on
    the encoding class + cache_key (the engines' spawn gates run per
    checker construction); pass ``use_cache=False`` to re-measure
    ``analyzer_sec``."""
    cls = type(enc)
    ck = getattr(enc, "cache_key", None)
    key = (
        cls.__module__, cls.__qualname__,
        ck() if callable(ck) else (enc.width, enc.max_actions),
    )
    if use_cache and key in _CERT_CACHE:
        return _CERT_CACHE[key]
    t0 = time.perf_counter()
    name = _enc_name(enc)
    obligations: list = []
    collapsed: list = []

    try:
        spec = _probe_spec(enc)
        spec_error = None
    except ValueError as e:
        spec, spec_error = None, e
    mask = _probe_ample(enc)

    sym_certified: Optional[bool] = None
    if spec_error is not None:
        obligations.append(_finding(
            name, "group-closure", False,
            f"structural validation failed: {spec_error}",
            scope="symmetry",
        ))
        sym_certified = False
    elif spec is not None:
        f = _check_group_closure(name, enc, spec)
        obligations.append(f)
        if f.severity == "error":
            sym_certified = False
        else:
            pnames, pfps, pinterp, _ = property_footprints(enc)
            collapsed += pinterp.collapsed
            rows = battery_rows(enc, spec, pfps)
            checks = [
                _check_orbit_structure(name, enc, spec, rows),
                _check_fingerprint_invariance(
                    name, enc, spec, rows
                ),
                _check_property_invariance(
                    name, enc, spec, rows, pnames, pfps
                ),
                _check_transition_equivariance(
                    name, enc, spec, rows
                ),
            ]
            obligations += checks
            sym_certified = all(
                c.severity != "error" for c in checks
            )

    ample_certified: Optional[bool] = None
    if mask is not None:
        if not hasattr(enc, "enabled_bits_vec") or not hasattr(
            enc, "step_slot_vec"
        ):
            obligations.append(_finding(
                name, "ample-enabledness", False,
                "ample mask declared but the encoding has no sparse "
                "dispatch path (enabled_bits_vec/step_slot_vec) — "
                "the guard obligations cannot be stated, let alone "
                "proven",
                scope="ample",
            ))
            ample_certified = False
        else:
            gfps, ginterp, _ = guard_footprints(enc)
            collapsed += ginterp.collapsed
            pnames, pfps, pinterp, _ = property_footprints(enc)
            collapsed += pinterp.collapsed
            rows = battery_rows(
                enc, spec if sym_certified else None,
                list(pfps) + list(gfps),
            )
            f1 = _check_ample_enabledness(name, enc, mask, gfps)
            obligations.append(f1)
            f2 = _check_ample_non_suppression(
                name, enc, spec if sym_certified else None, mask,
                gfps, pfps, rows,
            )
            obligations.append(f2)
            ample_certified = (
                f1.severity != "error" and f2.severity != "error"
            )

    res = SoundnessResult(
        encoding=name,
        sym_certified=sym_certified,
        ample_certified=ample_certified,
        obligations=obligations,
        collapsed=sorted(set(collapsed)),
        analyzer_sec=time.perf_counter() - t0,
    )
    if use_cache:
        _CERT_CACHE[key] = res
    return res


def soundness_status(enc) -> Optional[bool]:
    """Best-effort certificate status for telemetry lane configs:
    True/False when the analyzer ran, None when it cannot (no
    declared reductions, or the analysis itself raised — telemetry
    must never take an engine down)."""
    try:
        res = certify_encoding(enc)
    except Exception:
        return None
    if res.sym_certified is None and res.ample_certified is None:
        return None
    return res.certified


# -- the engine gates -------------------------------------------------------

def gate_symmetry(enc, engine: str,
                  unsound_ok: bool = False) -> bool:
    """Spawn-time certificate gate for ``--symmetry``: returns True
    when the declared ``DeviceRewriteSpec`` is certified, False when
    uncertified but ``unsound_ok`` waives the refusal, and raises the
    unified :func:`checkers.common.soundness_refusal` otherwise."""
    from ..checkers.common import soundness_refusal

    res = certify_encoding(enc)
    if res.sym_certified is not False:
        return True
    if unsound_ok:
        return False
    f = res.failed("symmetry")
    raise soundness_refusal(
        engine, "symmetry", f.rule if f else "group-closure",
        f.message if f else "uncertified spec",
    )


def gate_ample(enc, engine: str, unsound_ok: bool = False) -> bool:
    """Spawn-time certificate gate for ``--ample-set`` (same contract
    as :func:`gate_symmetry`, ample scope)."""
    from ..checkers.common import soundness_refusal

    res = certify_encoding(enc)
    if res.ample_certified is not False:
        return True
    if unsound_ok:
        return False
    f = res.failed("ample")
    raise soundness_refusal(
        engine, "ample-set", f.rule if f else "ample-enabledness",
        f.message if f else "uncertified mask",
    )


# -- the artifact + CLI -----------------------------------------------------

def write_soundness_artifact(results, root=None) -> str:
    """``SOUND_rNN.json`` in the LINT_r*/COMM_r* shape conventions:
    own round sequence, clean flag, provenance block, per-spec
    certificates."""
    from ..artifacts import artifact_path, next_round, provenance

    path = artifact_path(
        "SOUND", root=root,
        round=next_round(root, stems=("SOUND",)),
    )
    report = {
        "schema": "soundness-cert/v1",
        "clean": all(r.certified for r in results),
        "specs": {r.encoding: r.as_dict() for r in results},
        "provenance": provenance(
            lane={"analyzer": "analysis/soundness.py"}
        ),
    }
    with open(path, "w") as fh:
        json.dump(report, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


def analyze_main(argv) -> int:
    """``stateright_tpu analyze soundness [MODEL] [COUNT]
    [--no-artifact]`` — certify the registered soundness targets (or
    one model) and write ``SOUND_rNN.json``. Exit 0 when every
    checked spec certifies, 1 otherwise."""
    argv = list(argv)
    if not argv or argv[0] != "soundness":
        print(
            "usage: stateright_tpu analyze soundness "
            "[MODEL] [COUNT] [--no-artifact]\n"
            "  MODEL: one of the registered soundness targets "
            "(analysis/registry.SOUNDNESS_TARGETS); default all"
        )
        return 2
    rest = argv[1:]
    no_artifact = "--no-artifact" in rest
    rest = [a for a in rest if a != "--no-artifact"]
    model = rest[0] if rest else None
    count = int(rest[1]) if len(rest) > 1 else None

    from .registry import SOUNDNESS_TARGETS

    targets = [
        (tname, factory) for tname, factory in SOUNDNESS_TARGETS
        if model is None or tname == model
    ]
    if not targets:
        known = [t for t, _ in SOUNDNESS_TARGETS]
        print(f"unknown model {model!r}; targets: {known}")
        return 2
    results = []
    for tname, factory in targets:
        enc = factory(count) if count is not None else factory(None)
        res = certify_encoding(enc, use_cache=False)
        results.append(res)
        status = "certified" if res.certified else "REFUSED"
        print(
            f"{tname} ({res.encoding}): {status} "
            f"[{res.analyzer_sec:.2f}s]"
        )
        for f in res.obligations:
            mark = "ok " if f.severity == "info" else "FAIL"
            print(f"  {mark} {f.rule}: {f.message}")
        if res.collapsed:
            print(
                "  over-approximated primitives: "
                f"{res.collapsed}"
            )
    if not no_artifact:
        path = write_soundness_artifact(results)
        print(f"wrote {os.path.basename(path)}")
    return 0 if all(r.certified for r in results) else 1
