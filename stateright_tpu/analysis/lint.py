"""The kernel-lint driver: trace every contract path, run the rules.

For each registered encoding (:mod:`.registry`) the driver traces

* ``bits`` — ``vmap(enabled_bits_vec)``: the word-native mask path
  the sparse engines consume,
* ``bits[t]`` — the TRANSPOSED invocation of the same path
  (``enabled_bits_cols`` over a ``[W, N]`` block — the round-9
  resident layout, registry.TRANSPOSED_PATHS), same rules and
  allowances,
* ``mask`` — ``vmap(enabled_mask_vec)``: the dense contract view
  (bool[K] IS its return type, so the dense-mask rule is off; the
  gather rule still applies),
* ``step`` — ``vmap(step_slot_vec)``: the per-pair transition path,
* ``step[t]`` / ``step[t1]`` — the transposed-successor pair step
  in BOTH backend seams (``step_slot_cols_fn``: row states in for
  the TPU invocation, ``[W, N]`` column states in for the XLA:CPU
  one; ``[W, N]`` successors out either way), same table-gather
  allowance,
* ``engine:single`` — the shared sparse pair pipeline
  (checkers/tpu_sortmerge.py ``sparse_pair_candidates``) exactly as
  the single-chip engine invokes it — with the ``[W, N]`` frontier
  (registry.ENGINE_LAYOUT),
* ``engine:sharded`` — the same pipeline under ``shard_map`` with
  ``axis_name="shard"``, exactly as the sharded engine
  (parallel/engine_sortmerge.py) invokes it,

and runs the full rule registry (:mod:`.rules`) over each. Separate
wave-body fixtures trace each engine's ENTIRE per-wave program
(class-ladder switch included) on a small 2pc model so the
branch-shape rule and the carry-copy-bytes estimator see the real
switch structure — the thing the per-path traces can't show: the
single-chip body once per merge implementation, and (round 11) the
SHARDED body in its TRACED form, so the per-shard mesh-log append
(``slog``/``swave``, telemetry.SHARD_LOG_FIELDS) is priced by the
same five gated rules and the carry-copy budget.

Everything here runs on CPU: jaxprs are backend-independent, which is
what lets a CPU-only CI run refuse an encoding or engine change that
re-introduces a priced codegen artifact before it ever reaches a
chip.
"""

from __future__ import annotations

from typing import Optional

from .registry import ENCODINGS, EncodingSpec
from .rules import (
    RULES,
    Finding,
    TraceCtx,
    run_rules,  # noqa: F401 — re-exported for single-path callers
    run_rules_with_stats,
)

#: batch rows in every traced vmap / engine trace — any fixed N works
#: (the banned shapes are N-relative); 64 matches the codegen-shape
#: tests' calibration.
LINT_N = 64


def trace_encoding_paths(enc, n: int = LINT_N) -> dict:
    """``{label: ClosedJaxpr}`` for the per-encoding contract paths,
    traced at ``n`` batch rows — the row-major contract views (bits /
    mask / step) AND the transposed ``[W, N]`` invocations the engines
    actually run (``bits[t]`` / ``step[t]``, registry.TRANSPOSED_PATHS
    — the round-9 resident layout; same rules, same allowances)."""
    import jax
    import jax.numpy as jnp

    from ..encoding import enabled_bits_cols, step_slot_cols_fn

    vecs = jnp.zeros((n, enc.width), jnp.uint32)
    vecs_t = jnp.zeros((enc.width, n), jnp.uint32)
    slots = jnp.zeros((n,), jnp.uint32)
    return {
        "bits": jax.make_jaxpr(jax.vmap(enc.enabled_bits_vec))(vecs),
        "bits[t]": jax.make_jaxpr(
            lambda v: enabled_bits_cols(enc, v)
        )(vecs_t),
        "mask": jax.make_jaxpr(jax.vmap(enc.enabled_mask_vec))(vecs),
        "step": jax.make_jaxpr(jax.vmap(enc.step_slot_vec))(
            vecs, slots
        ),
        # BOTH backend seams of the transposed pair step: states_axis
        # 0 is the TPU invocation (row states off the seam-transpose
        # gather), states_axis 1 the XLA:CPU one (resident columns
        # gathered directly) — the engines pick per backend
        # (tpu_sortmerge/engine_sortmerge), so the gate must pin both.
        "step[t]": jax.make_jaxpr(
            step_slot_cols_fn(enc, states_axis=0)
        )(vecs, slots),
        "step[t1]": jax.make_jaxpr(
            step_slot_cols_fn(enc, states_axis=1)
        )(vecs_t, slots),
    }


def _shard_map_1dev(fn, in_specs):
    """Wrap ``fn`` in ``shard_map`` over a 1-device mesh with
    ``axis_name="shard"`` — the sharded engines' axis plumbing, which
    is what a sharded trace pins (feature-detecting the check_rep /
    check_vma kwarg rename across jax versions)."""
    import inspect

    import jax
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map

    kw = {}
    try:
        sm_params = inspect.signature(shard_map).parameters
        if "check_rep" in sm_params:
            kw["check_rep"] = False
        elif "check_vma" in sm_params:
            kw["check_vma"] = False
    except (TypeError, ValueError):
        kw["check_rep"] = False

    mesh = Mesh(np.array(jax.devices()[:1]), ("shard",))
    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=P(), **kw
    )


def trace_canonical_paths(enc, n: int = LINT_N) -> dict:
    """``{label: ClosedJaxpr}`` of the symmetry-canonicalization
    kernel paths (registry.CANONICAL_PATHS) — empty when the encoding
    declares no ``DeviceRewriteSpec``, so the audit is gated on the
    SAME capability probe the engines use. Three invocation styles:
    row-major ``canonicalize_rows`` (the host-replay contract view),
    the transposed ``canonicalize_t`` over ``[W, N]`` the engines run
    between step and fingerprint (``canon[t]``), and that invocation
    under ``shard_map`` (``canon:sharded`` — the sharded engine
    canonicalizes before the (owner, fp) routing seam)."""
    import jax
    import jax.numpy as jnp

    from ..encoding import device_rewrite_spec
    from ..ops.canonical import canonicalize_rows, canonicalize_t

    spec = device_rewrite_spec(enc)
    if spec is None:
        return {}
    from jax.sharding import PartitionSpec as P

    rows = jnp.zeros((n, enc.width), jnp.uint32)
    cols = jnp.zeros((enc.width, n), jnp.uint32)
    return {
        "canon": jax.make_jaxpr(
            lambda r: canonicalize_rows(spec, r, jnp)
        )(rows),
        "canon[t]": jax.make_jaxpr(
            lambda c: canonicalize_t(spec, c, jnp)
        )(cols),
        "canon:sharded": jax.make_jaxpr(
            _shard_map_1dev(
                lambda c: canonicalize_t(spec, c, jnp), (P(),)
            )
        )(cols),
    }


def engine_pair_width(enc) -> int:
    K = enc.max_actions
    return min(getattr(enc, "pair_width_hint", None) or K, K)


def engine_pipe_params(enc, n: int = LINT_N,
                       compact: bool = False) -> dict:
    """The ``sparse_pair_candidates`` kwargs of the traced engine
    invocation — ONE recipe shared by the jaxpr traces below and the
    tool's ``--hlo`` compile pass, so the two always price the same
    program.

    ``compact=False`` is the small-wave shape (``B_p == F*EV``, no
    compaction, whole-wave mask); ``compact=True`` forces the
    PRODUCTION branches the big bench lanes run — ``B_p < F*EV``
    (tiled packed-append compaction sorts) and a mask-cell budget
    below ``F*K`` (the tiled ``mtile`` mask loop) — which would
    otherwise never be audited."""
    EV = engine_pair_width(enc)
    K = enc.max_actions
    if compact:
        NT = 2
        T = n // NT
        B_p = max((n * EV) // 2, 1)
        return dict(
            EV=EV, B_p=B_p, NT=NT, T=T,
            mask_budget_cells=max(K, (n * K) // 4),
            Ba=B_p + T * EV,
        )
    return dict(
        EV=EV, B_p=n * EV, NT=1, T=n,
        mask_budget_cells=1 << 30, Ba=n * EV,
    )


def engine_trace_operands(enc, n: int = LINT_N) -> tuple:
    """``(frontier, fval, n_rows)`` of the traced engine invocation —
    the FULL resident ``[W, 2n]`` carry buffer with the class width
    ``n`` passed explicitly via ``n_rows``, exactly as both engines
    call ``sparse_pair_candidates`` since round 9 (capacity > class
    width on any real run, so the gated jaxpr must slice the larger
    buffer too: a codegen artifact specific to the n_rows path — a
    materialized strided-prefix copy, say — has to show up HERE, not
    first on a chip). Shared by the jaxpr traces and the tool's
    ``--hlo`` compile pass."""
    import jax.numpy as jnp

    frontier = jnp.zeros((enc.width, 2 * n), jnp.uint32)
    fval = jnp.zeros((n,), bool)
    return frontier, fval, n


def trace_engine_pipeline(enc, engine: str = "single",
                          n: int = LINT_N, compact: bool = False):
    """Trace ``sparse_pair_candidates`` at ``n`` frontier rows, in the
    exact invocation style of each engine: ``single`` is the
    single-chip call; ``sharded`` wraps the call in ``shard_map`` with
    ``axis_name="shard"`` over a 1-device mesh (the axis plumbing —
    ``lax.pvary`` carries etc. — is what differs, and is what this
    trace pins). ``compact`` selects the production
    compaction/tiled-mask branches (see :func:`engine_pipe_params`)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..checkers.tpu_sortmerge import sparse_pair_candidates

    params = engine_pipe_params(enc, n, compact)
    # The [W, N] resident layout (registry.ENGINE_LAYOUT): the traced
    # pipeline IS the engines' transposed invocation — full carry
    # buffer, class width via n_rows (engine_trace_operands).
    frontier, fval, n_rows = engine_trace_operands(enc, n)

    def pipe(frontier_t, fval, axis_name=None):
        return sparse_pair_candidates(
            enc, frontier_t, fval, jnp.bool_(True),
            axis_name=axis_name, n_rows=n_rows, **params,
        )
    if engine == "single":
        return jax.make_jaxpr(pipe)(frontier, fval)
    if engine != "sharded":
        raise ValueError(f"unknown engine {engine!r}")

    import inspect

    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map

    # The replication checker must be off (the pipeline's fori_loop
    # carries are shard-varying, same as the engine's own usage), but
    # the kwarg was renamed check_rep -> check_vma across jax
    # versions — feature-detect rather than assume.
    kw = {}
    try:
        sm_params = inspect.signature(shard_map).parameters
        if "check_rep" in sm_params:
            kw["check_rep"] = False
        elif "check_vma" in sm_params:
            kw["check_vma"] = False
    except (TypeError, ValueError):
        kw["check_rep"] = False

    mesh = Mesh(np.array(jax.devices()[:1]), ("shard",))
    sm = shard_map(
        lambda fr, fv: pipe(fr, fv, axis_name="shard"),
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=P(),
        **kw,
    )
    return jax.make_jaxpr(sm)(frontier, fval)


def trace_wave_body_fixture(track_paths: bool = True,
                            merge_impl: str = "xla"):
    """``(name, ClosedJaxpr)`` of the single-chip sort-merge engine's
    full wave body — class ladders, merge switches, fetch-class
    branches — built (never run) on a small 2pc model with short
    ladders so the switch structure is multi-class. Abstract-traced
    via ``eval_shape`` on the seed program, so no device buffers are
    allocated. ``merge_impl`` selects the visited-dedup invocation
    style (round 10): the gate traces the wave body once per
    implementation so the branch rules and the carry-copy budget
    price both the XLA-fallback and the Pallas-kernel wave programs
    (tables.CARRY_COPY_BYTE_BUDGETS keys both names)."""
    import jax
    import jax.numpy as jnp

    from ..models.two_phase_commit import TwoPhaseSys

    checker = TwoPhaseSys(rm_count=3).checker().spawn_tpu_sortmerge(
        capacity=1 << 11,
        frontier_capacity=1 << 9,
        cand_capacity=1 << 11,
        f_min=64,
        v_min=256,
        track_paths=track_paths,
        waves_per_sync=4,
        merge_impl=merge_impl,
    )
    init = jnp.asarray(checker.encoded.init_vecs())
    seed_fn, _chunk_fn = checker._build_programs(init.shape[0])
    carry_shapes = jax.eval_shape(seed_fn, init)
    tag = "" if merge_impl == "xla" else f",merge={merge_impl}"
    return (
        f"engine-fixture(2pc-rm3{tag})",
        jax.make_jaxpr(checker._wave_body)(carry_shapes),
    )


def trace_sharded_wave_body_fixture(track_paths: bool = True):
    """``(name, ClosedJaxpr)`` of the SHARDED sort-merge engine's full
    wave body — the routing sort, dest tiles, ``all_to_all``, merge
    switches — in its TRACED form (round 11): the per-shard mesh log
    (``slog``/``swave``, telemetry.SHARD_LOG_FIELDS) is part of the
    program, so the five gated rules and the carry-copy-bytes budget
    price the log-append path the mesh runs actually execute
    (registry.SHARDED_WAVE_BODY_FIXTURE keys the budget). Built on a
    1-device mesh (the axis plumbing, not the device count, is what
    the trace pins) with the same small 2pc model and short ladders
    as the single-chip fixture; abstract-traced via ``eval_shape`` on
    the seed program, so no buffers are allocated."""
    import jax
    import jax.numpy as jnp

    from ..models.two_phase_commit import TwoPhaseSys
    from .registry import SHARDED_WAVE_BODY_FIXTURE

    checker = TwoPhaseSys(rm_count=3).checker().spawn_tpu_sharded_sortmerge(
        n_shards=1,
        capacity=1 << 11,
        frontier_capacity=1 << 9,
        cand_capacity=1 << 11,
        bucket_capacity=1 << 10,
        f_min=64,
        v_min=256,
        track_paths=track_paths,
        waves_per_sync=4,
        merge_impl="xla",
    )
    # Force the traced program: the per-shard log path is the thing
    # this fixture registers (a truthy tracer stand-in flips the
    # _wave_log_enabled gate exactly as a real RunTracer would).
    checker._tracer = object()
    init = jnp.asarray(checker.encoded.init_vecs())
    seed_fn, _chunk_fn = checker._build_programs(init.shape[0])
    carry_shapes = jax.eval_shape(seed_fn, init)
    return (
        SHARDED_WAVE_BODY_FIXTURE,
        jax.make_jaxpr(checker._wave_body_sm)(carry_shapes),
    )


def trace_merge_kernels(n: int = LINT_N) -> dict:
    """``{label: ClosedJaxpr}`` of the streaming-merge dedup ops
    (registry.MERGE_KERNEL_PATHS): membership and visited append,
    each in both implementations, at a production-shaped fixture —
    a sorted 8n-row visited prefix, 4n sorted candidates, an n-row
    winner block (jaxprs are shape-relative, so any fixed multiple
    works; these mirror the engines' V ≫ B ≫ NF ordering). Pallas
    paths trace on CPU too (``pallas_call`` abstract-evals without
    running), so the CPU CI audits the kernel invocation the chip
    will run."""
    import jax
    import jax.numpy as jnp

    from ..ops.merge import member_sorted, merge_sorted, pallas_available

    V, B, NF = 8 * n, 4 * n, n
    a = (jnp.zeros(V, jnp.uint32), jnp.zeros(V, jnp.uint32))
    q = (jnp.zeros(B, jnp.uint32), jnp.zeros(B, jnp.uint32))
    w = (jnp.zeros(NF, jnp.uint32), jnp.zeros(NF, jnp.uint32))
    impls = ("xla",) + (("pallas",) if pallas_available() else ())
    out = {}
    for impl in impls:
        out[f"merge:member:{impl}"] = jax.make_jaxpr(
            lambda al, ah, ql, qh, _i=impl: member_sorted(
                al, ah, ql, qh, impl=_i
            )
        )(*a, *q)
        out[f"merge:append:{impl}"] = jax.make_jaxpr(
            lambda al, ah, bl, bh, _i=impl: merge_sorted(
                al, ah, bl, bh, impl=_i
            )
        )(*a, *w)
    return out


def lint_merge_kernels(n: int = LINT_N) -> tuple:
    """Run the rule registry over the merge-kernel invocations.
    Gathers are unaudited by design on these paths — the XLA
    fallback's vectorized binary search IS gathers, and the Pallas
    partition search is too; what the rules pin is the absence of
    dense masks and (on the XLA fallback) the 1-D lane discipline.
    The in-kernel [block, block] rank temporaries are the kernel's
    own idiom, so the lane-ALU rule stays off the pallas paths."""
    findings: list = []
    stats: list = []
    for label, closed in trace_merge_kernels(n).items():
        ctx = TraceCtx(
            path=label,
            encoding="ops/merge",
            n=n,
            k=0,
            sparse=False,
            allow_gathers=None,
            check_lane_alu=label.endswith(":xla"),
            check_branches=False,
        )
        fs, n_eqns = run_rules_with_stats(ctx, closed)
        findings.extend(fs)
        stats.append(
            dict(
                encoding="ops/merge",
                path=label,
                eqns=n_eqns,
                errors=sum(1 for f in fs if f.severity == "error"),
            )
        )
    return findings, stats


def _ctx_for_path(spec: EncodingSpec, enc, label: str,
                  n: int = LINT_N) -> TraceCtx:
    K = enc.max_actions
    if label in ("bits", "bits[t]"):
        # the transposed invocation runs the SAME rules at the same
        # allowances — the [W, N] batching must not re-grow a gather
        # or a lane-padded op the row-major view is pinned clean of.
        return TraceCtx(path=label, encoding=spec.name, n=n, k=K,
                        sparse=True, allow_gathers=0,
                        check_lane_alu=True)
    if label in ("canon", "canon[t]", "canon:sharded"):
        # the canonicalization kernel is held to the bits-path bar:
        # gather-free (rank via comparison counts + one-hot
        # select-sums — a permutation gather here is exactly the
        # priced artifact) and no lane-padded ALU; the sharded
        # invocation additionally runs the comms rules (the kernel is
        # collective-free by construction — canonicalization happens
        # BEFORE the routing seam, per shard, with no coordination).
        return TraceCtx(path=label, encoding=spec.name, n=n, k=K,
                        sparse=True, allow_gathers=0,
                        check_lane_alu=True,
                        check_comms=label == "canon:sharded")
    if label == "mask":
        # bool[K] is this path's CONTRACT (the dense view); only the
        # gather rule applies.
        return TraceCtx(path=label, encoding=spec.name, n=n, k=K,
                        sparse=False, allow_gathers=0,
                        check_lane_alu=False)
    if label in ("step", "step[t]", "step[t1]"):
        return TraceCtx(path=label, encoding=spec.name, n=n, k=K,
                        sparse=False,
                        allow_gathers=spec.max_step_gathers,
                        check_lane_alu=True, table_path=True)
    # engine pipelines: word ops are [N, L]-shaped by design (L == 1
    # collapses them to [N, 1] for small-K encodings), so the lane-ALU
    # rule stays off here; the dense-mask and gather bans are the
    # engine contract. The peel's [N, EV] pair-validity grid is by
    # design — when EV == K (tiny action sets) it is shape-identical
    # to the dense mask, so the dense-mask rule needs a real sparse
    # pair width (the same precondition the codegen-shape tests
    # calibrated). check_comms rides along (round 13): the pipeline
    # contains no collectives today, and the comms rules pin exactly
    # that — an all_gather sneaking in via sharding propagation (or a
    # buffer-sized psum added to the pair pipeline) fails here, not
    # first on a mesh.
    return TraceCtx(path=label, encoding=spec.name, n=n, k=K,
                    sparse=engine_pair_width(enc) < K,
                    allow_gathers=0, check_lane_alu=False,
                    check_comms=True)


def lint_encoding(spec: EncodingSpec,
                  engines: tuple = ("single", "sharded"),
                  n: int = LINT_N) -> tuple:
    """Run the rule registry over one encoding's contract paths.
    Returns ``(findings, path_stats)``."""
    enc = spec.factory()
    findings: list = []
    stats: list = []
    traced = trace_encoding_paths(enc, n)
    # capability-gated (registry.CANONICAL_PATHS): empty dict for
    # encodings without a DeviceRewriteSpec
    traced.update(trace_canonical_paths(enc, n))
    for engine in engines:
        # both the small-wave shape and the production
        # compaction/tiled-mask shape (the branch the big bench
        # lanes actually run) — see engine_pipe_params.
        traced[f"engine:{engine}"] = trace_engine_pipeline(
            enc, engine, n
        )
        traced[f"engine:{engine}+compact"] = trace_engine_pipeline(
            enc, engine, n, compact=True
        )
    for label, closed in traced.items():
        ctx = _ctx_for_path(spec, enc, label, n)
        fs, n_eqns = run_rules_with_stats(ctx, closed)
        if label.startswith("engine:") and not ctx.sparse:
            # EV == K: the peel's [N, EV] pair-validity grid is
            # shape-identical to the dense mask, so the dense-mask
            # rule cannot run on this path — record the skip loudly
            # instead of reporting an indistinguishable "0 errors"
            # (the coverage claim must stay honest).
            fs.append(Finding(
                rule="no-dense-mask",
                severity="info",
                encoding=spec.name,
                path=label,
                message=(
                    f"rule SKIPPED on this path: pair width EV == K "
                    f"= {enc.max_actions}, so the by-design [N, EV] "
                    "pair-validity grid is shape-identical to the "
                    "dense mask (the rule needs a real sparse pair "
                    "width; the bits-path audit still covers this "
                    "encoding's mask construction)"
                ),
            ))
        findings.extend(fs)
        stats.append(
            dict(
                encoding=spec.name,
                path=label,
                eqns=n_eqns,
                errors=sum(1 for f in fs if f.severity == "error"),
            )
        )
    return findings, stats


def lint_wave_body(merge_impl: str = "xla") -> tuple:
    """Run the branch-shape rule and the carry-copy-bytes estimator
    over the engine wave-body fixture (once per merge
    implementation; see trace_wave_body_fixture)."""
    name, closed = trace_wave_body_fixture(merge_impl=merge_impl)
    return _lint_traced_wave_body(name, closed)


def lint_sharded_wave_body() -> tuple:
    """Same rules over the sharded engine's TRACED wave body (the
    per-shard log path; see trace_sharded_wave_body_fixture)."""
    name, closed = trace_sharded_wave_body_fixture()
    return _lint_traced_wave_body(name, closed)


def _lint_traced_wave_body(name: str, closed) -> tuple:
    ctx = TraceCtx(
        path="wave-body",
        encoding=name,
        n=LINT_N,
        k=0,
        sparse=False,
        allow_gathers=None,  # winner-fetch gathers are the idiom
        check_lane_alu=False,
        check_branches=True,
    )
    findings, n_eqns = run_rules_with_stats(ctx, closed)
    stats = [
        dict(
            encoding=name,
            path="wave-body",
            eqns=n_eqns,
            errors=sum(1 for f in findings if f.severity == "error"),
        )
    ]
    return findings, stats


def run_lint(encodings: Optional[tuple] = None,
             engines: tuple = ("single", "sharded"),
             wave_body: bool = True,
             n: int = LINT_N) -> dict:
    """The whole gate: every registered encoding × the requested
    engine pipelines, plus the wave-body fixture. Returns a report
    dict (the ``--json`` artifact's content):

    ``clean``
        True iff no error-severity finding anywhere.
    ``findings``
        every finding (errors AND the informational carry-copy-bytes
        estimates), source-attributed.
    ``paths``
        per-(encoding, path) equation counts and error counts — the
        audit's coverage record.
    """
    specs = encodings if encodings is not None else ENCODINGS
    all_findings: list = []
    all_stats: list = []
    for spec in specs:
        fs, st = lint_encoding(spec, engines, n)
        all_findings.extend(fs)
        all_stats.extend(st)
    fs, st = lint_merge_kernels(n)
    all_findings.extend(fs)
    all_stats.extend(st)
    if wave_body:
        from ..ops.merge import pallas_available

        impls = ("xla",) + (
            ("pallas",) if pallas_available() else ()
        )
        for impl in impls:
            fs, st = lint_wave_body(merge_impl=impl)
            all_findings.extend(fs)
            all_stats.extend(st)
        # the sharded engine's TRACED wave body — the per-shard mesh
        # log path (round 11, registry.SHARDED_WAVE_BODY_FIXTURE)
        fs, st = lint_sharded_wave_body()
        all_findings.extend(fs)
        all_stats.extend(st)
    errors = [f for f in all_findings if f.severity == "error"]
    return dict(
        clean=not errors,
        n=n,
        engines=list(engines),
        rules=[
            dict(name=r.name, description=r.description)
            for r in RULES
        ],
        paths=all_stats,
        findings=[f.as_dict() for f in all_findings],
    )


def format_report(report: dict) -> str:
    """Human-readable lint report (tools/lint_kernels.py prints
    this)."""
    lines = []
    lines.append(
        f"kernel-lint: {len(report['rules'])} rules x "
        f"{len(report['paths'])} traced paths "
        f"(N={report['n']}, engines={'+'.join(report['engines'])})"
    )
    lines.append(f"  {'encoding':28s} {'path':24s} {'eqns':>6s} "
                 f"{'errors':>7s}")
    for p in report["paths"]:
        lines.append(
            f"  {p['encoding']:28s} {p['path']:24s} "
            f"{p['eqns']:6d} {p['errors']:7d}"
        )
    errors = [f for f in report["findings"]
              if f["severity"] == "error"]
    infos = [f for f in report["findings"] if f["severity"] == "info"]
    for f in errors:
        loc = f" @ {f['source']}" if f.get("source") else ""
        lines.append(
            f"ERROR [{f['rule']}] {f['encoding']} / {f['path']}: "
            f"{f['message']}{loc}"
        )
    for f in infos:
        lines.append(
            f"info  [{f['rule']}] {f['encoding']} / {f['path']}: "
            f"{f['message']}"
        )
    lines.append(
        "CLEAN — the sparse-engine codegen contract holds"
        if report["clean"]
        else f"{len(errors)} contract violation(s)"
    )
    return "\n".join(lines)
