"""The comms-lint driver: static collective accounting and
shard-safety verification over the sharded wave paths.

Second rule family of the kernel-lint framework (PR 3's walker + rule
registry + fixtures): where the codegen rules pin per-shard COMPUTE
shapes, the comms rules (rules.COMMS_RULES) pin the mesh's
COMMUNICATION contract — the invariants parallel/engine_sortmerge.py
documents in comments ("collectives are collective: every shard must
take the same switch branch or the all_to_all deadlocks"; the
all_to_all only ever fed from the (owner, fp) routing sort; psum'd
scalars only; no all_gather anywhere on a wave path), now
machine-checked on CPU before any chip time is spent.

Fixtures (``comms_fixture_params`` / :func:`trace_comms_fixture`):

* BOTH sharded engines' full wave bodies — the sort-merge engine
  (parallel/engine_sortmerge.py, routing SORT seam) and the hash
  engine (parallel/engine.py, owner-position SCATTER seam) — each in
  its traced (per-shard ``slog`` mesh log compiled in) and untraced
  form, on a real multi-shard mesh (S=2: the tile math, Bd cap and
  all_to_all shapes are shard-count-dependent, unlike the kernel
  lint's 1-device axis-plumbing fixture);
* the RECONCILIATION fixture: the sort-merge body at the exact
  ``dryrun_multichip`` 2pc rm=5 / S=8 / traced config TRACE_r16 was
  recorded under, so the static ``all_to_all_row_bytes`` in the COMM
  artifact is the number the committed trace's routed-rows counters
  multiply against (tests/test_comms_lint.py pins the product equals
  telemetry.shard_balance's ``routed_bytes_total`` exactly);
* every registry encoding's ``engine:sharded`` pair pipeline — zero
  collectives today, and the comms rules pin exactly that (an
  all_gather materialized by sharding propagation in a future
  encoding change fails here first).

The ``--hlo`` cross-check (:func:`hlo_collective_crosscheck`) compiles
a fixture's wave body on the live mesh and reconciles the optimized
module's collective ops (tables.parse_hlo_collectives — the SAME
category vocabulary as the jaxpr walk's COLLECTIVE_PRIMS) against the
jaxpr estimate: per-category op counts must match exactly; MORE HLO
collectives than the jaxpr accounts for means the SPMD partitioner
respecified something behind the walk's back and is a gated error,
fewer is an info (XLA folded a degenerate collective). Bytes are
reported per side with their ratio — measured 1.0 exactly on XLA:CPU
at the S=2 fixtures (PERF.md §comms-lint); a backend that types the
exchange per-participant would show a clean S-factor here, which is
why the ratio is reported rather than gated.

Everything except ``--hlo`` runs on abstract traces — no device
buffers, CPU-only CI.
"""

from __future__ import annotations

from typing import Optional

from .lint import LINT_N, trace_engine_pipeline
from .registry import ENCODINGS
from .rules import COMMS_RULES, Finding, TraceCtx, run_rules_with_stats

#: shard count of the default wave-body comms fixtures: the smallest
#: REAL mesh (S=1 degenerates the shuffle; the kernel lint keeps that
#: 1-device fixture for axis plumbing, this family needs live tiles).
COMMS_WAVE_SHARDS = 2

#: the reconciliation fixture's name — the sort-merge wave body at the
#: committed TRACE_r16 dryrun config (2pc rm=5, S=8, traced).
RECONCILIATION_FIXTURE = "comms(2pc-rm5,sortmerge,S8,traced)"

#: the exact engine config of dryrun_multichip's flagship workload
#: (__graft_entry__.py spawn_2pc5) — TRACE_r16's provenance lane.
RECONCILIATION_CONFIG = dict(
    rm_count=5,
    n_shards=8,
    capacity=1 << 12,
    frontier_capacity=512,
    cand_capacity=2048,
    bucket_capacity=1024,
    waves_per_sync=32,
    track_paths=True,
)


def _mesh(n_shards: int):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < n_shards:
        raise RuntimeError(
            f"comms fixture needs {n_shards} devices, have "
            f"{len(devices)}; set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_shards}"
        )
    return Mesh(np.array(devices[:n_shards]), ("shard",))


def comms_fixture_params(reconciliation: bool = True) -> list:
    """The wave-body fixture matrix: (engine, traced) x the default
    2pc-rm3/S=2 config, plus the rm=5/S=8 reconciliation config,
    plus the TIERED sort-merge chunk program (round 16,
    stateright_tpu/tier.py — the deferred-commit wave + commit path
    holds to the same five comms rules: the commit's v-ladder switch
    must stay collective-free, its termination psums scalar-only)."""
    out = []
    for engine in ("sortmerge", "hash"):
        for traced in (False, True):
            out.append(dict(engine=engine, traced=traced))
    out.append(dict(engine="sortmerge", traced=True, tiered=True))
    if reconciliation:
        out.append(dict(
            engine="sortmerge", traced=True,
            config=RECONCILIATION_CONFIG,
        ))
    return out


def comms_fixture_name(engine: str, traced: bool,
                       config: Optional[dict] = None,
                       tiered: bool = False) -> str:
    cfg = config or {}
    rm = cfg.get("rm_count", 3)
    s = cfg.get("n_shards", COMMS_WAVE_SHARDS)
    return (
        f"comms(2pc-rm{rm},{engine},S{s}"
        + (",tiered" if tiered else "")
        + (",traced" if traced else "")
        + ")"
    )


def trace_comms_fixture(engine: str = "sortmerge",
                        traced: bool = False,
                        config: Optional[dict] = None,
                        tiered: bool = False) -> dict:
    """Build one sharded engine on a real S-shard mesh and trace its
    full wave body (the ``_wave_body_sm`` hook both engines expose)
    on the seed program's carry shapes — abstract (``eval_shape``), no
    buffers. Returns the fixture dict the driver and the --hlo pass
    share: ``name``, ``closed`` (the jaxpr), ``fn`` + ``carry`` (the
    compilable callable for --hlo), ``seam`` (the engine's routing
    idiom the no-unsorted-all-to-all rule requires), and ``lane`` (the
    engine's telemetry lane config — ``dest_tile_lanes`` is the
    runtime side of the row-bytes reconciliation)."""
    import jax
    import jax.numpy as jnp

    from ..models.two_phase_commit import TwoPhaseSys

    cfg = dict(
        rm_count=3,
        n_shards=COMMS_WAVE_SHARDS,
        capacity=1 << 11,
        frontier_capacity=1 << 9,
        cand_capacity=1 << 11,
        bucket_capacity=1 << 10,
        waves_per_sync=4,
        track_paths=True,
    )
    if config:
        cfg.update(config)
    name = comms_fixture_name(engine, traced, cfg, tiered)
    rm = cfg.pop("rm_count")
    mesh = _mesh(cfg.pop("n_shards"))
    builder = TwoPhaseSys(rm_count=rm).checker()
    if engine == "sortmerge":
        checker = builder.spawn_tpu_sharded_sortmerge(
            mesh=mesh, f_min=64, v_min=256, merge_impl="xla", **cfg
        )
        seam = "sort"
    elif engine == "hash":
        checker = builder.spawn_tpu_sharded(mesh=mesh, **cfg)
        seam = "scatter"
    else:
        raise ValueError(f"unknown comms engine {engine!r}")
    if traced:
        # a truthy tracer stand-in flips _wave_log_enabled exactly as
        # a real RunTracer would — the per-shard slog path compiles in
        checker._tracer = object()
    init = jnp.asarray(checker.encoded.init_vecs())
    seed_fn, _chunk_fn = checker._build_programs(init.shape[0])
    carry_shapes = jax.eval_shape(seed_fn, init)
    if tiered:
        # The TIERED chunk program (stateright_tpu/tier.py): the
        # deferred-commit carry adds the pend/hot staging lanes and
        # the tier-shaped trace logs, and the program takes the
        # host's keep mask as a second, shard-split input. Traced as
        # one (carry, keep) pytree arg so the --hlo pass's
        # single-operand lower() keeps working.
        from ..telemetry import SHARD_LOG_LANES as SL
        from ..telemetry import WAVE_LOG_LANES as WL

        tier_fn = checker._build_programs(
            init.shape[0], tiered=True
        )
        S = checker.n_shards
        F = checker.frontier_capacity
        sds = jax.ShapeDtypeStruct
        ct = dict(carry_shapes)
        ct["pend_keys"] = sds((2, S * F), jnp.uint32)
        if checker.track_paths:
            ct["pend_par"] = sds((2, S * F), jnp.uint32)
        ct["pend_n"] = sds((S,), jnp.uint32)
        ct["pend_valid"] = sds((), jnp.bool_)
        ct["h_loc"] = sds((S,), jnp.uint32)
        if traced:
            ct["wlog"] = sds((1, WL), jnp.uint32)
            ct["pstash"] = sds((8,), jnp.uint32)
            ct["slog"] = sds((S, SL), jnp.uint32)
            ct["swave"] = sds((S * SL,), jnp.uint32)
        keep = sds((S * F,), jnp.bool_)

        def fn(args):
            return tier_fn(args[0], args[1])

        carry = (ct, keep)
        return dict(
            name=name,
            closed=jax.make_jaxpr(fn)(carry),
            fn=fn,
            carry=carry,
            seam=seam,
            lane=checker._lane_config(),
            n_shards=int(mesh.devices.size),
        )
    fn = checker._wave_body_sm
    return dict(
        name=name,
        closed=jax.make_jaxpr(fn)(carry_shapes),
        fn=fn,
        carry=carry_shapes,
        seam=seam,
        lane=checker._lane_config(),
        n_shards=int(mesh.devices.size),
    )


def _wave_body_ctx(name: str, seam: str) -> TraceCtx:
    # comms rules only: the codegen rules' gates are all off (the
    # kernel lint's own wave-body fixtures carry those; this family
    # prices communication).
    return TraceCtx(
        path="wave-body",
        encoding=name,
        n=LINT_N,
        k=0,
        sparse=False,
        allow_gathers=None,
        check_lane_alu=False,
        check_branches=False,
        check_comms=True,
        routing_seam=seam,
    )


def lint_comms_fixture(fixture: dict) -> tuple:
    """``(findings, stats_row, comms_summary)`` for one traced wave
    body. The comms summary is the comms-bytes info finding's data
    block plus the fixture's mesh/lane cross-reference fields — the
    COMM artifact's per-fixture record, and what shard_balance's
    ``comms_static`` block reconciles against at runtime."""
    ctx = _wave_body_ctx(fixture["name"], fixture["seam"])
    findings, n_eqns = run_rules_with_stats(ctx, fixture["closed"])
    est = [
        f for f in findings
        if f.rule == "comms-bytes" and f.severity == "info"
    ]
    lane = fixture["lane"]
    summary = dict(
        n_shards=fixture["n_shards"],
        seam=fixture["seam"],
        dest_tile_lanes=lane.get("dest_tile_lanes"),
        **(est[0].data if est else {"collectives": 0}),
    )
    stats = dict(
        encoding=fixture["name"],
        path="wave-body",
        eqns=n_eqns,
        errors=sum(1 for f in findings if f.severity == "error"),
    )
    return findings, stats, summary


def run_comms_lint(wave_bodies: bool = True,
                   encodings: Optional[tuple] = None,
                   reconciliation: bool = True,
                   n: int = LINT_N,
                   fixtures_out: Optional[list] = None) -> dict:
    """The whole comms gate. Returns the ``COMM_r*.json`` report dict:
    ``clean`` (no gated finding anywhere), ``findings`` (every comms
    finding incl. the per-fixture comms-bytes estimates), ``paths``
    (coverage rows), and ``comms`` (per-fixture collective accounting
    — categories, per-wave peak, all_to_all row bytes).

    ``fixtures_out``: pass a list to receive the traced wave-body
    fixture dicts — building a fixture constructs a full sharded
    engine and traces its body (the tool's most expensive step), so
    the ``--hlo`` pass reuses these instead of re-tracing."""
    all_findings: list = []
    all_stats: list = []
    comms: dict = {}
    if wave_bodies:
        for params in comms_fixture_params(reconciliation):
            fixture = trace_comms_fixture(**params)
            if fixtures_out is not None:
                fixtures_out.append(fixture)
            fs, st, summary = lint_comms_fixture(fixture)
            all_findings.extend(fs)
            all_stats.append(st)
            comms[fixture["name"]] = summary
    specs = encodings if encodings is not None else ENCODINGS
    for spec in specs:
        enc = spec.factory()
        closed = trace_engine_pipeline(enc, "sharded", n)
        ctx = TraceCtx(
            path="engine:sharded",
            encoding=spec.name,
            n=n,
            k=enc.max_actions,
            sparse=False,
            allow_gathers=None,
            check_lane_alu=False,
            check_comms=True,
            # the pair pipeline has no shuffle; the rule is off and
            # pins nothing here — an all_to_all appearing at all
            # would land in comms-bytes and the placement rules
            routing_seam=None,
        )
        fs, n_eqns = run_rules_with_stats(ctx, closed)
        all_findings.extend(fs)
        all_stats.append(dict(
            encoding=spec.name,
            path="engine:sharded",
            eqns=n_eqns,
            errors=sum(1 for f in fs if f.severity == "error"),
        ))
    errors = [f for f in all_findings if f.severity == "error"]
    return dict(
        clean=not errors,
        n=n,
        rules=[
            dict(name=r.name, description=r.description)
            for r in COMMS_RULES
        ],
        paths=all_stats,
        comms=comms,
        findings=[f.as_dict() for f in all_findings],
    )


# -- the HLO-level cross-check (the --hlo seam) ----------------------------


def reconcile_collective_categories(name: str, hlo: dict,
                                    jaxpr_categories: dict) -> dict:
    """Pure reconciliation of one fixture's per-category collective
    accounting (the --hlo pass's verdict logic, factored out so the
    deliberate-regression tests exercise it without a compile):
    MORE HLO ops than jaxpr eqns in a category is a gated finding (a
    collective XLA introduced — SPMD partitioner respecification —
    that the jaxpr walk can't see), fewer is an info (XLA folded a
    degenerate collective). Byte totals are reported with their
    per-category ratio, never gated (backend-dependent typing)."""
    findings: list = []
    ratios: dict = {}
    for cat in sorted(set(hlo) | set(jaxpr_categories)):
        h = hlo.get(cat, {"ops": 0, "bytes": 0})
        j = jaxpr_categories.get(cat, {"eqns": 0, "bytes": 0})
        if j["bytes"]:
            ratios[cat] = round(h["bytes"] / j["bytes"], 3)
        if h["ops"] > j["eqns"]:
            findings.append(Finding(
                rule="hlo-collective-reconcile",
                severity="error",
                encoding=name,
                path="hlo",
                message=(
                    f"compiled module has {h['ops']} '{cat}' "
                    f"collective op(s) but the jaxpr walk accounts "
                    f"for {j['eqns']} — XLA (SPMD partitioner "
                    "respecification) introduced collectives the "
                    "static estimate can't see; the comms budget "
                    "no longer bounds real traffic"
                ),
                primitive=cat,
                data={"hlo_ops": h["ops"], "jaxpr_eqns": j["eqns"]},
            ))
        elif h["ops"] < j["eqns"]:
            findings.append(Finding(
                rule="hlo-collective-reconcile",
                severity="info",
                encoding=name,
                path="hlo",
                message=(
                    f"compiled module has {h['ops']} '{cat}' op(s) "
                    f"vs {j['eqns']} jaxpr eqns — XLA folded "
                    "degenerate collectives (static estimate is an "
                    "upper bound here)"
                ),
                primitive=cat,
                data={"hlo_ops": h["ops"], "jaxpr_eqns": j["eqns"]},
            ))
    return dict(
        hlo=hlo,
        jaxpr=jaxpr_categories,
        byte_ratio=ratios,
        findings=findings,
    )


def hlo_collective_crosscheck(fixture: dict,
                              jaxpr_categories: dict) -> dict:
    """Compile one wave-body fixture on the live mesh and reconcile
    the optimized module's collective ops against the jaxpr-level
    accounting (see :func:`reconcile_collective_categories` for the
    verdict rules)."""
    import jax

    from .tables import parse_hlo_collectives

    txt = (
        jax.jit(fixture["fn"])
        .lower(fixture["carry"])
        .compile()
        .as_text()
    )
    return reconcile_collective_categories(
        fixture["name"], parse_hlo_collectives(txt), jaxpr_categories
    )


def format_comms_report(report: dict) -> str:
    """Human-readable comms-lint report (tools/lint_comms.py)."""
    lines = [
        f"comms-lint: {len(report['rules'])} rules x "
        f"{len(report['paths'])} traced paths (N={report['n']})"
    ]
    lines.append(
        f"  {'fixture':40s} {'path':16s} {'eqns':>6s} {'errors':>7s}"
    )
    for p in report["paths"]:
        lines.append(
            f"  {p['encoding']:40s} {p['path']:16s} "
            f"{p['eqns']:6d} {p['errors']:7d}"
        )
    for name, c in report.get("comms", {}).items():
        if not c.get("collectives"):
            lines.append(f"  {name}: no collectives")
            continue
        cats = ", ".join(
            f"{cat} x{s['eqns']} ({s['bytes']:,} B)"
            for cat, s in sorted(c["per_category"].items())
        )
        lines.append(
            f"  {name}: S={c['n_shards']} seam={c['seam']} "
            f"per-wave peak {c['per_wave_peak_bytes']:,} B"
            + (f" (budget {c['budget_bytes']:,})"
               if "budget_bytes" in c else "")
            + (f"; a2a row {c['all_to_all_row_bytes']} B x "
               f"<= {c['all_to_all_rows_max']} rows"
               if "all_to_all_row_bytes" in c else "")
            + f"; {cats}"
        )
    errors = [f for f in report["findings"]
              if f["severity"] == "error"]
    for f in errors:
        loc = f" @ {f['source']}" if f.get("source") else ""
        lines.append(
            f"ERROR [{f['rule']}] {f['encoding']} / {f['path']}: "
            f"{f['message']}{loc}"
        )
    lines.append(
        "CLEAN — the mesh communication contract holds"
        if report["clean"]
        else f"{len(errors)} comms violation(s)"
    )
    return "\n".join(lines)
