"""The kernel-lint rule registry: the sparse-engine codegen contract
as declarative, source-attributed checks over traced jaxprs.

Two rounds of perf work (PERF.md §ordered, §wave-wall) priced exactly
these artifacts; each rule pins one of them:

* ``no-dense-mask`` — no ``[N, K]``/``[F, K]`` bool materialization on
  a sparse path (the 82x predicate-pass ablation: the engine consumes
  packed ``uint32[L]`` words, never the dense mask);
* ``no-mask-gather`` — the enabled-mask paths trace gather-free
  (shift-mask field extracts and word selects only; the 8x
  compiled-codegen tax was per-slot table gathers here);
* ``allowed-table-gather`` — step/fetch paths may gather only the
  intended table rows (params, flat transition, packed history, crash
  mask — at most the encoding's declared allowance);
* ``no-lane-padded-alu`` — no ``[N, 1]``-shaped ALU/compute outputs
  and no stack-of-scalars concats (≥3 ``[N, 1]`` operands): a
  ``[N, 1]`` elementwise op pays the full 128-lane tile-padding tax
  and XLA cannot fuse through the concatenate. The allowed residue is
  the hand-paxos calibration: ``[N, 1]`` SLICES from consuming
  multi-lane gather rows and 2-operand index-pair concats, which fuse;
* ``no-branch-pad-concat`` — ``cond``/``switch`` branches must update
  carried buffers with class-local ``dynamic_update_slice`` blocks,
  never rebuild a full-capacity tensor by padding/concatenating a
  small class result up to peak shape (the pre-round-6 carry pattern:
  a 2-row tail wave paying the 686k-row peak wave's copies);
* ``carry-copy-bytes`` — prices the switch-carry movement: bytes
  every ``cond``/``switch`` must materialize for its carry, and the
  carry-movement bytes inside each branch. The estimate is an info
  finding; fixtures listed in ``tables.CARRY_COPY_BYTE_BUDGETS`` are
  additionally GATED (round 9) — exceeding the per-fixture byte
  budget is an error, so the round-9 class collapse (PERF.md
  §layout: 1.42 MB → 0.24 MB per wave on the 2pc fixture) cannot
  silently regress.

A rule sees the shared walk (:mod:`.walker`) plus a :class:`TraceCtx`
describing the traced path, and yields :class:`Finding`\\ s. Rules
never import each other's state; adding a rule is appending to
``RULES``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

from .tables import (
    ALL_GATHER_ALLOWANCES,
    BRANCH_PAD_CONCAT_GROWTH,
    BRANCH_PAD_CONCAT_MIN_BYTES,
    CARRY_COPY_BYTE_BUDGETS,
    CARRY_MOVE_PRIMS,
    COMMS_BYTE_BUDGETS,
    REDUCTION_CATEGORIES,
    SCALAR_REDUCTION_MAX_ELEMS,
    collective_bytes,
    collective_category,
    is_collective,
    is_gather,
    output_bytes,
)
from .walker import (
    EqnSite,
    SiteWalk,
    eqn_alu_n1,
    eqn_dense_bool_k,
    eqn_wide_concat_n1,
    iter_eqns,  # noqa: F401 — re-exported for external walkers
    source_of,
)


@dataclass(frozen=True)
class TraceCtx:
    """What the lint driver knows about one traced path."""

    #: path label ("bits", "mask", "step", "engine:single",
    #: "engine:sharded", "wave-body")
    path: str
    #: encoding (or engine fixture) the path was traced from
    encoding: str
    #: batch rows of the trace (N frontier rows / vmap batch)
    n: int
    #: the encoding's action count K (dense-mask last dim)
    k: int
    #: dense [n, k] bool is banned on this path (packed-words paths
    #: and the engine pipeline; enabled_mask_vec's dense view is the
    #: CONTRACT on the "mask" path, so it sets False)
    sparse: bool = True
    #: gathers allowed (0 on mask paths; the table-row allowance on
    #: step paths; None = gathers unaudited, e.g. the wave body whose
    #: winner-fetch gathers are the intended idiom)
    allow_gathers: Optional[int] = 0
    #: True on table-fetch paths (step): gather findings report under
    #: allowed-table-gather with the table-row diagnosis, even at
    #: allowance 0 — a mask-path message for a step-path defect sends
    #: the maintainer to the wrong contract
    table_path: bool = False
    #: audit [n, 1] ALU / stack-of-scalars concats on this path
    check_lane_alu: bool = True
    #: audit cond/switch branch shapes + price carry movement
    check_branches: bool = False
    #: run the comms rule family (COMMS_RULES, round 13): collective
    #: placement/accounting over sharded wave paths — off on the
    #: single-chip contract paths, which trace no axis context
    check_comms: bool = False
    #: routing seam the no-unsorted-all-to-all rule requires every
    #: all_to_all operand to derive from: "sort" (the sort-merge
    #: engine's (owner, fp) routing sort), "scatter" (the hash
    #: engine's owner-position tile build), or None (rule off — paths
    #: with no shuffle)
    routing_seam: Optional[str] = None


@dataclass(frozen=True)
class Finding:
    """One rule hit, attributed to the source equation."""

    rule: str
    severity: str  # "error" | "info"
    encoding: str
    path: str
    message: str
    primitive: Optional[str] = None
    source: Optional[str] = None
    data: dict = field(default_factory=dict)

    def format(self) -> str:
        loc = f" @ {self.source}" if self.source else ""
        return (
            f"[{self.rule}] {self.encoding} / {self.path}: "
            f"{self.message}{loc}"
        )

    def as_dict(self) -> dict:
        """The JSON-artifact record of one finding — the ONE
        serialization every report writer uses (run_lint,
        run_comms_lint, the --hlo pass), so a new Finding field can't
        land in some artifacts and not others."""
        return dict(
            rule=self.rule,
            severity=self.severity,
            encoding=self.encoding,
            path=self.path,
            message=self.message,
            primitive=self.primitive,
            source=self.source,
            **({"data": self.data} if self.data else {}),
        )


@dataclass(frozen=True)
class Rule:
    name: str
    description: str
    run: Callable[[TraceCtx, list], Iterable[Finding]]


def _out_shapes(eqn):
    for v in eqn.outvars:
        sh = getattr(v.aval, "shape", None)
        if sh is not None:
            yield v.aval, sh


# -- no-dense-mask ---------------------------------------------------------

def _no_dense_mask(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    if not ctx.sparse:
        return
    for site in sites:
        if not eqn_dense_bool_k(site.eqn, ctx.k):
            continue
        shapes = [
            sh for _, sh in _out_shapes(site.eqn)
            if len(sh) == 2 and sh[1] == ctx.k
        ]
        yield Finding(
            rule="no-dense-mask",
            severity="error",
            encoding=ctx.encoding,
            path=ctx.path,
            message=(
                f"dense bool[{shapes[0][0]}, K={ctx.k}] mask "
                f"materialized by `{site.primitive}` on a "
                "sparse path (the engine consumes packed "
                "uint32 words; PERF.md §wave-wall priced this "
                "pass 82x)"
            ),
            primitive=site.primitive,
            source=source_of(site.eqn),
        )


# -- no-mask-gather / allowed-table-gather ---------------------------------

def _no_mask_gather(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    # mask-class paths only: a step-path gather is a table-fetch
    # defect and reports under allowed-table-gather below.
    if ctx.allow_gathers != 0 or ctx.table_path:
        return
    engine = ctx.path.startswith("engine:")
    for site in sites:
        if is_gather(site.primitive):
            yield Finding(
                rule="no-mask-gather",
                severity="error",
                encoding=ctx.encoding,
                path=ctx.path,
                message=(
                    f"`{site.primitive}` on a gather-free path — "
                    + (
                        "the engine's pair pipeline (bitmap "
                        "predicate, peel, packed-append compaction) "
                        "is elementwise + sort only; one Ba-row "
                        "gather costs a whole extra sort (PERF.md "
                        "§gathers)"
                        if engine
                        else "mask paths must be shift-mask field "
                        "extracts and word selects only (the 8x "
                        "compiled-codegen tax, PERF.md §ordered)"
                    )
                ),
                primitive=site.primitive,
                source=source_of(site.eqn),
            )


def _allowed_table_gather(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    # table-fetch (step) paths only, at ANY allowance including 0 —
    # hand 2pc's step is pure slot arithmetic, so its allowance IS 0
    # and a gather there must still get the table-row diagnosis.
    if not ctx.table_path or ctx.allow_gathers is None:
        return
    gathers = [s for s in sites if is_gather(s.primitive)]
    if len(gathers) > ctx.allow_gathers:
        srcs = ", ".join(source_of(s.eqn) for s in gathers)
        yield Finding(
            rule="allowed-table-gather",
            severity="error",
            encoding=ctx.encoding,
            path=ctx.path,
            message=(
                f"{len(gathers)} gathers on a table-fetch path whose "
                f"allowance is {ctx.allow_gathers} (the intended "
                "idiom is one multi-lane gather per table row — "
                "params, flat transition, packed history, crash "
                f"mask); gather sites: {srcs}"
            ),
            primitive=gathers[0].primitive,
            source=source_of(gathers[0].eqn),
            data={"gathers": len(gathers),
                  "allowance": ctx.allow_gathers},
        )


# -- no-lane-padded-alu ----------------------------------------------------

def _no_lane_padded_alu(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    if not ctx.check_lane_alu:
        return
    n = ctx.n
    for site in sites:
        eqn = site.eqn
        name = site.primitive
        if eqn_alu_n1(eqn, n):
            yield Finding(
                rule="no-lane-padded-alu",
                severity="error",
                encoding=ctx.encoding,
                path=ctx.path,
                message=(
                    f"[{n}, 1]-shaped `{name}` — real compute "
                    "at 128x lane padding (PERF.md §ordered); "
                    "keep lane math 1-D [N]-shaped and "
                    "reshape only at the very end"
                ),
                primitive=name,
                source=source_of(eqn),
            )
        n1_ops = eqn_wide_concat_n1(eqn, n)
        if n1_ops:
            yield Finding(
                rule="no-lane-padded-alu",
                severity="error",
                encoding=ctx.encoding,
                path=ctx.path,
                message=(
                    f"stack-of-scalars concatenate of {n1_ops} "
                    f"[{n}, 1] lanes — XLA cannot fuse through a "
                    "wide concatenate (the ~470ms/run artifact, "
                    "PERF.md §ordered); 2-operand index-pair "
                    "concats are the calibrated residue"
                ),
                primitive=name,
                source=source_of(eqn),
                data={"n1_operands": n1_ops},
            )


# -- no-branch-pad-concat --------------------------------------------------

def _axis0(sh) -> int:
    return int(sh[0]) if sh else 1


def _zeroish_rows(site: EqnSite, eqn) -> tuple:
    """Split a concatenate's axis-0 operand rows into (filler, real):
    filler operands are literals, jaxpr constants, or values a
    ``broadcast_in_dim`` of a scalar produced inside the same
    sub-jaxpr — the static signature of a ``zeros(...)`` pad block."""
    producers = {}
    if site.jaxpr is not None:
        for e in site.jaxpr.eqns:
            if e.primitive.name == "broadcast_in_dim" and not getattr(
                e.invars[0].aval, "shape", ()
            ):
                for v in e.outvars:
                    producers[id(v)] = "scalar-broadcast"
        consts = set(map(id, site.jaxpr.constvars))
    else:
        consts = set()
    filler = real = 0
    for v in eqn.invars:
        sh = getattr(v.aval, "shape", None)
        rows = _axis0(sh) if sh else 1
        if (
            not hasattr(v, "count")  # Literal
            or id(v) in consts
            or id(v) in producers
        ):
            filler += rows
        else:
            real += rows
    return filler, real


def _no_branch_pad_concat(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    if not ctx.check_branches:
        return
    for site in sites:
        # Only a pad/concat RETURNED as part of a branch's carry
        # (directly or through convert/reshape passthroughs) is the
        # priced pattern (rebuilding a carried buffer at peak shape);
        # internal temporaries — merge sort lanes, index plumbing —
        # are the engine's legitimate concats.
        if not site.in_branch():
            continue
        eqn = site.eqn
        name = site.primitive
        if name not in ("pad", "concatenate"):
            continue
        if not site.reaches_output():
            continue
        outs = list(_out_shapes(eqn))
        if not outs:
            continue
        out_aval, out_sh = outs[0]
        nbytes = output_bytes(out_aval)
        if nbytes < BRANCH_PAD_CONCAT_MIN_BYTES or not out_sh:
            continue
        if name == "concatenate" and eqn.params.get("dimension") != 0:
            continue
        in0 = max(
            (_axis0(getattr(v.aval, "shape", ()))
             for v in eqn.invars
             if getattr(v.aval, "shape", None)),
            default=1,
        )
        grown = _axis0(out_sh) >= BRANCH_PAD_CONCAT_GROWTH * max(in0, 1)
        padded = False
        if name == "pad":
            cfg = eqn.params.get("padding_config") or ()
            if cfg:
                lo, hi, _ = cfg[0]
                padded = lo + hi >= max(in0, 1)
        else:
            filler, real = _zeroish_rows(site, eqn)
            padded = filler >= max(real, 1)
        if not (grown or padded):
            continue
        yield Finding(
            rule="no-branch-pad-concat",
            severity="error",
            encoding=ctx.encoding,
            path=ctx.path,
            message=(
                f"branch carry built by `{name}` inside "
                f"{site.branch_path()}: axis 0 {in0} -> "
                f"{_axis0(out_sh)} rows ({nbytes / 1e6:.2f} MB out)"
                " — switch branches must write class-local "
                "dynamic_update_slice blocks into the carried "
                "buffer, not pad a class result to peak shape (the "
                "round-6 carry rework, PERF.md §wave-wall)"
            ),
            primitive=name,
            source=source_of(eqn),
            data={"in_rows": in0, "out_rows": _axis0(out_sh),
                  "out_bytes": nbytes},
        )


# -- carry-copy-bytes (estimator) ------------------------------------------

def _carry_copy_bytes(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    """Price the carry each ``cond``/``switch`` materializes: the
    bytes of every branch's returned carry (the movement XLA performs
    between classes) plus the carry-movement primitive bytes inside
    branches. The estimate always lands as an info finding; since
    round 9 the rule is also GATED — a fixture listed in
    ``tables.CARRY_COPY_BYTE_BUDGETS`` whose switch-carry total
    exceeds its budget yields an ERROR, so a refactor can't silently
    re-inflate the switch carries the round-9 class collapse removed
    (the 2pc fixture went 1.42 MB → 0.24 MB/wave; PERF.md §layout)."""
    if not ctx.check_branches:
        return
    switch_bytes = 0
    n_switches = 0
    move_bytes = 0
    top = None  # fattest switch
    for site in sites:
        eqn = site.eqn
        if site.primitive == "cond":
            n_switches += 1
            b = sum(output_bytes(v.aval) for v in eqn.outvars)
            switch_bytes += b
            if top is None or b > top[0]:
                top = (b, len(eqn.params.get("branches", ())),
                       source_of(eqn))
        elif site.in_branch() and site.primitive in CARRY_MOVE_PRIMS:
            move_bytes += sum(
                output_bytes(v.aval) for v in eqn.outvars
            )
    if n_switches == 0:
        return
    top_b, top_nb, top_src = top
    budget = CARRY_COPY_BYTE_BUDGETS.get(ctx.encoding)
    yield Finding(
        rule="carry-copy-bytes",
        severity="info",
        encoding=ctx.encoding,
        path=ctx.path,
        message=(
            f"{n_switches} cond/switch eqns carry "
            f"{switch_bytes / 1e6:.2f} MB of outputs (fattest: "
            f"{top_b / 1e6:.2f} MB x {top_nb} branches @ {top_src}); "
            f"{move_bytes / 1e6:.2f} MB of pad/slice/concat/"
            "dynamic_update_slice outputs inside branches"
            + (f"; budget {budget / 1e6:.2f} MB"
               if budget is not None else "")
        ),
        primitive="cond",
        source=top_src,
        data={
            "switches": n_switches,
            "switch_carry_bytes": switch_bytes,
            "fattest_switch_bytes": top_b,
            "branch_move_bytes": move_bytes,
            **({"budget_bytes": budget} if budget is not None else {}),
        },
    )
    if budget is not None and switch_bytes > budget:
        yield Finding(
            rule="carry-copy-bytes",
            severity="error",
            encoding=ctx.encoding,
            path=ctx.path,
            message=(
                f"switch-carry bytes {switch_bytes:,} exceed this "
                f"fixture's budget {budget:,} "
                "(analysis/tables.CARRY_COPY_BYTE_BUDGETS) — the "
                "class ladder is copying carry tuples between "
                "branches again; keep merge cores returning the "
                "shared SoA result and resident-buffer updates in "
                "ONE fetch switch per wave (the round-9 collapse, "
                "PERF.md §layout). Raise the budget only for a "
                "deliberate, priced carry addition."
            ),
            primitive="cond",
            source=top_src,
            data={
                "switch_carry_bytes": switch_bytes,
                "budget_bytes": budget,
            },
        )


# -- the comms rule family (round 13: comms-lint) --------------------------
#
# Static collective accounting and shard-safety over the sharded wave
# paths (analysis/comms.py traces them; ISSUE: a misplaced collective
# only surfaces as a deadlock or a silent traffic blow-up ON CHIP,
# where debugging is most expensive). Each rule pins one documented
# invariant of parallel/engine_sortmerge.py / parallel/engine.py:
#
# * ``no-collective-in-switch`` — a collective under a cond/switch
#   whose index is SHARD-VARYING deadlocks: collectives are
#   collective, so every shard must take the same branch. The engines'
#   class switches are legal exactly because their indices are
#   pmax-agreed (walker.shard_varying_vars proves it);
# * ``no-unsorted-all-to-all`` — every all_to_all operand must be
#   data-dependent on the routing seam (the (owner, fp) sort / the
#   owner-position scatter), or the shuffle ships unrouted candidates;
# * ``scalar-only-reductions`` — psum/pmax/pmin operands stay rank-0/
#   tiny; a reduction over a resident buffer is an accidental
#   replication (S x the buffer per wave in all-reduce bandwidth);
# * ``no-all-gather`` — the wave path never all-gathers (visited state
#   is owner-sharded BY CONSTRUCTION; gathering it back is the 8x
#   traffic blow-up), gated with a per-fixture allowance table for
#   legitimate drain paths (tables.ALL_GATHER_ALLOWANCES);
# * ``comms-bytes`` — the collective analog of carry-copy-bytes:
#   price every collective from operand shapes, report per-category
#   totals + the PER-WAVE PEAK (fattest class branch + out-of-branch
#   collectives), GATED against tables.COMMS_BYTE_BUDGETS.


def _walk_of(sites) -> SiteWalk:
    """The SiteWalk (dataflow-capable) view of a rule's site list.
    The comms rules NEED the whole-jaxpr dataflow marks; a plain
    hand-built list can't recover the root jaxpr, and silently
    treating it as 'nothing is shard-varying / nothing is
    seam-derived' would pass the deadlock shape and flag every
    legitimate all_to_all — fail loudly instead (run_rules always
    constructs a SiteWalk; only bespoke callers can hit this)."""
    if isinstance(sites, SiteWalk):
        return sites
    raise TypeError(
        "comms rules require the SiteWalk from run_rules/"
        "run_rules_with_stats (whole-jaxpr dataflow marks); got a "
        "plain site list, whose root jaxpr is not recoverable"
    )


def _no_collective_in_switch(ctx: TraceCtx, sites) -> Iterable[Finding]:
    if not ctx.check_comms:
        return
    varying = _walk_of(sites).shard_varying()
    for site in sites:
        if not is_collective(site.primitive):
            continue
        for cond_eqn, idx in site.enclosing_conds():
            iv = cond_eqn.invars[0]
            if not hasattr(iv, "count"):
                continue  # literal index: trivially uniform
            if id(iv) in varying:
                yield Finding(
                    rule="no-collective-in-switch",
                    severity="error",
                    encoding=ctx.encoding,
                    path=ctx.path,
                    message=(
                        f"`{site.primitive}` nested under "
                        f"{site.branch_path()} whose switch index is "
                        "SHARD-VARYING — shards take different "
                        "branches and the collective deadlocks on "
                        "chip (the engine invariant: class switches "
                        "are pmax-agreed so every shard runs the "
                        f"same branch; switch @ {source_of(cond_eqn)})"
                    ),
                    primitive=site.primitive,
                    source=source_of(site.eqn),
                    data={"branch": idx,
                          "switch_source": source_of(cond_eqn)},
                )
                break


def _no_unsorted_all_to_all(ctx: TraceCtx, sites) -> Iterable[Finding]:
    if not ctx.check_comms or ctx.routing_seam is None:
        return
    seam = _walk_of(sites).seam_derived(ctx.routing_seam)
    seam_desc = (
        "the (owner, fp) routing sort"
        if ctx.routing_seam == "sort"
        else "the owner-position tile scatter"
    )
    for site in sites:
        if site.primitive != "all_to_all":
            continue
        routed = any(
            hasattr(v, "count") and id(v) in seam
            for v in site.eqn.invars
        )
        if not routed:
            yield Finding(
                rule="no-unsorted-all-to-all",
                severity="error",
                encoding=ctx.encoding,
                path=ctx.path,
                message=(
                    "all_to_all operand is not data-dependent on "
                    f"{seam_desc} — the shuffle ships unrouted "
                    "candidates, so rows land on shards that do not "
                    "own their fingerprints and the owner-local "
                    "dedup contract breaks silently "
                    "(engine_sortmerge.py wave step 2-3)"
                ),
                primitive=site.primitive,
                source=source_of(site.eqn),
                data={"seam": ctx.routing_seam},
            )


def _scalar_only_reductions(ctx: TraceCtx, sites) -> Iterable[Finding]:
    if not ctx.check_comms:
        return
    for site in sites:
        if not is_collective(site.primitive):
            continue
        if collective_category(site.primitive) \
                not in REDUCTION_CATEGORIES:
            continue
        for v in site.eqn.invars:
            sh = getattr(getattr(v, "aval", None), "shape", None)
            if sh is None:
                continue
            elems = 1
            for d in sh:
                elems *= int(d)
            if elems <= SCALAR_REDUCTION_MAX_ELEMS:
                continue
            yield Finding(
                rule="scalar-only-reductions",
                severity="error",
                encoding=ctx.encoding,
                path=ctx.path,
                message=(
                    f"`{site.primitive}` over a {list(sh)} operand "
                    f"({elems:,} elements > "
                    f"{SCALAR_REDUCTION_MAX_ELEMS}) — an accidental "
                    "replication: every shard pays the full buffer's "
                    "all-reduce bandwidth per wave; the engines "
                    "psum SCALARS (counters, flags) and tiny "
                    "per-property vectors only"
                ),
                primitive=site.primitive,
                source=source_of(site.eqn),
                data={"shape": [int(d) for d in sh],
                      "elements": elems},
            )
            break


def _no_all_gather(ctx: TraceCtx, sites) -> Iterable[Finding]:
    if not ctx.check_comms:
        return
    gsites = [
        s for s in sites
        if is_collective(s.primitive)
        and collective_category(s.primitive) == "all-gather"
    ]
    allowance = ALL_GATHER_ALLOWANCES.get(ctx.encoding, 0)
    if len(gsites) <= allowance:
        return
    srcs = ", ".join(source_of(s.eqn) for s in gsites)
    yield Finding(
        rule="no-all-gather",
        severity="error",
        encoding=ctx.encoding,
        path=ctx.path,
        message=(
            f"{len(gsites)} all_gather eqn(s) on a wave path whose "
            f"allowance is {allowance} — visited state is "
            "owner-sharded by construction; gathering it back onto "
            "every shard is the S-fold traffic blow-up sharding "
            "exists to avoid. Register a drain-path allowance in "
            "tables.ALL_GATHER_ALLOWANCES only for a deliberate, "
            f"priced collection; sites: {srcs}"
        ),
        primitive=gsites[0].primitive,
        source=source_of(gsites[0].eqn),
        data={"all_gathers": len(gsites), "allowance": allowance},
    )


def _branch_tree_peak(node: dict) -> int:
    """Per-wave peak of a branch tree: a node's own collective bytes
    plus, for EVERY nested cond below it, the fattest of that cond's
    branches — at any depth exactly one branch of each switch runs
    per wave, so siblings take max while distinct conds (which all
    execute) sum."""
    total = node["bytes"]
    for branches in node["conds"].values():
        total += max(
            _branch_tree_peak(child) for child in branches.values()
        )
    return total


def _comms_bytes(ctx: TraceCtx, sites) -> Iterable[Finding]:
    """Price every collective from operand shapes. Collectives are
    attributed to their FULL cond/switch branch path and the per-wave
    peak takes the fattest branch at every nesting level (mutually
    exclusive siblings max, sequential conds sum) plus everything
    outside any switch — the number the byte budget gates
    (tables.COMMS_BYTE_BUDGETS) and the one a mesh trace's routed-byte
    counters reconcile against (telemetry.shard_balance comms_static;
    PERF.md §comms-lint)."""
    if not ctx.check_comms:
        return
    per_cat: dict = {}
    # branch tree: bytes at this nesting level + per nested cond a
    # {branch_idx: subtree} map (see _branch_tree_peak)
    tree = {"bytes": 0, "conds": {}}
    a2a_rows_max = 0
    a2a_row_bytes = None
    a2a_eqns = 0
    n_coll = 0
    top = None
    for site in sites:
        if not is_collective(site.primitive):
            continue
        n_coll += 1
        b = collective_bytes(site.eqn)
        cat = collective_category(site.primitive)
        slot = per_cat.setdefault(cat, {"eqns": 0, "bytes": 0})
        slot["eqns"] += 1
        slot["bytes"] += b
        if top is None or b > top[0]:
            top = (b, site.primitive, source_of(site.eqn))
        node = tree
        for ce, idx in site.enclosing_conds():
            node = node["conds"].setdefault(id(ce), {}).setdefault(
                idx, {"bytes": 0, "conds": {}}
            )
        node["bytes"] += b
        if site.primitive == "all_to_all":
            a2a_eqns += 1
            for v in site.eqn.invars:
                sh = getattr(getattr(v, "aval", None), "shape", None)
                if sh and len(sh) >= 2:
                    rows = int(sh[0])
                    lanes = 1
                    for d in sh[1:]:
                        lanes *= int(d)
                    rb = lanes * v.aval.dtype.itemsize
                    a2a_rows_max = max(a2a_rows_max, rows)
                    a2a_row_bytes = (
                        rb if a2a_row_bytes is None
                        else max(a2a_row_bytes, rb)
                    )
    if n_coll == 0:
        return
    per_wave_peak = _branch_tree_peak(tree)
    budget = COMMS_BYTE_BUDGETS.get(ctx.encoding)
    top_b, top_prim, top_src = top
    yield Finding(
        rule="comms-bytes",
        severity="info",
        encoding=ctx.encoding,
        path=ctx.path,
        message=(
            f"{n_coll} collective eqns move "
            f"{sum(s['bytes'] for s in per_cat.values()) / 1e6:.3f}"
            " MB (static program total); per-wave peak "
            f"{per_wave_peak / 1e6:.3f} MB (fattest branch at every "
            f"switch level + unswitched collectives); fattest: "
            f"{top_prim} {top_b / 1e6:.3f} MB @ {top_src}"
            + (f"; budget {budget / 1e6:.3f} MB"
               if budget is not None else "")
        ),
        primitive=top_prim,
        source=top_src,
        data={
            "collectives": n_coll,
            "per_category": per_cat,
            "bytes_total": sum(
                s["bytes"] for s in per_cat.values()
            ),
            "per_wave_peak_bytes": per_wave_peak,
            "all_to_all_eqns": a2a_eqns,
            **({"all_to_all_row_bytes": a2a_row_bytes,
                "all_to_all_rows_max": a2a_rows_max}
               if a2a_row_bytes is not None else {}),
            **({"budget_bytes": budget}
               if budget is not None else {}),
        },
    )
    if budget is not None and per_wave_peak > budget:
        yield Finding(
            rule="comms-bytes",
            severity="error",
            encoding=ctx.encoding,
            path=ctx.path,
            message=(
                f"per-wave collective bytes {per_wave_peak:,} exceed "
                f"this fixture's budget {budget:,} "
                "(analysis/tables.COMMS_BYTE_BUDGETS) — the wave "
                "body grew cross-chip traffic (a second shuffle, a "
                "buffer-sized reduction, an S-fold gather). Raise "
                "the budget only for a deliberate, priced "
                "communication addition."
            ),
            primitive=top_prim,
            source=top_src,
            data={
                "per_wave_peak_bytes": per_wave_peak,
                "budget_bytes": budget,
            },
        )


#: the comms rule family — run alongside RULES by the shared driver,
#: active only on paths whose TraceCtx sets ``check_comms``
#: (analysis/comms.py's sharded fixtures; the kernel lint's engine
#: paths enable it too, as belt-and-braces against a collective
#: sneaking into the pair pipeline via sharding propagation).
COMMS_RULES: tuple = (
    Rule(
        name="no-collective-in-switch",
        description=(
            "collectives only under shard-UNIFORM (pmax-agreed) "
            "switch indices — a shard-varying branch deadlocks the "
            "mesh"
        ),
        run=_no_collective_in_switch,
    ),
    Rule(
        name="no-unsorted-all-to-all",
        description=(
            "every all_to_all operand derives from the routing seam "
            "(owner-sort / owner-scatter), never raw candidates"
        ),
        run=_no_unsorted_all_to_all,
    ),
    Rule(
        name="scalar-only-reductions",
        description=(
            "psum/pmax/pmin operands rank-0/tiny (<= "
            f"{SCALAR_REDUCTION_MAX_ELEMS} elements); buffer-sized "
            "reductions are accidental replication"
        ),
        run=_scalar_only_reductions,
    ),
    Rule(
        name="no-all-gather",
        description=(
            "no all_gather on wave paths (S-fold traffic); gated by "
            "the drain-path allowance table"
        ),
        run=_no_all_gather,
    ),
    Rule(
        name="comms-bytes",
        description=(
            "price collectives from operand shapes; per-wave peak "
            "GATED against tables.COMMS_BYTE_BUDGETS"
        ),
        run=_comms_bytes,
    ),
)


def _soundness_rule(obligation: str):
    """Lazy delegate for the soundness rule family: these rules run
    over ENCODINGS (the declared reduction specs), not traced paths —
    the analyzer drives them through ``certify_encoding``
    (analysis/soundness.py) and this registry entry filters its
    Finding stream to one obligation, so ``run_rules``-style drivers
    and ``analyze soundness`` report through the same Rule names."""

    def run(ctx, sites):
        enc = getattr(ctx, "encoded", None)
        if enc is None:
            return []
        from .soundness import certify_encoding

        return [
            f for f in certify_encoding(enc).obligations
            if f.rule == obligation
        ]

    return run


#: the reduction soundness obligation family (analysis/soundness.py,
#: certificate SOUND_r*.json): per-encoding STATIC proofs the engine
#: gates consult before trusting a declared DeviceRewriteSpec or
#: ample mask. Registered here so the obligation names and
#: descriptions live in the same registry as the codegen rules — the
#: refusal messages (checkers/common.soundness_refusal) and the
#: fixture tests key on these names.
SOUNDNESS_RULES: tuple = (
    Rule(
        name="group-closure",
        description=(
            "the rewrite set is a permutation-group action on the "
            "limb layout: structural bounds plus cross-field member "
            "bit disjointness (bijective relabeling)"
        ),
        run=_soundness_rule("group-closure"),
    ),
    Rule(
        name="orbit-structure",
        description=(
            "canonicalization is idempotent, member-permuting "
            "(tuple multiset preserved, non-group bits untouched), "
            "and keyed on the FULL per-member tuple"
        ),
        run=_soundness_rule("orbit-structure"),
    ),
    Rule(
        name="fingerprint-invariance",
        description=(
            "the canonical form — hence the fingerprint fold — is "
            "invariant under every generator transposition"
        ),
        run=_soundness_rule("fingerprint-invariance"),
    ),
    Rule(
        name="property-invariance",
        description=(
            "every registered Property predicate is group-invariant "
            "(member-uniform static bit footprint + semantic "
            "battery agreement)"
        ),
        run=_soundness_rule("property-invariance"),
    ),
    Rule(
        name="transition-equivariance",
        description=(
            "the successor set commutes with the group: "
            "multiset{tau.succ(v)} == multiset{succ(tau.v)}"
        ),
        run=_soundness_rule("transition-equivariance"),
    ),
    Rule(
        name="ample-enabledness",
        description=(
            "enabledness preservation: a dropped slot's guard "
            "implies some kept slot's guard over the footprint cone"
        ),
        run=_soundness_rule("ample-enabledness"),
    ),
    Rule(
        name="ample-non-suppression",
        description=(
            "no property-relevant dropped transition lacks a "
            "symmetric kept image (guard and successor agree under "
            "a group element)"
        ),
        run=_soundness_rule("ample-non-suppression"),
    ),
)


#: the registry — ``tools/lint_kernels.py`` and ``pytest -m lint``
#: both run exactly this list.
RULES: tuple = (
    Rule(
        name="no-dense-mask",
        description=(
            "no [N, K]/[F, K] bool materialization on the sparse "
            "path (packed uint32 words are the mask)"
        ),
        run=_no_dense_mask,
    ),
    Rule(
        name="no-mask-gather",
        description=(
            "enabled-mask paths trace gather-free (shift-mask field "
            "extracts + word selects only)"
        ),
        run=_no_mask_gather,
    ),
    Rule(
        name="allowed-table-gather",
        description=(
            "step paths gather at most the encoding's declared "
            "table-row allowance (the four intended fetches)"
        ),
        run=_allowed_table_gather,
    ),
    Rule(
        name="no-lane-padded-alu",
        description=(
            "no [N, 1]-shaped ALU outputs, no >=3-operand [N, 1] "
            "concats (hand-paxos fuse-through residue allowed)"
        ),
        run=_no_lane_padded_alu,
    ),
    Rule(
        name="no-branch-pad-concat",
        description=(
            "switch branches update carries with class-local "
            "dynamic_update_slice, never full-capacity pad+concat"
        ),
        run=_no_branch_pad_concat,
    ),
    Rule(
        name="carry-copy-bytes",
        description=(
            "price the carry bytes each switch materializes; GATED "
            "against per-fixture byte budgets "
            "(tables.CARRY_COPY_BYTE_BUDGETS)"
        ),
        run=_carry_copy_bytes,
    ),
)


def run_rules(ctx: TraceCtx, closed) -> list:
    """Run every registered rule over one traced path. ``closed`` is
    a ``ClosedJaxpr`` (``jax.make_jaxpr`` output)."""
    return run_rules_with_stats(ctx, closed)[0]


def run_rules_with_stats(ctx: TraceCtx, closed) -> tuple:
    """``(findings, n_eqns)`` — one walk serves both the rules and
    the coverage stats (the lint driver's per-path eqn counts; big
    traces run to thousands of eqns, so the walk is not re-done just
    to count). The walk is a :class:`walker.SiteWalk`, so the comms
    rules' whole-jaxpr dataflow marks compute at most once per path;
    COMMS_RULES run after RULES and self-gate on ``ctx.check_comms``."""
    sites = SiteWalk(closed)
    findings: list = []
    for rule in RULES + COMMS_RULES:
        findings.extend(rule.run(ctx, sites))
    return findings, len(sites)
