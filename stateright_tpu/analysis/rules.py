"""The kernel-lint rule registry: the sparse-engine codegen contract
as declarative, source-attributed checks over traced jaxprs.

Two rounds of perf work (PERF.md §ordered, §wave-wall) priced exactly
these artifacts; each rule pins one of them:

* ``no-dense-mask`` — no ``[N, K]``/``[F, K]`` bool materialization on
  a sparse path (the 82x predicate-pass ablation: the engine consumes
  packed ``uint32[L]`` words, never the dense mask);
* ``no-mask-gather`` — the enabled-mask paths trace gather-free
  (shift-mask field extracts and word selects only; the 8x
  compiled-codegen tax was per-slot table gathers here);
* ``allowed-table-gather`` — step/fetch paths may gather only the
  intended table rows (params, flat transition, packed history, crash
  mask — at most the encoding's declared allowance);
* ``no-lane-padded-alu`` — no ``[N, 1]``-shaped ALU/compute outputs
  and no stack-of-scalars concats (≥3 ``[N, 1]`` operands): a
  ``[N, 1]`` elementwise op pays the full 128-lane tile-padding tax
  and XLA cannot fuse through the concatenate. The allowed residue is
  the hand-paxos calibration: ``[N, 1]`` SLICES from consuming
  multi-lane gather rows and 2-operand index-pair concats, which fuse;
* ``no-branch-pad-concat`` — ``cond``/``switch`` branches must update
  carried buffers with class-local ``dynamic_update_slice`` blocks,
  never rebuild a full-capacity tensor by padding/concatenating a
  small class result up to peak shape (the pre-round-6 carry pattern:
  a 2-row tail wave paying the 686k-row peak wave's copies);
* ``carry-copy-bytes`` — prices the switch-carry movement: bytes
  every ``cond``/``switch`` must materialize for its carry, and the
  carry-movement bytes inside each branch. The estimate is an info
  finding; fixtures listed in ``tables.CARRY_COPY_BYTE_BUDGETS`` are
  additionally GATED (round 9) — exceeding the per-fixture byte
  budget is an error, so the round-9 class collapse (PERF.md
  §layout: 1.42 MB → 0.24 MB per wave on the 2pc fixture) cannot
  silently regress.

A rule sees the shared walk (:mod:`.walker`) plus a :class:`TraceCtx`
describing the traced path, and yields :class:`Finding`\\ s. Rules
never import each other's state; adding a rule is appending to
``RULES``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

from .tables import (
    BRANCH_PAD_CONCAT_GROWTH,
    BRANCH_PAD_CONCAT_MIN_BYTES,
    CARRY_COPY_BYTE_BUDGETS,
    CARRY_MOVE_PRIMS,
    is_gather,
    output_bytes,
)
from .walker import (
    EqnSite,
    eqn_alu_n1,
    eqn_dense_bool_k,
    eqn_wide_concat_n1,
    iter_eqns,
    source_of,
)


@dataclass(frozen=True)
class TraceCtx:
    """What the lint driver knows about one traced path."""

    #: path label ("bits", "mask", "step", "engine:single",
    #: "engine:sharded", "wave-body")
    path: str
    #: encoding (or engine fixture) the path was traced from
    encoding: str
    #: batch rows of the trace (N frontier rows / vmap batch)
    n: int
    #: the encoding's action count K (dense-mask last dim)
    k: int
    #: dense [n, k] bool is banned on this path (packed-words paths
    #: and the engine pipeline; enabled_mask_vec's dense view is the
    #: CONTRACT on the "mask" path, so it sets False)
    sparse: bool = True
    #: gathers allowed (0 on mask paths; the table-row allowance on
    #: step paths; None = gathers unaudited, e.g. the wave body whose
    #: winner-fetch gathers are the intended idiom)
    allow_gathers: Optional[int] = 0
    #: True on table-fetch paths (step): gather findings report under
    #: allowed-table-gather with the table-row diagnosis, even at
    #: allowance 0 — a mask-path message for a step-path defect sends
    #: the maintainer to the wrong contract
    table_path: bool = False
    #: audit [n, 1] ALU / stack-of-scalars concats on this path
    check_lane_alu: bool = True
    #: audit cond/switch branch shapes + price carry movement
    check_branches: bool = False


@dataclass(frozen=True)
class Finding:
    """One rule hit, attributed to the source equation."""

    rule: str
    severity: str  # "error" | "info"
    encoding: str
    path: str
    message: str
    primitive: Optional[str] = None
    source: Optional[str] = None
    data: dict = field(default_factory=dict)

    def format(self) -> str:
        loc = f" @ {self.source}" if self.source else ""
        return (
            f"[{self.rule}] {self.encoding} / {self.path}: "
            f"{self.message}{loc}"
        )


@dataclass(frozen=True)
class Rule:
    name: str
    description: str
    run: Callable[[TraceCtx, list], Iterable[Finding]]


def _out_shapes(eqn):
    for v in eqn.outvars:
        sh = getattr(v.aval, "shape", None)
        if sh is not None:
            yield v.aval, sh


# -- no-dense-mask ---------------------------------------------------------

def _no_dense_mask(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    if not ctx.sparse:
        return
    for site in sites:
        if not eqn_dense_bool_k(site.eqn, ctx.k):
            continue
        shapes = [
            sh for _, sh in _out_shapes(site.eqn)
            if len(sh) == 2 and sh[1] == ctx.k
        ]
        yield Finding(
            rule="no-dense-mask",
            severity="error",
            encoding=ctx.encoding,
            path=ctx.path,
            message=(
                f"dense bool[{shapes[0][0]}, K={ctx.k}] mask "
                f"materialized by `{site.primitive}` on a "
                "sparse path (the engine consumes packed "
                "uint32 words; PERF.md §wave-wall priced this "
                "pass 82x)"
            ),
            primitive=site.primitive,
            source=source_of(site.eqn),
        )


# -- no-mask-gather / allowed-table-gather ---------------------------------

def _no_mask_gather(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    # mask-class paths only: a step-path gather is a table-fetch
    # defect and reports under allowed-table-gather below.
    if ctx.allow_gathers != 0 or ctx.table_path:
        return
    engine = ctx.path.startswith("engine:")
    for site in sites:
        if is_gather(site.primitive):
            yield Finding(
                rule="no-mask-gather",
                severity="error",
                encoding=ctx.encoding,
                path=ctx.path,
                message=(
                    f"`{site.primitive}` on a gather-free path — "
                    + (
                        "the engine's pair pipeline (bitmap "
                        "predicate, peel, packed-append compaction) "
                        "is elementwise + sort only; one Ba-row "
                        "gather costs a whole extra sort (PERF.md "
                        "§gathers)"
                        if engine
                        else "mask paths must be shift-mask field "
                        "extracts and word selects only (the 8x "
                        "compiled-codegen tax, PERF.md §ordered)"
                    )
                ),
                primitive=site.primitive,
                source=source_of(site.eqn),
            )


def _allowed_table_gather(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    # table-fetch (step) paths only, at ANY allowance including 0 —
    # hand 2pc's step is pure slot arithmetic, so its allowance IS 0
    # and a gather there must still get the table-row diagnosis.
    if not ctx.table_path or ctx.allow_gathers is None:
        return
    gathers = [s for s in sites if is_gather(s.primitive)]
    if len(gathers) > ctx.allow_gathers:
        srcs = ", ".join(source_of(s.eqn) for s in gathers)
        yield Finding(
            rule="allowed-table-gather",
            severity="error",
            encoding=ctx.encoding,
            path=ctx.path,
            message=(
                f"{len(gathers)} gathers on a table-fetch path whose "
                f"allowance is {ctx.allow_gathers} (the intended "
                "idiom is one multi-lane gather per table row — "
                "params, flat transition, packed history, crash "
                f"mask); gather sites: {srcs}"
            ),
            primitive=gathers[0].primitive,
            source=source_of(gathers[0].eqn),
            data={"gathers": len(gathers),
                  "allowance": ctx.allow_gathers},
        )


# -- no-lane-padded-alu ----------------------------------------------------

def _no_lane_padded_alu(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    if not ctx.check_lane_alu:
        return
    n = ctx.n
    for site in sites:
        eqn = site.eqn
        name = site.primitive
        if eqn_alu_n1(eqn, n):
            yield Finding(
                rule="no-lane-padded-alu",
                severity="error",
                encoding=ctx.encoding,
                path=ctx.path,
                message=(
                    f"[{n}, 1]-shaped `{name}` — real compute "
                    "at 128x lane padding (PERF.md §ordered); "
                    "keep lane math 1-D [N]-shaped and "
                    "reshape only at the very end"
                ),
                primitive=name,
                source=source_of(eqn),
            )
        n1_ops = eqn_wide_concat_n1(eqn, n)
        if n1_ops:
            yield Finding(
                rule="no-lane-padded-alu",
                severity="error",
                encoding=ctx.encoding,
                path=ctx.path,
                message=(
                    f"stack-of-scalars concatenate of {n1_ops} "
                    f"[{n}, 1] lanes — XLA cannot fuse through a "
                    "wide concatenate (the ~470ms/run artifact, "
                    "PERF.md §ordered); 2-operand index-pair "
                    "concats are the calibrated residue"
                ),
                primitive=name,
                source=source_of(eqn),
                data={"n1_operands": n1_ops},
            )


# -- no-branch-pad-concat --------------------------------------------------

def _axis0(sh) -> int:
    return int(sh[0]) if sh else 1


def _zeroish_rows(site: EqnSite, eqn) -> tuple:
    """Split a concatenate's axis-0 operand rows into (filler, real):
    filler operands are literals, jaxpr constants, or values a
    ``broadcast_in_dim`` of a scalar produced inside the same
    sub-jaxpr — the static signature of a ``zeros(...)`` pad block."""
    producers = {}
    if site.jaxpr is not None:
        for e in site.jaxpr.eqns:
            if e.primitive.name == "broadcast_in_dim" and not getattr(
                e.invars[0].aval, "shape", ()
            ):
                for v in e.outvars:
                    producers[id(v)] = "scalar-broadcast"
        consts = set(map(id, site.jaxpr.constvars))
    else:
        consts = set()
    filler = real = 0
    for v in eqn.invars:
        sh = getattr(v.aval, "shape", None)
        rows = _axis0(sh) if sh else 1
        if (
            not hasattr(v, "count")  # Literal
            or id(v) in consts
            or id(v) in producers
        ):
            filler += rows
        else:
            real += rows
    return filler, real


def _no_branch_pad_concat(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    if not ctx.check_branches:
        return
    for site in sites:
        # Only a pad/concat RETURNED as part of a branch's carry
        # (directly or through convert/reshape passthroughs) is the
        # priced pattern (rebuilding a carried buffer at peak shape);
        # internal temporaries — merge sort lanes, index plumbing —
        # are the engine's legitimate concats.
        if not site.in_branch():
            continue
        eqn = site.eqn
        name = site.primitive
        if name not in ("pad", "concatenate"):
            continue
        if not site.reaches_output():
            continue
        outs = list(_out_shapes(eqn))
        if not outs:
            continue
        out_aval, out_sh = outs[0]
        nbytes = output_bytes(out_aval)
        if nbytes < BRANCH_PAD_CONCAT_MIN_BYTES or not out_sh:
            continue
        if name == "concatenate" and eqn.params.get("dimension") != 0:
            continue
        in0 = max(
            (_axis0(getattr(v.aval, "shape", ()))
             for v in eqn.invars
             if getattr(v.aval, "shape", None)),
            default=1,
        )
        grown = _axis0(out_sh) >= BRANCH_PAD_CONCAT_GROWTH * max(in0, 1)
        padded = False
        if name == "pad":
            cfg = eqn.params.get("padding_config") or ()
            if cfg:
                lo, hi, _ = cfg[0]
                padded = lo + hi >= max(in0, 1)
        else:
            filler, real = _zeroish_rows(site, eqn)
            padded = filler >= max(real, 1)
        if not (grown or padded):
            continue
        yield Finding(
            rule="no-branch-pad-concat",
            severity="error",
            encoding=ctx.encoding,
            path=ctx.path,
            message=(
                f"branch carry built by `{name}` inside "
                f"{site.branch_path()}: axis 0 {in0} -> "
                f"{_axis0(out_sh)} rows ({nbytes / 1e6:.2f} MB out)"
                " — switch branches must write class-local "
                "dynamic_update_slice blocks into the carried "
                "buffer, not pad a class result to peak shape (the "
                "round-6 carry rework, PERF.md §wave-wall)"
            ),
            primitive=name,
            source=source_of(eqn),
            data={"in_rows": in0, "out_rows": _axis0(out_sh),
                  "out_bytes": nbytes},
        )


# -- carry-copy-bytes (estimator) ------------------------------------------

def _carry_copy_bytes(ctx: TraceCtx, sites: list) -> Iterable[Finding]:
    """Price the carry each ``cond``/``switch`` materializes: the
    bytes of every branch's returned carry (the movement XLA performs
    between classes) plus the carry-movement primitive bytes inside
    branches. The estimate always lands as an info finding; since
    round 9 the rule is also GATED — a fixture listed in
    ``tables.CARRY_COPY_BYTE_BUDGETS`` whose switch-carry total
    exceeds its budget yields an ERROR, so a refactor can't silently
    re-inflate the switch carries the round-9 class collapse removed
    (the 2pc fixture went 1.42 MB → 0.24 MB/wave; PERF.md §layout)."""
    if not ctx.check_branches:
        return
    switch_bytes = 0
    n_switches = 0
    move_bytes = 0
    top = None  # fattest switch
    for site in sites:
        eqn = site.eqn
        if site.primitive == "cond":
            n_switches += 1
            b = sum(output_bytes(v.aval) for v in eqn.outvars)
            switch_bytes += b
            if top is None or b > top[0]:
                top = (b, len(eqn.params.get("branches", ())),
                       source_of(eqn))
        elif site.in_branch() and site.primitive in CARRY_MOVE_PRIMS:
            move_bytes += sum(
                output_bytes(v.aval) for v in eqn.outvars
            )
    if n_switches == 0:
        return
    top_b, top_nb, top_src = top
    budget = CARRY_COPY_BYTE_BUDGETS.get(ctx.encoding)
    yield Finding(
        rule="carry-copy-bytes",
        severity="info",
        encoding=ctx.encoding,
        path=ctx.path,
        message=(
            f"{n_switches} cond/switch eqns carry "
            f"{switch_bytes / 1e6:.2f} MB of outputs (fattest: "
            f"{top_b / 1e6:.2f} MB x {top_nb} branches @ {top_src}); "
            f"{move_bytes / 1e6:.2f} MB of pad/slice/concat/"
            "dynamic_update_slice outputs inside branches"
            + (f"; budget {budget / 1e6:.2f} MB"
               if budget is not None else "")
        ),
        primitive="cond",
        source=top_src,
        data={
            "switches": n_switches,
            "switch_carry_bytes": switch_bytes,
            "fattest_switch_bytes": top_b,
            "branch_move_bytes": move_bytes,
            **({"budget_bytes": budget} if budget is not None else {}),
        },
    )
    if budget is not None and switch_bytes > budget:
        yield Finding(
            rule="carry-copy-bytes",
            severity="error",
            encoding=ctx.encoding,
            path=ctx.path,
            message=(
                f"switch-carry bytes {switch_bytes:,} exceed this "
                f"fixture's budget {budget:,} "
                "(analysis/tables.CARRY_COPY_BYTE_BUDGETS) — the "
                "class ladder is copying carry tuples between "
                "branches again; keep merge cores returning the "
                "shared SoA result and resident-buffer updates in "
                "ONE fetch switch per wave (the round-9 collapse, "
                "PERF.md §layout). Raise the budget only for a "
                "deliberate, priced carry addition."
            ),
            primitive="cond",
            source=top_src,
            data={
                "switch_carry_bytes": switch_bytes,
                "budget_bytes": budget,
            },
        )


#: the registry — ``tools/lint_kernels.py`` and ``pytest -m lint``
#: both run exactly this list.
RULES: tuple = (
    Rule(
        name="no-dense-mask",
        description=(
            "no [N, K]/[F, K] bool materialization on the sparse "
            "path (packed uint32 words are the mask)"
        ),
        run=_no_dense_mask,
    ),
    Rule(
        name="no-mask-gather",
        description=(
            "enabled-mask paths trace gather-free (shift-mask field "
            "extracts + word selects only)"
        ),
        run=_no_mask_gather,
    ),
    Rule(
        name="allowed-table-gather",
        description=(
            "step paths gather at most the encoding's declared "
            "table-row allowance (the four intended fetches)"
        ),
        run=_allowed_table_gather,
    ),
    Rule(
        name="no-lane-padded-alu",
        description=(
            "no [N, 1]-shaped ALU outputs, no >=3-operand [N, 1] "
            "concats (hand-paxos fuse-through residue allowed)"
        ),
        run=_no_lane_padded_alu,
    ),
    Rule(
        name="no-branch-pad-concat",
        description=(
            "switch branches update carries with class-local "
            "dynamic_update_slice, never full-capacity pad+concat"
        ),
        run=_no_branch_pad_concat,
    ),
    Rule(
        name="carry-copy-bytes",
        description=(
            "price the carry bytes each switch materializes; GATED "
            "against per-fixture byte budgets "
            "(tables.CARRY_COPY_BYTE_BUDGETS)"
        ),
        run=_carry_copy_bytes,
    ),
)


def run_rules(ctx: TraceCtx, closed) -> list:
    """Run every registered rule over one traced path. ``closed`` is
    a ``ClosedJaxpr`` (``jax.make_jaxpr`` output)."""
    return run_rules_with_stats(ctx, closed)[0]


def run_rules_with_stats(ctx: TraceCtx, closed) -> tuple:
    """``(findings, n_eqns)`` — one walk serves both the rules and
    the coverage stats (the lint driver's per-path eqn counts; big
    traces run to thousands of eqns, so the walk is not re-done just
    to count)."""
    sites = list(iter_eqns(closed.jaxpr))
    findings: list = []
    for rule in RULES:
        findings.extend(rule.run(ctx, sites))
    return findings, len(sites)
